"""Experiment tracker zoo.

Reference: ``/root/reference/src/accelerate/tracking.py`` (1023 LoC) — a
``GeneralTracker`` ABC with 8 built-ins and main-process gating. Ported
concept-for-concept: trackers are host-side observers, nothing here touches
the mesh. Built-ins are gated on availability probes exactly like the
reference's ``is_*_available`` guards.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Any

import numpy as np

from .logging import get_logger
from .state import PartialState
from .utils import imports as _imports

logger = get_logger(__name__)

LOGGER_TYPE_TO_CLASS = {}


def on_main_process(function):
    """Run only on the main process (reference ``tracking.py:39``)."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True):
            state = PartialState()
            if state.is_main_process:
                return function(self, *args, **kwargs)
            return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """Tracker ABC (reference ``tracking.py:80``). Subclasses set ``name``,
    ``requires_logging_directory``, implement ``store_init_configuration``
    and ``log``, and may expose the raw client via ``tracker``."""

    main_process_only = True
    name = "generic"
    requires_logging_directory = False

    def __init__(self, _blank: bool = False, **kwargs):
        self._blank = _blank

    @property
    def tracker(self):
        return None

    def store_init_configuration(self, values: dict):
        pass

    def log(self, values: dict, step: int | None = None, **kwargs):
        pass

    def log_images(self, values: dict, step: int | None = None, **kwargs):
        pass

    def log_table(
        self,
        table_name: str,
        columns: list[str] | None = None,
        data: list[list] | None = None,
        dataframe=None,
        step: int | None = None,
        **kwargs,
    ):
        """Log a table either as ``columns`` + ``data`` rows or a dataframe.
        Base implementation is a no-op; WandB/ClearML override (reference
        ``tracking.py:360,822``)."""

    def finish(self):
        pass


class JSONLTracker(GeneralTracker):
    """Dependency-free built-in (``log_with="jsonl"``): one JSON object per
    ``log()`` call appended to ``{logging_dir}/{run_name}/metrics.jsonl``,
    flushed per record so a crash loses at most the in-flight line. The
    same file format the telemetry subsystem writes — a run with both
    enabled yields a complete, greppable trail with zero extra services."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.logging_dir, exist_ok=True)
        self._file = open(os.path.join(self.logging_dir, "metrics.jsonl"), "a")

    @property
    def tracker(self):
        return getattr(self, "_file", None)

    @on_main_process
    def store_init_configuration(self, values: dict):
        self._file.write(json.dumps({"event": "init", "config": _jsonable(values)}) + "\n")
        self._file.flush()

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        self._file.write(
            json.dumps({"step": step, "ts": time.time(), **_jsonable(_flatten_scalars(values))})
            + "\n"
        )
        self._file.flush()

    @on_main_process
    def finish(self):
        self._file.close()


class TensorBoardTracker(GeneralTracker):
    """(Reference ``tracking.py:165``.) Uses tensorboardX / tf summary if
    available, else falls back to JSONL scalars that TensorBoard's scalars
    plugin can be re-fed from."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str, **kwargs):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.logging_dir, exist_ok=True)
        self.writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # noqa: PLC0415

            self.writer = SummaryWriter(self.logging_dir, **kwargs)
        except Exception:
            try:
                from tensorboardX import SummaryWriter  # noqa: PLC0415

                self.writer = SummaryWriter(self.logging_dir, **kwargs)
            except Exception:
                self._jsonl = open(os.path.join(self.logging_dir, "scalars.jsonl"), "a")

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        if self.writer is not None:
            self.writer.add_hparams(_flatten_scalars(values), metric_dict={})
            self.writer.flush()
        else:
            with open(os.path.join(self.logging_dir, "hparams.json"), "w") as f:
                json.dump(_jsonable(values), f, indent=2)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        values = _flatten_scalars(values)
        if self.writer is not None:
            for k, v in values.items():
                if isinstance(v, str):
                    self.writer.add_text(k, v, global_step=step)
                else:
                    self.writer.add_scalar(k, v, global_step=step)
            self.writer.flush()
        else:
            self._jsonl.write(json.dumps({"step": step, "ts": time.time(), **_jsonable(values)}) + "\n")
            self._jsonl.flush()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs):
        """``{name: batch of HWC/NCHW arrays}`` → ``add_images`` (reference
        ``tracking.py:251``); the JSONL fallback stores ``.npy`` files next
        to the scalars so the data survives without a SummaryWriter."""
        if self.writer is not None:
            for k, v in values.items():
                self.writer.add_images(k, v, global_step=step, **kwargs)
            self.writer.flush()
        else:
            img_dir = os.path.join(self.logging_dir, "images")
            os.makedirs(img_dir, exist_ok=True)
            for k, v in values.items():
                safe = k.replace("/", "_")
                np.save(os.path.join(img_dir, f"{safe}_step{step or 0}.npy"), np.asarray(v))

    @on_main_process
    def finish(self):
        if self.writer is not None:
            self.writer.close()
        elif hasattr(self, "_jsonl"):
            self._jsonl.close()


class WandBTracker(GeneralTracker):
    """(Reference ``tracking.py:276``.)"""

    name = "wandb"
    requires_logging_directory = False
    main_process_only = True

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import wandb  # noqa: PLC0415

        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb  # noqa: PLC0415

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs):
        """``{name: list of images}`` → ``wandb.Image`` wraps (reference
        ``tracking.py:341``)."""
        import wandb  # noqa: PLC0415

        for k, v in values.items():
            self.log({k: [wandb.Image(image) for image in v]}, step=step, **kwargs)

    @on_main_process
    def log_table(
        self,
        table_name: str,
        columns: list[str] | None = None,
        data: list[list] | None = None,
        dataframe=None,
        step: int | None = None,
        **kwargs,
    ):
        """(Reference ``tracking.py:360``.)"""
        import wandb  # noqa: PLC0415

        table = wandb.Table(columns=columns, data=data, dataframe=dataframe)
        self.log({table_name: table}, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """(Reference ``tracking.py:579``.)"""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str | None = None, **kwargs):
        super().__init__()
        import mlflow  # noqa: PLC0415

        self._mlflow = mlflow
        experiment = mlflow.set_experiment(run_name)
        self.active_run = mlflow.start_run(**kwargs)

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        for k, v in _flatten_scalars(values).items():
            self._mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        metrics = {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)}
        self._mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self):
        self._mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """(Reference ``tracking.py:399``.)"""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from comet_ml import Experiment  # noqa: PLC0415

        self.run = Experiment(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.run.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        if step is not None:
            self.run.set_step(step)
        self.run.log_metrics(_flatten_scalars(values), step=step)

    @on_main_process
    def finish(self):
        self.run.end()


class AimTracker(GeneralTracker):
    """(Reference ``tracking.py:480``.)"""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        from aim import Run  # noqa: PLC0415

        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        for k, v in values.items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, kwargs: dict | None = None):
        """``{name: image | (image, caption)}`` → ``aim.Image`` tracks
        (reference ``tracking.py:540``); ``kwargs`` splits into the
        ``aim_image`` and ``track`` call kwargs."""
        import aim  # noqa: PLC0415

        kwargs = kwargs or {}
        image_kw = kwargs.get("aim_image", {})
        track_kw = kwargs.get("track", {})
        for k, v in values.items():
            caption = None
            if isinstance(v, tuple):
                v, caption = v
            image = aim.Image(v, caption=caption, **image_kw) if caption is not None else aim.Image(v, **image_kw)
            self.writer.track(image, name=k, step=step, **track_kw)

    @on_main_process
    def finish(self):
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    """(Reference ``tracking.py:724``.)"""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from clearml import Task  # noqa: PLC0415

        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        clearml_logger = self.task.get_logger()
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                continue
            title, _, series = k.partition("/")
            clearml_logger.report_scalar(title=title, series=series or title, value=v, iteration=step or 0)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs):
        """(Reference ``tracking.py:804``.)"""
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            title, _, series = k.partition("/")
            clearml_logger.report_image(
                title=title, series=series or title, iteration=step, image=v, **kwargs
            )

    @on_main_process
    def log_table(
        self,
        table_name: str,
        columns: list[str] | None = None,
        data: list[list] | None = None,
        dataframe=None,
        step: int | None = None,
        **kwargs,
    ):
        """``columns`` + ``data`` rows, or a dataframe (reference
        ``tracking.py:822``)."""
        to_report = dataframe
        if dataframe is None:
            if data is None:
                raise ValueError("log_table needs `data` when `dataframe` is None")
            to_report = [columns] + data if columns else data
        title, _, series = table_name.partition("/")
        self.task.get_logger().report_table(
            title=title, series=series or title, table_plot=to_report, iteration=step, **kwargs
        )

    @on_main_process
    def finish(self):
        self.task.close()


class DVCLiveTracker(GeneralTracker):
    """(Reference ``tracking.py:876``.)"""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str | None = None, live=None, **kwargs):
        super().__init__()
        from dvclive import Live  # noqa: PLC0415

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in _flatten_scalars(values).items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self):
        self.live.end()


LOGGER_TYPE_TO_CLASS.update(
    {
        "aim": AimTracker,
        "comet_ml": CometMLTracker,
        "mlflow": MLflowTracker,
        "tensorboard": TensorBoardTracker,
        "wandb": WandBTracker,
        "clearml": ClearMLTracker,
        "dvclive": DVCLiveTracker,
        "jsonl": JSONLTracker,
    }
)

_AVAILABILITY = {
    "tensorboard": lambda: True,  # JSONL fallback always works
    "wandb": _imports.is_wandb_available,
    "comet_ml": _imports.is_comet_ml_available,
    "mlflow": _imports.is_mlflow_available,
    "aim": _imports.is_aim_available,
    "clearml": _imports.is_clearml_available,
    "dvclive": _imports.is_dvclive_available,
    "jsonl": lambda: True,  # stdlib-only
}


def filter_trackers(log_with, logging_dir: str | None = None):
    """Resolve user input ("all", name, class instance, list) into tracker
    specs (reference ``filter_trackers`` ``tracking.py:971``)."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    loggers = []
    if "all" in log_with:
        log_with = [name for name in LOGGER_TYPE_TO_CLASS if _AVAILABILITY[name]()] + [
            t for t in log_with if isinstance(t, GeneralTracker)
        ]
    for tracker in log_with:
        if isinstance(tracker, GeneralTracker):
            loggers.append(tracker)
            continue
        name = str(tracker)
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(
                f"unknown tracker {name!r}; choose from {sorted(LOGGER_TYPE_TO_CLASS)}"
            )
        if not _AVAILABILITY[name]():
            logger.warning(f"tracker {name} is not available in this environment; skipping")
            continue
        if LOGGER_TYPE_TO_CLASS[name].requires_logging_directory and logging_dir is None:
            raise ValueError(f"tracker {name} requires a logging_dir / project_dir")
        loggers.append(name)
    return loggers


def init_trackers(log_with, project_name, logging_dir, config, init_kwargs):
    trackers = []
    for spec in log_with:
        if isinstance(spec, GeneralTracker):
            tracker = spec
        else:
            cls = LOGGER_TYPE_TO_CLASS[spec]
            kwargs = init_kwargs.get(spec, {})
            if cls.requires_logging_directory:
                tracker = cls(project_name, logging_dir, **kwargs)
            else:
                tracker = cls(project_name, **kwargs)
        if config is not None:
            tracker.store_init_configuration(config)
        trackers.append(tracker)
    return trackers


def _flatten_scalars(values: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_scalars(v, prefix=f"{key}/"))
        elif isinstance(v, (int, float, str, bool, np.number)):
            out[key] = v
        elif hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            out[key] = v.item()
    return out


def _jsonable(values):
    return json.loads(json.dumps(values, default=lambda o: getattr(o, "item", lambda: str(o))()))
