"""LR scheduler wrapper.

Reference: ``AcceleratedScheduler`` (``/root/reference/src/accelerate/
scheduler.py:25``) steps the underlying scheduler only when the optimizer
actually stepped, and by ``num_processes`` at a time unless
``split_batches`` (:54-82). Here a scheduler is an optax schedule function
``step -> lr``; the wrapper maintains the step counter with the same
skip/×N semantics and writes the lr into the optimizer's injected
hyperparams.
"""

from __future__ import annotations

from typing import Callable

from .optimizer import AcceleratedOptimizer
from .state import AcceleratorState, GradientState


class AcceleratedScheduler:
    def __init__(
        self,
        scheduler: Callable[[int], float],
        optimizers,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.split_batches = split_batches
        self.step_with_optimizer = step_with_optimizer
        self.gradient_state = GradientState()
        self._step_count = 0
        self._last_lr = None

    def step(self, *args, **kwargs):
        if not self.step_with_optimizer:
            self._advance(1)
            return
        if not self.gradient_state.sync_gradients:
            if self.gradient_state.adjust_scheduler:
                self._step_count += 0  # explicitly: nothing happens mid-accumulation
            return
        # only advance if none of the bound optimizers skipped their step
        if any(opt.step_was_skipped for opt in self.optimizers):
            return
        if self.split_batches:
            self._advance(1)
        else:
            # reference semantics (``scheduler.py:73-82``): ×num_processes per
            # step, because each *process* only sees 1/num_processes of the
            # batches. Here the loop consumes GLOBAL batches — sub-host mesh
            # parallelism (dp×fsdp) never hides batches from the loop — so the
            # multiplier is the host-process count, under which each host's
            # loader really does yield len/num_processes batches.
            state = AcceleratorState()
            num = state.num_processes if state.initialized else 1
            self._advance(num)

    def _advance(self, n: int):
        self._step_count += n
        if callable(self.scheduler):
            lr = float(self.scheduler(self._step_count))
        else:
            # torch-style scheduler object: step it n times, read its lr
            for _ in range(n):
                self.scheduler.step()
            lr = float(self.scheduler.get_last_lr()[0])
        self._last_lr = lr
        for opt in self.optimizers:
            try:
                opt.set_hyperparam("learning_rate", lr)
            except ValueError:
                pass  # fixed-lr optimizer: schedule is advisory only

    def get_last_lr(self):
        if self._last_lr is None:
            lr = self.optimizers[0].learning_rate if self.optimizers else None
            if lr is not None:
                return [lr]
            if callable(self.scheduler):
                return [float(self.scheduler(0))]
            return [float(self.scheduler.get_last_lr()[0])]
        return [self._last_lr]

    def state_dict(self):
        return {"step_count": self._step_count, "last_lr": self._last_lr}

    def load_state_dict(self, state):
        self._step_count = state["step_count"]
        self._last_lr = state.get("last_lr")
