"""Request-trace smoke: a 2-replica routed fleet serves a mixed trace with
request tracing armed, then the per-process trace files must stitch into
one coherent story:

* every completed request has a **complete span chain** — router submit →
  engine arrive → admit → first token → finish — under one trace_id;
* **zero orphaned flows** (every router dispatch arrow lands on a replica
  admission) and **exactly-once finish events**;
* a client-supplied trace_id survives submit → replica row → trace file
  **verbatim**;
* ``trace tail`` reproduces each request's TTFT from its spans to within
  5 ms of the engine-reported value and emits a phase-attribution table;
* the ``/metrics``-style exposition carries ``trace_id`` exemplars on the
  latency histograms and round-trips through the strict parser.

Run directly (``make reqtrace-smoke``) or via ``bench.py reqtrace`` (which
additionally prices the disabled-path guard — bar <1% of an engine
iteration).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the router host never imports jax, exactly like production
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]
N_REQUESTS = 14


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    return env


def _payload(i):
    p = {"id": i, "prompt": [1 + i % 7, 5, 11, 2], "max_new_tokens": 4 + i % 5}
    if i % 4 == 0:
        p["trace_id"] = f"client-{i:04d}"
    if i % 3 == 0:
        p["priority"] = "batch"
    return p


def main() -> int:
    logdir = os.path.join(tempfile.mkdtemp(prefix="reqtrace_smoke_"), "fleet")
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "2", "--logging-dir", logdir,
         "--health-interval", "0.2", *ENGINE_ARGS],
        env=_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results: list[str] = []
    threading.Thread(
        target=lambda: [results.append(l.strip()) for l in proc.stdout if l.strip()],
        daemon=True,
    ).start()
    try:
        for i in range(N_REQUESTS):
            proc.stdin.write(json.dumps(_payload(i)) + "\n")
        proc.stdin.flush()
        deadline = time.monotonic() + 300
        while len(results) < N_REQUESTS and time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(f"route exited early rc={proc.returncode}")
            time.sleep(0.1)
        proc.stdin.close()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == 0, f"route exited {rc}"
    rows = {r["id"]: r for r in map(json.loads, results)}
    assert len(rows) == N_REQUESTS, f"lost answers: {sorted(rows)}"
    errors = [r for r in rows.values() if "error" in r]
    assert not errors, f"error rows: {errors}"

    # every answer row carries a trace id; client-supplied ones verbatim
    for i, row in rows.items():
        assert row.get("trace_id"), f"row {i} without trace_id"
        if i % 4 == 0:
            assert row["trace_id"] == f"client-{i:04d}", row

    # merge the fleet's files: every request stitched cross-process, zero
    # orphan flows, one engine finish apiece
    from accelerate_tpu.diagnostics.reqtrace import (
        collect_request_flows,
        render_tail_report,
        request_timeline,
        tail_report,
    )
    from accelerate_tpu.diagnostics.tracing import (
        discover_trace_files,
        merge_traces,
        validate_chrome_trace,
    )

    paths = discover_trace_files(logdir)
    assert len(paths) == 3, f"expected router + 2 replica files, got {paths}"
    merged = merge_traces(
        paths=paths, output_path=os.path.join(logdir, "merged.trace.json")
    )
    validate_chrome_trace(merged)
    flows_meta = merged["metadata"]["request_flows"]
    assert flows_meta["trace_ids"] == N_REQUESTS, flows_meta
    assert flows_meta["cross_process"] == N_REQUESTS, flows_meta
    assert flows_meta["orphan_flows"] == 0, flows_meta

    flows = collect_request_flows(logdir)
    timelines = {tid: request_timeline(tid, evs) for tid, evs in flows.items()}
    for row in rows.values():
        t = timelines[row["trace_id"]]
        assert t["complete"], f"incomplete span chain: {t}"
        assert t["engine_finish_events"] == 1, f"finish not exactly-once: {t}"
        # span-derived TTFT vs the engine-reported answer-row value
        assert abs(t["ttft_s"] - row["ttft_s"]) < 0.005, (t["ttft_s"], row["ttft_s"])

    report = tail_report(logdir, k=5)
    assert report["measured_requests"] == N_REQUESTS
    assert report["incomplete"] == 0
    assert abs(sum(report["attribution"].values()) - 100.0) < 1e-6
    print(render_tail_report(report))

    # exemplar round trip: replay the replica telemetry trails through the
    # shared ingest mapping and render/parse the exposition strictly
    from accelerate_tpu.metrics.ingest import observe_record
    from accelerate_tpu.metrics.openmetrics import parse_openmetrics, render_openmetrics
    from accelerate_tpu.metrics.registry import MetricsRegistry

    registry = MetricsRegistry(gate_main_process=False)
    import glob

    for trail in glob.glob(os.path.join(logdir, "replica_*", "telemetry",
                                        "telemetry.jsonl")):
        with open(trail) as f:
            for line in f:
                try:
                    observe_record(registry, json.loads(line))
                except json.JSONDecodeError:
                    pass
    families = parse_openmetrics(render_openmetrics(registry))
    exemplars = families["accelerate_serving_ttft_seconds"]["exemplars"]
    assert exemplars, "no ttft exemplars on the scrape"
    exemplar_ids = {e["exemplar"]["labels"]["trace_id"] for e in exemplars}
    assert exemplar_ids <= set(timelines), (exemplar_ids, set(timelines))
    classes = {e["labels"].get("class") for e in exemplars}
    assert classes <= {"interactive", "batch"}, classes

    print(
        f"REQTRACE_SMOKE OK: {N_REQUESTS} requests, "
        f"{flows_meta['cross_process']} cross-process flows, 0 orphans, "
        f"{len(exemplar_ids)} exemplar trace_id(s) on the scrape"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
