"""``make metrics-smoke``: the scrape surface end to end against a recorded
logging_dir fixture.

1. Record the fixture: a 20-step toy loop with telemetry + diagnostics
   writes a real telemetry JSONL trail and trace trail.
2. Sidecar in-process: ``LoggingDirExporter`` refreshes from the fixture
   and the exposition round-trips through the strict OpenMetrics parser
   with the expected families (steps, compiles, goodput).
3. Sidecar over HTTP: the real ``accelerate-tpu metrics export`` CLI is
   spawned as a subprocess on an ephemeral port and scraped with urllib —
   the same bytes a Prometheus scraper would see.
4. SLO alerting: an impossible ``ACCELERATE_SLO_MIN_GOODPUT_PCT=101``
   makes ``metrics export --once`` exit 3 and write ``ALERTS.json``.

Exit code is the CI signal; prints a one-line OK.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _record_fixture(tmp: str) -> None:
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionModel

    acc = Accelerator(project_dir=tmp, telemetry=True, diagnostics=True)
    model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
    x = np.linspace(-1, 1, 16).astype(np.float32)
    y = (2 * x + 3).astype(np.float32)
    for _ in range(20):
        out = model(x=x, y=y)
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()
    acc.end_training()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    from accelerate_tpu.metrics.exporter import LoggingDirExporter
    from accelerate_tpu.metrics.openmetrics import parse_openmetrics, sample_value

    tmp = tempfile.mkdtemp(prefix="metrics_smoke_")
    _record_fixture(tmp)

    # -- in-process sidecar: refresh + strict round-trip ---------------------
    exporter = LoggingDirExporter(tmp)
    assert exporter.refresh() == [], "no SLO rules armed yet, nothing may fire"
    families = parse_openmetrics(exporter.render())
    steps = sample_value(families, "accelerate_steps")
    assert steps == 20, f"expected 20 step rows, scraped {steps}"
    assert sample_value(families, "accelerate_compiles") >= 1
    goodput = sample_value(families, "accelerate_goodput_ratio")
    assert goodput is not None and 0.0 <= goodput <= 1.0, goodput
    assert "accelerate_step_time_seconds" in families  # histogram family

    # -- real CLI sidecar over HTTP ------------------------------------------
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "metrics", "export", tmp, "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        body = None
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as resp:
                    assert "openmetrics-text" in resp.headers.get("Content-Type", "")
                    body = resp.read().decode()
                break
            except OSError:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"exporter died: {proc.stderr.read()[-2000:]}"
                    ) from None
                time.sleep(0.25)
        assert body is not None, "exporter never answered /metrics"
        scraped = parse_openmetrics(body)
        assert sample_value(scraped, "accelerate_steps") == 20
    finally:
        proc.terminate()
        proc.wait(timeout=30)

    # -- SLO alerting: --once exits 3 + writes ALERTS.json -------------------
    env_slo = dict(env, ACCELERATE_SLO_MIN_GOODPUT_PCT="101")
    once = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "metrics", "export", tmp, "--once"],
        env=env_slo, capture_output=True, text=True, timeout=300,
    )
    assert once.returncode == 3, (once.returncode, once.stderr[-2000:])
    parse_openmetrics(once.stdout)  # --once output is a full exposition too
    alerts = json.load(open(os.path.join(tmp, "ALERTS.json")))
    assert [a["rule"] for a in alerts["firing"]] == ["min_goodput_pct"]

    print(
        f"metrics-smoke OK: {len(families)} families in-process, "
        f"{len(scraped)} over HTTP (port {port}), steps=20, "
        f"goodput={goodput:.1%}, SLO breach -> exit 3 + ALERTS.json; "
        f"fixture at {tmp}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
