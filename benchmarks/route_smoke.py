"""Router durability + scale-out smoke: 2 replicas, mixed sticky/free
traffic, one replica killed -9 mid-run — zero lost or duplicated requests,
then a clean drain. Also measures the scale-out ratio (2-replica fleet
tok/s over a 1-replica baseline on the same trace) and per-replica slot
occupancy from the fleet JSONL — ratios only, never absolute wall-clock
gates, per the timing-noise rule (this box's clock swings ±5x; the
credible ratio is a real multi-chip host).

Run directly (``make route-smoke``) or via ``bench.py route``.
"""

import json
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# replicas are separate single-device processes — the parent never imports
# jax, exactly like the production router host
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "4", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]


def _replica_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # single-device replicas: fast start, no oversubscription
    return env


def _payload(i, sticky_every=3, n_new=8):
    p = {"id": i, "prompt": [1 + i % 7, 5, 11, 2], "max_new_tokens": n_new}
    if i % sticky_every == 0:
        p["session_id"] = f"chat-{i % 2}"  # sticky lane
    return p


def _run_trace(router, n, offset=0):
    """Submit ``n`` mixed sticky/free requests, wait for every answer, and
    return (tickets, wall_seconds, tokens)."""
    t0 = time.perf_counter()
    tickets = [router.submit(_payload(offset + i)) for i in range(n)]
    if not router.wait_idle(timeout=600):
        raise RuntimeError("router never went idle")
    # nothing to fence: the timed work is HTTP round-trips to replica
    # subprocesses and the results arrive as fully materialized JSON
    # tpu-lint: ignore[TPU008]
    wall = time.perf_counter() - t0
    tokens = sum(
        len(t.result.get("tokens", [])) for t in tickets if isinstance(t.result, dict)
    )
    return tickets, wall, tokens


def _spawn_fleet(n, logdir):
    from accelerate_tpu.serving.replica import spawn_replica, wait_until_ready
    from accelerate_tpu.serving.router import Router

    replicas = [
        spawn_replica(i, list(ENGINE_ARGS), env=_replica_env()) for i in range(n)
    ]
    router = Router(replicas, logging_dir=logdir, health_interval=0.2)
    try:
        wait_until_ready(replicas, timeout=300)
    except Exception:
        router.close()
        raise
    return replicas, router


def _occupancy_by_replica(logdir):
    path = os.path.join(logdir, "router", "replicas.jsonl")
    sums, counts = {}, {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                slots = row.get("num_slots") or 0
                if row.get("state") == "ready" and slots:
                    rid = row["replica_id"]
                    sums[rid] = sums.get(rid, 0.0) + row.get("active_slots", 0) / slots
                    counts[rid] = counts.get(rid, 0) + 1
    except OSError:
        pass
    return {rid: sums[rid] / counts[rid] for rid in sums if counts.get(rid)}


def run(platform: str = "cpu", n_requests: int = 16) -> dict:
    result: dict = {"n_requests": n_requests}

    # -- leg 1: 2-replica fleet — measured trace, then the kill ------------
    with tempfile.TemporaryDirectory() as logdir:
        replicas, router = _spawn_fleet(2, logdir)
        try:
            tickets, fleet_wall, fleet_tokens = _run_trace(router, n_requests)
            lost = [t for t in tickets if not isinstance(t.result, dict)
                    or "error" in t.result]
            assert not lost, f"fleet leg lost {len(lost)} requests"
            result["occupancy_by_replica"] = _occupancy_by_replica(logdir)

            # kill -9 one replica with a second wave in flight (long budgets
            # hold the wave open well past the kill even on a fast box);
            # deliveries land via callback so a double-fire is *observable*
            # — ticket.result alone would silently overwrite a duplicate
            deliveries = []
            wave = [router.submit(_payload(n_requests + i, n_new=32),
                                  callback=deliveries.append)
                    for i in range(n_requests // 2)]
            victim = replicas[0]
            deadline = time.monotonic() + 30
            while victim.in_flight == 0 and time.monotonic() < deadline:
                time.sleep(0.01)  # wait until the victim really holds work
            assert victim.in_flight > 0, "dispatch never placed work on the victim"
            os.kill(victim.pid, signal.SIGKILL)
            if not router.wait_idle(timeout=600):
                raise RuntimeError("router never recovered from the kill")
            answered = [t.result for t in wave]
            assert len(deliveries) == len(wave), (
                f"{len(deliveries)} deliveries for {len(wave)} requests "
                "— a request was dropped or double-delivered after the kill"
            )
            ids = [r.get("id") for r in deliveries]
            assert len(ids) == len(set(ids)), "duplicated delivery after kill"
            errors = [r for r in answered if "error" in r]
            assert not errors, f"kill lost requests: {errors}"
            deadline = time.monotonic() + 10  # the 0.2s health loop must notice
            while router.stats()["dead"] != 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            stats = router.stats()
            assert stats["dead"] == 1, f"router missed the death: {stats}"
            assert stats["requeues"] >= 1, f"kill landed on an idle replica: {stats}"
            result["requeues"] = stats["requeues"]
            result["killed_replica"] = victim.replica_id
            clean = router.drain(timeout=120)
            assert clean, "post-kill drain did not exit cleanly"
        finally:
            router.close()

    # -- leg 2: 1-replica baseline on the identical trace ------------------
    with tempfile.TemporaryDirectory() as logdir:
        _, router = _spawn_fleet(1, logdir)
        try:
            tickets, single_wall, single_tokens = _run_trace(router, n_requests)
            assert all("error" not in t.result for t in tickets)
            router.drain(timeout=120)
        finally:
            router.close()

    result["fleet_tok_s"] = fleet_tokens / fleet_wall if fleet_wall > 0 else 0.0
    result["single_tok_s"] = single_tokens / single_wall if single_wall > 0 else 0.0
    result["route_goodput_ratio"] = (
        result["fleet_tok_s"] / result["single_tok_s"]
        if result["single_tok_s"] > 0 else 0.0
    )
    return result


def main() -> int:
    r = run()
    occ = "  ".join(
        f"r{rid}={v:.0%}" for rid, v in sorted(r["occupancy_by_replica"].items())
    )
    print(
        f"route-smoke OK: {r['n_requests']} + {r['n_requests'] // 2} requests, "
        f"kill -9 replica {r['killed_replica']} survived "
        f"({r['requeues']} requeue(s), zero lost/duplicated)\n"
        f"  fleet {r['fleet_tok_s']:.1f} tok/s vs single {r['single_tok_s']:.1f} "
        f"tok/s -> route_goodput_ratio {r['route_goodput_ratio']:.2f} "
        f"(CPU dispatch-bound; ratio only, occupancy {occ})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
