"""Dev sweep: framework train-step throughput vs (bsz, seq, remat) on the
attached chip. One subprocess per point (clean HBM). Not run by the driver —
`bench.py` is the recorded artifact; this explores the config space."""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one(bsz, seq, remat):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.mesh import data_sharding
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=16,
        max_position_embeddings=seq, remat=remat,
    )
    accelerator = Accelerator(mixed_precision="bf16")
    model, opt = accelerator.prepare(
        LlamaForCausalLM.from_config(config, seed=0), optax.adamw(1e-4)
    )
    n_params = sum(int(x.size) for x in jax.tree.leaves(model.params))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32000, size=(bsz, seq)).astype(np.int32)
    sharding = data_sharding(accelerator.mesh)
    batch = {k: jax.device_put(jnp.asarray(v), sharding) for k, v in
             {"input_ids": ids, "labels": ids}.items()}

    def step():
        out = model(**batch)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        return out.loss.force()

    for _ in range(2):
        last = step()
    float(np.asarray(last))
    t0 = time.perf_counter()
    for _ in range(10):
        last = step()
    float(np.asarray(last))
    t = (time.perf_counter() - t0) / 10

    tokens = bsz * seq
    attn = 6.0 * config.num_hidden_layers * tokens * seq * config.hidden_size
    flops = 6.0 * n_params * tokens + attn
    print(f"RESULT bsz={bsz} seq={seq} remat={remat} t={t*1000:.1f}ms "
          f"tok/s={tokens/t:.0f} mfu={flops/t/197e12:.4f}")


if __name__ == "__main__":
    if len(sys.argv) > 3:
        remat = {"0": False, "1": True}.get(sys.argv[3], sys.argv[3])
        _one(int(sys.argv[1]), int(sys.argv[2]), remat)
        sys.exit(0)
    points = [
        (8, 1024, "dots_saveable"),
        (16, 1024, "dots_saveable"),
        (32, 1024, "dots_saveable"),
        (32, 1024, "1"),
        (64, 1024, "1"),
    ]
    for bsz, seq, remat in points:
        for attempt in range(3):
            r = subprocess.run(
                [sys.executable, __file__, str(bsz), str(seq), str(remat)],
                capture_output=True, text=True, timeout=1200,
            )
            out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
            if r.returncode == 0 and out:
                print(out[0], flush=True)
                break
            err = (r.stdout + r.stderr)[-400:]
            if "RESOURCE_EXHAUSTED" in err or "Out of memory" in err:
                print(f"OOM bsz={bsz} seq={seq} remat={remat}", flush=True)
                break
            print(f"retry {bsz}/{seq}: {err}", flush=True)
            time.sleep(15)
