"""``make telemetry-smoke``: a 5-step toy train loop with telemetry on,
asserting the JSONL trail is well-formed — every line parses, the compile
event carries cost facts, step records carry throughput, and the summary
agrees with the trail. Exit code is the CI signal; prints a one-line OK."""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator

    tmp = tempfile.mkdtemp(prefix="telemetry_smoke_")
    acc = Accelerator(project_dir=tmp, telemetry=True)

    # 5 fixed-shape steps of a 2-parameter regression (y = 2x + 3)
    def make_model():
        from accelerate_tpu.test_utils import RegressionModel

        return RegressionModel(a=0.0, b=0.0)

    model, opt = acc.prepare(make_model(), optax.sgd(0.1))
    rng = np.random.default_rng(0)
    for _ in range(5):
        x = rng.standard_normal(16).astype(np.float32)
        out = model(x=x, y=(2 * x + 3).astype(np.float32))
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()

    path = acc.telemetry.jsonl_path
    assert path and os.path.exists(path), "no JSONL trail was written"
    records = [json.loads(line) for line in open(path)]
    assert all("type" in r and "ts" in r for r in records), "malformed record"

    steps = [r for r in records if r["type"] == "step"]
    compiles = [r for r in records if r["type"] == "compile"]
    assert len(steps) == 5, f"expected 5 step records, got {len(steps)}"
    assert compiles, "no compile event was recorded"
    assert "flops" in compiles[0] and "collective_bytes" in compiles[0]
    assert all(r["step_time_s"] > 0 for r in steps)
    assert all(r.get("examples_per_sec", 0) > 0 for r in steps)

    s = acc.telemetry.summary()
    assert s["steps"] == 5 and s["recompiles"] == len(compiles)
    assert {"p50", "p95", "max"} <= set(s["step_time_s"])

    print(
        f"telemetry-smoke OK: {len(records)} records "
        f"({len(steps)} steps, {len(compiles)} compiles), "
        f"p50 step {s['step_time_s']['p50'] * 1e3:.2f} ms, trail at {path}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
