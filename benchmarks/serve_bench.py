"""Serving goodput: continuous-batching engine vs static-batch generate().

Replays a Poisson-arrival, mixed-length request trace (uniform prompt
lengths, geometric output lengths — the canonical serving mix where static
batching burns decode slots as padding) against

(a) the :class:`~accelerate_tpu.serving.InferenceEngine` (slot-scheduled
    decode over the block-paged KV cache), and
(b) a static-batch baseline: requests grouped into arrival-order batches of
    ``num_slots``, each batch run through ``generate(use_cache=True)`` with
    ``max_new_tokens`` = the batch's largest budget — every request in the
    batch waits for the slowest one, which is exactly the regime
    iteration-level scheduling removes (Orca OSDI '22, vLLM SOSP '23).

Both legs run the same model/weights with compile time excluded (warmup
request / warmup batch before the clock starts). Reported: ``serve_tok_s``
(goodput — emitted tokens per wall second), ``static_tok_s``, TTFT/TPOT
percentiles (engine), mean slot occupancy, and the decode-compile count
(must be exactly 1 across the whole engine run — the one-executable
contract).

Arrivals are replayed in wall time: a request is submitted only once the
clock passes its Poisson arrival offset, so queueing and TTFT are real,
not simulated. Run standalone (``python benchmarks/serve_bench.py``) or
through ``bench.py`` mode ``serve`` (the artifact row).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclass
class TraceRequest:
    arrival_s: float  # offset from trace start
    prompt: "np.ndarray"
    max_new_tokens: int


def make_trace(
    n_requests: int,
    arrival_rate_per_s: float,
    prompt_range: tuple[int, int],
    mean_new_tokens: int,
    max_new_cap: int,
    vocab_size: int,
    seed: int = 0,
):
    """Poisson arrivals; uniform prompt lengths; geometric output budgets
    clipped to ``max_new_cap`` (heavy right tail → the static baseline's
    padding waste is realistic, not adversarial)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_per_s, size=n_requests))
    lo, hi = prompt_range
    trace = []
    for t in arrivals:
        plen = int(rng.integers(lo, hi + 1))
        new = int(min(1 + rng.geometric(1.0 / mean_new_tokens), max_new_cap))
        trace.append(
            TraceRequest(
                arrival_s=float(t),
                prompt=rng.integers(0, vocab_size, size=plen).astype(np.int32),
                max_new_tokens=new,
            )
        )
    return trace


def make_shared_prefix_trace(
    n_requests: int,
    arrival_rate_per_s: float,
    prefix_len: int,
    tail_range: tuple[int, int],
    mean_new_tokens: int,
    max_new_cap: int,
    vocab_size: int,
    shared_frac: float = 0.8,
    seed: int = 0,
):
    """The production-chat mix: ``shared_frac`` of requests open with ONE
    common system prompt of ``prefix_len`` tokens followed by a short
    unique tail; the rest are cold (fully random prompts of comparable
    total length). Prefill work is prefix-dominated by construction, so a
    prefix-sharing engine collapses TTFT on the shared fraction while the
    no-sharing engine re-prefills the same tokens every time."""
    import numpy as np

    rng = np.random.default_rng(seed)
    system_prompt = rng.integers(0, vocab_size, size=prefix_len).astype(np.int32)
    arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate_per_s, size=n_requests))
    lo, hi = tail_range
    trace = []
    for t in arrivals:
        tail_len = int(rng.integers(lo, hi + 1))
        new = int(min(1 + rng.geometric(1.0 / mean_new_tokens), max_new_cap))
        if rng.random() < shared_frac:
            prompt = np.concatenate(
                [system_prompt, rng.integers(0, vocab_size, size=tail_len).astype(np.int32)]
            )
        else:
            prompt = rng.integers(
                0, vocab_size, size=prefix_len + tail_len
            ).astype(np.int32)
        trace.append(
            TraceRequest(arrival_s=float(t), prompt=prompt, max_new_tokens=new)
        )
    return trace


def warm_engine(model, engine_config, trace):
    """Build the engine and compile its two programs on a dummy request."""
    from accelerate_tpu.serving import InferenceEngine

    engine = InferenceEngine(model, engine_config)
    engine.add_request(trace[0].prompt[: max(2, len(trace[0].prompt) // 2)], 2)
    engine.run_until_idle(max_iterations=10_000)
    return engine


def run_engine_leg(model, engine_config, trace, engine=None) -> dict:
    """Wall-clock replay through the engine. Compile excluded: the engine
    is pre-warmed (or warmed here) and ``reset_stats()`` drops the
    warmup's idle-engine TTFT and drain iterations from every reported
    percentile; the decode-compile counter survives the reset and must
    still read 1 afterwards — across repeated legs too."""
    if engine is None:
        engine = warm_engine(model, engine_config, trace)
    engine.reset_stats()

    t0 = time.perf_counter()
    pending = list(trace)
    while pending or engine.scheduler.has_work():
        # wall-clock arrival simulation, not a compute measurement;
        # engine.step() device_gets every iteration, so the `elapsed`
        # read is fenced by construction
        # tpu-lint: ignore[TPU008] — intentional wall-clock replay
        now = time.perf_counter() - t0
        while pending and pending[0].arrival_s <= now:
            tr = pending.pop(0)
            engine.add_request(tr.prompt, tr.max_new_tokens, arrival_time=t0 + tr.arrival_s)
        if engine.scheduler.has_work():
            engine.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0].arrival_s - now)))
    elapsed = time.perf_counter() - t0

    stats = engine.stats()
    useful = stats["tokens_emitted"]
    out = {
        "serve_tok_s": useful / elapsed if elapsed > 0 else 0.0,
        "elapsed_s": elapsed,
        "tokens": useful,
        "completed": stats["completed"],
        "occupancy": stats["slot_occupancy_mean"],
        "decode_compiles": stats["decode_compiles"],
        "prefill_compiles": stats["prefill_compiles"],
        "prefix_hit_ratio": stats.get("prefix_hit_ratio", 0.0),
        "preemptions": stats.get("preemptions", 0),
        # flight-recorder attribution over this leg only (reset_stats()
        # above zeroed the recorder): the async_smoke host-hiding gauges
        "host_fraction": stats.get("host_fraction"),
        "overlap_hidden_s": stats.get("overlap_hidden_s", 0.0),
    }
    for key in ("ttft_s", "tpot_s"):
        if key in stats:
            out[key] = stats[key]
    assert stats["decode_compiles"] == 1, (
        f"decode step recompiled: {stats['decode_compiles']} executables "
        "(the [num_slots, 1] program must be traced exactly once)"
    )
    return out


def run_static_leg(model, trace, batch_size: int, prewarmed: set | None = None) -> dict:
    """Static-batch baseline: arrival-order batches of ``batch_size``
    through ``generate(use_cache=True)``; a batch starts only when its last
    member has arrived AND the previous batch finished (one device, no
    overlap) — its decode length is the batch max, so short completions pad."""
    import numpy as np

    batches = [trace[i : i + batch_size] for i in range(0, len(trace), batch_size)]

    # warm every distinct (batch rows, prompt bucket, decode length) shape so
    # the timed region contains zero static-path compiles — the baseline's
    # best case, keeping the goodput ratio about scheduling, not caching.
    # Decode length is the batch's EXACT max budget (bucketing it up would
    # unfairly inflate the baseline's padding waste). A caller-shared
    # ``prewarmed`` set skips the (expensive, full-decode) warm runs on
    # repeated legs — the compiled programs are cached on the apply_fn.
    warmed = prewarmed if prewarmed is not None else set()
    for batch in batches:
        shape = (
            len(batch),
            _bucket(max(len(tr.prompt) for tr in batch)),
            max(tr.max_new_tokens for tr in batch),
        )
        if shape not in warmed:
            warmed.add(shape)
            rows, plen, new = shape
            ids = np.zeros((rows, plen), np.int32)
            mask = np.ones((rows, plen), np.int32)
            np.asarray(generate_ref(model, ids, mask, new))

    t0 = time.perf_counter()
    done_at = 0.0  # virtual clock: device busy until here (offsets from t0)
    total_tokens = 0
    for batch in batches:
        ready = max(tr.arrival_s for tr in batch)
        start = max(done_at, ready)
        now = time.perf_counter() - t0
        if start > now:
            time.sleep(start - now)
        _pad_generate(model, batch)
        done_at = time.perf_counter() - t0
        total_tokens += sum(tr.max_new_tokens for tr in batch)
    elapsed = done_at
    return {
        "static_tok_s": total_tokens / elapsed if elapsed > 0 else 0.0,
        "elapsed_s": elapsed,
        "tokens": total_tokens,
        "batches": len(batches),
    }


def _bucket(n: int) -> int:
    b = 16
    while b < n:
        b *= 2
    return b


def _pad_generate(model, batch):
    """One static batch: right-pad prompts to the batch's bucketed max,
    decode everyone to the batch's exact max budget — the padding waste
    static batching pays by construction. Power-of-two prompt buckets keep
    the whole trace on a handful of pre-warmed executables."""
    import numpy as np

    plen = _bucket(max(len(tr.prompt) for tr in batch))
    new = max(tr.max_new_tokens for tr in batch)
    ids = np.zeros((len(batch), plen), np.int32)
    mask = np.zeros((len(batch), plen), np.int32)
    for i, tr in enumerate(batch):
        ids[i, : len(tr.prompt)] = tr.prompt
        mask[i, : len(tr.prompt)] = 1
    out = generate_ref(model, ids, mask, new)
    np.asarray(out)
    return out


def generate_ref(model, ids, mask, new):
    from accelerate_tpu.generation import generate

    return generate(model, ids, max_new_tokens=new, use_cache=True, attention_mask=mask)


def default_workload(platform: str):
    """(model, engine config, trace) sized for the attached backend."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.serving import EngineConfig

    if platform == "cpu":  # smoke sizing
        config = LlamaConfig.tiny(vocab_size=256, hidden_size=64, layers=2, heads=4, seq=128)
        model = LlamaForCausalLM.from_config(config, seed=0)
        engine_cfg = EngineConfig(
            num_slots=8, block_size=8, max_seq_len=128, prefill_chunk=32
        )
        # arrival rate well above capacity: goodput (not arrival) limited.
        # NOTE the CPU leg is a *smoke* of the machinery, not a credible
        # ratio: at tiny-model shapes both legs are dispatch-bound and this
        # box's wall clock swings ±5x — the acceptance ratio is the TPU run
        trace = make_trace(
            n_requests=64, arrival_rate_per_s=500.0, prompt_range=(4, 24),
            mean_new_tokens=12, max_new_cap=96, vocab_size=config.vocab_size,
        )
    else:
        # the bench flagship slice (~700M), bf16 resident weights — same
        # model the decode_tok_s row measures
        config = LlamaConfig.flagship_700m(max_position_embeddings=512)
        model = LlamaForCausalLM.from_config(config, seed=0)
        model.params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            model.params,
        )
        engine_cfg = EngineConfig(
            num_slots=16, block_size=16, max_seq_len=512, prefill_chunk=128
        )
        # arrival rate ~10x a slot's decode rate: the queue stays non-empty,
        # so the ratio measures sustained goodput, not arrival gaps
        trace = make_trace(
            n_requests=64, arrival_rate_per_s=400.0, prompt_range=(32, 160),
            mean_new_tokens=24, max_new_cap=96, vocab_size=config.vocab_size,
        )
    return model, engine_cfg, trace


def run(platform: str, legs: int = 3) -> dict:
    """Interleaved engine/static legs (E/S/E/S/E/S), median-of-``legs`` per
    side — on a box with ±5x wall-clock swings a single-shot ratio is a
    contention artifact waiting to happen (the r5 fp8 lesson). Warmup
    (engine programs + every static shape) happens once, outside all legs."""
    model, engine_cfg, trace = default_workload(platform)
    engine = warm_engine(model, engine_cfg, trace)
    prewarmed: set = set()
    eng_legs, static_legs = [], []
    for _ in range(legs):
        eng_legs.append(run_engine_leg(model, engine_cfg, trace, engine=engine))
        static_legs.append(
            run_static_leg(model, trace, engine_cfg.num_slots, prewarmed=prewarmed)
        )
    eng = sorted(eng_legs, key=lambda r: r["serve_tok_s"])[legs // 2]
    static = sorted(static_legs, key=lambda r: r["static_tok_s"])[legs // 2]
    return {
        "engine": eng,
        "static": static,
        "engine_legs_tok_s": [round(r["serve_tok_s"], 1) for r in eng_legs],
        "static_legs_tok_s": [round(r["static_tok_s"], 1) for r in static_legs],
        "goodput_ratio": (
            eng["serve_tok_s"] / static["static_tok_s"]
            if static["static_tok_s"] else None
        ),
        "num_slots": engine_cfg.num_slots,
        "block_size": engine_cfg.block_size,
        "n_requests": len(trace),
    }


def radix_workload(platform: str):
    """(model, engine config, 80%-shared-prefix trace) for the prefix-
    sharing leg. Prompts are prefix-dominated (the production chat shape);
    tails and output budgets stay short so prefill — the work sharing
    removes — is the bottleneck under load."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.serving import EngineConfig

    if platform == "cpu":  # smoke sizing (see default_workload's caveat)
        config = LlamaConfig.tiny(vocab_size=256, hidden_size=64, layers=2, heads=4, seq=128)
        model = LlamaForCausalLM.from_config(config, seed=0)
        engine_cfg = EngineConfig(
            num_slots=8, block_size=8, max_seq_len=128, prefill_chunk=16
        )
        trace = make_shared_prefix_trace(
            n_requests=48, arrival_rate_per_s=500.0, prefix_len=64,
            tail_range=(4, 12), mean_new_tokens=8, max_new_cap=24,
            vocab_size=config.vocab_size,
        )
    else:
        config = LlamaConfig.flagship_700m(max_position_embeddings=512)
        model = LlamaForCausalLM.from_config(config, seed=0)
        model.params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            model.params,
        )
        engine_cfg = EngineConfig(
            num_slots=16, block_size=16, max_seq_len=512, prefill_chunk=128
        )
        trace = make_shared_prefix_trace(
            n_requests=64, arrival_rate_per_s=400.0, prefix_len=256,
            tail_range=(8, 48), mean_new_tokens=24, max_new_cap=96,
            vocab_size=config.vocab_size,
        )
    return model, engine_cfg, trace


def run_radix(platform: str, legs: int = 3) -> dict:
    """Prefix sharing on vs off (the FCFS/no-sharing PR 4 engine) on the
    SAME 80%-shared-prefix trace and model — interleaved R/C legs,
    median-of-``legs`` per side, ratios only (the timing-noise rule). The
    sharing engine's radix cache warms on leg 1 and stays warm (the
    steady-state a long-lived server sits in); both engines keep the
    one-decode-executable contract, asserted inside every leg."""
    from dataclasses import replace

    model, engine_cfg, trace = radix_workload(platform)
    sharing_cfg = replace(engine_cfg, prefix_cache=True)
    cold_cfg = replace(engine_cfg, prefix_cache=False)
    sharing_engine = warm_engine(model, sharing_cfg, trace)
    cold_engine = warm_engine(model, cold_cfg, trace)
    share_legs, cold_legs = [], []
    for _ in range(legs):
        share_legs.append(run_engine_leg(model, sharing_cfg, trace, engine=sharing_engine))
        cold_legs.append(run_engine_leg(model, cold_cfg, trace, engine=cold_engine))
    share = sorted(share_legs, key=lambda r: r["serve_tok_s"])[legs // 2]
    cold = sorted(cold_legs, key=lambda r: r["serve_tok_s"])[legs // 2]
    return {
        "sharing": share,
        "no_sharing": cold,
        "sharing_legs_tok_s": [round(r["serve_tok_s"], 1) for r in share_legs],
        "no_sharing_legs_tok_s": [round(r["serve_tok_s"], 1) for r in cold_legs],
        "radix_goodput_ratio": (
            share["serve_tok_s"] / cold["serve_tok_s"]
            if cold["serve_tok_s"] else None
        ),
        "prefix_hit_ratio": share["prefix_hit_ratio"],
        "ttft_p50_sharing_s": share.get("ttft_s", {}).get("p50"),
        "ttft_p50_cold_s": cold.get("ttft_s", {}).get("p50"),
        "num_slots": engine_cfg.num_slots,
        "block_size": engine_cfg.block_size,
        "n_requests": len(trace),
    }


if __name__ == "__main__":
    import jax

    platform = jax.devices()[0].platform
    if len(sys.argv) > 1 and sys.argv[1] == "radix":
        result = run_radix(platform)
    else:
        result = run(platform)
    print(json.dumps(result, indent=2, default=float))
    sys.exit(0)
