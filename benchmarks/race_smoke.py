"""race-check + LockWatch smoke: the concurrency gate proves itself.

Three legs, mirroring ``lint_smoke``/``shard_smoke``:

1. **clean tree** — the real CLI race-checks the gated dirs
   (``serving``/``metrics``/``diagnostics``/``commands``/``analysis``)
   and must come back 0 errors / 0 warnings with exit 0 (the ``make
   lint`` gate);
2. **seeded inversion** — a temp file with two locks taken in opposite
   orders exits 2 naming RC002 (the gate actually gates);
3. **chaos fleet under LockWatch** — the PR 11 chaos schedule (kill -9 +
   503 burst + injected delay) runs against a real supervised 2-replica
   fleet with LockWatch armed on the router/supervisor locks
   (``ACCELERATE_SANITIZE=1`` in the replicas too): every request
   answered exactly once, zero orphaned processes, **zero lock-order
   violations**, no ``RACE_REPORT_*.json`` — and the hold-time
   histograms exist for every watched lock that was ever taken.

Run directly (``make race-smoke``).
"""

import glob
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATED = [
    os.path.join("accelerate_tpu", d)
    for d in ("serving", "metrics", "diagnostics", "commands", "analysis")
]

INVERSION = """
import threading

a = threading.Lock()
b = threading.Lock()

def forward():
    with a:
        with b:
            pass

def backward():
    with b:
        with a:
            pass
"""


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "race-check", *args],
        capture_output=True, text=True, cwd=REPO, timeout=600,
    )


def leg_clean_tree() -> dict:
    proc = _cli("--json", *GATED)
    assert proc.returncode == 0, f"tree has race findings:\n{proc.stdout}\n{proc.stderr}"
    payload = json.loads(proc.stdout)
    assert payload["errors"] == 0 and payload["warnings"] == 0, payload
    assert payload["files_scanned"] > 30
    return {"files_scanned": payload["files_scanned"]}


def leg_seeded_inversion() -> dict:
    with tempfile.TemporaryDirectory() as tmp:
        bad = os.path.join(tmp, "inversion.py")
        with open(bad, "w") as f:
            f.write(INVERSION)
        proc = _cli(bad)
        assert proc.returncode == 2, (
            f"seeded inversion not caught (exit {proc.returncode}):\n{proc.stdout}"
        )
        assert "RC002" in proc.stdout, proc.stdout
    return {"seeded_exit": 2}


def leg_chaos_fleet_under_lockwatch() -> dict:
    from accelerate_tpu.analysis.lockwatch import LockWatch, set_active_lockwatch

    from chaos_smoke import (
        CHAOS_SPEC,
        MIN_REPLICAS,
        _assert_no_orphans,
        _run_trace,
        _spawn_fleet,
    )

    n_requests = 12
    with tempfile.TemporaryDirectory() as logdir:
        # arm LockWatch for the in-process router/supervisor locks AND the
        # replica subprocesses (ACCELERATE_SANITIZE=1 rides the env); the
        # replicas' own RACE_REPORTs must land in logdir too, or the glob
        # below could never see a replica-side violation
        os.environ["ACCELERATE_SANITIZE"] = "1"
        os.environ["ACCELERATE_LOCKWATCH_DIR"] = logdir
        watch = LockWatch(report_dir=logdir, host="race_smoke")
        set_active_lockwatch(watch)
        try:
            router, pids = _spawn_fleet(
                MIN_REPLICAS, logdir, chaos_spec=CHAOS_SPEC, supervised=True
            )
            try:
                deliveries, _, _ = _run_trace(router, n_requests)
                errors = [r for r in deliveries if "error" in r]
                assert not errors, f"faults leaked as error rows: {errors}"
                assert router.drain(timeout=120), "post-chaos drain failed"
            finally:
                router.close()
            _assert_no_orphans(pids)
        finally:
            set_active_lockwatch(None)
            os.environ.pop("ACCELERATE_SANITIZE", None)
            os.environ.pop("ACCELERATE_LOCKWATCH_DIR", None)

        report = watch.report()
        assert watch.violations == 0, (
            f"LockWatch saw lock-order violations under chaos: {report['reports']}"
        )
        races = glob.glob(os.path.join(logdir, "RACE_REPORT_*.json"))
        assert not races, f"race report(s) written on a clean run: {races}"
        hist = report["hold_time_histograms"]
        assert any(name.startswith("Router._lock") for name in hist), (
            f"router lock never sampled: {sorted(hist)}"
        )
        return {
            "requests": n_requests,
            "violations": watch.violations,
            "order_edges": len(report["edges"]),
            "locks_sampled": sorted(hist),
            "router_lock_hold_p99_ms": hist.get("Router._lock", {}).get("p99_ms"),
        }


def main() -> int:
    clean = leg_clean_tree()
    seeded = leg_seeded_inversion()
    chaos = leg_chaos_fleet_under_lockwatch()
    print(
        f"race-smoke OK: tree clean 0/0 over {clean['files_scanned']} files; "
        f"seeded inversion exit {seeded['seeded_exit']} naming RC002; "
        f"chaos fleet ({chaos['requests']} requests, kill+503+delay) ran with "
        f"LockWatch armed — {chaos['violations']} violations, "
        f"{chaos['order_edges']} order edge(s), zero orphans; locks sampled: "
        f"{', '.join(chaos['locks_sampled'])} "
        f"(Router._lock hold p99 {chaos['router_lock_hold_p99_ms']} ms)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
