"""Quantized KV cache smoke: capacity doubling + fused-kernel agreement.

Proves the kv_dtype policy's contracts end-to-end on CPU-sized shapes:

1. **capacity** — at an equal HBM budget the int8 pool holds
   ``>= 1.8x`` the blocks of the bf16 pool (the exact ratio is
   ``2*hd/(hd+4)`` — 1.94x at the flagship's hd=128), measured through the
   same ``auto_num_blocks`` sizing ``serve --auto-blocks`` uses. Pure
   byte math — deterministic, no wall clock anywhere near it;
2. **pressure** — the radix shared-prefix pressure scenario at an equal
   synthetic pool-byte budget: the int8 engine serves with ~2x the blocks
   of the bf16 engine, completes every request un-truncated, and both
   keep the one-compiled-decode-executable contract;
3. **agreement** — the fused lax walk and the gather-then-dense reference
   agree on the same quantized pool to f32 noise (same stored bytes, same
   math), and both sit within the documented int8 tolerance of the f32
   reference;
4. **paged_attn_ratio** — timeit (min-of-5) of the fused walk vs the PR 4
   gather path at a mid-size decode shape. Reported as a ratio only,
   never gated (the ±5x box rule): the credible number is the TPU run,
   where the Pallas kernel replaces the lax scan.

Run via ``make kvq-smoke``; ``bench.py kv`` consumes :func:`run`.
"""

from __future__ import annotations

import json
import os
import sys
import timeit

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capacity_blocks(dtype: str, budget_bytes: int, *, num_layers=16,
                    num_kv_heads=12, head_dim=128, block_size=16,
                    max_seq_len=512) -> tuple[int, int]:
    """(num_blocks, per_block_bytes) the HBM model fits under
    ``budget_bytes`` of pool budget at the flagship serving geometry."""
    from accelerate_tpu.analysis.shardplan import auto_num_blocks, plan_kv_pool

    sizes = {ax: 1 for ax in ("dp", "pp", "fsdp", "ep", "cp", "tp")}
    per_block = sum(
        p.bytes_per_device
        for p in plan_kv_pool(
            num_layers=num_layers, num_kv_heads=num_kv_heads, head_dim=head_dim,
            num_slots=1, block_size=block_size, max_seq_len=max_seq_len,
            num_blocks=1, mesh_sizes=sizes, dtype=dtype,
        )
    )
    blocks, _ = auto_num_blocks(
        budget_bytes, 0, per_block, full_residency_blocks=10**9, min_blocks=2,
        reserve_frac=0.0,
    )
    return blocks, per_block


def _paged_attn_ratio() -> dict:
    """Fused (lax walk) vs gather-reference decode attention: jitted,
    warmed, timeit min-of-5 — the overhead-bar pattern every bench row on
    this box uses (never a raw wall-clock gate)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.ops.paged_attention import paged_attention

    b, nh, n_kv, hd, bs, mb = 8, 8, 4, 64, 16, 32
    nb = b * mb + 1
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, 1, nh, hd)).astype(np.float32))
    kp = jnp.asarray(rng.normal(size=(nb, bs, n_kv, hd)).astype(np.float32))
    vp = jnp.asarray(rng.normal(size=(nb, bs, n_kv, hd)).astype(np.float32))
    bt = np.arange(1, nb, dtype=np.int32).reshape(b, mb)
    idx = np.full((b,), mb * bs - 1, np.int32)

    legs = {}
    for impl in ("lax", "gather"):
        fn = jax.jit(lambda q, kp, vp, impl=impl: paged_attention(
            q, kp, vp, bt, idx, impl=impl
        ))
        fn(q, kp, vp).block_until_ready()  # compile + warm outside the timer
        legs[impl] = min(
            timeit.repeat(lambda: fn(q, kp, vp).block_until_ready(),
                          repeat=5, number=3)
        ) / 3
    return {
        "paged_attn_fused_s": legs["lax"],
        "paged_attn_gather_s": legs["gather"],
        "paged_attn_ratio": legs["gather"] / legs["lax"] if legs["lax"] else None,
    }


def run(platform: str) -> dict:
    import numpy as np

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.serving import EngineConfig, InferenceEngine
    from benchmarks.serve_bench import make_shared_prefix_trace

    # -- 1: capacity at the flagship geometry, equal budget
    budget = 1 << 30
    bf16_blocks, bf16_per_block = capacity_blocks("bfloat16", budget)
    int8_blocks, int8_per_block = capacity_blocks("int8", budget)
    capacity_ratio = int8_blocks / bf16_blocks
    assert capacity_ratio >= 1.8, (
        f"int8 should hold >=1.8x the blocks of bf16, got {capacity_ratio:.3f}"
    )

    # -- 2: the radix pressure scenario at an equal pool-byte budget —
    # derive each engine's num_blocks from the SAME byte budget and run
    # the same shared-prefix trace; int8's ~2x blocks complete everything
    config = LlamaConfig.tiny(vocab_size=256, hidden_size=64, layers=2,
                              heads=4, seq=128)
    model = LlamaForCausalLM.from_config(config, seed=0)
    geom = dict(num_layers=config.num_hidden_layers,
                num_kv_heads=config.num_key_value_heads,
                head_dim=config.head_dim, block_size=8, max_seq_len=128)
    # budget tuned so bf16 gets 13 usable blocks and int8 21: with 2 decode
    # slots the worst-case live need is 2 x ceil((48+12+16)/8) = 20 blocks,
    # so the int8 engine ALWAYS completes un-truncated while bf16 cannot
    # hold both worst-case requests — the capacity doubling made visible
    # as completed requests, not just a byte count
    tiny_budget = 14 * 2 * 2 * geom["num_layers"] * geom["num_kv_heads"] \
        * geom["head_dim"] * geom["block_size"]
    blocks = {
        dtype: capacity_blocks(dtype, tiny_budget, **geom)[0]
        for dtype in ("bfloat16", "int8")
    }
    # the capacity ratio is 2*hd/(hd+4): 1.94x at flagship hd=128 (gated
    # >=1.8 above), 1.6x at this tiny model's hd=16 — assert the formula,
    # not the flagship number
    expect_ratio = 2 * geom["head_dim"] / (geom["head_dim"] + 4)
    assert blocks["int8"] >= 0.9 * expect_ratio * blocks["bfloat16"]
    trace = make_shared_prefix_trace(
        n_requests=16, arrival_rate_per_s=500.0, prefix_len=48,
        tail_range=(4, 12), mean_new_tokens=6, max_new_cap=16,
        vocab_size=config.vocab_size,
    )
    results = {}
    for kv_dtype, nb in (("bf16", blocks["bfloat16"]), ("int8", blocks["int8"])):
        eng = InferenceEngine(model, EngineConfig(
            num_slots=2, block_size=8, max_seq_len=128, prefill_chunk=16,
            num_blocks=nb, kv_dtype=kv_dtype,
        ))
        reqs = [
            eng.add_request(r.prompt, r.max_new_tokens) for r in trace
        ]
        eng.run_until_idle(max_iterations=20000)
        st = eng.stats()
        assert st["decode_compiles"] == 1, (kv_dtype, st["decode_compiles"])
        results[kv_dtype] = {
            "num_blocks": nb,
            "completed": st["completed"],
            "out_of_blocks": st["out_of_blocks_total"],
            "truncated": sum(r.finish_reason == "out_of_blocks" for r in reqs),
            "kv_bytes_per_token": st["kv_bytes_per_token"],
            "prefix_hit_ratio": round(st["prefix_hit_ratio"], 4),
        }
    assert results["int8"]["truncated"] == 0, results
    assert results["int8"]["completed"] == len(trace)
    assert results["bf16"]["truncated"] >= 1, (
        "the bf16 leg no longer truncates — the pressure scenario has "
        "gone slack, retune tiny_budget"
    )

    # -- 3: fused and gather agree on the same quantized pool
    import jax.numpy as jnp

    from accelerate_tpu.ops.layers import write_paged_kv
    from accelerate_tpu.ops.paged_attention import paged_attention

    rng = np.random.default_rng(1)
    nb_, bs_, n_kv_, hd_ = 6, 8, 4, 16
    kp = jnp.zeros((nb_, bs_, n_kv_, hd_), jnp.int8)
    vp = jnp.zeros_like(kp)
    ks = jnp.ones((nb_, bs_, n_kv_), jnp.float32)
    vs = jnp.ones_like(ks)
    bt = np.asarray([[1, 2, 3, 4, 5]], np.int32)
    for p in range(30):
        k = jnp.asarray(rng.normal(size=(1, 1, n_kv_, hd_)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 1, n_kv_, hd_)).astype(np.float32))
        kp, vp, ks, vs = write_paged_kv(
            kp, vp, k, v, bt, np.asarray([[p]], np.int32),
            k_scale_l=ks, v_scale_l=vs,
        )
    q = jnp.asarray(rng.normal(size=(1, 1, 8, hd_)).astype(np.float32))
    idx = np.asarray([29], np.int32)
    fused = np.asarray(paged_attention(q, kp, vp, bt, idx, k_scale_l=ks,
                                       v_scale_l=vs, impl="lax"))
    gathered = np.asarray(paged_attention(q, kp, vp, bt, idx, k_scale_l=ks,
                                          v_scale_l=vs, impl="gather"))
    agree = float(np.abs(fused - gathered).max())
    assert agree < 1e-4, f"fused and gather diverged on the same bytes: {agree}"

    out = {
        "kv_bytes_per_token_bf16": results["bf16"]["kv_bytes_per_token"],
        "kv_bytes_per_token_int8": results["int8"]["kv_bytes_per_token"],
        "kv_slot_capacity_ratio": round(capacity_ratio, 4),
        "flagship_blocks_bf16": bf16_blocks,
        "flagship_blocks_int8": int8_blocks,
        "flagship_per_block_bytes": {"bf16": bf16_per_block, "int8": int8_per_block},
        "pressure": results,
        "fused_vs_gather_max_diff": agree,
        **_paged_attn_ratio(),
    }
    return out


def main() -> int:
    r = run("cpu")
    print(json.dumps(r, indent=2))
    print("KVQ SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
