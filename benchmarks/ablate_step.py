"""Dev ablation: where does the seq-1024 train step spend its time?
Times (a) fwd loss only, (b) fwd+bwd, (c) full step, under flash vs
blockwise attention and with/without the fused CE path. One subprocess
per point (clean HBM)."""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one(mode, attn_impl):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.ops.attention import AttentionContext, set_attention_context
    from accelerate_tpu.mesh import single_device_mesh

    set_attention_context(AttentionContext(mesh=single_device_mesh(), impl=attn_impl))

    config = LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=16,
        max_position_embeddings=1024, remat="dots_saveable",
    )
    model = LlamaForCausalLM.from_config(config, seed=0)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 32000, size=(8, 1024)).astype(np.int32))

    def cast(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x, p
        )

    def loss_fn(p, ids):
        return model.apply_fn(cast(p), input_ids=ids, labels=ids)["loss"].astype(jnp.float32)

    params = model.params
    if mode == "fwd":
        fn = jax.jit(loss_fn)
        def step():
            return fn(params, ids)
    elif mode == "fwdbwd":
        def vg(p, i):
            loss, grads = jax.value_and_grad(loss_fn)(p, i)
            # force the whole backward: fold every grad into the scalar
            return loss + sum(jnp.sum(g).astype(jnp.float32) for g in jax.tree.leaves(grads)) * 0.0
        g = jax.jit(vg)
        def step():
            return g(params, ids)
    else:  # full
        tx = optax.adamw(1e-4)
        opt_state = tx.init(params)

        import functools
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train(p, s, i):
            loss, grads = jax.value_and_grad(loss_fn)(p, i)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            up, s = tx.update(grads, s, p)
            return optax.apply_updates(p, up), s, loss
        state = {"p": params, "s": opt_state}
        def step():
            state["p"], state["s"], loss = train(state["p"], state["s"], ids)
            return loss

    for _ in range(2):
        last = step()
    float(np.asarray(last))
    t0 = time.perf_counter()
    for _ in range(10):
        last = step()
    float(np.asarray(last))
    t = (time.perf_counter() - t0) / 10
    print(f"RESULT mode={mode} attn={attn_impl} t={t*1000:.1f}ms")


if __name__ == "__main__":
    if len(sys.argv) > 2:
        _one(sys.argv[1], sys.argv[2])
        sys.exit(0)
    import sys as _s
    points = [("fwdbwd", "flash"), ("fwdbwd", "blockwise")]
    for mode, impl in points:
        for attempt in range(2):
            r = subprocess.run(
                [sys.executable, __file__, mode, impl],
                capture_output=True, text=True, timeout=600,
            )
            out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
            if r.returncode == 0 and out:
                print(out[0], flush=True)
                break
            print(f"retry {mode}/{impl}: {(r.stdout + r.stderr)[-300:]}", flush=True)
            time.sleep(10)
