"""``make lint-smoke``: the static-analysis pass + sanitizer end to end.

Four assertions, exit code is the CI signal:

1. a seeded-bad script trips error-severity rules through the REAL CLI
   (``accelerate-tpu lint --json`` exit 2, rule IDs present);
2. the shipped ``examples/`` + ``benchmarks/`` tree is clean (the
   self-application gate `make lint` enforces);
3. a deliberately shape-unstable toy loop under ``ACCELERATE_SANITIZE=1``
   reports the re-trace on stderr NAMING the offending argument;
4. the sanitizer wrote this host's collective-digest file and the
   monitor-side reader parses it back.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import textwrap

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BAD_SCRIPT = textwrap.dedent(
    """
    import time, random
    import jax
    import numpy as np

    @jax.jit
    def train_step(params, x):
        loss = (x * params).sum()
        if loss > 1.0:          # TPU004
            loss = loss * 0.5
        v = loss.item()         # TPU001
        t = time.time()         # TPU006
        return loss
    """
)


def main() -> int:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    # 1. seeded positives exit 2 with the right rule IDs
    with tempfile.TemporaryDirectory(prefix="lint_smoke_") as tmp:
        bad = os.path.join(tmp, "bad_train.py")
        with open(bad, "w") as f:
            f.write(BAD_SCRIPT)
        proc = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "lint", "--json", bad],
            capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
        )
        assert proc.returncode == 2, (proc.returncode, proc.stderr[-2000:])
        payload = json.loads(proc.stdout)
        rules = {f["rule"] for f in payload["findings"]}
        assert {"TPU001", "TPU004", "TPU006"} <= rules, rules

    # 2. the shipped tree is clean
    proc = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "lint", "--json", "examples", "benchmarks"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["errors"] == 0 and payload["warnings"] == 0, payload["findings"]

    # 3 + 4. runtime sanitizer on a shape-unstable loop (subprocess so
    # ACCELERATE_SANITIZE=1 — the env-var arming path — is what is proven)
    with tempfile.TemporaryDirectory(prefix="lint_smoke_run_") as tmp:
        loop = os.path.join(tmp, "unstable.py")
        with open(loop, "w") as f:
            f.write(textwrap.dedent(
                """
                import os, sys
                import numpy as np
                import optax
                from accelerate_tpu import Accelerator
                from accelerate_tpu.test_utils import RegressionModel

                acc = Accelerator(project_dir=os.environ["RUN_DIR"], telemetry=True)
                assert acc.sanitizer is not None, "ACCELERATE_SANITIZE=1 not honored"
                model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
                for n in (16, 24, 32):
                    x = np.linspace(-1, 1, n).astype(np.float32)
                    out = model(x=x, y=(2 * x + 3).astype(np.float32))
                    acc.backward(out.loss)
                    opt.step(); opt.zero_grad()
                acc.end_training()
                print("UNSTABLE_DONE")
                """
            ))
        run_dir = os.path.join(tmp, "run")
        os.makedirs(run_dir)
        proc = subprocess.run(
            [sys.executable, loop],
            capture_output=True, text=True, cwd=REPO,
            env={**env, "ACCELERATE_SANITIZE": "1", "RUN_DIR": run_dir,
                 "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")},
            timeout=420,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "UNSTABLE_DONE" in proc.stdout
        assert "TPU-SANITIZER[retrace]" in proc.stderr, proc.stderr[-2000:]
        assert "'inputs'" in proc.stderr, proc.stderr[-2000:]

        from accelerate_tpu.analysis.compiled import read_host_digests

        digests = read_host_digests(run_dir)
        assert 0 in digests and digests[0], digests

    print("LINT_SMOKE_OK: CLI exit codes, clean self-application, "
          "sanitizer retrace naming + digest files all verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
