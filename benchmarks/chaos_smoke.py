"""Self-healing fleet smoke: a seeded fault schedule (kill -9, injected
503 burst, response delay) against a supervised 2-replica fleet. Asserts
the invariants that make the robustness story honest:

* every submitted request is answered **exactly once** (callback-counted —
  ``ticket.result`` alone would silently overwrite a duplicate);
* **zero orphaned processes** — every pid the fleet ever spawned
  (including respawned incarnations) is gone after drain;
* the fleet **recovers to the target replica count** via supervised
  respawn (crash-loop backoff visible in the fleet trail);
* goodput under faults is reported as a **ratio** of the clean-leg
  goodput on the identical trace — never an absolute wall-clock gate,
  per the timing-noise rule (this box's clock swings ±5x).

Run directly (``make chaos-smoke``) or via ``bench.py chaos``.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# replicas are separate single-device processes — the parent never imports
# jax, exactly like the production router host
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "4", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]

#: the seeded schedule: replica 0 dies at its 5th request (with requests in
#: flight), replica 1 answers a 503 burst (router requeues, not final) and
#: injects a response delay — all keyed on request ordinals, so the same
#: spec against the same trace produces the same failure sequence
CHAOS_SPEC = "seed=1;r0:kill@5;r1:err503@2:2;r1:delay@3:0.05"
MIN_REPLICAS = 2


def _replica_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_CHAOS_SPEC", None)
    return env


def _payload(i, n_new=8):
    p = {"id": i, "prompt": [1 + i % 7, 5, 11, 2], "max_new_tokens": n_new}
    if i % 3 == 0:
        p["session_id"] = f"chat-{i % 2}"
    return p


def _spawn_fleet(n, logdir, chaos_spec=None, supervised=False):
    from accelerate_tpu.serving.replica import spawn_replica, wait_until_ready
    from accelerate_tpu.serving.router import Router
    from accelerate_tpu.serving.supervisor import ReplicaSupervisor, SupervisorConfig

    args = list(ENGINE_ARGS)
    if chaos_spec:
        args += ["--chaos-spec", chaos_spec]

    spawned_pids = []

    def spawn_fn(replica_id):
        handle = spawn_replica(replica_id, list(args), env=_replica_env())
        spawned_pids.append(handle.pid)
        return handle

    replicas = [spawn_fn(i) for i in range(n)]
    supervisor = None
    if supervised:
        supervisor = ReplicaSupervisor(
            spawn_fn,
            SupervisorConfig(min_replicas=n, max_replicas=n,
                             backoff_base_s=0.25, seed=0),
        )
    router = Router(
        replicas, logging_dir=logdir, health_interval=0.2, supervisor=supervisor
    )
    try:
        wait_until_ready(replicas, timeout=300)
    except Exception:
        router.close()
        raise
    return router, spawned_pids


def _run_trace(router, n, offset=0):
    """Submit ``n`` requests, wait for every answer; deliveries land via
    callback so a double-fire is observable. Returns (deliveries, wall,
    tokens)."""
    deliveries = []
    t0 = time.perf_counter()
    tickets = [
        router.submit(_payload(offset + i), callback=deliveries.append)
        for i in range(n)
    ]
    if not router.wait_idle(timeout=600):
        raise RuntimeError("router never went idle")
    # nothing to fence: the timed work is HTTP round-trips to replica
    # subprocesses, results arrive as materialized JSON
    # tpu-lint: ignore[TPU008]
    wall = time.perf_counter() - t0
    assert len(deliveries) == len(tickets), (
        f"{len(deliveries)} deliveries for {len(tickets)} requests — "
        "a request was dropped or double-delivered"
    )
    ids = [r.get("id") for r in deliveries]
    assert len(ids) == len(set(ids)), "duplicated delivery"
    tokens = sum(len(r.get("tokens", [])) for r in deliveries if isinstance(r, dict))
    return deliveries, wall, tokens


def _assert_no_orphans(pids, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = []
        for pid in pids:
            try:
                os.kill(pid, 0)
                alive.append(pid)
            except OSError:
                pass
        if not alive:
            return
        time.sleep(0.25)
    raise AssertionError(f"orphaned process(es) after the run: {alive}")


def run(platform: str = "cpu", n_requests: int = 16) -> dict:
    result: dict = {"n_requests": n_requests, "chaos_spec": CHAOS_SPEC}

    # LockWatch rides the whole run: the router/supervisor locks are
    # wrapped in order-graph shims, and the seeded kill/503/delay schedule
    # must complete with ZERO lock-order violations (the runtime half of
    # `accelerate-tpu race-check`)
    from accelerate_tpu.analysis.lockwatch import (
        LockWatch,
        get_active_lockwatch,
        set_active_lockwatch,
    )

    prior_watch = get_active_lockwatch()
    watch = LockWatch(host="chaos_smoke")
    set_active_lockwatch(watch)

    # the process-global watch must be restored even when a leg fails —
    # a leaked armed watch would wrap every later lock in this process
    try:
        # -- leg 1: clean supervised fleet (the baseline goodput) --------------
        with tempfile.TemporaryDirectory() as logdir:
            router, pids = _spawn_fleet(MIN_REPLICAS, logdir, supervised=True)
            try:
                deliveries, clean_wall, clean_tokens = _run_trace(router, n_requests)
                errors = [r for r in deliveries if "error" in r]
                assert not errors, f"clean leg errored: {errors}"
                assert router.drain(timeout=120), "clean drain failed"
            finally:
                router.close()
            _assert_no_orphans(pids)

        # -- leg 2: identical trace under the seeded fault schedule ------------
        with tempfile.TemporaryDirectory() as logdir:
            router, pids = _spawn_fleet(
                MIN_REPLICAS, logdir, chaos_spec=CHAOS_SPEC, supervised=True
            )
            try:
                deliveries, fault_wall, fault_tokens = _run_trace(router, n_requests)
                errors = [r for r in deliveries if "error" in r]
                assert not errors, f"faults leaked as error rows: {errors}"

                # the fleet must RECOVER to the target count via respawn
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    stats = router.stats()
                    if stats["ready"] >= MIN_REPLICAS:
                        break
                    time.sleep(0.25)
                stats = router.stats()
                assert stats["ready"] >= MIN_REPLICAS, (
                    f"fleet never recovered: {stats['ready']}/{MIN_REPLICAS} ready"
                )
                assert stats["supervisor"]["respawns"] >= 1, (
                    "the kill never triggered a supervised respawn"
                )
                result["respawns"] = stats["supervisor"]["respawns"]
                result["requeues"] = stats["requeues"]
                result["recovery_ratio"] = stats["ready"] / MIN_REPLICAS
                # crash-loop backoff is visible in the fleet trail
                trail = os.path.join(logdir, "router", "replicas.jsonl")
                rows = [json.loads(line) for line in open(trail) if line.strip()]
                assert any(
                    r.get("replica_id") == 0 and r.get("backoff_s", 0) > 0
                    for r in rows
                ), "backoff never reached the fleet trail"
                assert any(
                    r.get("replica_id") == 0 and r.get("restarts", 0) >= 1
                    for r in rows
                ), "restart count never reached the fleet trail"
                assert router.drain(timeout=120), "post-chaos drain failed"
            finally:
                router.close()
            _assert_no_orphans(pids)
    finally:
        set_active_lockwatch(prior_watch)

    assert watch.violations == 0, (
        f"LockWatch saw {watch.violations} lock-order violation(s) under "
        f"chaos: {watch.report()['reports']}"
    )
    result["lock_order_violations"] = watch.violations
    result["locks_watched"] = sorted(watch.hold_histograms())

    result["clean_tok_s"] = clean_tokens / clean_wall if clean_wall > 0 else 0.0
    result["fault_tok_s"] = fault_tokens / fault_wall if fault_wall > 0 else 0.0
    result["chaos_goodput_ratio"] = (
        result["fault_tok_s"] / result["clean_tok_s"]
        if result["clean_tok_s"] > 0 else 0.0
    )
    return result


def main() -> int:
    r = run()
    print(
        f"chaos-smoke OK: {r['n_requests']} + {r['n_requests']} requests under "
        f"'{r['chaos_spec']}' — exactly-once delivery, zero orphans, "
        f"{r['respawns']} respawn(s), recovery {r['recovery_ratio']:.0%} of "
        f"target fleet, {r['lock_order_violations']} lock-order violation(s) "
        f"with LockWatch armed on {len(r['locks_watched'])} lock(s)\n"
        f"  goodput under faults {r['fault_tok_s']:.1f} tok/s vs clean "
        f"{r['clean_tok_s']:.1f} tok/s -> chaos_goodput_ratio "
        f"{r['chaos_goodput_ratio']:.2f} ({r['requeues']} requeue(s); CPU "
        f"dispatch-bound, ratio only)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
