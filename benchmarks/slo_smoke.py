"""SLO closed-loop smoke: the seeded ``overbudget-storm`` scenario on a
real 2-replica fleet, run twice.

What it pins, end to end:

1. **Determinism** — the two runs' ``WORKLOAD.json`` manifests carry the
   identical ``schedule_sha256`` (same spec ⇒ byte-identical schedule);
2. **The closed loop** — the storm's impossible ``deadline_ms`` budgets
   breach the armed windowed objectives, and the supervisor's SLO policy
   logs ``kind:"scale_decision"`` rows *with the evidence attached*
   (objective, burn rate, dominant phase);
3. **Scorecard agreement** — ``slo report`` verdicts round-trip through
   ``--json``, and the exporter's ``slo_burn_rate{objective=…}`` gauges
   agree with :func:`~accelerate_tpu.metrics.slo.evaluate_from_dir` on
   the firing set (monitor, report, and /metrics tell one story);
4. **Serving invariants survive** — exactly-once delivery (every request
   answered exactly once, expiries included) and ``decode_compiles == 1``
   per replica.

Run directly (``make slo-smoke``) or via ``bench.py fleet``.
"""

import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# replicas are separate single-device processes — the parent never imports
# jax, exactly like the production router host
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the seeded scenario both legs replay (93 requests, 42 deadline-bound —
#: 20 at 5/25 ms, impossible on any host). Dispatch is uncapped, so the
#: pressure lands inside the replicas: engines evict the impossible
#: deadlines mid-decode (partial answers, finish_reason=
#: "deadline_exceeded") and the router's ``fleet_deadline_expired``
#: totals counter carries them to the windowed error-rate objective
SPEC_TEXT = "overbudget-storm:7:4:20"

#: bounded-queue admission control: past this depth, batch-class arrivals
#: shed with explicit over-capacity error rows (deterministic breach fuel)
MAX_QUEUE_DEPTH = 8

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "4", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]

#: armed for the parent's windowed evaluation only (replicas just serve):
#: the error-rate budget is tiny so one expiry in the window fires it, and
#: MIN_GOODPUT_PCT=101 fires whenever a goodput ledger exists at all
SLO_ENV = {
    "ACCELERATE_SLO_MAX_ERROR_RATE": "0.0001",
    "ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S": "60",
    "ACCELERATE_SLO_MIN_GOODPUT_PCT": "101",
    "ACCELERATE_SLO_MIN_GOODPUT_PCT_WINDOW_S": "60",
}


def _replica_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # single-device replicas: fast start, no oversubscription
    for k in list(env):
        if k.startswith("ACCELERATE_SLO_"):
            del env[k]  # SLO evaluation belongs to the router host, not replicas
    return env


def _decision_rows(logdir):
    rows = []
    try:
        with open(os.path.join(logdir, "router", "replicas.jsonl")) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("kind") == "scale_decision":
                    rows.append(row)
    except OSError:
        pass
    return rows


def _leg(spec, logdir):
    """One full traced run: generate → serve through a supervised 2-replica
    fleet with the SLO policy armed → assert the closed loop's artifacts."""
    from accelerate_tpu.metrics.slo import evaluate_from_dir
    from accelerate_tpu.serving.replica import spawn_replica, wait_until_ready
    from accelerate_tpu.serving.router import Router
    from accelerate_tpu.serving.supervisor import (
        ReplicaSupervisor,
        SupervisorConfig,
    )
    from accelerate_tpu.serving.workload import (
        generate_schedule,
        run_schedule,
        write_workload_manifest,
    )

    schedule = generate_schedule(spec)
    write_workload_manifest(logdir, spec, schedule)

    def spawn_fn(replica_id):
        return spawn_replica(replica_id, list(ENGINE_ARGS), env=_replica_env())

    replicas = [spawn_fn(i) for i in range(2)]

    # the same throttled evaluate_from_dir closure the route CLI wires up
    slo_cache = {"ts": 0.0, "verdict": None}

    def slo_fn():
        now = time.monotonic()
        if now - slo_cache["ts"] >= 0.5:
            slo_cache["ts"] = now
            slo_cache["verdict"] = evaluate_from_dir(logdir)
        return slo_cache["verdict"]

    supervisor = ReplicaSupervisor(
        spawn_fn,
        SupervisorConfig(min_replicas=2, max_replicas=3, scale_interval_s=0.25),
        slo_fn=slo_fn,
    )
    router = Router(
        replicas, logging_dir=logdir, health_interval=0.2,
        supervisor=supervisor, max_queue_depth=MAX_QUEUE_DEPTH,
    )
    leg = {"n_requests": len(schedule)}
    try:
        wait_until_ready(replicas, timeout=300)

        # deliveries land via callback so a double-fire is observable —
        # ticket.result alone would silently overwrite a duplicate
        deliveries = []
        submitted = run_schedule(
            schedule, lambda p: router.submit(p, callback=deliveries.append)
        )
        assert submitted == len(schedule), (submitted, len(schedule))
        if not router.wait_idle(timeout=600):
            raise RuntimeError("router never went idle")

        # -- exactly-once delivery (expiries are answers too) --------------
        assert len(deliveries) == len(schedule), (
            f"{len(deliveries)} deliveries for {len(schedule)} requests "
            "— a request was dropped or double-delivered"
        )
        ids = [d.get("id") for d in deliveries]
        assert len(ids) == len(set(ids)), "duplicated delivery"
        # expiries surface two ways: router-side (queue expiry/shed → an
        # "error" answer) and engine-side (slot evicted mid-decode → a
        # *partial* answer with finish_reason="deadline_exceeded"). The
        # storm's ≤25 ms budgets guarantee at least the latter.
        errors = [
            d for d in deliveries
            if "error" in d or d.get("finish_reason") == "deadline_exceeded"
        ]
        assert errors, (
            "the storm never shed or expired a request — not a storm"
        )
        leg["expired_or_shed"] = len(errors)

        # -- the breach fired and the supervisor decided, with evidence ----
        deadline = time.monotonic() + 15
        decisions = _decision_rows(logdir)
        while (
            not any(d.get("objective") for d in decisions)
            and time.monotonic() < deadline
        ):
            time.sleep(0.2)
            decisions = _decision_rows(logdir)
        verdict = evaluate_from_dir(logdir)
        leg["firing"] = sorted(f["rule"] for f in verdict["firing"])
        assert "max_error_rate" in leg["firing"], (
            f"expiries never breached the windowed error-rate objective: "
            f"{verdict['objectives']}"
        )
        assert decisions, "no scale_decision rows in the fleet trail"
        evidenced = [
            d for d in decisions
            if d.get("objective") and isinstance(d.get("burn_rate"), (int, float))
        ]
        assert evidenced, f"decision rows lack breach evidence: {decisions}"
        leg["scale_decisions"] = len(decisions)
        leg["decision_actions"] = sorted({d.get("action") for d in decisions})

        # -- one decode executable per (initial) replica --------------------
        compiles = []
        for r in replicas:
            with urllib.request.urlopen(r.base_url + "/stats", timeout=10) as resp:
                stats = json.loads(resp.read())
            compiles.append(stats["decode_compiles"])
        assert compiles == [1, 1], (
            f"deadline chaos recompiled a replica: decode_compiles={compiles}"
        )
        leg["decode_compiles"] = compiles

        # -- scorecard: text and --json agree, gauges agree -----------------
        from accelerate_tpu.commands.slo import build_report, render_report

        report = build_report(logdir)
        text = render_report(report)
        roundtrip = json.loads(json.dumps(report, default=str))
        assert roundtrip["scenarios"][0]["verdict"] == \
            report["scenarios"][0]["verdict"]
        assert report["scenarios"][0]["verdict"] == "fail", report["scenarios"][0]
        assert "overbudget-storm" in text and "overall: FAIL" in text, text
        assert roundtrip["pass"] is False
        leg["report_verdict"] = report["scenarios"][0]["verdict"]
        leg["schedule_sha256"] = roundtrip["scenarios"][0]["schedule_sha256"]

        from accelerate_tpu.metrics.exporter import LoggingDirExporter

        exporter = LoggingDirExporter(logdir)
        exp_firing = sorted(f["rule"] for f in exporter.refresh())
        assert exp_firing == leg["firing"], (
            f"/metrics and slo report disagree: {exp_firing} vs {leg['firing']}"
        )
        rendered = exporter.render()
        for name in verdict["objectives"]:
            assert f'slo_burn_rate{{objective="{name}"}}' in rendered, name
            assert f'slo_budget_remaining{{objective="{name}"}}' in rendered, name
        leg["slo_gauges_agree"] = True

        clean = router.drain(timeout=120)
        assert clean, "drain did not exit cleanly"
    finally:
        router.close()
    return leg


def run(platform: str = "cpu") -> dict:
    from accelerate_tpu.serving.workload import parse_trace_spec

    spec = parse_trace_spec(SPEC_TEXT)
    saved = {k: os.environ.get(k) for k in SLO_ENV}
    os.environ.update(SLO_ENV)
    try:
        legs = []
        for _ in range(2):
            with tempfile.TemporaryDirectory() as logdir:
                legs.append(_leg(spec, logdir))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    assert legs[0]["schedule_sha256"] == legs[1]["schedule_sha256"], (
        "same spec, different schedules: "
        f"{legs[0]['schedule_sha256']} vs {legs[1]['schedule_sha256']}"
    )
    return {
        "spec": SPEC_TEXT,
        "n_requests": legs[0]["n_requests"],
        "schedules_identical": True,
        "schedule_sha256": legs[0]["schedule_sha256"],
        "decode_compiles": legs[0]["decode_compiles"],
        "scale_decisions": [leg["scale_decisions"] for leg in legs],
        "decision_actions": sorted(
            set(legs[0]["decision_actions"]) | set(legs[1]["decision_actions"])
        ),
        "firing": legs[0]["firing"],
        "expired_or_shed": [leg["expired_or_shed"] for leg in legs],
        "report_verdict": legs[0]["report_verdict"],
        "slo_gauges_agree": all(leg["slo_gauges_agree"] for leg in legs),
    }


def main() -> int:
    r = run()
    print(
        f"slo-smoke OK: {r['spec']} x2 — {r['n_requests']} requests/leg, "
        f"schedules identical ({r['schedule_sha256'][:12]})\n"
        f"  breach fired {r['firing']}, "
        f"{r['scale_decisions']} scale decision(s) with evidence "
        f"(actions {r['decision_actions']}), "
        f"{r['expired_or_shed']} expiries/leg answered exactly once\n"
        f"  slo report verdict '{r['report_verdict']}' round-trips --json, "
        f"/metrics gauges agree, decode_compiles={r['decode_compiles']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
