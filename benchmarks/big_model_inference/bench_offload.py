"""Big-model-inference benchmark: load time + s/token for a disk-offloaded
model, the measurement behind the reference's published table
(``/root/reference/benchmarks/big_model_inference/README.md:27-37``; the
OPT-30B fp32 + disk row is 112.3 s load / 33.9 s/token on 2× Titan RTX).

The chip here can't hold OPT-30B, so the comparison is made on the
*bandwidth-normalised* metric the disk-offload regime is governed by:

    effective_stream_bandwidth = model_bytes_streamed_per_token / s_per_token

The reference row moves ~120 GB (fp32 30B) per generated token at
33.9 s/token → **3.54 GB/s** effective. Any configuration whose pipeline
sustains a higher effective bandwidth beats that row shape-for-shape; int8
quantized loading additionally divides the bytes per token by 4.

Run: ``python benchmarks/big_model_inference/bench_offload.py [--layers N]``
Prints one JSON line per configuration (fp32 disk, int8 disk).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def _drop_page_cache() -> bool:
    """Cold-cache the disk tier so s/token includes the real read (the
    reference's 120 GB model couldn't fit its 32 GB page cache either)."""
    try:
        subprocess.run(["sync"], check=True)
        with open("/proc/sys/vm/drop_caches", "w") as f:
            f.write("3\n")
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


def _build_config(tag: str, quantize, layers: int, hidden: int):
    import time as _time

    from accelerate_tpu.big_modeling import dispatch_model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils.quantization import BnbQuantizationConfig, quantize_model_params

    config = LlamaConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=hidden * 4,
        num_hidden_layers=layers, num_attention_heads=16, num_key_value_heads=16,
        max_position_embeddings=256, remat=False,
    )
    t0 = _time.perf_counter()
    model = LlamaForCausalLM.from_config(config, seed=0)
    if quantize == "nf4":
        model = quantize_model_params(
            model,
            BnbQuantizationConfig(load_in_4bit=True, quantize_embeddings=True),
        )
    elif quantize:  # int8 (True kept for backward compat)
        model = quantize_model_params(
            model, BnbQuantizationConfig(quantize_embeddings=True)
        )
    offload_dir = tempfile.mkdtemp(prefix=f"bench_offload_{tag}_")
    dispatched = dispatch_model(model, {"": "disk"}, offload_dir=offload_dir)
    load_s = _time.perf_counter() - t0
    bytes_on_disk = sum(
        os.path.getsize(os.path.join(offload_dir, f))
        for f in os.listdir(offload_dir)
        if f.endswith(".dat")
    )
    return {
        "tag": tag, "dispatched": dispatched, "dir": offload_dir,
        "load_s": load_s, "bytes": bytes_on_disk, "per_token": [],
    }


def run_configs(config_list, layers: int, hidden: int, tokens: int) -> list[dict]:
    """Measure every configuration INTERLEAVED per token (fp32 token,
    int8 token, nf4 token, repeat): on a shared 1-core host any ambient
    CPU load then hits each configuration nearly equally instead of
    poisoning whichever ran while the neighbour was busy."""
    import numpy as np

    from accelerate_tpu.generation import generate

    # short prompt: the reference's s/token regime (OPT-30B decode,
    # README.md:36-37) is WEIGHT-MOVEMENT-bound — 120 GB per token
    # against a trivial prompt's matmuls. A long prompt on this 1-core
    # measurement host would instead measure prefill compute, which the
    # effective-stream metric deliberately excludes.
    ids = np.random.default_rng(0).integers(0, 32000, size=(1, 8)).astype(np.int32)
    built = [_build_config(tag, quantize, layers, hidden) for tag, quantize in config_list]
    try:
        for b in built:  # warmup: one token (compiles every segment fn)
            generate(b["dispatched"], ids, max_new_tokens=1)
        cold = True
        for _ in range(tokens):
            for b in built:
                # each measured token starts cold-cache so its disk read
                # is real (same input → identical shapes, compile cached)
                cold = _drop_page_cache() and cold
                t0 = time.perf_counter()
                generate(b["dispatched"], ids, max_new_tokens=1)
                # generate()'s full-forward path device_gets the logits
                # every token, so it host-syncs before returning and the
                # elapsed read measures real compute, not dispatch:
                # tpu-lint: ignore[TPU008] — generate() host-syncs internally
                b["per_token"].append(time.perf_counter() - t0)
        results = []
        for b in built:
            # median, not mean: one ambient-load spike shouldn't own a row
            s_per_token = float(np.median(b["per_token"]))
            bw = b["bytes"] / s_per_token
            results.append(
                {
                    "config": b["tag"],
                    "load_s": round(b["load_s"], 2),
                    "model_bytes": b["bytes"],
                    "cold_cache": cold,
                    "s_per_token": round(s_per_token, 4),
                    "effective_stream_gb_per_s": round(bw / 1e9, 3),
                    "reference_opt30b_row_gb_per_s": 3.54,
                    "beats_reference_row": bw / 1e9 > 3.54,
                }
            )
        return results
    finally:
        for b in built:
            shutil.rmtree(b["dir"], ignore_errors=True)


def run_config(tag: str, quantize, layers: int, hidden: int, tokens: int) -> dict:
    """Single-configuration entry kept for direct CLI use."""
    return run_configs([(tag, quantize)], layers, hidden, tokens)[0]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--layers", type=int, default=12)
    parser.add_argument("--hidden", type=int, default=1024)
    parser.add_argument("--tokens", type=int, default=4)
    parser.add_argument(
        "--platform", default="cpu", choices=("cpu", "tpu"),
        help="cpu (default) measures the streaming pipeline against local "
        "disk+RAM; tpu uses the attached chip — NOTE: in dev environments "
        "where the chip sits behind a network tunnel, H2D bandwidth "
        "measures the tunnel, not the pipeline",
    )
    args = parser.parse_args()
    if args.platform == "cpu":
        # the config update wins over site plugins that ignore JAX_PLATFORMS
        import jax

        jax.config.update("jax_platforms", "cpu")

    for result in run_configs(
        [("fp32_disk", False), ("int8_disk", True), ("nf4_disk", "nf4")],
        args.layers, args.hidden, args.tokens,
    ):
        print(json.dumps(result))


if __name__ == "__main__":
    main()
