"""Async engine smoke: double-buffered vs synchronous dispatch on the
IDENTICAL trace.

Interleaved legs (ASYNC/SYNC/ASYNC/SYNC/...) of the same Poisson
mixed-length trace through the same engine geometry and the same model —
the only difference is ``EngineConfig(async_dispatch=...)`` — with
pairwise ratios and **ratios only** (the timing-noise rule). Headline
keys: ``async_tpot_ratio`` (async TPOT p50 / sync TPOT p50, < 1 is the
ROADMAP item-5 win), ``async_host_fraction`` vs ``sync_host_fraction``
(the host must leave the per-token critical path: strictly lower on the
async leg at equal throughput), and ``async_goodput_ratio`` (throughput
must not regress). ``decode_burst=1`` on BOTH legs — one device round
trip per token is where the host sync dominates and the overlap has the
most wall time to hide; larger bursts amortise the sync and shrink the
effect this smoke exists to measure.

Both legs assert the one-decode-executable contract inside
``run_engine_leg``; token parity is asserted here request-for-request
(dispatch-after-harvest ordering makes the async engine token-identical
by construction — this smoke re-checks it end to end).

NOTE the CPU leg is a *smoke* of the machinery, not a credible ratio: at
tiny-model shapes the device round is microseconds of XLA CPU work, so
the hideable window is small and the box's wall clock swings. The TPOT
gate is parallelism-aware: with >1 CPU (or a real accelerator) it is
``async_tpot_ratio < 1.0`` (any win); on a 1-CPU container the host and
the XLA worker timeslice one core, so overlap *cannot* cut wall time —
measured directly: dispatch-then-host-work-then-block runs ~15% SLOWER
than serial on this class of box — and the gate degrades to a
no-regression bound (< 1.10) while the host_fraction / overlap /
parity / goodput gates stay strict. The credible number is the TPU run.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.serve_bench import default_workload, run_engine_leg, warm_engine


def workload(platform: str):
    model, engine_cfg, trace = default_workload(platform)
    # decode_burst=1: per-token dispatch, the regime the overlap targets
    async_cfg = replace(engine_cfg, decode_burst=1, async_dispatch=True)
    sync_cfg = replace(async_cfg, async_dispatch=False)
    return model, async_cfg, sync_cfg, trace


def run(platform: str, legs: int = 3) -> dict:
    model, async_cfg, sync_cfg, trace = workload(platform)
    async_engine = warm_engine(model, async_cfg, trace)
    sync_engine = warm_engine(model, sync_cfg, trace)

    async_legs, sync_legs = [], []
    for _ in range(legs):
        async_legs.append(run_engine_leg(model, async_cfg, trace, engine=async_engine))
        sync_legs.append(run_engine_leg(model, sync_cfg, trace, engine=sync_engine))

    # token parity, request for request, on a fresh replay of the trace
    def replay_tokens(engine):
        reqs = [engine.add_request(tr.prompt, tr.max_new_tokens) for tr in trace]
        engine.run_until_idle(max_iterations=100_000)
        return [list(r.output_tokens) for r in reqs]

    assert replay_tokens(async_engine) == replay_tokens(sync_engine), (
        "async engine output diverged from the synchronous engine — "
        "dispatch-after-harvest must keep decode inputs identical"
    )

    # ratios are taken PAIRWISE over adjacent interleaved legs (async leg i
    # vs sync leg i ran back to back, sharing the box's weather), then the
    # median pair wins — a cross-leg median-vs-median on a ±2x box pairs a
    # warm leg against a cold one and reports contention, not the overlap
    pair_ratios = sorted(
        a["tpot_s"]["p50"] / s["tpot_s"]["p50"]
        for a, s in zip(async_legs, sync_legs)
        if a.get("tpot_s", {}).get("p50") and s.get("tpot_s", {}).get("p50")
    )
    goodput_ratios = sorted(
        a["serve_tok_s"] / s["serve_tok_s"]
        for a, s in zip(async_legs, sync_legs)
        if s["serve_tok_s"]
    )
    med = legs // 2
    a_med = sorted(async_legs, key=lambda r: r.get("tpot_s", {}).get("p50", 0.0))[med]
    s_med = sorted(sync_legs, key=lambda r: r.get("tpot_s", {}).get("p50", 0.0))[med]
    # host_fraction: median over legs per side (each leg's recorder window
    # is exactly that leg — run_engine_leg resets stats before replay)
    a_hf = sorted(l["host_fraction"] for l in async_legs if l["host_fraction"] is not None)
    s_hf = sorted(l["host_fraction"] for l in sync_legs if l["host_fraction"] is not None)
    result = {
        "async_tpot_ratio": (
            pair_ratios[len(pair_ratios) // 2] if pair_ratios else None
        ),
        "async_host_fraction": a_hf[len(a_hf) // 2] if a_hf else None,
        "sync_host_fraction": s_hf[len(s_hf) // 2] if s_hf else None,
        "async_goodput_ratio": (
            goodput_ratios[len(goodput_ratios) // 2] if goodput_ratios else None
        ),
        "overlap_hidden_s": a_med.get("overlap_hidden_s"),
        "async_tpot_p50_s": a_med.get("tpot_s", {}).get("p50"),
        "sync_tpot_p50_s": s_med.get("tpot_s", {}).get("p50"),
        "async_legs_tok_s": [round(l["serve_tok_s"], 1) for l in async_legs],
        "sync_legs_tok_s": [round(l["serve_tok_s"], 1) for l in sync_legs],
        "decode_compiles": [a_med["decode_compiles"], s_med["decode_compiles"]],
        "decode_burst": 1,
        "token_parity": True,
        "n_requests": len(trace),
    }
    return result


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    result = run(platform)
    # overlap needs a core for the XLA worker BESIDE the host thread to
    # turn hidden host time into wall time; on a 1-CPU box the two
    # timeslice and the honest expectation is parity, not a win
    cpus = os.cpu_count() or 1
    can_parallelize = platform != "cpu" or cpus > 1
    tpot_bar = 1.0 if can_parallelize else 1.10
    result["cpu_count"] = cpus
    result["tpot_bar"] = tpot_bar
    print(json.dumps(result, indent=2, default=float))
    failures = []
    ratio = result["async_tpot_ratio"]
    if ratio is None or ratio >= tpot_bar:
        failures.append(
            f"async_tpot_ratio {ratio} >= {tpot_bar} at decode_burst=1: the "
            "double-buffered dispatch must cut TPOT when the host is on "
            "the per-token critical path"
            if can_parallelize
            else f"async_tpot_ratio {ratio} >= {tpot_bar} at decode_burst=1: "
            "on a 1-CPU box the overlap cannot win wall time, but it must "
            "not cost this much either"
        )
    if not can_parallelize:
        print(
            "ASYNC_SMOKE NOTE: 1 CPU visible — host and XLA worker share "
            "the core, so the TPOT gate is the no-regression bound "
            f"{tpot_bar}; the < 1.0 win gate needs a second core or a "
            "real accelerator",
            file=sys.stderr,
        )
    a_hf, s_hf = result["async_host_fraction"], result["sync_host_fraction"]
    if a_hf is None or s_hf is None or a_hf >= s_hf:
        failures.append(
            f"async_host_fraction {a_hf} not strictly below sync "
            f"{s_hf}: the overlap hid no host time"
        )
    if not result["overlap_hidden_s"]:
        failures.append(
            "overlap_hidden_s == 0 on the async leg: the flight recorder "
            "never saw host work run under an in-flight dispatch"
        )
    good = result["async_goodput_ratio"]
    if good is None or good < 0.9:
        failures.append(
            f"async_goodput_ratio {good} < 0.9: throughput must not "
            "regress with the overlap on"
        )
    for f in failures:
        print(f"ASYNC_SMOKE FAIL: {f}", file=sys.stderr)
    print(
        "ASYNC_SMOKE "
        f"{(ratio or 0.0):.4f} {(a_hf if a_hf is not None else -1.0):.4f} "
        f"{(s_hf if s_hf is not None else -1.0):.4f} "
        f"{result['decode_compiles'][0]} {result['decode_compiles'][1]}"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
