"""Speculative serving smoke: spec-on vs spec-off on the IDENTICAL trace.

Interleaved legs (SPEC/OFF/SPEC/OFF/...) of the same Poisson mixed-length
trace through the same engine geometry and the same model — the only
difference is ``EngineConfig(spec_k=..., draft="early_exit:1")`` — with a
median per side and **ratios only** (the timing-noise rule). Headline
keys: ``spec_serve_tpot_ratio`` (spec TPOT p50 / off TPOT p50, < 1 is a
win), ``spec_serve_accept_rate`` (the rate the trace actually achieved),
and ``spec_serve_goodput_ratio`` (mixed-traffic goodput must not regress).
Both legs assert the one-decode-executable contract inside
``run_engine_leg``; token parity is asserted here request-for-request.

The model is a 4-layer tiny slice whose layers past the first have their
output projections (``wo``, ``w_down``) scaled by 0.02 — the deep suffix
is near-transparent, so the ``early_exit:1`` draft agrees with the target
at a high, repeatable accept rate while costing 1/4 of a target forward
(the c_draft/c_target regime where speculation pays even on a CPU box,
where the k+1-wide verify is genuinely ~k+1x compute rather than the
~1x weight-read of the memory-bound TPU decode). That is deliberate: on
random weights truncated-depth agreement sits at its floor (see
docs/source/concept_guides/performance.md), and a smoke gates on the
machinery's win AT a usable accept rate — the achieved rate is reported
beside the ratio, never assumed. Trained checkpoints reach comparable
agreement with distilled drafts; the floor case is covered by the
``spec`` bench row and the parity matrix in tests/test_spec_serving.py.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.serve_bench import make_trace, run_engine_leg, warm_engine

#: draft depth / round size of the smoke (the TPOT lever at accept ~= 1)
SPEC_K = 8


def build_model():
    """Tiny 4-layer llama, layers 2-4's output projections scaled to
    near-transparency (high draft agreement at 1/4 draft cost — module
    doc)."""
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(
        vocab_size=256, hidden_size=64, layers=4, heads=4, seq=256
    )
    model = LlamaForCausalLM.from_config(config, seed=0)
    layers = dict(model.params["layers"])
    for key in ("wo", "w_down"):
        arr = np.array(layers[key])
        arr[1:] *= 0.02
        layers[key] = jnp.asarray(arr)
    model.params = {**model.params, "layers": layers}
    return model, config


def workload(platform: str):
    from accelerate_tpu.serving import EngineConfig

    model, config = build_model()
    # decode-dominated mix: short prompts, geometric outputs with a real
    # tail, arrivals well above capacity so TPOT measures sustained decode
    trace = make_trace(
        n_requests=32, arrival_rate_per_s=500.0, prompt_range=(4, 24),
        mean_new_tokens=24, max_new_cap=64, vocab_size=config.vocab_size,
    )
    spec_cfg = EngineConfig(
        num_slots=8, block_size=16, max_seq_len=128, prefill_chunk=32,
        spec_k=SPEC_K, draft="early_exit:1",
    )
    off_cfg = replace(spec_cfg, spec_k=0)
    return model, spec_cfg, off_cfg, trace


def run(platform: str, legs: int = 3) -> dict:
    model, spec_cfg, off_cfg, trace = workload(platform)
    spec_engine = warm_engine(model, spec_cfg, trace)
    off_engine = warm_engine(model, off_cfg, trace)

    def leg(engine, cfg):
        out = run_engine_leg(model, cfg, trace, engine=engine)
        out["accept_rate"] = engine.stats().get("spec_accept_rate")
        return out

    spec_legs, off_legs = [], []
    for _ in range(legs):
        spec_legs.append(leg(spec_engine, spec_cfg))
        off_legs.append(leg(off_engine, off_cfg))

    # token parity, request for request, on a fresh replay of the trace
    # (run_engine_leg drains between legs, so per-request tokens are
    # re-derived here rather than fished out of leg internals)
    def replay_tokens(engine):
        reqs = [engine.add_request(tr.prompt, tr.max_new_tokens) for tr in trace]
        engine.run_until_idle(max_iterations=100_000)
        return [list(r.output_tokens) for r in reqs]

    spec_tokens = replay_tokens(spec_engine)
    off_tokens = replay_tokens(off_engine)
    assert spec_tokens == off_tokens, (
        "speculative engine output diverged from the non-spec engine — "
        "greedy acceptance must be lossless"
    )

    med = legs // 2
    # ratios are taken PAIRWISE over adjacent interleaved legs (spec leg i
    # vs off leg i ran back to back, sharing the box's weather), then the
    # median pair wins — a cross-leg median-vs-median on a ±2x box pairs
    # a warm leg against a cold one and reports contention, not spec
    pair_ratios = sorted(
        s["tpot_s"]["p50"] / o["tpot_s"]["p50"]
        for s, o in zip(spec_legs, off_legs)
        if s.get("tpot_s", {}).get("p50") and o.get("tpot_s", {}).get("p50")
    )
    goodput_ratios = sorted(
        s["serve_tok_s"] / o["serve_tok_s"]
        for s, o in zip(spec_legs, off_legs)
        if o["serve_tok_s"]
    )
    spec = sorted(spec_legs, key=lambda r: r.get("tpot_s", {}).get("p50", 0.0))[med]
    off = sorted(off_legs, key=lambda r: r.get("tpot_s", {}).get("p50", 0.0))[med]
    spec_tpot = spec.get("tpot_s", {}).get("p50")
    off_tpot = off.get("tpot_s", {}).get("p50")
    accept = max(
        (l["accept_rate"] for l in spec_legs if l.get("accept_rate") is not None),
        default=0.0,
    )
    result = {
        "spec_serve_tpot_ratio": (
            pair_ratios[len(pair_ratios) // 2] if pair_ratios else None
        ),
        "spec_serve_accept_rate": accept,
        "spec_serve_goodput_ratio": (
            goodput_ratios[len(goodput_ratios) // 2] if goodput_ratios else None
        ),
        "spec_k": SPEC_K,
        "draft": "early_exit:1",
        "spec_tpot_p50_s": spec_tpot,
        "off_tpot_p50_s": off_tpot,
        "spec_legs_tok_s": [round(l["serve_tok_s"], 1) for l in spec_legs],
        "off_legs_tok_s": [round(l["serve_tok_s"], 1) for l in off_legs],
        "decode_compiles": [spec["decode_compiles"], off["decode_compiles"]],
        "token_parity": True,
        "n_requests": len(trace),
    }
    return result


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    result = run(platform)
    print(json.dumps(result, indent=2, default=float))
    failures = []
    if not result["spec_serve_accept_rate"] or result["spec_serve_accept_rate"] < 0.3:
        failures.append(
            f"accept rate {result['spec_serve_accept_rate']} < 0.3: the "
            "near-transparent suffix should make the draft agree — the "
            "draft/verify plumbing is broken, not the acceptance"
        )
    ratio = result["spec_serve_tpot_ratio"]
    if ratio is None or ratio >= 1.0:
        failures.append(
            f"spec_serve_tpot_ratio {ratio} >= 1.0 at accept rate "
            f"{result['spec_serve_accept_rate']:.2f}: speculation must cut "
            "TPOT when the draft agrees"
        )
    good = result["spec_serve_goodput_ratio"]
    if good is None or good < 0.9:
        failures.append(
            f"spec_serve_goodput_ratio {good} < 0.9: mixed-traffic goodput "
            "must not regress with speculation on"
        )
    for f in failures:
        print(f"SPEC_SMOKE FAIL: {f}", file=sys.stderr)
    print(
        "SPEC_SMOKE "
        f"{(ratio or 0.0):.4f} {result['spec_serve_accept_rate']:.4f} "
        f"{(good or 0.0):.4f} "
        f"{result['decode_compiles'][0]} {result['decode_compiles'][1]}"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
