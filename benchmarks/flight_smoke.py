"""Flight-recorder smoke: a real serve subprocess decodes a small request
mix with the flight recorder armed, an on-demand ``/profile`` window is
captured mid-traffic, and then every observability surface must agree:

* the **phase-sum invariant holds** on every recorded iteration — the
  five exclusive phases (schedule / prefill / dispatch / device_wait /
  harvest) sum to the iteration wall time (they are telescoping
  ``perf_counter`` stamps, so a mismatch means a dropped stamp);
* ``stats()['host_fraction']`` and ``trace tail --iterations`` computed
  from the emitted trace events **agree** on the host-vs-device split
  (the ROADMAP item-5 number) — two independent code paths, one answer;
* the ``/profile?seconds=N`` capture lands ``flight_window.json`` +
  ``manifest.json`` under ``<logging_dir>/profiles/`` and the engine
  keeps serving through and after the window with ``decode_compiles``
  still 1 (profiling never perturbs the compiled executable);
* the HBM watermarks ride ``stats()`` (estimate-labelled on CPU).

Run directly (``make flight-smoke``) or via ``bench.py flight`` (which
additionally prices the disabled-path guard — bar <1% of an engine
iteration).
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]
N_REQUESTS = 8
PHASES = ("schedule", "prefill", "dispatch", "device_wait", "harvest")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    return env


def main() -> int:
    logdir = os.path.join(tempfile.mkdtemp(prefix="flight_smoke_"), "run")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "serve", *ENGINE_ARGS, "--http", str(port), "--logging-dir", logdir],
        env=_env(), stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    base = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 300
        while True:
            if proc.poll() is not None:
                raise RuntimeError(f"serve exited early rc={proc.returncode}")
            try:
                with urllib.request.urlopen(f"{base}/healthz", timeout=2) as r:
                    if json.loads(r.read()).get("state") == "ready":
                        break
            except (OSError, ValueError):
                pass
            if time.monotonic() > deadline:
                raise RuntimeError("serve never became ready")
            time.sleep(0.25)

        def gen(i):
            body = json.dumps({
                "id": i, "prompt": [1 + i % 7, 5, 11, 2],
                "max_new_tokens": 12 + i % 5,
            }).encode()
            req = urllib.request.Request(
                f"{base}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=180) as r:
                return json.loads(r.read())

        assert gen(0)["finish_reason"] == "length"

        # capture the profiler window WHILE traffic decodes
        worker = threading.Thread(
            target=lambda: [gen(i) for i in range(1, N_REQUESTS)], daemon=True
        )
        worker.start()
        with urllib.request.urlopen(f"{base}/profile?seconds=0.5",
                                    timeout=120) as r:
            manifest = json.loads(r.read())
        worker.join(timeout=300)
        assert not worker.is_alive(), "traffic wedged behind the profiler"

        window_path = os.path.join(manifest["profile_dir"],
                                   "flight_window.json")
        assert os.path.isfile(window_path), manifest
        assert os.path.isfile(
            os.path.join(manifest["profile_dir"], "manifest.json")
        )
        with open(window_path) as f:
            window = json.load(f)
        assert window["phases"] == list(PHASES)
        assert window["iterations"] == len(window["entries"])
        # the tentpole invariant, re-checked offline on every entry the
        # window captured: exclusive phases telescope to the wall time
        for e in window["entries"]:
            total = sum(e[f"{p}_s"] for p in PHASES)
            assert abs(total - e["wall_s"]) < 1e-6, e

        # the engine kept serving and never re-traced
        assert gen(99)["finish_reason"] == "length"
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["decode_compiles"] == 1, stats
        assert 0.0 < stats["host_fraction"] <= 1.0, stats
        assert stats["hbm_used_bytes"] > 0, stats
        assert stats["hbm_bytes_source"] in ("memory_stats", "estimate")

        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            scrape = r.read().decode()
        for needle in ("serving_host_fraction", "serving_iteration_seconds",
                       "serving_hbm_used_bytes"):
            assert needle in scrape, needle
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    # offline: the trace-derived attribution must agree with the engine
    from accelerate_tpu.diagnostics.reqtrace import (
        iteration_report,
        render_iteration_report,
    )
    from accelerate_tpu.diagnostics.tracing import discover_profile_artifacts

    report = iteration_report(logdir, k=8)
    assert report["iterations"] > 0, "no serve/flight events in the traces"
    assert abs(sum(report["attribution"].values()) - 100.0) < 1e-6
    # two independent surfaces, one host-share answer: the engine's
    # cumulative stats() vs the offline reader over the emitted events.
    # The trace sees every iteration; /stats snapshots slightly later —
    # allow a small drift window.
    assert abs(report["host_fraction"] - stats["host_fraction"]) < 0.05, (
        report["host_fraction"], stats["host_fraction"],
    )
    assert discover_profile_artifacts(logdir) == [manifest["profile_dir"]]
    print(render_iteration_report(report))

    print(
        f"FLIGHT_SMOKE OK: {report['iterations']} iterations, "
        f"host fraction {report['host_fraction']:.1%} (engine "
        f"{stats['host_fraction']:.1%}), "
        f"{window['iterations']} in the {manifest['seconds']:.2f}s "
        f"profile window, decode_compiles=1"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
