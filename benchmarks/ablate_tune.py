"""Dev ablation: candidate optimizations for the seq-1024 full train step.
Variants: bf16 rope, a remat policy that additionally saves named
rope/swiglu outputs, and their combination."""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one(variant):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.ops import layers as L

    if "bf16rope" in variant:
        def fast_rope(x, cos, sin, positions):
            dtype = x.dtype
            cos = cos[positions][:, :, None, :].astype(dtype)
            sin = sin[positions][:, :, None, :].astype(dtype)
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)

        L.apply_rope = fast_rope

    import importlib
    import accelerate_tpu.models.llama as llama_mod
    importlib.reload(llama_mod)

    remat = "dots_saveable"
    if "savenames" in variant:
        # tag rope/swiglu outputs; policy saves dots + those names
        orig_layer_apply = llama_mod.llama_layer_apply

        from jax.ad_checkpoint import checkpoint_name

        def tagged_layer_apply(config, layer, x, cos, sin, positions, attention_mask,
                               return_kv=False):
            return orig_layer_apply(config, layer, x, cos, sin, positions,
                                    attention_mask, return_kv=return_kv)

        # tag inside apply_rope + silu product instead (fewer touch points)
        base_rope = L.apply_rope

        def rope_tagged(x, cos, sin, positions):
            return checkpoint_name(base_rope(x, cos, sin, positions), "rope")

        L.apply_rope = rope_tagged
        importlib.reload(llama_mod)

        import accelerate_tpu.parallel.pipeline as pl

        orig_wrap = pl.remat_wrap

        def tuned_wrap(body, remat_arg):
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_saveable,
                jax.checkpoint_policies.save_only_these_names("rope"),
            )
            return jax.checkpoint(body, prevent_cse=False, policy=policy)

        pl.remat_wrap = tuned_wrap
        llama_mod.remat_wrap = tuned_wrap

    config = llama_mod.LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=16,
        max_position_embeddings=1024, remat=remat,
    )
    model = llama_mod.LlamaForCausalLM.from_config(config, seed=0)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 32000, size=(8, 1024)).astype(np.int32))

    def cast(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x, p
        )

    def loss_fn(p, i):
        return model.apply_fn(cast(p), input_ids=i, labels=i)["loss"].astype(jnp.float32)

    tx = optax.adamw(1e-4)
    params = model.params
    opt_state = tx.init(params)

    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train(p, s, i):
        loss, grads = jax.value_and_grad(loss_fn)(p, i)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        up, s = tx.update(grads, s, p)
        return optax.apply_updates(p, up), s, loss

    state = {"p": params, "s": opt_state}

    def step():
        state["p"], state["s"], loss = train(state["p"], state["s"], ids)
        return loss

    for _ in range(2):
        last = step()
    float(np.asarray(last))
    t0 = time.perf_counter()
    for _ in range(10):
        last = step()
    float(np.asarray(last))
    t = (time.perf_counter() - t0) / 10
    print(f"RESULT variant={variant} t={t*1000:.1f}ms tok/s={8*1024/t:.0f}")


if __name__ == "__main__":
    if len(sys.argv) > 1:
        _one(sys.argv[1])
        sys.exit(0)
    for variant in ["full", "bf16rope", "savenames", "bf16rope+savenames"]:
        for attempt in range(2):
            r = subprocess.run(
                [sys.executable, __file__, variant],
                capture_output=True, text=True, timeout=400,
            )
            out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
            if r.returncode == 0 and out:
                print(out[0], flush=True)
                break
            print(f"retry {variant}: {(r.stdout + r.stderr)[-300:]}", flush=True)
            time.sleep(10)
