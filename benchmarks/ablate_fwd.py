"""Dev ablation: component cost inside the seq-1024 fwd pass. Variants
monkeypatch one component to a cheap stand-in; the delta vs baseline is
that component's cost. Numerics are garbage — timing only."""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one(variant):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.ops import layers as L
    from accelerate_tpu.ops import attention as A

    if variant == "norope":
        L.apply_rope = lambda x, cos, sin, positions: x
    elif variant == "nonorm":
        L.rms_norm = lambda x, w, eps=1e-6: x
    elif variant == "noattn":
        A.attention = lambda q, k, v, segment_mask=None, causal=True, scale=None: v.repeat(
            q.shape[2] // v.shape[2], 2
        ) if q.shape[2] != v.shape[2] else v
    elif variant == "sumloss":
        pass  # handled below

    # import AFTER patching so the model module binds the stand-ins
    import importlib
    import accelerate_tpu.models.llama as llama_mod
    importlib.reload(llama_mod)

    config = llama_mod.LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=16,
        max_position_embeddings=1024, remat="dots_saveable",
    )
    model = llama_mod.LlamaForCausalLM.from_config(config, seed=0)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 32000, size=(8, 1024)).astype(np.int32))

    def cast(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x, p
        )

    if variant == "sumloss":
        def loss_fn(p, i):
            out = model.apply_fn(cast(p), input_ids=i)
            return out["logits"].astype(jnp.float32).mean()
    elif variant == "nohead":
        def loss_fn(p, i):
            out = model.apply_fn(cast(p), input_ids=i)
            # touch only the last position's logits: head matmul shrinks to 8 rows
            return out["logits"][:, -1, :].astype(jnp.float32).mean()
    else:
        def loss_fn(p, i):
            return model.apply_fn(cast(p), input_ids=i, labels=i)["loss"].astype(jnp.float32)

    fn = jax.jit(loss_fn)
    params = model.params
    for _ in range(2):
        last = fn(params, ids)
    float(np.asarray(last))
    t0 = time.perf_counter()
    for _ in range(10):
        last = fn(params, ids)
    float(np.asarray(last))
    t = (time.perf_counter() - t0) / 10
    print(f"RESULT variant={variant} t={t*1000:.1f}ms")


if __name__ == "__main__":
    if len(sys.argv) > 1:
        _one(sys.argv[1])
        sys.exit(0)
    for variant in ["base", "norope", "nonorm", "noattn", "sumloss", "nohead"]:
        for attempt in range(2):
            r = subprocess.run(
                [sys.executable, __file__, variant],
                capture_output=True, text=True, timeout=400,
            )
            out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
            if r.returncode == 0 and out:
                print(out[0], flush=True)
                break
            print(f"retry {variant}: {(r.stdout + r.stderr)[-200:]}", flush=True)
            time.sleep(10)
