"""``make ckpt-smoke``: the save → kill → auto-resume round-trip on a CPU
mesh, as a single CI-signal script (exit code 0 = the committed-checkpoint
invariant and auto-resume both held).

Phase 1 (child, ``train`` mode): a fault-tolerant Accelerator trains a toy
regression; at step 3 the process sends itself SIGTERM (standing in for a
TPU preemption notice). The handler's flag fires at the next step
boundary → ONE emergency ``save_state()`` → clean exit 143 with a
``PREEMPTED.json`` sentinel.

Phase 2 (parent): asserts the checkpoints dir holds exactly one committed,
manifest-valid checkpoint and no partial ``.tmp`` was promoted.

Phase 3 (child, ``resume`` mode): ``ACCELERATE_AUTO_RESUME=1`` — a fresh
Accelerator restores inside ``prepare()`` and reports the restored step
counter, which must be the 3 optimizer steps phase 1 completed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

KILL_AT_STEP = 3


def child(mode: str, project_dir: str) -> int:
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, FaultTolerancePlugin, ProjectConfiguration

    from accelerate_tpu.test_utils import RegressionModel

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=project_dir, automatic_checkpoint_naming=True
        ),
        fault_tolerance=FaultTolerancePlugin(),
    )
    model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
    if mode == "resume":
        # auto-resume already fired inside prepare()
        print(f"RESUMED_STEP {acc.step}", flush=True)
        return 0

    x = np.linspace(-1, 1, 32).astype(np.float32)
    y = (2 * x + 3).astype(np.float32)
    for i in range(10):
        if i == KILL_AT_STEP:
            import signal

            os.kill(os.getpid(), signal.SIGTERM)  # simulated preemption
        out = model(x=x, y=y)
        acc.backward(out.loss)  # boundary check fires here at i == KILL_AT_STEP
        opt.step()
        opt.zero_grad()
        acc.step += 1
    print("ERROR: trained past the preemption", flush=True)
    return 1


def main() -> int:
    import tempfile

    tmp = tempfile.mkdtemp(prefix="ckpt_smoke_")
    project_dir = os.path.join(tmp, "proj")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")

    rc = subprocess.run(
        [sys.executable, __file__, "train", project_dir], env=env, timeout=600
    )
    assert rc.returncode == 143, f"expected clean preemption exit 143, got {rc.returncode}"

    from accelerate_tpu.checkpointing import _sorted_checkpoints
    from accelerate_tpu.resilience.manifest import SENTINEL_NAME, validate_checkpoint

    checkpoints_dir = os.path.join(project_dir, "checkpoints")
    names = sorted(os.listdir(checkpoints_dir))
    committed = _sorted_checkpoints(checkpoints_dir)
    assert len(committed) == 1, f"expected exactly one committed checkpoint, got {names}"
    assert not any(n.endswith(".tmp") for n in names), f"a .tmp was left committed-looking: {names}"
    ok, reason = validate_checkpoint(committed[0])
    assert ok, f"emergency checkpoint failed validation: {reason}"
    sentinel = json.load(open(os.path.join(checkpoints_dir, SENTINEL_NAME)))
    assert sentinel["step"] == KILL_AT_STEP, sentinel

    env["ACCELERATE_AUTO_RESUME"] = "1"
    out = subprocess.run(
        [sys.executable, __file__, "resume", project_dir],
        env=env, timeout=600, capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    resumed_step = next(
        int(line.split()[1]) for line in out.stdout.splitlines()
        if line.startswith("RESUMED_STEP")
    )
    assert resumed_step == KILL_AT_STEP, f"resumed at step {resumed_step}, saved at {KILL_AT_STEP}"

    manifest = json.load(open(os.path.join(committed[0], "manifest.json")))
    print(
        f"ckpt-smoke OK: SIGTERM at step {KILL_AT_STEP} → emergency save "
        f"({manifest['kind']}, {sum(f['bytes'] for f in manifest['files'].values())} bytes, "
        f"exit 143) → auto-resume restored step {resumed_step}"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] in ("train", "resume"):
        sys.exit(child(sys.argv[1], sys.argv[2]))
    sys.exit(main())
