"""Dev ablation: flash-kernel block sizes for the long-context rows
(seq 2048/4096) at the FLAGSHIP shape (h1536/L16/12h/d128 — the shape the
bench's primary row measures; earlier revisions of this script swept the
r3 h1024/L24 shape, whose d=64 head dim has different VMEM pressure).

Each point runs in its own subprocess (clean HBM) and reports the remat
policy that actually fit — at seq 4096 the dots_saveable residuals may
exceed HBM, and a silent fallback to full remat costs ~25% MFU on its
own, which matters more than any block-size choice.
"""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one(seq, bq, bkv, remat):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.mesh import data_sharding
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.ops.attention import attention_context

    bsz = max(8 * 1024 // seq, 1)
    config = LlamaConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_hidden_layers=16, num_attention_heads=12, num_key_value_heads=12,
        max_position_embeddings=seq,
        remat={"0": False, "1": True}.get(remat, remat),
    )
    accelerator = Accelerator(mixed_precision="bf16")
    model, opt = accelerator.prepare(
        LlamaForCausalLM.from_config(config, seed=0), optax.adamw(1e-4)
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32000, size=(bsz, seq)).astype(np.int32)
    sharding = data_sharding(accelerator.mesh)
    batch = {k: jax.device_put(jnp.asarray(v), sharding) for k, v in
             {"input_ids": ids, "labels": ids}.items()}

    kw = {}
    if bq:
        kw = {"block_q": bq, "block_kv": bkv}
    with attention_context(**kw):
        def step():
            out = model(**batch)
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            return out.loss.force()

        for _ in range(2):
            last = step()
        float(np.asarray(last))
        t0 = time.perf_counter()
        for _ in range(10):
            last = step()
        float(np.asarray(last))
        t = (time.perf_counter() - t0) / 10
    print(f"RESULT seq={seq} bq={bq} bkv={bkv} remat={remat} "
          f"t={t*1000:.1f}ms tok/s={bsz*seq/t:.0f}")


def _micro(seq, bq, bkv):
    """Flash kernel alone (fwd+bwd) at the flagship per-layer shape."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import causal_attn_fwd_bwd_flops, flagship_attn_shape

    from accelerate_tpu.ops.flash_attention import flash_attention

    b, nh, d = flagship_attn_shape(seq)
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, seq, nh, d)), jnp.bfloat16)
               for _ in range(3))

    def fwd_bwd(q, k, v):
        def scalar(q):
            return flash_attention(
                q, k, v, causal=True, block_q=bq, block_kv=bkv
            ).astype(jnp.float32).sum()
        loss, g = jax.value_and_grad(scalar)(q)
        return loss + g.astype(jnp.float32).sum()

    jitted = jax.jit(fwd_bwd)
    for _ in range(2):
        last = jitted(q, k, v)
    float(np.asarray(last))
    t0 = time.perf_counter()
    for _ in range(20):
        last = jitted(q, k, v)
    float(np.asarray(last))
    t = (time.perf_counter() - t0) / 20
    flops = causal_attn_fwd_bwd_flops(b, nh, seq, d)
    print(f"MICRO seq={seq} bq={bq} bkv={bkv} t={t*1e6:.0f}us "
          f"eff_tflops={flops/t/1e12:.1f}")


def _sweep(points, mode):
    for p in points:
        for attempt in range(2):
            r = subprocess.run(
                [sys.executable, __file__, mode, *[str(x) for x in p]],
                capture_output=True, text=True, timeout=600,
            )
            out = [l for l in r.stdout.splitlines()
                   if l.startswith(("RESULT", "MICRO"))]
            if r.returncode == 0 and out:
                print(out[0], flush=True)
                break
            print(f"retry {mode}{p}: {(r.stdout + r.stderr)[-300:]}", flush=True)
            time.sleep(10)


if __name__ == "__main__":
    if len(sys.argv) > 5 and sys.argv[1] == "one":
        _one(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]), sys.argv[5])
        sys.exit(0)
    if len(sys.argv) > 4 and sys.argv[1] == "micro":
        _micro(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
        sys.exit(0)
    which = sys.argv[1] if len(sys.argv) > 1 else "step"
    if which == "micro-sweep":
        pts = []
        for seq in (1024, 2048, 4096):
            for bq, bkv in ((512, 512), (512, 1024), (1024, 1024),
                            (1024, 2048), (2048, 1024), (2048, 2048)):
                if bq <= seq and bkv <= seq:
                    pts.append((seq, bq, bkv))
        _sweep(pts, "micro")
    else:
        pts = []
        for seq in (2048, 4096):
            # bq=0 → the resolve_flash_blocks auto choice (current default)
            for bq, bkv in ((0, 0), (512, 1024), (1024, 1024), (1024, 2048),
                            (2048, 1024), (2048, 2048)):
                pts.append((seq, bq, bkv, "dots_saveable"))
        pts.append((4096, 0, 0, "1"))  # full-remat comparison point
        _sweep(pts, "one")
