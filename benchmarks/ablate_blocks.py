"""Dev ablation: flash-kernel block sizes for the long-context rows
(seq 2048/4096). The round-2 tuning targeted seq 1024; deeper sequences
may want bigger kv blocks."""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one(seq, bq, bkv):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.mesh import data_sharding
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.ops.attention import attention_context

    bsz = max(8 * 1024 // seq, 1)
    config = LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_hidden_layers=24, num_attention_heads=16, num_key_value_heads=16,
        max_position_embeddings=seq, remat="dots_saveable",
    )
    accelerator = Accelerator(mixed_precision="bf16")
    model, opt = accelerator.prepare(
        LlamaForCausalLM.from_config(config, seed=0), optax.adamw(1e-4)
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32000, size=(bsz, seq)).astype(np.int32)
    sharding = data_sharding(accelerator.mesh)
    batch = {k: jax.device_put(jnp.asarray(v), sharding) for k, v in
             {"input_ids": ids, "labels": ids}.items()}

    with attention_context(block_q=bq, block_kv=bkv):
        def step():
            out = model(**batch)
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            return out.loss.force()

        for _ in range(2):
            last = step()
        float(np.asarray(last))
        t0 = time.perf_counter()
        for _ in range(10):
            last = step()
        float(np.asarray(last))
        t = (time.perf_counter() - t0) / 10
    print(f"RESULT seq={seq} bq={bq} bkv={bkv} t={t*1000:.1f}ms tok/s={bsz*seq/t:.0f}")


if __name__ == "__main__":
    if len(sys.argv) > 3:
        _one(int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]))
        sys.exit(0)
    points = [(2048, 512, 1024), (2048, 1024, 1024), (2048, 512, 2048),
              (2048, 1024, 2048), (2048, 256, 1024)]
    if len(sys.argv) > 1 and sys.argv[1] == "4k":
        points = [(4096, 512, 1024), (4096, 1024, 2048), (4096, 512, 2048)]
    for seq, bq, bkv in points:
        for attempt in range(2):
            r = subprocess.run(
                [sys.executable, __file__, str(seq), str(bq), str(bkv)],
                capture_output=True, text=True, timeout=400,
            )
            out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
            if r.returncode == 0 and out:
                print(out[0], flush=True)
                break
            print(f"retry {seq}/{bq}/{bkv}: {(r.stdout + r.stderr)[-200:]}", flush=True)
            time.sleep(10)
