"""``make shard-smoke``: the static sharding-plan pre-flight end to end.

Four assertions, exit code is the CI signal:

1. the clean flagship plan over a virtual (dp=1, fsdp=2, tp=2) mesh exits
   0 through the REAL CLI with zero findings;
2. a seeded dead partition rule exits 2 naming SP001;
3. an over-budget ``--hbm-gb`` cap exits 2 naming SP004 with the tier
   breakdown attached;
4. ``--json`` round-trips: the payload parses, the tier totals sum to the
   reported per-device bytes, and every finding carries a catalogued ID.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _run(*args):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "shard-check", *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240,
    )


def main() -> int:
    # 1. clean plan exits 0
    proc = _run("--preset", "flagship", "--virtual", "1,2,2", "--json")
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-2000:])
    clean = json.loads(proc.stdout)
    assert clean["findings"] == [], clean["findings"]
    assert set(clean["tiers"]) == {"params", "opt_state", "kv_pool"}, clean["tiers"]

    # 2. seeded dead rule exits 2 naming SP001
    proc = _run("--virtual", "1,2,2", "--json", "--extra-rule", "no_such_param=tp")
    assert proc.returncode == 2, (proc.returncode, proc.stderr[-2000:])
    payload = json.loads(proc.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"SP001"}, payload["findings"]

    # 3. over-budget cap exits 2 naming SP004 with a tier breakdown
    proc = _run("--preset", "flagship", "--virtual", "1,2,2", "--json",
                "--hbm-gb", "0.5")
    assert proc.returncode == 2, (proc.returncode, proc.stderr[-2000:])
    payload = json.loads(proc.stdout)
    sp004 = [f for f in payload["findings"] if f["rule"] == "SP004"]
    assert sp004, payload["findings"]
    assert sp004[0]["detail"]["tiers"]["opt_state"] > 0, sp004[0]

    # 4. --json round-trips and is internally consistent
    from accelerate_tpu.analysis.shardplan import SP_RULES

    for payload in (clean, json.loads(proc.stdout)):
        assert payload["bytes_per_device"] == sum(
            t["bytes_per_device"] for t in payload["tiers"].values()
        ), payload["tiers"]
        assert all(f["rule"] in SP_RULES for f in payload["findings"])
        assert payload["errors"] == sum(
            1 for f in payload["findings"] if f["severity"] == "error"
        )

    print("SHARD_SMOKE_OK: clean plan exit 0, seeded SP001/SP004 exit 2, "
          "--json round-trip consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
