"""Radix prefix-cache smoke: shared-prefix trace vs cold trace.

Proves the PR's three contracts end-to-end on CPU-sized shapes, in under a
minute:

1. an 80%-shared-prefix trace through the sharing engine reports a
   positive prefix hit ratio, and the same trace through the no-sharing
   engine reports exactly zero — the cache is really doing the skipping;
2. both engines keep the one-compiled-decode-executable contract
   (``decode_compiles == 1`` across warmup + the measured leg);
3. a pool-pressure scenario that truncates with
   ``finish_reason="out_of_blocks"`` on the no-swap engine completes
   fully (every request ``length``-finished, token-identical) once
   ``swap_gb`` turns the host-DRAM tier on, with at least one preemption
   observed.

Wall-clock is never gated (the ±5x box rule) — ratios and counters only.
Run via ``make radix-smoke``.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.serving import EngineConfig, InferenceEngine
    from benchmarks.serve_bench import (
        make_shared_prefix_trace,
        run_engine_leg,
        warm_engine,
    )

    config = LlamaConfig.tiny(vocab_size=256, hidden_size=64, layers=2, heads=4, seq=128)
    model = LlamaForCausalLM.from_config(config, seed=0)
    engine_cfg = EngineConfig(num_slots=4, block_size=8, max_seq_len=128, prefill_chunk=16)
    trace = make_shared_prefix_trace(
        n_requests=24, arrival_rate_per_s=500.0, prefix_len=48, tail_range=(4, 12),
        mean_new_tokens=6, max_new_cap=16, vocab_size=config.vocab_size,
    )

    # -- 1+2: hit ratio positive with sharing, zero without, one executable
    sharing = warm_engine(model, replace(engine_cfg, prefix_cache=True), trace)
    cold = warm_engine(model, replace(engine_cfg, prefix_cache=False), trace)
    share_leg = run_engine_leg(model, None, trace, engine=sharing)
    cold_leg = run_engine_leg(model, None, trace, engine=cold)
    assert share_leg["prefix_hit_ratio"] > 0, share_leg
    assert cold_leg["prefix_hit_ratio"] == 0, cold_leg
    assert share_leg["decode_compiles"] == 1 and cold_leg["decode_compiles"] == 1
    assert share_leg["completed"] == cold_leg["completed"] == len(trace)

    # -- 3: swap preemption completes what truncation used to cut short
    pressure = dict(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                    num_blocks=6, prefix_cache=False)
    prompts = [np.arange(8, dtype=np.int32), np.arange(8, dtype=np.int32) + 1]

    def pressure_run(swap_gb):
        eng = InferenceEngine(model, EngineConfig(swap_gb=swap_gb, **pressure))
        reqs = [eng.add_request(p, max_new_tokens=30) for p in prompts]
        eng.run_until_idle(max_iterations=5000)
        return eng.stats(), reqs

    no_swap_stats, no_swap_reqs = pressure_run(0.0)
    swap_stats, swap_reqs = pressure_run(0.01)
    assert any(r.finish_reason == "out_of_blocks" for r in no_swap_reqs), (
        "pressure scenario no longer truncates without swap — retune it"
    )
    assert all(r.finish_reason == "length" for r in swap_reqs), [
        r.finish_reason for r in swap_reqs
    ]
    assert swap_stats["preemptions"] >= 1 and swap_stats["out_of_blocks_total"] == 0
    assert swap_stats["decode_compiles"] == 1

    print(json.dumps({
        "prefix_hit_ratio_sharing": round(share_leg["prefix_hit_ratio"], 4),
        "prefix_hit_ratio_cold": cold_leg["prefix_hit_ratio"],
        "sharing_tok_s": round(share_leg["serve_tok_s"], 1),
        "cold_tok_s": round(cold_leg["serve_tok_s"], 1),
        "decode_compiles": [share_leg["decode_compiles"], cold_leg["decode_compiles"]],
        "pressure_no_swap_reasons": [r.finish_reason for r in no_swap_reqs],
        "pressure_swap_reasons": [r.finish_reason for r in swap_reqs],
        "pressure_preemptions": swap_stats["preemptions"],
        "pressure_swapped_blocks": swap_stats["swapped_out_blocks"],
    }, indent=2))
    print("RADIX SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
