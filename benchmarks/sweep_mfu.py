"""Model-shape / precision sweep behind the flagship bench config.

Times the hand-fused raw-jit train step (the same program ``bench.py``'s
``raw`` mode measures) for candidate Llama-architecture slices on the
attached chip, one subprocess per config (clean HBM). Round-4 findings on
TPU v5e that picked the current flagship (hidden 1536 / 16 layers):

    ctl_1024   (h1024 ff4096 L24, r3 flagship)  mfu 0.434
    h1536_L16  (h1536 ff6144 L16, 702M)         mfu 0.593   <- flagship
    h2048_L8   (h2048 ff8192 L12→L8, 668M)      mfu 0.638   (too shallow)
    h1536_L16 @seq2048 bsz4                     mfu 0.568
    h1536_L16 @seq4096 bsz2                     mfu 0.547
    h1536_L16 fp8 dense (full remat both)       0.87x bf16  (no native
                                                 fp8 MXU on v5e)

Run: ``python benchmarks/sweep_mfu.py`` (all configs) or
``python benchmarks/sweep_mfu.py <name>`` (one config, in-process).
"""

from __future__ import annotations

import subprocess
import sys
import time

CONFIGS = {
    # name: (hidden, ff, layers, heads, seq, bsz, dense_mode)
    "ctl_1024": (1024, 4096, 24, 16, 1024, 8, "bf16"),
    "h1536_L16": (1536, 6144, 16, 12, 1024, 8, "bf16"),
    "h2048_L8": (2048, 8192, 8, 16, 1024, 8, "bf16"),
    "h1536_L16_s2048": (1536, 6144, 16, 12, 2048, 4, "bf16"),
    "h1536_L16_s4096": (1536, 6144, 16, 12, 4096, 2, "bf16"),
    # fp8 comparisons run under FULL remat (the f8 custom-vjp residuals
    # exceed HBM under dots_saveable); suffix _rT forces it
    "h1536L16_bf16_rT": (1536, 6144, 16, 12, 1024, 8, "bf16"),
    "h1536L16_f8_rT": (1536, 6144, 16, 12, 1024, 8, "f8"),
}


def child(name: str) -> None:
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.ops.fp8 import fp8_autocast

    h, ff, L, nh, seq, bsz, dense_mode = CONFIGS[name]
    config = LlamaConfig(
        vocab_size=32000, hidden_size=h, intermediate_size=ff,
        num_hidden_layers=L, num_attention_heads=nh, num_key_value_heads=nh,
        max_position_embeddings=seq,
        remat=(True if name.endswith("_rT") else "dots_saveable"),
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32000, size=(bsz, seq)).astype(np.int32)
    model = LlamaForCausalLM.from_config(config, seed=0)
    tx = optax.adamw(1e-4)
    params = model.params
    opt_state = tx.init(params)
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))

    def loss_fn(p, b):
        p16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            p,
        )
        if dense_mode == "f8":
            with fp8_autocast(enabled=True):
                return model.apply_fn(p16, **b)["loss"].astype(jnp.float32)
        return model.apply_fn(p16, **b)["loss"].astype(jnp.float32)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, s, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    state = {"p": params, "s": opt_state}

    def step():
        state["p"], state["s"], loss = train_step(state["p"], state["s"], batch)
        return loss

    for _ in range(2):
        last = step()
    float(np.asarray(last))
    t0 = time.perf_counter()
    for _ in range(10):
        last = step()
    lv = float(np.asarray(last))
    t = (time.perf_counter() - t0) / 10
    tokens = bsz * seq
    attn = 6.0 * L * tokens * seq * h
    flops = 6.0 * n_params * tokens + attn
    print(
        f"RESULT {name} t={t:.4f}s tok/s={tokens / t:.0f} "
        f"mfu={flops / t / 197e12:.4f} n_params={n_params} loss={lv:.3f}"
    )


if __name__ == "__main__":
    if len(sys.argv) > 1:
        child(sys.argv[1])
        sys.exit(0)
    for name in CONFIGS:
        r = subprocess.run(
            [sys.executable, __file__, name], capture_output=True, text=True, timeout=1800
        )
        out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
        print(
            out[0]
            if out
            else f"RESULT {name} FAILED rc={r.returncode}\n{r.stderr[-800:]}"
        )
        sys.stdout.flush()
