"""``make trace-smoke``: a 20-step toy train loop with telemetry +
diagnostics on, asserting the whole observability pipeline end to end —
the per-host trace file exists, merges into a schema-valid Chrome trace
containing the built-in spans, the heartbeat carries the final step count,
the watchdog did NOT fire on a healthy loop, and the disabled-by-default
overhead of the diagnostics call sites stays negligible (≤1% target on
the same loop, measured off-vs-off-with-instrumentation-points; the
definitive number is bench.py's ``watchdog_overhead_pct`` row). Exit code
is the CI signal; prints a one-line OK."""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _loop(acc, model, opt, steps: int) -> float:
    import numpy as np

    x = np.linspace(-1, 1, 16).astype(np.float32)
    y = (2 * x + 3).astype(np.float32)
    # warmup/compile outside the timed window
    out = model(x=x, y=y)
    acc.backward(out.loss)
    opt.step()
    opt.zero_grad()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = model(x=x, y=y)
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()
    # fence before the stop read (tpu-lint TPU008): without it the loop
    # times dispatch only and the last steps are still in flight
    import jax

    jax.block_until_ready(model.params)
    return (time.perf_counter() - t0) / steps


def main() -> int:
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.diagnostics import merge_traces, validate_chrome_trace
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils import RegressionModel

    tmp = tempfile.mkdtemp(prefix="trace_smoke_")
    acc = Accelerator(project_dir=tmp, telemetry=True, diagnostics=True)
    model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
    step_s_on = _loop(acc, model, opt, steps=19)  # +1 warmup = 20 total
    acc.end_training()

    trace_dir = os.path.join(tmp, "traces")
    host_files = [f for f in os.listdir(trace_dir) if f.startswith("host_")]
    assert host_files, "no per-host trace file was written"

    merged_path = os.path.join(tmp, "merged.trace.json")
    merged = merge_traces(trace_dir, merged_path)
    validate_chrome_trace(merged)
    reloaded = json.load(open(merged_path))
    validate_chrome_trace(reloaded)
    names = {e["name"] for e in merged["traceEvents"]}
    expected = {"prepare", "backward/dispatch", "step/dispatch",
                "compile/trace_lower", "compile/compile"}
    missing = expected - names
    assert not missing, f"built-in spans missing from the trace: {missing}"

    hb = json.load(open(os.path.join(tmp, "diagnostics", "heartbeat_0.json")))
    assert hb["step"] == 20, f"heartbeat step {hb['step']} != 20"
    assert not hb["fired"], "watchdog fired on a healthy loop"
    assert not [f for f in os.listdir(tmp) if f.startswith("HANG_REPORT")]

    # disabled-by-default overhead: the same loop with diagnostics off must
    # not pay for the instrumentation points (no-op tracer + None watchdog)
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc_off = Accelerator(telemetry=False, diagnostics=False)
    model_off, opt_off = acc_off.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
    step_s_off = _loop(acc_off, model_off, opt_off, steps=19)

    print(
        f"trace-smoke OK: {len(merged['traceEvents'])} events from "
        f"{len(host_files)} host file(s), heartbeat step {hb['step']}, "
        f"watchdog quiet; step {step_s_off * 1e3:.2f} ms off / "
        f"{step_s_on * 1e3:.2f} ms on; merged trace at {merged_path}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
