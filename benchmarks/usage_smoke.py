"""Usage-ledger smoke: a seeded 3-tenant trace through a real routed
2-replica fleet, with the conservation invariant checked everywhere the
numbers surface.

What it pins, end to end:

1. **Conservation on both replicas** — each replica's ledger satisfies
   Σ per-request decode device-seconds == cumulative ``device_wait`` and
   Σ per-request block-seconds == the pool-occupancy integral;
2. **The tenant dimension round-trips** — ``--trace ...:tenants=3``
   assigns ``t0/t1/t2`` from a seeded stream, every answer row carries
   its tenant and its measured costs (``device_time_s`` /
   ``kv_block_seconds`` / ``swap_bytes``) exactly once, and the fleet's
   per-tenant device-seconds sum to the fleet total;
3. **Scorecard and scrape agree** — ``usage report --json`` on the
   fleet's logging dir (router trail at the root, one telemetry trail
   per replica) round-trips with ``"conserved": true``, and each
   replica's ``GET /metrics`` tenant-labeled counters equal its own
   ledger rollup;
4. **Serving invariants survive** — ``decode_compiles == [1, 1]``: the
   ledger rides existing edges, it never perturbs the one compiled
   decode executable.

Run directly (``make usage-smoke``).
"""

import json
import math
import os
import re
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# replicas are separate single-device processes — the parent never imports
# jax, exactly like the production router host
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the seeded 3-tenant trace: ~30 bursty arrivals, tenant assignment is a
#: post-process on the schedule (same arrivals as the tenant-less spec)
SPEC_TEXT = "bursty-diurnal:7:3:10:tenants=3"

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "4", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]

_REL_TOL = 1e-6
_ABS_TOL = 1e-9


def _replica_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # single-device replicas: fast start, no oversubscription
    # a step row (with the ledger snapshot) every iteration, so the
    # telemetry trail's last snapshot is the replica's final state
    env["ACCELERATE_SERVE_STATS_INTERVAL"] = "1"
    env.pop("ACCELERATE_SERVE_USAGE", None)  # the default-on path is the product
    return env


def _close(a, b):
    return math.isclose(a, b, rel_tol=_REL_TOL, abs_tol=_ABS_TOL)


def _assert_conserved(snap, who):
    assert _close(snap["decode_device_seconds"], snap["device_wait_seconds"]), (
        f"{who}: decode attribution leaks: Σ shares "
        f"{snap['decode_device_seconds']} vs device_wait "
        f"{snap['device_wait_seconds']}"
    )
    assert _close(snap["block_seconds"], snap["pool_block_seconds"]), (
        f"{who}: block-second attribution leaks: Σ integrals "
        f"{snap['block_seconds']} vs pool integral {snap['pool_block_seconds']}"
    )


def _scrape_tenant_counters(base_url, name):
    """Parse one tenant-labeled counter family off a replica's /metrics."""
    with urllib.request.urlopen(base_url + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    out = {}
    for m in re.finditer(
        rf'^accelerate_{name}_total{{tenant="([^"]+)"}} (\S+)$', text, re.M
    ):
        out[m.group(1)] = float(m.group(2))
    return out


def run(platform: str = "cpu") -> dict:
    from accelerate_tpu.serving.replica import spawn_replica, wait_until_ready
    from accelerate_tpu.serving.router import Router
    from accelerate_tpu.serving.workload import (
        generate_schedule,
        parse_trace_spec,
        run_schedule,
        write_workload_manifest,
    )

    spec = parse_trace_spec(SPEC_TEXT)
    schedule = generate_schedule(spec)
    traced_tenants = {e["payload"]["tenant"] for e in schedule}
    assert traced_tenants <= {"t0", "t1", "t2"} and len(traced_tenants) >= 2

    with tempfile.TemporaryDirectory() as logdir:
        write_workload_manifest(logdir, spec, schedule)
        replicas = [
            spawn_replica(
                i,
                ENGINE_ARGS
                + ["--logging-dir", os.path.join(logdir, f"replica_{i}")],
                env=_replica_env(),
            )
            for i in range(2)
        ]
        router = Router(replicas, logging_dir=logdir, health_interval=0.2)
        try:
            wait_until_ready(replicas, timeout=300)
            deliveries = []
            submitted = run_schedule(
                schedule, lambda p: router.submit(p, callback=deliveries.append)
            )
            assert submitted == len(schedule), (submitted, len(schedule))
            if not router.wait_idle(timeout=600):
                raise RuntimeError("router never went idle")

            # -- every answer carries its tenant + costs, exactly once -----
            assert len(deliveries) == len(schedule), (
                f"{len(deliveries)} deliveries for {len(schedule)} requests"
            )
            ids = [d.get("id") for d in deliveries]
            assert len(ids) == len(set(ids)), "duplicated delivery"
            by_tenant_rows = {}
            for d in deliveries:
                assert d.get("tenant") in traced_tenants, d
                assert d.get("device_time_s", -1.0) >= 0.0, d
                assert d.get("kv_block_seconds", -1.0) >= 0.0, d
                assert "swap_bytes" in d, d
                by_tenant_rows.setdefault(d["tenant"], []).append(d)

            # -- conservation + scrape agreement per replica ---------------
            compiles, fleet_total, fleet_by_tenant = [], 0.0, {}
            for r in replicas:
                with urllib.request.urlopen(
                    r.base_url + "/stats", timeout=10
                ) as resp:
                    stats = json.loads(resp.read())
                compiles.append(stats["decode_compiles"])
                snap = stats["usage"]
                _assert_conserved(snap, f"replica {r.replica_id}")
                assert snap["requests_live"] == 0
                fleet_total += snap["device_seconds"]
                for tenant, trow in snap["by_tenant"].items():
                    fleet_by_tenant[tenant] = (
                        fleet_by_tenant.get(tenant, 0.0) + trow["device_seconds"]
                    )
                scraped = _scrape_tenant_counters(
                    r.base_url, "serving_usage_device_seconds"
                )
                for tenant, trow in snap["by_tenant"].items():
                    assert tenant in scraped and _close(
                        scraped[tenant], trow["device_seconds"]
                    ), (
                        f"replica {r.replica_id}: /metrics disagrees with the "
                        f"ledger for {tenant}: {scraped.get(tenant)} vs "
                        f"{trow['device_seconds']}"
                    )
            assert compiles == [1, 1], (
                f"usage accounting recompiled a replica: {compiles}"
            )
            # tenants partition the fleet total — nothing double-billed
            assert _close(sum(fleet_by_tenant.values()), fleet_total)

            clean = router.drain(timeout=120)
            assert clean, "drain did not exit cleanly"
        finally:
            router.close()

        # -- the offline scorecard sees the same story -----------------------
        from accelerate_tpu.commands.usage import build_report

        report = build_report(logdir)
        roundtrip = json.loads(json.dumps(report, default=str))
        assert roundtrip["conserved"] is True and roundtrip["pass"] is True, (
            roundtrip
        )
        ledger_runs = [
            row for row in roundtrip["runs"] if row["usage"] is not None
        ]
        assert len(ledger_runs) == 2, (
            f"expected both replicas' trails in the report: {roundtrip['runs']}"
        )
        report_finished = sum(
            row["usage"]["requests_finished"] for row in ledger_runs
        )
        assert report_finished == len(schedule), (
            f"trail snapshots closed {report_finished} accounts for "
            f"{len(schedule)} requests"
        )

    return {
        "spec": SPEC_TEXT,
        "n_requests": len(schedule),
        "tenants": sorted(traced_tenants),
        "decode_compiles": compiles,
        "conserved": True,
        "report_pass": True,
        "fleet_device_seconds": fleet_total,
        "by_tenant_device_seconds": {
            t: fleet_by_tenant[t] for t in sorted(fleet_by_tenant)
        },
        "requests_by_tenant": {
            t: len(by_tenant_rows[t]) for t in sorted(by_tenant_rows)
        },
    }


def main() -> int:
    r = run()
    shares = "  ".join(
        f"{t} {s:.4g}s" for t, s in r["by_tenant_device_seconds"].items()
    )
    print(
        f"usage-smoke OK: {r['spec']} — {r['n_requests']} requests over "
        f"{len(r['tenants'])} tenants through a routed 2-replica fleet\n"
        f"  both ledgers conserved (device-time and block-seconds), "
        f"usage report --json round-trips pass=true, "
        f"/metrics tenant counters agree, "
        f"decode_compiles={r['decode_compiles']}\n"
        f"  fleet device-seconds {r['fleet_device_seconds']:.4g}s "
        f"partitioned: {shares}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
