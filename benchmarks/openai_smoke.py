"""OpenAI front-door smoke: a 2-replica routed fleet behind
``accelerate-tpu route --http``, driven by an OpenAI client (the real
``openai`` package when installed, a byte-identical stdlib fallback
otherwise — the wire contract is what's under test, not the SDK).

Asserts, over a mixed greedy/sampled/schema-constrained trace:

1. every non-stream completion/chat answer is well-formed (object, id
   prefix, usage arithmetic) and a fixed ``seed`` reproduces byte-equal
   text through the router;
2. every ``response_format: json_schema`` answer parses as JSON AND
   validates against the schema;
3. SSE streams frame correctly end to end — every stream yields exactly
   one finish chunk (with usage) and one ``data: [DONE]`` terminator,
   and a ``stop`` sequence never over-sends past the truncation;
4. OpenAI error objects come back for malformed requests (the fleet
   answers 400s, it does not die);
5. each replica still reports ``decode_compiles == 1`` after the whole
   trace — per-request sampling/grammar rides the ONE compiled decode
   executable.

Run directly (``make openai-smoke``) or via ``bench.py`` modes that
reuse the fleet. No absolute wall-clock gates (timing-noise rule).
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the parent drives HTTP only — replicas are their own jax processes
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ENGINE_ARGS = [
    "--preset", "tiny", "--num-slots", "4", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
    "--max-new-tokens", "16", "--logprobs-topn", "2",
]

SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"enum": ["alpha", "beta", "gamma"]},
        "n": {"type": "integer"},
    },
    "required": ["name", "n"],
}


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # single-device replicas
    env.pop("ACCELERATE_TELEMETRY", None)
    return env


def _wait_ready(port, proc, timeout=300):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"route exited rc={proc.returncode}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if json.loads(r.read()).get("state") == "ready":
                    return
        except (OSError, ValueError):
            pass
        time.sleep(0.25)
    raise RuntimeError("route fleet never became ready")


class _StdlibClient:
    """Just enough of the OpenAI HTTP contract to stand in for the SDK:
    POST JSON, surface the error object, iterate SSE data: lines."""

    name = "stdlib"

    def __init__(self, base_url):
        self.base_url = base_url.rstrip("/")

    def _post(self, path, body, stream=False):
        req = urllib.request.Request(
            self.base_url + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            resp = urllib.request.urlopen(req, timeout=300)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())
        with resp:
            raw = resp.read().decode()
        return resp.status, raw if stream else json.loads(raw)

    def completion(self, **body):
        return self._post("/completions", body)

    def chat(self, **body):
        return self._post("/chat/completions", body)

    def chat_stream(self, **body):
        status, raw = self._post(
            "/chat/completions", dict(body, stream=True), stream=True
        )
        assert status == 200, raw
        events = [
            line[6:] for line in raw.split("\n\n") if line.startswith("data: ")
        ]
        assert events and events[-1] == "[DONE]", "missing [DONE] terminator"
        return [json.loads(e) for e in events[:-1]]


class _OpenAIClient(_StdlibClient):
    """The real SDK for the happy paths; error-path probes stay on the
    stdlib POST so the raw error object remains inspectable."""

    name = "openai"

    def __init__(self, base_url, openai_module):
        super().__init__(base_url)
        self._sdk = openai_module.OpenAI(base_url=base_url, api_key="smoke")

    def chat(self, **body):
        out = self._sdk.chat.completions.create(
            model=body.pop("model", "accelerate-tpu"), **body
        )
        return 200, out.model_dump()

    def chat_stream(self, **body):
        stream = self._sdk.chat.completions.create(
            model=body.pop("model", "accelerate-tpu"), stream=True, **body
        )
        return [chunk.model_dump() for chunk in stream]


def _make_client(base_url):
    try:
        import openai  # noqa: F401 — optional, never installed by us
    except ImportError:
        return _StdlibClient(base_url)
    return _OpenAIClient(base_url, openai)


def _check_stream(chunks):
    """Exactly-once framing: one finish chunk, usage on it, text joins."""
    finals = [c for c in chunks if c["choices"][0].get("finish_reason")]
    assert len(finals) == 1, f"{len(finals)} finish chunks in one stream"
    assert finals[0].get("usage"), "finish chunk must carry usage"
    text = "".join(
        c["choices"][0].get("delta", {}).get("content") or "" for c in chunks
    )
    return text, finals[0]


def run(platform: str = "cpu", n_requests: int = 12) -> dict:
    result: dict = {"n_requests": n_requests}
    port = _free_port()
    with tempfile.TemporaryDirectory() as logdir:
        proc = subprocess.Popen(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "route", "--replicas", "2", "--logging-dir", logdir,
             "--http", str(port), *ENGINE_ARGS],
            env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True,
        )
        try:
            _wait_ready(port, proc)
            client = _make_client(f"http://127.0.0.1:{port}/v1")
            result["client"] = client.name

            # -- mixed non-stream trace ---------------------------------
            schema_ok = 0
            for i in range(n_requests):
                kind = i % 3
                if kind == 0:  # greedy completion
                    st, body = client.completion(
                        prompt=f"request {i}", temperature=0, max_tokens=8,
                    )
                    assert st == 200, body
                    assert body["object"] == "text_completion"
                    u = body["usage"]
                    assert u["total_tokens"] == (
                        u["prompt_tokens"] + u["completion_tokens"]
                    )
                elif kind == 1:  # sampled chat with a fixed seed
                    st, body = client.chat(
                        messages=[{"role": "user", "content": f"hello {i}"}],
                        temperature=0.8, seed=1000 + i, max_tokens=8,
                    )
                    assert st == 200, body
                    assert body["choices"][0]["message"]["role"] == "assistant"
                else:  # schema-constrained chat
                    st, body = client.chat(
                        messages=[{"role": "user", "content": "json please"}],
                        temperature=0.7, seed=i, max_tokens=48,
                        response_format={
                            "type": "json_schema",
                            "json_schema": {"name": "t", "schema": SCHEMA},
                        },
                    )
                    assert st == 200, body
                    value = json.loads(body["choices"][0]["message"]["content"])
                    assert value["name"] in SCHEMA["properties"]["name"]["enum"]
                    assert isinstance(value["n"], int)
                    assert set(SCHEMA["required"]) <= set(value)
                    schema_ok += 1
            result["schema_valid"] = schema_ok

            # seed determinism THROUGH the router (either replica)
            req = dict(
                messages=[{"role": "user", "content": "det"}],
                temperature=0.9, seed=7, max_tokens=8,
            )
            _, a = client.chat(**req)
            _, b = client.chat(**req)
            assert (
                a["choices"][0]["message"]["content"]
                == b["choices"][0]["message"]["content"]
            ), "fixed seed must reproduce through the fleet"
            result["seed_deterministic"] = True

            # -- streaming legs -----------------------------------------
            streams = 0
            for i in range(4):
                chunks = client.chat_stream(
                    messages=[{"role": "user", "content": f"stream {i}"}],
                    temperature=0 if i % 2 else 0.8, seed=i, max_tokens=8,
                )
                text, final = _check_stream(chunks)
                assert len(text) >= 1
                streams += 1
            # stop sequences: the stream never over-sends past truncation
            chunks = client.chat_stream(
                messages=[{"role": "user", "content": "stop test"}],
                temperature=0, max_tokens=12, stop=["X"],
            )
            text, final = _check_stream(chunks)
            assert len(text) == final["usage"]["completion_tokens"], (
                "streamed more text than the stop-truncated answer"
            )
            result["streams_exactly_once"] = streams + 1

            # -- error objects (raw POST, SDK-independent) --------------
            raw = _StdlibClient(f"http://127.0.0.1:{port}/v1")
            st, body = raw.completion(prompt="x", n=3)
            assert st == 400 and body["error"]["param"] == "n", body
            st, body = raw.completion(prompt=42)
            assert st == 400 and body["error"]["type"] == "invalid_request_error"
            st, body = raw.chat(messages=[])
            assert st == 400 and body["error"]["param"] == "messages"
            result["error_objects"] = 3

            # -- one executable per replica -----------------------------
            trail = os.path.join(logdir, "router", "replicas.jsonl")
            base_urls = set()
            with open(trail) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if row.get("base_url"):
                        base_urls.add(row["base_url"])
            assert len(base_urls) == 2, f"expected 2 replicas: {base_urls}"
            compiles, sampled, masked = [], 0, 0
            for url in sorted(base_urls):
                with urllib.request.urlopen(url + "/stats", timeout=10) as r:
                    stats = json.loads(r.read())
                compiles.append(stats["decode_compiles"])
                sampled += stats.get("sampled_tokens_sample", 0)
                masked += stats.get("grammar_masked_steps", 0)
            assert compiles == [1, 1], (
                f"per-request sampling/grammar recompiled a replica: {compiles}"
            )
            assert sampled > 0, "the sampled lanes never fired"
            assert masked > 0, "the grammar mask never fired"
            result["decode_compiles"] = compiles
            result["sampled_tokens"] = sampled
            result["grammar_masked_steps"] = masked

            proc.stdin.close()  # EOF → drain → exit 0
            rc = proc.wait(timeout=180)
            assert rc == 0, f"route drain exited rc={rc}"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
    return result


def main():
    r = run()
    print(
        f"openai-smoke: client={r['client']} n={r['n_requests']} "
        f"schema_valid={r['schema_valid']} "
        f"streams={r['streams_exactly_once']} "
        f"decode_compiles={r['decode_compiles']} "
        f"sampled_tokens={r['sampled_tokens']} "
        f"grammar_masked_steps={r['grammar_masked_steps']}"
    )
    print(
        "OPENAI SMOKE OK: 2-replica fleet, OpenAI contract end to end, "
        "schema-valid constrained output, exactly-once SSE, one decode "
        "executable per replica"
    )


if __name__ == "__main__":
    main()
