"""Self-healing fleet: supervisor (``serving/supervisor.py``), chaos
harness (``serving/chaos.py``), and request-lifecycle robustness
(deadlines + load shed) across router/scheduler/engine.

Policy (backoff, quarantine, probation, autoscale, deadline accounting)
runs against in-process stubs — tier-1 cheap, no jax, no subprocess.
Durability — seeded kill -9 / SIGSTOP-wedge schedules through the real
CLI, respawn-with-backoff observed in the fleet trail, zero orphans — is
proven against REAL serve processes, the PR 7 way. Engine-level deadline
eviction (freelist invariant) rides the slow lane with the other
compile-heavy engine tests.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from accelerate_tpu.serving.chaos import (
    ChaosInjector,
    ChaosSpecError,
    parse_chaos_spec,
)
from accelerate_tpu.serving.replica import ReplicaHandle
from accelerate_tpu.serving.router import Router
from accelerate_tpu.serving.supervisor import ReplicaSupervisor, SupervisorConfig

# ---------------------------------------------------------------------------
# chaos spec parsing + injector (tier-1: pure host)
# ---------------------------------------------------------------------------


def test_chaos_spec_parses_scopes_and_kinds():
    seed, faults = parse_chaos_spec(
        "seed=7; r0:kill@5; r1:delay@4:0.25; err503@2:3; blackout@0:4; r0:stop@3:2.5"
    )
    assert seed == 7
    by_kind = {f.kind: f for f in faults}
    assert by_kind["kill"].replica == 0 and by_kind["kill"].at_request == 5
    assert by_kind["delay"].replica == 1 and by_kind["delay"].arg == 0.25
    assert by_kind["err503"].replica is None and by_kind["err503"].arg == 3.0
    assert by_kind["blackout"].at_request == 0
    assert by_kind["stop"].arg == 2.5


@pytest.mark.parametrize(
    "bad",
    [
        "explode@3",          # unknown kind
        "kill@-1",            # negative ordinal
        "kill@x",             # non-numeric ordinal
        "delay@3",            # missing required argument
        "kill@0",             # ordinal 0 only valid for blackout
        "delay@3:0.5..0.1",   # inverted range
        "kill@3:1:2",         # too many arguments
        "seed=abc",           # malformed seed
    ],
)
def test_chaos_spec_malformed_raises(bad):
    with pytest.raises(ChaosSpecError):
        parse_chaos_spec(bad)


def test_injector_scoping_and_503_burst():
    _, faults = parse_chaos_spec("r0:kill@1; err503@2:2")
    inj = ChaosInjector(faults, replica_id=1)  # r0's kill is not ours
    assert inj.on_generate() is None          # request 1
    assert inj.on_generate() == "err503"      # request 2
    assert inj.on_generate() == "err503"      # request 3
    assert inj.on_generate() is None          # request 4: burst over
    assert inj.injected["err503"] == 2 and inj.injected["kill"] == 0


def test_injector_blackout_window_and_startup():
    _, faults = parse_chaos_spec("blackout@0:0.15; blackout@1:0.15")
    inj = ChaosInjector(faults, replica_id=0)
    assert inj.healthz_blackout()  # startup blackout active immediately
    time.sleep(0.2)
    assert not inj.healthz_blackout()
    inj.on_generate()  # request 1 re-arms it
    assert inj.healthz_blackout()


def test_injector_seeded_delays_deterministic(monkeypatch):
    """The same (spec, seed, replica) draws the same jittered delays —
    chaos runs replay, they don't dice-roll."""
    import accelerate_tpu.serving.chaos as chaos_mod

    def draws(seed):
        slept = []
        monkeypatch.setattr(chaos_mod.time, "sleep", slept.append)
        _, faults = parse_chaos_spec("delay@1:0.1..0.5; delay@2:0.1..0.5")
        inj = ChaosInjector(faults, seed=seed, replica_id=0)
        inj.on_generate()
        inj.on_generate()
        return slept

    a, b, c = draws(3), draws(3), draws(4)
    assert a == b, "same seed must draw the same delays"
    assert a != c, "different seeds must draw different delays"
    assert all(0.1 <= s < 0.5 for s in a)


def test_injector_env_fallback(monkeypatch):
    monkeypatch.setenv("ACCELERATE_CHAOS_SPEC", "r2:kill@9")
    inj = ChaosInjector.from_spec(None, replica_id=2)
    assert inj is not None and inj._kills == {9}
    monkeypatch.setenv("ACCELERATE_CHAOS_SPEC", "")
    assert ChaosInjector.from_spec(None, replica_id=2) is None
    assert ChaosInjector.from_spec("kill@3", replica_id=0)._kills == {3}
    # a malformed env seed refuses like a malformed spec entry (error row
    # + exit 2 at the serve front end), never a bare traceback
    monkeypatch.setenv("ACCELERATE_CHAOS_SEED", "abc")
    with pytest.raises(ChaosSpecError, match="ACCELERATE_CHAOS_SEED"):
        ChaosInjector.from_spec("kill@3", replica_id=0)


# ---------------------------------------------------------------------------
# supervisor policy against stub replicas (tier-1: no jax, no processes)
# ---------------------------------------------------------------------------


class FakeProc:
    """Just enough of subprocess.Popen for the router/supervisor: poll/
    kill/wait/send_signal. SIGTERM 'exits' it (the serve drain contract)."""

    _pids = iter(range(100000, 200000))

    def __init__(self):
        self.pid = next(FakeProc._pids)
        self.returncode = None

    def poll(self):
        return self.returncode

    def wait(self, timeout=None):
        return self.returncode

    def kill(self):
        self.returncode = -9

    def send_signal(self, sig):
        self.returncode = 0  # drain: clean exit


class SupStub(ReplicaHandle):
    """Spawned-replica stub: fake process + instant generate."""

    def __init__(self, replica_id):
        super().__init__(replica_id, f"http://stub/{replica_id}", process=FakeProc())
        self.state = "ready"
        self.handled = []
        self._hlock = threading.Lock()

    def check_health(self, timeout=2.0):
        if self.process.poll() is not None:
            return None
        self.last_heartbeat = time.time()
        return {"state": self.state, "queue_depth": 0, "active_slots": 0}

    def generate(self, payload, timeout=None):
        from accelerate_tpu.serving.replica import ReplicaError

        if self.process.poll() is not None:
            raise ReplicaError(f"stub {self.replica_id} is down")
        with self._hlock:
            self.handled.append(payload)
        return {"id": payload.get("id"), "tokens": [1], "finish_reason": "length"}


def _supervised_router(tmp_path, n=1, **cfg_kw):
    spawned = []

    def spawn_fn(replica_id):
        handle = SupStub(replica_id)
        spawned.append(handle)
        return handle

    cfg_kw.setdefault("min_replicas", n)
    cfg_kw.setdefault("max_replicas", n)
    cfg_kw.setdefault("backoff_base_s", 0.05)
    cfg_kw.setdefault("backoff_max_s", 0.5)
    cfg_kw.setdefault("jitter", 0.0)
    sup = ReplicaSupervisor(spawn_fn, SupervisorConfig(**cfg_kw))
    replicas = [spawn_fn(i) for i in range(n)]
    router = Router(
        replicas, logging_dir=str(tmp_path), health_interval=0.05, supervisor=sup
    )
    return router, sup, spawned


def _wait_until(cond, timeout=20.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll)
    return cond()


def test_supervisor_respawns_dead_replica(tmp_path):
    """A dead replica comes back: requests submitted during the outage are
    served by the respawned incarnation (no dead-fleet fail-fast while the
    supervisor will respawn), and the fleet trail records the restart."""
    router, sup, spawned = _supervised_router(tmp_path, n=1)
    try:
        first = router.submit({"id": "a", "prompt": [1]})
        assert first.done.wait(timeout=20) and "tokens" in first.result
        spawned[0].process.kill()  # the only replica dies
        assert _wait_until(lambda: router.stats()["dead"] == 1 or len(spawned) > 1)
        # submitted while dead — must NOT be answered with a dead-fleet error
        during = router.submit({"id": "b", "prompt": [1]})
        assert during.done.wait(timeout=20)
        assert during.result.get("tokens") == [1], during.result
        assert len(spawned) == 2 and spawned[1].restarts == 1
        assert not spawned[1].probation  # a single death is no quarantine
        stats = router.stats()
        assert stats["supervisor"]["respawns"] == 1
        assert stats["per_replica"][0]["restarts"] == 1

        # the fleet trail records the restart + the aggregate respawn count
        # (written on health ticks — wait for one to land before closing)
        def trail_has_restart():
            rows = [
                json.loads(line)
                for line in (
                    tmp_path / "router" / "replicas.jsonl"
                ).read_text().splitlines()
            ]
            return any(
                r.get("restarts") == 1 for r in rows if r.get("replica_id") == 0
            ) and any(
                r.get("kind") == "router" and r.get("respawns") == 1 for r in rows
            )

        assert _wait_until(trail_has_restart), "restart never reached the trail"
    finally:
        router.close()


def test_supervisor_backoff_grows_and_quarantine_probation(tmp_path):
    """Consecutive rapid deaths double the backoff; at quarantine_after the
    next incarnation rejoins half-open (probation) and one served request
    promotes it back to full membership, resetting the death count."""
    router, sup, spawned = _supervised_router(
        tmp_path, n=1, quarantine_after=2, probation_successes=1,
        rapid_death_s=60.0,
    )
    try:
        spawned[0].process.kill()
        assert _wait_until(lambda: len(spawned) == 2)
        first_backoff = sup._meta[0]["backoff_s"]
        assert not spawned[1].probation
        spawned[1].process.kill()  # rapid second death -> quarantine
        assert _wait_until(lambda: len(spawned) == 3)
        assert sup._meta[0]["backoff_s"] > first_backoff
        assert spawned[2].probation, "post-quarantine rejoin must be half-open"
        assert router.stats()["probation"] == 1
        # one successful probe request clears probation + resets the count
        probe = router.submit({"id": "p", "prompt": [1]})
        assert probe.done.wait(timeout=20) and probe.result["tokens"] == [1]
        assert _wait_until(lambda: not spawned[2].probation)
        assert sup._meta[0]["deaths"] == 0 and not sup._meta[0]["quarantined"]
    finally:
        router.close()


def test_supervisor_scales_up_and_down(tmp_path):
    """Queue pressure spawns a replica up to max_replicas; a sustained idle
    fleet drains back to min_replicas (SIGTERM -> `terminated`, never
    `dead` — a scale-down must not look like a crash or trigger respawn)."""
    router, sup, spawned = _supervised_router(
        tmp_path, n=1, min_replicas=1, max_replicas=2,
        scale_interval_s=0.05, scale_up_queue_per_replica=2,
        scale_down_idle_ticks=3,
    )
    try:
        spawned[0].state = "starting"  # hold dispatch so the queue builds
        tickets = [router.submit({"id": i, "prompt": [1]}) for i in range(6)]
        assert _wait_until(lambda: len(spawned) == 2), "never scaled up"
        assert spawned[1].replica_id == 1
        spawned[0].state = "ready"
        for t in tickets:
            assert t.done.wait(timeout=20)
        # idle now: the supervisor drains the highest-numbered replica
        assert _wait_until(lambda: spawned[1].state == "terminated")
        stats = router.stats()
        assert stats["supervisor"]["scale_ups"] == 1
        assert stats["supervisor"]["scale_downs"] == 1
        assert stats["dead"] == 0, "scale-down must not read as a death"
        assert stats["supervisor"]["respawns"] == 0
    finally:
        router.close()


def test_monitor_renders_supervisor_state(tmp_path):
    """The fleet panel shows per-replica restart/backoff/quarantine state
    and the aggregate router totals line (respawns/shed/deadline-expired)."""
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status

    now = time.time()
    d = tmp_path / "router"
    d.mkdir()
    rows = [
        {"schema": 1, "ts": now, "kind": "router", "replica_id": None,
         "state": None, "pid": None, "queue_depth": 4, "delivered": 20,
         "requeues": 3, "shed": 2, "deadline_expired": 5, "respawns": 1,
         "quarantined": 1, "scale_ups": 0, "scale_downs": 0,
         "min_replicas": 2, "max_replicas": 4},
        {"schema": 1, "ts": now, "replica_id": 0, "state": "ready",
         "queue_depth": 1, "active_slots": 1, "num_slots": 4, "in_flight": 1,
         "heartbeat_age_s": 0.1, "restarts": 2, "probation": True},
        {"schema": 1, "ts": now, "replica_id": 1, "state": "dead",
         "queue_depth": 0, "active_slots": 0, "num_slots": 4, "in_flight": 0,
         "heartbeat_age_s": 9.0, "restarts": 1, "quarantined": True,
         "backoff_s": 2.0, "respawn_in_s": 1.5},
    ]
    with open(d / "replicas.jsonl", "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    status = collect_status(str(tmp_path), now=now)
    assert status["router"]["respawns"] == 1
    assert [r["replica_id"] for r in status["fleet"]] == [0, 1]
    text = render_status(status)
    assert "restarts 2" in text and "probation" in text
    assert "QUARANTINED" in text and "respawn in" in text
    assert "respawns 1" in text and "shed 2" in text
    assert "deadline-expired 5" in text


def test_exporter_tails_router_trail_into_counters(tmp_path):
    """The sidecar exporter replays fleet-trail rows through
    ingest.observe_router_row: the serving_router_*_total counters and the
    per-replica restart gauge reach a scrape without the router embedding
    an HTTP server."""
    from accelerate_tpu.metrics.exporter import LoggingDirExporter

    d = tmp_path / "router"
    d.mkdir()
    with open(d / "replicas.jsonl", "w") as f:
        f.write(json.dumps({
            "schema": 1, "kind": "router", "ts": time.time(),
            "respawns": 2, "shed": 3, "deadline_expired": 4,
            "queue_depth": 1, "delivered": 9, "requeues": 5,
        }) + "\n")
        f.write(json.dumps({
            "schema": 1, "ts": time.time(), "replica_id": 0,
            "state": "ready", "restarts": 2,
        }) + "\n")
    exporter = LoggingDirExporter(str(tmp_path))
    exporter.refresh()
    text = exporter.render()
    assert "serving_router_respawns_total 2" in text
    assert "serving_router_shed_total 3" in text
    assert "serving_router_deadline_expired_total 4" in text
    assert 'serving_replica_restarts{replica="0"} 2' in text


# ---------------------------------------------------------------------------
# scheduler deadline accounting (tier-1: pure host)
# ---------------------------------------------------------------------------


def _sched(num_slots=2, num_blocks=9, block_size=8, max_seq=32):
    from accelerate_tpu.serving import BlockAllocator, SlotScheduler

    return SlotScheduler(num_slots, BlockAllocator(num_blocks), block_size, max_seq)


def test_scheduler_expires_queued_and_running_deadlines():
    from accelerate_tpu.serving import Request, RequestState

    sched = _sched()
    now = time.perf_counter()
    running = sched.submit(Request(prompt=[1] * 4, max_new_tokens=8, deadline=now + 60))
    fine = sched.submit(Request(prompt=[3] * 4, max_new_tokens=8))
    queued = sched.submit(Request(prompt=[2] * 4, max_new_tokens=8, deadline=now + 60))
    assert sched.deadline_live == 2
    admitted = sched.admit()  # 2 slots: running + fine; queued waits
    assert running in admitted and fine in admitted
    assert queued.slot is None
    free_before = sched.allocator.free_count

    # nothing expired yet: the sweep is a no-op
    assert sched.expire_deadlines(now=now) == []

    running.deadline = queued.deadline = now - 1.0
    expired = sched.expire_deadlines(now=now)
    assert {r.request_id for r in expired} == {running.request_id, queued.request_id}
    assert all(r.finish_reason == "deadline_exceeded" for r in expired)
    # the queued one left the waiting deque without ever holding blocks
    assert sched.deadline_live == 1  # running's slot not yet evicted
    # the running one frees its blocks on the same-iteration evict sweep
    sched.evict_finished()
    assert sched.deadline_live == 0
    assert sched.allocator.free_count > free_before
    assert fine.state in (RequestState.PREFILL, RequestState.QUEUED)
    # full accounting: every block owned by live requests only
    for req in (r for r in sched.slots if r is not None):
        assert req.finish_reason is None


# ---------------------------------------------------------------------------
# engine deadline eviction (slow lane: compiles the tiny model)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


@pytest.mark.slow
def test_engine_deadline_eviction_frees_blocks(tiny_model):
    """Deadline expiry mid-decode keeps the partial output, finishes with
    `deadline_exceeded`, and frees the slot + blocks the same iteration
    (freelist invariant holds; block-table edits only — the one compiled
    decode executable survives)."""
    from accelerate_tpu.serving import EngineConfig, InferenceEngine, RequestState

    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                     prefill_chunk=8, decode_burst=2),
    )
    # queued expiry: a microscopic budget is gone before the first step
    doomed = engine.add_request([5, 6, 7], max_new_tokens=8, deadline_ms=0.001)
    victim = engine.add_request([1, 2, 3], max_new_tokens=40, deadline_ms=1e9)
    bystander = engine.add_request([4, 5, 6], max_new_tokens=4)
    while len(victim.output_tokens) < 2:
        engine.step()
    assert doomed.finish_reason == "deadline_exceeded" and not doomed.output_tokens
    victim.deadline = time.perf_counter() - 1.0  # expire it mid-decode
    engine.step()
    assert victim.state is RequestState.FINISHED
    assert victim.finish_reason == "deadline_exceeded"
    assert len(victim.output_tokens) >= 2, "partial output must survive"
    assert victim.blocks == [] and victim.slot is None
    done = engine.run_until_idle(max_iterations=2000)
    assert bystander in done or bystander.finish_reason == "length"
    stats = engine.stats()
    assert stats["deadline_expired_total"] == 2
    assert stats["decode_compiles"] == 1, "deadline eviction must not retrace"
    assert stats["allocated_blocks"] == 0
    assert (
        stats["free_blocks"] + stats["cached_blocks"]
        == engine.allocator.num_blocks - 1
    ), "freelist invariant broken by deadline eviction"


@pytest.mark.slow
def test_engine_malformed_deadline_raises(tiny_model):
    """Mirrors the unknown-`priority` contract: a malformed deadline_ms is
    a ValueError at add_request, which the serve front end answers as an
    error row instead of dying."""
    from accelerate_tpu.serving import EngineConfig, InferenceEngine

    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8),
    )
    for bad in ("soon", -5, 0, float("nan")):
        with pytest.raises(ValueError, match="deadline_ms"):
            engine.add_request([1, 2, 3], max_new_tokens=4, deadline_ms=bad)
    assert engine.scheduler.queue_depth == 0


# ---------------------------------------------------------------------------
# real-process chaos schedules through the CLI (the acceptance bars)
# ---------------------------------------------------------------------------

_TINY_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "64", "--prefill-chunk", "8", "--decode-burst", "2",
]


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_TELEMETRY", None)
    env.pop("ACCELERATE_CHAOS_SPEC", None)
    return env


def _read_lines(stream, sink):
    for line in stream:
        line = line.strip()
        if line:
            sink.append(line)


def _start_reader(proc, sink):
    t = threading.Thread(target=_read_lines, args=(proc.stdout, sink), daemon=True)
    t.start()
    return t


def _wait_results(sink, n, timeout, proc=None):
    deadline = time.monotonic() + timeout
    while len(sink) < n and time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            break
        time.sleep(0.1)
    return [json.loads(line) for line in sink]


def _req(i, session=None, n_new=4):
    payload = {"id": i, "prompt": [1 + (i % 5), 7, 3], "max_new_tokens": n_new}
    if session is not None:
        payload["session_id"] = session
    return json.dumps(payload) + "\n"


def _trail_rows(logdir):
    path = os.path.join(logdir, "router", "replicas.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _trail_pids(rows):
    return {r["pid"] for r in rows if r.get("pid") and r.get("replica_id") is not None}


def _assert_all_dead(pids, timeout=10.0):
    """Every pid must be gone. A just-reaped child can linger as a zombie
    for an instant after the parent exits — poll briefly before declaring
    an orphan (os.kill(pid, 0) succeeds on zombies)."""

    def alive():
        out = []
        for pid in pids:
            try:
                os.kill(pid, 0)
                out.append(pid)
            except OSError:
                continue
        return out

    deadline = time.monotonic() + timeout
    while alive() and time.monotonic() < deadline:
        time.sleep(0.25)
    leftovers = alive()
    assert not leftovers, f"orphaned process(es) survived the run: {leftovers}"


def _route(tmp_path, *extra, replicas=2):
    return subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", str(replicas), "--logging-dir", str(tmp_path),
         "--health-interval", "0.2", *extra, *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )


def test_chaos_cli_kill_respawn_exactly_once(tmp_path):
    """Acceptance: under a seeded kill -9 schedule, every submitted request
    is answered exactly once, the supervisor respawns the victim (restart
    visible in the fleet trail), the fleet recovers to --min-replicas
    ready, and zero processes are orphaned."""
    proc = _route(
        tmp_path, "--respawn", "--min-replicas", "2",
        "--chaos-spec", "seed=1;r0:kill@3;r1:delay@2:0.05..0.2",
    )
    results = []
    _start_reader(proc, results)
    try:
        # warmup pins sessions: chat-0 -> replica 0, chat-1 -> replica 1
        for i in range(4):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}"))
        proc.stdin.flush()
        assert len(_wait_results(results, 4, timeout=240, proc=proc)) == 4, (
            f"fleet never answered warmup; rc={proc.poll()}"
        )
        pids_before = _trail_pids(_trail_rows(tmp_path))
        assert len(pids_before) == 2
        # the wave lands replica 0's 3rd request -> chaos kill -9 with
        # requests in flight on it
        for i in range(4, 12):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}", n_new=8))
        proc.stdin.flush()
        parsed = _wait_results(results, 12, timeout=240, proc=proc)
        assert len(parsed) == 12, f"rc={proc.poll()} results={len(parsed)}"

        # fleet recovers: replica 0 re-reports ready with restarts >= 1
        def recovered():
            rows = _trail_rows(tmp_path)
            latest = {}
            for r in rows:
                if r.get("replica_id") is not None:
                    latest[r["replica_id"]] = r
            return (
                len(latest) >= 2
                and latest.get(0, {}).get("state") == "ready"
                and latest.get(0, {}).get("restarts", 0) >= 1
            )

        deadline = time.monotonic() + 120
        while not recovered() and time.monotonic() < deadline:
            time.sleep(0.25)
        assert recovered(), "fleet never recovered to 2 ready replicas"
        proc.stdin.close()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    ids = sorted(r.get("id") for r in parsed)
    assert ids == list(range(12)), f"lost/duplicated: {ids}"
    errors = [r for r in parsed if "error" in r]
    assert not errors, f"kill lost requests: {errors}"
    rows = _trail_rows(tmp_path)
    assert any(r.get("state") == "dead" for r in rows), "death never recorded"
    assert any(
        r.get("kind") == "router" and r.get("respawns", 0) >= 1 for r in rows
    ), "supervisor respawn never reached the trail"
    # crash-loop backoff was armed for the death (visible in the trail)
    assert any(
        r.get("replica_id") == 0 and r.get("backoff_s", 0) > 0 for r in rows
    )
    _assert_all_dead(_trail_pids(rows))


def test_chaos_cli_sigstop_wedge_rescued_and_not_orphaned(tmp_path):
    """A SIGSTOP'd replica (wedged: socket open, /healthz starved) is
    marked dead, its stranded request is rescued to the survivor, the
    frozen process is KILLED (not abandoned — the no-orphans invariant),
    and the supervisor respawns the identity."""
    proc = _route(
        tmp_path, "--respawn", "--min-replicas", "2",
        "--health-interval", "0.1", "--chaos-spec", "r0:stop@2",
    )
    results = []
    _start_reader(proc, results)
    try:
        for i in range(2):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}"))
        proc.stdin.flush()
        assert len(_wait_results(results, 2, timeout=240, proc=proc)) == 2
        wedged_pids = _trail_pids(_trail_rows(tmp_path))
        # replica 0's 2nd request freezes it with the POST in flight
        for i in range(2, 6):
            proc.stdin.write(_req(i, session="chat-0", n_new=8))
        proc.stdin.flush()
        parsed = _wait_results(results, 6, timeout=240, proc=proc)
        assert len(parsed) == 6, (
            f"wedged request never rescued; rc={proc.poll()}"
        )
        proc.stdin.close()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    ids = sorted(r.get("id") for r in parsed)
    assert ids == list(range(6)), f"lost/duplicated: {ids}"
    assert not [r for r in parsed if "error" in r]
    rows = _trail_rows(tmp_path)
    assert any(r.get("state") == "dead" for r in rows)
    # the frozen process must be gone: killed on the death verdict, and
    # every other pid reaped by drain
    _assert_all_dead(wedged_pids | _trail_pids(rows))


def test_chaos_cli_dead_fleet_without_respawn_regression(tmp_path):
    """Regression pin: WITHOUT --respawn the same kill schedule degrades to
    PR 7's dead-fleet behaviour — queued requests are answered with the
    every-replica-is-dead error row, and nothing respawns."""
    proc = _route(tmp_path, "--chaos-spec", "r0:kill@2", replicas=1)
    results = []
    _start_reader(proc, results)
    try:
        proc.stdin.write(_req(0))
        proc.stdin.flush()
        assert len(_wait_results(results, 1, timeout=240, proc=proc)) == 1
        for i in range(1, 4):
            proc.stdin.write(_req(i, n_new=8))
        proc.stdin.flush()
        parsed = _wait_results(results, 4, timeout=240, proc=proc)
        proc.stdin.close()
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    parsed = [json.loads(line) for line in results]
    assert sorted(r.get("id") for r in parsed) == [0, 1, 2, 3]
    dead_rows = [r for r in parsed if "error" in r]
    assert dead_rows, "dead fleet must answer error rows, not hang"
    assert any("every replica is dead" in r["error"] for r in dead_rows)
    rows = _trail_rows(tmp_path)
    assert not any(r.get("restarts") for r in rows if r.get("replica_id") == 0)
    _assert_all_dead(_trail_pids(rows))


def test_route_bringup_timeout_kills_spawned_replicas(tmp_path):
    """Satellite: when wait_until_ready times out (here: one replica's
    /healthz blacked out from startup), route kills every already-spawned
    replica before exiting 1 — no orphans on failed bring-up."""
    proc = _route(
        tmp_path, "--ready-timeout", "10",
        "--chaos-spec", "r1:blackout@0:9999",
    )
    try:
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == 1
    rows = _trail_rows(tmp_path)
    pids = _trail_pids(rows)
    assert pids, "health loop never recorded the spawned pids"
    # give the kernel a beat to reap, then assert both replicas are gone
    time.sleep(0.5)
    _assert_all_dead(pids)


def test_serve_cli_malformed_chaos_spec_refuses(tmp_path):
    """A typo'd spec must refuse bring-up (exit 2, error row) — silently
    running a clean 'chaos' test would certify nothing."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "serve", "--chaos-spec", "explode@oops", *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 2
    assert "unknown chaos fault" in out
