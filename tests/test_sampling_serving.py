"""Per-slot sampling, constrained decoding, and the OpenAI front door.

The contract under test: per-request sampling knobs and grammar DFA
states ride the ONE compiled decode executable as fixed-shape lane
inputs — ``decode_compiles == 1`` with the lanes armed, including with
speculation and on a 4-device mesh — while greedy requests stay
token-identical to the lanes-off (``per_slot_sampling=False``) engine at
every ``kv_dtype``, and a fixed seed reproduces the exact same tokens
regardless of admission order or preempt/swap/resume.

Tier-1 (pure host / no compiles): params validation + resolution, stop
matching, the regex→DFA compiler and JSON-schema subset, the OpenAI
request/response translation (golden payloads, SSE framing, error
objects) against a fake submit fn, and the metrics/monitor plumbing.
The engine end-to-end legs and the real ``serve --http`` / ``route
--http`` subprocess tests ride the slow lane.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from accelerate_tpu.serving import (
    ITERATION_PHASES,
    EngineConfig,
    GrammarError,
    InferenceEngine,
    SamplingParams,
    compile_grammar,
    resolve_sampling,
    validate_instance,
)
from accelerate_tpu.serving.grammar import compile_regex, schema_to_regex
from accelerate_tpu.serving.sampling import match_stop

# ---------------------------------------------------------------------------
# sampling params: validation + resolution (tier-1)
# ---------------------------------------------------------------------------


def test_flight_phase_vocabulary_unchanged():
    """Sampling/grammar work lands inside the existing phases (the pick is
    part of dispatch, stop bookkeeping is harvest) — the flight recorder's
    phase vocabulary must NOT grow."""
    assert ITERATION_PHASES == (
        "schedule", "prefill", "dispatch", "device_wait", "harvest"
    )


@pytest.mark.parametrize(
    "kw, match",
    [
        (dict(temperature=-0.1), "temperature"),
        (dict(top_p=0.0), "top_p"),
        (dict(top_p=1.5), "top_p"),
        (dict(top_k=-1), "top_k"),
        (dict(repetition_penalty=0.0), "repetition_penalty"),
        (dict(min_tokens=-1), "min_tokens"),
        (dict(logprobs=-1), "logprobs"),
    ],
)
def test_sampling_params_refusals(kw, match):
    with pytest.raises(ValueError, match=match):
        SamplingParams(**kw).validate()


def test_resolve_sampling_coercions():
    # None inherits the engine default
    default = SamplingParams(do_sample=True, temperature=0.7)
    assert resolve_sampling(None, default) is default
    assert resolve_sampling(None) == SamplingParams()
    # dicts validate; a bare token-id sequence becomes one stop sequence
    p = resolve_sampling({"do_sample": True, "seed": 7, "stop": [3, 4]})
    assert p.seed == 7 and p.stop == ((3, 4),)
    p = resolve_sampling({"stop": [[3], [4, 5]]})
    assert p.stop == ((3,), (4, 5))
    with pytest.raises(ValueError, match="unknown sampling params"):
        resolve_sampling({"temprature": 1.0})  # typo'd key names itself
    with pytest.raises(ValueError, match="dict or SamplingParams"):
        resolve_sampling("greedy")
    # inert == indistinguishable from bare greedy (argmax fast path)
    assert SamplingParams().inert
    assert not SamplingParams(do_sample=True).inert
    assert not SamplingParams(repetition_penalty=1.2).inert
    assert not SamplingParams(logprobs=2).inert


def test_match_stop_suffix_semantics():
    # returns the matched length (the caller trims that many tokens)
    assert match_stop([1, 2, 3], ((2, 3),)) == 2
    assert match_stop([1, 2, 3], ((9,), (3,))) == 1
    assert match_stop([1, 2, 3], ((1, 2),)) == 0  # suffix only
    assert match_stop([1], ((1, 1),)) == 0  # longer than output
    assert match_stop([1, 2, 3], ()) == 0


# ---------------------------------------------------------------------------
# grammar: regex → DFA, schema subset, cache (tier-1)
# ---------------------------------------------------------------------------


def test_regex_dfa_walk_and_final_states():
    g = compile_regex("ab+c", 256, eos_id=0)
    s = g.start
    assert g.allows(s, ord("a")) and not g.allows(s, ord("b"))
    s = g.advance(s, ord("a"))
    s = g.advance(s, ord("b"))
    assert g.allows(s, ord("b")) and g.allows(s, ord("c"))
    s = g.advance(s, ord("c"))
    assert g.accepting[s]
    # 'c' is terminal for this pattern: accepting with no way forward
    assert g.final[s]
    # eos is only allowed from accepting states
    assert g.allows(s, 0)
    assert not g.allows(g.start, 0)


def test_regex_open_ended_accepting_is_not_final():
    g = compile_regex("[0-9]+", 256)
    s = g.advance(g.start, ord("7"))
    assert g.accepting[s] and not g.final[s]  # more digits always legal


def test_padded_tables_shapes():
    g = compile_regex("ab", 256)
    allow, trans = g.padded_tables(16)
    assert allow.shape == (16, 256) and trans.shape == (16, 256)
    # padding rows are inert (all-allow) — a stale lane value can never
    # produce an all-masked distribution
    assert allow[g.num_states:].all()
    assert (trans[g.num_states:] == 0).all()
    with pytest.raises(GrammarError, match="grammar_states"):
        g.padded_tables(g.num_states - 1)


def test_schema_subset_to_regex_and_validate():
    assert json.loads("42") == 42  # sanity on the target encoding
    for schema, good, bad in [
        ({"type": "integer"}, 42, 4.5),
        ({"type": "boolean"}, True, "true"),
        ({"type": "number"}, -3.5, "x"),
        ({"enum": ["a", "b"]}, "a", "c"),
        ({"type": "string"}, "hi", 7),
        ({"type": "null"}, None, 0),
    ]:
        pattern = schema_to_regex(schema)
        assert isinstance(pattern, str) and pattern
        assert validate_instance(schema, good) is None
        with pytest.raises(GrammarError):
            validate_instance(schema, bad)
    obj_schema = {
        "type": "object",
        "properties": {"n": {"type": "integer"}},
        "required": ["n"],
    }
    assert validate_instance(obj_schema, {"n": 1}) is None
    with pytest.raises(GrammarError, match="missing property"):
        validate_instance(obj_schema, {})
    arr = {"type": "array", "items": {"type": "integer"}}
    assert validate_instance(arr, [1, 2]) is None
    with pytest.raises(GrammarError):
        validate_instance(arr, [1, "x"])


@pytest.mark.parametrize(
    "spec, match",
    [
        ({"type": "regex", "pattern": ""}, "pattern"),
        ({"type": "json_schema"}, "schema"),
        ({"type": "bnf", "rules": "x"}, "unknown grammar type"),
        # lowercase letters are bytes >= 97: 'true|false' cannot be spelt
        # over the 64-token byte vocab — refused at compile, not at decode
        ({"type": "json_schema", "schema": {"type": "boolean"}},
         "matches nothing over this vocabulary"),
    ],
)
def test_grammar_compile_refusals(spec, match):
    with pytest.raises(GrammarError, match=match):
        compile_grammar(spec, 64, eos_id=0)


def test_grammar_cache_memoises_by_spec_and_vocab():
    spec = {"type": "regex", "pattern": "[0-9]{1,4}"}
    a = compile_grammar(spec, 256, eos_id=0, max_states=64)
    b = compile_grammar(dict(spec), 256, eos_id=0, max_states=64)
    assert a is b  # hash of the spec, not object identity
    c = compile_grammar(spec, 128, eos_id=0, max_states=64)
    assert c is not a  # vocab is part of the key
    assert a.hash == c.hash  # ... but the spec hash matches


# ---------------------------------------------------------------------------
# OpenAI front end: translation + framing against a fake submit (tier-1)
# ---------------------------------------------------------------------------


def _fake_submit(result_fn, capture):
    """A submit fn that answers synchronously: records the payload, echoes
    a result row derived from it."""

    def submit(payload, cb):
        capture.append(payload)
        cb(result_fn(payload))

    return submit


def _ok_result(payload, tokens=(104, 105)):
    out = {
        "tokens": list(tokens),
        "finish_reason": "length",
        "prompt_tokens": len(payload["prompt"]),
    }
    if "trace_id" in payload:  # the serve loop echoes it back like this
        out["trace_id"] = payload["trace_id"]
    return out


def test_openai_completion_payload_and_body_golden():
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    sent = []
    fe = OpenAIFrontend(_fake_submit(_ok_result, sent))
    kind, status, body = fe.handle("/v1/completions", {
        "prompt": "hi", "temperature": 0, "max_tokens": 4, "stop": "X",
        "seed": 3, "x_accelerate_priority": "batch",
        "x_accelerate_trace_id": "0af7651916cd43dd8448eb211c80319c",
    })
    assert (kind, status) == ("json", 200)
    payload = sent[0]
    assert payload["prompt"] == [104, 105]  # UTF-8 bytes of "hi"
    assert payload["sampling"]["do_sample"] is False  # temperature 0 == greedy
    assert payload["sampling"]["seed"] == 3
    assert payload["sampling"]["stop"] == [[88]]
    assert payload["max_new_tokens"] == 4
    assert payload["priority"] == "batch"
    assert payload["trace_id"] == "0af7651916cd43dd8448eb211c80319c"
    assert body["object"] == "text_completion"
    assert body["id"].startswith("cmpl-")
    assert body["choices"][0]["text"] == "hi"
    assert body["choices"][0]["finish_reason"] == "length"
    assert body["usage"] == {
        "prompt_tokens": 2, "completion_tokens": 2, "total_tokens": 4,
    }
    assert body["x_accelerate"]["trace_id"] == "0af7651916cd43dd8448eb211c80319c"


def test_openai_chat_payload_defaults_to_sampling():
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    sent = []
    fe = OpenAIFrontend(_fake_submit(_ok_result, sent))
    kind, status, body = fe.handle("/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hello"}],
        "top_p": 0.9, "response_format": {
            "type": "json_schema",
            "json_schema": {"name": "t", "schema": {"type": "integer"}},
        },
    })
    assert status == 200
    payload = sent[0]
    # OpenAI default temperature 1.0 → sampled lanes, top_p forwarded
    assert payload["sampling"]["do_sample"] is True
    assert payload["sampling"]["top_p"] == 0.9
    assert payload["grammar"] == {"type": "json_schema",
                                  "schema": {"type": "integer"}}
    assert body["object"] == "chat.completion"
    assert body["id"].startswith("chatcmpl-")
    assert body["choices"][0]["message"]["role"] == "assistant"
    assert body["choices"][0]["message"]["content"] == "hi"


def test_openai_sse_framing_delta_mode():
    """Streaming contract: a role-bearing first chunk, content deltas,
    exactly one finish chunk carrying usage, then ``data: [DONE]``."""
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    def submit(payload, cb):
        stream = payload["_stream"]
        stream([104])
        stream([105, 33])
        cb(_ok_result(payload, tokens=(104, 105, 33)))

    fe = OpenAIFrontend(submit, streaming="delta")
    kind, events = fe.handle("/v1/chat/completions", {
        "messages": [{"role": "user", "content": "go"}], "stream": True,
    })
    assert kind == "sse"
    frames = list(events)
    assert all(f.startswith("data: ") and f.endswith("\n\n") for f in frames)
    assert frames[-1] == "data: [DONE]\n\n"
    chunks = [json.loads(f[6:]) for f in frames[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
    text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    assert text == "hi!"
    finals = [c for c in chunks if c["choices"][0]["finish_reason"]]
    assert len(finals) == 1
    assert finals[0]["usage"]["completion_tokens"] == 3


def test_openai_sse_at_completion_replays_whole_answer():
    """Route mode: replicas answer whole completions, the front end still
    speaks SSE — one content chunk, one finish chunk, [DONE]."""
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    fe = OpenAIFrontend(_fake_submit(_ok_result, []), streaming="at_completion")
    kind, events = fe.handle("/v1/completions", {"prompt": "x", "stream": True})
    frames = list(events)
    assert frames[-1] == "data: [DONE]\n\n"
    chunks = [json.loads(f[6:]) for f in frames[:-1]]
    assert "".join(c["choices"][0].get("text") or "" for c in chunks) == "hi"
    assert sum(1 for c in chunks if c["choices"][0]["finish_reason"]) == 1


@pytest.mark.parametrize(
    "path, body, param",
    [
        ("/v1/completions", {"prompt": "x", "n": 3}, "n"),
        ("/v1/completions", {"prompt": 42}, "prompt"),
        ("/v1/completions", {"prompt": "x", "temperature": 3.0}, "temperature"),
        ("/v1/completions", {"prompt": "x", "seed": "lucky"}, "seed"),
        ("/v1/completions", {"prompt": "x", "max_tokens": 0}, "max_tokens"),
        ("/v1/completions",
         {"prompt": "x", "response_format": {"type": "json_object"}},
         "response_format"),
        ("/v1/chat/completions", {"messages": []}, "messages"),
        ("/v1/chat/completions",
         {"messages": [{"role": "user", "content": "x"}], "tools": [{}]},
         "tools"),
    ],
)
def test_openai_error_objects(path, body, param):
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    fe = OpenAIFrontend(_fake_submit(_ok_result, []))
    kind, status, out = fe.handle(path, body)
    assert (kind, status) == ("json", 400)
    err = out["error"]
    assert err["type"] == "invalid_request_error"
    assert err["param"] == param
    assert isinstance(err["message"], str) and err["message"]


def test_openai_engine_error_rows_become_error_objects():
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    fe = OpenAIFrontend(_fake_submit(lambda p: {"error": "queue full"}, []))
    kind, status, out = fe.handle("/v1/completions", {"prompt": "x"})
    assert status == 400 and "queue full" in out["error"]["message"]


# ---------------------------------------------------------------------------
# metrics + monitor plumbing (tier-1: synthetic rows)
# ---------------------------------------------------------------------------


def test_sampling_metrics_round_trip_both_surfaces():
    """The new counters/gauges flow through BOTH ingest surfaces — the
    telemetry step-row path and the live stats()-dict path — into the
    documented serving_* names with the mode label split."""
    from accelerate_tpu.metrics.ingest import observe_engine_stats, observe_record
    from accelerate_tpu.metrics.openmetrics import (
        parse_openmetrics,
        render_openmetrics,
        sample_value,
    )
    from accelerate_tpu.metrics.registry import MetricsRegistry

    reg = MetricsRegistry(gate_main_process=False)
    observe_record(reg, {
        "type": "serving", "kind": "step",
        "sampled_tokens_greedy": 40, "sampled_tokens_sample": 10,
        "grammar_masked_steps": 6,
        "rejection_drafted_tokens": 20, "rejection_accepted_tokens": 15,
        "rejection_accept_rate": 0.75,
    })
    families = parse_openmetrics(render_openmetrics(reg))
    assert families["accelerate_serving_sampled_tokens"]["type"] == "counter"
    assert sample_value(
        families, "accelerate_serving_sampled_tokens", mode="greedy") == 40
    assert sample_value(
        families, "accelerate_serving_sampled_tokens", mode="sample") == 10
    assert sample_value(families, "accelerate_serving_grammar_masked_steps") == 6
    assert sample_value(families, "accelerate_serving_rejection_accept_rate") == 0.75

    # the stats() path ratchets the same counters (set_total semantics)
    observe_engine_stats(reg, {
        "sampled_tokens_greedy": 100, "sampled_tokens_sample": 30,
        "grammar_masked_steps": 9, "rejection_accept_rate": 0.8,
    })
    families = parse_openmetrics(render_openmetrics(reg))
    assert sample_value(
        families, "accelerate_serving_sampled_tokens", mode="greedy") == 100
    assert sample_value(
        families, "accelerate_serving_sampled_tokens", mode="sample") == 30
    assert sample_value(families, "accelerate_serving_grammar_masked_steps") == 9
    assert sample_value(families, "accelerate_serving_rejection_accept_rate") == 0.8


# ---------------------------------------------------------------------------
# engine end-to-end (slow lane: compiles the tiny model)
# ---------------------------------------------------------------------------

KV_DTYPES = ("bf16", "int8", "fp8")


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


def _cfg(**kw):
    base = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8)
    base.update(kw)
    return EngineConfig(**base)


def _skip_without_fp8(kv_dtype: str) -> None:
    if kv_dtype == "fp8":
        from accelerate_tpu.utils.compat import has_fp8_storage

        if not has_fp8_storage():
            pytest.skip("float8_e4m3fn storage unsupported on this jax stack")


def _prompts(seed, sizes=(5, 11, 17, 3, 9)):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=n).astype(np.int32) for n in sizes]


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_greedy_token_identity_lanes_vs_legacy(tiny_model, kv_dtype):
    """The headline bar: arming the lanes changes NOTHING for greedy
    traffic — token-identical to the ``per_slot_sampling=False`` engine
    (the PR 16 executables) at every kv_dtype, one executable each side."""
    _skip_without_fp8(kv_dtype)
    prompts = _prompts(0)
    budgets = [3 + 4 * i for i in range(5)]

    def run(per_slot):
        eng = InferenceEngine(
            tiny_model, _cfg(per_slot_sampling=per_slot, kv_dtype=kv_dtype)
        )
        reqs = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
        eng.run_until_idle(max_iterations=5000)
        return eng, [list(r.output_tokens) for r in reqs]

    lanes_eng, lanes_toks = run(True)
    _, legacy_toks = run(False)
    assert lanes_toks == legacy_toks
    st = lanes_eng.stats()
    assert st["decode_compiles"] == 1 and st["prefill_compiles"] == 1
    assert st["sampled_tokens_greedy"] == sum(budgets)
    assert st["sampled_tokens_sample"] == 0


@pytest.mark.slow
def test_fixed_seed_reproduces_across_admission_order(tiny_model):
    """A request's sampled tokens are a function of (prompt, seed, step) —
    never of which slot it landed in or who was admitted first."""
    prompts = _prompts(1, sizes=(6, 9, 12))
    payloads = [
        {"do_sample": True, "temperature": 0.9, "seed": 100 + i,
         "top_k": 40, "top_p": 0.95}
        for i in range(3)
    ]

    def run(order):
        eng = InferenceEngine(tiny_model, _cfg())
        reqs = {}
        for i in order:
            reqs[i] = eng.add_request(prompts[i], 8, sampling=payloads[i])
        eng.run_until_idle(max_iterations=5000)
        return {i: list(r.output_tokens) for i, r in reqs.items()}

    a = run([0, 1, 2])
    b = run([2, 0, 1])
    assert a == b
    assert any(a[i] for i in a)  # the trace actually decoded tokens


@pytest.mark.slow
def test_fixed_seed_reproduces_across_swap_preemption(tiny_model):
    """Preempt → swap out → restore mid-request replays nothing: the
    per-slot key is derived from (seed, position), so a sampled request
    resumes exactly where it left off, token-identical to the
    uncontended run."""
    prompts = [np.arange(8, dtype=np.int32), np.arange(8, dtype=np.int32) + 1]
    sampling = [
        {"do_sample": True, "temperature": 1.1, "seed": 7},
        {"do_sample": True, "temperature": 0.8, "seed": 8, "top_k": 20},
    ]

    def run(**pressure):
        eng = InferenceEngine(
            tiny_model,
            _cfg(num_slots=2, prefix_cache=False, **pressure),
        )
        reqs = [
            eng.add_request(p, max_new_tokens=30, sampling=s)
            for p, s in zip(prompts, sampling)
        ]
        eng.run_until_idle(max_iterations=5000)
        return eng, [list(r.output_tokens) for r in reqs]

    squeezed_eng, squeezed = run(num_blocks=6, swap_gb=0.01)
    _, roomy = run()
    assert squeezed == roomy
    st = squeezed_eng.stats()
    assert st["preemptions"] >= 1
    assert st["swapped_out_blocks"] == st["swapped_in_blocks"] > 0
    assert st["decode_compiles"] == 1


@pytest.mark.slow
def test_mixed_batch_one_executable_with_logprobs(tiny_model):
    """Greedy + sampled + grammar-constrained slots decode side by side in
    the SAME compiled executable; logprobs ride the existing harvest."""
    eng = InferenceEngine(tiny_model, _cfg(logprobs_topn=3))
    greedy = eng.add_request(_prompts(2)[0], 6)
    sampled = eng.add_request(
        _prompts(2)[1], 6,
        sampling={"do_sample": True, "temperature": 0.8, "seed": 5, "logprobs": 2},
    )
    digits = eng.add_request(
        _prompts(2)[3], 6,
        sampling={"do_sample": True, "temperature": 0.9, "seed": 6},
        grammar={"type": "regex", "pattern": "[0-9]+"},
    )
    eng.run_until_idle(max_iterations=5000)
    st = eng.stats()
    assert st["decode_compiles"] == 1 and st["prefill_compiles"] == 1
    assert st["sampled_tokens_greedy"] > 0 and st["sampled_tokens_sample"] > 0
    assert st["grammar_masked_steps"] == len(digits.output_tokens)
    assert greedy.finish_reason == "length"
    # the constrained slot only ever emitted digit bytes
    assert all(48 <= t <= 57 for t in digits.output_tokens)
    # logprobs: one entry per emitted token — the picked token's logprob
    # plus a descending top-2, all in the log domain
    assert sampled.logprobs is not None
    assert len(sampled.logprobs) == len(sampled.output_tokens)
    for entry, tok in zip(sampled.logprobs, sampled.output_tokens):
        assert entry["token"] == tok
        assert entry["logprob"] <= 0.0
        assert len(entry["top"]) == 2
        assert entry["top"][0][1] >= entry["top"][1][1]
    assert greedy.logprobs is None  # opt-in per request
    # grammar rows recycle once the holder finishes
    assert st["grammar_rows_live"] == 0


@pytest.mark.slow
def test_mixed_batch_one_executable_on_mesh4(tiny_model):
    """The same mixed batch over fsdp=2 x tp=2: lanes + grammar tables are
    replicated GSPMD inputs, decode_compiles == 1 on the mesh, and the
    sampled output is identical to the single-device engine."""
    import jax

    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs a >= 4-device (virtual) mesh")
    mesh = build_mesh(MeshPlugin(dp=1, fsdp=2, tp=2), devices=devices[:4])
    prompts = _prompts(3, sizes=(5, 12, 9))

    def run(mesh_arg):
        eng = InferenceEngine(tiny_model, _cfg(decode_burst=2), mesh=mesh_arg)
        reqs = [
            eng.add_request(prompts[0], 5),
            eng.add_request(
                prompts[1], 5,
                sampling={"do_sample": True, "temperature": 0.9, "seed": 11},
            ),
            eng.add_request(
                prompts[2], 5,
                sampling={"do_sample": True, "temperature": 0.7, "seed": 12},
                grammar={"type": "regex", "pattern": "[0-9]+"},
            ),
        ]
        eng.run_until_idle(max_iterations=5000)
        return eng, [list(r.output_tokens) for r in reqs]

    _, single = run(None)
    sharded_eng, sharded = run(mesh)
    assert sharded == single
    st = sharded_eng.stats()
    assert st["decode_compiles"] == 1
    assert st["mesh"] == {"fsdp": 2, "tp": 2}


@pytest.mark.slow
def test_rejection_sampling_goes_greedy_at_low_temperature(tiny_model):
    """temperature → 0 is the analytic sanity check for the rejection
    path: target and draft both collapse to argmax, so a draft token is
    accepted exactly when the two argmaxes agree — the sampled output
    equals the greedy spec output token for token and the rejection
    accept rate lands on the greedy agreement rate."""

    def run(sampling):
        eng = InferenceEngine(
            tiny_model, _cfg(spec_k=3, draft="early_exit:1")
        )
        reqs = [
            eng.add_request(p, 8, sampling=sampling)
            for p in _prompts(4, sizes=(6, 13))
        ]
        eng.run_until_idle(max_iterations=5000)
        return eng, [list(r.output_tokens) for r in reqs]

    greedy_eng, greedy_toks = run(None)
    eng, cold_toks = run({"do_sample": True, "temperature": 1e-6, "seed": 1})
    assert cold_toks == greedy_toks
    st = eng.stats()
    assert st["decode_compiles"] == 1
    assert st["rejection_drafted_tokens"] > 0
    # identical tokens → identical rounds: the rejection rate reproduces
    # the greedy longest-prefix agreement rate, not some sampled blur
    assert st["rejection_accept_rate"] == pytest.approx(
        greedy_eng.stats()["spec_accept_rate"], abs=0.1
    )
    # hot sampling still makes progress and keeps the rate in range
    hot_eng, hot_toks = run({"do_sample": True, "temperature": 2.0, "seed": 2})
    assert all(toks for toks in hot_toks)
    assert 0.0 < hot_eng.stats()["rejection_accept_rate"] <= 1.0


@pytest.mark.slow
def test_constrained_output_parses_and_validates(tiny_model):
    """Every grammar-constrained completion is valid JSON for its schema —
    including under sampling and composed with speculation. (Only scalar
    schemas fit the 64-token test vocab: object braces are bytes >= 123.)"""
    schema = {"type": "integer"}

    def run(spec_k):
        eng = InferenceEngine(
            tiny_model,
            _cfg(spec_k=spec_k,
                 draft="early_exit:1" if spec_k else "early_exit:2"),
        )
        reqs = [
            eng.add_request(
                p, 8,
                sampling={"do_sample": True, "temperature": 1.2, "seed": 20 + i},
                grammar={"type": "json_schema", "schema": schema},
            )
            for i, p in enumerate(_prompts(5, sizes=(4, 7, 10)))
        ]
        eng.run_until_idle(max_iterations=5000)
        return eng, reqs

    for spec_k in (0, 3):
        eng, reqs = run(spec_k)
        assert eng.stats()["decode_compiles"] == 1
        for req in reqs:
            text = bytes(req.output_tokens).decode()
            value = json.loads(text)  # digits (int mask) always parse
            assert validate_instance(schema, value) is None
            # a DFA-final state finishes the request as a natural stop
            assert req.finish_reason in ("stop", "length")


@pytest.mark.slow
def test_stop_sequences_and_min_tokens(tiny_model):
    eng = InferenceEngine(tiny_model, _cfg())
    probe = eng.add_request(_prompts(6)[0], 10)
    eng.run_until_idle(max_iterations=5000)
    toks = list(probe.output_tokens)
    assert len(toks) == 10
    stop_tok = toks[2]
    first = toks.index(stop_tok)

    # stop sequences: matched at the tail, trimmed from the answer
    eng = InferenceEngine(tiny_model, _cfg())
    stopped = eng.add_request(
        _prompts(6)[0], 10, sampling={"stop": [[stop_tok]]}
    )
    eng.run_until_idle(max_iterations=5000)
    assert list(stopped.output_tokens) == toks[:first]
    assert stopped.finish_reason == "stop"

    # min_tokens: the in-trace lane masks eos until the floor is reached
    eos = toks[2]
    eng = InferenceEngine(tiny_model, _cfg(eos_token_id=eos))
    early = eng.add_request(_prompts(6)[0], 10)
    floored = eng.add_request(_prompts(6)[0], 10, sampling={"min_tokens": 6})
    eng.run_until_idle(max_iterations=5000)
    assert early.finish_reason == "eos" and len(early.output_tokens) == first + 1
    assert len(floored.output_tokens) >= 6
    assert eng.stats()["decode_compiles"] == 1


@pytest.mark.slow
def test_sampling_telemetry_rows_and_monitor_line(tiny_model, tmp_path):
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status
    from accelerate_tpu.telemetry import TelemetryRecorder, set_active_recorder

    recorder = TelemetryRecorder(logging_dir=str(tmp_path))
    set_active_recorder(recorder)
    try:
        eng = InferenceEngine(tiny_model, _cfg(num_slots=2, stats_interval=2))
        eng.add_request(_prompts(7)[0], 6)
        eng.add_request(
            _prompts(7)[1], 6,
            sampling={"do_sample": True, "temperature": 0.9, "seed": 3},
        )
        eng.run_until_idle(max_iterations=5000)
    finally:
        set_active_recorder(None)
        recorder.close()

    steps = [
        r for r in recorder.records
        if r.get("type") == "serving" and r.get("kind") == "step"
    ]
    assert steps, "stats_interval=2 must have emitted step rows"
    last = steps[-1]
    assert last["sampled_tokens_greedy"] > 0
    assert last["sampled_tokens_sample"] > 0
    assert last["grammar_masked_steps"] == 0

    status = collect_status(str(tmp_path))
    srv = status["serving"]
    assert srv["sampled_tokens_sample"] > 0
    rendered = render_status(status)
    assert "sampling: greedy" in rendered and "grammar-masked" in rendered


# ---------------------------------------------------------------------------
# the OpenAI door on the real CLIs (slow lane: subprocesses)
# ---------------------------------------------------------------------------

_TINY_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_TELEMETRY", None)
    return env


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_ready(port, proc, timeout=240):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"server exited rc={proc.returncode}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if json.loads(r.read()).get("state") == "ready":
                    return
        except (OSError, ValueError):
            pass
        time.sleep(0.25)
    raise RuntimeError("server never became ready")


def _post(port, path, body, stream=False):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        resp = urllib.request.urlopen(req, timeout=180)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    with resp:
        raw = resp.read().decode()
    return resp.status, raw if stream else json.loads(raw)


def _sse_chunks(raw):
    events = [line[6:] for line in raw.split("\n\n") if line.startswith("data: ")]
    assert events and events[-1] == "[DONE]"
    return [json.loads(e) for e in events[:-1]]


@pytest.mark.slow
def test_openai_endpoints_on_live_serve(tmp_path):
    """Golden requests through a REAL ``serve --http`` subprocess: both
    endpoints, SSE framing on the wire (chunked HTTP/1.1), schema-valid
    constrained output, error objects, and decode_compiles == 1 after the
    whole mixed trace."""
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "serve", *_TINY_ARGS, "--max-new-tokens", "16",
         "--logprobs-topn", "2", "--http", str(port)],
        env=_cli_env(), stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        _wait_ready(port, proc)

        # greedy completion: deterministic, usage adds up
        st, body = _post(port, "/v1/completions", {
            "prompt": "hello", "temperature": 0, "max_tokens": 8,
        })
        assert st == 200 and body["object"] == "text_completion"
        assert body["usage"]["prompt_tokens"] == 5
        assert body["usage"]["total_tokens"] == (
            body["usage"]["prompt_tokens"] + body["usage"]["completion_tokens"]
        )
        _, again = _post(port, "/v1/completions", {
            "prompt": "hello", "temperature": 0, "max_tokens": 8,
        })
        assert again["choices"][0]["text"] == body["choices"][0]["text"]

        # seeded sampling reproduces; logprobs ride along
        req = {"prompt": "abc", "temperature": 0.8, "seed": 42,
               "max_tokens": 6, "logprobs": 2}
        st, one = _post(port, "/v1/completions", req)
        _, two = _post(port, "/v1/completions", req)
        assert st == 200
        assert one["choices"][0]["text"] == two["choices"][0]["text"]
        lp = one["choices"][0]["logprobs"]
        assert len(lp["token_logprobs"]) == one["usage"]["completion_tokens"]

        # constrained chat answers valid JSON for the schema
        schema = {"type": "object",
                  "properties": {"name": {"enum": ["alpha", "beta", "gamma"]},
                                 "n": {"type": "integer"}},
                  "required": ["name", "n"]}
        st, body = _post(port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "give me json"}],
            "temperature": 0.7, "seed": 1, "max_tokens": 48,
            "response_format": {"type": "json_schema",
                                "json_schema": {"name": "t", "schema": schema}},
        })
        assert st == 200
        value = json.loads(body["choices"][0]["message"]["content"])
        assert validate_instance(schema, value) is None
        assert body["choices"][0]["finish_reason"] == "stop"

        # SSE chat over the wire: role delta, one finish chunk w/ usage
        st, raw = _post(port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "hi"}],
            "temperature": 0, "max_tokens": 6, "stream": True,
        }, stream=True)
        assert st == 200
        chunks = _sse_chunks(raw)
        assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
        finals = [c for c in chunks if c["choices"][0]["finish_reason"]]
        assert len(finals) == 1 and "usage" in finals[0]

        # streamed deltas never over-send past a later stop truncation
        st, raw = _post(port, "/v1/completions", {
            "prompt": "hello", "temperature": 0, "max_tokens": 12,
            "stop": ["X"], "stream": True,
        }, stream=True)
        chunks = _sse_chunks(raw)
        streamed = "".join(c["choices"][0].get("text") or "" for c in chunks)
        finals = [c for c in chunks if c["choices"][0]["finish_reason"]]
        assert len(streamed) == finals[0]["usage"]["completion_tokens"]

        # OpenAI error objects over the wire
        st, body = _post(port, "/v1/completions", {"prompt": "x", "n": 3})
        assert st == 400 and body["error"]["param"] == "n"
        st, body = _post(port, "/v1/completions",
                         {"prompt": "x", "logprobs": 9})  # over the cap
        assert st == 400 and body["error"]["type"] == "invalid_request_error"

        # one executable after the whole mixed trace
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        assert stats["decode_compiles"] == 1
        assert stats["sampled_tokens_sample"] > 0
        assert stats["grammar_masked_steps"] > 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


@pytest.mark.slow
def test_openai_endpoints_on_route_fleet(tmp_path):
    """The same front door mounted on the router: an unmodified OpenAI
    HTTP client (stdlib here) completes a streaming chat against
    ``accelerate-tpu route --http`` — sampling/grammar payloads forward
    verbatim to the replica."""
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "1", "--logging-dir", str(tmp_path),
         "--http", str(port), *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    try:
        _wait_ready(port, proc)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as r:
            health = json.loads(r.read())
        assert health["replicas"] >= 1

        st, body = _post(port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "hello"}],
            "temperature": 0.7, "seed": 9, "max_tokens": 6,
        })
        assert st == 200 and body["object"] == "chat.completion"
        assert body["usage"]["completion_tokens"] >= 1

        # streaming (at_completion mode): SSE framing intact end to end
        st, raw = _post(port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "stream please"}],
            "temperature": 0, "max_tokens": 6, "stream": True,
        }, stream=True)
        assert st == 200
        chunks = _sse_chunks(raw)
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        assert len(text) >= 1
        assert sum(1 for c in chunks if c["choices"][0]["finish_reason"]) == 1

        # error objects answer from the router too
        st, body = _post(port, "/v1/completions", {"prompt": 42})
        assert st == 400 and body["error"]["param"] == "prompt"
    finally:
        if proc.stdin:
            proc.stdin.close()
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
