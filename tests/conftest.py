"""Test env: force an 8-device virtual CPU mesh before JAX initialises.

This is the "multi-node without a cluster" fake backend (SURVEY §4): every
sharding/collective path runs against 8 host-platform devices, mirroring the
reference's gloo-on-localhost trick (``/root/reference/src/accelerate/
test_utils/testing.py``) but inside one process.
"""

import os
import sys

#: ``ACCELERATE_TEST_BACKEND=tpu`` runs the suite against the attached
#: real backend instead of the virtual CPU mesh (the reference's
#: ``get_backend`` override) — that is the lane where ``require_tpu``
#: tests (e.g. the bf16-over-ICI GPipe smoke) actually execute.
_TEST_BACKEND = os.environ.get("ACCELERATE_TEST_BACKEND", "cpu").lower()

def _xla_flag_supported(flag: str) -> bool:
    """XLA ABORTS the process on unknown flags in XLA_FLAGS (no exception to
    catch), and older jaxlibs lack the CPU collective-timeout flag — probe in
    a throwaway subprocess so an unsupported flag degrades to 'not set'
    instead of killing the whole pytest session at collection."""
    import subprocess

    env = dict(os.environ, XLA_FLAGS=flag, JAX_PLATFORMS="cpu")
    try:
        return (
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                env=env,
                capture_output=True,
                timeout=120,
            ).returncode
            == 0
        )
    except Exception:
        return False


if _TEST_BACKEND == "cpu":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    if "collective_call_terminate_timeout" not in flags and _xla_flag_supported(
        "--xla_cpu_collective_call_terminate_timeout_seconds=600"
    ):
        # single-core machines time-slice all 8 device threads: a heavy
        # program can exceed XLA CPU's default 40s collective rendezvous
        # window, which ABORTS the process. Give the scheduler room.
        flags = (flags + " --xla_cpu_collective_call_terminate_timeout_seconds=600").strip()
    os.environ["XLA_FLAGS"] = flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The env var alone is not enough when a site plugin (e.g. an out-of-tree TPU
# backend) registers itself and rewrites platform selection — the config
# update below always wins as long as it runs before backend init.
import jax  # noqa: E402

if _TEST_BACKEND == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def reset_state():
    """Reset the Borg singletons between tests (reference
    ``AccelerateTestCase``, ``test_utils/testing.py:479``)."""
    yield
    from accelerate_tpu.ops.attention import set_attention_context
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_attention_context(None)
    from accelerate_tpu.parallel.pipeline import set_default_microbatches

    set_default_microbatches(0)
    from accelerate_tpu.resilience.preemption import get_active_handler

    handler = get_active_handler()
    if handler is not None:  # restore the process signal handlers
        handler.uninstall()
    from accelerate_tpu.analysis.sanitizer import set_active_sanitizer

    set_active_sanitizer(None)
    from accelerate_tpu.serving.flight import set_active_flight_recorder

    set_active_flight_recorder(None)
