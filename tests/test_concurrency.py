"""The static concurrency analyzer + LockWatch runtime sanitizer
(``accelerate_tpu/analysis/concurrency.py`` / ``lockwatch.py``).

Golden fixture corpus: ONE positive and ONE negative snippet per RC rule
— every positive must fire exactly its rule, every negative must be
clean (zero false positives is the bar that makes the ``make lint`` gate
a gate instead of noise). Plus: cross-file class unification (the
supervisor-takes-the-router's-lock idiom), suppression syntax, the CLI's
exit codes, self-application to the serving/metrics/diagnostics tree,
and LockWatch's deterministic two-thread inversion detection.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from accelerate_tpu.analysis.concurrency import (
    RC_RULES,
    race_check_paths,
    race_check_source,
    race_check_sources,
)
from accelerate_tpu.analysis.engine import normalize_rule_ids
from accelerate_tpu.analysis.lockwatch import (
    NULL_LOCKWATCH,
    LockWatch,
    WatchedLock,
    get_active_lockwatch,
    maybe_watch,
    set_active_lockwatch,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the tree the `make lint` gate race-checks (self-application surface)
GATED_DIRS = [
    os.path.join(REPO, "accelerate_tpu", d)
    for d in ("serving", "metrics", "diagnostics", "commands", "analysis")
]

# ---------------------------------------------------------------------------
# golden corpus: {rule: (positive_snippet, negative_snippet)}
# ---------------------------------------------------------------------------

CORPUS = {
    "RC001": (
        """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
    def bump(self):
        with self._lock:
            self._n += 1
    def reset(self):
        self._n = 0  # guarded attribute written without the lock
""",
        """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # __init__ happens-before publication: exempt
    def bump(self):
        with self._lock:
            self._n += 1
    def reset(self):
        with self._lock:
            self._n = 0
    def snapshot(self):
        with self._lock:
            return self._n
""",
    ),
    "RC002": (
        """
import threading

a = threading.Lock()
b = threading.Lock()

def one():
    with a:
        with b:
            pass

def two():
    with b:
        with a:  # reverse order: deadlock under the right interleaving
            pass
""",
        """
import threading

a = threading.Lock()
b = threading.Lock()

def one():
    with a:
        with b:
            pass

def two():
    with a:
        with b:  # same global order everywhere
            pass
""",
    ),
    "RC003": (
        """
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
    def tick(self):
        with self._lock:
            self._n += 1
            time.sleep(1.0)  # every other thread stalls behind this
""",
        """
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
    def tick(self):
        with self._lock:
            self._n += 1
        time.sleep(1.0)  # blocking work with the lock released
""",
    ),
    "RC004": (
        """
import threading

class Inbox:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []
    def put(self, x):
        with self._cv:
            self._items.append(x)
            self._cv.notify()
    def get(self):
        with self._cv:
            if not self._items:
                self._cv.wait()  # spurious wakeup pops an empty list
            return self._items.pop()
""",
        """
import threading

class Inbox:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []
    def put(self, x):
        with self._cv:
            self._items.append(x)
            self._cv.notify()
    def get(self):
        with self._cv:
            while not self._items:
                self._cv.wait()
            return self._items.pop()
""",
    ),
    "RC005": (
        """
import threading

class Worker:
    def __init__(self):
        self.thread = threading.Thread(target=self._run)
        self.thread.start()
        self.items = []  # the thread can observe the object half-built
    def _run(self):
        pass
""",
        """
import threading

class Worker:
    def __init__(self):
        self.items = []  # state fully built first...
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()  # ...thread starts as the LAST step
    def _run(self):
        pass
""",
    ),
    "RC006": (
        """
import threading

class Emitter:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs = []
    def subscribe(self, cb):
        with self._lock:
            self._subs.append(cb)
    def publish(self, evt):
        with self._lock:
            for cb in self._subs:
                cb(evt)  # re-entrant subscribe() self-deadlocks
""",
        """
import threading

class Emitter:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs = []
    def subscribe(self, cb):
        with self._lock:
            self._subs.append(cb)
    def publish(self, evt):
        with self._lock:
            subs = list(self._subs)  # snapshot under the lock...
        for cb in subs:
            cb(evt)  # ...invoke with it released
""",
    ),
}


class TestGoldenCorpus:
    @pytest.mark.parametrize("rule_id", sorted(CORPUS))
    def test_positive_fires(self, rule_id):
        positive, _ = CORPUS[rule_id]
        findings = race_check_source(positive, path=f"pos_{rule_id}.py")
        fired = {f.rule for f in findings}
        assert fired == {rule_id}, (
            f"{rule_id} positive fired {fired or 'nothing'}:\n"
            + "\n".join(f.render() for f in findings)
        )

    @pytest.mark.parametrize("rule_id", sorted(CORPUS))
    def test_negative_clean(self, rule_id):
        _, negative = CORPUS[rule_id]
        findings = race_check_source(negative, path=f"neg_{rule_id}.py")
        assert not findings, (
            f"{rule_id} negative false-positived:\n"
            + "\n".join(f.render() for f in findings)
        )

    def test_every_rule_has_fixture_and_metadata(self):
        assert set(CORPUS) == set(RC_RULES)
        for rule in RC_RULES.values():
            assert rule.severity in ("error", "warning")
            assert rule.summary and rule.fixit


class TestAnalysisDetails:
    def test_caller_holds_the_lock_idiom_clean(self):
        """A helper only ever called with the lock held inherits the held
        set — the router's `_pick_replica` idiom must not false-positive."""
        src = """
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []
    def push(self, x):
        with self._lock:
            self._helper(x)
    def pop(self):
        with self._lock:
            self._helper(None)
            return self._q.pop()
    def _helper(self, x):
        self._q.append(x)  # caller holds the lock at every call site
"""
        assert not race_check_source(src, "helper.py")

    def test_helper_with_one_unlocked_call_site_fires(self):
        src = """
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []
    def push(self, x):
        with self._lock:
            self._helper(x)
    def sneak(self, x):
        self._helper(x)  # entry-held intersection is now empty
    def _helper(self, x):
        self._q.append(x)
"""
        findings = race_check_source(src, "helper2.py")
        assert {f.rule for f in findings} == {"RC001"}

    def test_cross_file_unification(self):
        """supervisor-takes-the-router's-lock: a write to `router.items`
        under `router._lock` in another FILE guards the attribute, and the
        router's own lock-free read is the finding (the PR 11 defect class
        this tool was built to catch)."""
        router_src = """
import threading

class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
    def sweep(self):
        for item in self.items:  # lock-free iteration
            item.probe()
"""
        supervisor_src = """
class Supervisor:
    def __init__(self, router):
        self._router = router
    def grow(self, item):
        router = self._router
        with router._lock:
            router.items.append(item)  # mutates under the router's lock
"""
        findings = race_check_sources(
            {"router.py": router_src, "supervisor.py": supervisor_src}
        )
        assert len(findings) == 1
        f = findings[0]
        assert f.rule == "RC001" and f.path == "router.py"
        assert "Router.items" in f.message and "Router._lock" in f.message

    def test_rc002_class_pair_inversion(self):
        """The router/supervisor shape: two classes each take their own
        lock then the other's — a cycle through receiver unification."""
        src = """
import threading

class Left:
    def __init__(self):
        self._lock = threading.Lock()
        self.right = None
    def poke(self):
        right = self.right
        with self._lock:
            with right._lock:
                pass

class Right:
    def __init__(self):
        self._lock = threading.Lock()
        self.left = None
    def poke(self):
        left = self.left
        with self._lock:
            with left._lock:
                pass
"""
        findings = race_check_source(src, "pair.py")
        assert {f.rule for f in findings} == {"RC002"}

    def test_rc004_notify_without_lock(self):
        src = """
import threading

class P:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []
    def put(self, x):
        with self._lock:
            self._items.append(x)
        self._cv.notify()  # lock released: RuntimeError at run time
    def get(self):
        with self._cv:
            while not self._items:
                self._cv.wait()
            return self._items.pop()
"""
        findings = race_check_source(src, "notify.py")
        assert {f.rule for f in findings} == {"RC004"}

    def test_function_local_locks_do_not_merge_across_functions(self):
        """`a = threading.Lock()` inside two different functions is two
        different (per-call, unshared) locks — opposite nesting across
        them is NOT an inversion (review-caught false positive)."""
        src = """
import threading

def one():
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass

def two():
    a = threading.Lock()
    b = threading.Lock()
    with b:
        with a:
            pass
"""
        assert not race_check_source(src, "locals.py")

    def test_closure_lock_still_tracked_in_nested_scope(self):
        """A function-local lock closed over by a nested handler class (the
        exporter refresh_lock idiom) stays tracked in that scope."""
        src = """
import threading
import time

def serve():
    refresh_lock = threading.Lock()
    class Handler:
        def do_GET(self):
            with refresh_lock:
                time.sleep(1.0)
    return Handler
"""
        findings = race_check_source(src, "closure.py")
        assert [f.rule for f in findings] == ["RC003"]

    def test_rc005_fire_and_forget_non_daemon(self):
        src = """
import threading

def kick(fn):
    threading.Thread(target=fn).start()
"""
        findings = race_check_source(src, "fire.py")
        assert {f.rule for f in findings} == {"RC005"}

    def test_rc005_aliased_fire_and_forget(self):
        """`t = Thread(...); t.start()` — the dominant spelling — fires
        too (review-caught gap), while a thread that escapes (stored on an
        attribute and joined elsewhere, returned, or passed on) does not."""
        fired = race_check_source(
            "import threading\n"
            "def go(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n",
            "alias.py",
        )
        assert [f.rule for f in fired] == ["RC005"]
        stored = race_check_source(
            "import threading\n"
            "class W:\n"
            "    def start(self):\n"
            "        t = threading.Thread(target=self._run)\n"
            "        t.start()\n"
            "        self._t = t\n"
            "    def stop(self):\n"
            "        self._t.join()\n"
            "    def _run(self):\n"
            "        pass\n",
            "stored.py",
        )
        assert not stored
        returned = race_check_source(
            "import threading\n"
            "def make(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    return t\n",
            "returned.py",
        )
        assert not returned

    def test_syntax_error_is_a_finding(self):
        findings = race_check_source("def broken(:\n", "broken.py")
        assert findings and findings[0].rule == "RC000"


class TestSuppression:
    POSITIVE = CORPUS["RC001"][0]

    def test_inline_suppression(self):
        src = self.POSITIVE.replace(
            "self._n = 0  # guarded",
            "self._n = 0  # tpu-lint: ignore[RC001] — reset is single-threaded; guarded",
        )
        assert not race_check_source(src, "sup.py")

    def test_wrong_id_does_not_suppress(self):
        src = self.POSITIVE.replace(
            "self._n = 0  # guarded",
            "self._n = 0  # tpu-lint: ignore[RC002] — guarded",
        )
        assert race_check_source(src, "sup2.py")

    def test_skip_file(self):
        src = "# tpu-lint: skip-file\n" + self.POSITIVE
        assert not race_check_source(src, "skip.py")

    def test_select_ignore(self):
        findings = race_check_source(self.POSITIVE, "sel.py", select={"RC002"})
        assert not findings
        findings = race_check_source(self.POSITIVE, "ign.py", ignore={"RC001"})
        assert not findings

    def test_normalize_rule_ids_rc_family(self):
        assert normalize_rule_ids("rc1,RC006", catalogue=RC_RULES, prefix="RC") == {
            "RC001",
            "RC006",
        }
        with pytest.raises(ValueError):
            normalize_rule_ids("RC099", catalogue=RC_RULES, prefix="RC")


class TestSelfApplication:
    def test_serving_tree_is_race_clean(self):
        """The gate: serving/metrics/diagnostics/commands/analysis pass
        race-check with zero suppression-free findings. This is the test
        that found (and now pins the fixes for) the PR 11 latent defects:
        lock-free iteration of the supervisor-mutated replica list, the
        unlocked supervisor bind seeding, and the teardown kill race."""
        findings, files = race_check_paths(GATED_DIRS)
        assert files > 30
        assert not findings, "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# LockWatch: the runtime half
# ---------------------------------------------------------------------------


class TestLockWatch:
    def setup_method(self):
        self._saved = get_active_lockwatch()

    def teardown_method(self):
        set_active_lockwatch(self._saved)

    def test_two_thread_inversion_detected_deterministically(self, tmp_path):
        """Thread 1 takes A→B; thread 2 (sequenced strictly after via an
        Event — no timing dependence) takes B→A. The second order closes
        the cycle: exactly one violation, RACE_REPORT names both stacks."""
        watch = LockWatch(report_dir=str(tmp_path), host="testhost")
        a = WatchedLock(threading.Lock(), "A", watch)
        b = WatchedLock(threading.Lock(), "B", watch)
        first_done = threading.Event()

        def forward():
            with a:
                with b:
                    pass
            first_done.set()

        def backward():
            first_done.wait(timeout=10)
            with b:
                with a:  # inversion: the A→B edge already exists
                    pass

        t1 = threading.Thread(target=forward, daemon=True)
        t2 = threading.Thread(target=backward, daemon=True)
        t1.start()
        t1.join(timeout=10)
        t2.start()
        t2.join(timeout=10)

        assert watch.violations == 1
        report_path = tmp_path / "RACE_REPORT_testhost.json"
        assert report_path.exists()
        report = json.loads(report_path.read_text())
        assert report["kind"] == "lock_order_inversion"
        assert report["acquiring"] == "A" and report["while_holding"] == "B"
        assert report["cycle"][0] == report["cycle"][-1] or set(
            report["cycle"]
        ) == {"A", "B"}
        # both witnesses are named with stacks
        assert report["witness"]["stack"]
        assert any(
            v.get("stack") for v in report["reverse_order_witnesses"].values()
        )
        assert "A" in report["hold_time_histograms"]

    def test_clean_run_is_silent(self, tmp_path):
        watch = LockWatch(report_dir=str(tmp_path))
        a = WatchedLock(threading.Lock(), "A", watch)
        b = WatchedLock(threading.Lock(), "B", watch)

        def worker():
            for _ in range(50):
                with a:
                    with b:
                        pass

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert watch.violations == 0
        assert not list(tmp_path.glob("RACE_REPORT_*.json"))
        hist = watch.hold_histograms()
        assert hist["A"]["count"] == 200 and hist["B"]["count"] == 200

    def test_condition_over_watched_lock(self):
        """threading.Condition built on a WatchedLock keeps working — the
        router wraps the lock its work-Condition shares."""
        watch = LockWatch()
        lock = WatchedLock(threading.Lock(), "L", watch)
        cv = threading.Condition(lock)
        got = []

        def consumer():
            with cv:
                while not got:
                    cv.wait(timeout=5)

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        time.sleep(0.05)
        with cv:
            got.append(1)
            cv.notify()
        t.join(timeout=10)
        assert not t.is_alive()
        assert watch.violations == 0

    def test_maybe_watch_disabled_returns_raw_lock(self):
        set_active_lockwatch(None)
        raw = threading.Lock()
        assert maybe_watch(raw, "X") is raw
        assert not get_active_lockwatch()
        assert NULL_LOCKWATCH.report() == {}

    def test_maybe_watch_armed_wraps_and_adopts_report_dir(self, tmp_path):
        watch = LockWatch()
        set_active_lockwatch(watch)
        wrapped = maybe_watch(threading.Lock(), "X", report_dir=str(tmp_path))
        assert isinstance(wrapped, WatchedLock)
        assert watch.report_dir == str(tmp_path)

    def test_rlock_reentry_is_not_an_order_fact(self):
        watch = LockWatch()
        r = WatchedLock(threading.RLock(), "R", watch)
        with r:
            with r:  # re-entry: no self-edge, no violation
                pass
        assert watch.violations == 0

    def test_rlock_reentry_below_top_of_stack_not_inversion(self):
        """`with R: with X: with R:` on one thread (R re-entrant) can never
        block — it must not record a spurious X->R edge after R->X was
        observed (review-caught false positive)."""
        watch = LockWatch()
        r = WatchedLock(threading.RLock(), "R", watch)
        x = WatchedLock(threading.Lock(), "X", watch)
        with r:
            with x:
                pass
        with r:
            with x:
                with r:
                    pass
        assert watch.violations == 0


class TestMonitorIntegration:
    def test_collect_status_surfaces_race_report(self, tmp_path):
        from accelerate_tpu.diagnostics.monitor import collect_status, render_status

        report = {
            "kind": "lock_order_inversion",
            "host": 7,
            "acquiring": "Router._lock",
            "while_holding": "ReplicaSupervisor._lock",
            "cycle": ["Router._lock", "ReplicaSupervisor._lock", "Router._lock"],
            "ts": time.time(),
        }
        (tmp_path / "RACE_REPORT_7.json").write_text(json.dumps(report))
        status = collect_status(str(tmp_path))
        assert len(status["race_reports"]) == 1
        assert status["race_reports"][0]["acquiring"] == "Router._lock"
        text = render_status(status)
        assert "RACE" in text and "Router._lock" in text

    def test_monitor_once_exits_2_on_race_report(self, tmp_path):
        (tmp_path / "RACE_REPORT_0.json").write_text(
            json.dumps({"kind": "lock_order_inversion", "host": 0})
        )
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "accelerate_tpu.commands.accelerate_cli",
                "monitor",
                str(tmp_path),
                "--once",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# the real CLI
# ---------------------------------------------------------------------------


def _race_check_cli(*args, cwd=REPO):
    return subprocess.run(
        [
            sys.executable,
            "-m",
            "accelerate_tpu.commands.accelerate_cli",
            "race-check",
            *args,
        ],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=300,
    )


class TestRaceCheckCLI:
    def test_seeded_bad_file_exits_2_naming_the_rule(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(CORPUS["RC002"][0])
        proc = _race_check_cli("--json", str(bad))
        assert proc.returncode == 2, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["errors"] >= 1
        assert any(f["rule"] == "RC002" for f in payload["findings"])
        assert "RC002" in proc.stdout

    def test_clean_and_warning_only_exit_0(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text(CORPUS["RC001"][1])
        assert _race_check_cli(str(clean)).returncode == 0
        warn = tmp_path / "warn.py"
        warn.write_text(CORPUS["RC005"][0])  # RC005 is warning severity
        proc = _race_check_cli(str(warn))
        assert proc.returncode == 0 and "RC005" in proc.stdout

    def test_exit_1_on_missing_path(self):
        assert _race_check_cli("/no/such/path.py").returncode == 1

    def test_select_filters(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(CORPUS["RC003"][0])
        assert _race_check_cli("--select", "RC001", str(bad)).returncode == 0
        assert _race_check_cli("--select", "RC003", str(bad)).returncode == 2

    def test_unknown_rule_id_exit_1(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(CORPUS["RC001"][0])
        assert _race_check_cli("--select", "RC099", str(bad)).returncode == 1

    def test_list_rules(self):
        proc = _race_check_cli("--list-rules")
        assert proc.returncode == 0
        for rule_id in RC_RULES:
            assert rule_id in proc.stdout
