"""DeepSpeed config-file ingestion + questionnaire depth + test_utils
helpers (reference: ds-config `auto` handling ``accelerator.py:1651-1891``,
``cluster.py:54`` questionnaire, ``test_utils/testing.py``)."""

import json
from unittest import mock

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, DeepSpeedPlugin
from accelerate_tpu.commands.config import ClusterConfig, get_cluster_input
from accelerate_tpu.test_utils import (
    DEFAULT_LAUNCH_COMMAND,
    RegressionDataset,
    RegressionModel,
    get_backend,
    get_launch_command,
    require_cpu,
    require_tpu,
)


def _ds_config(tmp_path, **overrides):
    cfg = {
        "train_micro_batch_size_per_gpu": "auto",
        "train_batch_size": "auto",
        "gradient_accumulation_steps": 2,
        "gradient_clipping": 0.7,
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "cpu"},
            "offload_param": {"device": "auto"},
        },
        "optimizer": {"type": "AdamW", "params": {"lr": "auto"}},
    }
    cfg.update(overrides)
    path = tmp_path / "ds_config.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def test_ds_config_file_overrides_plugin_fields(tmp_path):
    plugin = DeepSpeedPlugin(hf_ds_config=_ds_config(tmp_path))
    assert plugin.zero_stage == 3
    assert plugin.gradient_accumulation_steps == 2
    assert plugin.gradient_clipping == 0.7
    assert plugin.offload_optimizer_device == "cpu"
    assert plugin.offload_param_device is None  # "auto" leaves the default
    assert plugin.to_fsdp_plugin().sharding_strategy == "FULL_SHARD"


def test_ds_config_auto_filled_at_prepare(tmp_path):
    plugin = DeepSpeedPlugin(hf_ds_config=_ds_config(tmp_path))
    accelerator = Accelerator(deepspeed_plugin=plugin)

    class _Loader:
        def __init__(self):
            self.dataset = RegressionDataset(length=64)
            self.batch_size = 16
            self.drop_last = False
            self.sampler = self.batch_sampler = self.collate_fn = None

    model = RegressionModel()
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.05)
    accelerator.prepare(model, tx, _Loader())
    cfg = plugin.deepspeed_config
    assert cfg["train_micro_batch_size_per_gpu"] != "auto"
    assert cfg["train_batch_size"] == 16 * plugin.gradient_accumulation_steps
    assert cfg["optimizer"]["params"]["lr"] == pytest.approx(0.05)


def test_questionnaire_deepspeed_branch():
    answers = iter([
        "jax_tpu",  # compute env
        "1",        # hosts
        "1",        # fsdp extent (1 → offer deepspeed)
        "yes",      # use deepspeed?
        "",         # no config file → questionnaire
        "3",        # zero stage
        "yes",      # offload optimizer
        "no",       # offload params
        "4",        # zero shard extent
        "2",        # tp
        "1",        # cp
        "1",        # ep
        "1",        # pp
        "bf16",     # precision
        "1",        # accumulation
        "no",       # debug
        "main",     # main fn
    ])
    with mock.patch("builtins.input", lambda prompt="": next(answers)):
        cfg = get_cluster_input()
    assert cfg.use_deepspeed
    assert cfg.deepspeed_config["zero_stage"] == 3
    assert cfg.deepspeed_config["offload_optimizer_device"] == "cpu"
    assert cfg.mesh_fsdp == 4 and cfg.use_fsdp
    assert cfg.mesh_tp == 2
    env = cfg.to_environment()
    assert env["ACCELERATE_USE_DEEPSPEED"] == "true"
    assert env["ACCELERATE_DEEPSPEED_ZERO_STAGE"] == "3"


def test_questionnaire_fsdp_branch_roundtrips(tmp_path):
    answers = iter([
        "cpu_mesh", "8",        # env + devices
        "1",                    # hosts
        "2",                    # fsdp extent
        "FULL_SHARD", "0", "yes", "no",  # fsdp sub-questionnaire
        "1", "2", "1",          # tp, cp, ep
        "2",                    # pp
        "ulysses",              # cp mode
        "bf16", "2", "yes",     # precision, accum, debug
        "train",                # main fn
    ])
    with mock.patch("builtins.input", lambda prompt="": next(answers)):
        cfg = get_cluster_input()
    assert cfg.fsdp_config["activation_checkpointing"] is True
    assert cfg.context_parallel_mode == "ulysses"
    assert cfg.debug
    path = cfg.save(str(tmp_path / "cfg.yaml"))
    loaded = ClusterConfig.load(path)
    assert loaded.fsdp_config == cfg.fsdp_config
    assert loaded.main_training_function == "train"


def test_questionnaire_fsdp_answers_build_working_accelerator(monkeypatch):
    """Full round trip: fsdp questionnaire answers → ClusterConfig → launch
    env contract → an Accelerator whose FSDP plugin and mesh reflect every
    answer (reference cluster.py:54 sub-questionnaire → env → plugin)."""
    from accelerate_tpu.state import AcceleratorState, GradientState

    answers = iter([
        "jax_tpu",              # compute env
        "1",                    # hosts
        "2",                    # fsdp extent
        "SHARD_GRAD_OP",        # sharding strategy
        "1000",                 # min_num_params
        "yes",                  # activation checkpointing
        "no",                   # offload params
        "1", "1", "1", "1",     # tp, cp, ep, pp
        "bf16", "1", "no", "main",
    ])
    with mock.patch("builtins.input", lambda prompt="": next(answers)):
        cfg = get_cluster_input()
    assert cfg.use_fsdp and cfg.mesh_fsdp == 2
    assert cfg.fsdp_config["offload_params"] is False

    env = cfg.to_environment()
    assert env["ACCELERATE_USE_FSDP"] == "true"
    assert env["FSDP_SHARDING_STRATEGY"] == "SHARD_GRAD_OP"
    assert env["FSDP_MIN_NUM_PARAMS"] == "1000"
    assert env["FSDP_ACTIVATION_CHECKPOINTING"] == "True"
    for k, v in env.items():
        if k.startswith(("FSDP_", "ACCELERATE_")):
            monkeypatch.setenv(k, v)

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator()
    try:
        plugin = acc.fsdp_plugin
        assert plugin is not None
        assert plugin.sharding_strategy == "SHARD_GRAD_OP"
        assert plugin.min_num_params == 1000
        assert plugin.activation_checkpointing is True
        assert plugin.cpu_offload is False
        assert dict(acc.mesh.shape)["fsdp"] == 2
    finally:
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()


def test_questionnaire_deepspeed_answers_build_working_accelerator(monkeypatch):
    """DeepSpeed questionnaire answers reach a working Accelerator: zero
    stage + offload map onto the plugin (→ GSPMD fsdp sharding)."""
    from accelerate_tpu.state import AcceleratorState, GradientState

    answers = iter([
        "jax_tpu",  # compute env
        "1",        # hosts
        "1",        # fsdp extent (1 → offer deepspeed)
        "yes",      # use deepspeed?
        "",         # no config file → questionnaire
        "3",        # zero stage
        "no",       # offload optimizer
        "no",       # offload params
        "2",        # zero shard extent
        "1", "1", "1", "1",  # tp, cp, ep, pp
        "bf16", "1", "no", "main",
    ])
    with mock.patch("builtins.input", lambda prompt="": next(answers)):
        cfg = get_cluster_input()
    for k, v in cfg.to_environment().items():
        if k.startswith(("FSDP_", "ACCELERATE_")):
            monkeypatch.setenv(k, v)

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator()
    try:
        assert acc.deepspeed_plugin is not None
        assert acc.deepspeed_plugin.zero_stage == 3
        assert dict(acc.mesh.shape)["fsdp"] == 2
    finally:
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()


def test_launch_command_builder():
    cmd = get_launch_command(num_cpu_devices=4, mesh_tp=2, debug=True)
    assert "--num_cpu_devices" in cmd and "4" in cmd
    assert "--mesh_tp" in cmd and "2" in cmd
    assert "--debug" in cmd
    assert DEFAULT_LAUNCH_COMMAND[0].endswith("python") or "python" in DEFAULT_LAUNCH_COMMAND[0]


def test_get_backend_and_require_markers():
    platform, count, mem_fn = get_backend()
    assert platform == "cpu" and count == 8
    assert callable(mem_fn)

    @require_cpu
    def runs():
        return True

    assert runs()


@require_tpu
def test_require_tpu_skips_on_cpu():
    raise AssertionError("must be skipped on the CPU mesh")

def test_megatron_plugin_lowers_to_mesh_axes():
    import jax

    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import MegatronLMPlugin

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(megatron_lm_plugin=MegatronLMPlugin(tp_degree=2, sequence_parallelism=True))
    shape = dict(acc.mesh.shape)
    assert shape["tp"] == 2
    # SP does NOT multiply the device requirement (Megatron shards over the
    # existing tp group; here the cp axis is sized explicitly by the user)
    assert shape["cp"] == 1


def test_megatron_sp_shards_residual_activations_on_tp():
    """Under tp>1 + sequence_parallelism=True the norm/residual-region
    activations are sequence-sharded over the tp group (Megatron-SP,
    reference ``utils/dataclasses.py:1916-1919,2112``): residual_spec()
    carries tp on the sequence dim, a compiled forward actually lays the
    constrained activation out that way, and the numerics are unchanged
    vs plain TP."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.models.llama import _constrain, residual_spec
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import MegatronLMPlugin

    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)

    def run(sp: bool):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        acc = Accelerator(
            megatron_lm_plugin=MegatronLMPlugin(tp_degree=2, sequence_parallelism=sp)
        )
        spec = residual_spec()
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0)
        sharded = jax.jit(
            lambda x: jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(acc.mesh, residual_spec())
            )
        )(jnp.zeros((8, 16, 64)))
        prepared = acc.prepare(model)
        logits = np.asarray(prepared(input_ids=ids).logits.force())
        return spec, sharded.sharding, logits

    spec_sp, sharding_sp, logits_sp = run(True)
    assert spec_sp == P(("dp", "fsdp"), ("cp", "tp"), None)
    # the compiled layout really shards the sequence dim over tp
    assert isinstance(sharding_sp, NamedSharding)
    assert sharding_sp.spec[1] in (("cp", "tp"), "tp") or "tp" in tuple(
        np.atleast_1d(sharding_sp.spec[1])
    )
    spec_tp, sharding_tp, logits_tp = run(False)
    assert spec_tp == P(("dp", "fsdp"), "cp", None)
    np.testing.assert_allclose(logits_sp, logits_tp, rtol=2e-5, atol=2e-5)


def test_megatron_pp_maps_to_pipeline_axis():
    """pp_degree lowers onto the pp mesh axis (GPipe schedule) the way
    tp_degree lowers onto tp (reference delegates both to Megatron,
    utils/dataclasses.py:1836)."""
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import MegatronLMPlugin

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(megatron_lm_plugin=MegatronLMPlugin(pp_degree=2, tp_degree=2))
    shape = dict(acc.mesh.shape)
    assert shape["pp"] == 2
    assert shape["tp"] == 2


def test_ring_with_dp_downgrades_without_timeout_flag(monkeypatch):
    """XLA CPU's default 40s collective rendezvous window aborts ring+dp>1
    training programs on few-core hosts; without the extended-timeout flag
    the accelerator must route to the allgather formulation. With the flag
    (which the launcher/conftest set) the real ring runs."""
    import os

    from accelerate_tpu import ContextParallelPlugin, MeshPlugin
    from accelerate_tpu.ops.attention import get_attention_context
    from accelerate_tpu.state import AcceleratorState, GradientState

    import re

    flags = os.environ.get("XLA_FLAGS", "")
    bare = re.sub(
        r"--xla_cpu_collective_call_terminate_timeout_seconds=\d+", "", flags
    )
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    with monkeypatch.context() as m:
        m.setenv("XLA_FLAGS", bare)
        Accelerator(
            mesh_plugin=MeshPlugin(dp=2, fsdp=2, cp=2),
            context_parallel_plugin=ContextParallelPlugin(mode="ring"),
        )
        assert get_attention_context().cp_mode == "allgather"

    # with the flag present: real ring, even dp>1. Set it explicitly (not
    # every jaxlib supports it, so conftest may have left it out — safe to
    # fake here because only the Accelerator's regex reads it; XLA parsed
    # XLA_FLAGS once at backend init, long before this test)
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    with monkeypatch.context() as m:
        m.setenv(
            "XLA_FLAGS",
            (bare + " --xla_cpu_collective_call_terminate_timeout_seconds=600").strip(),
        )
        Accelerator(
            mesh_plugin=MeshPlugin(dp=2, fsdp=2, cp=2),
            context_parallel_plugin=ContextParallelPlugin(mode="ring"),
        )
        assert get_attention_context().cp_mode == "ring"


def test_fsdp_activation_checkpointing_wires_model_remat():
    """FSDP plugin activation_checkpointing flips the model's remat knob at
    prepare (reference wires checkpoint_wrapper, accelerator.py:1523)."""
    import optax

    from accelerate_tpu import FullyShardedDataParallelPlugin, MeshPlugin
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        mesh_plugin=MeshPlugin(dp=4, fsdp=2),
        fsdp_plugin=FullyShardedDataParallelPlugin(activation_checkpointing=True),
    )
    cfg = LlamaConfig.tiny()
    assert cfg.remat is False
    base = LlamaForCausalLM.from_config(cfg, seed=0)
    acc.prepare(base, optax.sgd(0.1))
    # the wiring flips the MODEL's private config copy; the caller's object
    # is untouched (no leak into other models built from the same config)
    assert base.config.remat is True
    assert cfg.remat is False


def test_megatron_ducktyped_plugin_lowers():
    """An upstream-accelerate-style plugin object (degree fields, no
    to_mesh_axes method) still lowers onto the mesh axes."""
    from accelerate_tpu.state import AcceleratorState, GradientState

    class ForeignMegatronPlugin:
        tp_degree = 2
        pp_degree = 2

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(megatron_lm_plugin=ForeignMegatronPlugin())
    shape = dict(acc.mesh.shape)
    assert shape["tp"] == 2 and shape["pp"] == 2


def test_dummy_optim_and_scheduler_from_ds_config(tmp_path):
    """Reference contract: a ds-config file owns optimizer/scheduler; the
    user passes DummyOptim/DummyScheduler to prepare() and gets real ones
    built from the config with "auto" values filled
    (reference utils/deepspeed.py:229-290)."""
    import json as _json

    import numpy as np
    import optax

    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import DummyOptim, DummyScheduler

    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "AdamW", "params": {"lr": "auto", "weight_decay": 0.01}},
        "scheduler": {
            "type": "WarmupDecayLR",
            "params": {
                "warmup_min_lr": 0.0, "warmup_max_lr": "auto",
                "warmup_num_steps": 4, "total_num_steps": 16,
            },
        },
    }
    path = tmp_path / "ds.json"
    path.write_text(_json.dumps(cfg))

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(deepspeed_plugin=DeepSpeedPlugin(hf_ds_config=str(path)))
    model = RegressionModel()
    optimizer = DummyOptim(lr=0.05)
    scheduler = DummyScheduler(total_num_steps=16)
    model, opt, sched = acc.prepare(model, optimizer, scheduler)

    x = np.random.default_rng(0).normal(size=(16, 1)).astype("float32")
    y = 2.0 * x + 1.0
    losses = []
    for _ in range(8):
        out = model(x=x)
        loss = ((out.prediction - y) ** 2).mean()
        acc.backward(loss)
        opt.step()
        sched.step()
        opt.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # the schedule is live AND "auto" warmup_max_lr filled from the
    # optimizer lr: after 8 steps of WarmupDecayLR(warmup=4, total=16,
    # max=0.05) the lr is 0.05 * (1 - 4/12)
    lr = float(opt.param_groups[0]["learning_rate"])
    assert abs(lr - 0.05 * (1 - 4 / 12)) < 1e-6


def test_dummy_optim_without_ds_plugin_raises():
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import DummyOptim

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator()
    with pytest.raises(ValueError, match="DummyOptim"):
        acc.prepare(RegressionModel(), DummyOptim())


def test_multi_plugin_deepspeed_selection(tmp_path):
    """Dict-of-plugins with runtime selection (reference
    ``utils/deepspeed.py:25-41`` + ``state.py:1100-1116``)."""
    from accelerate_tpu.utils import get_active_deepspeed_plugin

    z2 = DeepSpeedPlugin(zero_stage=2)
    z3 = DeepSpeedPlugin(hf_ds_config=_ds_config(tmp_path))
    acc = Accelerator(deepspeed_plugin={"student": z2, "teacher": z3})

    # first plugin is active by default
    assert get_active_deepspeed_plugin(acc.state) is z2
    assert acc.deepspeed_plugin is z2
    assert z2.selected and not z3.selected
    assert acc.state.get_deepspeed_plugin("teacher") is z3

    acc.state.select_deepspeed_plugin("teacher")
    assert acc.deepspeed_plugin is z3
    assert z3.selected and not z2.selected
    assert acc.deepspeed_plugin.zero_stage == 3

    with pytest.raises(KeyError, match="registered"):
        acc.state.select_deepspeed_plugin("nope")
    with pytest.raises(ValueError, match="select_deepspeed_plugin"):
        z2.select()
    with pytest.raises(NotImplementedError):
        z2.selected = True


def test_single_plugin_active_and_empty_dict_rejected():
    from accelerate_tpu.utils import get_active_deepspeed_plugin

    plugin = DeepSpeedPlugin(zero_stage=1)
    acc = Accelerator(deepspeed_plugin=plugin)
    assert get_active_deepspeed_plugin(acc.state) is plugin
    with pytest.raises(ValueError, match="named selection"):
        acc.state.select_deepspeed_plugin("any")

    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    with pytest.raises(ValueError, match="empty"):
        Accelerator(deepspeed_plugin={})


def test_get_active_plugin_without_deepspeed_raises():
    from accelerate_tpu.utils import get_active_deepspeed_plugin

    acc = Accelerator()
    with pytest.raises(ValueError, match="none were enabled"):
        get_active_deepspeed_plugin(acc.state)
