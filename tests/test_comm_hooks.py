"""DDP communication-hook analog: compressed dp-axis gradient reduction
(reference ``DDPCommunicationHookType`` / ``fp16_compress_hook``,
``utils/dataclasses.py:117-214``). Numerics on the 8-CPU mesh + compiled-HLO
proof that the gradient all-reduce rides the compressed wire dtype."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, SimpleLoader as _Loader
from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs


def _train_steps(comm_hook, n_steps=3, split=False):
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    handlers = [DistributedDataParallelKwargs(comm_hook=comm_hook)] if comm_hook else None
    accelerator = Accelerator(kwargs_handlers=handlers)
    model, opt, dl = accelerator.prepare(
        RegressionModel(a=0.0, b=0.0), optax.sgd(0.1),
        _Loader(RegressionDataset(length=64), batch_size=16),
    )
    if comm_hook:
        assert accelerator._grad_comm_hook == comm_hook
    losses = []
    it = iter([])
    for _ in range(n_steps):
        try:
            batch = next(it)
        except StopIteration:
            it = iter(dl)
            batch = next(it)
        out = model(**batch)
        accelerator.backward(out.loss)
        if split:
            assert opt.grads is not None  # forces the split grad path
        opt.step()
        opt.zero_grad()
        losses.append(float(np.asarray(out.loss.force())))
    params = {k: float(np.asarray(v)) for k, v in model.params.items()}
    return params, losses


def test_bf16_comm_hook_matches_full_precision_numerics():
    base_params, base_losses = _train_steps(None)
    hook_params, hook_losses = _train_steps("bf16")
    for k in base_params:
        assert hook_params[k] == pytest.approx(base_params[k], rel=2e-2, abs=2e-2)
    assert hook_losses[0] == pytest.approx(base_losses[0], rel=2e-2)


def test_bf16_comm_hook_split_path_matches():
    base_params, _ = _train_steps(None, split=True)
    hook_params, _ = _train_steps("bf16", split=True)
    for k in base_params:
        assert hook_params[k] == pytest.approx(base_params[k], rel=2e-2, abs=2e-2)


def test_unsupported_hook_warns_and_deactivates(caplog):
    import logging

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    with caplog.at_level(logging.WARNING, logger="accelerate_tpu.accelerator"):
        accelerator = Accelerator(
            kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="power_sgd")]
        )
    assert accelerator._grad_comm_hook is None
    assert any("power_sgd" in r.getMessage() for r in caplog.records)


# ---------------------------------------------------------------------------
# wire-format proof: the gradient cross-shard reduction is bf16 on the wire.
# Parsed from the pre-optimization StableHLO — the backend may later promote
# (XLA:CPU rewrites bf16 all-reduce to f32 because its collectives have no
# bf16 kernel; TPU/DCN executes the declared wire dtype, which is where the
# bytes-on-wire claim lives).
# ---------------------------------------------------------------------------

from accelerate_tpu.utils.hlo import stablehlo_allreduce_bytes as _allreduce_bytes


def _mesh_and_batch():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    x = jnp.asarray(np.random.default_rng(0).standard_normal((64, 32)), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    return mesh, x


def _loss_fn(params, frozen, inputs, scale):
    pred = inputs[0] @ params["w"]
    loss = (pred**2).mean() * scale
    return loss, loss


def test_wire_bytes_halved_vs_full_precision_reduction():
    """The compiled program's gradient all-reduce moves bf16 — half the
    bytes of the f32 baseline (the reference hook's exact claim)."""
    from accelerate_tpu.lazy import ddp_compressed_vag

    mesh, x = _mesh_and_batch()
    params = {"w": jnp.ones((32, 32), jnp.float32)}
    one = jnp.float32(1.0)

    vag = ddp_compressed_vag(_loss_fn, mesh, [x], "bf16")
    text = jax.jit(vag).lower(params, [], [x], one).as_text()
    by_dtype = _allreduce_bytes(text)
    assert by_dtype.get("bf16", 0) > 0, f"no bf16 all-reduce found: {by_dtype}"
    # the gradient payload (32*32 leaves) rides bf16, not f32; the only f32
    # all-reduces left are the two scalar loss pmeans
    grad_bytes_bf16 = by_dtype["bf16"]
    assert grad_bytes_bf16 >= 32 * 32 * 2
    assert by_dtype.get("f32", 0) <= 2 * 4
    # vs the full-precision payload: exactly half the gradient bytes
    assert grad_bytes_bf16 * 2 == 32 * 32 * 4

    vag_f16 = ddp_compressed_vag(_loss_fn, mesh, [x], "fp16")  # fp16 wire
    text_fp16 = jax.jit(vag_f16).lower(params, [], [x], one).as_text()
    assert _allreduce_bytes(text_fp16).get("f16", 0) > 0


def test_compressed_vag_grad_values_match_plain():
    """shard_map + compressed psum computes the same averaged gradient as
    plain GSPMD value_and_grad (bf16 wire tolerance)."""
    from accelerate_tpu.lazy import ddp_compressed_vag

    mesh, x = _mesh_and_batch()
    params = {"w": jnp.asarray(np.random.default_rng(1).standard_normal((32, 32)), jnp.float32)}
    one = jnp.float32(1.0)

    vag = ddp_compressed_vag(_loss_fn, mesh, [x], "bf16")
    (scaled, unscaled), grads = jax.jit(vag)(params, [], [x], one)

    plain = jax.value_and_grad(lambda p: _loss_fn(p, [], [x], one)[0])
    ref_loss, ref_grads = jax.jit(plain)(params)

    np.testing.assert_allclose(np.asarray(unscaled), np.asarray(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads["w"]), np.asarray(ref_grads["w"]), rtol=2e-2, atol=2e-2
    )
