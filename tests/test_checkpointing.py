"""Checkpoint round-trip (reference analog: ``tests/test_state_checkpointing.py``
— resume must reproduce identical training trajectories)."""

import os

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ProjectConfiguration
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


class _Loader:
    def __init__(self, dataset, batch_size):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = False
        self.sampler = None
        self.batch_sampler = None
        self.collate_fn = None


def _fresh_accelerator(**kwargs):
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    return Accelerator(**kwargs)


def _train_steps(accelerator, model, opt, dl, n):
    it = iter(dl)
    for _ in range(n):
        batch = next(it)
        out = model(**batch)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
    return float(out.loss.item())


def test_save_load_state_roundtrip(tmp_path):
    accelerator = _fresh_accelerator()
    model, opt, dl = accelerator.prepare(
        RegressionModel(), optax.adam(0.05), _Loader(RegressionDataset(length=64), 16)
    )
    _train_steps(accelerator, model, opt, dl, 3)
    params_before = {k: np.asarray(v) for k, v in model.params.items()}

    ckpt = accelerator.save_state(str(tmp_path / "ckpt"))
    assert os.path.isdir(ckpt)

    # keep training, then restore — params and optimizer state must match
    _train_steps(accelerator, model, opt, dl, 3)
    assert not np.allclose(np.asarray(model.params["a"]), params_before["a"])
    accelerator.load_state(str(tmp_path / "ckpt"))
    for k in params_before:
        np.testing.assert_array_equal(np.asarray(model.params[k]), params_before[k])


def test_resume_training_trajectory_identical(tmp_path):
    """Train 6 steps straight vs save@3 → restore → 3 more: same params."""

    def build():
        accelerator = _fresh_accelerator()
        return accelerator, *accelerator.prepare(
            RegressionModel(), optax.adam(0.05), _Loader(RegressionDataset(length=96), 16)
        )

    acc1, m1, o1, d1 = build()
    _train_steps(acc1, m1, o1, d1, 6)
    straight = {k: np.asarray(v) for k, v in m1.params.items()}

    acc2, m2, o2, d2 = build()
    _train_steps(acc2, m2, o2, d2, 3)
    acc2.save_state(str(tmp_path / "mid"))

    acc3, m3, o3, d3 = build()
    acc3.load_state(str(tmp_path / "mid"))
    d3 = acc3.skip_first_batches(d3, 3)  # the documented resume idiom
    _train_steps(acc3, m3, o3, d3, 3)
    resumed = {k: np.asarray(v) for k, v in m3.params.items()}
    for k in straight:
        np.testing.assert_allclose(resumed[k], straight[k], rtol=1e-6)


def test_automatic_checkpoint_rotation(tmp_path):
    config = ProjectConfiguration(
        project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=2
    )
    accelerator = _fresh_accelerator(project_config=config)
    model, opt, dl = accelerator.prepare(
        RegressionModel(), optax.adam(0.05), _Loader(RegressionDataset(length=32), 16)
    )
    _train_steps(accelerator, model, opt, dl, 1)
    for _ in range(4):
        accelerator.save_state()
    checkpoints = sorted(os.listdir(tmp_path / "checkpoints"))
    assert checkpoints == ["checkpoint_2", "checkpoint_3"]


def test_register_for_checkpointing_custom_object(tmp_path):
    class Counter:
        def __init__(self):
            self.n = 0

        def state_dict(self):
            return {"n": self.n}

        def load_state_dict(self, sd):
            self.n = sd["n"]

    accelerator = _fresh_accelerator()
    model, opt, dl = accelerator.prepare(
        RegressionModel(), optax.adam(0.05), _Loader(RegressionDataset(length=32), 16)
    )
    counter = Counter()
    accelerator.register_for_checkpointing(counter)
    counter.n = 7
    _train_steps(accelerator, model, opt, dl, 1)
    accelerator.save_state(str(tmp_path / "c"))
    counter.n = 0
    accelerator.load_state(str(tmp_path / "c"))
    assert counter.n == 7


def test_save_model_weights(tmp_path):
    accelerator = _fresh_accelerator()
    model = accelerator.prepare(RegressionModel(a=5, b=6))
    accelerator.save_model(model, str(tmp_path / "m"))
    files = os.listdir(tmp_path / "m")
    assert any(f.startswith("model") for f in files)
    from accelerate_tpu.checkpointing import load_array_dict

    flat = load_array_dict(str(tmp_path / "m" / "model"))
    assert float(flat["a"]) == 5.0


def test_async_save_roundtrip(tmp_path):
    """async_save returns before files land; the next load joins the
    writer (orbax-style contract — SURVEY §3.6 'sharded async checkpoint')."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.checkpointing import wait_for_checkpoint
    from accelerate_tpu.test_utils import RegressionModel

    accelerator = Accelerator()
    model, opt = accelerator.prepare(RegressionModel(a=1.5, b=-0.5), optax.sgd(0.1))
    out = accelerator.save_state(str(tmp_path / "ckpt"), async_save=True)
    wait_for_checkpoint()
    assert (tmp_path / "ckpt" / "accelerator_state.json").exists()

    # mutate, save async again, then load WITHOUT waiting — load must join
    model.params = {"a": model.params["a"] * 0 + 9.0, "b": model.params["b"]}
    accelerator.save_state(str(tmp_path / "ckpt2"), async_save=True)
    accelerator.load_state(str(tmp_path / "ckpt2"))
    assert float(np.asarray(model.params["a"])) == 9.0

    accelerator.load_state(str(tmp_path / "ckpt"))
    assert float(np.asarray(model.params["a"])) == 1.5


def test_async_save_snapshots_state_at_call_time(tmp_path):
    """Values mutated right after an async save must NOT leak into the
    files (the writer sees a snapshot)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.checkpointing import wait_for_checkpoint
    from accelerate_tpu.test_utils import RegressionModel

    accelerator = Accelerator()
    model, opt = accelerator.prepare(RegressionModel(a=3.0, b=0.0), optax.sgd(0.1))
    accelerator.step = 7
    accelerator.save_state(str(tmp_path / "snap"), async_save=True)
    accelerator.step = 999  # training races ahead
    wait_for_checkpoint()
    import json

    meta = json.loads((tmp_path / "snap" / "accelerator_state.json").read_text())
    assert meta["step"] == 7
