"""MoE / expert parallelism (SURVEY §2.2 EP row; reference hook is only
DeepSpeed-MoE leaf marking, ``utils/dataclasses.py:1060-1066`` — the model
family itself is capability this build adds)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshPlugin
from accelerate_tpu.models.mixtral import (
    MixtralConfig,
    MixtralForCausalLM,
    init_mixtral_params,
    moe_ffn,
)
from accelerate_tpu.state import AcceleratorState, GradientState

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _layer0(config, seed=0):
    params = init_mixtral_params(jax.random.key(seed), config)
    return jax.tree.map(lambda l: l[0], params["layers"])


def _naive_moe(config, layer, x):
    """Oracle: every token through its top-k experts, computed directly."""
    c = config
    b, s, h = x.shape
    tokens = np.asarray(x).reshape(-1, h)
    logits = tokens @ np.asarray(layer["router"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    k = c.num_experts_per_tok
    out = np.zeros_like(tokens)
    for t in range(tokens.shape[0]):
        idx = np.argsort(-probs[t])[:k]
        w = probs[t][idx] / probs[t][idx].sum()
        for e, wi in zip(idx, w):
            g = np.asarray(tokens[t] @ np.asarray(layer["e_gate"][e]))
            u = np.asarray(tokens[t] @ np.asarray(layer["e_up"][e]))
            silu = g / (1 + np.exp(-g)) * u
            out[t] += wi * (silu @ np.asarray(layer["e_down"][e]))
    return out.reshape(b, s, h)


def test_moe_ffn_matches_naive_dense_oracle():
    config = MixtralConfig.tiny(hidden_size=32, experts=4, top_k=2)
    config.capacity_factor = float(config.num_local_experts)  # no token drops
    layer = _layer0(config)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 32)), jnp.float32)
    y, aux = jax.jit(lambda l, x: moe_ffn(config, l, x))(layer, x)
    ref = _naive_moe(config, layer, x)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow_tokens():
    """With capacity < tokens, overflowing tokens contribute zero output —
    the documented Switch/GShard drop semantics, not an error."""
    config = MixtralConfig.tiny(hidden_size=32, experts=2, top_k=1)
    config.capacity_factor = 0.25
    layer = _layer0(config)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 16, 32)), jnp.float32)
    y, _ = jax.jit(lambda l, x: moe_ffn(config, l, x))(layer, x)
    # some tokens dropped → some rows exactly zero
    rows = np.asarray(y).reshape(-1, 32)
    assert np.any(np.all(rows == 0, axis=1))
    assert not np.all(rows == 0)


def test_mixtral_forward_and_loss():
    config = MixtralConfig.tiny()
    model = MixtralForCausalLM.from_config(config, seed=0)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    out = model.apply_fn(model.params, input_ids=ids, labels=ids)
    assert out["logits"].shape == (2, 16, 256)
    assert np.isfinite(float(out["loss"]))
    assert float(out["aux_loss"]) > 0.5  # ~1.0 for a uniform router


def test_expert_parallel_training_matches_single_device():
    """ep=4 sharded loss == unsharded loss, for several steps of training."""
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(8, 16)).astype(np.int32)

    def run(mesh_kwargs, n_dev):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        acc = Accelerator(
            mesh_plugin=MeshPlugin(devices=jax.devices()[:n_dev], **mesh_kwargs)
        )
        config = MixtralConfig.tiny(experts=4, top_k=2)
        config.capacity_factor = float(config.num_local_experts)
        model = MixtralForCausalLM.from_config(config, seed=0)
        model, opt = acc.prepare(model, optax.adamw(1e-2))
        losses = []
        for _ in range(3):
            out = model(input_ids=ids, labels=ids)
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()
            losses.append(out.loss.item())
        return losses

    dense = run({"dp": 1}, 1)
    ep = run({"dp": 1, "ep": 4}, 4)
    np.testing.assert_allclose(ep, dense, rtol=2e-4)
    ep_mixed = run({"dp": 2, "ep": 2, "tp": 2}, 8)
    np.testing.assert_allclose(ep_mixed, dense, rtol=2e-4)


def test_mixtral_in_zoo():
    from accelerate_tpu.models import MODEL_ZOO

    assert "mixtral-8x7b" in MODEL_ZOO
