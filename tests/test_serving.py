"""Continuous-batching serving engine (``accelerate_tpu/serving/``).

Host-side scheduling/accounting tests run in the tier-1 lane (no compiles);
engine end-to-end tests (token parity, chunked prefill, compile counting)
are compile-heavy and ride the slow lane like the generation suite.
"""

import numpy as np
import pytest

from accelerate_tpu.serving import (
    BlockAllocator,
    EngineConfig,
    InferenceEngine,
    Request,
    RequestState,
    SlotScheduler,
    blocks_needed,
)

# ---------------------------------------------------------------------------
# block freelist accounting (tier-1: pure host)
# ---------------------------------------------------------------------------


def test_allocator_accounting_no_leak():
    alloc = BlockAllocator(num_blocks=9)  # 8 usable + null
    assert alloc.free_count == 8
    a = alloc.allocate(3)
    b = alloc.allocate(5)
    assert alloc.free_count == 0 and alloc.allocated_count == 8
    assert not alloc.can_allocate(1)
    alloc.free(a)
    alloc.free(b)
    assert alloc.free_count == 8 and alloc.allocated_count == 0
    assert 0 not in a + b  # the null block is never handed out


def test_allocator_double_free_raises():
    alloc = BlockAllocator(num_blocks=4)
    blocks = alloc.allocate(2)
    alloc.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        alloc.free(blocks)


def test_allocator_rejects_null_and_overdraft():
    alloc = BlockAllocator(num_blocks=4)
    with pytest.raises(ValueError, match="null block"):
        alloc.free([0])
    with pytest.raises(RuntimeError, match="out of KV blocks"):
        alloc.allocate(4)  # only 3 usable


def test_blocks_needed():
    assert blocks_needed(0, 8) == 0
    assert blocks_needed(1, 8) == 1
    assert blocks_needed(8, 8) == 1
    assert blocks_needed(9, 8) == 2


# ---------------------------------------------------------------------------
# scheduler admission / eviction (tier-1: pure host)
# ---------------------------------------------------------------------------


def _sched(num_slots=2, num_blocks=9, block_size=8, max_seq=32):
    return SlotScheduler(num_slots, BlockAllocator(num_blocks), block_size, max_seq)


def test_scheduler_fcfs_admission_and_eviction():
    sched = _sched()
    reqs = [sched.submit(Request(prompt=[1] * 4, max_new_tokens=4)) for _ in range(3)]
    admitted = sched.admit()
    assert [r.request_id for r in admitted] == [r.request_id for r in reqs[:2]]
    assert sched.queue_depth == 1 and sched.occupancy == 1.0
    assert all(r.state is RequestState.PREFILL and r.blocks for r in admitted)

    # finishing slot 0 frees its blocks and opens the slot for request 3
    admitted[0].state = RequestState.FINISHED
    freed_blocks = list(admitted[0].blocks)
    evicted = sched.evict_finished()
    assert evicted == [reqs[0]] and admitted[0].blocks == []
    assert sched.allocator.can_allocate(len(freed_blocks))
    third = sched.admit()
    assert third == [reqs[2]] and reqs[2].slot == 0


def test_scheduler_admission_bounded_by_freelist():
    # 4 usable blocks; each request's prompt (9 tokens) + first decode
    # block needs ceil(10/8)=2 blocks → only two admissions fit the pool
    sched = _sched(num_slots=3, num_blocks=5, block_size=8, max_seq=32)
    for _ in range(3):
        sched.submit(Request(prompt=[1] * 9, max_new_tokens=4))
    admitted = sched.admit()
    assert len(admitted) == 2
    assert sched.queue_depth == 1  # head-of-line blocked on blocks, not slots


def test_scheduler_rejects_over_budget_request():
    sched = _sched(max_seq=16)
    with pytest.raises(ValueError, match="max_seq_len"):
        sched.submit(Request(prompt=[1] * 10, max_new_tokens=10))
    with pytest.raises(ValueError, match="empty prompt"):
        sched.submit(Request(prompt=[], max_new_tokens=2))


def test_scheduler_rejects_unadmittable_prompt():
    """A prompt whose admission footprint exceeds the whole pool must be
    rejected at submit() — queued forever, it would head-of-line block
    admit() and spin run_until_idle() for good."""
    sched = _sched(num_slots=2, num_blocks=4, block_size=8, max_seq=64)  # 3 usable
    with pytest.raises(ValueError, match="KV blocks"):
        sched.submit(Request(prompt=[1] * 40, max_new_tokens=4))


def test_grow_for_decode_capped_at_request_budget():
    """Burst lookahead must not demand blocks past the request's own
    prompt+max_new: under pool pressure that would truncate requests whose
    real remaining tokens already fit (review finding)."""
    sched = _sched(num_slots=1, num_blocks=3, block_size=8, max_seq=64)  # 2 usable
    req = sched.submit(Request(prompt=[1] * 8, max_new_tokens=4))
    (admitted,) = sched.admit()
    assert len(admitted.blocks) == 2
    req.prefill_pos = 8
    req.output_tokens = [1] * 3  # context 10, one token of budget left
    # a burst of 8 would reach position 18 (3 blocks) — but the budget ends
    # at 12, which the 2 allocated blocks already cover
    assert sched.grow_for_decode(req, tokens_ahead=8)
    assert len(req.blocks) == 2


def test_grow_for_decode_allocates_incrementally():
    sched = _sched(num_slots=1, num_blocks=9, block_size=8, max_seq=64)
    req = sched.submit(Request(prompt=[1] * 8, max_new_tokens=24))
    (admitted,) = sched.admit()
    assert len(admitted.blocks) == 2  # prompt block + first decode block
    req.prefill_pos = 8
    req.output_tokens = [1] * 9  # context 16 → next write crosses a boundary
    assert sched.grow_for_decode(req, tokens_ahead=1)
    assert len(req.blocks) == 3
    # a burst lookahead allocates the whole span it will write
    assert sched.grow_for_decode(req, tokens_ahead=16)
    assert len(req.blocks) == blocks_needed(16 + 16, 8)


# ---------------------------------------------------------------------------
# mesh sharding policy (tier-1: pure placement decisions, no compiles)
# ---------------------------------------------------------------------------


def _mesh4():
    import jax

    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs a >= 4-device (virtual) mesh")
    return build_mesh(MeshPlugin(dp=1, fsdp=2, tp=2), devices=devices[:4])


def test_paged_kv_sharding_policy():
    """The pool shards its kv-head dim over tp (K/V are produced tp-sharded
    by wk/wv) and falls back to replicated when tp doesn't divide."""
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.parallel.sharding import paged_kv_sharding

    mesh = _mesh4()
    assert paged_kv_sharding(mesh, num_kv_heads=4).spec == P(
        None, None, None, "tp", None
    )
    assert paged_kv_sharding(mesh, num_kv_heads=3).spec == P()


# ---------------------------------------------------------------------------
# sharded-engine parity (the acceptance bar: mesh decode == single device)
# ---------------------------------------------------------------------------


def test_sharded_engine_matches_single_device(tiny_model):
    """Token-identical greedy output between the mesh-sharded engine
    (fsdp=2 x tp=2 over 4 virtual CPU devices) and the single-device
    engine, with the one-compiled-decode-executable contract still holding
    under GSPMD and zero leaked blocks."""
    mesh = _mesh4()
    geometry = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8,
                    decode_burst=2)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (5, 12, 9)]
    budgets = [4, 7, 5]

    def run(mesh_arg):
        engine = InferenceEngine(tiny_model, EngineConfig(**geometry), mesh=mesh_arg)
        reqs = [engine.add_request(p, b) for p, b in zip(prompts, budgets)]
        engine.run_until_idle(max_iterations=5000)
        return engine, [list(r.output_tokens) for r in reqs]

    single_engine, single_tokens = run(None)
    sharded_engine, sharded_tokens = run(mesh)
    assert sharded_tokens == single_tokens
    stats = sharded_engine.stats()
    assert stats["decode_compiles"] == 1  # sharding never broke the contract
    assert stats["prefill_compiles"] == 1
    assert stats["allocated_blocks"] == 0
    assert stats["mesh"] == {"fsdp": 2, "tp": 2}
    assert single_engine.stats()["decode_compiles"] == 1
    # the pool really is distributed: each device holds 1/tp of the kv heads
    shard_shapes = {s.data.shape for s in sharded_engine._kp.addressable_shards}
    full = sharded_engine._kp.shape
    assert shard_shapes == {(*full[:3], full[3] // 2, full[4])}


# ---------------------------------------------------------------------------
# engine end-to-end (slow lane: compiles the tiny model)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


@pytest.mark.slow
@pytest.mark.parametrize("decode_burst", [1, 4])
def test_continuous_matches_static_greedy(tiny_model, decode_burst):
    """Token-for-token parity with generate(use_cache=True) for a mixed-
    length multi-request trace, across burst granularities, with exactly
    one decode executable and zero leaked blocks."""
    from accelerate_tpu.generation import generate

    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=3, block_size=8, max_seq_len=64,
                     prefill_chunk=8, decode_burst=decode_burst),
    )
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (5, 11, 17, 3, 9)]
    reqs = [engine.add_request(p, max_new_tokens=3 + 4 * i) for i, p in enumerate(prompts)]
    done = engine.run_until_idle(max_iterations=5000)
    assert len(done) == len(reqs)
    for p, r in zip(prompts, reqs):
        ref = np.asarray(
            generate(tiny_model, p[None, :], max_new_tokens=r.max_new_tokens, use_cache=True)
        )[0]
        got = np.concatenate([p, np.asarray(r.output_tokens, np.int32)])
        np.testing.assert_array_equal(got, ref)
    stats = engine.stats()
    assert stats["decode_compiles"] == 1
    assert stats["allocated_blocks"] == 0
    # the radix cache (on by default) retains finished prompts' full
    # blocks; free + cached must still account for every usable block
    assert (
        stats["free_blocks"] + stats["cached_blocks"]
        == engine.allocator.num_blocks - 1
    )


@pytest.mark.slow
def test_compile_count_one_decode_executable_multi_wave(tiny_model):
    """Admission waves with different prompt/output geometry must reuse the
    same decode executable — the engine's core contract."""
    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8),
    )
    rng = np.random.default_rng(1)
    for wave in ((4, 2), (13, 9), (21, 5), (7, 17)):
        plen, new = wave
        engine.add_request(rng.integers(0, 64, size=plen).astype(np.int32), new)
        engine.run_until_idle(max_iterations=5000)
    stats = engine.stats()
    assert stats["decode_compiles"] == 1
    assert stats["prefill_compiles"] == 1
    assert stats["completed"] == 4


@pytest.mark.slow
def test_chunked_prefill_matches_one_shot_logits(tiny_model):
    """Prefilling a prompt in chunks through the paged path yields the same
    last-token logits as the dense one-shot prefill (decode correctness
    then follows from the shared cached_attention)."""
    import jax.numpy as jnp

    model = tiny_model
    cfg = model.config
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 64, size=(1, 13)).astype(np.int32)

    dense = model.apply_fn(model.params, input_ids=ids, use_cache=True, max_cache_len=16)
    ref = np.asarray(dense["logits"][:, -1, :])

    bs, nb, mb = 8, 6, 4
    shape = (cfg.num_hidden_layers, nb, bs, cfg.num_key_value_heads, cfg.head_dim)
    pages = {"k": jnp.zeros(shape), "v": jnp.zeros(shape)}
    bt = np.zeros((1, mb), np.int32)
    bt[0, :2] = [1, 2]
    chunked = None
    for start in range(0, 16, 8):  # two chunks of 8 (last padded by 3)
        end = min(start + 8, 13)
        if start >= 13:
            break
        chunk = np.zeros((1, 8), np.int32)
        chunk[0, : end - start] = ids[0, start:end]
        mask = np.zeros((1, 8), bool)
        mask[0, : end - start] = True
        out = model.apply_fn(
            model.params, input_ids=chunk, paged_kv=pages, block_tables=bt,
            cache_positions=np.asarray([start], np.int32), paged_write_mask=mask,
        )
        pages = out["paged_kv"]
        chunked = np.asarray(out["logits"][0, (13 - 1) - start, :])[None] if end == 13 else chunked
    np.testing.assert_allclose(chunked, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_eos_finishes_early_and_matches_generate(tiny_model):
    from accelerate_tpu.generation import generate

    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 64, size=9).astype(np.int32)
    # pick the 3rd greedy token as the eos so the engine must stop early
    ref_free = np.asarray(generate(tiny_model, prompt[None, :], max_new_tokens=8, use_cache=True))[0]
    eos = int(ref_free[len(prompt) + 2])
    ref = np.asarray(
        generate(tiny_model, prompt[None, :], max_new_tokens=8, use_cache=True, eos_token_id=eos)
    )[0]

    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                     eos_token_id=eos),
    )
    req = engine.add_request(prompt, max_new_tokens=8)
    engine.run_until_idle(max_iterations=5000)
    assert req.finish_reason == "eos"
    got = np.concatenate([prompt, np.asarray(req.output_tokens, np.int32)])
    np.testing.assert_array_equal(got, ref[: len(got)])
    assert req.output_tokens[-1] == eos and len(req.output_tokens) < 8


@pytest.mark.slow
def test_pool_exhaustion_truncates_not_deadlocks(tiny_model):
    """A drained freelist force-finishes the victim with
    finish_reason="out_of_blocks" instead of stalling the engine."""
    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                     num_blocks=4),  # 3 usable blocks for 2 slots
    )
    r1 = engine.add_request(np.arange(8, dtype=np.int32), max_new_tokens=30)
    r2 = engine.add_request(np.arange(8, dtype=np.int32) + 1, max_new_tokens=30)
    done = engine.run_until_idle(max_iterations=5000)
    assert len(done) == 2
    reasons = {r.finish_reason for r in (r1, r2)}
    assert "out_of_blocks" in reasons
    assert engine.stats()["allocated_blocks"] == 0  # truncation still frees


@pytest.mark.slow
def test_stream_yields_tokens_incrementally(tiny_model):
    engine = InferenceEngine(
        tiny_model,
        EngineConfig(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                     decode_burst=2),
    )
    prompt = np.arange(6, dtype=np.int32)
    toks = list(engine.stream(prompt, max_new_tokens=7))
    assert len(toks) == 7
    from accelerate_tpu.generation import generate

    ref = np.asarray(generate(tiny_model, prompt[None, :], max_new_tokens=7, use_cache=True))[0]
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref[6:])


@pytest.mark.slow
def test_requires_paged_kv_flag():
    from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    model = GPT2LMHeadModel.from_config(GPT2Config.tiny(layers=2, seq=64), seed=0)
    with pytest.raises(ValueError, match="supports_paged_kv"):
        InferenceEngine(model, EngineConfig(num_slots=2, max_seq_len=64))


@pytest.mark.slow
def test_serving_telemetry_rows_and_monitor(tiny_model, tmp_path):
    """The engine's telemetry rows land in the JSONL trail and surface in
    the monitor snapshot/rendering (serving health end-to-end)."""
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status
    from accelerate_tpu.telemetry import TelemetryRecorder, set_active_recorder

    recorder = TelemetryRecorder(logging_dir=str(tmp_path))
    set_active_recorder(recorder)
    try:
        engine = InferenceEngine(
            tiny_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         prefill_chunk=8, stats_interval=2),
        )
        rng = np.random.default_rng(4)
        for i in range(3):
            engine.add_request(rng.integers(0, 64, size=5 + i).astype(np.int32), 4)
        engine.run_until_idle(max_iterations=5000)
    finally:
        set_active_recorder(None)
        recorder.close()

    kinds = [r.get("kind") for r in recorder.records if r.get("type") == "serving"]
    assert "request" in kinds and "step" in kinds
    req_rows = [
        r for r in recorder.records
        if r.get("type") == "serving" and r.get("kind") == "request"
    ]
    assert len(req_rows) == 3
    assert all(r["ttft_s"] is not None and r["new_tokens"] == 4 for r in req_rows)

    status = collect_status(str(tmp_path))
    assert status["serving"] is not None
    assert status["serving"]["completed"] == 3
    assert status["serving"]["decode_compiles"] == 1
    assert "serving:" in render_status(status)


# ---------------------------------------------------------------------------
# quantized KV cache: the kv_dtype parity matrix
# {bf16, int8, fp8} x {dense-equivalence, prefix-hit/CoW, swap round-trip,
# sharded mesh}. Tolerances here are THE documented numbers
# (docs/source/usage_guides/serving.md); within one engine a kv_dtype is
# deterministic, so the sharing/swap/mesh legs assert token-identity.
# ---------------------------------------------------------------------------

#: |paged last-token logits - dense decode logits| ceiling per kv_dtype on
#: the tiny f32 model (storage rounding only — same attention math)
KV_LOGIT_ATOL = {"bf16": 0.06, "int8": 0.12, "fp8": 0.35}

KV_DTYPES = ("bf16", "int8", "fp8")


def _skip_without_fp8(kv_dtype: str) -> None:
    """fp8 is a documented graceful-degradation path (the engine raises a
    guidance error where f8 casts don't lower) — skip its legs there."""
    if kv_dtype == "fp8":
        from accelerate_tpu.utils.compat import has_fp8_storage

        if not has_fp8_storage():
            pytest.skip("float8_e4m3fn storage unsupported on this jax stack")


def test_engine_kv_stats_and_capacity_math(tiny_model):
    """stats() carries the kv_dtype policy rows, and the byte math is the
    documented formula: 2 pools x layers x n_kv x (hd x itemsize + 4-byte
    scale when quantized)."""
    cfg = tiny_model.config
    expect = {
        "auto": 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * cfg.head_dim * 4,
        "bf16": 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * cfg.head_dim * 2,
        "int8": 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * (cfg.head_dim + 4),
    }
    for kv_dtype, bytes_per_token in expect.items():
        eng = InferenceEngine(
            tiny_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         kv_dtype=kv_dtype),
        )
        st = eng.stats()
        assert st["kv_bytes_per_token"] == bytes_per_token
        assert st["kv_bytes_per_block"] == bytes_per_token * 8
        assert st["kv_slot_capacity"] == 2  # full residency: both slots fit
        has_scales = eng._ks is not None
        assert has_scales == (kv_dtype == "int8")
    with pytest.raises(ValueError, match="unknown kv_dtype"):
        InferenceEngine(
            tiny_model, EngineConfig(num_slots=2, max_seq_len=64, kv_dtype="int4")
        )


def test_swap_pool_quantized_scales_byte_exact():
    """A quantized SwapPool round-trips payload AND f32 scale rows
    byte-exactly (a quantized block without its exact scales is garbage),
    and prices both into bytes_per_block."""
    from accelerate_tpu.serving import SwapPool

    shape = (2, 4, 2, 8)  # layers, bs, n_kv, hd
    per_block = 2 * int(np.prod(shape)) + 2 * 4 * int(np.prod(shape[:-1]))
    pool = SwapPool(num_layers=2, block_size=4, num_kv_heads=2, head_dim=8,
                    dtype=np.int8, capacity_gb=2 * per_block / (1 << 30),
                    quantized=True)
    assert pool.bytes_per_block == per_block
    assert pool.capacity_blocks == 2
    rng = np.random.default_rng(0)
    k = rng.integers(-127, 128, size=shape).astype(np.int8)
    v = rng.integers(-127, 128, size=shape).astype(np.int8)
    ks = rng.random(shape[:-1]).astype(np.float32)
    vs = rng.random(shape[:-1]).astype(np.float32)
    h = pool.store(k, v, ks, vs)
    k2, v2, ks2, vs2 = pool.load(h)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)
    np.testing.assert_array_equal(ks, ks2)  # byte-exact, not allclose
    np.testing.assert_array_equal(vs, vs2)
    with pytest.raises(ValueError, match="needs scale rows"):
        pool.store(k, v)


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_kv_dtype_paged_logits_match_dense(tiny_model, kv_dtype):
    """Dense-equivalence leg: chunk-prefilling through a quantized pool
    yields last-token logits within the documented tolerance of the dense
    one-shot prefill (the acceptance bar's logit contract)."""
    _skip_without_fp8(kv_dtype)
    import jax.numpy as jnp

    from accelerate_tpu.ops.fp8 import kv_storage_dtype

    model = tiny_model
    cfg = model.config
    rng = np.random.default_rng(11)
    ids = rng.integers(0, 64, size=(1, 13)).astype(np.int32)
    dense = model.apply_fn(model.params, input_ids=ids, use_cache=True, max_cache_len=16)
    ref = np.asarray(dense["logits"][:, -1, :], np.float32)

    store_dtype, quantized = kv_storage_dtype(kv_dtype)
    bs, nb, mb = 8, 6, 4
    shape = (cfg.num_hidden_layers, nb, bs, cfg.num_key_value_heads, cfg.head_dim)
    pages = {"k": jnp.zeros(shape, store_dtype), "v": jnp.zeros(shape, store_dtype)}
    if quantized:
        pages["k_scale"] = jnp.ones(shape[:-1], jnp.float32)
        pages["v_scale"] = jnp.ones(shape[:-1], jnp.float32)
    bt = np.zeros((1, mb), np.int32)
    bt[0, :2] = [1, 2]
    got = None
    for start in range(0, 16, 8):
        end = min(start + 8, 13)
        if start >= 13:
            break
        chunk = np.zeros((1, 8), np.int32)
        chunk[0, : end - start] = ids[0, start:end]
        mask = np.zeros((1, 8), bool)
        mask[0, : end - start] = True
        out = model.apply_fn(
            model.params, input_ids=chunk, paged_kv=pages, block_tables=bt,
            cache_positions=np.asarray([start], np.int32), paged_write_mask=mask,
        )
        pages = out["paged_kv"]
        if quantized:
            assert "k_scale" in pages and "v_scale" in pages
        if end == 13:
            got = np.asarray(out["logits"][0, (13 - 1) - start, :], np.float32)[None]
    assert np.abs(got - ref).max() < KV_LOGIT_ATOL[kv_dtype]


@pytest.mark.slow
def test_kv_bf16_greedy_token_identical_to_generate():
    """At kv_dtype="bf16" on a bf16 model the engine's greedy output stays
    token-identical to generate(use_cache=True) — bf16 storage is a cast,
    not a quantization, so the PR 4 parity contract survives the fused
    kernel unchanged."""
    import jax.numpy as jnp

    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    model = LlamaForCausalLM.from_config(config, seed=0, dtype=jnp.bfloat16)
    engine = InferenceEngine(
        model,
        EngineConfig(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8,
                     kv_dtype="bf16"),
    )
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (5, 11, 17)]
    reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
    engine.run_until_idle(max_iterations=5000)
    for p, r in zip(prompts, reqs):
        ref = np.asarray(
            generate(model, p[None, :], max_new_tokens=8, use_cache=True)
        )[0]
        np.testing.assert_array_equal(
            np.concatenate([p, np.asarray(r.output_tokens, np.int32)]), ref
        )
    assert engine.stats()["decode_compiles"] == 1


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_kv_dtype_prefix_hit_and_cow_parity(tiny_model, kv_dtype):
    """Prefix-hit/CoW leg: a warm engine serving a shared-prefix prompt
    (full-block hit + partial-block CoW divergence) emits the same tokens
    as a cold engine at the same kv_dtype — adopted quantized blocks and
    CoW copies reuse the exact stored bytes + scales, so within one
    kv_dtype the cache is invisible."""
    _skip_without_fp8(kv_dtype)
    def run(warm):
        eng = InferenceEngine(
            tiny_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         prefill_chunk=8, kv_dtype=kv_dtype, prefix_cache=warm),
        )
        base = np.arange(20, dtype=np.int32) % 60
        r1 = eng.add_request(base, 6)
        eng.run_until_idle(max_iterations=5000)
        # full-block hit (same 16-token prefix) + mid-block divergence
        shared = np.concatenate([base[:19], np.asarray([61], np.int32)])
        r2 = eng.add_request(shared, 6)
        eng.run_until_idle(max_iterations=5000)
        return eng, r1.output_tokens, r2.output_tokens

    warm_eng, w1, w2 = run(True)
    _, c1, c2 = run(False)
    assert (w1, w2) == (c1, c2)
    st = warm_eng.stats()
    assert st["prefix_hit_tokens"] > 0  # the warm leg really hit the cache
    assert st["decode_compiles"] == 1


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_kv_dtype_swap_round_trip_parity(tiny_model, kv_dtype):
    """Swap leg: under pool pressure with the host swap tier on, both
    requests complete un-truncated and token-identical to a
    full-residency run at the same kv_dtype — quantized payload + scale
    rows survived swap-out -> swap-in exactly."""
    _skip_without_fp8(kv_dtype)
    geom = dict(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                prefix_cache=False, kv_dtype=kv_dtype)
    prompts = [np.arange(8, dtype=np.int32), np.arange(8, dtype=np.int32) + 1]

    def run(num_blocks=None, swap_gb=0.0):
        eng = InferenceEngine(
            tiny_model, EngineConfig(num_blocks=num_blocks, swap_gb=swap_gb, **geom)
        )
        reqs = [eng.add_request(p, max_new_tokens=30) for p in prompts]
        eng.run_until_idle(max_iterations=5000)
        return eng.stats(), reqs

    swap_stats, swapped = run(num_blocks=6, swap_gb=0.01)
    assert [r.finish_reason for r in swapped] == ["length", "length"]
    assert swap_stats["preemptions"] >= 1
    assert swap_stats["swapped_out_blocks"] == swap_stats["swapped_in_blocks"] > 0
    assert swap_stats["decode_compiles"] == 1
    _, full = run()
    for s, f in zip(swapped, full):
        assert s.output_tokens == f.output_tokens


@pytest.mark.slow
def test_kv_int8_sharded_mesh_parity(tiny_model):
    """Sharded-mesh leg: the int8 engine over fsdp=2 x tp=2 is
    token-identical to the single-device int8 engine, the scale arrays
    shard their kv-head dim alongside the pools, and the
    one-decode-executable contract holds."""
    mesh = _mesh4()
    geometry = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8,
                    decode_burst=2, kv_dtype="int8")
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (5, 12, 9)]

    def run(mesh_arg):
        engine = InferenceEngine(tiny_model, EngineConfig(**geometry), mesh=mesh_arg)
        reqs = [engine.add_request(p, b) for p, b in zip(prompts, (4, 7, 5))]
        engine.run_until_idle(max_iterations=5000)
        return engine, [list(r.output_tokens) for r in reqs]

    _, single_tokens = run(None)
    sharded, sharded_tokens = run(mesh)
    assert sharded_tokens == single_tokens
    assert sharded.stats()["decode_compiles"] == 1
    full = sharded._ks.shape
    shard_shapes = {s.data.shape for s in sharded._ks.addressable_shards}
    assert shard_shapes == {(*full[:3], full[3] // 2)}
