"""Fault-tolerance subsystem: manifest validation, atomic commit,
preemption handling, auto-resume, IO retries (``accelerate_tpu/resilience``).

The committed-checkpoint invariant under test throughout: a checkpoint
directory either exists completely (manifest validates) or not at all
(only ever a ``.tmp`` that discovery ignores) — a SIGKILL mid-save must
never produce a loadable-looking partial directory.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import random
import signal
import subprocess
import sys
import textwrap

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, FaultTolerancePlugin, ProjectConfiguration
from accelerate_tpu.checkpointing import _ASYNC_SAVE, _rotate_checkpoints, _sorted_checkpoints
from accelerate_tpu.resilience.manifest import (
    SENTINEL_NAME,
    build_manifest,
    find_latest_valid_checkpoint,
    validate_checkpoint,
    write_manifest,
)
from accelerate_tpu.resilience.preemption import PreemptionHandler, get_active_handler
from accelerate_tpu.resilience.retry import run_with_retries
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Loader:
    def __init__(self, dataset, batch_size):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = False
        self.sampler = None
        self.batch_sampler = None
        self.collate_fn = None


# ---------------------------------------------------------------------------
# satellite: _sorted_checkpoints robustness
# ---------------------------------------------------------------------------


def test_sorted_checkpoints_skips_non_numeric_entries(tmp_path):
    """A leftover ``checkpoint_12.tmp`` from an interrupted save (or any
    stray ``checkpoint_*`` name) must be skipped, not ``int()``-ed into a
    ValueError."""
    for name in ("checkpoint_3", "checkpoint_12.tmp", "checkpoint_abc",
                 "checkpoint_1", "checkpoint_"):
        (tmp_path / name).mkdir()
    result = _sorted_checkpoints(str(tmp_path))
    assert [os.path.basename(p) for p in result] == ["checkpoint_1", "checkpoint_3"]


# ---------------------------------------------------------------------------
# manifest validation
# ---------------------------------------------------------------------------


def _fake_checkpoint(path, payload=b"x" * 256):
    os.makedirs(path)
    with open(os.path.join(path, "model.safetensors"), "wb") as f:
        f.write(payload)
    with open(os.path.join(path, "accelerator_state.json"), "w") as f:
        json.dump({"step": 1}, f)
    write_manifest(str(path), build_manifest(str(path), kind="gathered", step=1))


def test_manifest_validation_rejects_truncation_and_corruption(tmp_path):
    ckpt = tmp_path / "checkpoint_0"
    _fake_checkpoint(str(ckpt))
    ok, reason = validate_checkpoint(str(ckpt))
    assert ok, reason

    # truncation → size mismatch
    model_file = ckpt / "model.safetensors"
    model_file.write_bytes(b"x" * 10)
    ok, reason = validate_checkpoint(str(ckpt))
    assert not ok and "size mismatch" in reason

    # same-size bit rot → checksum mismatch
    model_file.write_bytes(b"y" * 256)
    ok, reason = validate_checkpoint(str(ckpt))
    assert not ok and "checksum mismatch" in reason

    # missing file
    model_file.unlink()
    ok, reason = validate_checkpoint(str(ckpt))
    assert not ok and "missing" in reason

    # a .tmp dir is never valid, manifest or not
    tmp_ckpt = tmp_path / "checkpoint_1.tmp"
    _fake_checkpoint(str(tmp_ckpt))
    ok, reason = validate_checkpoint(str(tmp_ckpt))
    assert not ok and ".tmp" in reason


def test_find_latest_valid_skips_corrupt_for_previous(tmp_path):
    """Auto-resume selection: newest checkpoint is corrupt → fall back to
    the previous valid one; interrupted ``.tmp`` dirs are invisible."""
    _fake_checkpoint(str(tmp_path / "checkpoint_0"))
    _fake_checkpoint(str(tmp_path / "checkpoint_1"))
    (tmp_path / "checkpoint_2.tmp").mkdir()  # interrupted save leftover
    # corrupt the newest committed one
    (tmp_path / "checkpoint_1" / "model.safetensors").write_bytes(b"z")
    chosen = find_latest_valid_checkpoint(str(tmp_path))
    assert chosen is not None and os.path.basename(chosen) == "checkpoint_0"

    # corrupt that too → nothing valid
    (tmp_path / "checkpoint_0" / "model.safetensors").unlink()
    assert find_latest_valid_checkpoint(str(tmp_path)) is None


def test_legacy_checkpoint_without_manifest_accepted(tmp_path):
    ckpt = tmp_path / "checkpoint_0"
    ckpt.mkdir()
    (ckpt / "accelerator_state.json").write_text(json.dumps({"step": 2}))
    ok, reason = validate_checkpoint(str(ckpt))
    assert ok and "legacy" in reason


# ---------------------------------------------------------------------------
# retry/backoff
# ---------------------------------------------------------------------------


def test_retry_succeeds_after_transient_failures():
    calls = {"n": 0}
    sleeps: list[float] = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("stale NFS handle")
        return "ok"

    assert run_with_retries(flaky, attempts=4, backoff=0.25, sleep=sleeps.append) == "ok"
    assert calls["n"] == 3
    assert sleeps == [0.25, 0.5]  # exponential


def test_retry_exhausts_and_raises():
    def always_fails():
        raise OSError("gone")

    with pytest.raises(OSError, match="gone"):
        run_with_retries(always_fails, attempts=3, backoff=0.0)


def test_retry_does_not_catch_programming_errors():
    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        run_with_retries(buggy, attempts=5, backoff=0.0)
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# preemption handler
# ---------------------------------------------------------------------------


def test_preemption_handler_flag_and_uninstall(tmp_path):
    previous = signal.getsignal(signal.SIGTERM)
    handler = PreemptionHandler(handle_sigint=False)
    try:
        assert handler.install()
        assert get_active_handler() is handler
        assert not handler.preemption_requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert handler.preemption_requested
        assert handler.reason == "SIGTERM"
        sentinel = handler.write_sentinel(str(tmp_path), "/ck/checkpoint_3", step=7)
        payload = json.loads(open(sentinel).read())
        assert payload["reason"] == "SIGTERM" and payload["step"] == 7
    finally:
        handler.uninstall()
    assert get_active_handler() is None
    assert signal.getsignal(signal.SIGTERM) == previous


def test_fault_tolerance_plugin_env_hydration(monkeypatch):
    monkeypatch.setenv("ACCELERATE_FT_SHARDED_IO", "false")
    monkeypatch.setenv("ACCELERATE_FT_IO_ATTEMPTS", "7")
    monkeypatch.setenv("ACCELERATE_FT_CONSENSUS_INTERVAL", "16")
    plugin = FaultTolerancePlugin()
    assert plugin.sharded_io is False
    assert plugin.io_attempts == 7
    assert plugin.consensus_interval == 16


def test_launch_parser_accepts_auto_resume():
    from accelerate_tpu.commands.launch import launch_command_parser

    parser = launch_command_parser()
    args = parser.parse_args(["--auto-resume", "train.py"])
    assert args.auto_resume is True
    args = parser.parse_args(["train.py"])
    assert args.auto_resume is None


# ---------------------------------------------------------------------------
# sharded piece collection / restore (no files, no Accelerator)
# ---------------------------------------------------------------------------


def test_collect_and_restore_pieces_same_and_cross_sharding():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from accelerate_tpu.resilience.distributed import (
        collect_addressable_pieces,
        restore_tree_from_pieces,
    )

    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devices, ("x",))
    value = np.arange(64, dtype=np.float32).reshape(8, 8)
    sharded = jax.device_put(value, NamedSharding(mesh, PartitionSpec("x")))
    replicated = jax.device_put(np.float32(3.5), NamedSharding(mesh, PartitionSpec()))
    tree = {"w": sharded, "s": replicated}

    pieces, table = collect_addressable_pieces(tree)
    # 8 one-row pieces of w (one per device) + 1 deduplicated scalar piece
    assert sum(1 for k in pieces if k.startswith("w::")) == 8
    assert sum(1 for k in pieces if k.startswith("s::")) == 1
    assert table["w"]["global_shape"] == [8, 8]

    def load_piece(piece):
        return pieces[piece["piece"]]

    # same-sharding fast path
    restored = restore_tree_from_pieces(tree, table, load_piece)
    np.testing.assert_array_equal(np.asarray(restored["w"]), value)
    assert float(restored["s"]) == 3.5

    # cross-sharding: restore onto a 2-way sharding (gather-from-manifest)
    mesh2 = Mesh(devices.reshape(2, 4), ("a", "b"))
    target = {
        "w": jax.device_put(np.zeros((8, 8), np.float32), NamedSharding(mesh2, PartitionSpec("b"))),
        "s": jax.device_put(np.float32(0), NamedSharding(mesh2, PartitionSpec())),
    }
    restored2 = restore_tree_from_pieces(target, table, load_piece)
    np.testing.assert_array_equal(np.asarray(restored2["w"]), value)
    assert restored2["w"].sharding.spec == PartitionSpec("b")


def test_assemble_rejects_partial_single_piece():
    """A lone piece that does NOT cover the full array (torn multi-host
    checkpoint) must raise, never hand back np.empty garbage."""
    from accelerate_tpu.resilience.distributed import _assemble_full

    data = {"w::p0": np.ones((2, 4), np.float32)}
    entry = {
        "global_shape": [4, 4],
        "dtype": "float32",
        "pieces": [{"piece": "w::p0", "offsets": [[0, 2], [0, 4]]}],
    }
    with pytest.raises(ValueError, match="cover"):
        _assemble_full(entry, lambda p: data[p["piece"]])
    # the same piece covering the whole array is fine
    entry_full = {
        "global_shape": [2, 4],
        "dtype": "float32",
        "pieces": [{"piece": "w::p0", "offsets": [[0, 2], [0, 4]]}],
    }
    np.testing.assert_array_equal(
        _assemble_full(entry_full, lambda p: data[p["piece"]]), data["w::p0"]
    )


# ---------------------------------------------------------------------------
# rotation vs pending async writes
# ---------------------------------------------------------------------------


def test_rotation_never_deletes_pending_async_checkpoint(tmp_path):
    for i in range(4):
        (tmp_path / f"checkpoint_{i}").mkdir()
    pending = str(tmp_path / "checkpoint_0")
    _ASYNC_SAVE["pending_dirs"].add(pending)
    try:
        _rotate_checkpoints(str(tmp_path), total_limit=2)
    finally:
        _ASYNC_SAVE["pending_dirs"].discard(pending)
    remaining = sorted(d for d in os.listdir(tmp_path) if d.startswith("checkpoint_"))
    # the pending one survives even though it is oldest; enough others go
    assert "checkpoint_0" in remaining
    assert "checkpoint_1" not in remaining and "checkpoint_2" not in remaining


# ---------------------------------------------------------------------------
# end-to-end (in-process): emergency save, validated auto-resume, telemetry
# ---------------------------------------------------------------------------


def _fresh_accelerator(**kwargs):
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    return Accelerator(**kwargs)


def _step(accelerator, model, opt, x, y):
    out = model(x=x, y=y)
    accelerator.backward(out.loss)
    opt.step()
    opt.zero_grad()
    accelerator.step += 1


def test_sigterm_triggers_emergency_save_and_clean_exit(tmp_path):
    config = ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True)
    accelerator = _fresh_accelerator(
        project_config=config, fault_tolerance=FaultTolerancePlugin(exit_code=143)
    )
    try:
        model, opt = accelerator.prepare(RegressionModel(a=1.0, b=2.0), optax.adam(0.05))
        x = np.arange(16, dtype=np.float32)
        y = 2 * x + 3
        _step(accelerator, model, opt, x, y)
        os.kill(os.getpid(), signal.SIGTERM)  # simulated preemption notice
        assert accelerator.preemption_requested
        with pytest.raises(SystemExit) as exc:
            _step(accelerator, model, opt, x, y)
        assert exc.value.code == 143
    finally:
        if accelerator._preemption_handler is not None:
            accelerator._preemption_handler.uninstall()

    checkpoints_dir = tmp_path / "checkpoints"
    names = sorted(os.listdir(checkpoints_dir))
    assert "checkpoint_0" in names and SENTINEL_NAME in names
    ok, reason = validate_checkpoint(str(checkpoints_dir / "checkpoint_0"))
    assert ok, reason
    sentinel = json.loads((checkpoints_dir / SENTINEL_NAME).read_text())
    assert sentinel["reason"] == "SIGTERM" and sentinel["step"] == 1


def test_preemption_defers_until_accumulation_window_closes(tmp_path):
    """Mid-window (parked loss / accumulated grads) the emergency save is
    deferred — acting there would drop the partial gradient window."""
    config = ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True)
    accelerator = _fresh_accelerator(
        project_config=config,
        fault_tolerance=FaultTolerancePlugin(handle_signals=False),
    )
    try:
        model, opt = accelerator.prepare(RegressionModel(a=1.0, b=2.0), optax.sgd(0.1))
        accelerator._preemption_handler.request_preemption("test")
        opt._grads = {"a": np.zeros(()), "b": np.zeros(())}  # mid-window
        accelerator.check_preemption()  # deferred: no SystemExit
        opt._grads = None  # window closed
        with pytest.raises(SystemExit):
            accelerator.check_preemption()
    finally:
        accelerator._preemption_handler.uninstall()


def test_auto_resume_skips_corrupt_checkpoint_for_valid_one(tmp_path, monkeypatch):
    """The full loop: two saves, newest corrupted on disk → a fresh
    fault-tolerant Accelerator resumes from the OLDER valid one."""
    config = ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True)
    accelerator = _fresh_accelerator(project_config=config)
    model, opt = accelerator.prepare(RegressionModel(a=1.0, b=2.0), optax.adam(0.05))
    x = np.arange(16, dtype=np.float32)
    y = 2 * x + 3
    _step(accelerator, model, opt, x, y)
    accelerator.save_state(sharded=True)  # checkpoint_0
    good = {k: np.asarray(v) for k, v in model.params.items()}
    _step(accelerator, model, opt, x, y)
    accelerator.save_state(sharded=True)  # checkpoint_1

    # corrupt the newest: flip bytes in its shard file, keep the size
    ck1 = tmp_path / "checkpoints" / "checkpoint_1"
    shard_files = [
        os.path.join(root, f)
        for root, _, files in os.walk(ck1)
        for f in files
        if f.startswith("model")
    ]
    assert shard_files
    data = bytearray(open(shard_files[0], "rb").read())
    data[-8:] = b"\xff" * 8
    open(shard_files[0], "wb").write(bytes(data))

    resumed = _fresh_accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        ),
        fault_tolerance=FaultTolerancePlugin(handle_signals=False),
    )
    try:
        model2, opt2 = resumed.prepare(RegressionModel(a=0.0, b=0.0), optax.adam(0.05))
    finally:
        if resumed._preemption_handler is not None:
            resumed._preemption_handler.uninstall()
    assert resumed.step == 1  # checkpoint_0's step, not checkpoint_1's
    for k in good:
        np.testing.assert_array_equal(np.asarray(model2.params[k]), good[k])


def test_commit_into_existing_dir_preserves_unrelated_content(tmp_path):
    """Non-automatic naming resolves save_state to ``checkpoints/`` itself:
    the commit must merge-overwrite there, never delete unrelated content
    (a pending sentinel, user files) the way a wholesale replace would."""
    accelerator = _fresh_accelerator(project_dir=str(tmp_path))
    model, opt = accelerator.prepare(RegressionModel(a=1.0, b=2.0), optax.adam(0.05))
    ckdir = tmp_path / "checkpoints"
    ckdir.mkdir()
    (ckdir / SENTINEL_NAME).write_text("{}")
    (ckdir / "user_notes.txt").write_text("keep me")
    out = accelerator.save_state()
    assert os.path.samefile(out, ckdir)
    assert (ckdir / SENTINEL_NAME).exists() and (ckdir / "user_notes.txt").exists()
    ok, reason = validate_checkpoint(str(ckdir), check_crc=True)
    assert ok, reason
    accelerator.save_state()  # overwrite-in-place round-trips too
    accelerator.load_state(str(ckdir))


def test_checkpoint_telemetry_records_save_and_restore(tmp_path):
    accelerator = _fresh_accelerator(project_dir=str(tmp_path), telemetry=True)
    model, opt = accelerator.prepare(RegressionModel(a=1.0, b=2.0), optax.adam(0.05))
    out = accelerator.save_state(str(tmp_path / "ck"), sharded=True)
    accelerator.load_state(out)
    records = [json.loads(line) for line in open(accelerator.telemetry.jsonl_path)]
    ckpt_records = [r for r in records if r["type"] == "checkpoint"]
    kinds = [r["kind"] for r in ckpt_records]
    assert "save" in kinds and "restore" in kinds
    save = next(r for r in ckpt_records if r["kind"] == "save")
    assert save["bytes"] > 0 and save["shard_count"] == 1 and save["seconds"] > 0
    accelerator.telemetry.close()


def test_sharded_save_resume_trajectory_identical(tmp_path):
    """6 straight steps == save@3 (sharded) → fresh accelerator → resume →
    3 more, with the dataloader position coming back from the checkpoint."""

    def build():
        accelerator = _fresh_accelerator()
        return accelerator, *accelerator.prepare(
            RegressionModel(), optax.adam(0.05), _Loader(RegressionDataset(length=96), 16)
        )

    def train(accelerator, model, opt, dl, n):
        it = iter(dl)
        for _ in range(n):
            batch = next(it)
            out = model(**batch)
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()

    acc1, m1, o1, d1 = build()
    train(acc1, m1, o1, d1, 6)
    straight = {k: np.asarray(v) for k, v in m1.params.items()}

    acc2, m2, o2, d2 = build()
    train(acc2, m2, o2, d2, 3)
    acc2.save_state(str(tmp_path / "mid"), sharded=True)

    acc3, m3, o3, d3 = build()
    acc3.load_state(str(tmp_path / "mid"))
    assert d3.position == 3  # restored mid-epoch position, no manual skip
    train(acc3, m3, o3, d3, 3)
    for k in straight:
        np.testing.assert_allclose(np.asarray(m3.params[k]), straight[k], rtol=1e-6)


# ---------------------------------------------------------------------------
# subprocess invariants (slow lane): kill -9 mid-save, SIGTERM mid-training,
# atexit draining
# ---------------------------------------------------------------------------


def _subprocess_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


_KILL_DURING_SAVE_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    import numpy as np, optax
    from accelerate_tpu import Accelerator, ProjectConfiguration
    from accelerate_tpu.test_utils import RegressionModel
    import accelerate_tpu.checkpointing as ckpt

    project_dir = sys.argv[1]
    acc = Accelerator(project_config=ProjectConfiguration(
        project_dir=project_dir, automatic_checkpoint_naming=True))
    model, opt = acc.prepare(RegressionModel(a=1.0, b=2.0), optax.adam(0.05))
    x = np.arange(16, dtype=np.float32)
    out = model(x=x, y=2 * x + 3)
    acc.backward(out.loss)
    opt.step(); opt.zero_grad()
    acc.save_state()            # checkpoint_0: committed, valid
    acc.step = 99

    real = ckpt.save_array_dict
    def slow_save(flat, path, safe):
        real(flat, path, safe)
        print("MID_WRITE", flush=True)   # parent kills us here
        time.sleep(60)
    ckpt.save_array_dict = slow_save
    acc.save_state()            # checkpoint_1: killed mid-write
    print("UNREACHABLE", flush=True)
    """
)


@pytest.mark.slow
def test_kill_during_save_never_leaves_partial_checkpoint(tmp_path):
    """SIGKILL mid-write: the interrupted save exists only as a ``.tmp``,
    discovery skips it, and auto-resume selects the previous committed
    checkpoint."""
    script = tmp_path / "victim.py"
    script.write_text(_KILL_DURING_SAVE_SCRIPT)
    proc = subprocess.Popen(
        [sys.executable, str(script), str(tmp_path / "proj")],
        env=_subprocess_env(),
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        for line in proc.stdout:
            if "MID_WRITE" in line:
                proc.kill()  # SIGKILL: no handlers, no cleanup
                break
            assert "UNREACHABLE" not in line
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    checkpoints_dir = str(tmp_path / "proj" / "checkpoints")
    names = sorted(os.listdir(checkpoints_dir))
    assert "checkpoint_1" not in names, "partial save must never be committed"
    assert "checkpoint_1.tmp" in names, f"expected interrupted .tmp, got {names}"
    assert [os.path.basename(p) for p in _sorted_checkpoints(checkpoints_dir)] == ["checkpoint_0"]
    chosen = find_latest_valid_checkpoint(checkpoints_dir)
    assert chosen is not None and os.path.basename(chosen) == "checkpoint_0"
    meta = json.loads(open(os.path.join(chosen, "accelerator_state.json")).read())
    assert meta["step"] != 99  # the pre-kill state, not the doomed save's


_KILL_RESUME_SCRIPT = textwrap.dedent(
    """
    import hashlib, json, os, pickle, random, signal, sys
    import numpy as np, optax
    from accelerate_tpu import Accelerator, FaultTolerancePlugin, ProjectConfiguration
    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel

    mode, project_dir, out_path = sys.argv[1:4]

    class Loader:
        def __init__(self, dataset, batch_size):
            self.dataset = dataset
            self.batch_size = batch_size
            self.drop_last = False
            self.sampler = None
            self.batch_sampler = None
            self.collate_fn = None

    def rng_fingerprint():
        return {
            "python": hashlib.sha256(pickle.dumps(random.getstate())).hexdigest(),
            "numpy": hashlib.sha256(pickle.dumps(np.random.get_state())).hexdigest(),
        }

    random.seed(1234); np.random.seed(5678)
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=project_dir, automatic_checkpoint_naming=True),
        fault_tolerance=FaultTolerancePlugin(),
    )
    model, opt, dl = acc.prepare(
        RegressionModel(), optax.adam(0.05), Loader(RegressionDataset(length=96), 16))

    if mode == "resume":
        # auto-resume already fired inside prepare()
        report = {
            "step": acc.step,
            "dl_position": dl.position,
            "rng": rng_fingerprint(),
        }
        json.dump(report, open(out_path, "w"))
        sys.exit(0)

    it = iter(dl)
    for i in range(6):
        if i == 3 and mode == "train":
            # completed exactly 3 optimizer steps; record ground truth,
            # then the preemption notice arrives
            json.dump(
                {"dl_position_at_kill": dl.batches_yielded, "step_at_kill": acc.step,
                 "rng": rng_fingerprint()},
                open(out_path, "w"))
            os.kill(os.getpid(), signal.SIGTERM)
        batch = next(it)
        out = model(**batch)
        acc.backward(out.loss)   # i==3: boundary check fires here -> save+exit
        opt.step(); opt.zero_grad()
        acc.step += 1
    print("FINISHED_ALL_STEPS", flush=True)
    """
)


@pytest.mark.slow
def test_sigterm_mid_training_emergency_save_then_auto_resume(tmp_path):
    """The acceptance invariant end-to-end, across real processes:
    SIGTERM mid-training → synchronized emergency save + clean exit 143 →
    a fresh auto-resume process restores step counter, RNG, and dataloader
    position to within one optimizer step (the one fetched-but-unstepped
    batch), never touching a ``.tmp``."""
    project_dir = str(tmp_path / "proj")
    script = tmp_path / "job.py"
    script.write_text(_KILL_RESUME_SCRIPT)
    train_report = tmp_path / "train.json"
    rc = subprocess.run(
        [sys.executable, str(script), "train", project_dir, str(train_report)],
        env=_subprocess_env(),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert rc.returncode == 143, rc.stderr[-2000:]
    assert "FINISHED_ALL_STEPS" not in rc.stdout

    checkpoints_dir = os.path.join(project_dir, "checkpoints")
    names = sorted(os.listdir(checkpoints_dir))
    assert SENTINEL_NAME in names
    committed = _sorted_checkpoints(checkpoints_dir)
    assert len(committed) == 1
    assert not any(n.endswith(".tmp") for n in names)
    ok, reason = validate_checkpoint(committed[0])
    assert ok, reason

    truth = json.loads(train_report.read_text())
    assert truth["step_at_kill"] == 3

    resume_report = tmp_path / "resume.json"
    rc = subprocess.run(
        [sys.executable, str(script), "resume", project_dir, str(resume_report)],
        env=_subprocess_env(),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert rc.returncode == 0, rc.stderr[-2000:]
    resumed = json.loads(resume_report.read_text())
    # step counter and RNG restore exactly; the dataloader is within one
    # batch of the kill point (batch 3 was fetched but its step never ran)
    assert resumed["step"] == truth["step_at_kill"]
    assert resumed["rng"] == truth["rng"]
    assert resumed["dl_position"] == truth["dl_position_at_kill"] + 1
    # the sentinel was consumed by the successful resume
    assert not os.path.exists(os.path.join(checkpoints_dir, SENTINEL_NAME))


_ATEXIT_SCRIPT = textwrap.dedent(
    """
    import sys
    import numpy as np, optax
    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionModel

    acc = Accelerator()
    model, opt = acc.prepare(RegressionModel(a=4.0, b=1.0), optax.sgd(0.1))
    acc.save_state(sys.argv[1], async_save=True)
    sys.exit(0)   # no wait_for_checkpoint: atexit must drain + commit
    """
)


@pytest.mark.slow
def test_atexit_joins_and_commits_inflight_async_save(tmp_path):
    ckpt = str(tmp_path / "ck")
    script = tmp_path / "exiter.py"
    script.write_text(_ATEXIT_SCRIPT)
    rc = subprocess.run(
        [sys.executable, str(script), ckpt],
        env=_subprocess_env(),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert rc.returncode == 0, rc.stderr[-2000:]
    assert os.path.isdir(ckpt), "async save abandoned at interpreter exit"
    assert not os.path.isdir(ckpt + ".tmp")
    ok, reason = validate_checkpoint(ckpt)
    assert ok, reason
