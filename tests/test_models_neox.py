"""GPT-NeoX / GPT-J family: training on sharded meshes, streaming offload,
pipeline inference, numerical parity against HF-transformers' torch models
(reference exposure: GPT-J-6B / GPT-NeoX-20B rows of
``benchmarks/big_model_inference/README.md:31-34``)."""

import jax
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshPlugin, prepare_pippy
from accelerate_tpu.big_modeling import cpu_offload
from accelerate_tpu.models.gpt_neox import (
    GPTNeoXConfig,
    GPTNeoXForCausalLM,
    convert_hf_gpt_neox_state_dict,
    convert_hf_gptj_state_dict,
)

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _tiny(layers=2, **kw):
    config = GPTNeoXConfig.tiny(layers=layers, **kw)
    model = GPTNeoXForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    return config, model, ids


def test_forward_shapes_and_loss():
    config, model, ids = _tiny()
    out = model.apply_fn(model.params, input_ids=ids, labels=ids)
    assert out["logits"].shape == (2, 16, 256)
    assert np.isfinite(float(out["loss"]))


def test_gptj_variant_forward():
    config, model, ids = _tiny(shared_layernorm=True, attention_bias=False)
    assert "ln2_g" not in model.params["layers"]
    assert "b_qkv" not in model.params["layers"]
    assert "lm_head_b" in model.params
    out = model.apply_fn(model.params, input_ids=ids, labels=ids)
    assert np.isfinite(float(out["loss"]))


def test_training_on_sharded_mesh():
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    config = GPTNeoXConfig.tiny(layers=2)
    model, opt = accelerator.prepare(
        GPTNeoXForCausalLM.from_config(config, seed=0), optax.adamw(1e-2)
    )
    ids = np.random.default_rng(0).integers(0, 256, size=(8, 16)).astype(np.int32)
    losses = []
    for _ in range(5):
        out = model(input_ids=ids, labels=ids)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]


def test_gptj_training_on_sharded_mesh():
    """The GPT-J variant's extra rank-1 ``lm_head_b`` must shard under
    prepare(): regression for the ``lm_head`` rule (rank-2 spec) shadowing
    ``lm_head_b`` in first-search-hit rule matching."""
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    config = GPTNeoXConfig.tiny(layers=2, shared_layernorm=True, attention_bias=False)
    model, opt = accelerator.prepare(
        GPTNeoXForCausalLM.from_config(config, seed=0), optax.adamw(1e-2)
    )
    ids = np.random.default_rng(0).integers(0, 256, size=(8, 16)).astype(np.int32)
    losses = []
    for _ in range(4):
        out = model(input_ids=ids, labels=ids)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]


def test_streaming_offload_matches_resident():
    config, model, ids = _tiny()
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    out = cpu_offload(model)(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_inference_matches():
    config, model, ids = _tiny(layers=4)
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids}, devices=jax.devices()[:2]
    )
    out = pipelined(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_kv_cache_decode_matches_full_forward():
    config, model, ids = _tiny()
    full = model.apply_fn(model.params, input_ids=ids)["logits"]
    pre = model.apply_fn(
        model.params, input_ids=ids[:, :8], use_cache=True, max_cache_len=16
    )
    cache = pre["kv_cache"]
    outs = [pre["logits"][:, -1:]]
    for t in range(8, 16):
        step = model.apply_fn(
            model.params,
            input_ids=ids[:, t : t + 1],
            kv_cache=cache,
            cache_index=np.full((2,), t, np.int32),
        )
        cache = step["kv_cache"]
        outs.append(step["logits"])
    decoded = np.concatenate([np.asarray(o) for o in outs], axis=1)
    np.testing.assert_allclose(
        decoded, np.asarray(full[:, 7:, :]), rtol=2e-4, atol=2e-4
    )


def test_parity_with_hf_gpt_neox():
    """Logit-level parity against transformers' torch GPT-NeoX built from
    the same (converted) weights: pins the per-head QKV de-interleave and
    the partial rotate-half rotary. ``highest`` matmul precision — XLA:CPU's
    default oneDNN fastmath matmul rounds at ~bf16."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    torch.manual_seed(0)
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=256, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, max_position_embeddings=128,
        rotary_pct=0.25, use_parallel_residual=True,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    config = GPTNeoXConfig.tiny(layers=2)
    model = GPTNeoXForCausalLM.from_config(config)
    params = jax.tree.map(np.asarray, convert_hf_gpt_neox_state_dict(flat, config))
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    with jax.default_matmul_precision("highest"):
        ours = np.asarray(model.apply_fn(params, input_ids=ids)["logits"])
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_parity_with_hf_gpt_neox_sequential_residual():
    """``use_parallel_residual=False`` checkpoints (StableLM-style NeoX)
    compute the sequential residual; parity pins the post-attention
    LayerNorm reading the attn-updated hidden state."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    torch.manual_seed(0)
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=256, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, max_position_embeddings=128,
        rotary_pct=0.25, use_parallel_residual=False,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    config = GPTNeoXConfig.tiny(layers=2, use_parallel_residual=False)
    model = GPTNeoXForCausalLM.from_config(config)
    params = jax.tree.map(np.asarray, convert_hf_gpt_neox_state_dict(flat, config))
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    with jax.default_matmul_precision("highest"):
        ours = np.asarray(model.apply_fn(params, input_ids=ids)["logits"])
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_overlong_sequence_raises():
    config, model, _ = _tiny()
    ids = np.zeros((1, config.max_position_embeddings + 1), np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        model.apply_fn(model.params, input_ids=ids)


def test_parity_with_hf_gptj():
    """Logit-level parity against transformers' torch GPT-J: pins the
    rotate-every-two → rotate-half even/odd column permutation of the q/k
    projections and the shared-LayerNorm parallel residual."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    torch.manual_seed(0)
    hf_cfg = transformers.GPTJConfig(
        vocab_size=256, n_embd=64, n_inner=256, n_layer=2, n_head=4,
        n_positions=128, rotary_dim=4, resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    hf = transformers.GPTJForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    config = GPTNeoXConfig.tiny(
        layers=2, shared_layernorm=True, attention_bias=False
    )
    assert config.rotary_dim == 4
    model = GPTNeoXForCausalLM.from_config(config)
    params = jax.tree.map(np.asarray, convert_hf_gptj_state_dict(flat, config))
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    with jax.default_matmul_precision("highest"):
        ours = np.asarray(model.apply_fn(params, input_ids=ids)["logits"])
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_zoo_shapes():
    from accelerate_tpu.models import MODEL_ZOO

    import accelerate_tpu.big_modeling as bm

    for name, lo, hi in [("gpt-neox-20b", 19e9, 22e9), ("gpt-j-6b", 5.5e9, 6.5e9)]:
        cfg, factory = MODEL_ZOO[name]
        with bm.init_empty_weights():
            meta = factory(cfg)
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(meta.params))
        assert lo < n < hi, (name, n)


def test_gelu_flavor_exact_for_neox_tanh_for_gptj():
    """GPT-NeoX checkpoints use exact (erf) GELU (HF ``hidden_act="gelu"``)
    while GPT-J uses the tanh approximation (``gelu_new``) — the ~1e-3 gap
    at |x|~2 is above checkpoint-parity tolerance, so the family resolution
    (and its explicit override) is pinned here."""
    import jax.numpy as jnp

    from accelerate_tpu.models.gpt_neox import _gelu

    x = jnp.linspace(-4.0, 4.0, 101, dtype=jnp.float32)
    exact = jax.nn.gelu(x, approximate=False)
    tanh = jax.nn.gelu(x, approximate=True)
    assert float(jnp.abs(exact - tanh).max()) > 1e-4  # the flavors differ

    neox = GPTNeoXConfig.tiny()
    gptj = GPTNeoXConfig.tiny(shared_layernorm=True, attention_bias=False)
    np.testing.assert_array_equal(np.asarray(_gelu(neox, x)), np.asarray(exact))
    np.testing.assert_array_equal(np.asarray(_gelu(gptj, x)), np.asarray(tanh))
    # explicit override beats the family default
    forced = GPTNeoXConfig.tiny(gelu_approximate=True)
    np.testing.assert_array_equal(np.asarray(_gelu(forced, x)), np.asarray(tanh))
