"""Flagship model: forward shape/loss sanity + sharded training step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, FullyShardedDataParallelPlugin, MeshPlugin
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _batch(b=8, s=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(b, s)).astype(np.int32)
    return {"input_ids": ids, "labels": ids.copy(), "attention_mask": np.ones((b, s), np.int32)}


def test_llama_forward_shapes():
    config = LlamaConfig.tiny()
    model = LlamaForCausalLM.from_config(config)
    batch = _batch()
    out = model.apply_fn(model.params, **{k: jnp.asarray(v) for k, v in batch.items()})
    assert out.logits.shape == (8, 32, 256)
    assert out.loss.shape == ()
    assert np.isfinite(float(out.loss))
    # random model ≈ uniform: loss ≈ ln(vocab)
    assert abs(float(out.loss) - np.log(256)) < 1.0


def test_llama_trains_under_accelerator_with_tp_fsdp_mesh():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator = Accelerator(
        mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2),
        fsdp_plugin=FullyShardedDataParallelPlugin(),
    )
    config = LlamaConfig.tiny()
    model = LlamaForCausalLM.from_config(config)
    tx = optax.adamw(1e-3)
    model, opt = accelerator.prepare(model, tx)

    # params actually sharded: wq [L, h, nh*hd] → P(None, fsdp, tp)
    wq = model.params["layers"]["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")

    from accelerate_tpu.mesh import data_sharding

    sharding = data_sharding(accelerator.mesh)
    batch = {k: jax.device_put(jnp.asarray(v), sharding) for k, v in _batch().items()}
    losses = []
    for _ in range(5):
        out = model(**batch)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]  # memorising a fixed batch


def test_llama_tiny_matches_replicated_vs_sharded():
    """Same init, same batch: loss on a dp=8 mesh equals single-logical-device
    computation (GSPMD correctness check)."""
    config = LlamaConfig.tiny(layers=1, hidden_size=32, heads=2)
    model = LlamaForCausalLM.from_config(config, seed=3)
    batch = {k: jnp.asarray(v) for k, v in _batch(b=8, s=16).items()}
    loss_plain = float(model.apply_fn(model.params, **batch).loss)

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=-1))
    prepared = accelerator.prepare(LlamaForCausalLM.from_config(config, seed=3))
    from accelerate_tpu.mesh import data_sharding

    sharding = data_sharding(accelerator.mesh)
    sharded_batch = {k: jax.device_put(v, sharding) for k, v in batch.items()}
    loss_sharded = prepared(**sharded_batch).loss.item()
    np.testing.assert_allclose(loss_sharded, loss_plain, rtol=2e-5)


def test_remat_policy_variants_match_full_remat():
    """remat accepts a jax.checkpoint_policies name (dots_saveable keeps
    matmul outputs resident); loss must be identical to remat=True, and an
    unknown policy name must fail loudly."""
    import pytest

    config = LlamaConfig.tiny(layers=2, hidden_size=32, heads=2)
    batch = {k: jnp.asarray(v) for k, v in _batch(b=4, s=16).items()}

    losses = {}
    for remat in (True, "dots_saveable"):
        config.remat = remat
        model = LlamaForCausalLM.from_config(config, seed=7)
        out = model.apply_fn(model.params, **batch)
        losses[str(remat)] = float(out.loss)
    assert abs(losses["True"] - losses["dots_saveable"]) < 1e-6

    config.remat = "not_a_policy"
    model = LlamaForCausalLM.from_config(config, seed=7)
    with pytest.raises(ValueError, match="unknown remat policy"):
        jax.grad(lambda p: model.apply_fn(p, **batch).loss)(model.params)
