"""BERT family: classification forward/loss, sharded training, streaming
offload, pipeline inference (reference exposure: BERT-base is the
``nlp_example.py`` model and ``examples/inference/pippy/bert.py``)."""

import jax
import numpy as np
import pytest
import optax

from accelerate_tpu import Accelerator, MeshPlugin, prepare_pippy
from accelerate_tpu.big_modeling import cpu_offload
from accelerate_tpu.models.bert import BertConfig, BertForSequenceClassification

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _tiny(layers=2):
    config = BertConfig.tiny(layers=layers)
    model = BertForSequenceClassification.from_config(config, seed=1)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, size=(4, 32)).astype(np.int32)
    labels = rng.integers(0, config.num_labels, size=(4,)).astype(np.int32)
    return config, model, ids, labels


def test_forward_shapes_and_loss():
    config, model, ids, labels = _tiny()
    out = model.apply_fn(model.params, input_ids=ids, labels=labels)
    assert out.logits.shape == (4, config.num_labels)
    loss = float(out.loss)
    assert np.isfinite(loss)
    assert abs(loss - np.log(config.num_labels)) < 0.5  # random ≈ uniform


def test_training_on_sharded_mesh():
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    config = BertConfig.tiny(layers=2)
    model, opt = accelerator.prepare(
        BertForSequenceClassification.from_config(config, seed=0), optax.adamw(1e-3)
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, size=(8, 32)).astype(np.int32)
    labels = rng.integers(0, config.num_labels, size=(8,)).astype(np.int32)
    losses = []
    for _ in range(5):
        out = model(input_ids=ids, labels=labels)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]


def test_streaming_offload_matches_resident():
    config, model, ids, _ = _tiny()
    ref = model.apply_fn(model.params, input_ids=ids).logits
    out = cpu_offload(model)(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_inference_matches():
    config, model, ids, _ = _tiny(layers=4)
    ref = model.apply_fn(model.params, input_ids=ids).logits
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids}, devices=jax.devices()[:2]
    )
    out = pipelined(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_zoo_has_bert():
    from accelerate_tpu.models import MODEL_ZOO

    assert "bert-base" in MODEL_ZOO
