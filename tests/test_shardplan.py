"""The static sharding-plan analyzer (``accelerate_tpu/analysis/shardplan.py``)
and its runtime seams.

The acceptance bar: on ``LlamaConfig.flagship_700m()`` over a virtual
``(dp=1, fsdp=2, tp=2)`` mesh, predicted per-device param+optimizer bytes
match the LIVE sharded ``jax.Array`` footprint exactly (leaf by leaf —
arrays are materialized one at a time so the test never holds the whole
~8 GiB model), the clean plan exits 0 through the real CLI, and each
seeded misconfiguration exits 2 naming its SP rule ID.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MESH_SIZES = {"dp": 1, "pp": 1, "fsdp": 2, "ep": 1, "cp": 1, "tp": 2}


def _flagship_abstract(dtype="float32"):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models.llama import (
        LLAMA_PARTITION_RULES,
        LlamaConfig,
        init_llama_params,
    )

    config = LlamaConfig.flagship_700m()
    params = jax.eval_shape(
        lambda key: init_llama_params(key, config, dtype=jnp.dtype(dtype)),
        jax.random.PRNGKey(0),
    )
    return params, config, list(LLAMA_PARTITION_RULES)


def _mesh4():
    import jax

    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs a >= 4-device (virtual) mesh")
    return build_mesh(MeshPlugin(dp=1, fsdp=2, tp=2), devices=devices[:4])


# ---------------------------------------------------------------------------
# the analyzer proper (virtual mesh: no devices touched)
# ---------------------------------------------------------------------------


class TestAnalyzer:
    def test_clean_flagship_plan_has_no_findings(self):
        from accelerate_tpu.analysis.shardplan import analyze_plan

        params, config, rules = _flagship_abstract()
        report = analyze_plan(params, MESH_SIZES, rules=rules, optimizer="adam")
        assert report.findings == [], [f.to_dict() for f in report.findings]
        tiers = report.tiers
        assert set(tiers) == {"params", "opt_state"}
        # the sharded tiers really shrink per device (norms replicate, so
        # strictly between global/4 and global)
        for tier in tiers.values():
            assert tier["bytes_global"] / 4 < tier["bytes_per_device"] < tier["bytes_global"]
        # adam: mu + nu mirror the params byte-for-byte, count is noise
        assert tiers["opt_state"]["bytes_global"] >= 2 * tiers["params"]["bytes_global"]

    def test_dead_rule_sp001(self):
        from accelerate_tpu.analysis.shardplan import analyze_plan

        params, config, rules = _flagship_abstract()
        from jax.sharding import PartitionSpec as P

        report = analyze_plan(
            params, MESH_SIZES, rules=[("no_such_param", P("tp"))] + rules,
            optimizer="none",
        )
        assert [f.rule for f in report.findings] == ["SP001"]
        assert "no_such_param" in report.findings[0].subject

    def test_forced_replicated_sp002(self):
        from accelerate_tpu.analysis.shardplan import analyze_plan

        params, config, rules = _flagship_abstract()
        from jax.sharding import PartitionSpec as P

        report = analyze_plan(
            params, MESH_SIZES, rules=[("embed_tokens", P())] + rules,
            optimizer="none",
        )
        rules_fired = {f.rule for f in report.findings}
        # the shadowed original embed rule is now dead too — both findings
        # describe the same seeded bug
        assert rules_fired == {"SP001", "SP002"}
        sp002 = [f for f in report.findings if f.rule == "SP002"]
        assert sp002[0].subject == "embed_tokens"

    def test_non_divisible_axis_sp003(self):
        from accelerate_tpu.analysis.shardplan import analyze_plan

        params, config, rules = _flagship_abstract()
        from jax.sharding import PartitionSpec as P

        sizes = dict(MESH_SIZES, tp=7, fsdp=1)  # 1536 % 7 != 0
        report = analyze_plan(
            params, sizes, rules=[("embed_tokens", P(None, "tp"))] + rules,
            optimizer="none",
        )
        sp003 = [f for f in report.findings if f.rule == "SP003"]
        assert sp003 and sp003[0].subject == "embed_tokens"
        assert sp003[0].detail["extent"] == 7

    def test_unknown_axis_is_sp003_with_extent_zero(self):
        from accelerate_tpu.analysis.shardplan import analyze_plan

        params, config, rules = _flagship_abstract()
        from jax.sharding import PartitionSpec as P

        report = analyze_plan(
            params, MESH_SIZES, rules=[("embed_tokens", P("model"))] + rules,
            optimizer="none",
        )
        sp003 = [f for f in report.findings if f.rule == "SP003"]
        assert sp003 and sp003[0].detail["extent"] == 0

    def test_over_budget_sp004_breakdown(self):
        from accelerate_tpu.analysis.shardplan import analyze_plan

        params, config, rules = _flagship_abstract()
        report = analyze_plan(
            params, MESH_SIZES, rules=rules, optimizer="adam", hbm_gb=0.5,
        )
        sp004 = [f for f in report.findings if f.rule == "SP004"]
        assert len(sp004) == 1
        assert sp004[0].severity == "error"
        tiers = sp004[0].detail["tiers"]
        assert tiers["opt_state"] > tiers["params"] > 0
        assert sp004[0].detail["bytes_per_device"] == report.bytes_per_device

    def test_kv_pool_tier_tp_sharding(self):
        from accelerate_tpu.analysis.shardplan import plan_kv_pool

        # 12 kv heads over tp=2: sharded; over tp=5: replicated fallback
        sharded = plan_kv_pool(16, 12, 128, 8, 16, 512, dict(MESH_SIZES))
        assert all(l.bytes_per_device * 2 == l.bytes_global for l in sharded)
        repl = plan_kv_pool(16, 12, 128, 8, 16, 512, dict(MESH_SIZES, tp=5))
        assert all(l.bytes_per_device == l.bytes_global for l in repl)
        # default pool = full residency: slots * ceil(seq/block) + null
        assert sharded[0].shape[1] == 8 * 32 + 1

    def test_mesh_spec_parsing(self):
        from accelerate_tpu.analysis.shardplan import parse_mesh_spec

        assert parse_mesh_spec("1,2,2")["fsdp"] == 2
        assert parse_mesh_spec("1,2,2")["tp"] == 2
        named = parse_mesh_spec("dp=2, tp=4, cp=2")
        assert (named["dp"], named["tp"], named["cp"]) == (2, 4, 2)
        with pytest.raises(ValueError):
            parse_mesh_spec("bogus=2")
        with pytest.raises(ValueError):
            parse_mesh_spec("1,2,3,4")


# ---------------------------------------------------------------------------
# the acceptance bar: predicted == live jax.Array footprint, exactly
# ---------------------------------------------------------------------------


class TestLiveParity:
    def test_flagship_predicted_matches_live_footprint_exactly(self):
        """Every param+opt leaf of the sharded flagship plan, placed for
        real on the 4-device virtual CPU mesh one leaf at a time: the
        bytes each device holds must equal the prediction EXACTLY."""
        import jax
        from jax.sharding import NamedSharding

        from accelerate_tpu.analysis.shardplan import (
            analyze_plan,
            mesh_sizes_of,
        )
        from accelerate_tpu.parallel.sharding import explain_partition_spec
        from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

        mesh = _mesh4()
        params, config, rules = _flagship_abstract()
        report = analyze_plan(
            params, mesh_sizes_of(mesh), rules=rules, optimizer="adam"
        )
        assert report.findings == []

        plugin = FullyShardedDataParallelPlugin()
        devices = list(mesh.devices.flat)
        checked = 0
        for leaf in report.leaves:
            assert leaf.tier in ("params", "opt_state")
            # the analyzer's spec string round-trips through the REAL
            # placement decision for params; opt leaves inherit it
            if leaf.tier == "params":
                decision = explain_partition_spec(
                    leaf.path, leaf.shape, mesh, plugin, rules
                )
                assert str(decision.spec) == leaf.spec, leaf.path
                sharding = NamedSharding(mesh, decision.spec)
            else:
                # reconstruct the opt leaf's sharding from the param twin
                twin = next(
                    (
                        p
                        for p in report.leaves
                        if p.tier == "params" and p.shape == leaf.shape
                        and p.spec == leaf.spec
                    ),
                    None,
                )
                if twin is None:  # replicated scalar (adam count)
                    from jax.sharding import PartitionSpec

                    sharding = NamedSharding(mesh, PartitionSpec())
                else:
                    sharding = NamedSharding(
                        mesh,
                        explain_partition_spec(
                            twin.path, twin.shape, mesh, plugin, rules
                        ).spec,
                    )
            arr = jax.device_put(np.zeros(leaf.shape, leaf.dtype), sharding)
            for dev in devices:
                live = sum(
                    int(s.data.nbytes)
                    for s in arr.addressable_shards
                    if s.device == dev
                )
                assert live == leaf.bytes_per_device, (
                    f"{leaf.tier}/{leaf.path} on {dev}: "
                    f"live {live} != predicted {leaf.bytes_per_device}"
                )
            del arr
            checked += 1
        assert checked == len(report.leaves) > 20

    def test_kv_pool_prediction_matches_live_engine_pool(self, tiny_paged_model):
        """The kv-pool tier's per-device bytes equal the real sharded
        engine pool's shard bytes (the PR 7 sharded engine as ground
        truth)."""
        from accelerate_tpu.analysis.shardplan import mesh_sizes_of, plan_kv_pool
        from accelerate_tpu.serving import EngineConfig, InferenceEngine

        mesh = _mesh4()
        cfg = tiny_paged_model.config
        geometry = dict(num_slots=2, block_size=8, max_seq_len=64)
        engine = InferenceEngine(
            tiny_paged_model, EngineConfig(**geometry), mesh=mesh
        )
        plan = plan_kv_pool(
            num_layers=cfg.num_hidden_layers,
            num_kv_heads=cfg.num_key_value_heads,
            head_dim=cfg.head_dim,
            mesh_sizes=mesh_sizes_of(mesh),
            dtype=str(engine._kp.dtype),
            **geometry,
        )
        k_plan = next(p for p in plan if p.path.endswith(".k"))
        dev0 = engine._kp.addressable_shards[0].device
        live = sum(
            int(s.data.nbytes)
            for s in engine._kp.addressable_shards
            if s.device == dev0
        )
        assert live == k_plan.bytes_per_device
        assert tuple(engine._kp.shape) == k_plan.shape


# ---------------------------------------------------------------------------
# SP005: resharding report from HLO text
# ---------------------------------------------------------------------------


HLO_FIXTURE = """
  %ag = f32[8,4096,4096] all-gather(f32[8,2048,4096] %p0), dimensions={1}
  %aa = f32[1024,1024] all-to-all(f32[1024,1024] %p1), dimensions={0}
  %ar = f32[4096] all-reduce(f32[4096] %p2), replica_groups={}
  %small = f32[16] all-gather(f32[8] %p3), dimensions={0}
  %ags = (f32[8,65536], f32[8,131072]) all-gather-start(f32[8,65536] %p4), dimensions={1}
"""


class TestReshardingReport:
    def test_ranks_top_offenders_and_skips_small(self):
        from accelerate_tpu.analysis.shardplan import resharding_report

        entries = resharding_report(HLO_FIXTURE, min_bytes=1 << 20)
        ops = [e["op"] for e in entries]
        # biggest first; the all-reduce (not a reshard) and the tiny
        # all-gather are absent; the async -start counts its result only
        assert ops[0] == "all-gather"
        assert entries[0]["bytes"] == 8 * 4096 * 4096 * 4
        assert "all-reduce" not in ops
        assert all(e["bytes"] >= 1 << 20 for e in entries)
        assert "all-gather-start" in ops
        start = next(e for e in entries if e["op"] == "all-gather-start")
        assert start["bytes"] == 8 * 131072 * 4

    def test_findings_are_sp005_warnings(self):
        from accelerate_tpu.analysis.shardplan import resharding_findings

        findings = resharding_findings(HLO_FIXTURE, label="step")
        assert findings and all(f.rule == "SP005" for f in findings)
        assert all(f.severity == "warning" for f in findings)
        assert "MB/step" in findings[0].message


# ---------------------------------------------------------------------------
# SP006: manifest piece table vs the plan
# ---------------------------------------------------------------------------


class TestManifestDiff:
    def _plans(self):
        from accelerate_tpu.analysis.shardplan import plan_params

        params, config, rules = _flagship_abstract()
        return plan_params(params, MESH_SIZES, rules=rules)

    def test_sharded_vs_replicated_mismatch_flagged(self):
        from accelerate_tpu.analysis.shardplan import manifest_findings

        manifest = {
            "arrays": {
                "model_0": {
                    # saved replicated, plan shards it -> SP006
                    "embed_tokens": {"spec": "PartitionSpec()"},
                    # saved sharded, plan shards it -> clean
                    "layers.wq": {"spec": "PartitionSpec(None, 'fsdp', 'tp')"},
                    # unrecorded spec -> skipped
                    "norm": {"spec": None},
                    # unknown key -> skipped
                    "not_a_param": {"spec": "PartitionSpec('fsdp',)"},
                }
            }
        }
        findings = manifest_findings(manifest, self._plans())
        assert [f.rule for f in findings] == ["SP006"]
        assert "embed_tokens" in findings[0].subject

    def test_matching_manifest_clean(self):
        from accelerate_tpu.analysis.shardplan import manifest_findings

        manifest = {
            "arrays": {
                "model_0": {
                    "layers.wq": {"spec": "PartitionSpec(None, 'fsdp', 'tp')"},
                    "norm": {"spec": "PartitionSpec()"},
                }
            }
        }
        assert manifest_findings(manifest, self._plans()) == []


# ---------------------------------------------------------------------------
# runtime seams
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_paged_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


class TestEnginePreflight:
    def test_engine_refuses_over_budget(self, tiny_paged_model):
        from accelerate_tpu.serving import EngineConfig, InferenceEngine

        with pytest.raises(ValueError, match="SP004"):
            InferenceEngine(
                tiny_paged_model,
                EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                             hbm_budget_gb=1e-6),
            )

    def test_engine_starts_under_budget_and_reports(self, tiny_paged_model):
        from accelerate_tpu.serving import EngineConfig, InferenceEngine

        engine = InferenceEngine(
            tiny_paged_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         hbm_budget_gb=1.0),
        )
        report = engine.hbm_preflight
        assert report is not None and not report["over"]
        assert report["headroom_bytes"] > 0
        assert report["total_bytes"] == report["params_bytes"] + report["pool_bytes"]
        assert engine.stats()["hbm_preflight"]["over"] is False

    def test_swap_pool_host_bytes_reported_not_budgeted(self, tiny_paged_model):
        """With swap_gb set, the preflight reports the host-DRAM swap tier
        alongside the HBM tiers but never counts it against the budget —
        swapped blocks live on the host (the tier's whole point)."""
        from accelerate_tpu.serving import EngineConfig, InferenceEngine

        engine = InferenceEngine(
            tiny_paged_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         hbm_budget_gb=1.0, swap_gb=0.25),
        )
        report = engine.hbm_preflight
        assert report["swap_pool_host_bytes"] > 0
        assert report["total_bytes"] == report["params_bytes"] + report["pool_bytes"]

    def test_plan_swap_pool_and_analyze_plan_host_tier(self):
        import jax.numpy as jnp

        from accelerate_tpu.analysis.shardplan import analyze_plan, plan_swap_pool

        swap = plan_swap_pool(num_layers=2, num_kv_heads=4, head_dim=16,
                              block_size=8, swap_gb=0.5, dtype="float32")
        per_block = 2 * 4 * 2 * 8 * 4 * 16
        assert swap["bytes_per_block"] == per_block
        assert swap["swap_blocks"] == int(0.5 * (1 << 30)) // per_block
        assert swap["swap_pool_host_bytes"] == swap["swap_blocks"] * per_block

        params = {"w": jnp.zeros((8, 8))}
        kv_pool = dict(num_layers=2, num_kv_heads=4, head_dim=16, num_slots=2,
                       block_size=8, max_seq_len=64, dtype="float32")
        report = analyze_plan(
            params, {"dp": 1}, optimizer="none", kv_pool=kv_pool, swap_gb=0.5
        )
        assert report.host["swap_pool_host_bytes"] == swap["swap_pool_host_bytes"]
        assert report.to_dict()["host"] == report.host
        # host bytes never leak into the per-device HBM sum
        assert report.bytes_per_device == sum(
            l.bytes_per_device for l in report.leaves
        )
        no_swap = analyze_plan(params, {"dp": 1}, optimizer="none", kv_pool=kv_pool)
        assert no_swap.host is None

    def test_auto_num_blocks_math(self):
        from accelerate_tpu.analysis.shardplan import auto_num_blocks

        # 100 MB budget, 40 MB params, 1 MB/block, 5% reserve -> 55 fit
        n, headroom = auto_num_blocks(
            100 << 20, 40 << 20, 1 << 20, full_residency_blocks=1000, min_blocks=4
        )
        assert n == 55
        assert headroom == (100 << 20) - (40 << 20) - n * (1 << 20)
        # full residency caps it
        n2, _ = auto_num_blocks(
            100 << 20, 40 << 20, 1 << 20, full_residency_blocks=10, min_blocks=4
        )
        assert n2 == 10
        with pytest.raises(ValueError, match="SP004"):
            auto_num_blocks(
                42 << 20, 40 << 20, 1 << 20, full_residency_blocks=10, min_blocks=4
            )

    def test_arg_bytes_report_replicated_and_sharded(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from accelerate_tpu.analysis.shardplan import arg_bytes_report

        mesh = _mesh4()
        x = jax.device_put(jnp.zeros((64, 64), jnp.float32), NamedSharding(mesh, P("fsdp", "tp")))
        r = jax.device_put(jnp.zeros((16,), jnp.float32), NamedSharding(mesh, P()))
        host = np.zeros((8,), np.float32)
        predicted, actual = arg_bytes_report(((x, r), host))
        expect = (64 * 64 * 4) // 4 + 16 * 4 + 8 * 4
        assert predicted == expect
        assert actual == expect


class TestCompileFactBytes:
    def test_sanitized_compile_records_carry_predicted_vs_actual(self, tmp_path):
        """The AOT path stamps arg_bytes_predicted/actual onto compile
        facts when the sanitizer is armed; on a single-device replicated
        toy the two models must agree exactly."""
        import io

        import optax

        from accelerate_tpu import Accelerator
        from accelerate_tpu.test_utils import RegressionModel

        acc = Accelerator(project_dir=str(tmp_path), telemetry=True, sanitize=True)
        acc.sanitizer._stream = io.StringIO()
        model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
        try:
            x = np.linspace(-1, 1, 16).astype(np.float32)
            out = model(x=x, y=(2 * x + 3).astype(np.float32))
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()
            compiles = [
                json.loads(line)
                for line in open(acc.telemetry.jsonl_path)
                if '"compile"' in line
            ]
            compiles = [r for r in compiles if r.get("type") == "compile"]
            assert compiles
            stamped = [r for r in compiles if "arg_bytes_predicted" in r]
            assert stamped, compiles
            for r in stamped:
                assert r["arg_bytes_predicted"] == r["arg_bytes_actual"] > 0
        finally:
            acc.end_training()


class TestValidatedWarnsOnce:
    def test_one_shot_warning_names_path_and_axis(self, caplog):
        import logging

        import jax

        from accelerate_tpu.parallel import sharding as sharding_mod
        from jax.sharding import PartitionSpec as P

        mesh = _mesh4()
        sharding_mod._DIVISIBILITY_WARNED.clear()
        params = {"w": np.zeros((10, 6), np.float32)}  # 10 % 4 != 0
        rules = [("w", P(("fsdp", "tp"), None))]
        with caplog.at_level(logging.WARNING, logger=sharding_mod.__name__):
            sharding_mod.infer_param_sharding(params, mesh, rules=rules)
            sharding_mod.infer_param_sharding(params, mesh, rules=rules)
        hits = [
            rec for rec in caplog.records
            if "SP003" in rec.getMessage() and "'w'" in rec.getMessage()
        ]
        assert len(hits) == 1  # once per (path, axis), not once per call
        assert "does not divide" in hits[0].getMessage()


# ---------------------------------------------------------------------------
# the CLI (real subprocess, same pattern as the lint CLI tests)
# ---------------------------------------------------------------------------


class TestShardCheckCLI:
    def _run(self, args):
        return subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "shard-check", *args],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240,
        )

    def test_clean_flagship_plan_exits_0(self):
        proc = self._run(["--preset", "flagship", "--virtual", "1,2,2", "--json"])
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert payload["findings"] == []
        assert set(payload["tiers"]) == {"params", "opt_state", "kv_pool"}
        assert payload["bytes_per_device"] == sum(
            t["bytes_per_device"] for t in payload["tiers"].values()
        )

    def test_dead_rule_exits_2_naming_sp001(self):
        proc = self._run(["--virtual", "1,2,2", "--json",
                          "--extra-rule", "no_such_param=tp"])
        assert proc.returncode == 2, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert {f["rule"] for f in payload["findings"]} == {"SP001"}

    def test_forced_replicated_exits_2_naming_sp002(self):
        proc = self._run(["--virtual", "1,2,2", "--json", "--ignore", "SP001",
                          "--extra-rule", "embed_tokens="])
        assert proc.returncode == 2, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert {f["rule"] for f in payload["findings"]} == {"SP002"}
        assert payload["findings"][0]["subject"] == "embed_tokens"

    def test_non_divisible_exits_2_naming_sp003(self):
        proc = self._run(["--virtual", "dp=1,fsdp=1,tp=7", "--json",
                          "--ignore", "SP001,SP002",
                          "--extra-rule", "embed_tokens=None,tp"])
        assert proc.returncode == 2, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert {f["rule"] for f in payload["findings"]} == {"SP003"}

    def test_over_budget_exits_2_naming_sp004(self):
        proc = self._run(["--preset", "flagship", "--virtual", "1,2,2",
                          "--json", "--hbm-gb", "0.5"])
        assert proc.returncode == 2, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert {f["rule"] for f in payload["findings"]} == {"SP004"}
        assert payload["findings"][0]["detail"]["tiers"]["opt_state"] > 0

    def test_bad_mesh_spec_exits_1(self):
        assert self._run(["--virtual", "bogus=1"]).returncode == 1

    def test_activation_estimate_failure_exits_1_not_silent(self):
        """--seq over max_position_embeddings: the logits tier cannot be
        priced — a usage error, NOT a silently understated exit-0 plan."""
        proc = self._run(["--preset", "flagship", "--virtual", "1,2,2",
                          "--batch", "8", "--seq", "4096"])
        assert proc.returncode == 1, (proc.returncode, proc.stdout[-500:])
        assert "activation estimate failed" in proc.stderr

    def test_list_rules(self):
        proc = self._run(["--list-rules"])
        assert proc.returncode == 0
        for rid in ("SP001", "SP002", "SP003", "SP004", "SP005", "SP006"):
            assert rid in proc.stdout


# ---------------------------------------------------------------------------
# quantized KV pool planning (kv_dtype policy)
# ---------------------------------------------------------------------------


class TestQuantizedKvPlan:
    def test_plan_kv_pool_int8_adds_scale_leaves(self):
        """int8/fp8 dtypes emit the two f32 amax scale leaves beside the
        payload, kv-head dim sharded over tp like the pools."""
        from accelerate_tpu.analysis.shardplan import plan_kv_pool

        kw = dict(num_layers=2, num_kv_heads=4, head_dim=8, num_slots=2,
                  block_size=8, max_seq_len=64, mesh_sizes=MESH_SIZES)
        plans = plan_kv_pool(dtype="int8", **kw)
        assert [p.path for p in plans] == [
            "kv_pool.k", "kv_pool.v", "kv_pool.k_scale", "kv_pool.v_scale"
        ]
        k = next(p for p in plans if p.path == "kv_pool.k")
        ks = next(p for p in plans if p.path == "kv_pool.k_scale")
        nb = 2 * 8 + 1
        assert k.bytes_global == 2 * nb * 8 * 4 * 8 * 1          # int8 payload
        assert ks.bytes_global == 2 * nb * 8 * 4 * 4             # f32 scales
        assert k.bytes_per_device == k.bytes_global // 2         # tp=2
        assert ks.bytes_per_device == ks.bytes_global // 2
        assert "'tp'" in ks.spec
        # fp8 spelling aliases float8_e4m3fn at the same byte cost
        fp8 = plan_kv_pool(dtype="fp8", **kw)
        assert [p.bytes_global for p in fp8] == [p.bytes_global for p in plans]
        assert fp8[0].dtype == "float8_e4m3fn"
        # float dtypes stay two scale-free leaves (the PR 8 behaviour)
        assert len(plan_kv_pool(dtype="bfloat16", **kw)) == 2

    def test_plan_swap_pool_quantized_matches_live_swap_pool(self):
        """plan_swap_pool's per-block bytes at int8 equal the live
        SwapPool's (payload + scale mirrors)."""
        from accelerate_tpu.analysis.shardplan import plan_swap_pool
        from accelerate_tpu.serving import SwapPool

        geom = dict(num_layers=2, num_kv_heads=4, head_dim=8, block_size=8)
        plan = plan_swap_pool(swap_gb=0.001, dtype="int8", **geom)
        live = SwapPool(dtype=np.int8, capacity_gb=0.001, quantized=True, **geom)
        assert plan["bytes_per_block"] == live.bytes_per_block
        assert plan["swap_blocks"] == live.capacity_blocks

    def test_int8_predicted_pool_bytes_match_live_engine_exactly(self, tiny_paged_model):
        """The acceptance invariant at kv_dtype="int8": predicted kv-pool
        tier bytes (payload + scales) == the live sharded engine's
        _kp/_vp/_ks/_vs shard bytes, per device, exactly."""
        from accelerate_tpu.analysis.shardplan import mesh_sizes_of, plan_kv_pool
        from accelerate_tpu.serving import EngineConfig, InferenceEngine

        mesh = _mesh4()
        cfg = tiny_paged_model.config
        geometry = dict(num_slots=2, block_size=8, max_seq_len=64)
        engine = InferenceEngine(
            tiny_paged_model, EngineConfig(kv_dtype="int8", **geometry), mesh=mesh
        )
        plans = plan_kv_pool(
            num_layers=cfg.num_hidden_layers,
            num_kv_heads=cfg.num_key_value_heads,
            head_dim=cfg.head_dim,
            mesh_sizes=mesh_sizes_of(mesh),
            dtype="int8",
            **geometry,
        )
        dev0 = engine._kp.addressable_shards[0].device
        live = sum(
            int(s.data.nbytes)
            for arr in (engine._kp, engine._vp, engine._ks, engine._vs)
            for s in arr.addressable_shards
            if s.device == dev0
        )
        assert live == sum(p.bytes_per_device for p in plans)

    def test_auto_blocks_capacity_ratio_int8_vs_bf16(self):
        """At equal HBM budget the int8 pool holds ~2x the blocks of the
        bf16 pool (2*hd / (hd+4) — 1.94x at the flagship's hd=128): the
        auto_num_blocks sizing this CLI flag and bench ratio both use."""
        from accelerate_tpu.analysis.shardplan import auto_num_blocks, plan_kv_pool

        sizes = {ax: 1 for ax in MESH_SIZES}
        per_block = {}
        for dtype in ("bfloat16", "int8"):
            per_block[dtype] = sum(
                p.bytes_per_device
                for p in plan_kv_pool(
                    num_layers=16, num_kv_heads=12, head_dim=128, num_slots=1,
                    block_size=16, max_seq_len=512, num_blocks=1,
                    mesh_sizes=sizes, dtype=dtype,
                )
            )
        budget, params = 8 << 30, 2 << 30
        blocks = {
            d: auto_num_blocks(budget, params, pb, full_residency_blocks=10**9,
                               min_blocks=2)[0]
            for d, pb in per_block.items()
        }
        ratio = blocks["int8"] / blocks["bfloat16"]
        assert ratio >= 1.8
        assert abs(ratio - 2 * 128 / (128 + 4)) < 0.01

    def test_shard_check_cli_kv_dtype_json(self):
        """--kv-dtype int8 flows through the real CLI: the JSON report's
        kv_pool tier carries the scale leaves."""
        proc = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
             "shard-check", "--preset", "tiny", "--virtual", "dp=1,fsdp=1,tp=1",
             "--kv-dtype", "int8", "--json", "--leaves"],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout)
        paths = [l["path"] for l in report["leaves"] if l["tier"] == "kv_pool"]
        assert "kv_pool.k_scale" in paths and "kv_pool.v_scale" in paths
        assert next(
            l for l in report["leaves"] if l["path"] == "kv_pool.k"
        )["dtype"] == "int8"
