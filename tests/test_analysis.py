"""The static-analysis pass + runtime sanitizer (accelerate_tpu/analysis/).

Golden fixture corpus: ONE positive and ONE negative snippet per lint rule
— every positive must fire exactly its rule, every negative must be clean
(zero false positives is the bar that makes `make lint` a gate instead of
noise). Plus: the jaxpr/HLO analyzers against a toy jitted step, digest
stability, suppression syntax, the CLI's exit codes, and the sanitizer's
runtime reports.
"""

import io
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from accelerate_tpu.analysis.engine import (
    lint_paths,
    lint_source,
    normalize_rule_ids,
)
from accelerate_tpu.analysis.rules import RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# golden corpus: {rule: (positive_snippet, negative_snippet)}
# ---------------------------------------------------------------------------

CORPUS = {
    "TPU001": (
        """
import jax

@jax.jit
def train_step(params, x):
    loss = (x * params).sum()
    v = loss.item()
    return v
""",
        """
import jax

@jax.jit
def train_step(params, x):
    return (x * params).sum()

def outer(model, batch):
    loss = train_step(model, batch)
    return loss.item()  # outside the traced function: fine
""",
    ),
    "TPU002": (
        """
import jax

@jax.jit
def train_step(params, x):
    return float((x * params).sum())
""",
        """
import jax

@jax.jit
def train_step(params, x):
    scale = float(0.5)  # cast of a literal, not a traced value
    return (x * params).sum() * scale
""",
    ),
    "TPU003": (
        """
import jax
import numpy as np

@jax.jit
def train_step(params, x):
    host = np.asarray(x)
    return host.sum()
""",
        """
import jax
import jax.numpy as jnp

@jax.jit
def train_step(params, x):
    return jnp.asarray(x).sum()  # jnp stays traced
""",
    ),
    "TPU004": (
        """
import jax

@jax.jit
def train_step(params, x):
    loss = (x * params).sum()
    if loss > 1.0:
        loss = loss * 0.5
    return loss
""",
        """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("training",))
def train_step(params, x, training):
    if training:  # static arg: branch resolved at trace time by design
        x = x * 2
    return (x * params).sum()
""",
    ),
    "TPU005": (
        """
import jax

@jax.jit
def train_step(params, x):
    loss = (x * params).sum()
    print(loss)
    return loss
""",
        """
import jax

@jax.jit
def train_step(params, x):
    loss = (x * params).sum()
    jax.debug.print("loss {l}", l=loss)
    return loss
""",
    ),
    "TPU006": (
        """
import time
import jax

@jax.jit
def train_step(params, x):
    t = time.time()
    return (x * params).sum() + t
""",
        """
import time
import jax

@jax.jit
def train_step(params, x, now):
    return (x * params).sum() + now  # timestamp passed in as an input

def loop(params, x):
    now = time.time()  # wall clock OUTSIDE the trace
    return train_step(params, x, now)
""",
    ),
    "TPU007": (
        """
import random
import jax

@jax.jit
def train_step(params, x):
    noise = random.random()
    return (x * params).sum() + noise
""",
        """
import jax

@jax.jit
def train_step(params, x, key):
    noise = jax.random.normal(key, x.shape)
    return ((x + noise) * params).sum()
""",
    ),
    "TPU008": (
        """
import time
import jax

def bench(fn, x):
    jitted = jax.jit(fn)
    t0 = time.perf_counter()
    out = jitted(x)
    return time.perf_counter() - t0
""",
        """
import time
import jax

def bench(fn, x):
    jitted = jax.jit(fn)
    t0 = time.perf_counter()
    out = jitted(x)
    jax.block_until_ready(out)
    return time.perf_counter() - t0
""",
    ),
    "TPU009": (
        """
import jax

@jax.jit
def train_step(x, history=[]):
    return x * 2
""",
        """
import jax

@jax.jit
def train_step(x, scale=2.0):
    return x * scale
""",
    ),
    "TPU010": (
        """
import jax

step = jax.jit(lambda x, i: x * i)

def loop(x):
    for i in range(100):
        x = step(x, i)
    return x
""",
        """
import jax
import jax.numpy as jnp

step = jax.jit(lambda x, i: x * i)

def loop(x):
    for i in range(100):
        x = step(x, jnp.asarray(i))  # array-wrapped: one trace
    return x
""",
    ),
    "TPU011": (
        """
import jax
from jax import lax

@jax.jit
def train_step(params, grads):
    if (grads * grads).sum() > 1.0:
        grads = lax.psum(grads, "dp")
    return params - grads
""",
        """
import jax
from jax import lax

@jax.jit
def train_step(params, grads):
    grads = lax.psum(grads, "dp")  # unconditional: same order everywhere
    big = (grads * grads).sum() > 1.0
    return params - grads * big
""",
    ),
    "TPU012": (
        """
from jax.sharding import PartitionSpec as P

PARTITION_RULES = [
    ("wq", P(None, "model")),
]
""",
        """
from jax.sharding import PartitionSpec as P

PARTITION_RULES = [
    ("wq", P(None, "tp")),
    ("embed", P("tp", "fsdp")),
]
""",
    ),
}


class TestGoldenCorpus:
    @pytest.mark.parametrize("rule_id", sorted(CORPUS))
    def test_positive_fires(self, rule_id):
        positive, _ = CORPUS[rule_id]
        findings = lint_source(positive, f"{rule_id}_pos.py")
        assert rule_id in {f.rule for f in findings}, (
            f"{rule_id} did not fire on its positive fixture: "
            f"{[f.rule for f in findings]}"
        )

    @pytest.mark.parametrize("rule_id", sorted(CORPUS))
    def test_negative_clean(self, rule_id):
        _, negative = CORPUS[rule_id]
        findings = lint_source(negative, f"{rule_id}_neg.py")
        assert findings == [], (
            f"false positive(s) on the {rule_id} negative fixture: "
            f"{[(f.rule, f.line) for f in findings]}"
        )

    def test_every_rule_has_fixture_and_metadata(self):
        assert set(CORPUS) == set(RULES)
        for rule in RULES.values():
            assert rule.severity in ("error", "warning")
            assert rule.summary and rule.fixit

    @pytest.mark.parametrize(
        "import_line, call",
        [
            ("from jax import random", "random.normal(key, x.shape)"),
            ("import jax.random as random", "random.normal(key, x.shape)"),
            ("from jax import random as jrandom", "jrandom.normal(key, x.shape)"),
        ],
    )
    def test_tpu007_exempts_jax_random_aliases(self, import_line, call):
        """``from jax import random`` is the idiom TPU007's own fixit
        recommends — it must not trip the host-RNG rule."""
        src = f"""
import jax
{import_line}

@jax.jit
def train_step(params, x, key):
    noise = {call}
    return ((x + noise) * params).sum()
"""
        assert lint_source(src, "jax_alias.py") == []

    def test_tpu010_enumerate_payload_not_flagged(self):
        """`for step, batch in enumerate(loader)` is the canonical training
        loop — the payload element is whatever the iterable yields, not a
        loop-varying Python scalar; only the index is."""
        src = """
import jax

train_step = jax.jit(lambda params, batch: params)

def loop(params, loader):
    for step, batch in enumerate(loader):
        params = train_step(params, batch)
    return params
"""
        assert lint_source(src, "enum.py") == []

    def test_tpu010_enumerate_index_still_flagged(self):
        src = """
import jax

train_step = jax.jit(lambda params, i: params * i)

def loop(params, loader):
    for step, batch in enumerate(loader):
        params = train_step(params, step)
    return params
"""
        assert {f.rule for f in lint_source(src, "enum_idx.py")} == {"TPU010"}

    def test_tpu012_local_mesh_axes_exempt(self):
        """A file that constructs its own Mesh with custom axis names may
        name them in PartitionSpec — the rule only polices axes no mesh in
        sight defines."""
        src = """
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(np.array(devices).reshape(2, 2), ("x", "y"))
spec = P("x", "y")
"""
        assert lint_source(src, "custom_mesh.py") == []

    def test_tpu012_make_mesh_axes_exempt(self):
        """jax.make_mesh is the modern constructor — axes it declares are
        just as legitimate as Mesh(...)'s."""
        src = """
import jax
from jax.sharding import PartitionSpec as P

mesh = jax.make_mesh((2, 2), ("x", "y"))
spec = P("x", "y")
"""
        assert lint_source(src, "make_mesh.py") == []

    def test_tpu012_multi_axis_tuple_entry_checked(self):
        src = """
from jax.sharding import PartitionSpec as P

spec = P(("dp", "model"), None)
"""
        assert {f.rule for f in lint_source(src, "tuple_axis.py")} == {"TPU012"}

    def test_tpu007_still_fires_on_stdlib_random(self):
        src = """
import jax
import random

@jax.jit
def train_step(params, x):
    return (x * params).sum() * random.random()
"""
        assert {f.rule for f in lint_source(src, "host_rng.py")} == {"TPU007"}

    def test_tpu008_in_loop_timer_fires(self):
        """Per-iteration timing is the canonical real-world form of the
        unfenced-timing bug — the timer start lives inside the loop body,
        not at the function's top level."""
        src = """
import time
import jax

def bench(fn, x, times):
    jitted = jax.jit(fn)
    for i in range(10):
        t0 = time.perf_counter()
        out = jitted(x)
        times.append(time.perf_counter() - t0)
    return times
"""
        findings = lint_source(src, "loop_timer.py")
        assert "TPU008" in {f.rule for f in findings}

    def test_tpu008_in_loop_timer_fenced_clean(self):
        src = """
import time
import jax

def bench(fn, x, times):
    jitted = jax.jit(fn)
    for i in range(10):
        t0 = time.perf_counter()
        out = jitted(x)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return times
"""
        assert lint_source(src, "loop_timer_ok.py") == []

    def test_tpu008_module_level_script_fires(self):
        """Script-level timing with no enclosing def — benchmarks are often
        written this way, so the module body must be scanned too."""
        src = """
import time
import jax
import jax.numpy as jnp

jitted = jax.jit(lambda x: x * 2)
x = jnp.ones((8,))
t0 = time.perf_counter()
out = jitted(x)
elapsed = time.perf_counter() - t0
"""
        findings = lint_source(src, "script_timer.py")
        assert "TPU008" in {f.rule for f in findings}

    def test_tpu011_local_lax_ops_not_flagged(self):
        """lax.gather / lax.broadcast / lax.reduce are LOCAL ops (indexing,
        shape broadcast, monoid reduce) — they must not trip the
        collective-order rule even under traced control flow."""
        src = """
import jax
import jax.numpy as jnp
from jax import lax

@jax.jit
def train_step(params, x):
    if x.sum() > 0:
        y = lax.broadcast(x, (2,))
        z = lax.reduce(x, 0.0, lax.add, (0,))
        return params + y.sum() + z
    return params
"""
        findings = lint_source(src, "local_lax.py")
        assert "TPU011" not in {f.rule for f in findings}

    def test_tpu011_eager_short_names_need_ops_root(self):
        """`accelerator.gather(...)` under traced control IS the eager
        collective; a bare `gather(...)` on some unrelated object is not."""
        src = """
import jax

@jax.jit
def train_step(accelerator, x):
    if x.sum() > 0:
        x = accelerator.gather(x)
    return x
"""
        findings = lint_source(src, "eager_gather.py")
        assert "TPU011" in {f.rule for f in findings}


class TestSuppression:
    POSITIVE = CORPUS["TPU001"][0]

    def test_inline_suppression(self):
        src = self.POSITIVE.replace(
            "v = loss.item()", "v = loss.item()  # tpu-lint: ignore[TPU001] — test"
        )
        assert lint_source(src, "s.py") == []

    def test_line_above_suppression(self):
        src = self.POSITIVE.replace(
            "    v = loss.item()",
            "    # tpu-lint: ignore[TPU001] — reason\n    v = loss.item()",
        )
        assert lint_source(src, "s.py") == []

    def test_skip_file(self):
        src = "# tpu-lint: skip-file\n" + self.POSITIVE
        assert lint_source(src, "s.py") == []

    def test_wrong_id_does_not_suppress(self):
        src = self.POSITIVE.replace(
            "v = loss.item()", "v = loss.item()  # tpu-lint: ignore[TPU005]"
        )
        assert {f.rule for f in lint_source(src, "s.py")} == {"TPU001"}

    def test_select_ignore(self):
        findings = lint_source(self.POSITIVE, "s.py", select={"TPU005"})
        assert findings == []
        findings = lint_source(self.POSITIVE, "s.py", ignore={"TPU001"})
        assert findings == []

    def test_normalize_rule_ids(self):
        assert normalize_rule_ids("TPU001, tpu4") == {"TPU001", "TPU004"}
        assert normalize_rule_ids(None) is None
        with pytest.raises(ValueError):
            normalize_rule_ids("TPU999")

    def test_syntax_error_is_a_finding(self):
        findings = lint_source("def broken(:\n", "bad.py")
        assert [f.rule for f in findings] == ["TPU000"]
        assert findings[0].severity == "error"


class TestSelfApplication:
    def test_examples_and_benchmarks_clean(self):
        """The self-application gate `make lint` enforces: the shipped
        examples/ + benchmarks/ tree has zero findings (true positives
        fixed, intentional patterns suppressed with reasons)."""
        findings, files = lint_paths(
            [os.path.join(REPO, "examples"), os.path.join(REPO, "benchmarks")]
        )
        assert files > 20
        assert findings == [], [(f.path, f.line, f.rule) for f in findings]


# ---------------------------------------------------------------------------
# jaxpr/HLO analyzers
# ---------------------------------------------------------------------------


class TestDonationChecker:
    def test_flags_non_donated_aliasable_input(self):
        import jax.numpy as jnp

        from accelerate_tpu.analysis.compiled import donation_report

        def step(params, grads):
            return params - 0.1 * grads, (grads * grads).sum()

        params = jnp.ones((64, 64), jnp.float32)
        grads = jnp.ones((64, 64), jnp.float32)
        report = donation_report(step, (params, grads), donate_argnums=(), label="t")
        # new_params matches BOTH inputs' aval but only one output slot
        # exists, so exactly one candidate is excused by it
        assert report["wasted_bytes"] == 64 * 64 * 4
        assert len(report["candidates"]) == 1
        assert report["candidates"][0]["arg"].startswith("args[0]")

    def test_donated_input_consumes_the_match(self):
        import jax.numpy as jnp

        from accelerate_tpu.analysis.compiled import donation_report

        def step(params, grads):
            return params - 0.1 * grads, (grads * grads).sum()

        params = jnp.ones((8, 8), jnp.float32)
        grads = jnp.ones((8, 8), jnp.float32)
        report = donation_report(step, (params, grads), donate_argnums=(0,), label="t")
        assert report["wasted_bytes"] == 0
        assert report["candidates"] == []

    def test_no_match_no_report(self):
        import jax.numpy as jnp

        from accelerate_tpu.analysis.compiled import donation_report

        def fwd(x):
            return x.sum()

        report = donation_report(fwd, (jnp.ones((16,), jnp.float32),))
        assert report["wasted_bytes"] == 0


class TestRecompileFingerprinter:
    def test_names_the_changed_argument(self):
        from accelerate_tpu.analysis.compiled import (
            RecompileFingerprinter,
            format_signature_diff,
            signature_entries,
        )

        fp = RecompileFingerprinter()
        a16 = {"x": np.zeros((16,), np.float32), "y": np.zeros((4,), np.int32)}
        a24 = {"x": np.zeros((24,), np.float32), "y": np.zeros((4,), np.int32)}
        h1, diff1 = fp.note("step", signature_entries(a16))
        assert diff1 is None
        h2, diff2 = fp.note("step", signature_entries(a16))
        assert h2 == h1 and diff2 is None  # exact repeat: no diff
        h3, diff3 = fp.note("step", signature_entries(a24))
        assert h3 != h1 and diff3 is not None
        changed = {c["arg"] for c in diff3["changed"]}
        assert any("'x'" in c for c in changed), changed
        assert all("'y'" not in c for c in changed), changed
        text = format_signature_diff(diff3)
        assert "(16,):float32 -> (24,):float32" in text

    def test_structure_change_reported(self):
        from accelerate_tpu.analysis.compiled import (
            RecompileFingerprinter,
            signature_entries,
        )

        fp = RecompileFingerprinter()
        fp.note("step", signature_entries({"x": np.zeros(3)}))
        _, diff = fp.note(
            "step", signature_entries({"x": np.zeros(3), "extra": np.zeros(1)})
        )
        assert diff is not None and any("extra" in p for p in diff["added"])


class TestCollectiveDigest:
    HLO_A = """
  %ar = f32[128] all-reduce(f32[128] %p0), replica_groups={}
  %ag = f32[256] all-gather(f32[128] %p1), dimensions={0}
"""
    HLO_B = """
  %ag = f32[256] all-gather(f32[128] %p1), dimensions={0}
  %ar = f32[128] all-reduce(f32[128] %p0), replica_groups={}
"""

    def test_same_text_same_digest(self):
        from accelerate_tpu.analysis.compiled import collective_digest

        d1, seq1 = collective_digest(self.HLO_A)
        d2, seq2 = collective_digest(self.HLO_A)
        assert d1 == d2 and seq1 == seq2
        assert len(seq1) == 2 and seq1[0].startswith("all-reduce")

    def test_reordered_collectives_change_digest(self):
        from accelerate_tpu.analysis.compiled import collective_digest

        da, _ = collective_digest(self.HLO_A)
        db, _ = collective_digest(self.HLO_B)
        assert da != db

    def test_real_program_digest_is_stable(self):
        """Same jitted program lowered twice -> identical digest; the
        digest walks REAL compiled HLO, not just the fixture strings."""
        import jax
        import jax.numpy as jnp

        from accelerate_tpu.analysis.compiled import collective_digest

        def fn(x):
            return (x * 2).sum()

        x = jnp.ones((32,), jnp.float32)
        t1 = jax.jit(fn).lower(x).compile().as_text()
        t2 = jax.jit(fn).lower(x).compile().as_text()
        assert collective_digest(t1)[0] == collective_digest(t2)[0]

    def test_host_digest_files_round_trip_and_diff(self, tmp_path):
        from accelerate_tpu.analysis.compiled import (
            diff_host_digests,
            read_host_digests,
            write_host_digest,
        )

        d = str(tmp_path)
        write_host_digest(d, 0, "fused_step", "aaaa", ["all-reduce f32[4]"])
        write_host_digest(d, 1, "fused_step", "bbbb", ["all-gather f32[4]"])
        write_host_digest(d, 2, "fused_step", "aaaa", ["all-reduce f32[4]"])
        write_host_digest(d, 0, "forward", "cccc", [])
        digests = read_host_digests(d)
        assert set(digests) == {0, 1, 2}
        diffs = diff_host_digests(digests)
        assert len(diffs) == 1
        assert diffs[0]["label"] == "fused_step"
        assert diffs[0]["divergent_hosts"] == [1]  # minority named
        assert diffs[0]["tie"] is False

    def test_two_host_split_is_a_tie_not_a_minority(self):
        """With exactly 2 hosts disagreeing 1-1 there is no majority to
        presume correct — both hosts are named rather than arbitrarily
        blaming whichever digest iterates second."""
        from accelerate_tpu.analysis.compiled import diff_host_digests

        digests = {
            0: {"fused_step": {"digest": "aaaa"}},
            1: {"fused_step": {"digest": "bbbb"}},
        }
        diffs = diff_host_digests(digests)
        assert len(diffs) == 1
        assert diffs[0]["tie"] is True
        assert diffs[0]["divergent_hosts"] == [0, 1]

    def test_monitor_surfaces_divergence(self, tmp_path):
        from accelerate_tpu.analysis.compiled import write_host_digest
        from accelerate_tpu.diagnostics.monitor import collect_status, render_status

        d = str(tmp_path)
        write_host_digest(d, 0, "fused_step", "aaaa", [])
        write_host_digest(d, 1, "fused_step", "bbbb", [])
        status = collect_status(d)
        assert status["collective_divergence"]
        rendered = render_status(status)
        assert "COLLECTIVE ORDER DIVERGES" in rendered
        # 2 hosts split 1-1: no majority exists, so the report says so
        # instead of arbitrarily blaming one host
        assert "no majority" in rendered
        assert "hosts 0, 1" in rendered


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------


class TestSanitizerRuntime:
    def test_shape_unstable_loop_names_the_argument(self, tmp_path):
        """The acceptance scenario: a deliberately shape-unstable toy loop
        under Accelerator(sanitize=True) produces a stderr/telemetry
        report NAMING the offending argument."""
        import optax

        from accelerate_tpu import Accelerator
        from accelerate_tpu.test_utils import RegressionModel

        acc = Accelerator(project_dir=str(tmp_path), telemetry=True, sanitize=True)
        stream = io.StringIO()
        acc.sanitizer._stream = stream
        model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
        try:
            for n in (16, 16, 24):
                x = np.linspace(-1, 1, n).astype(np.float32)
                out = model(x=x, y=(2 * x + 3).astype(np.float32))
                acc.backward(out.loss)
                opt.step()
                opt.zero_grad()
            assert acc.sanitizer.counts["retrace"] == 1
            text = stream.getvalue()
            assert "re-traced" in text
            assert "'inputs'" in text and "(16,):float32 -> (24,):float32" in text
            # the compile record in the telemetry trail carries the diff too
            records = [
                json.loads(line) for line in open(acc.telemetry.jsonl_path)
            ]
            compiles = [r for r in records if r["type"] == "compile"]
            assert any(r.get("changed_args") for r in compiles)
            events = [
                r for r in records
                if r["type"] == "event" and r["kind"] == "sanitizer_retrace"
            ]
            assert events and "'inputs'" in events[0]["message"]
            # per-host collective digest file written
            from accelerate_tpu.analysis.compiled import read_host_digests

            assert 0 in read_host_digests(acc.logging_dir)
        finally:
            acc.end_training()

    def test_nan_loss_probe(self, tmp_path):
        import optax

        from accelerate_tpu import Accelerator
        from accelerate_tpu.test_utils import RegressionModel

        acc = Accelerator(project_dir=str(tmp_path), sanitize=True)
        stream = io.StringIO()
        acc.sanitizer._stream = stream
        model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
        try:
            x = np.array([np.nan] * 8, np.float32)
            out = model(x=x, y=np.ones(8, np.float32))
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()
            assert acc.sanitizer.counts["nonfinite_loss"] >= 1
            assert "loss is nan" in stream.getvalue()
        finally:
            acc.end_training()

    def test_disabled_path_is_one_global_read(self):
        from accelerate_tpu.analysis.sanitizer import (
            NULL_SANITIZER,
            get_active_sanitizer,
            set_active_sanitizer,
        )

        set_active_sanitizer(None)
        assert get_active_sanitizer() is NULL_SANITIZER
        assert not get_active_sanitizer()

    def test_report_limit_caps_stderr(self):
        from accelerate_tpu.analysis.sanitizer import Sanitizer

        stream = io.StringIO()
        san = Sanitizer(max_reports=2, stream=stream)
        for i in range(5):
            san._emit("retrace", f"r{i}")
        printed = stream.getvalue().count("TPU-SANITIZER[retrace]")
        assert printed == 3  # 2 reports + 1 "limit reached" line
        assert san.counts["retrace"] == 5


class TestEngineRetraceMessage:
    def test_decode_retrace_names_argument_and_raises_under_sanitizer(self):
        """Unit-level: the engine's one-executable watchdog composes the
        fingerprint diff into the failure message (acceptance: 'the
        serving engine's re-trace assertion failure message now includes
        that fingerprint diff')."""
        from accelerate_tpu.analysis.sanitizer import Sanitizer, set_active_sanitizer
        from accelerate_tpu.serving.engine import InferenceEngine

        engine = InferenceEngine.__new__(InferenceEngine)  # no model needed
        engine._decode_traces = 1
        engine._decode_traces_seen = 0
        engine._decode_sig = None
        engine.retrace_report = None
        sig1 = (("block_tables", (8, 32), "int32"), ("toks", (8, 1), "int32"))
        sig2 = (("block_tables", (8, 64), "int32"), ("toks", (8, 1), "int32"))
        engine._check_one_executable(sig1)  # first trace: baseline
        assert engine.retrace_report is None
        engine._decode_traces = 2  # a second trace happened
        try:
            set_active_sanitizer(Sanitizer(stream=io.StringIO()))
            with pytest.raises(RuntimeError) as err:
                engine._check_one_executable(sig2)
        finally:
            set_active_sanitizer(None)
        message = str(err.value)
        assert "re-traced" in message
        assert "block_tables" in message
        assert "(8, 32):int32 -> (8, 64):int32" in message
        assert engine.retrace_report == message


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class TestLintCLI:
    def _run(self, args, cwd=None):
        return subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", "lint", *args],
            capture_output=True, text=True, cwd=cwd or REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=240,
        )

    def test_json_exit_2_on_errors(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(CORPUS["TPU001"][0])
        proc = self._run(["--json", str(bad)])
        assert proc.returncode == 2, proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["errors"] == 1
        assert payload["findings"][0]["rule"] == "TPU001"
        assert payload["findings"][0]["severity"] == "error"

    def test_exit_0_on_clean_and_warning_only(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text(CORPUS["TPU001"][1])
        assert self._run([str(clean)]).returncode == 0
        warn = tmp_path / "warn.py"
        warn.write_text(CORPUS["TPU008"][0])  # TPU008 is warning severity
        proc = self._run(["--json", str(warn)])
        assert proc.returncode == 0
        assert json.loads(proc.stdout)["warnings"] == 1

    def test_exit_1_on_missing_path(self):
        assert self._run(["/nonexistent/path.py"]).returncode == 1

    def test_select_filters(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(CORPUS["TPU001"][0])
        proc = self._run(["--json", "--select", "TPU005", str(bad)])
        assert proc.returncode == 0
        assert json.loads(proc.stdout)["findings"] == []
