"""Attention stack: Pallas flash kernel (interpret mode), blockwise
fallback, and the three context-parallel modes on the 8-device CPU mesh.

Oracle is the naive einsum attention (``ops/layers.py``). Mirrors the
reference's closed-form collective checks (``test_utils/scripts/test_ops.py``)
in spirit: every distributed path must equal its single-device answer.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

jax.config.update("jax_default_matmul_precision", "highest")

from accelerate_tpu.ops.attention import AttentionContext, attention, attention_context
from accelerate_tpu.ops.flash_attention import blockwise_attention, flash_attention
from accelerate_tpu.ops.layers import causal_mask, dot_product_attention
from accelerate_tpu.parallel.context import context_parallel_attention

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _make_qkv(b=2, s=128, h=4, d=32, n_kv=None, seed=0):
    rng = np.random.default_rng(seed)
    n_kv = n_kv or h
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, n_kv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, n_kv, d)), jnp.float32)
    return q, k, v


def _oracle(q, k, v, segment_mask=None, causal=True):
    s, skv = q.shape[1], k.shape[1]
    mask = jnp.ones((s, skv), bool)
    if causal:
        mask = causal_mask(s, skv)
    mask = mask[None, None]
    if segment_mask is not None:
        mask = mask & segment_mask[:, None, None, :].astype(bool)
    return dot_product_attention(q, k, v, mask=mask)


class TestFlashKernel:
    def test_forward_causal(self):
        q, k, v = _make_qkv()
        out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64, interpret=True)
        np.testing.assert_allclose(out, _oracle(q, k, v), atol=2e-5)

    def test_forward_non_causal(self):
        q, k, v = _make_qkv()
        out = flash_attention(q, k, v, causal=False, block_q=64, block_kv=64, interpret=True)
        np.testing.assert_allclose(out, _oracle(q, k, v, causal=False), atol=2e-5)

    def test_forward_segment_mask(self):
        q, k, v = _make_qkv()
        rng = np.random.default_rng(1)
        mask = jnp.asarray(rng.random((2, 128)) > 0.3).at[:, 0].set(True)
        out = flash_attention(q, k, v, segment_mask=mask, causal=True, interpret=True)
        np.testing.assert_allclose(out, _oracle(q, k, v, segment_mask=mask), atol=2e-5)

    def test_forward_unpadded_seq(self):
        # seq not a multiple of the block: exercises pad + bias masking
        q, k, v = _make_qkv(s=100)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64, interpret=True)
        np.testing.assert_allclose(out, _oracle(q, k, v), atol=2e-5)

    def test_gqa(self):
        q, k, v = _make_qkv(h=8, n_kv=2)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        rep_k = jnp.repeat(k, 4, axis=2)
        rep_v = jnp.repeat(v, 4, axis=2)
        np.testing.assert_allclose(out, _oracle(q, rep_k, rep_v), atol=2e-5)

    def test_gradients(self):
        q, k, v = _make_qkv(s=128)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=True, interpret=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (_oracle(q, k, v) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            scale = float(jnp.abs(b).max())
            np.testing.assert_allclose(a, b, atol=2e-4 * max(scale, 1.0))

    def test_gradients_fully_masked_rows(self):
        # Left-padded mask + causal: the first query rows see zero valid keys,
        # so lse is the sentinel NEG_INF and the backward must zero p rather
        # than evaluate exp(NEG_INF - NEG_INF) = 1 (regression: grads were
        # garbage for padded batches).
        q, k, v = _make_qkv(s=128)
        mask = jnp.ones((2, 128), bool).at[:, :48].set(False)

        # fully-masked rows must resolve to output 0, not mean(v)
        out_f = flash_attention(q, k, v, segment_mask=mask, causal=True, interpret=True)
        out_b = blockwise_attention(q, k, v, segment_mask=mask, causal=True)
        assert float(jnp.abs(out_f[:, :48]).max()) == 0.0
        assert float(jnp.abs(out_b[:, :48]).max()) == 0.0

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, segment_mask=mask, causal=True, interpret=True) ** 2).sum()

        def loss_block(q, k, v):
            return (blockwise_attention(q, k, v, segment_mask=mask, causal=True) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gb):
            scale = float(jnp.abs(b).max())
            np.testing.assert_allclose(a, b, atol=2e-4 * max(scale, 1.0))
        assert all(bool(jnp.isfinite(g).all()) for g in gf)

    def test_bf16(self):
        q, k, v = _make_qkv()
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        out = flash_attention(q, k, v, causal=True, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = _oracle(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
        np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2)


class TestBlockwise:
    def test_forward_and_grad(self):
        q, k, v = _make_qkv(s=192)
        rng = np.random.default_rng(1)
        mask = jnp.asarray(rng.random((2, 192)) > 0.3).at[:, 0].set(True)
        out = blockwise_attention(q, k, v, segment_mask=mask, causal=True, block_kv=64)
        np.testing.assert_allclose(out, _oracle(q, k, v, segment_mask=mask), atol=2e-5)

        def loss_bw(q, k, v):
            return (blockwise_attention(q, k, v, segment_mask=mask, block_kv=64) ** 2).sum()

        def loss_ref(q, k, v):
            return (_oracle(q, k, v, segment_mask=mask) ** 2).sum()

        gb = jax.grad(loss_bw, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gb, gr):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_gqa(self):
        q, k, v = _make_qkv(h=8, n_kv=4)
        out = blockwise_attention(q, k, v, causal=True, block_kv=64)
        ref = _oracle(q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2))
        np.testing.assert_allclose(out, ref, atol=2e-5)


def _cp_mesh(cp=4):
    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    return build_mesh(MeshPlugin(dp=-1, cp=cp))


@pytest.mark.parametrize("mode", ["ring", "ulysses", "allgather"])
class TestContextParallel:
    def test_matches_dense(self, mode):
        mesh = _cp_mesh(cp=4)
        q, k, v = _make_qkv(b=2, s=256, h=4, d=32)
        rng = np.random.default_rng(2)
        mask = jnp.asarray(rng.random((2, 256)) > 0.2).at[:, 0].set(True)

        fn = jax.jit(
            functools.partial(
                context_parallel_attention, mesh=mesh, mode=mode, causal=True
            )
        )
        out = fn(q, k, v, mask)
        np.testing.assert_allclose(out, _oracle(q, k, v, segment_mask=mask), atol=3e-5)

    def test_gradients_match_dense(self, mode):
        mesh = _cp_mesh(cp=4)
        q, k, v = _make_qkv(b=1, s=128, h=4, d=16, seed=3)

        def loss_cp(q, k, v):
            out = context_parallel_attention(q, k, v, None, mesh=mesh, mode=mode)
            return (out.astype(jnp.float32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_oracle(q, k, v) ** 2).sum()

        gc = jax.jit(jax.grad(loss_cp, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gc, gr):
            np.testing.assert_allclose(a, b, atol=2e-4)

    def test_non_causal(self, mode):
        mesh = _cp_mesh(cp=4)
        q, k, v = _make_qkv(b=2, s=128, h=4, d=16, seed=4)
        out = jax.jit(
            functools.partial(
                context_parallel_attention, mesh=mesh, mode=mode, causal=False
            )
        )(q, k, v, None)
        np.testing.assert_allclose(out, _oracle(q, k, v, causal=False), atol=3e-5)


class TestDispatcher:
    def test_default_is_blockwise_on_cpu(self):
        q, k, v = _make_qkv()
        out = attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, _oracle(q, k, v), atol=2e-5)

    def test_cp_context_routes_to_ring(self):
        mesh = _cp_mesh(cp=4)
        q, k, v = _make_qkv(s=256)
        with attention_context(mesh=mesh, cp_mode="ring"):
            out = jax.jit(lambda *a: attention(*a, causal=True))(q, k, v)
        np.testing.assert_allclose(out, _oracle(q, k, v), atol=3e-5)

    def test_accelerator_sets_context(self):
        from accelerate_tpu import Accelerator, MeshPlugin
        from accelerate_tpu.ops.attention import get_attention_context

        # fsdp batch axis: the real ring survives on the CPU backend (a
        # dp>1 mesh would downgrade to allgather — XLA CPU deadlock guard,
        # covered by test_config_plugins)
        acc = Accelerator(mesh_plugin=MeshPlugin(dp=1, fsdp=4, cp=2))
        ctx = get_attention_context()
        assert ctx.cp_mode == "ring"
        assert dict(ctx.mesh.shape)["cp"] == 2


class TestRingFlash:
    """Flash-kernel ring (ops/ring_flash.py): forward + whole-ring custom
    VJP must match the einsum ring body (and thus the dense oracle) in
    interpret mode."""

    def _sharded(self, use_flash, q, k, v, mask, causal=True):
        from functools import partial

        from accelerate_tpu.parallel.context import ring_attention_local

        mesh = _cp_mesh(cp=4)
        P_ = jax.sharding.PartitionSpec

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P_(None, "cp", None, None),) * 3 + (P_(None, "cp"),),
            out_specs=P_(None, "cp", None, None),
            check_vma=False,
        )
        def run(q_, k_, v_, m_):
            return ring_attention_local(
                q_, k_, v_, m_, causal=causal, use_flash=use_flash
            )

        return run(q, k, v, mask)

    def test_forward_matches_einsum_ring(self):
        q, k, v = _make_qkv(b=2, s=256, h=4, d=32)
        rng = np.random.default_rng(3)
        mask = jnp.asarray(rng.random((2, 256)) > 0.2).at[:, 0].set(True)
        out_flash = self._sharded(True, q, k, v, mask)
        out_einsum = self._sharded(False, q, k, v, mask)
        np.testing.assert_allclose(out_flash, out_einsum, atol=3e-4)
        np.testing.assert_allclose(out_flash, _oracle(q, k, v, segment_mask=mask), atol=3e-4)

    def test_grads_match_einsum_ring(self):
        q, k, v = _make_qkv(b=1, s=128, h=2, d=32)
        mask = jnp.ones((1, 128), dtype=bool)

        def loss(use_flash):
            def fn(q, k, v):
                return (self._sharded(use_flash, q, k, v, mask) ** 2).sum()

            return jax.grad(fn, argnums=(0, 1, 2))(q, k, v)

        g_flash = loss(True)
        g_einsum = loss(False)
        for a, b in zip(g_flash, g_einsum):
            scale = max(float(jnp.abs(b).max()), 1.0)
            np.testing.assert_allclose(a, b, atol=5e-4 * scale)
        assert all(bool(jnp.isfinite(g).all()) for g in g_flash)

    def test_non_causal_ring(self):
        q, k, v = _make_qkv(b=1, s=128, h=2, d=32)
        mask = jnp.ones((1, 128), dtype=bool)
        out = self._sharded(True, q, k, v, mask, causal=False)
        ref = _oracle(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=3e-4)
