"""Per-request resource attribution (``serving/usage.py``) — the
conservation-checked usage ledger.

The headline property under test is **conservation, asserted**: the sum
of per-request decode device-time shares equals the engine's cumulative
``device_wait`` accrual, and the sum of per-request KV block-second
integrals equals the pool-occupancy integral — to float tolerance, under
every scheduling feature that edits block ownership or harvest timing
(chunked prefill, radix hit + CoW, swap preemption, deadline expiry,
speculative rounds, async + sync dispatch, a 4-device mesh), across
every kv_dtype. Plus the tenant dimension's round-trip (payload →
engine → rollups → trails), the exported-cardinality cap, and the
disabled path staying one truthiness check.

Tier-1 tests are pure host (ledger arithmetic, CLI plumbing, trail
readers); engine end-to-end conservation rides the slow lane like the
rest of the serving suite.
"""

import argparse
import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from accelerate_tpu.serving.usage import (
    DEFAULT_TOP_K,
    OTHER_TENANT,
    UsageLedger,
    cap_by_key,
    normalize_tenant,
)

KV_DTYPES = ("bf16", "int8", "fp8")


# ---------------------------------------------------------------------------
# tenant normalization + cardinality cap (tier-1: pure host)
# ---------------------------------------------------------------------------


def test_normalize_tenant_contract():
    assert normalize_tenant("acme") == "acme"
    assert normalize_tenant("  padded  ") == "padded"
    assert normalize_tenant("x" * 200) == "x" * 64
    for bad in (None, "", "   ", 7, 1.5, ["a"], {"t": 1}, True):
        assert normalize_tenant(bad) == "default"


def test_cap_by_key_top_k_plus_other():
    """K+1 tenants export as the K heaviest + an ``other`` fold summing
    every numeric field of the rest."""
    k = 3
    entries = {
        f"t{i}": {"device_seconds": float(i), "swap_bytes": i, "name": "x"}
        for i in range(k + 2)  # t0..t4, weights 0..4
    }
    capped = cap_by_key(entries, k)
    assert set(capped) == {"t4", "t3", "t2", OTHER_TENANT}
    assert capped[OTHER_TENANT]["device_seconds"] == 1.0  # t0 + t1
    assert capped[OTHER_TENANT]["swap_bytes"] == 1
    assert "name" not in capped[OTHER_TENANT]  # non-numeric fields dropped
    # at or under the cap: pass-through copies, no fold bucket
    small = cap_by_key(dict(list(entries.items())[:k]), k)
    assert OTHER_TENANT not in small and len(small) == k


def test_cap_by_key_merges_literal_other_tenant():
    entries = {
        "other": {"device_seconds": 10.0},
        "a": {"device_seconds": 5.0},
        "b": {"device_seconds": 1.0},
        "c": {"device_seconds": 0.5},
    }
    capped = cap_by_key(entries, 2)
    # "other" won a top-K slot on weight; the fold (b + c) merges into it
    assert capped[OTHER_TENANT]["device_seconds"] == 11.5


# ---------------------------------------------------------------------------
# ledger arithmetic (tier-1: synthetic edges, no engine)
# ---------------------------------------------------------------------------


class _FakeReq:
    def __init__(self, rid, tenant="default", priority="interactive"):
        self.request_id = rid
        self.tenant = tenant
        self.priority = priority
        self.trace_id = f"trace-{rid}"
        self.blocks = []
        self.swap_plan = []
        self.output_tokens = []
        self.finish_reason = "eos"


def _conserved(snap, rel=1e-9):
    assert math.isclose(
        snap["decode_device_seconds"], snap["device_wait_seconds"],
        rel_tol=rel, abs_tol=1e-12,
    ), (snap["decode_device_seconds"], snap["device_wait_seconds"])
    assert math.isclose(
        snap["block_seconds"], snap["pool_block_seconds"],
        rel_tol=rel, abs_tol=1e-12,
    ), (snap["block_seconds"], snap["pool_block_seconds"])


def test_ledger_conservation_synthetic_edges():
    """Interleaved grow/shrink/swap edges with overlapping holders: the
    per-request integrals sum to the pool integral, and decode shares sum
    to the round total, without any engine in the loop."""
    ledger = UsageLedger()
    reqs = [_FakeReq(i, tenant=f"t{i % 2}") for i in range(3)]
    for r in reqs:
        ledger.begin(r)
    for step in range(40):
        r = reqs[step % 3]
        if step % 7 == 3 and r.blocks:
            r.swap_plan = list(r.blocks[: len(r.blocks) // 2])  # swap out
        elif step % 5 == 1:
            r.swap_plan = []
            r.blocks = r.blocks[:-1]  # shrink (eviction edge)
        else:
            r.blocks = r.blocks + [step]  # grow
        ledger.update_blocks(r)
        live = [q for q in reqs if q.request_id in ledger._live]
        ledger.accrue_decode(
            0.001, [(q.request_id, 1 + q.request_id) for q in live]
        )
    summaries = [ledger.finish(r) for r in reqs]
    assert all(s is not None for s in summaries)
    snap = ledger.snapshot()
    _conserved(snap)
    assert math.isclose(
        snap["device_wait_seconds"], 0.040, rel_tol=1e-9
    )
    assert snap["requests_finished"] == 3 and snap["requests_live"] == 0
    assert set(snap["by_tenant"]) == {"t0", "t1"}
    # the answer-row summary mirrors the folded record
    total = sum(s["device_time_s"] for s in summaries)
    assert math.isclose(total, snap["device_seconds"], rel_tol=1e-9)


def test_ledger_finish_exactly_once_and_late_edges_noop():
    ledger = UsageLedger()
    r = _FakeReq(1, tenant="acme")
    ledger.begin(r)
    r.blocks = [0, 1]
    ledger.update_blocks(r)
    first = ledger.finish(r)
    assert first is not None
    assert ledger.finish(r) is None  # exactly-once
    before = ledger.snapshot()
    ledger.update_blocks(r)  # late edge after close: must not resurrect
    ledger.accrue_decode(1.0, [(r.request_id, 1)])
    after = ledger.snapshot()
    assert after["block_seconds"] == before["block_seconds"]
    assert after["decode_device_seconds"] == before["decode_device_seconds"]
    # the partner total still advances (the round happened) — but with no
    # live holder the per-request side is deliberately unattributed
    assert after["device_wait_seconds"] == before["device_wait_seconds"] + 1.0


def test_ledger_decode_equal_split_fallback():
    """A round whose every share weight is zero (all-discarded harvest)
    loses no device time: callers pass equal weights as the fallback."""
    ledger = UsageLedger()
    reqs = [_FakeReq(i) for i in range(2)]
    for r in reqs:
        ledger.begin(r)
    ledger.accrue_decode(0.008, [(r.request_id, 1) for r in reqs])
    for r in reqs:
        ledger.finish(r)
    snap = ledger.snapshot()
    _conserved(snap)
    by_class = snap["by_class"]["interactive"]
    assert math.isclose(by_class["decode_device_seconds"], 0.008, rel_tol=1e-9)


def test_ledger_snapshot_caps_tenants_and_reset():
    ledger = UsageLedger(top_k=2)
    reqs = [_FakeReq(i, tenant=f"tenant-{i}") for i in range(4)]
    for r in reqs:
        ledger.begin(r)
        ledger.accrue_decode(0.001 * (i := r.request_id + 1), [(r.request_id, 1)])
        ledger.finish(r)
    snap = ledger.snapshot()
    assert len(snap["by_tenant"]) == 3  # top 2 + "other"
    assert OTHER_TENANT in snap["by_tenant"]
    assert snap["top_k"] == 2
    assert len(snap["heavy_hitters"]) == 2
    ledger.reset()
    zero = ledger.snapshot()
    assert zero["requests_finished"] == 0
    assert zero["device_seconds"] == 0.0 and zero["by_tenant"] == {}


# ---------------------------------------------------------------------------
# CLI plumbing + workload tenants (tier-1: pure host)
# ---------------------------------------------------------------------------


def _parse_serve(argv, monkeypatch, env=None):
    from accelerate_tpu.commands import serve as serve_cmd

    monkeypatch.delenv("ACCELERATE_SERVE_USAGE", raising=False)
    if env is not None:
        monkeypatch.setenv("ACCELERATE_SERVE_USAGE", env)
    parser = argparse.ArgumentParser()
    serve_cmd.add_parser(parser.add_subparsers())
    return parser.parse_args(argv)


def test_serve_usage_accounting_flag_and_env(monkeypatch):
    assert _parse_serve(["serve"], monkeypatch).usage_accounting is True
    assert _parse_serve(
        ["serve", "--no-usage-accounting"], monkeypatch
    ).usage_accounting is False
    assert _parse_serve(["serve"], monkeypatch, env="0").usage_accounting is False
    assert _parse_serve(
        ["serve", "--usage-accounting"], monkeypatch, env="0"
    ).usage_accounting is True


def test_engine_config_usage_accounting_default_on():
    from accelerate_tpu.serving import EngineConfig

    assert EngineConfig().usage_accounting is True


def test_workload_tenants_spec_round_trip():
    from accelerate_tpu.serving.workload import generate_schedule, parse_trace_spec

    spec = parse_trace_spec("bursty-diurnal:3:2:8:tenants=3")
    assert spec.tenants == 3
    assert spec.as_text() == "bursty-diurnal:3:2:8:tenants=3"
    schedule = generate_schedule(spec)
    tenants = {e["payload"]["tenant"] for e in schedule}
    assert tenants <= {"t0", "t1", "t2"} and len(tenants) >= 2
    # deterministic: same spec, same assignment
    assert schedule == generate_schedule(parse_trace_spec(spec.as_text()))
    # tenants=N changes WHO bills, never the arrival schedule
    plain = generate_schedule(parse_trace_spec("bursty-diurnal:3:2:8"))
    assert "tenant" not in plain[0]["payload"]
    assert [e["t"] for e in plain] == [e["t"] for e in schedule]


def test_workload_tenants_spec_malformed():
    from accelerate_tpu.serving.workload import TraceSpecError, parse_trace_spec

    with pytest.raises(TraceSpecError):
        parse_trace_spec("bursty-diurnal:3:2:8:tenants=x")
    with pytest.raises(TraceSpecError):
        parse_trace_spec("bursty-diurnal:3:2:8:tenants=-1")
    with pytest.raises(TraceSpecError):
        parse_trace_spec("bursty-diurnal:3:2:8:bogus=1")


def test_openai_tenant_and_cost_fields():
    """``x_accelerate_tenant`` rides into the payload; the vendor block
    carries the ledger's measured costs back out."""
    from accelerate_tpu.serving.openai_api import OpenAIFrontend

    captured = {}

    def submit(payload, cb):
        captured.update(payload)
        cb({
            "tokens": [65, 66], "prompt_tokens": 3, "finish_reason": "eos",
            "trace_id": "tr-1", "tenant": "acme", "device_time_s": 0.25,
            "kv_block_seconds": 1.5, "swap_bytes": 4096,
        })

    frontend = OpenAIFrontend(submit)
    kind, status, body = frontend.handle(
        "/v1/completions",
        {"prompt": "hi", "x_accelerate_tenant": "acme", "temperature": 0},
    )
    assert (kind, status) == ("json", 200)
    assert captured["tenant"] == "acme"
    vendor = body["x_accelerate"]
    assert vendor["tenant"] == "acme"
    assert vendor["device_time_s"] == 0.25
    assert vendor["kv_block_seconds"] == 1.5
    assert vendor["swap_bytes"] == 4096


# ---------------------------------------------------------------------------
# metrics ingest + usage report CLI (tier-1: trail readers, no jax)
# ---------------------------------------------------------------------------


def _sample_snapshot():
    return {
        "schema": 1,
        "requests_finished": 2,
        "requests_live": 0,
        "top_k": DEFAULT_TOP_K,
        "device_seconds": 0.5,
        "decode_device_seconds": 0.3,
        "prefill_device_seconds": 0.2,
        "block_seconds": 4.0,
        "swap_bytes": 1024,
        "spec_drafted_tokens": 0,
        "spec_accepted_tokens": 0,
        "grammar_masked_steps": 0,
        "device_wait_seconds": 0.3,
        "pool_block_seconds": 4.0,
        "by_tenant": {
            "acme": {"requests": 1, "tokens": 8, "device_seconds": 0.4,
                     "block_seconds": 3.0, "swap_bytes": 1024},
            "default": {"requests": 1, "tokens": 4, "device_seconds": 0.1,
                        "block_seconds": 1.0, "swap_bytes": 0},
        },
        "by_class": {"interactive": {"requests": 2, "tokens": 12,
                                     "device_seconds": 0.5}},
        "heavy_hitters": [{"request_id": 1, "trace_id": "tr-1",
                           "tenant": "acme", "class": "interactive",
                           "device_seconds": 0.4, "block_seconds": 3.0,
                           "swap_bytes": 1024, "new_tokens": 8,
                           "finish_reason": "eos"}],
    }


def test_ingest_usage_counters_both_surfaces():
    """The same tenant-labeled counters come out of a telemetry step row
    and out of ``observe_engine_stats`` — the one-table-two-surfaces rule."""
    from accelerate_tpu.metrics.ingest import observe_record, observe_engine_stats
    from accelerate_tpu.metrics.openmetrics import render_openmetrics
    from accelerate_tpu.metrics.registry import MetricsRegistry

    snap = _sample_snapshot()
    via_record = MetricsRegistry()
    observe_record(
        via_record,
        {"type": "serving", "kind": "step", "schema": 1, "usage": snap},
    )
    via_stats = MetricsRegistry()
    observe_engine_stats(via_stats, {"usage": snap})
    for registry in (via_record, via_stats):
        text = render_openmetrics(registry)
        assert 'serving_usage_device_seconds_total{tenant="acme"} 0.4' in text
        assert 'serving_usage_block_seconds_total{tenant="acme"} 3' in text
        assert 'serving_usage_swap_bytes_total{tenant="acme"} 1024' in text
        assert 'serving_usage_device_seconds_total{tenant="default"} 0.1' in text
        assert "serving_usage_requests_total 2" in text


def test_ingest_router_by_tenant_counters():
    from accelerate_tpu.metrics.ingest import observe_router_row
    from accelerate_tpu.metrics.openmetrics import render_openmetrics
    from accelerate_tpu.metrics.registry import MetricsRegistry

    registry = MetricsRegistry()
    observe_router_row(registry, {
        "kind": "router", "delivered": 5, "shed": 1,
        "by_tenant": {
            "acme": {"delivered": 3, "shed": 1, "requeued": 2,
                     "deadline_expired": 0},
        },
    })
    text = render_openmetrics(registry)
    assert 'serving_router_delivered_total{tenant="acme"} 3' in text
    assert 'serving_router_shed_total{tenant="acme"} 1' in text
    assert 'serving_router_requeues_total{tenant="acme"} 2' in text
    assert "serving_router_delivered_total 5" in text  # aggregate intact


def _write_run(tmp_path, snap, by_tenant_router=None):
    from accelerate_tpu.telemetry import TelemetryRecorder

    recorder = TelemetryRecorder(logging_dir=str(tmp_path))
    recorder.record_serving("step", tokens_per_sec=1.0, usage=snap)
    recorder.close()
    if by_tenant_router is not None:
        router_dir = tmp_path / "router"
        router_dir.mkdir(exist_ok=True)
        with open(router_dir / "replicas.jsonl", "w") as f:
            f.write(json.dumps({
                "kind": "router", "schema": 1, "delivered": 2,
                "by_tenant": by_tenant_router,
            }) + "\n")


def test_usage_report_conservation_verdict(tmp_path, capsys):
    from accelerate_tpu.commands.usage import build_report, render_report

    _write_run(
        tmp_path, _sample_snapshot(),
        by_tenant_router={"acme": {"delivered": 2, "shed": 0, "requeued": 0,
                                   "deadline_expired": 0}},
    )
    report = build_report(str(tmp_path))
    assert report["conserved"] is True and report["pass"] is True
    run = report["runs"][0]
    assert run["conservation"]["device"]["ok"] is True
    assert run["conservation"]["blocks"]["ok"] is True
    assert run["router_by_tenant"]["acme"]["delivered"] == 2
    text = render_report(report)
    assert "CONSERVED" in text and "tenant acme" in text
    assert "tr-1" in text  # heavy-hitter exemplar links into trace tooling

    # a cooked snapshot that violates conservation FAILS the report
    bad = _sample_snapshot()
    bad["decode_device_seconds"] = bad["device_wait_seconds"] * 2
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    _write_run(bad_dir, bad)
    bad_report = build_report(str(bad_dir))
    assert bad_report["conserved"] is False and bad_report["pass"] is False
    assert "VIOLATED" in render_report(bad_report)


def test_usage_report_cli_json_round_trip(tmp_path, capsys):
    from accelerate_tpu.commands.accelerate_cli import main

    _write_run(tmp_path, _sample_snapshot())
    assert main(["usage", "report", str(tmp_path), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["schema"] == 1 and report["conserved"] is True
    snap = report["runs"][0]["usage"]
    assert snap["by_tenant"]["acme"]["device_seconds"] == 0.4
    # rendered form agrees with the machine-readable verdict
    assert main(["usage", "report", str(tmp_path), "--by", "class"]) == 0
    assert "interactive" in capsys.readouterr().out


def test_usage_report_without_snapshot(tmp_path, capsys):
    from accelerate_tpu.commands.accelerate_cli import main
    from accelerate_tpu.telemetry import TelemetryRecorder

    recorder = TelemetryRecorder(logging_dir=str(tmp_path))
    recorder.record_serving("step", tokens_per_sec=1.0)  # no usage field
    recorder.close()
    assert main(["usage", "report", str(tmp_path)]) == 0
    assert "no usage snapshot" in capsys.readouterr().out


def test_router_ticket_tenant_property():
    from accelerate_tpu.serving.router import Ticket

    assert Ticket(payload={"tenant": "acme", "prompt": [1]}).tenant == "acme"
    assert Ticket(payload={"prompt": [1]}).tenant == "default"
    assert Ticket(payload={"tenant": 7, "prompt": [1]}).tenant == "default"


def test_monitor_renders_usage_panel():
    from accelerate_tpu.diagnostics.monitor import render_status

    status = {
        "logging_dir": "/tmp/x", "steps": None, "optimizer_steps": None,
        "step_time_s": None, "step_rate": None, "examples_per_sec": None,
        "tokens_per_sec": None, "mfu": None, "recompiles": None,
        "last_record_age_s": None, "skipped_unknown_schema": 0,
        "hosts": [], "stragglers": [], "wedged": [], "hang_reports": [],
        "race_reports": [], "collective_divergence": [], "fleet": [],
        "fleet_dead": [], "scale_decisions": [],
        "serving": {
            "tokens_per_sec": 10.0, "queue_depth": 0, "slot_occupancy": 0.5,
            "free_blocks": 3, "decode_compiles": 1, "completed": 2,
            "ttft_p50_s": 0.1, "ttft_p99_s": 0.2,
            "usage": _sample_snapshot(),
        },
    }
    text = render_status(status)
    assert "usage: device 0.5s" in text
    assert "tenants: acme 0.4s" in text


# ---------------------------------------------------------------------------
# engine end-to-end conservation (slow lane)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


def _cfg(**kw):
    from accelerate_tpu.serving import EngineConfig

    base = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8)
    base.update(kw)
    return EngineConfig(**base)


def _prompts(seed, sizes=(5, 11, 17, 3, 9)):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=n).astype(np.int32) for n in sizes]


def _skip_without_fp8(kv_dtype):
    if kv_dtype == "fp8":
        from accelerate_tpu.utils.compat import has_fp8_storage

        if not has_fp8_storage():
            pytest.skip("float8_e4m3fn storage unsupported on this jax stack")


def _drive_mixed(eng):
    return [
        eng.add_request(p, 3 + 4 * i, tenant=f"t{i % 3}")
        for i, p in enumerate(_prompts(0))
    ]


def _drive_radix_cow(eng):
    base = np.arange(20, dtype=np.int32) % 60
    r1 = eng.add_request(base, 6, tenant="warm")
    eng.run_until_idle(max_iterations=5000)
    shared = np.concatenate([base[:19], np.asarray([61], np.int32)])
    r2 = eng.add_request(shared, 6, tenant="hit")
    return [r1, r2]


def _drive_swap(eng):
    return [
        eng.add_request(
            np.arange(8, dtype=np.int32) + i, max_new_tokens=30,
            tenant=f"t{i}",
        )
        for i in range(2)
    ]


def _drive_deadline(eng):
    doomed = eng.add_request([5, 6, 7], 8, deadline_ms=0.001, tenant="doomed")
    rest = [
        eng.add_request(p, 6, tenant="survivor")
        for p in _prompts(3, sizes=(5, 9))
    ]
    return [doomed] + rest


_SCENARIOS = {
    "chunked_prefill": (_drive_mixed, dict(decode_burst=1)),
    "radix_cow": (_drive_radix_cow, dict(prefix_cache=True)),
    "swap_preempt": (
        _drive_swap,
        dict(num_slots=2, num_blocks=6, swap_gb=0.01, prefix_cache=False),
    ),
    "deadline": (_drive_deadline, {}),
    "spec_k3": (_drive_mixed, dict(spec_k=3, draft="early_exit:1")),
}


def _run_and_assert_conserved(model, drive, **cfg_kw):
    """Run the drive on an async and a sync engine; assert conservation,
    one decode executable, and flight agreement on both."""
    from accelerate_tpu.serving import InferenceEngine

    snaps = []
    for async_dispatch in (True, False):
        eng = InferenceEngine(model, _cfg(async_dispatch=async_dispatch, **cfg_kw))
        reqs = drive(eng)
        eng.run_until_idle(max_iterations=5000)
        stats = eng.stats()
        assert stats["decode_compiles"] == 1
        snap = stats["usage"]
        _conserved(snap)
        assert snap["requests_live"] == 0
        assert snap["requests_finished"] == len(reqs)
        # the ledger's decode total is the flight recorder's device_wait —
        # the same floats, attributed instead of merely bucketed
        if eng._flight is not None:
            assert math.isclose(
                snap["device_wait_seconds"],
                eng._flight.phase_totals_s["device_wait"],
                rel_tol=1e-9, abs_tol=1e-12,
            )
        # every finished request carries its answer-row cost summary
        for r in reqs:
            assert r.usage is not None
            assert r.usage["device_time_s"] >= 0.0
            # a deadline-doomed request can close before it ever holds a
            # block, so the integral's floor is 0, not positive
            assert r.usage["kv_block_seconds"] >= 0.0
        snaps.append((eng, reqs, snap))
    return snaps


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
@pytest.mark.parametrize("scenario", sorted(_SCENARIOS))
def test_conservation_matrix(tiny_model, scenario, kv_dtype):
    _skip_without_fp8(kv_dtype)
    drive, cfg_kw = _SCENARIOS[scenario]
    snaps = _run_and_assert_conserved(
        tiny_model, drive, kv_dtype=kv_dtype, **cfg_kw
    )
    for eng, reqs, snap in snaps:
        if scenario == "swap_preempt":
            assert eng.stats()["preemptions"] >= 1
            assert snap["swap_bytes"] > 0
            by = snap["by_tenant"]
            assert sum(v["swap_bytes"] for v in by.values()) == snap["swap_bytes"]
        elif scenario == "deadline":
            assert reqs[0].finish_reason == "deadline_exceeded"
            # the doomed request's account still closed, exactly once
            assert reqs[0].usage is not None
            assert "doomed" in snap["by_tenant"]
        elif scenario == "spec_k3":
            assert snap["spec_drafted_tokens"] > 0
            assert snap["spec_drafted_tokens"] == eng.stats()["spec_drafted_tokens"]
        elif scenario == "radix_cow":
            assert eng.stats()["prefix_hit_tokens"] > 0
            # both the cold and the warm holder billed block-seconds
            assert all(
                v["block_seconds"] > 0 for v in snap["by_tenant"].values()
            )


@pytest.mark.slow
def test_conservation_mesh4(tiny_model):
    import jax

    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs a >= 4-device (virtual) mesh")
    mesh = build_mesh(MeshPlugin(dp=1, fsdp=2, tp=2), devices=devices[:4])

    from accelerate_tpu.serving import InferenceEngine

    eng = InferenceEngine(tiny_model, _cfg(decode_burst=2), mesh=mesh)
    reqs = [
        eng.add_request(p, b, tenant=f"t{i % 2}")
        for i, (p, b) in enumerate(
            zip(_prompts(7, sizes=(5, 12, 9)), (4, 7, 5))
        )
    ]
    eng.run_until_idle(max_iterations=5000)
    stats = eng.stats()
    assert stats["decode_compiles"] == 1
    _conserved(stats["usage"])
    assert all(r.usage is not None for r in reqs)


@pytest.mark.slow
def test_tenant_round_trip_and_disabled_path(tiny_model):
    """Tenant flows add_request → request rows → by_tenant rollups; with
    accounting off the engine carries no ledger and rows carry no costs."""
    from accelerate_tpu.serving import InferenceEngine

    eng = InferenceEngine(tiny_model, _cfg())
    reqs = [
        eng.add_request([1 + i, 2, 3], 4, tenant=t)
        for i, t in enumerate(("acme", "  acme  ", None, ""))
    ]
    eng.run_until_idle(max_iterations=5000)
    assert [r.tenant for r in reqs] == ["acme", "acme", "default", "default"]
    by = eng.stats()["usage"]["by_tenant"]
    assert by["acme"]["requests"] == 2 and by["default"]["requests"] == 2

    off = InferenceEngine(tiny_model, _cfg(usage_accounting=False))
    assert off.usage is None
    offreqs = [off.add_request([1, 2, 3], 4, tenant="acme")]
    off.run_until_idle(max_iterations=5000)
    assert offreqs[0].tenant == "acme"  # the dimension survives
    assert offreqs[0].usage is None  # no costs without the ledger
    assert "usage" not in off.stats()


# ---------------------------------------------------------------------------
# exactly-once usage rows under chaos (slow lane, routed fleet CLI)
# ---------------------------------------------------------------------------

_TINY_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "64", "--prefill-chunk", "8", "--decode-burst", "2",
]


@pytest.mark.slow
def test_chaos_exactly_once_usage_rows(tmp_path):
    """Under a seeded kill schedule against a routed fleet, every request
    is answered exactly once and every answer carries its usage costs —
    a redispatched request bills its final (answering) replica only."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_SERVE_USAGE", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "2", "--respawn", "--min-replicas", "2",
         "--logging-dir", str(tmp_path), "--health-interval", "0.2",
         "--chaos-spec", "seed=1;r0:kill@3", *_TINY_ARGS],
        env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results = []

    def read():
        for line in proc.stdout:
            line = line.strip()
            if line:
                results.append(line)

    threading.Thread(target=read, daemon=True).start()
    try:
        for i in range(8):
            proc.stdin.write(json.dumps({
                "id": i, "prompt": [1 + (i % 5), 7, 3], "max_new_tokens": 4,
                "tenant": f"t{i % 2}",
            }) + "\n")
            proc.stdin.flush()
        deadline = time.monotonic() + 240
        while len(results) < 8 and time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            time.sleep(0.1)
        proc.stdin.close()
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    assert sorted(r.get("id") for r in parsed) == list(range(8))
    assert not [r for r in parsed if "error" in r]
    for r in parsed:
        # exactly one usage summary per answer, from the answering replica
        assert r["tenant"] == f"t{r['id'] % 2}"
        assert r["device_time_s"] >= 0.0
        assert r["kv_block_seconds"] > 0.0
