"""Generation loop (the reference's s/token benchmark path goes through
transformers.generate on hooked models; here the framework owns the loop —
``accelerate_tpu/generation.py``)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.big_modeling import cpu_offload
from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _model(cls=LlamaForCausalLM, cfg=None):
    cfg = cfg or LlamaConfig.tiny(layers=2, seq=64)
    return cls.from_config(cfg, seed=0), cfg


def test_greedy_matches_stepwise_argmax():
    model, cfg = _model()
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 8)).astype(np.int32)
    out = generate(_as_callable(model), ids, max_new_tokens=4)
    assert out.shape == (2, 12)
    # re-derive token 1 by hand: argmax at the prompt boundary
    full = model.apply_fn(model.params, input_ids=out[:, :12],
                          attention_mask=np.asarray(out[:, :12] >= 0, np.int32))
    # positions 8..10 predicted tokens must equal the argmax of the logits
    # one position earlier (greedy consistency)
    logits = np.asarray(full["logits"])
    for t in range(8, 11):
        np.testing.assert_array_equal(out[:, t], logits[:, t - 1, :].argmax(-1))


class _as_callable:
    """Minimal callable over a raw Model (generation accepts any callable)."""

    def __init__(self, model):
        self.model = model

    def __call__(self, **kw):
        return self.model.apply_fn(self.model.params, **kw)


def test_generate_through_streaming_offload():
    model, cfg = _model()
    ref = generate(_as_callable(model), np.zeros((1, 4), np.int32), max_new_tokens=3)
    dispatched = cpu_offload(model)
    out = generate(dispatched, np.zeros((1, 4), np.int32), max_new_tokens=3)
    np.testing.assert_array_equal(out, ref)


def test_generate_gpt2_and_eos():
    model, cfg = _model(GPT2LMHeadModel, GPT2Config.tiny(layers=2, seq=64))
    wrapped = _as_callable(model)
    ids = np.random.default_rng(1).integers(0, 256, size=(1, 4)).astype(np.int32)
    out = generate(wrapped, ids, max_new_tokens=6)
    assert out.shape == (1, 10)
    # eos halts: pick the actually-generated first token as "eos"
    eos = int(out[0, 4])
    halted = generate(wrapped, ids, max_new_tokens=6, eos_token_id=eos)
    assert halted.shape[1] <= 10
    assert int(halted[0, 4]) == eos


def test_sampling_respects_temperature_determinism():
    model, cfg = _model()
    wrapped = _as_callable(model)
    ids = np.zeros((1, 4), np.int32)
    a = generate(wrapped, ids, max_new_tokens=4, do_sample=True, seed=7)
    b = generate(wrapped, ids, max_new_tokens=4, do_sample=True, seed=7)
    np.testing.assert_array_equal(a, b)  # same seed → same tokens


def test_ragged_prompts_decode_from_their_own_positions():
    """Right-padded shorter prompts must continue from THEIR last real
    token — batched output equals each row generated alone."""
    model, cfg = _model()
    wrapped = _as_callable(model)
    rng = np.random.default_rng(3)
    long_p = rng.integers(0, 256, size=(6,)).astype(np.int32)
    short_p = rng.integers(0, 256, size=(3,)).astype(np.int32)

    batch_ids = np.zeros((2, 6), np.int32)
    batch_ids[0] = long_p
    batch_ids[1, :3] = short_p
    mask = np.asarray([[1] * 6, [1, 1, 1, 0, 0, 0]], np.int32)
    out = generate(wrapped, batch_ids, max_new_tokens=3, attention_mask=mask)

    solo_long = generate(wrapped, long_p[None], max_new_tokens=3)
    solo_short = generate(wrapped, short_p[None], max_new_tokens=3)
    np.testing.assert_array_equal(out[0, :9], solo_long[0])
    np.testing.assert_array_equal(out[1, 3:6], solo_short[0, 3:6])


def test_cached_generation_matches_full_forward():
    """KV-cache decode must produce token-for-token the same greedy output
    as O(n²) re-forwards, including ragged right-padded batches."""
    model, cfg = _model()
    wrapped = _as_callable(model)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 256, size=(2, 6)).astype(np.int32)
    ref = generate(wrapped, ids, max_new_tokens=5)
    cached = generate(model, ids, max_new_tokens=5, use_cache=True)
    np.testing.assert_array_equal(cached, ref)

    # ragged batch
    mask = np.asarray([[1] * 6, [1, 1, 1, 0, 0, 0]], np.int32)
    ref = generate(wrapped, ids, max_new_tokens=4, attention_mask=mask)
    cached = generate(model, ids, max_new_tokens=4, attention_mask=mask, use_cache=True)
    np.testing.assert_array_equal(cached[0], ref[0])
    np.testing.assert_array_equal(cached[1, :7], ref[1, :7])


def test_cached_generation_eos_matches_full_forward():
    """The cached path's in-scan eos masking + host trim must stop at the
    same step (and emit the same tokens) as the full-forward loop's
    finished.all() break."""
    model, cfg = _model()
    wrapped = _as_callable(model)
    ids = np.random.default_rng(9).integers(0, 256, size=(2, 6)).astype(np.int32)
    # pick the greedy first new token of row 0 as "eos" so generation halts
    # mid-way through max_new_tokens deterministically
    probe = generate(wrapped, ids, max_new_tokens=1)
    eos = int(probe[0, 6])
    ref = generate(wrapped, ids, max_new_tokens=8, eos_token_id=eos)
    cached = generate(model, ids, max_new_tokens=8, eos_token_id=eos, use_cache=True)
    assert cached.shape == ref.shape
    np.testing.assert_array_equal(cached, ref)


def test_cached_generation_eos_zero_does_not_collide_with_padding():
    """eos_token_id=0 must not be confused with the zero-initialised output
    buffer: rows keep their real tokens until THEY emit 0."""
    model, cfg = _model()
    wrapped = _as_callable(model)
    ids = np.random.default_rng(10).integers(1, 256, size=(2, 5)).astype(np.int32)
    ref = generate(wrapped, ids, max_new_tokens=6, eos_token_id=0)
    cached = generate(model, ids, max_new_tokens=6, eos_token_id=0, use_cache=True)
    assert cached.shape == ref.shape
    np.testing.assert_array_equal(cached, ref)


def test_cached_generation_sampling_is_seed_deterministic():
    model, cfg = _model()
    ids = np.random.default_rng(11).integers(0, 256, size=(2, 5)).astype(np.int32)
    a = generate(model, ids, max_new_tokens=5, do_sample=True, temperature=0.8,
                 seed=3, use_cache=True)
    b = generate(model, ids, max_new_tokens=5, do_sample=True, temperature=0.8,
                 seed=3, use_cache=True)
    c = generate(model, ids, max_new_tokens=5, do_sample=True, temperature=0.8,
                 seed=4, use_cache=True)
    np.testing.assert_array_equal(a, b)
    assert a.shape == c.shape and not np.array_equal(a, c)


def test_cached_generation_zero_new_tokens_returns_prompt():
    model, cfg = _model()
    ids = np.random.default_rng(12).integers(0, 256, size=(2, 5)).astype(np.int32)
    out = generate(model, ids, max_new_tokens=0, use_cache=True)
    np.testing.assert_array_equal(out, ids)
    ref = generate(_as_callable(model), ids, max_new_tokens=0)
    np.testing.assert_array_equal(ref, ids)


def test_cached_generation_chunked_eos_loop_spans_chunks(monkeypatch):
    """With a tiny chunk length the decode loop crosses several compiled
    chunks and still matches the full-forward output (and stops early when
    every row finished)."""
    import accelerate_tpu.generation as gen

    monkeypatch.setattr(gen, "_EOS_CHUNK", 2)
    model, cfg = _model()
    wrapped = _as_callable(model)
    ids = np.random.default_rng(13).integers(0, 256, size=(2, 6)).astype(np.int32)
    probe = generate(wrapped, ids, max_new_tokens=3)
    eos = int(probe[0, 8])  # third greedy token of row 0
    ref = generate(wrapped, ids, max_new_tokens=9, eos_token_id=eos)
    cached = generate(model, ids, max_new_tokens=9, eos_token_id=eos, use_cache=True)
    assert cached.shape == ref.shape
    np.testing.assert_array_equal(cached, ref)


def test_cached_generation_on_prepared_model():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    model, cfg = _model()
    ids = np.random.default_rng(6).integers(0, 256, size=(1, 5)).astype(np.int32)
    ref = generate(_as_callable(model), ids, max_new_tokens=4)
    prepared = accelerator.prepare_model(model)
    cached = generate(prepared, ids, max_new_tokens=4, use_cache=True)
    np.testing.assert_array_equal(cached, ref)


def test_use_cache_falls_back_for_unsupported_models():
    model, cfg = _model(GPT2LMHeadModel, GPT2Config.tiny(layers=2, seq=64))
    wrapped = _as_callable(model)
    ids = np.random.default_rng(7).integers(0, 256, size=(1, 4)).astype(np.int32)
    ref = generate(wrapped, ids, max_new_tokens=3)
    out = generate(wrapped, ids, max_new_tokens=3, use_cache=True)  # silent fallback
    np.testing.assert_array_equal(out, ref)


def test_generation_past_max_positions_raises():
    model, cfg = _model()  # tiny seq=64
    wrapped = _as_callable(model)
    ids = np.zeros((1, 60), np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(wrapped, ids, max_new_tokens=10)  # 70 > 64
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(model, ids, max_new_tokens=10, use_cache=True)


def test_cached_generation_compiles_once():
    model, cfg = _model()
    ids = np.zeros((1, 4), np.int32)
    generate(model, ids, max_new_tokens=3, use_cache=True)
    cache = model.apply_fn._generation_jit_cache
    assert len(cache) == 1
    generate(model, ids, max_new_tokens=3, use_cache=True)
    assert len(cache) == 1  # same jit objects reused


def test_dispatched_model_never_takes_cached_path():
    """use_cache on a DispatchedModel must stream, not materialise."""
    from accelerate_tpu.big_modeling import DispatchedModel

    model, cfg = _model()
    dispatched = cpu_offload(model)
    called = {"materialize": 0}
    orig = DispatchedModel._materialize_full

    def counting(self):
        called["materialize"] += 1
        return orig(self)

    DispatchedModel._materialize_full = counting
    try:
        ref = generate(_as_callable(model), np.zeros((1, 4), np.int32), max_new_tokens=2)
        out = generate(dispatched, np.zeros((1, 4), np.int32), max_new_tokens=2, use_cache=True)
    finally:
        DispatchedModel._materialize_full = orig
    assert called["materialize"] == 0
    np.testing.assert_array_equal(out, ref)


def test_cached_generation_respects_autocast_island():
    """The cached-apply closure must key on the live compute_dtype, not a
    stale snapshot — autocast islands mutate it."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import AutocastKwargs

    accelerator = Accelerator(mixed_precision="bf16")
    model, cfg = _model()
    prepared = accelerator.prepare_model(model)
    ids = np.random.default_rng(8).integers(0, 256, size=(1, 5)).astype(np.int32)

    with accelerator.autocast(autocast_handler=AutocastKwargs(enabled=False)):
        full_precision = generate(prepared, ids, max_new_tokens=3, use_cache=True)
    bf16 = generate(prepared, ids, max_new_tokens=3, use_cache=True)
    # two distinct closures cached, one per dtype policy
    assert len(prepared._cached_generation_apply) == 2
    assert None in prepared._cached_generation_apply
    # both decode sane token streams (values may differ by precision)
    assert full_precision.shape == bf16.shape == (1, 8)


def test_gpt2_cached_generation_matches_full_forward():
    """GPT-2's KV-cache prefill/decode (learned positions, fused QKV) must
    match O(n^2) re-forwards token-for-token, incl. ragged prompts."""
    from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config.tiny(layers=2, hidden_size=64, heads=4, seq=64)
    model = GPT2LMHeadModel.from_config(cfg, seed=1)
    assert model.supports_kv_cache
    wrapped = _as_callable(model)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 256, size=(2, 6)).astype(np.int32)
    ref = generate(wrapped, ids, max_new_tokens=5)
    cached = generate(model, ids, max_new_tokens=5, use_cache=True)
    np.testing.assert_array_equal(cached, ref)

    mask = np.asarray([[1] * 6, [1, 1, 1, 0, 0, 0]], np.int32)
    ref = generate(wrapped, ids, max_new_tokens=4, attention_mask=mask)
    cached = generate(model, ids, max_new_tokens=4, attention_mask=mask, use_cache=True)
    np.testing.assert_array_equal(cached[0], ref[0])
    np.testing.assert_array_equal(cached[1, :7], ref[1, :7])


# ---------------------------------------------------------------------------
# KV-cache generation over pp meshes (parallel.pipeline.pipeline_cached_stack)
# + mixtral cached decode
# ---------------------------------------------------------------------------


def _mesh_accelerator(**mesh_kwargs):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.mesh import MeshPlugin
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    return Accelerator(mesh_plugin=MeshPlugin(**mesh_kwargs))


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_cached_generation_on_pp_mesh_matches_full_forward(family):
    """Cached == uncached on a pp=2 (x tp=2 x dp=2) mesh: stage-split
    weights serve generation through stage-local caches instead of
    refusing (the round-2 NotImplementedError sites)."""
    acc = _mesh_accelerator(pp=2, tp=2, dp=2)
    if family == "llama":
        cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=4, heads=4, seq=64)
        model = acc.prepare(LlamaForCausalLM.from_config(cfg, seed=0))
    else:
        cfg = GPT2Config.tiny(vocab_size=128, hidden_size=64, layers=4, heads=4, seq=64)
        model = acc.prepare(GPT2LMHeadModel.from_config(cfg, seed=0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(2, 8)).astype(np.int32)
    ref = np.asarray(generate(model, ids, max_new_tokens=6, use_cache=False))
    cached = np.asarray(generate(model, ids, max_new_tokens=6, use_cache=True))
    np.testing.assert_array_equal(cached, ref)


def test_mixtral_cached_generation_matches_full_forward():
    """Mixtral KV-cache decode (attention caches; experts are stateless)
    on a plain mesh and with expert parallelism."""
    from accelerate_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    acc = _mesh_accelerator(ep=2, dp=4)
    cfg = MixtralConfig.tiny(
        vocab_size=128, hidden_size=64, layers=4, heads=4, experts=4, seq=64
    )
    model = acc.prepare(MixtralForCausalLM.from_config(cfg, seed=0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(2, 8)).astype(np.int32)
    ref = np.asarray(generate(model, ids, max_new_tokens=5, use_cache=False))
    cached = np.asarray(generate(model, ids, max_new_tokens=5, use_cache=True))
    np.testing.assert_array_equal(cached, ref)


def test_mixtral_cached_generation_on_pp_mesh():
    from accelerate_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    acc = _mesh_accelerator(pp=2, ep=2, dp=2)
    cfg = MixtralConfig.tiny(
        vocab_size=128, hidden_size=64, layers=4, heads=4, experts=4, seq=64
    )
    model = acc.prepare(MixtralForCausalLM.from_config(cfg, seed=0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(2, 8)).astype(np.int32)
    ref = np.asarray(generate(model, ids, max_new_tokens=5, use_cache=False))
    cached = np.asarray(generate(model, ids, max_new_tokens=5, use_cache=True))
    np.testing.assert_array_equal(cached, ref)


# ---------------------------------------------------------------------------
# chunked decode + speculative decoding
# ---------------------------------------------------------------------------


def test_chunked_decode_matches_full_forward():
    """s > 1 decode (the speculative-verify path): feeding a chunk against
    the KV cache must produce the same logits as the full forward at every
    chunk position, for both the rope and learned-position families."""
    from accelerate_tpu.models.gpt_neox import GPTNeoXConfig, GPTNeoXForCausalLM

    for cls, cfg in [
        (LlamaForCausalLM, LlamaConfig.tiny(layers=2, seq=64)),
        (GPT2LMHeadModel, GPT2Config.tiny(layers=2)),
        (GPTNeoXForCausalLM, GPTNeoXConfig.tiny(layers=2)),
    ]:
        model = cls.from_config(cfg, seed=1)
        ids = np.random.default_rng(0).integers(0, 256, size=(2, 12)).astype(np.int32)
        with jax.default_matmul_precision("highest"):
            full = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])
            pre = model.apply_fn(
                model.params, input_ids=ids[:, :8], use_cache=True, max_cache_len=12
            )
            step = model.apply_fn(
                model.params, input_ids=ids[:, 8:12],
                kv_cache=pre["kv_cache"], cache_index=np.full((2,), 8, np.int32),
            )
        np.testing.assert_allclose(
            np.asarray(step["logits"]), full[:, 8:12], rtol=2e-4, atol=2e-4
        )


def _spec_case():
    target = LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=4, seq=64), seed=1)
    draft = LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=2, seq=64), seed=9)
    ids = np.random.default_rng(0).integers(1, 250, size=(3, 10)).astype(np.int32)
    mask = np.ones((3, 10), np.int32)
    mask[1, 7:] = 0
    ids[1, 7:] = 0  # ragged right-padded row
    return target, draft, ids, mask


def test_speculative_equals_plain_greedy():
    """The speculative guarantee: output identical to plain greedy decoding
    for ANY draft — an unrelated random draft (low acceptance), the target
    itself (full acceptance), and k at both extremes."""
    target, draft, ids, mask = _spec_case()
    with jax.default_matmul_precision("highest"):
        plain = np.asarray(
            generate(target, ids, max_new_tokens=12, use_cache=True, attention_mask=mask)
        )
        for d, k in [(draft, 4), (target, 4), (draft, 1), (target, 8)]:
            spec = np.asarray(
                generate(target, ids, max_new_tokens=12, draft_model=d,
                         num_draft_tokens=k, attention_mask=mask)
            )
            np.testing.assert_array_equal(spec, plain)


def test_speculative_eos_matches_plain():
    target, draft, ids, mask = _spec_case()
    with jax.default_matmul_precision("highest"):
        probe = np.asarray(
            generate(target, ids, max_new_tokens=12, use_cache=True, attention_mask=mask)
        )
        eos = int(probe[0, -1])  # a token we know the model emits
        plain = np.asarray(
            generate(target, ids, max_new_tokens=12, use_cache=True,
                     attention_mask=mask, eos_token_id=eos)
        )
        spec = np.asarray(
            generate(target, ids, max_new_tokens=12, draft_model=draft,
                     num_draft_tokens=3, attention_mask=mask, eos_token_id=eos)
        )
    np.testing.assert_array_equal(spec, plain)


def test_speculative_rejects_sampling():
    target, draft, ids, mask = _spec_case()
    with pytest.raises(NotImplementedError, match="greedy-only"):
        generate(target, ids, max_new_tokens=4, draft_model=draft, do_sample=True)


def test_speculative_gpt2_family():
    t = GPT2LMHeadModel.from_config(GPT2Config.tiny(layers=4), seed=1)
    d = GPT2LMHeadModel.from_config(GPT2Config.tiny(layers=2), seed=7)
    ids = np.random.default_rng(2).integers(1, 250, size=(2, 9)).astype(np.int32)
    with jax.default_matmul_precision("highest"):
        plain = np.asarray(generate(t, ids, max_new_tokens=10, use_cache=True))
        spec = np.asarray(
            generate(t, ids, max_new_tokens=10, draft_model=d, num_draft_tokens=5)
        )
    np.testing.assert_array_equal(spec, plain)


def test_speculative_draft_swap_same_target():
    """Regression: the compiled draft-feed closure is cached on the target's
    jit cache — swapping in a different draft (even another architecture)
    must not reuse the first draft's apply_fn with the new params."""
    target, llama_draft, ids, mask = _spec_case()
    gpt2_draft = GPT2LMHeadModel.from_config(
        GPT2Config.tiny(layers=2, vocab_size=256), seed=3
    )
    with jax.default_matmul_precision("highest"):
        plain = np.asarray(
            generate(target, ids, max_new_tokens=8, use_cache=True, attention_mask=mask)
        )
        for d in (gpt2_draft, llama_draft):
            spec = np.asarray(
                generate(target, ids, max_new_tokens=8, draft_model=d,
                         num_draft_tokens=5, attention_mask=mask)
            )
            np.testing.assert_array_equal(spec, plain)


def test_speculative_bad_mask_raises():
    target, draft, ids, _ = _spec_case()
    bad = np.ones((ids.shape[0], ids.shape[1] + 3), np.int32)
    with pytest.raises(ValueError, match="attention_mask shape"):
        generate(target, ids, max_new_tokens=4, draft_model=draft, attention_mask=bad)


def test_speculative_on_prepared_target():
    """Speculative decoding through a prepare()'d mesh-sharded target (the
    PreparedModel cache backend) with a raw-Model draft."""
    acc = _mesh_accelerator(dp=2, fsdp=2, tp=2)
    target = acc.prepare(
        LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=4, seq=64), seed=1)
    )
    draft = LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=2, seq=64), seed=9)
    ids = np.random.default_rng(0).integers(1, 250, size=(2, 8)).astype(np.int32)
    with jax.default_matmul_precision("highest"):
        plain = np.asarray(generate(target, ids, max_new_tokens=8, use_cache=True))
        spec = np.asarray(
            generate(target, ids, max_new_tokens=8, draft_model=draft, num_draft_tokens=4)
        )
    np.testing.assert_array_equal(spec, plain)


def test_speculative_rejects_zero_draft_tokens():
    target, draft, ids, mask = _spec_case()
    for bad in (0, -3):
        with pytest.raises(ValueError, match="num_draft_tokens"):
            generate(target, ids, max_new_tokens=4, draft_model=draft,
                     num_draft_tokens=bad, attention_mask=mask)


def test_speculative_exact_fit_budget_matches_plain():
    """prompt + max_new == max_position_embeddings: the speculative cache
    is clamped to the position-table size (no k+1 margin) and overshoot
    writes are dropped — emitted tokens must still equal plain greedy."""
    cfg = LlamaConfig.tiny(layers=2, seq=24)
    target = LlamaForCausalLM.from_config(cfg, seed=0)
    draft = LlamaForCausalLM.from_config(
        LlamaConfig.tiny(layers=1, seq=24), seed=9
    )
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 8)).astype(np.int32)
    plain = np.asarray(generate(target, ids, max_new_tokens=16, use_cache=True))
    for k in (3, 5):
        spec = np.asarray(
            generate(target, ids, max_new_tokens=16, draft_model=draft,
                     num_draft_tokens=k)
        )
        np.testing.assert_array_equal(spec, plain)


def test_speculative_over_budget_raises():
    cfg = LlamaConfig.tiny(layers=2, seq=24)
    target = LlamaForCausalLM.from_config(cfg, seed=0)
    draft = LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=1, seq=24), seed=9)
    ids = np.zeros((1, 8), np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(target, ids, max_new_tokens=17, draft_model=draft, num_draft_tokens=4)
