"""Speculative decoding in the continuous-batching engine.

The contract under test: a spec-armed engine (``EngineConfig(spec_k=k,
draft="early_exit:N")``) is **token-identical** to the non-spec engine at
every ``kv_dtype`` and across every scheduler interaction (chunked
prefill, radix prefix hits, swap preemption, deadline expiry, eos), while
still compiling exactly ONE decode executable — the spec round (draft scan
+ ``[num_slots, k+1]`` verify + shared acceptance) *is* that executable.

Tier-1 (pure host / no compiles): draft-spec parsing, config refusals,
the shard-check draft tier, metrics/monitor field plumbing. The engine
end-to-end legs ride the slow lane like the rest of the serving suite.
"""

import numpy as np
import pytest

from accelerate_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    parse_draft_spec,
)

# ---------------------------------------------------------------------------
# draft-spec parsing + config refusals (tier-1)
# ---------------------------------------------------------------------------


def test_parse_draft_spec_early_exit():
    spec = parse_draft_spec("early_exit:2", num_layers=16)
    assert (spec.kind, spec.layers) == ("early_exit", 2)
    assert str(spec) == "early_exit:2"
    # whitespace tolerated; depth bound enforced against the target
    assert parse_draft_spec(" early_exit:1 ", num_layers=2).layers == 1


@pytest.mark.parametrize(
    "bad, match",
    [
        ("early_exit:0", "must be >= 1"),
        ("early_exit:2", "must be < the target"),  # num_layers=2 below
        ("early_exit:x", "not an integer"),
        ("", "malformed draft spec"),
        ("mystery", "unknown draft spec"),
        ("ckpts/draft.safetensors", "not supported yet"),
    ],
)
def test_parse_draft_spec_rejects(bad, match):
    with pytest.raises(ValueError, match=match):
        parse_draft_spec(bad, num_layers=2)


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


def _cfg(**kw):
    base = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8)
    base.update(kw)
    return EngineConfig(**base)


def test_engine_refuses_bad_spec_configs(tiny_model):
    # do_sample + spec composes on the per-slot-sampling engine (rejection
    # sampling in the verify round); only the legacy lanes-off executables
    # are still greedy-only
    with pytest.raises(ValueError, match="greedy-only"):
        InferenceEngine(
            tiny_model,
            _cfg(spec_k=4, draft="early_exit:1", do_sample=True,
                 per_slot_sampling=False),
        )
    with pytest.raises(ValueError, match="logprobs"):
        InferenceEngine(
            tiny_model, _cfg(spec_k=4, draft="early_exit:1", logprobs_topn=2)
        )
    with pytest.raises(ValueError, match="must be < the target"):
        InferenceEngine(tiny_model, _cfg(spec_k=4, draft="early_exit:2"))
    with pytest.raises(ValueError, match="not supported yet"):
        InferenceEngine(tiny_model, _cfg(spec_k=4, draft="ckpts/d.safetensors"))
    with pytest.raises(ValueError, match="spec_k must be >= 1"):
        InferenceEngine(tiny_model, _cfg(spec_k=-1))


def test_engine_stats_carry_spec_fields(tiny_model):
    eng = InferenceEngine(tiny_model, _cfg(spec_k=4, draft="early_exit:1"))
    st = eng.stats()
    assert st["spec_k"] == 4 and st["spec_draft"] == "early_exit:1"
    assert st["spec_drafted_tokens"] == 0 and st["spec_accept_rate"] == 0.0
    # spec off: the fields are absent entirely (monitor keys off spec_k)
    assert "spec_k" not in InferenceEngine(tiny_model, _cfg()).stats()


# ---------------------------------------------------------------------------
# shard-check draft tier (tier-1: abstract shapes only)
# ---------------------------------------------------------------------------


def test_draft_params_tier_prices_the_layer_slice(tiny_model):
    """The draft tier is exactly draft_layers/num_layers of the stacked
    layer params, under the same partition rules as the full stack."""
    from accelerate_tpu.analysis.shardplan import plan_draft_params, plan_params

    sizes = {ax: 1 for ax in ("dp", "pp", "fsdp", "ep", "cp", "tp")}
    rules = tiny_model.partition_rules
    params = tiny_model.params
    full_layers = sum(
        p.bytes_per_device
        for p in plan_params({"layers": params["layers"]}, sizes, rules=rules)
    )
    draft = plan_draft_params(params, sizes, rules, draft_layers=1)
    draft_bytes = sum(p.bytes_per_device for p in draft)
    assert draft_bytes * 2 == full_layers  # 1 of 2 layers
    assert all(p.tier == "draft_params" for p in draft)
    assert all(p.path.startswith("draft.layers.") for p in draft)


def test_engine_preflight_refusal_names_the_draft_tier(tiny_model):
    """With spec armed, the SP004 pre-flight budgets target + draft + pools
    and the refusal message names the draft share."""
    with pytest.raises(ValueError, match=r"SP004.*draft"):
        InferenceEngine(
            tiny_model,
            _cfg(spec_k=4, draft="early_exit:1", hbm_budget_gb=1e-6),
        )
    # generous budget: the report carries the draft tier and starts fine
    eng = InferenceEngine(
        tiny_model, _cfg(spec_k=4, draft="early_exit:1", hbm_budget_gb=8.0)
    )
    report = eng.hbm_preflight
    assert report["draft_layers"] == 1 and report["draft_bytes"] > 0
    assert report["total_bytes"] == (
        report["params_bytes"] + report["draft_bytes"] + report["pool_bytes"]
    )


# ---------------------------------------------------------------------------
# metrics + monitor plumbing (tier-1: synthetic rows, no engine dispatch)
# ---------------------------------------------------------------------------


def test_spec_metrics_round_trip_render_parse():
    """Accept-rate telemetry fields round-trip through BOTH export
    surfaces — the telemetry step-row path and the live stats()-dict path —
    into the documented serving_spec_* names."""
    from accelerate_tpu.metrics.ingest import observe_engine_stats, observe_record
    from accelerate_tpu.metrics.openmetrics import (
        parse_openmetrics,
        render_openmetrics,
        sample_value,
    )
    from accelerate_tpu.metrics.registry import MetricsRegistry

    reg = MetricsRegistry(gate_main_process=False)
    observe_record(reg, {
        "type": "serving", "kind": "step", "spec_k": 4,
        "spec_drafted_tokens": 120, "spec_accepted_tokens": 90,
        "spec_accept_rate": 0.75,
    })
    families = parse_openmetrics(render_openmetrics(reg))
    assert families["accelerate_serving_spec_drafted_tokens"]["type"] == "counter"
    assert sample_value(families, "accelerate_serving_spec_drafted_tokens") == 120
    assert sample_value(families, "accelerate_serving_spec_accepted_tokens") == 90
    assert sample_value(families, "accelerate_serving_spec_accept_rate") == 0.75

    # the stats() path ratchets the same counters (set_total semantics)
    observe_engine_stats(reg, {
        "spec_drafted_tokens": 200, "spec_accepted_tokens": 150,
        "spec_accept_rate": 0.75,
    })
    families = parse_openmetrics(render_openmetrics(reg))
    assert sample_value(families, "accelerate_serving_spec_drafted_tokens") == 200
    assert sample_value(families, "accelerate_serving_spec_accepted_tokens") == 150


# ---------------------------------------------------------------------------
# engine end-to-end (slow lane: compiles the tiny model)
# ---------------------------------------------------------------------------

KV_DTYPES = ("bf16", "int8", "fp8")


def _skip_without_fp8(kv_dtype: str) -> None:
    if kv_dtype == "fp8":
        from accelerate_tpu.utils.compat import has_fp8_storage

        if not has_fp8_storage():
            pytest.skip("float8_e4m3fn storage unsupported on this jax stack")


def _run_trace(model, spec_k, prompts, budgets, **cfg_kw):
    eng = InferenceEngine(
        model,
        _cfg(spec_k=spec_k, draft="early_exit:1" if spec_k else "early_exit:2",
             **cfg_kw),
    )
    reqs = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
    eng.run_until_idle(max_iterations=5000)
    return eng, [list(r.output_tokens) for r in reqs]


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
def test_spec_token_parity_across_kv_dtypes(tiny_model, kv_dtype):
    """The headline bar: spec-armed output == non-spec output, token for
    token, at every kv_dtype — on a mixed-length trace whose prompts force
    chunked prefill (17 > prefill_chunk 8) and whose budgets finish
    mid-round. One decode executable each side."""
    _skip_without_fp8(kv_dtype)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (5, 11, 17, 3, 9)]
    budgets = [3 + 4 * i for i in range(5)]
    base, base_toks = _run_trace(tiny_model, 0, prompts, budgets, kv_dtype=kv_dtype)
    spec, spec_toks = _run_trace(tiny_model, 4, prompts, budgets, kv_dtype=kv_dtype)
    assert spec_toks == base_toks
    st = spec.stats()
    assert st["decode_compiles"] == 1 and st["prefill_compiles"] == 1
    assert base.stats()["decode_compiles"] == 1
    assert st["spec_drafted_tokens"] > 0
    assert 0.0 <= st["spec_accept_rate"] <= 1.0
    assert st["allocated_blocks"] == 0  # rollback never leaked a block


@pytest.mark.slow
@pytest.mark.parametrize("spec_k", [1, 3, 8])
def test_spec_parity_across_k(tiny_model, spec_k):
    """k is a throughput knob, never a correctness one — including k=8
    rounds that overshoot short budgets by most of the round."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (6, 13)]
    _, base_toks = _run_trace(tiny_model, 0, prompts, [7, 5])
    _, spec_toks = _run_trace(tiny_model, spec_k, prompts, [7, 5])
    assert spec_toks == base_toks


@pytest.mark.slow
def test_spec_eos_parity(tiny_model):
    """eos raised mid-round: the host emit loop cuts the accepted run at
    the eos exactly like the non-spec burst loop does."""
    from accelerate_tpu.generation import generate

    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 64, size=9).astype(np.int32)
    ref = np.asarray(
        generate(tiny_model, prompt[None, :], max_new_tokens=8, use_cache=True)
    )[0]
    eos = int(ref[len(prompt) + 2])

    def run(spec_k):
        eng = InferenceEngine(
            tiny_model,
            _cfg(num_slots=2, eos_token_id=eos, spec_k=spec_k,
                 draft="early_exit:1" if spec_k else "early_exit:2"),
        )
        req = eng.add_request(prompt, max_new_tokens=8)
        eng.run_until_idle(max_iterations=5000)
        return req

    r0, r4 = run(0), run(4)
    assert r4.output_tokens == r0.output_tokens
    assert r4.finish_reason == "eos" and len(r4.output_tokens) < 8


@pytest.mark.slow
def test_spec_radix_prefix_hit_parity(tiny_model):
    """A warm radix hit hands the spec engine cached blocks whose draft
    layers were written by a previous request's prefill/verify — valid by
    construction (the draft IS the target's first layers), so warm output
    == cold output == non-spec output."""
    base = np.arange(20, dtype=np.int32) % 60
    shared = np.concatenate([base[:19], np.asarray([61], np.int32)])

    def run(spec_k, prefix_cache):
        eng = InferenceEngine(
            tiny_model,
            _cfg(num_slots=2, prefix_cache=prefix_cache, spec_k=spec_k,
                 draft="early_exit:1" if spec_k else "early_exit:2"),
        )
        r1 = eng.add_request(base, 6)
        eng.run_until_idle(max_iterations=5000)
        r2 = eng.add_request(shared, 6)  # full-block hit + mid-block CoW
        eng.run_until_idle(max_iterations=5000)
        return eng, (r1.output_tokens, r2.output_tokens)

    warm_eng, warm = run(4, True)
    _, cold = run(4, False)
    _, base_toks = run(0, True)
    assert warm == cold == base_toks
    st = warm_eng.stats()
    assert st["prefix_hit_tokens"] > 0  # the warm leg really hit the cache
    assert st["decode_compiles"] == 1


@pytest.mark.slow
def test_spec_swap_preemption_parity(tiny_model):
    """Pool pressure with the host swap tier: preempted + restored rows
    carry the draft layers byte-exactly (they are just pool layers), so
    the spec engine completes un-truncated and token-identical to the
    non-spec engine under the same pressure."""
    prompts = [np.arange(8, dtype=np.int32), np.arange(8, dtype=np.int32) + 1]

    def run(spec_k):
        eng = InferenceEngine(
            tiny_model,
            _cfg(num_slots=2, prefix_cache=False, num_blocks=6, swap_gb=0.01,
                 spec_k=spec_k, draft="early_exit:1" if spec_k else "early_exit:2"),
        )
        reqs = [eng.add_request(p, max_new_tokens=30) for p in prompts]
        eng.run_until_idle(max_iterations=5000)
        return eng, reqs

    spec_eng, spec_reqs = run(4)
    _, base_reqs = run(0)
    assert [r.finish_reason for r in spec_reqs] == ["length", "length"]
    assert [r.output_tokens for r in spec_reqs] == [r.output_tokens for r in base_reqs]
    st = spec_eng.stats()
    assert st["preemptions"] >= 1
    assert st["swapped_out_blocks"] == st["swapped_in_blocks"] > 0
    assert st["decode_compiles"] == 1


@pytest.mark.slow
def test_spec_deadline_expiry_interaction(tiny_model):
    """An already-expired queued request dies with deadline_exceeded while
    the spec lanes keep decoding — and the survivors stay token-identical
    to the non-spec engine under the same mix."""
    rng = np.random.default_rng(5)
    live_prompt = rng.integers(0, 64, size=7).astype(np.int32)

    def run(spec_k):
        eng = InferenceEngine(
            tiny_model,
            _cfg(num_slots=2, spec_k=spec_k,
                 draft="early_exit:1" if spec_k else "early_exit:2"),
        )
        doomed = eng.add_request(np.arange(5, dtype=np.int32), 6,
                                 deadline_ms=1e-3)
        live = eng.add_request(live_prompt, 9, deadline_ms=60_000.0)
        import time

        time.sleep(0.002)  # the doomed deadline elapses while queued
        eng.run_until_idle(max_iterations=5000)
        return eng, doomed, live

    spec_eng, spec_doomed, spec_live = run(4)
    _, base_doomed, base_live = run(0)
    for doomed in (spec_doomed, base_doomed):
        assert doomed.finish_reason == "deadline_exceeded"
    assert spec_live.output_tokens == base_live.output_tokens
    assert spec_live.finish_reason == "length"
    assert spec_eng.stats()["deadline_expired_total"] == 1
    assert spec_eng.stats()["decode_compiles"] == 1


# ---------------------------------------------------------------------------
# sharded mesh: the one-executable assertion with spec armed
# ---------------------------------------------------------------------------


def _mesh4():
    import jax

    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs a >= 4-device (virtual) mesh")
    return build_mesh(MeshPlugin(dp=1, fsdp=2, tp=2), devices=devices[:4])


@pytest.mark.slow
def test_spec_sharded_mesh_parity_one_executable(tiny_model):
    """The spec round over fsdp=2 x tp=2 (GSPMD NamedSharding, draft slice
    included) is token-identical to the single-device spec engine AND to
    the non-spec engine, with decode_compiles == 1 on the mesh."""
    mesh = _mesh4()
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 64, size=n).astype(np.int32) for n in (5, 12, 9)]
    budgets = [4, 7, 5]

    def run(spec_k, mesh_arg):
        eng = InferenceEngine(
            tiny_model,
            _cfg(spec_k=spec_k, decode_burst=2,
                 draft="early_exit:1" if spec_k else "early_exit:2"),
            mesh=mesh_arg,
        )
        reqs = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
        eng.run_until_idle(max_iterations=5000)
        return eng, [list(r.output_tokens) for r in reqs]

    _, single_spec = run(4, None)
    sharded_eng, sharded_spec = run(4, mesh)
    _, base_toks = run(0, None)
    assert sharded_spec == single_spec == base_toks
    stats = sharded_eng.stats()
    assert stats["decode_compiles"] == 1
    assert stats["mesh"] == {"fsdp": 2, "tp": 2}


# ---------------------------------------------------------------------------
# telemetry + monitor (slow: runs the engine under a recorder)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_spec_telemetry_rows_and_monitor_line(tiny_model, tmp_path):
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status
    from accelerate_tpu.telemetry import TelemetryRecorder, set_active_recorder

    recorder = TelemetryRecorder(logging_dir=str(tmp_path))
    set_active_recorder(recorder)
    try:
        eng = InferenceEngine(
            tiny_model,
            _cfg(num_slots=2, stats_interval=2, spec_k=4, draft="early_exit:1"),
        )
        rng = np.random.default_rng(4)
        for i in range(3):
            eng.add_request(rng.integers(0, 64, size=5 + i).astype(np.int32), 6)
        eng.run_until_idle(max_iterations=5000)
    finally:
        set_active_recorder(None)
        recorder.close()

    steps = [
        r for r in recorder.records
        if r.get("type") == "serving" and r.get("kind") == "step"
    ]
    assert steps, "stats_interval=2 must have emitted step rows"
    last = steps[-1]
    assert last["spec_k"] == 4 and last["spec_draft"] == "early_exit:1"
    assert last["spec_drafted_tokens"] > 0
    assert 0.0 <= last["spec_accept_rate"] <= 1.0
    assert last["spec_accepted_tokens"] <= last["spec_drafted_tokens"]

    status = collect_status(str(tmp_path))
    srv = status["serving"]
    assert srv["spec_k"] == 4 and srv["spec_drafted_tokens"] > 0
    rendered = render_status(status)
    assert "spec: k=4 (early_exit:1)" in rendered
