"""by_feature scripts stay single-feature deltas over the canonical loop
(reference ``tests/test_examples.py::ExampleDifferenceTests`` via
``test_utils/examples.py:26-146``)."""

import os

import pytest

from accelerate_tpu.test_utils.examples import assert_single_feature_delta

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")
BASES = [
    os.path.join(EXAMPLES, "nlp_example.py"),
    os.path.join(EXAMPLES, "complete_nlp_example.py"),
]

CASES = [
    ("gradient_accumulation.py", ["accelerator.accumulate(model)", "gradient_accumulation_steps"]),
    ("checkpointing.py", ["automatic_checkpoint_naming", "accelerator.save_state()"]),
    ("memory.py", ["find_executable_batch_size"]),
    ("profiler.py", ["accelerator.profile()", "ProfileKwargs"]),
    ("early_stopping.py", ["accelerator.set_trigger()", "accelerator.check_trigger()"]),
    ("local_sgd.py", ["LocalSGD", "local_sgd.step()"]),
    ("tracking.py", ["log_with"]),
    ("multi_process_metrics.py", ["samples_seen"]),
    ("ddp_comm_hook.py", ["DistributedDataParallelKwargs", "comm_hook"]),
]


@pytest.mark.parametrize("script,markers", CASES, ids=[c[0] for c in CASES])
def test_by_feature_is_single_feature_delta(script, markers):
    assert_single_feature_delta(
        os.path.join(EXAMPLES, "by_feature", script), BASES, markers
    )


def test_complete_cv_is_cv_plus_services():
    """The CV path has a freshness twin like NLP: ``complete_cv_example``
    must stay ``cv_example`` + checkpointing/resume/tracking (reference
    pairs the same two scripts in ``ExampleDifferenceTests``)."""
    assert_single_feature_delta(
        os.path.join(EXAMPLES, "complete_cv_example.py"),
        [os.path.join(EXAMPLES, "cv_example.py")],
        ["checkpointing_steps", "resume_from_checkpoint", "with_tracking"],
        max_novel=90,  # the services block is bigger than one feature delta
    )
