"""Big-model inference: meta init, device-map math, offload tiers, streaming
dispatch (reference analogs: ``tests/test_big_modeling.py`` 1050 LoC,
``tests/test_modeling_utils.py`` 1000 LoC, ``tests/test_offload.py``)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    DispatchedModel,
    cpu_offload,
    disk_offload,
    dispatch_model,
    init_empty_weights,
    load_checkpoint_and_dispatch,
    load_checkpoint_in_model,
)
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.modules import Model
from accelerate_tpu.utils.memory import find_executable_batch_size, should_reduce_batch_size
from accelerate_tpu.utils.modeling import (
    compute_module_sizes,
    dtype_byte_size,
    flat_param_shapes,
    get_balanced_memory,
    infer_auto_device_map,
)
from accelerate_tpu.utils.offload import (
    OffloadedWeightsLoader,
    load_offloaded_weight,
    offload_state_dict,
    offload_weight,
    save_offload_index,
)


# ---------------------------------------------------------------------------
# dtype / size math
# ---------------------------------------------------------------------------


def test_dtype_byte_size():
    assert dtype_byte_size(jnp.float32) == 4
    assert dtype_byte_size(jnp.bfloat16) == 2
    assert dtype_byte_size(jnp.int8) == 1
    assert dtype_byte_size("int4") == 0.5
    assert dtype_byte_size(jnp.bool_) == 1


def test_compute_module_sizes_prefix_accumulation():
    shapes = {
        "embed.weight": ((10, 4), jnp.float32),
        "layers.0.w": ((4, 4), jnp.float32),
        "layers.1.w": ((4, 4), jnp.float32),
    }
    sizes = compute_module_sizes(shapes)
    assert sizes["embed.weight"] == 160
    assert sizes["layers"] == 128
    assert sizes[""] == 288
    # dtype override halves fp32 → bf16
    assert compute_module_sizes(shapes, dtype=jnp.bfloat16)[""] == 144


def test_infer_auto_device_map_spills_over_tiers():
    shapes = {
        "a.w": ((100,), jnp.float32),  # 400 B
        "b.w": ((100,), jnp.float32),
        "c.w": ((100,), jnp.float32),
    }
    dm = infer_auto_device_map(shapes, max_memory={0: 500, "cpu": 500, "disk": float("inf")})
    assert dm == {"a": 0, "b": "cpu", "c": "disk"}


def test_infer_auto_device_map_no_split_keeps_unit_whole():
    shapes = {
        "layer.q": ((100,), jnp.float32),
        "layer.k": ((100,), jnp.float32),
    }
    dm = infer_auto_device_map(
        shapes, max_memory={0: 500, "cpu": 10**9}, no_split_prefixes=["layer"]
    )
    assert dm == {"layer": "cpu"}  # 800B doesn't fit on chip; unit stays whole
    dm2 = infer_auto_device_map(shapes, max_memory={0: 500, "cpu": 10**9})
    assert dm2 == {"layer.q": 0, "layer.k": "cpu"}  # splittable → spills


def test_infer_auto_device_map_tied_weights_colocated():
    shapes = {
        "embed": ((50,), jnp.float32),  # 200B
        "mid.w": ((100,), jnp.float32),
        "head": ((50,), jnp.float32),
    }
    dm = infer_auto_device_map(
        shapes,
        max_memory={0: 450, "cpu": 10**9},
        tied_parameters=[["embed", "head"]],
    )
    assert dm["embed"] == dm["head"] == 0  # tied pair placed together (400B)
    assert dm["mid"] == "cpu"


def test_get_balanced_memory_spreads():
    shapes = {f"layers.{i}.w": ((1000,), jnp.float32) for i in range(8)}  # 32 kB
    balanced = get_balanced_memory(shapes, max_memory={0: 10**9, 1: 10**9, "cpu": 10**9})
    assert balanced[0] == balanced[1]
    assert balanced[0] < 10**9  # clamped to ~half the model + slack


def test_flat_param_shapes_expands_stacked_layers():
    config = LlamaConfig.tiny(layers=3)
    model = LlamaForCausalLM.from_config(config)
    flat = flat_param_shapes(model, expand_stacked="layers")
    assert "layers.0.wq" in flat and "layers.2.wq" in flat
    assert flat["layers.0.wq"][0] == (64, 64)


# ---------------------------------------------------------------------------
# offload store
# ---------------------------------------------------------------------------


def test_offload_weight_roundtrip(tmp_path):
    index = {}
    w = np.random.randn(4, 6).astype(np.float32)
    index = offload_weight(w, "block.w", str(tmp_path), index)
    save_offload_index(index, str(tmp_path))
    loaded = load_offloaded_weight(str(tmp_path / "block.w.dat"), index["block.w"])
    np.testing.assert_array_equal(np.asarray(loaded), w)


def test_offload_bf16_roundtrip(tmp_path):
    import ml_dtypes

    w = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    index = offload_weight(w, "w", str(tmp_path), {})
    loaded = load_offloaded_weight(str(tmp_path / "w.dat"), index["w"])
    np.testing.assert_array_equal(np.asarray(loaded, dtype=np.float32), np.arange(8.0))


def test_offloaded_weights_loader_mixed(tmp_path):
    disk = {"d1": np.ones((2, 2)), "d2": np.zeros((3,))}
    offload_state_dict(str(tmp_path), disk)
    loader = OffloadedWeightsLoader(state_dict={"m1": np.full((2,), 7.0)}, save_folder=str(tmp_path))
    assert set(loader) == {"m1", "d1", "d2"}
    np.testing.assert_array_equal(np.asarray(loader["d1"]), disk["d1"])
    np.testing.assert_array_equal(loader["m1"], np.full((2,), 7.0))


# ---------------------------------------------------------------------------
# meta init + dispatch
# ---------------------------------------------------------------------------


def test_init_empty_weights_builds_abstract_params():
    config = LlamaConfig.tiny()
    with init_empty_weights():
        model = LlamaForCausalLM.from_config(config)
    leaves = jax.tree.leaves(model.params)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    # zero memory: shapes known without materialisation
    assert model.params["embed_tokens"].shape == (256, 64)


def _tiny_model_and_batch():
    config = LlamaConfig.tiny(layers=2)
    model = LlamaForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    return config, model, {"input_ids": jnp.asarray(ids)}


def test_cpu_offload_streaming_matches_resident():
    config, model, batch = _tiny_model_and_batch()
    ref = model.apply_fn(model.params, **batch)["logits"]
    dispatched = cpu_offload(model)
    assert isinstance(dispatched, DispatchedModel)
    out = dispatched(**batch)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_disk_offload_streaming_matches_resident(tmp_path):
    config, model, batch = _tiny_model_and_batch()
    ref = model.apply_fn(model.params, **batch)["logits"]
    dispatched = disk_offload(model, str(tmp_path))
    out = dispatched(**batch)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)
    assert os.path.exists(tmp_path / "index.json")


def test_mixed_device_map_dispatch(tmp_path):
    config, model, batch = _tiny_model_and_batch()
    ref = model.apply_fn(model.params, **batch)["logits"]
    device_map = {"embed_tokens": 0, "layers": "cpu", "norm": 0, "lm_head": "disk"}
    dispatched = dispatch_model(model, device_map, offload_dir=str(tmp_path))
    out = dispatched(**batch)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)
    assert dispatched.hf_device_map["layers"] == "cpu"


def test_load_checkpoint_in_model_with_hf_names(tmp_path):
    """Round-trip through HF-transformers llama naming incl. transposes."""
    config = LlamaConfig.tiny(layers=2)
    src = LlamaForCausalLM.from_config(config, seed=5)
    # write an HF-style checkpoint from src params
    hf = {}
    p = src.params
    hf["model.embed_tokens.weight"] = np.asarray(p["embed_tokens"])
    hf["model.norm.weight"] = np.asarray(p["norm"])
    hf["lm_head.weight"] = np.asarray(p["lm_head"]).T
    names = {
        "wq": "self_attn.q_proj", "wk": "self_attn.k_proj", "wv": "self_attn.v_proj",
        "wo": "self_attn.o_proj", "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
        "w_down": "mlp.down_proj",
    }
    for i in range(2):
        for ours, theirs in names.items():
            hf[f"model.layers.{i}.{theirs}.weight"] = np.asarray(p["layers"][ours][i]).T
        hf[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(p["layers"]["attn_norm"][i])
        hf[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(p["layers"]["mlp_norm"][i])
    np.savez(tmp_path / "model.npz", **hf)

    with init_empty_weights():
        dst = LlamaForCausalLM.from_config(config)
    load_checkpoint_in_model(dst, str(tmp_path / "model.npz"))
    for key in ("embed_tokens", "norm", "lm_head"):
        np.testing.assert_allclose(np.asarray(dst.params[key]), np.asarray(src.params[key]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(dst.params["layers"]["wq"]), np.asarray(src.params["layers"]["wq"]), rtol=1e-6
    )


def test_load_checkpoint_and_dispatch_auto(tmp_path):
    config, model, batch = _tiny_model_and_batch()
    ref = model.apply_fn(model.params, **batch)["logits"]
    from accelerate_tpu.checkpointing import save_array_dict, _flatten_tree

    save_array_dict(_flatten_tree(model.params), str(tmp_path / "model"))
    with init_empty_weights():
        empty = LlamaForCausalLM.from_config(config, seed=1)
    loaded = load_checkpoint_and_dispatch(
        empty, str(tmp_path / "model.safetensors"), device_map={"": 0}
    )
    out = loaded(**batch)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# OOM retry
# ---------------------------------------------------------------------------


def test_should_reduce_batch_size_matches_xla_oom():
    assert should_reduce_batch_size(RuntimeError("RESOURCE_EXHAUSTED: out of HBM"))
    assert not should_reduce_batch_size(ValueError("shape mismatch"))


def test_find_executable_batch_size_halves():
    attempts = []

    @find_executable_batch_size(starting_batch_size=64)
    def train(batch_size):
        attempts.append(batch_size)
        if batch_size > 16:
            raise RuntimeError("RESOURCE_EXHAUSTED: fake OOM")
        return batch_size

    assert train() == 16
    assert attempts == [64, 32, 16]


def test_find_executable_batch_size_requires_arg_name():
    @find_executable_batch_size(starting_batch_size=4)
    def bad(foo):
        return foo

    with pytest.raises(TypeError):
        bad()


def test_per_layer_device_map_straddles_tiers(tmp_path):
    """OPT-30B shape: some layers HBM-resident, the rest streamed from disk."""
    config, model, batch = _tiny_model_and_batch()
    ref = model.apply_fn(model.params, **batch)["logits"]
    device_map = {
        "embed_tokens": 0,
        "layers.0": 0,
        "layers.1": "disk",
        "norm": 0,
        "lm_head": "cpu",
    }
    dispatched = dispatch_model(model, device_map, offload_dir=str(tmp_path))
    assert any(k[1] == 0 for k in dispatched.tiered.resident_slices)
    out = dispatched(**batch)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_streaming_computes_loss_with_labels():
    config, model, batch = _tiny_model_and_batch()
    ids = np.asarray(batch["input_ids"])
    ref = model.apply_fn(model.params, input_ids=ids, labels=ids)["loss"]
    dispatched = cpu_offload(model)
    out = dispatched(input_ids=ids, labels=ids)
    np.testing.assert_allclose(float(out.loss), float(ref), rtol=2e-5)


def test_dispatch_rejects_incomplete_device_map():
    config, model, batch = _tiny_model_and_batch()
    with pytest.raises(ValueError, match="does not cover"):
        dispatch_model(model, {"layers": "cpu"})


def test_auto_device_map_per_layer_granularity_respected(tmp_path):
    """Auto-inferred maps at layer granularity must actually place layers on
    the spill tiers (regression: dispatch used to default everything to 0)."""
    config, model, batch = _tiny_model_and_batch()
    ref = model.apply_fn(model.params, **batch)["logits"]
    from accelerate_tpu.checkpointing import save_array_dict, _flatten_tree

    save_array_dict(_flatten_tree(model.params), str(tmp_path / "model"))
    with init_empty_weights():
        empty = LlamaForCausalLM.from_config(config, seed=1)
    # budget that fits embed + ~1 layer on "chip", rest must spill to cpu
    loaded = load_checkpoint_and_dispatch(
        empty, str(tmp_path / "model.safetensors"), device_map="auto",
        max_memory={0: 150_000, "cpu": 10**12},
    )
    tiers = set(map(str, loaded.hf_device_map.values()))
    assert "cpu" in tiers and "0" in tiers
    out = loaded(**batch)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# streaming pipeline: disk-read prefetch overlap + memory invariant
# ---------------------------------------------------------------------------


def test_streaming_prefetches_next_segment_load_during_compute(monkeypatch):
    """Segment i+1's host/disk load must overlap segment i's compute: wall
    time ≈ load + N·max(load, compute), not N·(load + compute)."""
    import time

    from accelerate_tpu.big_modeling import TieredParams

    N, F, C = 6, 0.04, 0.04
    params = {f"w{i}": np.full((8,), float(i), np.float32) for i in range(N)}

    def _seg_fn(i):
        def fn(seg_params, carry):
            # synchronous stand-in for blocking compute (pre-seeded below so
            # the streaming loop uses it as the "compiled" segment fn)
            time.sleep(C)
            return carry + float(np.asarray(seg_params[f"w{i}"]).sum())

        return fn

    steps = [(f"s{i}", [f"w{i}"], _seg_fn(i)) for i in range(N)]
    model = Model(lambda p: None, params, name="segmented")
    model.segments = lambda x: {
        "steps": steps,
        "init": lambda: float(x),
        "finalize": lambda c: c,
    }

    orig_fetch = TieredParams.fetch_host_or_disk

    def slow_fetch(self, path, idx=None):
        time.sleep(F)  # simulated slow disk read
        return orig_fetch(self, path, idx)

    monkeypatch.setattr(TieredParams, "fetch_host_or_disk", slow_fetch)
    dispatched = cpu_offload(model)
    # pre-seed the segment-fn cache: compute stays synchronous on the main
    # thread, so wall time directly exposes whether loads overlap compute
    dispatched._segment_fns = {f"s{i}": _seg_fn(i) for i in range(N)}
    t0 = time.monotonic()
    out = dispatched(0.0)
    elapsed = time.monotonic() - t0
    expected = sum(float(i) * 8 for i in range(N))
    assert float(out) == expected
    serial = N * (F + C)
    assert elapsed < 0.8 * serial, f"no overlap: {elapsed:.3f}s vs serial {serial:.3f}s"


def test_streaming_decode_stage_overlaps_fetch_and_compute(monkeypatch):
    """The pipeline is THREE-stage: while segment i computes, segment i+1
    decodes/places and segment i+2 reads — wall time approaches
    N·max(fetch, decode, compute), not N·(fetch + decode + compute)."""
    import time

    from accelerate_tpu.big_modeling import DispatchedModel, TieredParams

    N, F, D, C = 6, 0.03, 0.03, 0.03
    params = {f"w{i}": np.full((8,), float(i), np.float32) for i in range(N)}

    def _seg_fn(i):
        def fn(seg_params, carry):
            time.sleep(C)
            return carry + float(np.asarray(seg_params[f"w{i}"]).sum())

        return fn

    steps = [(f"s{i}", [f"w{i}"], _seg_fn(i)) for i in range(N)]
    model = Model(lambda p: None, params, name="segmented")
    model.segments = lambda x: {
        "steps": steps,
        "init": lambda: float(x),
        "finalize": lambda c: c,
    }

    orig_fetch = TieredParams.fetch_host_or_disk

    def slow_fetch(self, path, idx=None):
        time.sleep(F)
        return orig_fetch(self, path, idx)

    orig_decode = DispatchedModel._segment_decode_put

    def slow_decode(self, raw):
        time.sleep(D)
        return orig_decode(self, raw)

    monkeypatch.setattr(TieredParams, "fetch_host_or_disk", slow_fetch)
    monkeypatch.setattr(DispatchedModel, "_segment_decode_put", slow_decode)
    dispatched = cpu_offload(model)
    dispatched._segment_fns = {f"s{i}": _seg_fn(i) for i in range(N)}
    t0 = time.monotonic()
    out = dispatched(0.0)
    elapsed = time.monotonic() - t0
    assert float(out) == sum(float(i) * 8 for i in range(N))
    serial = N * (F + D + C)
    # pipeline fill (F + D) + N*max stage; allow generous scheduler slack —
    # the assertion only needs to rule out fully-serial execution
    assert elapsed < 0.75 * serial, f"stages serialized: {elapsed:.3f}s vs {serial:.3f}s"


def test_native_decoder_output_is_zero_copy_alignable():
    """The decode stage's output must be 64-byte aligned: XLA:CPU's
    device_put aliases aligned host buffers (zero copy) and memcpy's the
    rest — the difference was the single largest cost on the nf4 path."""
    from accelerate_tpu.native import aligned_empty, q4_decode_codes

    for shape in ((64, 32), (3, 5, 8), (1, 2)):
        out = aligned_empty(shape, np.int8)
        assert out.shape == shape
        assert out.ctypes.data % 64 == 0

    packed = np.random.default_rng(0).integers(0, 255, size=(16, 8), dtype=np.uint8)
    lut = np.arange(16, dtype=np.int8)
    c8 = q4_decode_codes(packed, lut)
    if c8 is not None:  # native decoder built on this host
        assert c8.ctypes.data % 64 == 0
        # decode correctness vs the pure-numpy nibble unpack (packing puts
        # the EVEN element in the high nibble — quantization.py:470)
        lo, hi = packed & 0xF, packed >> 4
        expect = np.empty((16, 16), np.int8)
        expect[:, 0::2], expect[:, 1::2] = lut[hi], lut[lo]
        np.testing.assert_array_equal(c8, expect)


def test_streaming_peak_memory_stays_below_full_model(tmp_path):
    """Memory invariant (reference pins this in
    benchmarks/big_model_inference/README.md:44-46): streaming a
    disk-offloaded model must never materialise all params on device."""
    from accelerate_tpu.big_modeling import DispatchedModel

    config = LlamaConfig.tiny(layers=8, hidden_size=64)
    model = LlamaForCausalLM.from_config(config, seed=0)
    total_bytes = sum(np.asarray(l).nbytes for l in jax.tree.leaves(model.params))
    ids = np.random.default_rng(0).integers(0, 256, size=(1, 8)).astype(np.int32)

    live_samples = []
    # hook stage 2 (decode+place) — the streaming loop's per-segment entry
    # point on the pipeline (stage 1 holds only host numpy, invisible to
    # jax.live_arrays and bounded to one segment by the single IO worker)
    orig = DispatchedModel._segment_decode_put

    def sampling(self, *a, **k):
        out = orig(self, *a, **k)
        live_samples.append(sum(x.nbytes for x in jax.live_arrays()))
        return out

    # baseline after dispatch: on the CPU backend device_get during offload
    # pins a host-copy cache on each param array, which live_arrays counts —
    # only arrays created during *streaming* are the invariant under test
    dispatched = disk_offload(model, str(tmp_path))
    baseline = sum(x.nbytes for x in jax.live_arrays())
    try:
        DispatchedModel._segment_decode_put = sampling
        dispatched(input_ids=ids)
    finally:
        DispatchedModel._segment_decode_put = orig
    peak_extra = max(live_samples) - baseline
    # resident set at any instant: ≤2 segments of weights + activations —
    # far below the whole model
    assert peak_extra < 0.7 * total_bytes, (
        f"peak {peak_extra} vs model {total_bytes}: streaming materialised too much"
    )
