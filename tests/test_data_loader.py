"""Exhaustive index-math cases for the sharded samplers — ported from the
reference's behavioural pin (``/root/reference/tests/test_data_loader.py``,
838 LoC) so shard schedules are bit-identical to Accelerate's."""

import random

import numpy as np
import pytest

from accelerate_tpu.data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SkipBatchSampler,
    default_collate,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import GradientState, PartialState


def check_batch_sampler_shards(batch_sampler, expected, split_batches=False, even_batches=True):
    shards = [
        BatchSamplerShard(batch_sampler, 2, i, split_batches=split_batches, even_batches=even_batches)
        for i in range(2)
    ]
    shard_lists = [list(s) for s in shards]
    if not split_batches:
        assert [len(s) for s in shards] == [len(e) for e in expected]
    assert shard_lists == expected


def test_batch_sampler_shards_with_no_splits():
    bs = BatchSampler(range(24), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 20]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17], [21, 22, 23]],
    ]
    check_batch_sampler_shards(bs, expected)
    check_batch_sampler_shards(BatchSampler(range(24), batch_size=3, drop_last=True), expected)

    bs = BatchSampler(range(21), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 20]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17], [0, 1, 2]],
    ]
    check_batch_sampler_shards(bs, expected)

    bs = BatchSampler(range(21), batch_size=3, drop_last=True)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17]],
    ]
    check_batch_sampler_shards(bs, expected)

    bs = BatchSampler(range(22), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 20]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17], [21, 0, 1]],
    ]
    check_batch_sampler_shards(bs, expected)

    bs = BatchSampler(range(20), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 0]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17], [1, 2, 3]],
    ]
    check_batch_sampler_shards(bs, expected)

    bs = BatchSampler(range(2), batch_size=3, drop_last=False)
    check_batch_sampler_shards(bs, [[[0, 1, 0]], [[1, 0, 1]]])

    bs = BatchSampler(range(2), batch_size=3, drop_last=True)
    check_batch_sampler_shards(bs, [[], []])


def test_batch_sampler_shards_with_splits():
    bs = BatchSampler(range(24), batch_size=4, drop_last=False)
    expected = [
        [[0, 1], [4, 5], [8, 9], [12, 13], [16, 17], [20, 21]],
        [[2, 3], [6, 7], [10, 11], [14, 15], [18, 19], [22, 23]],
    ]
    check_batch_sampler_shards(bs, expected, split_batches=True)
    check_batch_sampler_shards(
        BatchSampler(range(24), batch_size=4, drop_last=True), expected, split_batches=True
    )

    bs = BatchSampler(range(22), batch_size=4, drop_last=False)
    expected = [
        [[0, 1], [4, 5], [8, 9], [12, 13], [16, 17], [20, 21]],
        [[2, 3], [6, 7], [10, 11], [14, 15], [18, 19], [0, 1]],
    ]
    check_batch_sampler_shards(bs, expected, split_batches=True)

    bs = BatchSampler(range(21), batch_size=4, drop_last=False)
    expected = [
        [[0, 1], [4, 5], [8, 9], [12, 13], [16, 17], [20, 0]],
        [[2, 3], [6, 7], [10, 11], [14, 15], [18, 19], [1, 2]],
    ]
    check_batch_sampler_shards(bs, expected, split_batches=True)

    bs = BatchSampler(range(21), batch_size=4, drop_last=True)
    expected = [
        [[0, 1], [4, 5], [8, 9], [12, 13], [16, 17]],
        [[2, 3], [6, 7], [10, 11], [14, 15], [18, 19]],
    ]
    check_batch_sampler_shards(bs, expected, split_batches=True)

    bs = BatchSampler(range(2), batch_size=4, drop_last=False)
    check_batch_sampler_shards(bs, [[[0, 1]], [[0, 1]]], split_batches=True)


def test_batch_sampler_shards_with_no_splits_no_even():
    bs = BatchSampler(range(24), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 20]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17], [21, 22, 23]],
    ]
    check_batch_sampler_shards(bs, expected, even_batches=False)

    bs = BatchSampler(range(21), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 20]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17]],
    ]
    check_batch_sampler_shards(bs, expected, even_batches=False)

    bs = BatchSampler(range(22), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19, 20]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17], [21]],
    ]
    check_batch_sampler_shards(bs, expected, even_batches=False)

    bs = BatchSampler(range(20), batch_size=3, drop_last=False)
    expected = [
        [[0, 1, 2], [6, 7, 8], [12, 13, 14], [18, 19]],
        [[3, 4, 5], [9, 10, 11], [15, 16, 17]],
    ]
    check_batch_sampler_shards(bs, expected, even_batches=False)

    bs = BatchSampler(range(2), batch_size=3, drop_last=False)
    check_batch_sampler_shards(bs, [[[0, 1]], []], even_batches=False)


def test_batch_sampler_shards_with_splits_no_even():
    bs = BatchSampler(range(22), batch_size=4, drop_last=False)
    expected = [
        [[0, 1], [4, 5], [8, 9], [12, 13], [16, 17], [20, 21]],
        [[2, 3], [6, 7], [10, 11], [14, 15], [18, 19]],
    ]
    check_batch_sampler_shards(bs, expected, split_batches=True, even_batches=False)

    bs = BatchSampler(range(21), batch_size=4, drop_last=False)
    expected = [
        [[0, 1], [4, 5], [8, 9], [12, 13], [16, 17], [20]],
        [[2, 3], [6, 7], [10, 11], [14, 15], [18, 19]],
    ]
    check_batch_sampler_shards(bs, expected, split_batches=True, even_batches=False)

    bs = BatchSampler(range(2), batch_size=4, drop_last=False)
    check_batch_sampler_shards(bs, [[[0, 1]], []], split_batches=True, even_batches=False)


def test_batch_sampler_with_varying_batch_size():
    batch_sampler = [[0, 1, 2], [3, 4], [5, 6, 7, 8], [9, 10, 11], [12, 13]]
    shards = [BatchSamplerShard(batch_sampler, 2, i, even_batches=False) for i in range(2)]
    assert len(shards[0]) == 3
    assert len(shards[1]) == 2
    assert list(shards[0]) == [[0, 1, 2], [5, 6, 7, 8], [12, 13]]
    assert list(shards[1]) == [[3, 4], [9, 10, 11]]


def test_batch_sampler_shard_validation():
    with pytest.raises(ValueError):
        BatchSamplerShard(BatchSampler(range(10), batch_size=3, drop_last=False), 2, 0, split_batches=True)
    with pytest.raises(ValueError):
        BatchSamplerShard([[0, 1]], 2, 0, even_batches=True)


class RandomLengthIterable:
    """Deterministic random-length stream (reference RandomIterableDataset)."""

    def __init__(self, p_stop=0.01, max_length=1000):
        self.p_stop = p_stop
        self.max_length = max_length

    def __iter__(self):
        count, stop = 0, False
        while not stop and count < self.max_length:
            yield count
            count += 1
            stop = random.random() < self.p_stop


def check_iterable_dataset_shards(dataset, seed, batch_size, drop_last=False, num_processes=2, split_batches=False):
    random.seed(seed)
    reference = list(dataset)
    shards = [
        IterableDatasetShard(
            dataset,
            batch_size=batch_size,
            drop_last=drop_last,
            num_processes=num_processes,
            process_index=i,
            split_batches=split_batches,
        )
        for i in range(num_processes)
    ]
    shard_lists = []
    for s in shards:
        random.seed(seed)
        shard_lists.append(list(s))

    shard_batch_size = batch_size // num_processes if split_batches else batch_size
    first = shard_lists[0]
    for lst in shard_lists[1:]:
        assert len(lst) == len(first)
        assert len(lst) % shard_batch_size == 0

    observed = []
    for idx in range(0, len(first), shard_batch_size):
        for lst in shard_lists:
            observed += lst[idx : idx + shard_batch_size]
    if not drop_last:
        while len(reference) < len(observed):
            reference += reference
    assert observed == reference[: len(observed)]


@pytest.mark.parametrize("drop_last", [False, True])
@pytest.mark.parametrize("split_batches", [False, True])
@pytest.mark.parametrize("max_length", [1000, 2])
def test_iterable_dataset_shard(drop_last, split_batches, max_length):
    dataset = RandomLengthIterable(max_length=max_length)
    check_iterable_dataset_shards(dataset, 42, batch_size=4, drop_last=drop_last, split_batches=split_batches)


def test_seedable_sampler_determinism():
    s1 = SeedableRandomSampler(10, seed=7, epoch=0)
    s2 = SeedableRandomSampler(10, seed=7, epoch=0)
    assert list(s1) == list(s2)
    s2.set_epoch(1)
    assert list(s1) != list(s2)
    assert sorted(list(s2)) == list(range(10))


def test_default_collate_dict_and_arrays():
    samples = [{"x": np.ones((2,)), "y": 1}, {"x": np.zeros((2,)), "y": 2}]
    batch = default_collate(samples)
    assert batch["x"].shape == (2, 2)
    np.testing.assert_array_equal(batch["y"], [1, 2])


class _ArrayDataset:
    def __init__(self, n=32, width=3):
        self.x = np.arange(n * width, dtype=np.float32).reshape(n, width)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "label": np.int32(i % 2)}


def test_dataloader_shard_yields_global_sharded_arrays():
    import jax

    state = PartialState()
    dl = prepare_data_loader(_ArrayDataset(32), num_processes=1, process_index=0)
    # raw loader: wrap into batches of 1 by default
    batches = list(dl)
    assert len(batches) == 32
    assert isinstance(batches[0]["x"], jax.Array)


class _SimpleLoader:
    """Duck-typed user loader (native dict interface)."""

    def __init__(self, dataset, batch_size, drop_last=False, shuffle=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.sampler = None
        self.batch_sampler = None
        self.collate_fn = None


def test_prepare_data_loader_batching_and_end_flag():
    state = PartialState()
    gs = GradientState()
    dl = prepare_data_loader(_SimpleLoader(_ArrayDataset(32), batch_size=8))
    seen = []
    for batch in dl:
        seen.append(np.asarray(batch["x"]))
        if len(seen) < 4:
            assert not dl.end_of_dataloader
        else:
            assert dl.end_of_dataloader
    assert len(seen) == 4
    assert seen[0].shape == (8, 3)
    np.testing.assert_array_equal(np.concatenate(seen), _ArrayDataset(32).x)


def test_dataloader_remainder_propagates_to_gradient_state():
    state = PartialState()
    gs = GradientState()
    dl = prepare_data_loader(_SimpleLoader(_ArrayDataset(30), batch_size=8))
    it = iter(dl)
    next(it)
    assert gs.in_dataloader
    assert gs.remainder == 30 % dl.total_batch_size
    for _ in it:
        pass
    assert not gs.in_dataloader


def test_skip_first_batches():
    state = PartialState()
    dl = prepare_data_loader(_SimpleLoader(_ArrayDataset(32), batch_size=8))
    skipped = skip_first_batches(dl, 2)
    batches = [np.asarray(b["x"]) for b in skipped]
    assert len(batches) == 2
    np.testing.assert_array_equal(batches[0], _ArrayDataset(32).x[16:24])
    assert len(skipped) == 2


def test_skip_batch_sampler():
    bs = BatchSampler(range(16), batch_size=4, drop_last=False)
    skip = SkipBatchSampler(bs, skip_batches=2)
    assert list(skip) == [[8, 9, 10, 11], [12, 13, 14, 15]]
    assert len(skip) == 2


def test_set_epoch_reshuffles():
    state = PartialState()
    dl = prepare_data_loader(
        _SimpleLoader(_ArrayDataset(16), batch_size=4), use_seedable_sampler=True, put_on_device=False
    )
    dl.set_epoch(0)
    first = [np.asarray(b["x"]) for b in dl]
    dl.set_epoch(1)
    second = [np.asarray(b["x"]) for b in dl]
    assert not all(np.array_equal(a, b) for a, b in zip(first, second))
    # same multiset of rows
    assert sorted(np.concatenate(first)[:, 0].tolist()) == sorted(np.concatenate(second)[:, 0].tolist())
