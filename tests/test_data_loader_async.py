"""Async prefetch, streaming schedule, dispatcher, and stateful resume
(reference analogs: ``MpDeviceLoaderWrapper`` ``data_loader.py:632``,
``DataLoaderDispatcher`` :682, StatefulDataLoader support :449)."""

import itertools
import time

import numpy as np
import pytest

from accelerate_tpu.data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoaderDispatcher,
    DataLoaderShard,
    SequentialSampler,
    prepare_data_loader,
    skip_first_batches,
)


class _Dataset:
    def __init__(self, n, delay=0.0):
        self.n = n
        self.delay = delay

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if self.delay:
            time.sleep(self.delay)
        return {"x": np.float32(i)}


def _shard_loader(n=32, batch_size=4, prefetch=2, delay=0.0, num_processes=1):
    sampler = BatchSampler(SequentialSampler(n), batch_size=batch_size)
    shard = BatchSamplerShard(sampler, num_processes=num_processes, process_index=0)
    return DataLoaderShard(
        _Dataset(n, delay=delay), batch_sampler=shard, sharding=None,
        prefetch_batches=prefetch,
    )


def test_prefetch_and_sync_paths_yield_identical_batches():
    a = [b["x"].tolist() for b in _shard_loader(prefetch=2)]
    b = [b["x"].tolist() for b in _shard_loader(prefetch=0)]
    assert a == b
    assert len(a) == 8


def test_prefetch_overlaps_collate_with_consumer():
    """With slow per-sample loading and a slow consumer, total wall time
    must approach max(load, consume), not their sum."""
    n, bs, delay = 24, 4, 0.01
    per_batch = bs * delay  # 40ms of "collation" per batch
    loader = _shard_loader(n=n, batch_size=bs, prefetch=3, delay=delay)
    t0 = time.monotonic()
    count = 0
    for _ in loader:
        time.sleep(per_batch)  # consumer work, same cost as producer
        count += 1
    elapsed = time.monotonic() - t0
    n_batches = n // bs
    serial = 2 * n_batches * per_batch
    # overlap should cut ≥25% off the serial time (generous for CI jitter)
    assert elapsed < 0.75 * serial, f"no overlap: {elapsed:.3f}s vs serial {serial:.3f}s"
    assert count == n_batches


def test_prefetch_propagates_exceptions():
    class _Bad(_Dataset):
        def __getitem__(self, i):
            if i >= 8:
                raise RuntimeError("boom at 8")
            return {"x": np.float32(i)}

    sampler = BatchSampler(SequentialSampler(16), batch_size=4)
    shard = BatchSamplerShard(sampler, num_processes=1, process_index=0)
    loader = DataLoaderShard(_Bad(16), batch_sampler=shard, sharding=None, prefetch_batches=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)


def test_early_break_stops_producer_thread():
    import threading

    before = {t.name for t in threading.enumerate()}
    loader = _shard_loader(n=64, batch_size=4, prefetch=2)
    for i, _ in enumerate(loader):
        if i == 1:
            break
    time.sleep(0.3)
    leaked = [
        t for t in threading.enumerate()
        if t.name == "dataloader-prefetch" and t.is_alive() and t.name not in before
    ]
    assert not leaked


def test_streaming_schedule_is_lazy():
    """The round-robin shard must not consume the whole sampler up front."""
    consumed = []

    class _CountingSampler:
        batch_size = 4
        drop_last = False

        def __len__(self):
            return 1000

        def __iter__(self):
            for i in range(1000):
                consumed.append(i)
                yield list(range(i * 4, i * 4 + 4))

    shard = BatchSamplerShard(_CountingSampler(), num_processes=2, process_index=0)
    it = iter(shard)
    next(it)
    assert len(consumed) < 10, f"schedule materialised {len(consumed)} batches eagerly"


def test_streaming_schedule_matches_reference_semantics():
    """Pin the even_batches wraparound math (reference data_loader.py:189-256)
    across uneven tails."""
    for n, bs, P in [(10, 3, 2), (17, 4, 4), (8, 4, 2), (7, 2, 4), (3, 2, 4)]:
        sampler = BatchSampler(SequentialSampler(n), batch_size=bs)
        per_proc = [
            list(BatchSamplerShard(sampler, num_processes=P, process_index=p))
            for p in range(P)
        ]
        lens = {len(x) for x in per_proc}
        assert len(lens) == 1, f"uneven counts {lens} for n={n},bs={bs},P={P}"
        for batches in per_proc:
            assert all(len(b) == bs for b in batches)
        # every dataset index appears at least once
        seen = set(itertools.chain.from_iterable(itertools.chain.from_iterable(per_proc)))
        assert seen == set(range(n))


def test_dispatcher_single_process_matches_shard():
    loader = prepare_data_loader(
        _Dataset(32), num_processes=1, process_index=0, put_on_device=False,
        dispatch_batches=True,
    )
    assert isinstance(loader, DataLoaderDispatcher)
    xs = list(itertools.chain.from_iterable(b["x"].tolist() for b in loader))
    assert xs == [float(i) for i in range(32)]


def test_dispatcher_iterable_dataset():
    class _Stream:
        def __iter__(self):
            return iter({"x": np.float32(i)} for i in range(12))

    loader = prepare_data_loader(
        _Stream(), num_processes=1, process_index=0, put_on_device=False,
        dispatch_batches=True,
    )
    xs = list(itertools.chain.from_iterable(b["x"].tolist() for b in loader))
    assert xs == [float(i) for i in range(12)]


def test_state_dict_roundtrip_resumes_mid_epoch():
    loader = _shard_loader(n=32, batch_size=4)
    seen = []
    state = None
    for i, batch in enumerate(loader):
        seen.append(batch["x"].tolist())
        if i == 2:
            state = loader.state_dict()
            break
    assert state["batches_yielded"] == 3

    fresh = _shard_loader(n=32, batch_size=4)
    fresh.load_state_dict(state)
    rest = [b["x"].tolist() for b in fresh]
    full = [b["x"].tolist() for b in _shard_loader(n=32, batch_size=4)]
    assert seen + rest == full


def test_state_dict_after_full_epoch_does_not_reskip():
    loader = _shard_loader(n=16, batch_size=4)
    list(loader)
    state = loader.state_dict()
    assert state["batches_yielded"] == 0
    fresh = _shard_loader(n=16, batch_size=4)
    fresh.load_state_dict(state)
    assert len(list(fresh)) == 4


def test_skip_first_batches_still_works_with_prefetch():
    loader = _shard_loader(n=32, batch_size=4)
    skipped = skip_first_batches(loader, 3)
    xs = [b["x"].tolist() for b in skipped]
    assert xs[0] == [12.0, 13.0, 14.0, 15.0]
    assert len(xs) == 5
