"""Multi-replica router (``serving/router.py`` + ``serving/replica.py`` +
``accelerate-tpu route``).

Placement/affinity/requeue policy runs against in-process stub replicas
(no jax, no subprocess — tier-1 cheap). Durability — kill -9 a replica
mid-stream, SIGTERM drain — is proven against REAL serve processes through
the real CLI, the same way the resilience kill→resume tests work.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from accelerate_tpu.serving.replica import ReplicaError, ReplicaHandle
from accelerate_tpu.serving.router import Router

# ---------------------------------------------------------------------------
# stub-replica policy tests (tier-1: no jax, no processes)
# ---------------------------------------------------------------------------


class StubReplica(ReplicaHandle):
    """In-process replica double: `generate` sleeps `latency` then answers;
    `down=True` makes dispatch fail at the transport level and health
    checks go silent (a kill -9 as the router experiences it)."""

    def __init__(self, replica_id, latency=0.0):
        super().__init__(replica_id, f"http://stub/{replica_id}")
        self.state = "ready"
        self.latency = latency
        self.down = False
        self.handled = []
        self._hlock = threading.Lock()

    def check_health(self, timeout=2.0):
        if self.down:
            return None
        self.last_heartbeat = time.time()
        return {"state": self.state, "queue_depth": 0, "active_slots": 0}

    def generate(self, payload, timeout=None):
        if self.down:
            raise ReplicaError(f"stub {self.replica_id} is down")
        time.sleep(self.latency)
        with self._hlock:
            self.handled.append(payload)
        return {
            "id": payload.get("id"),
            "tokens": [1, 2, 3],
            "finish_reason": "length",
        }


def _router(replicas, **kw):
    kw.setdefault("health_interval", 60.0)  # policy tests drive health manually
    return Router(replicas, **kw)


def test_prefix_affinity_prefers_warm_replica():
    """Free requests sharing a leading block hash pile onto the replica
    that recently served that prefix (its radix cache is warm), even when
    it is no longer the least-loaded choice; short prompts never affine."""
    from accelerate_tpu.serving.router import AFFINITY_PREFIX_TOKENS

    r0, r1 = StubReplica(0, latency=0.3), StubReplica(1, latency=0.3)
    router = _router([r0, r1])
    shared = list(range(AFFINITY_PREFIX_TOKENS)) + [7, 7]
    try:
        first = router.submit({"id": "w0", "prompt": shared})
        assert first.done.wait(timeout=30)
        assert any(p["id"] == "w0" for p in r0.handled)  # idle tie → replica 0
        # skew load toward r0 with a short (non-affining) request...
        router.submit({"id": "f1", "prompt": [1, 2]})  # → r0 (tie at 0,0)
        time.sleep(0.1)
        # ...yet the shared-prefix request still lands on warm r0, while a
        # cold long prompt balances to the emptier r1
        warm = router.submit({"id": "w1", "prompt": shared + [9]})
        cold = router.submit({"id": "c1", "prompt": [500 + i for i in range(20)]})
        assert warm.done.wait(timeout=30) and cold.done.wait(timeout=30)
        assert router.wait_idle(timeout=30)
        assert any(p["id"] == "w1" for p in r0.handled)
        assert any(p["id"] == "c1" for p in r1.handled)
    finally:
        router.close()


def test_least_loaded_placement_splits_across_replicas():
    r0, r1 = StubReplica(0, latency=0.5), StubReplica(1, latency=0.5)
    router = _router([r0, r1])
    try:
        tickets = [router.submit({"id": i, "prompt": [1]}) for i in range(4)]
        assert router.wait_idle(timeout=30)
        assert all(t.result["tokens"] == [1, 2, 3] for t in tickets)
        # with both replicas slower than dispatch, least-loaded alternates
        assert len(r0.handled) == 2 and len(r1.handled) == 2
    finally:
        router.close()


def test_session_affinity_beats_least_loaded():
    r0, r1 = StubReplica(0, latency=0.5), StubReplica(1, latency=0.5)
    router = _router([r0, r1])
    try:
        first = router.submit({"id": "s1", "prompt": [1], "session_id": "chat-a"})
        assert first.done.wait(timeout=30)
        assert any(p["id"] == "s1" for p in r0.handled)  # idle tie → replica 0
        # skew load so replica 1 is now the least-loaded choice...
        router.submit({"id": "f1", "prompt": [1]})  # → r0 (tie)
        router.submit({"id": "f2", "prompt": [1]})  # → r1
        router.submit({"id": "f3", "prompt": [1]})  # → r0 (tie at 1,1)
        time.sleep(0.2)  # let dispatch place the free requests
        # ...yet the session request still lands on its warm replica 0
        sticky = router.submit({"id": "s2", "prompt": [1], "session_id": "chat-a"})
        assert router.wait_idle(timeout=30)
        assert any(p["id"] == "s2" for p in r0.handled)
        assert sticky.result["finish_reason"] == "length"
    finally:
        router.close()


def test_dead_replica_requeues_and_releases_sessions():
    r0, r1 = StubReplica(0, latency=0.2), StubReplica(1, latency=0.2)
    router = _router([r0, r1])
    try:
        warm = router.submit({"id": "w", "prompt": [1], "session_id": "chat-a"})
        assert warm.done.wait(timeout=30)
        assert any(p["id"] == "w" for p in r0.handled)
        r0.down = True  # kill -9, as the router sees it
        after = [
            router.submit({"id": f"a{i}", "prompt": [1], "session_id": "chat-a"})
            for i in range(3)
        ]
        assert router.wait_idle(timeout=30)
        # every request answered exactly once, by the survivor
        for t in after:
            assert t.result["finish_reason"] == "length"
        assert {p["id"] for p in r1.handled} >= {"a0", "a1", "a2"}
        assert r0.state == "dead" and not r0.sessions
        stats = router.stats()
        assert stats["dead"] == 1 and stats["delivered"] == 4
        assert stats["requeues"] >= 1
    finally:
        router.close()


def test_wedged_replica_inflight_rescued():
    """A replica whose process stays alive but stops answering (engine
    deadlock) holds its POSTed requests on an open socket forever — no
    transport error ever fires the normal requeue. Marking it dead must
    rescue the stranded in-flight requests onto a survivor."""
    release = threading.Event()

    class WedgedStub(StubReplica):
        wedged = False

        def check_health(self, timeout=2.0):
            if self.wedged:
                return None  # /healthz starved, like the real wedge
            return super().check_health(timeout)

        def generate(self, payload, timeout=None):
            if self.wedged:
                release.wait(30)  # socket open, no answer, no error
                raise ReplicaError("connection reset at teardown")
            return super().generate(payload, timeout)

    r0, r1 = WedgedStub(0), StubReplica(1)
    r0.wedged = True
    router = _router([r0, r1], health_interval=0.05)
    try:
        # idle tie-break sends the first request to the wedged replica
        ticket = router.submit({"id": "stuck", "prompt": [1]})
        assert ticket.done.wait(timeout=30), "stranded request never rescued"
        assert ticket.result["tokens"] == [1, 2, 3]
        assert any(p["id"] == "stuck" for p in r1.handled)
        assert r0.state == "dead"
        stats = router.stats()
        assert stats["delivered"] == 1 and stats["requeues"] >= 1
    finally:
        release.set()
        router.close()


def test_request_timeout_requeues_without_marking_dead():
    """A request_timeout expiry on a slow-but-alive replica requeues the
    ticket WITHOUT walking the death path: the replica keeps its `ready`
    state and a clean failure counter (a dead replica resets the
    connection instantly — a timeout is never death evidence)."""
    from accelerate_tpu.serving.replica import ReplicaTimeout

    class SlowStub(StubReplica):
        def generate(self, payload, timeout=None):
            if timeout is not None and self.latency > timeout:
                time.sleep(timeout)
                raise ReplicaTimeout(f"stub {self.replica_id}: request_timeout")
            return super().generate(payload, timeout)

    fast, slow = StubReplica(0, latency=0.05), SlowStub(1, latency=10.0)
    router = _router([fast, slow], request_timeout=0.1)
    try:
        # skew the fast replica so least-loaded sends the probe to slow r1;
        # un-skew it mid-timeout so the requeued attempt balances to r0
        fast.queue_depth = 2
        threading.Timer(0.12, lambda: setattr(fast, "queue_depth", 0)).start()
        ticket = router.submit({"id": "t0", "prompt": [1]})
        assert ticket.done.wait(timeout=30)
        assert ticket.result["tokens"] == [1, 2, 3]
        assert any(p["id"] == "t0" for p in fast.handled)  # requeued over
        assert slow.state == "ready", "timeout must not mark the replica dead"
        assert slow.consecutive_failures == 0
        stats = router.stats()
        assert stats["dead"] == 0 and stats["requeues"] >= 1
    finally:
        router.close()


def test_deadline_expires_in_queue_and_on_retry():
    """A ticket whose deadline passes while queued is answered with a
    deadline-exceeded error row instead of ever being dispatched; the
    remaining budget is forwarded to the replica on dispatch."""
    seen = []

    class Recording(StubReplica):
        def generate(self, payload, timeout=None):
            seen.append(dict(payload))
            return super().generate(payload, timeout)

    r0 = Recording(0)
    r0.state = "starting"  # hold dispatch: tickets really wait in the queue
    router = _router([r0])
    try:
        first = router.submit({"id": "slow", "prompt": [1], "deadline_ms": 60_000})
        doomed = router.submit({"id": "doomed", "prompt": [1], "deadline_ms": 20})
        # the queue sweep answers the expired ticket even with no replica
        # dispatchable — a caller's deadline must not wait for capacity
        assert doomed.done.wait(timeout=30)
        assert "deadline_exceeded" in doomed.result["error"]
        r0.state = "ready"
        assert first.done.wait(timeout=30)
        assert first.result["tokens"] == [1, 2, 3]
        # the dispatched ticket carried its REMAINING budget, not the original
        sent = [p for p in seen if p.get("id") == "slow"]
        assert sent and 0 < sent[0]["deadline_ms"] < 60_000
        assert not any(p.get("id") == "doomed" for p in seen)
        stats = router.stats()
        assert stats["deadline_expired"] == 1 and stats["delivered"] == 2
    finally:
        router.close()


def test_malformed_deadline_answers_error_row():
    r0 = StubReplica(0)
    router = _router([r0])
    try:
        ticket = router.submit({"id": "bad", "prompt": [1], "deadline_ms": "soon"})
        assert ticket.done.wait(timeout=10)
        assert "malformed deadline_ms" in ticket.result["error"]
        assert not r0.handled
        assert router.stats()["rejected"] == 1
    finally:
        router.close()


def test_bounded_queue_sheds_batch_before_interactive():
    """Load-shed admission: at max_queue_depth an interactive arrival
    displaces the newest queued batch ticket (explicit over-capacity error
    row); with no batch ticket left, the arrival itself is shed. Nothing
    is ever silently dropped."""
    r0 = StubReplica(0)
    r0.state = "starting"  # not dispatchable yet: the queue really builds
    router = _router([r0], max_queue_depth=2)
    try:
        b1 = router.submit({"id": "b1", "prompt": [1], "priority": "batch"})
        b2 = router.submit({"id": "b2", "prompt": [1], "priority": "batch"})
        # interactive arrival over a full queue sheds the NEWEST batch
        # ticket (b2 — it has waited the least)
        i1 = router.submit({"id": "i1", "prompt": [1]})
        assert b2.done.wait(timeout=10)
        assert "over capacity" in b2.result["error"]
        # the next interactive arrival displaces the remaining batch ticket
        i2 = router.submit({"id": "i2", "prompt": [1]})
        assert b1.done.wait(timeout=10)
        assert "over capacity" in b1.result["error"]
        # with only interactive queued, an interactive arrival is itself
        # shed (never displaces its own class)...
        i3 = router.submit({"id": "i3", "prompt": [1]})
        assert i3.done.wait(timeout=10)
        assert "over capacity" in i3.result["error"]
        # ...as is a batch arrival (batch never displaces anything)
        b3 = router.submit({"id": "b3", "prompt": [1], "priority": "batch"})
        assert b3.done.wait(timeout=10)
        assert "over capacity" in b3.result["error"]
        r0.state = "ready"  # open the floodgate; survivors drain
        assert router.wait_idle(timeout=30)
        assert i1.result["tokens"] == [1, 2, 3]
        assert i2.result["tokens"] == [1, 2, 3]
        stats = router.stats()
        assert stats["shed"] == 4 and stats["delivered"] == 4
    finally:
        router.close()


def test_stop_admission_answers_instead_of_dropping():
    r0 = StubReplica(0)
    router = _router([r0])
    try:
        router.stop_admission()
        ticket = router.submit({"id": "late", "prompt": [1]})
        assert ticket.done.wait(timeout=10)
        assert "draining" in ticket.result["error"]
        assert router.stats()["rejected"] == 1 and not r0.handled
    finally:
        router.close()


def test_drain_finishes_inflight_before_returning(tmp_path):
    r0 = StubReplica(0, latency=0.3)
    router = _router([r0], logging_dir=str(tmp_path))
    tickets = [router.submit({"id": i, "prompt": [1]}) for i in range(2)]
    assert router.drain(timeout=30)
    assert all(t.result["finish_reason"] == "length" for t in tickets)
    # the fleet trail recorded the terminal state
    trail = (tmp_path / "router" / "replicas.jsonl").read_text().splitlines()
    last = json.loads(trail[-1])
    assert last["state"] in ("draining", "terminated")


def test_fleet_rows_carry_health_fields(tmp_path):
    r0 = StubReplica(0)
    router = Router([r0], logging_dir=str(tmp_path), health_interval=0.05)
    try:
        time.sleep(0.4)
    finally:
        router.close()
    rows = [
        json.loads(line)
        for line in (tmp_path / "router" / "replicas.jsonl").read_text().splitlines()
    ]
    assert rows
    row = rows[-1]
    assert row["replica_id"] == 0 and row["state"] == "ready"
    assert {"queue_depth", "active_slots", "in_flight", "heartbeat_age_s"} <= set(row)


# ---------------------------------------------------------------------------
# monitor fleet panel (tier-1: pure file reads)
# ---------------------------------------------------------------------------


def _write_fleet(tmp_path, rows):
    d = tmp_path / "router"
    d.mkdir(exist_ok=True)
    with open(d / "replicas.jsonl", "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_monitor_fleet_panel_and_dead_detection(tmp_path):
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status

    now = time.time()
    _write_fleet(
        tmp_path,
        [
            {"schema": 1, "ts": now - 2, "replica_id": 0, "state": "ready",
             "queue_depth": 3, "active_slots": 2, "num_slots": 4, "in_flight": 2,
             "heartbeat_age_s": 0.1},
            {"schema": 1, "ts": now - 1, "replica_id": 1, "state": "dead",
             "queue_depth": 0, "active_slots": 0, "num_slots": 4, "in_flight": 0,
             "heartbeat_age_s": 9.0},
            {"schema": 1, "ts": now, "replica_id": 0, "state": "ready",
             "queue_depth": 1, "active_slots": 2, "num_slots": 4, "in_flight": 1,
             "heartbeat_age_s": 0.2},
        ],
    )
    status = collect_status(str(tmp_path), now=now)
    fleet = status["fleet"]
    assert [r["replica_id"] for r in fleet] == [0, 1]
    assert fleet[0]["state"] == "ready" and fleet[0]["queue_depth"] == 1  # newest row wins
    assert status["fleet_dead"] == [1]
    text = render_status(status)
    assert "fleet" in text and "DEAD" in text


def test_monitor_fleet_wedged_on_stale_rows(tmp_path):
    from accelerate_tpu.diagnostics.monitor import ROUTER_STALE_S, collect_status

    now = time.time()
    _write_fleet(
        tmp_path,
        [{"schema": 1, "ts": now - ROUTER_STALE_S - 5, "replica_id": 0,
          "state": "ready", "queue_depth": 0, "active_slots": 0, "in_flight": 0}],
    )
    status = collect_status(str(tmp_path), now=now)
    assert status["fleet_dead"] == [0]
    # a cleanly terminated fleet is NOT dead, however old the trail
    _write_fleet(
        tmp_path,
        [{"schema": 1, "ts": now - 500, "replica_id": 0, "state": "terminated",
          "queue_depth": 0, "active_slots": 0, "in_flight": 0}],
    )
    status = collect_status(str(tmp_path), now=now)
    assert status["fleet_dead"] == []


def test_monitor_once_exit_2_on_dead_replica(tmp_path, capsys):
    from accelerate_tpu.commands.accelerate_cli import main

    _write_fleet(
        tmp_path,
        [{"schema": 1, "ts": time.time(), "replica_id": 0, "state": "dead",
          "queue_depth": 0, "active_slots": 0, "in_flight": 0}],
    )
    assert main(["monitor", str(tmp_path), "--once"]) == 2
    out = capsys.readouterr().out
    assert "DEAD" in out


# ---------------------------------------------------------------------------
# real-process durability (the acceptance bars): kill -9 + SIGTERM drain
# ---------------------------------------------------------------------------

_TINY_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "64", "--prefill-chunk", "8", "--decode-burst", "2",
]


def _cli_env():
    """Single-device CPU replicas: strip the 8-device test mesh so each
    spawned jax process starts fast and the box is not oversubscribed."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_TELEMETRY", None)
    return env


def _read_lines(stream, sink):
    for line in stream:
        line = line.strip()
        if line:
            sink.append(line)


def _start_reader(proc, sink):
    t = threading.Thread(target=_read_lines, args=(proc.stdout, sink), daemon=True)
    t.start()
    return t


def _wait_results(sink, n, timeout, proc=None):
    deadline = time.monotonic() + timeout
    while len(sink) < n and time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            break
        time.sleep(0.1)
    return [json.loads(line) for line in sink]


def _req(i, session=None, n_new=4):
    payload = {"id": i, "prompt": [1 + (i % 5), 7, 3], "max_new_tokens": n_new}
    if session is not None:
        payload["session_id"] = session
    return json.dumps(payload) + "\n"


def test_route_cli_survives_kill9_mid_stream(tmp_path):
    """Acceptance: kill -9 one of two replicas with requests in flight —
    every request is answered exactly once (requeued to the survivor)."""
    logdir = tmp_path / "fleet"
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "2", "--logging-dir", str(logdir),
         "--health-interval", "0.2", *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results = []
    _start_reader(proc, results)
    try:
        # warm both replicas with sticky sessions so the victim holds state
        for i in range(4):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}"))
        proc.stdin.flush()
        assert len(_wait_results(results, 4, timeout=240, proc=proc)) == 4, (
            f"fleet never answered warmup; rc={proc.poll()}"
        )

        # find a live replica pid from the fleet trail and kill -9 it with
        # the next wave already submitted (in flight on both replicas)
        rows = [
            json.loads(line)
            for line in (logdir / "router" / "replicas.jsonl").read_text().splitlines()
        ]
        pids = {r["replica_id"]: r["pid"] for r in rows if r.get("pid")}
        assert len(pids) == 2
        for i in range(4, 12):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}", n_new=8))
        proc.stdin.flush()
        os.kill(pids[0], signal.SIGKILL)

        parsed = _wait_results(results, 12, timeout=240, proc=proc)
        proc.stdin.close()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    ids = [r.get("id") for r in parsed]
    assert sorted(ids) == list(range(12)), f"lost/duplicated requests: {sorted(ids)}"
    assert len(ids) == len(set(ids)), "duplicated delivery"
    errors = [r for r in parsed if "error" in r]
    assert not errors, f"requests lost to the kill: {errors}"
    # the router noticed the death
    rows = [
        json.loads(line)
        for line in (logdir / "router" / "replicas.jsonl").read_text().splitlines()
    ]
    assert any(r["state"] == "dead" for r in rows)


def test_route_cli_sigterm_drains_and_exits_zero(tmp_path):
    """Acceptance: SIGTERM mid-stream answers every in-flight request, then
    exits 0 (replica drained via its own SIGTERM path underneath)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "1", "--logging-dir", str(tmp_path),
         "--health-interval", "0.2", *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results = []
    _start_reader(proc, results)
    try:
        proc.stdin.write(_req(0))  # proves the fleet is up before the burst
        proc.stdin.flush()
        assert len(_wait_results(results, 1, timeout=240, proc=proc)) == 1
        for i in range(1, 5):
            proc.stdin.write(_req(i, n_new=8))
        proc.stdin.flush()
        time.sleep(0.3)  # let the pipe land in the router before the signal
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    answered = {r.get("id") for r in parsed}
    assert answered == set(range(5)), f"drain lost requests: {sorted(answered)}"
    # in-flight requests were *completed*, not error'd out
    completed = [r for r in parsed if "tokens" in r]
    assert completed, "drain answered nothing with a real completion"


def test_serve_cli_sigterm_drains_inflight(tmp_path):
    """Satellite: the single-engine serve CLI drains on SIGTERM — stops
    admission, finishes in-flight via run_until_idle, answers stragglers,
    exits 0."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "serve", *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results = []
    _start_reader(proc, results)
    try:
        proc.stdin.write(_req(0))
        proc.stdin.flush()
        assert len(_wait_results(results, 1, timeout=240, proc=proc)) == 1, (
            f"serve never answered; rc={proc.poll()}"
        )
        for i in range(1, 4):
            proc.stdin.write(_req(i, n_new=8))
        proc.stdin.flush()
        time.sleep(0.3)  # let the reader thread consume the pipe first
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    assert {r.get("id") for r in parsed} == set(range(4))
    assert all("tokens" in r for r in parsed), f"straggler lost: {parsed}"


# ---------------------------------------------------------------------------
# serve front end /healthz state machine (in-process, stub engine)
# ---------------------------------------------------------------------------


class _StubScheduler:
    queue_depth = 2

    def active(self, state=None):
        return [object()]

    def has_work(self):
        return False


class _StubEngine:
    scheduler = _StubScheduler()
    config = type("C", (), {"num_slots": 4})()

    def stats(self):
        return {"queue_depth": 2, "completed": 0, "tokens_emitted": 0,
                "decode_compiles": 1, "iterations": 0}

    def step(self):
        return []


def _probe(url, timeout=5):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_serve_healthz_state_machine(monkeypatch):
    import queue as queue_mod
    import socket
    import urllib.error

    from accelerate_tpu.commands import serve as serve_mod
    from accelerate_tpu.commands.serve import ServeHealth, _serve_http

    # hold the drain grace open so probing the `draining` state can't race
    # the loop's exit; the test ends the loop via `stop` instead
    monkeypatch.setattr(serve_mod, "_DRAIN_IDLE_GRACE_S", 60.0)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    health = ServeHealth()
    health.mark_ready()
    stop = threading.Event()
    inbox: queue_mod.Queue = queue_mod.Queue()
    t = threading.Thread(
        target=_serve_http, args=(_StubEngine(), inbox, stop, port),
        kwargs={"health": health}, daemon=True,
    )
    t.start()
    try:
        payload = None
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                payload = _probe(f"http://127.0.0.1:{port}/healthz")
                break
            except OSError:
                time.sleep(0.1)
        assert payload is not None
        assert payload["state"] == "ready"
        assert payload["queue_depth"] == 2 and payload["active_slots"] == 1
        assert payload["num_slots"] == 4 and payload["pid"]

        health.mark_draining()
        assert _probe(f"http://127.0.0.1:{port}/healthz")["state"] == "draining"
        # draining front end refuses new admissions with an answer, not a hang
        req = __import__("urllib.request", fromlist=["request"]).Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"id": 1, "prompt": [1]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            __import__("urllib.request", fromlist=["request"]).urlopen(req, timeout=10)
        assert exc_info.value.code == 503
    finally:
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive()


# ---------------------------------------------------------------------------
# race-check regression pins (PR 12): each of these is a 2-thread proof of a
# concurrency defect the static pass surfaced in the PR 11 router/supervisor
# ---------------------------------------------------------------------------


class _InterleaveDetectingTrail:
    """File double whose write() detects a second thread entering while one
    is mid-write — exactly the torn-JSONL hazard on the real fleet trail
    (two threads interleaving write() calls on one buffered file)."""

    def __init__(self):
        self.concurrent_entries = 0
        self.lines = []
        self._busy = False

    def write(self, text):
        if self._busy:
            self.concurrent_entries += 1
        self._busy = True
        time.sleep(0.001)  # widen the interleave window deterministically
        self.lines.append(text)
        self._busy = False

    def flush(self):
        pass

    def close(self):
        pass


def test_fleet_trail_writes_serialized_across_threads(tmp_path):
    """The health tick and _mark_dead both flush fleet rows; without the
    trail leaf-lock two threads interleave write() calls and tear rows.
    (race-check drove the _trail_lock; this pins the behaviour.)"""
    r0 = StubReplica(0)
    router = _router([r0], logging_dir=str(tmp_path))
    trail = _InterleaveDetectingTrail()
    try:
        with router._trail_lock:
            router._trail.close()
            router._trail = trail
        threads = [
            threading.Thread(
                target=lambda: [router._write_fleet_rows() for _ in range(20)],
                daemon=True,
            )
            for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert trail.concurrent_entries == 0, (
            f"{trail.concurrent_entries} concurrent write() entries — "
            "fleet-trail rows can tear mid-line"
        )
        assert len(trail.lines) == 2 * 20 * 2  # totals row + one replica row
        for line in trail.lines:
            json.loads(line)  # every row is intact JSON
    finally:
        router.close()


def test_mark_dead_stands_down_once_teardown_owns_the_fleet(tmp_path):
    """drain() SIGTERMs replicas whose exits are EXPECTED; a health probe
    racing it used to mark the exiting replica dead and SIGKILL it while
    it answered its last in-flight requests. _mark_dead now checks the
    teardown flag under the lock and stands down."""
    r0 = StubReplica(0)
    router = _router([r0], logging_dir=str(tmp_path))
    try:
        with router._lock:
            router._health_paused = True  # the drain path sets this under the lock
        t = threading.Thread(target=router._mark_dead, args=(r0,), daemon=True)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive()
        assert r0.state == "ready", "death verdict raced the teardown"
    finally:
        router.close()


def test_health_sweep_survives_concurrent_fleet_edits(tmp_path):
    """The supervisor appends (scale-up) and replaces (respawn) replicas
    under the router lock at runtime; the sweep used to iterate the live
    list lock-free. It now probes a lock-held snapshot: edits landing
    mid-sweep neither crash it nor leak into this sweep's probe set."""
    r0, r1 = StubReplica(0), StubReplica(1)
    router = _router([r0, r1], logging_dir=str(tmp_path))
    probed = []
    entered = threading.Event()
    release = threading.Event()
    orig_probe = router._probe_one

    def slow_probe(r):
        probed.append(r)
        entered.set()
        release.wait(timeout=30)
        orig_probe(r)

    try:
        router._probe_one = slow_probe
        sweep = threading.Thread(target=router._health_sweep, daemon=True)
        sweep.start()
        assert entered.wait(timeout=30)
        with router._lock:  # supervisor-style mid-sweep edits
            router.replicas.append(StubReplica(2))
            router.replicas[0] = StubReplica(0)
        release.set()
        sweep.join(timeout=60)
        assert not sweep.is_alive()
        # the sweep probed its snapshot: the originals, not the mid-sweep edits
        assert set(probed) == {r0, r1}
    finally:
        release.set()
        router.close()
