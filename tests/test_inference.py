"""Pipeline-parallel inference (reference ``inference.py:31-184``
``prepare_pippy``; ``test_utils/scripts/external_deps/test_pippy.py``)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.inference import (
    find_pippy_batch_size,
    generate_stage_map,
    prepare_pippy,
)
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _model_and_batch(layers=4):
    config = LlamaConfig.tiny(layers=layers)
    model = LlamaForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(8, 16)).astype(np.int32)
    return config, model, ids


def test_pipelined_logits_match_single_device():
    config, model, ids = _model_and_batch()
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids}, devices=jax.devices()[:4]
    )
    out = pipelined(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_stage_params_are_disjoint_and_placed():
    config, model, ids = _model_and_batch()
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids}, devices=jax.devices()[:4]
    )
    assert len(pipelined._stage_params) == 4
    for s, params in enumerate(pipelined._stage_params):
        for leaf in params.values():
            assert leaf.devices() == {pipelined.devices[s]}
    # layer slices are distributed, not replicated: the big embed lives on
    # exactly one stage
    owners = [s for s, p in enumerate(pipelined._stage_params) if "embed_tokens" in p]
    assert len(owners) == 1


def test_microbatching_handles_uneven_batch():
    config, model, _ = _model_and_batch(layers=2)
    ids = np.random.default_rng(0).integers(0, 256, size=(5, 16)).astype(np.int32)
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids},
        devices=jax.devices()[:2], num_chunks=2,
    )
    out = pipelined(input_ids=ids)
    assert out.logits.shape[0] == 5
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_uneven_batch_scalar_parity_exact():
    """Non-chunk-divisible batches: chunks are equal-sized with a RAGGED
    tail, so every chunk's loss covers only real rows (the reference pads
    then discards, ``/root/reference/src/accelerate/inference.py:99-122``;
    same semantics, no padded rows ever exist) — the row-weighted
    chunk-mean equals the dense full-batch loss (same mean over the same
    5 rows)."""
    config, model, _ = _model_and_batch(layers=2)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 256, size=(5, 16)).astype(np.int32)
    labels = rng.integers(0, 256, size=(5, 16)).astype(np.int32)
    ref = model.apply_fn(model.params, input_ids=ids, labels=labels)["loss"]
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids, "labels": labels},
        devices=jax.devices()[:2], num_chunks=4,  # mb=2 → real rows 2,2,1,0
    )
    out = pipelined(input_ids=ids, labels=labels)
    np.testing.assert_allclose(np.asarray(out.loss), np.asarray(ref), rtol=2e-5, atol=2e-5)
    assert out.logits.shape[0] == 5


def test_explicit_split_points():
    config, model, ids = _model_and_batch(layers=2)
    pipelined = prepare_pippy(
        model, split_points=["layer"], example_kwargs={"input_ids": ids},
        devices=jax.devices()[:2],
    )
    assert pipelined.hf_split_points == ["layer"]
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    out = pipelined(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_find_pippy_batch_size():
    assert find_pippy_batch_size((np.zeros((4, 2)),), {}) == 4
    assert find_pippy_batch_size((), {"x": np.zeros((3,))}) == 3
    assert find_pippy_batch_size((), {}) is None


def test_model_without_segments_raises():
    from accelerate_tpu.modules import Model

    bare = Model(lambda p, x: x, {"w": np.zeros(2)})
    with pytest.raises(ValueError, match="segment plan"):
        prepare_pippy(bare, example_args=(np.zeros((2, 2)),))


def test_stage_map_balances_bytes():
    steps = [(f"s{i}", [f"w{i}"], lambda s, c: c) for i in range(8)]
    flat = {f"w{i}": np.zeros((100,), np.float32) for i in range(8)}
    bounds = generate_stage_map(steps, flat, 4)
    assert bounds == [0, 2, 4, 6]
