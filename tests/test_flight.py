# Copyright The HuggingFace Team. All rights reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.
"""Per-iteration flight recorder: phase-sum == wall-time invariant, ring
cap + reset_stats interaction, disabled path, ``trace tail --iterations``
math, ``/profile`` round-trip on a live serve subprocess, HANG_REPORT
flight tails, and the fleet profile fan-out."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from accelerate_tpu.serving.flight import (
    ITERATION_PHASES,
    FlightRecorder,
    get_active_flight_recorder,
    set_active_flight_recorder,
)

# ---------------------------------------------------------------------------
# recorder unit behaviour
# ---------------------------------------------------------------------------


def _entry_phases(i):
    """Deterministic synthetic phase durations for iteration ``i``."""
    phases = {
        "schedule": 0.001, "prefill": 0.002 * (i % 3), "dispatch": 0.003,
        "device_wait": 0.010 + 0.001 * i, "harvest": 0.0005,
    }
    return phases, sum(phases.values())


def test_record_asserts_phase_sum_equals_wall():
    fl = FlightRecorder(history=8)
    phases, wall = _entry_phases(1)
    entry = fl.record(1, t_start=100.0, wall_s=wall, **phases)
    assert entry["wall_s"] == pytest.approx(wall)
    # a dropped stamp (phases missing time) is an AssertionError, not a log
    with pytest.raises(AssertionError):
        fl.record(2, t_start=101.0, wall_s=wall + 0.5, **phases)
    # a wrong phase vocabulary is refused outright
    with pytest.raises(AssertionError):
        fl.record(3, t_start=102.0, wall_s=0.001, schedule=0.001)


def test_ring_caps_and_totals_stay_cumulative():
    fl = FlightRecorder(history=4)
    total_wall = 0.0
    for i in range(10):
        phases, wall = _entry_phases(i)
        fl.record(i, t_start=float(i), wall_s=wall, **phases)
        total_wall += wall
    assert len(fl) == 4  # bounded ring
    assert fl.iterations == 10  # cumulative count keeps counting past it
    assert fl.wall_total_s == pytest.approx(total_wall)
    # host fraction is cumulative (all 10), not ring-windowed
    dev = sum(_entry_phases(i)[0]["device_wait"] for i in range(10))
    assert fl.host_fraction() == pytest.approx(1.0 - dev / total_wall)
    # tail is newest-last; window filters on the start stamp
    assert [e["iteration"] for e in fl.tail(2)] == [8, 9]
    assert [e["iteration"] for e in fl.window(8.0)] == [8, 9]
    summary = fl.summary()
    assert summary["flight_window"] == 4
    assert set(summary["iteration_phases_s"]) == set(ITERATION_PHASES)
    fl.reset()
    assert len(fl) == 0 and fl.iterations == 0 and fl.summary() == {}
    assert fl.current_phase == "idle"


def test_phase_vocabulary_pinned_across_surfaces():
    """The jax-free readers hardcode the phase tuple — they must never
    drift from the recorder's."""
    from accelerate_tpu.diagnostics import reqtrace
    from accelerate_tpu.metrics import ingest

    assert ingest._FLIGHT_PHASES == ITERATION_PHASES
    assert reqtrace.ITERATION_PHASES == ITERATION_PHASES


def test_observe_flight_feeds_per_phase_histogram():
    from accelerate_tpu.metrics.ingest import observe_flight
    from accelerate_tpu.metrics.openmetrics import render_openmetrics
    from accelerate_tpu.metrics.registry import MetricsRegistry

    registry = MetricsRegistry(gate_main_process=False)
    fl = FlightRecorder(history=4)
    phases, wall = _entry_phases(2)
    entry = fl.record(1, t_start=0.0, wall_s=wall, **phases)
    observe_flight(registry, entry)
    text = render_openmetrics(registry)
    assert 'serving_iteration_seconds' in text
    assert 'phase="total"' in text and 'phase="device_wait"' in text


# ---------------------------------------------------------------------------
# engine integration (slow lane: compiles the tiny model)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


def _tiny_engine(tiny_model, **overrides):
    from accelerate_tpu.serving import EngineConfig, InferenceEngine

    kw = dict(num_slots=2, block_size=8, max_seq_len=96, prefill_chunk=8,
              decode_burst=2, stats_interval=0)
    kw.update(overrides)
    return InferenceEngine(tiny_model, EngineConfig(**kw))


@pytest.mark.slow
def test_engine_phases_sum_to_wall_and_reset_clears_ring(tiny_model):
    engine = _tiny_engine(tiny_model, flight_history=16)
    assert get_active_flight_recorder() is engine._flight
    # warmup leg
    engine.add_request([1, 2, 3], max_new_tokens=8)
    engine.run_until_idle(max_iterations=100)
    warm_iters = engine.stats()["iterations"]
    assert warm_iters > 0 and len(engine._flight) == min(warm_iters, 16)
    for e in engine._flight.tail(16):
        # the invariant record() asserts, re-checked from the outside
        assert sum(e[f"{p}_s"] for p in ITERATION_PHASES) == pytest.approx(
            e["wall_s"], abs=1e-6
        )
    # warmup -> reset -> measure reports ONLY post-reset iterations for
    # both stats() and the ring (the satellite-6 small fix)
    engine.reset_stats()
    assert len(engine._flight) == 0 and engine._flight.iterations == 0
    assert "host_fraction" not in engine.stats()
    engine.add_request([5, 6], max_new_tokens=4)
    engine.run_until_idle(max_iterations=100)
    stats = engine.stats()
    assert stats["iterations"] == engine._flight.iterations == len(engine._flight)
    assert 0.0 < stats["host_fraction"] <= 1.0
    assert stats["flight_window"] == stats["iterations"]
    assert set(stats["iteration_phases_s"]) == set(ITERATION_PHASES)
    # hbm watermarks ride stats() (estimate-labelled on CPU: no
    # memory_stats, so the static params+pools model answers)
    assert stats["hbm_used_bytes"] > 0
    assert stats["hbm_bytes_source"] in ("memory_stats", "estimate")
    assert stats["decode_compiles"] == 1


@pytest.mark.slow
def test_flight_disabled_path(tiny_model):
    set_active_flight_recorder(None)
    engine = _tiny_engine(tiny_model, flight_history=0)
    assert engine._flight is None
    # a disabled engine must not arm the process-global recorder either
    assert get_active_flight_recorder() is None
    engine.add_request([1, 2, 3], max_new_tokens=4)
    engine.run_until_idle(max_iterations=100)
    stats = engine.stats()
    for key in ("host_fraction", "iteration_p50_s", "flight_window"):
        assert key not in stats
    # the hbm watermarks are independent of the recorder
    assert stats["hbm_used_bytes"] > 0


# ---------------------------------------------------------------------------
# trace tail --iterations math (synthetic traces — no engine, no jax time)
# ---------------------------------------------------------------------------


def _write_trace(path, pid, wall_minus_mono_s, events, name=None):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rows = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": name or f"host_{pid}"}},
        {"name": "clock_sync", "ph": "M", "pid": pid, "tid": 0,
         "args": {"wall_minus_mono_s": wall_minus_mono_s}},
    ]
    with open(path, "w") as f:
        f.write("[\n")
        for row in rows + events:
            f.write(json.dumps(row) + ",\n")


def _flight_event(i, ts, wall_s, device_wait_s, pid=0):
    args = {"iteration": i, "wall_s": wall_s,
            "schedule_s": 0.0, "prefill_s": 0.0, "device_wait_s": device_wait_s,
            "harvest_s": 0.0}
    args["dispatch_s"] = wall_s - device_wait_s
    return {"name": "serve/flight", "ph": "i", "s": "p", "ts": ts,
            "pid": pid, "tid": 1, "args": args}


def test_iteration_report_math_on_synthetic_fleet(tmp_path):
    from accelerate_tpu.diagnostics.reqtrace import (
        iteration_report,
        render_iteration_report,
    )

    # two replicas with skewed clocks; 3 iterations each, known split:
    # total wall 6.0s of which device_wait 1.5s -> host fraction 0.75
    r0 = [_flight_event(i, 1_000_000.0 * (i + 1), 1.0, 0.25, pid=10)
          for i in range(3)]
    r1 = [_flight_event(i, 2_000_000.0 * (i + 1), 1.0, 0.25, pid=11)
          for i in range(3)]
    _write_trace(str(tmp_path / "replica_0" / "traces" / "host_10.trace.json"),
                 10, 500.0, r0, name="replica_0")
    _write_trace(str(tmp_path / "replica_1" / "traces" / "host_11.trace.json"),
                 11, -500.0, r1, name="replica_1")
    report = iteration_report(str(tmp_path), k=4)
    assert report["iterations"] == 6
    assert report["wall_total_s"] == pytest.approx(6.0)
    assert report["host_fraction"] == pytest.approx(0.75)
    assert report["device_fraction"] == pytest.approx(0.25)
    assert report["phase_totals_s"]["device_wait"] == pytest.approx(1.5)
    assert len(report["tail"]) == 4
    assert sum(report["attribution"].values()) == pytest.approx(100.0)
    assert report["attribution"]["device_wait"] == pytest.approx(25.0)
    text = render_iteration_report(report)
    assert "host 75.0%" in text and "device 25.0%" in text
    assert "replica_0" in text or "replica_1" in text
    # malformed/foreign rows are skipped, never fatal
    _write_trace(str(tmp_path / "traces" / "host_1.trace.json"), 1, 0.0, [
        {"name": "serve/flight", "ph": "i", "ts": 5.0, "pid": 1, "tid": 0,
         "args": {"wall_s": "not-a-number"}},
    ])
    assert iteration_report(str(tmp_path), k=4)["iterations"] == 6


def test_trace_tail_iterations_cli_empty_dir_exits_1(tmp_path):
    from accelerate_tpu.commands import monitor as monitor_cmd

    class Args:
        logging_dir = str(tmp_path)
        k = 5
        metric = "ttft"
        iterations = True
        json = False

    (tmp_path / "traces").mkdir()
    _write_trace(str(tmp_path / "traces" / "host_0.trace.json"), 0, 0.0, [])
    assert monitor_cmd.trace_tail_command(Args()) == 1


# ---------------------------------------------------------------------------
# HANG_REPORT flight_tail (wedged stub — no real hang needed)
# ---------------------------------------------------------------------------


def test_hang_report_embeds_flight_tail():
    from accelerate_tpu.diagnostics.watchdog import Watchdog

    fl = FlightRecorder(history=8)
    for i in range(5):
        phases, wall = _entry_phases(i)
        fl.record(i, t_start=float(i), wall_s=wall, **phases)
    fl.current_phase = "device_wait"  # wedged mid-harvest-sync
    set_active_flight_recorder(fl)
    try:
        report = Watchdog(floor_seconds=1.0).build_report(elapsed=9.0, deadline=1.0)
    finally:
        set_active_flight_recorder(None)
    tail = report["flight_tail"]
    assert tail["current_phase"] == "device_wait"
    assert tail["iterations"] == 5
    assert [e["iteration"] for e in tail["entries"]] == [0, 1, 2, 3, 4]
    assert 0.0 < tail["host_fraction"] < 1.0
    # no recorder armed -> the section is None, not missing
    report = Watchdog(floor_seconds=1.0).build_report(elapsed=9.0, deadline=1.0)
    assert report["flight_tail"] is None


def test_monitor_renders_iteration_line_and_hang_phase(tmp_path):
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status

    tel_dir = tmp_path / "telemetry"
    tel_dir.mkdir()
    now = time.time()
    with open(tel_dir / "telemetry.jsonl", "w") as f:
        f.write(json.dumps({
            "type": "serving", "kind": "step", "iteration": 64,
            "tokens_per_sec": 500.0, "queue_depth": 1, "slot_occupancy": 0.5,
            "free_blocks": 9, "decode_compiles": 1, "completed_total": 4,
            "host_fraction": 0.82, "iteration_p50_s": 0.012,
            "iteration_p99_s": 0.040, "flight_phase": "harvest",
            "hbm_used_bytes": float(2 << 30), "hbm_headroom_bytes": float(1 << 30),
            "hbm_bytes_source": "estimate", "ts": now,
        }) + "\n")
    (tmp_path / "HANG_REPORT_0.json").write_text(json.dumps({
        "host": 0, "stalled_phase": "serve/decode", "elapsed_s": 42.0,
        "ts": now, "flight_tail": {"current_phase": "device_wait",
                                   "iterations": 9, "entries": []},
    }))
    status = collect_status(str(tmp_path), now=now)
    srv = status["serving"]
    assert srv["host_fraction"] == pytest.approx(0.82)
    assert srv["flight_phase"] == "harvest"
    assert status["hang_reports"][0]["flight_phase"] == "device_wait"
    text = render_status(status)
    assert "iteration: host 82%" in text
    assert "hbm 2.00 GiB (headroom 1.00) [estimate]" in text
    assert "engine phase device_wait" in text


# ---------------------------------------------------------------------------
# /profile round-trip on a real serve subprocess + fleet fan-out stubs
# ---------------------------------------------------------------------------

_TINY_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "96", "--prefill-chunk", "8", "--decode-burst", "2",
]


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_TELEMETRY", None)
    return env


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_ready(port, proc, timeout=240):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"serve exited rc={proc.returncode}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if json.loads(r.read()).get("state") == "ready":
                    return
        except (OSError, ValueError):
            pass
        time.sleep(0.25)
    raise RuntimeError("serve never became ready")


def test_profile_roundtrip_on_live_serve(tmp_path):
    """GET /profile?seconds=N on a serving engine: jax-profiler artifacts
    + the flight window land under logging_dir/profiles/, the engine keeps
    serving through the capture, and decode_compiles==1 still holds."""
    port = _free_port()
    logdir = str(tmp_path / "run")
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "serve", *_TINY_ARGS, "--http", str(port), "--logging-dir", logdir],
        env=_cli_env(), stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        _wait_ready(port, proc)

        def gen(i):
            body = json.dumps(
                {"id": i, "prompt": [1, 2, 3, 1 + i % 5], "max_new_tokens": 16}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=180) as r:
                return json.loads(r.read())

        assert gen(0)["finish_reason"] == "length"
        # traffic runs THROUGH the capture window
        worker = threading.Thread(
            target=lambda: [gen(i) for i in range(1, 5)], daemon=True
        )
        worker.start()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/profile?seconds=0.4", timeout=120
        ) as r:
            manifest = json.loads(r.read())
        worker.join(timeout=180)
        assert manifest["profile_dir"].startswith(
            os.path.join(logdir, "profiles")
        )
        flight_window = os.path.join(manifest["profile_dir"], "flight_window.json")
        assert os.path.isfile(flight_window)
        with open(flight_window) as f:
            window = json.load(f)
        assert window["phases"] == list(ITERATION_PHASES)
        for e in window["entries"]:
            assert sum(e[f"{p}_s"] for p in ITERATION_PHASES) == pytest.approx(
                e["wall_s"], abs=1e-6
            )
        assert os.path.isfile(os.path.join(manifest["profile_dir"], "manifest.json"))
        # the engine survived the capture and never re-traced
        assert gen(9)["finish_reason"] == "length"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        assert stats["decode_compiles"] == 1
        assert 0.0 < stats["host_fraction"] <= 1.0
        # bad / missing-logging-dir inputs answer with codes, not crashes
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/profile?seconds=banana", timeout=10
            )
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # merge discovers the capture beside the stitched timeline
        from accelerate_tpu.diagnostics.tracing import discover_profile_artifacts

        assert discover_profile_artifacts(logdir) == [manifest["profile_dir"]]
        # the offline reader agrees with the engine about the host share
        from accelerate_tpu.diagnostics.reqtrace import iteration_report

        report = iteration_report(logdir, k=5)
        assert report["iterations"] > 0
        assert report["host_fraction"] == pytest.approx(
            stats["host_fraction"], abs=0.05
        )
    finally:
        proc.terminate()
        proc.wait(timeout=60)


class _StubProfileHandler:
    """Factory for a stub replica HTTP server answering /profile."""

    @staticmethod
    def serve(received):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                received.append(self.path)
                body = json.dumps({
                    "profile_dir": f"/tmp/stub{self.server.server_port}",
                    "seconds": 0.1, "flight_iterations": 3,
                    "host_fraction": 0.5, "artifacts": [],
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server


def test_profile_fleet_fans_out_to_stub_replicas(tmp_path):
    from accelerate_tpu.commands.profile import (
        discover_replica_urls,
        profile_fleet,
    )

    received_a, received_b = [], []
    a = _StubProfileHandler.serve(received_a)
    b = _StubProfileHandler.serve(received_b)
    try:
        # fleet trail: newest row per replica wins; dead replicas and the
        # aggregate kind="router" totals row are skipped
        router_dir = tmp_path / "router"
        router_dir.mkdir()
        rows = [
            {"replica_id": 0, "state": "dead", "base_url": "http://127.0.0.1:1/"},
            {"replica_id": 0, "state": "ready",
             "base_url": f"http://127.0.0.1:{a.server_port}/"},
            {"replica_id": 1, "state": "ready",
             "base_url": f"http://127.0.0.1:{b.server_port}"},
            {"replica_id": 2, "state": "dead", "base_url": "http://127.0.0.1:2"},
            {"kind": "router", "replica_id": None, "state": None},
        ]
        with open(router_dir / "replicas.jsonl", "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        urls = discover_replica_urls(str(tmp_path))
        assert urls == [
            f"http://127.0.0.1:{a.server_port}",
            f"http://127.0.0.1:{b.server_port}",
        ]
        results = profile_fleet(urls, seconds=0.1)
        assert [r["ok"] for r in results] == [True, True]
        assert all(r["flight_iterations"] == 3 for r in results)
        assert received_a == ["/profile?seconds=0.1"]
        assert received_b == ["/profile?seconds=0.1"]
        # a dead URL reports per-replica failure without sinking the rest
        results = profile_fleet(urls + ["http://127.0.0.1:1"], seconds=0.1)
        assert [r["ok"] for r in results] == [True, True, False]
    finally:
        a.shutdown()
        b.shutdown()
