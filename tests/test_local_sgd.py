"""LocalSGD: K-step divergent local training + parameter averaging over dp
(reference ``/root/reference/src/accelerate/local_sgd.py:19-104``; here the
workers are dp shards carrying a stacked replica axis — see
``accelerate_tpu/local_sgd.py``)."""

import jax
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, LocalSGD, MeshPlugin
from accelerate_tpu.test_utils import RegressionModel


LR = 0.1


def _np_sgd_steps(a, b, x, y, lr, steps):
    """Closed-form SGD on mse loss of y = a·x + b for one worker's slice."""
    for _ in range(steps):
        pred = a * x + b
        ga = np.mean(2.0 * (pred - y) * x)
        gb = np.mean(2.0 * (pred - y))
        a, b = a - lr * ga, b - lr * gb
    return a, b


def _make(dp):
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=dp, devices=jax.devices()[:dp]))
    model = RegressionModel(a=0.5, b=-0.5)
    model, opt = accelerator.prepare(model, optax.sgd(LR))
    return accelerator, model, opt


def _data(n, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n,)).astype(np.float32)
    y = (2.0 * x + 3.0).astype(np.float32)
    return x, y


def test_local_steps_match_independent_workers_closed_form():
    """Inside the context each dp replica trains alone on its slice; the
    exit average equals the mean of independently trained workers."""
    R, b, steps = 4, 4, 3
    accelerator, model, opt = _make(R)
    x, y = _data(R * b)

    with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=100) as local_sgd:
        for _ in range(steps):
            out = model(x=x, y=y)
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            local_sgd.step()

    # numpy oracle: worker r sees the contiguous slice r of the global batch
    workers = [
        _np_sgd_steps(0.5, -0.5, x[r * b : (r + 1) * b], y[r * b : (r + 1) * b], LR, steps)
        for r in range(R)
    ]
    a_ref = np.mean([w[0] for w in workers])
    b_ref = np.mean([w[1] for w in workers])
    assert np.allclose(float(np.asarray(model.params["a"])), a_ref, atol=1e-5)
    assert np.allclose(float(np.asarray(model.params["b"])), b_ref, atol=1e-5)


def test_sync_every_step_equals_full_batch_sgd():
    """local_sgd_steps=1 degenerates to synchronous data-parallel SGD: the
    average of per-slice gradients is the full-batch gradient."""
    R, b, steps = 2, 8, 4
    accelerator, model, opt = _make(R)
    x, y = _data(R * b, seed=11)

    with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=1) as local_sgd:
        for _ in range(steps):
            out = model(x=x, y=y)
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            local_sgd.step()

    a_ref, b_ref = _np_sgd_steps(0.5, -0.5, x, y, LR, steps)
    assert np.allclose(float(np.asarray(model.params["a"])), a_ref, atol=1e-5)
    assert np.allclose(float(np.asarray(model.params["b"])), b_ref, atol=1e-5)


def test_mid_context_sync_boundary():
    """With local_sgd_steps=2 and 4 steps: sync at 2 and 4 — oracle is two
    rounds of (2 local steps, average)."""
    R, b = 2, 4
    accelerator, model, opt = _make(R)
    x, y = _data(R * b, seed=3)

    with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=2) as local_sgd:
        for _ in range(4):
            out = model(x=x, y=y)
            accelerator.backward(out.loss)
            opt.step()
            opt.zero_grad()
            local_sgd.step()

    a_w = [0.5] * R
    b_w = [-0.5] * R
    for _round in range(2):
        for r in range(R):
            a_w[r], b_w[r] = _np_sgd_steps(
                a_w[r], b_w[r], x[r * b : (r + 1) * b], y[r * b : (r + 1) * b], LR, 2
            )
        a_w = [np.mean(a_w)] * R
        b_w = [np.mean(b_w)] * R
    assert np.allclose(float(np.asarray(model.params["a"])), a_w[0], atol=1e-5)
    assert np.allclose(float(np.asarray(model.params["b"])), b_w[0], atol=1e-5)


def test_disabled_and_single_replica_are_noops():
    accelerator, model, opt = _make(2)
    x, y = _data(8)
    with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=2, enabled=False) as l:
        out = model(x=x, y=y)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        l.step()
    a_ref, b_ref = _np_sgd_steps(0.5, -0.5, x, y, LR, 1)
    assert np.allclose(float(np.asarray(model.params["a"])), a_ref, atol=1e-5)

    # dp=1: enabled silently degrades (reference: distributed_type == NO)
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc1 = Accelerator(mesh_plugin=MeshPlugin(dp=1, devices=jax.devices()[:1]))
    m1 = acc1.prepare_model(RegressionModel())
    with LocalSGD(accelerator=acc1, model=m1, local_sgd_steps=2) as l1:
        assert not l1.enabled


def test_model_parallel_mesh_raises():
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, tp=2, devices=jax.devices()[:4]))
    model = accelerator.prepare_model(RegressionModel())
    with pytest.raises(NotImplementedError):
        LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=2)


def test_params_shape_restored_after_context():
    accelerator, model, opt = _make(4)
    orig_shapes = jax.tree.map(lambda l: l.shape, model.params)
    x, y = _data(8)
    with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=3) as l:
        stacked = jax.tree.leaves(model.params)[0]
        assert stacked.shape[0] == 4
        out = model(x=x, y=y)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        l.step()
    assert jax.tree.map(lambda l: l.shape, model.params) == orig_shapes
    # training continues fine after the context
    out = model(x=x, y=y)
    accelerator.backward(out.loss)
    opt.step()
    assert np.isfinite(out.loss.item())
