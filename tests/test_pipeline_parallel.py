"""Pipeline-parallel (GPipe over the ``pp`` mesh axis) training tests.

The reference's pipeline training is Megatron-delegated
(``/root/reference/src/accelerate/utils/dataclasses.py:1836,1912``); here the
schedule is a shard_map program (``accelerate_tpu/parallel/pipeline.py``), so
it can be verified exactly against the dense computation on the virtual CPU
mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, FullyShardedDataParallelPlugin, MeshPlugin
from accelerate_tpu.mesh import build_mesh, data_sharding
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.models.llama import init_llama_params, llama_apply
from accelerate_tpu.ops.attention import attention_context
from accelerate_tpu.parallel.pipeline import gpipe, pipeline_microbatches
from accelerate_tpu.state import AcceleratorState, GradientState

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)

P = jax.sharding.PartitionSpec


def _reset():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def _batch(b=8, s=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, vocab, size=(b, s)), jnp.int32)
    return ids


# ---------------------------------------------------------------------------
# microbatch resolution
# ---------------------------------------------------------------------------


def test_pipeline_microbatches_auto_picks_divisor_at_least_stages():
    assert pipeline_microbatches(8, 0, 4) == 4
    assert pipeline_microbatches(12, 0, 4) == 4
    assert pipeline_microbatches(10, 0, 4) == 5  # 4 doesn't divide 10
    assert pipeline_microbatches(7, 0, 4) == 7  # prime batch → per-example


def test_pipeline_microbatches_explicit_must_divide():
    assert pipeline_microbatches(8, 2, 4) == 2
    with pytest.raises(ValueError):
        pipeline_microbatches(8, 3, 4)
    with pytest.raises(ValueError, match=">= 1"):
        pipeline_microbatches(8, -2, 4)


def test_megatron_num_micro_batches_reaches_schedule():
    """MegatronLMPlugin(num_micro_batches=...) sets the session default the
    GPipe resolver falls back to (reference field dataclasses.py:1912)."""
    from accelerate_tpu.parallel.pipeline import set_default_microbatches
    from accelerate_tpu.utils.dataclasses import MegatronLMPlugin

    _reset()
    try:
        Accelerator(megatron_lm_plugin=MegatronLMPlugin(pp_degree=2, num_micro_batches=8))
        assert pipeline_microbatches(16, 0, 2) == 8  # default honoured
        assert pipeline_microbatches(16, 4, 2) == 4  # explicit wins
    finally:
        set_default_microbatches(0)


def test_pp_bf16_over_ici_on_real_tpu():
    """bf16 inter-stage traffic over real ICI links: the CPU-mesh pp tests
    round-trip through f32 (the XLA:CPU AllReducePromotion workaround,
    ``parallel/pipeline.py`` cpu_widen), so the native-bf16 GPipe path only
    executes on TPU hardware — this smoke runs when the suite is pointed
    at a multi-chip TPU (``ACCELERATE_TEST_BACKEND=tpu``; VERDICT r3
    weak-7)."""
    if jax.devices()[0].platform != "tpu" or jax.device_count() < 2:
        pytest.skip("needs >=2 real TPU devices (ACCELERATE_TEST_BACKEND=tpu)")
    _reset()
    fsdp = jax.device_count() // 2
    acc = Accelerator(
        mesh_plugin=MeshPlugin(pp=2, fsdp=fsdp),
        mixed_precision="bf16",
    )
    model, opt = acc.prepare(
        LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=4), seed=0),
        optax.adamw(1e-3),
    )
    # batch must shard over the fsdp extent on any slice size
    rows = max(8, 2 * fsdp)
    ids = np.random.default_rng(0).integers(0, 256, size=(rows, 32)).astype(np.int32)
    out = model(input_ids=ids, labels=ids)
    acc.backward(out.loss)
    opt.step()
    assert np.isfinite(float(np.asarray(out.loss.force())))


def test_accelerator_accepts_pp_with_cp():
    """pp×cp compose since round 4 (VERDICT r3 weak-8): the cp attention's
    shard_map claims only its own axes, so it nests inside the GPipe 'pp'
    stage body."""
    _reset()
    acc = Accelerator(mesh_plugin=MeshPlugin(dp=2, pp=2, cp=2))
    shape = dict(acc.mesh.shape)
    assert shape["pp"] == 2 and shape["cp"] == 2


def test_ensure_no_pipeline_axis_guard():
    """The guard user models without a GPipe path call: refuses a pp>1
    mesh instead of silently training un-pipelined with stage-split
    weights (every built-in family now implements the path)."""
    from accelerate_tpu.parallel.pipeline import ensure_no_pipeline_axis

    ensure_no_pipeline_axis("custom")  # no mesh context: fine
    mesh = build_mesh(MeshPlugin(dp=4, pp=2))
    with attention_context(mesh=mesh):
        with pytest.raises(NotImplementedError, match="pipeline-parallel"):
            ensure_no_pipeline_axis("custom")


def test_t5_pipeline_bf16_operands_survive_cpu_backend():
    """bf16 params make the rel-bias tables and encoder output bf16; their
    boundary crossings must be widened on XLA:CPU or the backward-transpose
    psums abort the process (AllReducePromotion copy-opcode check failure)."""
    from accelerate_tpu.models.t5 import T5Config, init_t5_params, t5_apply

    c = T5Config.tiny(layers=4, hidden_size=32, heads=2)
    params = init_t5_params(jax.random.PRNGKey(0), c, dtype=jnp.bfloat16)
    enc = _batch(b=8, s=24)
    dec = _batch(b=8, s=12, seed=1)

    def loss_fn(p):
        return t5_apply(c, p, enc, labels=dec)["loss"].astype(jnp.float32)

    mesh = build_mesh(MeshPlugin(dp=1, pp=4, fsdp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
        loss = float(loss)
    assert np.isfinite(loss)
    assert all(
        bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
        for g in jax.tree.leaves(grads)
    )


def test_t5_pipeline_matches_dense():
    """Both t5 stacks pipeline (decoder cross-attends its microbatch's
    slice of the encoder output via the extra_aligned operand); a padded
    encoder mask must survive the schedule."""
    from accelerate_tpu.models.t5 import T5Config, init_t5_params, t5_apply

    c = T5Config.tiny(layers=4, hidden_size=32, heads=2)
    params = init_t5_params(jax.random.PRNGKey(0), c)
    enc = _batch(b=8, s=24)
    dec = _batch(b=8, s=12, seed=1)
    mask = jnp.asarray(np.tile([1] * 16 + [0] * 8, (8, 1)), jnp.int32)

    def loss_fn(p):
        return t5_apply(c, p, enc, attention_mask=mask, labels=dec)["loss"]

    loss_d, grads_d = jax.value_and_grad(loss_fn)(params)
    mesh = build_mesh(MeshPlugin(dp=1, pp=4, fsdp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        loss_p, grads_p = jax.jit(jax.value_and_grad(loss_fn))(params)
        loss_p = float(loss_p)
    assert abs(loss_p - float(loss_d)) < 1e-4
    max_err = jax.tree.reduce(
        max, jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_d, grads_p)
    )
    assert max_err < 1e-4


def test_mixtral_pipeline_matches_dense_lm_loss():
    """MoE x GPipe: per-token routing means the pipelined lm loss is exact
    when capacity drops nothing; aux is the per-microbatch statistic."""
    from accelerate_tpu.models.mixtral import (
        MixtralConfig,
        init_mixtral_params,
        mixtral_apply,
    )

    c = MixtralConfig.tiny(vocab_size=256, hidden_size=32, layers=4, heads=2, experts=2, seq=64)
    c.capacity_factor = 8.0  # no token drops at any microbatch size
    params = init_mixtral_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32)

    out_d = mixtral_apply(c, params, ids, labels=ids)
    mesh = build_mesh(MeshPlugin(dp=1, pp=2, fsdp=2, ep=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        out_p = jax.jit(lambda p: mixtral_apply(c, p, ids, labels=ids))(params)
        lm_p, aux_p = float(out_p["lm_loss"]), float(out_p["aux_loss"])
        grads = jax.jit(
            jax.grad(lambda p: mixtral_apply(c, p, ids, labels=ids)["loss"])
        )(params)
        finite = all(bool(jnp.all(jnp.isfinite(g))) for g in jax.tree.leaves(grads))
    assert abs(lm_p - float(out_d["lm_loss"])) < 1e-4
    assert abs(aux_p - float(out_d["aux_loss"])) < 0.1
    assert finite


def test_bert_pipeline_matches_dense():
    from accelerate_tpu.models.bert import BertConfig, bert_apply, init_bert_params

    c = BertConfig.tiny(layers=4, hidden_size=32, heads=2)
    params = init_bert_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32, vocab=512)
    labels = jnp.asarray(np.arange(8) % c.num_labels, jnp.int32)

    def loss_fn(p):
        return bert_apply(c, p, ids, labels=labels)["loss"]

    loss_d, grads_d = jax.value_and_grad(loss_fn)(params)
    mesh = build_mesh(MeshPlugin(dp=1, pp=2, fsdp=2, tp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        loss_p, grads_p = jax.jit(jax.value_and_grad(loss_fn))(params)
        loss_p = float(loss_p)
    assert abs(loss_p - float(loss_d)) < 1e-4
    max_err = jax.tree.reduce(
        max, jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_d, grads_p)
    )
    assert max_err < 1e-4


def test_gpt2_pipeline_loss_and_grads_match_dense():
    """GPT-2's GPipe path (mask-only aligned operand; positions folded into
    the embedding) matches the dense computation."""
    from accelerate_tpu.models.gpt2 import GPT2Config, gpt2_apply, init_gpt2_params

    c = GPT2Config.tiny(layers=4, hidden_size=32, heads=2)
    params = init_gpt2_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32)
    mask = jnp.ones_like(ids)

    def loss_fn(p):
        return gpt2_apply(c, p, ids, attention_mask=mask, labels=ids)["loss"]

    loss_d, grads_d = jax.value_and_grad(loss_fn)(params)
    mesh = build_mesh(MeshPlugin(dp=1, pp=4, fsdp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        loss_p, grads_p = jax.jit(jax.value_and_grad(loss_fn))(params)
        loss_p = float(loss_p)
    assert abs(loss_p - float(loss_d)) < 1e-4
    max_err = jax.tree.reduce(
        max, jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_d, grads_p)
    )
    assert max_err < 1e-4


# ---------------------------------------------------------------------------
# gpipe primitive
# ---------------------------------------------------------------------------


def test_gpipe_matches_sequential_scan():
    """A 4-stage pipeline of elementwise affine layers == scanning all
    layers on one device."""
    mesh = build_mesh(MeshPlugin(dp=2, pp=4))
    L, b, d = 8, 8, 16
    rng = np.random.default_rng(0)
    weights = {
        "w": jnp.asarray(rng.normal(size=(L, d)) * 0.1 + 1.0, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(L, d)) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)

    def stage_fn(local, x_mb):
        def body(h, layer):
            return jnp.tanh(h * layer["w"] + layer["b"]), None

        y, _ = jax.lax.scan(body, x_mb, local)
        return y

    dense = stage_fn(weights, x)
    with jax.set_mesh(mesh):
        piped = jax.jit(
            lambda w, x: gpipe(stage_fn, w, x, mesh=mesh, num_microbatches=4)
        )(weights, x)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(dense), atol=1e-6)


def test_gpipe_grads_flow_through_schedule():
    """jax.grad through the pipeline (ppermute transposes) == dense grads."""
    mesh = build_mesh(MeshPlugin(dp=1, pp=4, fsdp=2))
    L, b, d = 4, 8, 8
    rng = np.random.default_rng(1)
    weights = jnp.asarray(rng.normal(size=(L, d)) * 0.1 + 1.0, jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)

    def stage_fn(local, x_mb):
        def body(h, w):
            return jnp.tanh(h * w), None

        y, _ = jax.lax.scan(body, x_mb, local)
        return y

    def dense_loss(w):
        return jnp.sum(stage_fn(w, x) ** 2)

    def piped_loss(w):
        return jnp.sum(gpipe(stage_fn, w, x, mesh=mesh) ** 2)

    g_dense = jax.grad(dense_loss)(weights)
    with jax.set_mesh(mesh):
        g_piped = jax.jit(jax.grad(piped_loss))(weights)
    np.testing.assert_allclose(np.asarray(g_piped), np.asarray(g_dense), atol=1e-5)


# ---------------------------------------------------------------------------
# llama integration
# ---------------------------------------------------------------------------


def test_llama_pipeline_loss_and_grads_match_dense():
    c = LlamaConfig.tiny(layers=4, hidden_size=32, heads=2, seq=64)
    params = init_llama_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32)
    mask = jnp.ones_like(ids)

    def loss_fn(p):
        return llama_apply(c, p, ids, attention_mask=mask, labels=ids)["loss"]

    loss_d, grads_d = jax.value_and_grad(loss_fn)(params)

    mesh = build_mesh(MeshPlugin(dp=1, pp=4, fsdp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        loss_p, grads_p = jax.jit(jax.value_and_grad(loss_fn))(params)
        loss_p = float(loss_p)
    assert abs(loss_p - float(loss_d)) < 1e-4
    max_err = jax.tree.reduce(
        max, jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_d, grads_p)
    )
    assert max_err < 1e-4, f"pipeline grads diverge from dense: {max_err}"


def test_llama_pipeline_respects_padding_mask():
    """The per-microbatch aligned-operand routing: a padded batch must give
    the same loss pipelined as dense (mask rides the GPipe schedule)."""
    c = LlamaConfig.tiny(layers=2, hidden_size=32, heads=2, seq=64)
    params = init_llama_params(jax.random.PRNGKey(2), c)
    ids = _batch(b=8, s=32, seed=5)
    mask = jnp.asarray(np.tile([1] * 20 + [0] * 12, (8, 1)), jnp.int32)
    labels = jnp.where(mask == 1, ids, -100)

    def loss_fn(p):
        return llama_apply(c, p, ids, attention_mask=mask, labels=labels)["loss"]

    loss_d = float(loss_fn(params))
    mesh = build_mesh(MeshPlugin(dp=1, pp=2, fsdp=2, tp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        loss_p = float(jax.jit(loss_fn)(params))
    assert abs(loss_p - loss_d) < 1e-4


def test_llama_pipeline_trains_under_accelerator_megatron_facade():
    """MegatronLMPlugin(pp_degree=2) lowers onto the pp mesh axis and the
    full deferred-autodiff user loop trains (reference delegates this to
    Megatron; utils/dataclasses.py:1836)."""
    from accelerate_tpu.utils.dataclasses import MegatronLMPlugin

    _reset()
    acc = Accelerator(
        megatron_lm_plugin=MegatronLMPlugin(tp_degree=2, pp_degree=2),
        fsdp_plugin=FullyShardedDataParallelPlugin(),
    )
    assert dict(acc.mesh.shape)["pp"] == 2
    c = LlamaConfig.tiny(layers=4, hidden_size=32, heads=2, seq=64)
    model = LlamaForCausalLM.from_config(c, seed=1)
    model, opt = acc.prepare(model, optax.adamw(1e-2))
    # stage placement: stacked layer params split over pp
    assert model.params["layers"]["wq"].sharding.spec == P("pp", "fsdp", "tp")

    ids = _batch(b=8, s=32)
    sh = data_sharding(acc.mesh)
    batch = {
        "input_ids": jax.device_put(ids, sh),
        "labels": jax.device_put(ids, sh),
    }
    losses = []
    for _ in range(5):
        out = model(**batch)
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(float(out.loss))
    assert losses[-1] < losses[0]


def test_llama_pipeline_bf16_mixed_precision_step():
    """bf16 training through the pipeline on the CPU mesh: the manual-axis
    traffic is widened to f32 there (XLA:CPU's AllReducePromotion pass
    check-fails on the copy-rooted bf16 psums shard_map's transpose
    inserts); compute stays bf16 and the step must run + decrease."""
    _reset()
    acc = Accelerator(
        mesh_plugin=MeshPlugin(dp=1, pp=2, fsdp=4),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_num_params=0),
        mixed_precision="bf16",
    )
    c = LlamaConfig.tiny(layers=2, hidden_size=64, heads=4, seq=64)
    model, opt = acc.prepare(LlamaForCausalLM.from_config(c, seed=0), optax.adamw(1e-2))
    ids = _batch(b=8, s=64)
    losses = []
    for _ in range(3):
        out = model(input_ids=ids, labels=ids)
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(float(out.loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_llama_pipeline_rejects_indivisible_stage_split():
    c = LlamaConfig.tiny(layers=3, hidden_size=32, heads=2, seq=64)
    params = init_llama_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32)
    mesh = build_mesh(MeshPlugin(dp=4, pp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        with pytest.raises(ValueError, match="pipeline stages"):
            llama_apply(c, params, ids, labels=ids)


@pytest.mark.parametrize("cp_mode", ["ring", "ulysses"])
def test_llama_pipeline_composes_with_cp_grad_parity(cp_mode):
    """pp=2 × cp=2 (context-parallel attention nested inside each GPipe
    stage body) matches the dense single-logical-device loss AND
    gradients, for both the ring (ppermute KV) and Ulysses (all_to_all)
    formulations — the long-context flagship combination VERDICT r3
    weak-8 asked for."""
    c = LlamaConfig.tiny(layers=2, hidden_size=32, heads=2, seq=64)
    params = init_llama_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32)

    def loss_fn(p):
        return llama_apply(c, p, ids, labels=ids)["loss"]

    loss_d, grads_d = jax.value_and_grad(loss_fn)(params)
    mesh = build_mesh(MeshPlugin(dp=2, pp=2, cp=2))
    with attention_context(mesh=mesh, cp_mode=cp_mode), jax.set_mesh(mesh):
        loss_p, grads_p = jax.jit(jax.value_and_grad(loss_fn))(params)
        loss_p = float(loss_p)
    assert abs(loss_p - float(loss_d)) < 1e-4
    max_err = jax.tree.reduce(
        max, jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_d, grads_p)
    )
    assert max_err < 1e-4


def test_llama_pipeline_prefill_matches_plain_forward():
    """KV-cache prefill over a pp mesh (stage-local caches via
    pipeline_cached_stack) returns the same logits AND the same cache the
    plain single-device scan produces (round 2 refused this path)."""
    c = LlamaConfig.tiny(layers=2, hidden_size=32, heads=2, seq=64)
    params = init_llama_params(jax.random.PRNGKey(0), c)
    ids = _batch(b=8, s=32)

    plain = llama_apply(c, params, ids, use_cache=True, max_cache_len=48)

    mesh = build_mesh(MeshPlugin(dp=4, pp=2))
    with attention_context(mesh=mesh), jax.set_mesh(mesh):
        piped = jax.jit(
            lambda p, i: llama_apply(c, p, i, use_cache=True, max_cache_len=48)
        )(params, ids)
    np.testing.assert_allclose(
        np.asarray(piped["logits"]), np.asarray(plain["logits"]), rtol=2e-5, atol=2e-5
    )
    for side in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(piped["kv_cache"][side]), np.asarray(plain["kv_cache"][side]),
            rtol=2e-5, atol=2e-5,
        )
