"""User hook API (reference ``hooks.py:37,95,124,183``;
``tests/test_hooks.py`` 401 LoC) + the parity gaps wired this round:
AutocastKwargs islands, ProfileKwargs schedule, jax RNG sync/checkpoint."""

import os

import jax
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.hooks import (
    ModelHook,
    SequentialHook,
    add_hook_to_module,
    remove_hook_from_module,
)
from accelerate_tpu.test_utils import RegressionModel


def _prepared():
    accelerator = Accelerator()
    model = accelerator.prepare_model(RegressionModel(a=2.0, b=0.0))
    return accelerator, model


class _ScaleInputHook(ModelHook):
    def pre_forward(self, module, *args, **kwargs):
        kwargs["x"] = kwargs["x"] * 2.0
        return args, kwargs


class _TagOutputHook(ModelHook):
    def __init__(self):
        self.calls = 0

    def post_forward(self, module, output):
        self.calls += 1
        return output


def test_pre_forward_transforms_inputs():
    accelerator, model = _prepared()
    x = np.asarray([1.0, 2.0], np.float32)
    base = np.asarray(model(x=x).prediction.force())
    add_hook_to_module(model, _ScaleInputHook())
    doubled = np.asarray(model(x=x).prediction.force())
    np.testing.assert_allclose(doubled, base * 2.0, rtol=1e-6)


def test_post_forward_runs_and_remove_restores():
    accelerator, model = _prepared()
    hook = _TagOutputHook()
    add_hook_to_module(model, hook)
    x = np.asarray([1.0], np.float32)
    model(x=x).prediction.force()
    assert hook.calls == 1
    assert model._hf_hook is hook
    remove_hook_from_module(model)
    assert getattr(model, "_hf_hook", None) is None
    model(x=x).prediction.force()
    assert hook.calls == 1  # no longer invoked


def test_append_builds_sequential_hook():
    accelerator, model = _prepared()
    h1, h2 = _TagOutputHook(), _TagOutputHook()
    add_hook_to_module(model, h1)
    add_hook_to_module(model, h2, append=True)
    assert isinstance(model._hf_hook, SequentialHook)
    model(x=np.asarray([1.0], np.float32)).prediction.force()
    assert h1.calls == 1 and h2.calls == 1


def test_hook_on_dispatched_model():
    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(layers=2), seed=0)
    dispatched = cpu_offload(model)
    hook = _TagOutputHook()
    add_hook_to_module(dispatched, hook)
    ids = np.zeros((1, 8), np.int32)
    dispatched(input_ids=ids)
    assert hook.calls == 1


def test_autocast_disabled_island():
    from accelerate_tpu.utils.dataclasses import AutocastKwargs

    accelerator = Accelerator(mixed_precision="bf16")
    model = accelerator.prepare_model(RegressionModel(a=2.0, b=0.0))
    assert model.compute_dtype is not None
    with accelerator.autocast(autocast_handler=AutocastKwargs(enabled=False)):
        assert model.compute_dtype is None
    assert model.compute_dtype is not None


def test_profile_schedule_writes_trace(tmp_path):
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    accelerator = Accelerator()
    handler = ProfileKwargs(wait=1, warmup=0, active=1, output_trace_dir=str(tmp_path))
    with accelerator.profile(handler) as prof:
        for _ in range(4):
            jax.block_until_ready(jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8)))
            prof.step()
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "schedule never entered an active window / wrote no trace"


def test_profile_with_flops_records_cost_analysis(tmp_path):
    """``with_flops`` dumps the XLA cost analysis of every compiled step
    executed during the session (round-2 verdict: the field was accepted
    but nothing consumed it)."""
    import json

    import optax

    from accelerate_tpu.test_utils import RegressionModel
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    accelerator = Accelerator()
    model, opt = accelerator.prepare(RegressionModel(), optax.sgd(0.1))
    x = np.random.default_rng(0).normal(size=(8, 1)).astype("float32")
    y = 2 * x + 1
    handler = ProfileKwargs(active=2, with_flops=True, output_trace_dir=str(tmp_path))
    with accelerator.profile(handler) as prof:
        for _ in range(2):
            out = model(x=x)
            loss = ((out.prediction - y) ** 2).mean()
            accelerator.backward(loss)
            opt.step()
            opt.zero_grad()
            prof.step()
    stats = json.load(open(tmp_path / "flops.json"))
    assert stats["compiled_programs"], stats
    assert stats["total_flops"] > 0


def test_jax_rng_in_sync_and_checkpoint(tmp_path):
    from accelerate_tpu.checkpointing import _collect_rng_state, _restore_rng_state
    from accelerate_tpu.utils.random import get_rng_key, set_seed, split_rng_key

    set_seed(123)
    k0 = np.asarray(jax.random.key_data(get_rng_key()))
    bundle = _collect_rng_state()
    assert "jax_key" in bundle
    # advance, then restore: key returns to the snapshot
    split_rng_key()
    k1 = np.asarray(jax.random.key_data(get_rng_key()))
    assert not np.array_equal(k0, k1)
    _restore_rng_state(bundle)
    k2 = np.asarray(jax.random.key_data(get_rng_key()))
    np.testing.assert_array_equal(k0, k2)
    # the sync path is a no-op single-process but must not crash
    from accelerate_tpu.utils.random import synchronize_rng_states

    synchronize_rng_states(["python", "numpy", "jax"])


def test_autocast_island_binds_at_call_time():
    """A deferred call recorded inside the island must run full-precision
    even though it traces AFTER the context exited."""
    from accelerate_tpu.utils.dataclasses import AutocastKwargs

    accelerator = Accelerator(mixed_precision="bf16")
    model = accelerator.prepare_model(RegressionModel(a=1.0, b=0.0))
    x = np.asarray([1.0 / 3.0], np.float32)
    with accelerator.autocast(autocast_handler=AutocastKwargs(enabled=False)):
        island = model(x=x)  # recorded now, traced later
    inside = float(np.asarray(island.prediction.force()))
    outside = float(np.asarray(model(x=x).prediction.force()))
    assert inside == np.float32(1.0 / 3.0), "island call was downcast"
    assert outside != inside, "bf16 policy did not apply outside the island"


def test_hook_on_raw_model_raises():
    from accelerate_tpu.modules import Model

    bare = Model(lambda p, x: x, {"w": np.zeros(2)})
    with pytest.raises(TypeError, match="not callable"):
        add_hook_to_module(bare, ModelHook())
