"""Double-buffered dispatch (``EngineConfig.async_dispatch``) — the
sync-vs-async contract of ROADMAP item 5.

The bar under test: the async loop changes WHEN tokens surface (one
``step()`` late, landed by the drain flush), never WHICH tokens — output
is token-identical to the synchronous engine across every kv_dtype and
every scheduling feature that edits engine state while a round is in
flight (chunked prefill, radix hit + CoW, swap preemption, deadline
expiry, speculative rounds, sampling lanes + grammar). One compiled
decode executable on both legs, exactly-once finishes under fences and
chaos, LockWatch-clean, and the flight recorder's ``overlap_hidden_s``
accounting consistent by construction.

Tier-1 tests cover the config/CLI plumbing (pure host); engine
end-to-end parity rides the slow lane like the rest of the serving
suite.
"""

import argparse
import io
import json
import os
import queue as queue_mod
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from accelerate_tpu.serving import EngineConfig, InferenceEngine, RequestState

KV_DTYPES = ("bf16", "int8", "fp8")


def _skip_without_fp8(kv_dtype: str) -> None:
    if kv_dtype == "fp8":
        from accelerate_tpu.utils.compat import has_fp8_storage

        if not has_fp8_storage():
            pytest.skip("float8_e4m3fn storage unsupported on this jax stack")


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


def _cfg(**kw):
    base = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8)
    base.update(kw)
    return EngineConfig(**base)


def _prompts(seed, sizes=(5, 11, 17, 3, 9)):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=n).astype(np.int32) for n in sizes]


# ---------------------------------------------------------------------------
# config + CLI plumbing (tier-1: pure host)
# ---------------------------------------------------------------------------


def test_async_dispatch_default_on():
    assert EngineConfig().async_dispatch is True


def test_serve_cli_sync_engine_flag_and_env(monkeypatch):
    """`--sync-engine` flips the escape hatch; ACCELERATE_SYNC_ENGINE=1
    sets the default (0/empty means async — the flag never un-sets env)."""
    from accelerate_tpu.commands import serve as serve_cmd

    def parse(argv):
        parser = argparse.ArgumentParser()
        serve_cmd.add_parser(parser.add_subparsers())
        return parser.parse_args(argv)

    monkeypatch.delenv("ACCELERATE_SYNC_ENGINE", raising=False)
    assert parse(["serve"]).sync_engine is False
    assert parse(["serve", "--sync-engine"]).sync_engine is True
    monkeypatch.setenv("ACCELERATE_SYNC_ENGINE", "1")
    assert parse(["serve"]).sync_engine is True
    monkeypatch.setenv("ACCELERATE_SYNC_ENGINE", "0")
    assert parse(["serve"]).sync_engine is False


def test_route_forwards_sync_engine_to_replicas():
    from accelerate_tpu.commands.route import _serve_args

    ns = argparse.Namespace(
        preset="tiny", dtype="f32", num_slots=2, block_size=8, max_seq_len=64,
        prefill_chunk=8, decode_burst=2, max_new_tokens=4, eos_token_id=None,
        temperature=None, seed=0, kv_dtype=None, chaos_spec=None, spec_k=None,
        draft=None, logprobs_topn=None, mesh=False, sync_engine=True,
    )
    assert "--sync-engine" in _serve_args(ns)
    ns.sync_engine = False
    assert "--sync-engine" not in _serve_args(ns)


# ---------------------------------------------------------------------------
# sync-vs-async token parity across kv_dtypes x scheduling features
# ---------------------------------------------------------------------------


def _pair(model, drive, **cfg_kw):
    """Run the same `drive` trace on an async and a sync engine. Asserts
    the headline invariants (token identity, one decode executable each,
    zero leaked blocks, zero hidden overlap on the sync leg) and hands
    back both engines + request lists for scenario-specific checks."""

    def leg(async_dispatch):
        eng = InferenceEngine(
            model, _cfg(async_dispatch=async_dispatch, **cfg_kw)
        )
        reqs = drive(eng)
        eng.run_until_idle(max_iterations=5000)
        return eng, reqs, [list(r.output_tokens) for r in reqs]

    a_eng, a_reqs, a_toks = leg(True)
    s_eng, s_reqs, s_toks = leg(False)
    assert a_toks == s_toks, "async dispatch changed the emitted tokens"
    for eng in (a_eng, s_eng):
        st = eng.stats()
        assert st["decode_compiles"] == 1
        assert st["allocated_blocks"] == 0
        assert eng._inflight is None  # run_until_idle really drained
    assert s_eng._flight.overlap_hidden_total_s == 0.0
    return a_eng, s_eng, a_reqs, s_reqs


def _drive_mixed(eng):
    # 17-token prompt > prefill_chunk 8 forces chunked prefill; staggered
    # budgets finish mid-wave so admission churns while rounds are in flight
    return [eng.add_request(p, 3 + 4 * i) for i, p in enumerate(_prompts(0))]


def _drive_radix_cow(eng):
    base = np.arange(20, dtype=np.int32) % 60
    r1 = eng.add_request(base, 6)
    eng.run_until_idle(max_iterations=5000)
    # full-block hit (16-token shared prefix) + mid-block CoW divergence
    shared = np.concatenate([base[:19], np.asarray([61], np.int32)])
    r2 = eng.add_request(shared, 6)
    return [r1, r2]


def _drive_swap(eng):
    return [
        eng.add_request(np.arange(8, dtype=np.int32) + i, max_new_tokens=30)
        for i in range(2)
    ]


def _drive_deadline(eng):
    # a microscopic budget expires while queued — deterministic on both
    # legs (the sweep runs before admission); bystanders decode normally
    doomed = eng.add_request([5, 6, 7], 8, deadline_ms=0.001)
    rest = [eng.add_request(p, 6) for p in _prompts(3, sizes=(5, 9))]
    return [doomed] + rest


def _drive_lanes(eng):
    ps = _prompts(2)
    return [
        eng.add_request(ps[0], 6),
        eng.add_request(
            ps[1], 6,
            sampling={"do_sample": True, "temperature": 0.8, "seed": 5},
        ),
        eng.add_request(
            ps[3], 6,
            sampling={"do_sample": True, "temperature": 0.9, "seed": 6},
            grammar={"type": "regex", "pattern": "[0-9]+"},
        ),
    ]


_SCENARIOS = {
    "chunked_prefill": (_drive_mixed, dict(decode_burst=1)),
    "radix_cow": (_drive_radix_cow, dict(prefix_cache=True)),
    "swap_preempt": (
        _drive_swap,
        dict(num_slots=2, num_blocks=6, swap_gb=0.01, prefix_cache=False),
    ),
    "deadline": (_drive_deadline, {}),
    "spec_k3": (_drive_mixed, dict(spec_k=3, draft="early_exit:1")),
    "lanes": (_drive_lanes, {}),
}


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", KV_DTYPES)
@pytest.mark.parametrize("scenario", sorted(_SCENARIOS))
def test_async_token_parity(tiny_model, scenario, kv_dtype):
    _skip_without_fp8(kv_dtype)
    drive, cfg_kw = _SCENARIOS[scenario]
    a_eng, s_eng, a_reqs, s_reqs = _pair(
        tiny_model, drive, kv_dtype=kv_dtype, **cfg_kw
    )
    if scenario == "swap_preempt":
        # the pressure really bit on both legs: the async one exercised the
        # fence-then-batched-gather swap-out against an in-flight round
        for eng in (a_eng, s_eng):
            st = eng.stats()
            assert st["preemptions"] >= 1
            assert st["swapped_out_blocks"] == st["swapped_in_blocks"] > 0
        assert all(r.finish_reason == "length" for r in a_reqs)
    elif scenario == "deadline":
        assert a_reqs[0].finish_reason == "deadline_exceeded"
        assert s_reqs[0].finish_reason == "deadline_exceeded"
        assert not a_reqs[0].output_tokens
    elif scenario == "radix_cow":
        assert a_eng.stats()["prefix_hit_tokens"] > 0
        assert s_eng.stats()["prefix_hit_tokens"] > 0
    elif scenario == "spec_k3":
        assert a_eng.stats()["spec_drafted_tokens"] > 0
    elif scenario == "lanes":
        # the constrained slot only ever emitted digit bytes on both legs
        assert a_reqs[2].output_tokens
        assert all(48 <= t <= 57 for t in a_reqs[2].output_tokens)


@pytest.mark.slow
def test_async_mesh4_parity_one_executable(tiny_model):
    """Async over fsdp=2 x tp=2: token-identical to the sync mesh engine
    AND the async single-device engine, one decode executable under GSPMD."""
    import jax

    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.utils.dataclasses import MeshPlugin

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs a >= 4-device (virtual) mesh")
    mesh = build_mesh(MeshPlugin(dp=1, fsdp=2, tp=2), devices=devices[:4])

    geometry = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8,
                    decode_burst=2)
    prompts = _prompts(7, sizes=(5, 12, 9))
    budgets = [4, 7, 5]

    def run(mesh_arg, async_dispatch):
        eng = InferenceEngine(
            tiny_model,
            _cfg(async_dispatch=async_dispatch, **geometry),
            mesh=mesh_arg,
        )
        reqs = [eng.add_request(p, b) for p, b in zip(prompts, budgets)]
        eng.run_until_idle(max_iterations=5000)
        return eng, [list(r.output_tokens) for r in reqs]

    mesh_async, toks_mesh_async = run(mesh, True)
    _, toks_mesh_sync = run(mesh, False)
    _, toks_single_async = run(None, True)
    assert toks_mesh_async == toks_mesh_sync == toks_single_async
    st = mesh_async.stats()
    assert st["decode_compiles"] == 1
    assert st["prefill_compiles"] == 1
    assert st["mesh"] == {"fsdp": 2, "tp": 2}


# ---------------------------------------------------------------------------
# overlap accounting (the flight recorder learned to hide host time)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_async_overlap_accounting(tiny_model):
    """The async leg records hidden overlap (> 0 on a real workload),
    every ring entry bounds it by wall - device_wait, and host_fraction
    follows the documented formula on both legs (sync reduces to the
    pre-item-5 1 - device_wait/wall)."""
    a_eng, s_eng, _, _ = _pair(tiny_model, _drive_mixed, decode_burst=1)
    fl = a_eng._flight
    assert fl.overlap_hidden_total_s > 0.0
    for e in fl.tail(len(fl)):
        assert -1e-6 <= e["overlap_hidden_s"] <= (
            e["wall_s"] - e["device_wait_s"] + 1e-6
        )
    expect = max(
        0.0,
        1.0
        - (fl.phase_totals_s["device_wait"] + fl.overlap_hidden_total_s)
        / fl.wall_total_s,
    )
    assert fl.host_fraction() == pytest.approx(expect, abs=1e-12)
    sf = s_eng._flight
    assert sf.host_fraction() == pytest.approx(
        max(0.0, 1.0 - sf.phase_totals_s["device_wait"] / sf.wall_total_s),
        abs=1e-12,
    )
    # the stat surfaces: stats() and telemetry both carry the new field
    assert a_eng.stats()["overlap_hidden_s"] == fl.overlap_hidden_total_s
    assert s_eng.stats()["overlap_hidden_s"] == 0.0


# ---------------------------------------------------------------------------
# run_until_idle drain-boundary + exactly-once (the satellite bugfix pins)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_run_until_idle_cap_counts_drain_flush(tiny_model):
    """Regression pin for the one-late boundary: a cap that lands exactly
    on the final drain flush succeeds and returns the finish once; a cap
    that lands between dispatch and harvest raises, and the follow-up
    drain still returns the finish exactly once (never dropped, never
    duplicated)."""

    def fresh():
        eng = InferenceEngine(tiny_model, _cfg(async_dispatch=True))
        req = eng.add_request([1, 2, 3, 4, 5], max_new_tokens=4)
        return eng, req

    # measure the exact iteration count, drain flush included, and the
    # step at which the finish surfaces (the final harvest; the last
    # iteration after it is the scheduler evicting the finished slot)
    eng, req = fresh()
    n = 0
    finish_step = None
    while eng.scheduler.has_work() or eng._inflight is not None:
        eng.step()
        n += 1
        if finish_step is None and req.state is RequestState.FINISHED:
            finish_step = n
        assert n < 5000
    assert req.state is RequestState.FINISHED
    assert finish_step is not None and finish_step >= 2
    assert n >= 2  # at least one dispatch + the one-late drain harvest

    eng, req = fresh()
    done = eng.run_until_idle(max_iterations=n)
    assert done.count(req) == 1
    assert eng._inflight is None

    # cap one short of the finishing harvest: the final round has been
    # dispatched but not harvested when the cap fires, and no finish has
    # been collected yet, so nothing is lost to the raise
    eng, req = fresh()
    with pytest.raises(RuntimeError, match="not idle"):
        eng.run_until_idle(max_iterations=finish_step - 1)
    assert eng._inflight is not None  # the cap really landed mid-flight
    done = eng.run_until_idle()
    assert done.count(req) == 1
    assert eng.stats()["completed"] == 1


@pytest.mark.slow
def test_exactly_once_finishes_under_swap_fence(tiny_model):
    """Step-by-step drive of the swap-pressure workload: every request is
    returned by exactly one step() call even when a mid-schedule fence
    force-harvests the in-flight round into the backlog."""
    eng = InferenceEngine(
        tiny_model,
        _cfg(async_dispatch=True, num_slots=2, num_blocks=6, swap_gb=0.01,
             prefix_cache=False),
    )
    reqs = [
        eng.add_request(np.arange(8, dtype=np.int32) + i, max_new_tokens=30)
        for i in range(2)
    ]
    seen = []
    it = 0
    while eng.scheduler.has_work() or eng._inflight is not None:
        assert it < 5000
        seen.extend(r.request_id for r in eng.step())
        it += 1
    assert sorted(seen) == sorted(r.request_id for r in reqs)
    assert eng.stats()["preemptions"] >= 1
    assert all(r.finish_reason == "length" for r in reqs)


@pytest.mark.slow
def test_stream_yields_every_token_async(tiny_model):
    """stream() under the async loop still yields every token exactly
    once — the trailing flush after FINISHED drains the one-late tail."""
    eng = InferenceEngine(tiny_model, _cfg(async_dispatch=True))
    toks = list(eng.stream([3, 1, 4, 1, 5], max_new_tokens=6))
    ref_eng = InferenceEngine(tiny_model, _cfg(async_dispatch=False))
    ref = ref_eng.add_request([3, 1, 4, 1, 5], max_new_tokens=6)
    ref_eng.run_until_idle(max_iterations=5000)
    assert toks == ref.output_tokens


# ---------------------------------------------------------------------------
# LockWatch: the serve front end's loop with the async engine underneath
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_lockwatch_clean_async_engine_loop(tiny_model):
    """The serve front end (engine loop thread + concurrent /healthz
    probes) with LockWatch armed over the async engine: every request
    answered, zero lock-order violations."""
    from accelerate_tpu.analysis.lockwatch import (
        LockWatch,
        get_active_lockwatch,
        set_active_lockwatch,
    )
    from accelerate_tpu.commands.serve import ServeHealth, _engine_loop

    saved = get_active_lockwatch()
    watch = LockWatch(stream=io.StringIO())
    set_active_lockwatch(watch)
    try:
        engine = InferenceEngine(tiny_model, _cfg(async_dispatch=True))
        health = ServeHealth(replica_id=0)  # constructed armed -> watched
        health.mark_ready()
        inbox = queue_mod.Queue()
        results = []
        stop = threading.Event()
        loop = threading.Thread(
            target=_engine_loop, args=(engine, inbox, results.append, stop),
            kwargs=dict(health=health), daemon=True,
        )
        loop.start()
        probe_stop = threading.Event()

        def probe():  # the /healthz handler's concurrent reads
            while not probe_stop.is_set():
                health.payload(engine)
                time.sleep(0.001)

        prober = threading.Thread(target=probe, daemon=True)
        prober.start()
        for i in range(6):
            inbox.put(
                ({"id": i, "prompt": [1 + i % 5, 7, 3], "max_new_tokens": 6},
                 None)
            )
        deadline = time.monotonic() + 240
        while len(results) < 6 and time.monotonic() < deadline:
            time.sleep(0.05)
        stop.set()
        loop.join(timeout=120)
        probe_stop.set()
        prober.join(timeout=10)
        assert len(results) == 6, f"unanswered requests: {6 - len(results)}"
        assert not [r for r in results if "error" in r]
        assert watch.violations == 0, watch.report()
        assert engine.stats()["decode_compiles"] == 1
    finally:
        set_active_lockwatch(saved)


# ---------------------------------------------------------------------------
# chaos: exactly-once through real processes with the async loop (default)
# ---------------------------------------------------------------------------

_TINY_ARGS = [
    "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
    "--max-seq-len", "64", "--prefill-chunk", "8", "--decode-burst", "2",
]


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_TELEMETRY", None)
    env.pop("ACCELERATE_CHAOS_SPEC", None)
    env.pop("ACCELERATE_SYNC_ENGINE", None)  # the async loop IS under test
    return env


def _start_reader(proc, sink):
    def read():
        for line in proc.stdout:
            line = line.strip()
            if line:
                sink.append(line)

    t = threading.Thread(target=read, daemon=True)
    t.start()
    return t


def _wait_results(sink, n, timeout, proc=None):
    deadline = time.monotonic() + timeout
    while len(sink) < n and time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            break
        time.sleep(0.1)
    return [json.loads(line) for line in sink]


def _req(i, session=None, n_new=4):
    payload = {"id": i, "prompt": [1 + (i % 5), 7, 3], "max_new_tokens": n_new}
    if session is not None:
        payload["session_id"] = session
    return json.dumps(payload) + "\n"


@pytest.mark.slow
@pytest.mark.parametrize("spec", ["seed=1;r0:kill@3", "r0:stop@2"])
def test_chaos_exactly_once_async_loop(tmp_path, spec):
    """Under a seeded kill -9 / SIGSTOP schedule against a routed fleet of
    async-default replicas, every submitted request is answered exactly
    once and the tokens for identical prompts agree across replicas (the
    async loop never forked the decode output)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "2", "--respawn", "--min-replicas", "2",
         "--logging-dir", str(tmp_path), "--health-interval", "0.2",
         "--chaos-spec", spec, *_TINY_ARGS],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results = []
    _start_reader(proc, results)
    try:
        # warmup pins sessions: chat-0 -> replica 0, chat-1 -> replica 1
        for i in range(4):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}"))
        proc.stdin.flush()
        assert len(_wait_results(results, 4, timeout=240, proc=proc)) == 4, (
            f"fleet never answered warmup; rc={proc.poll()}"
        )
        # the wave trips the schedule on replica 0 with requests in flight
        for i in range(4, 10):
            proc.stdin.write(_req(i, session=f"chat-{i % 2}", n_new=8))
        proc.stdin.flush()
        parsed = _wait_results(results, 10, timeout=240, proc=proc)
        assert len(parsed) == 10, f"rc={proc.poll()} results={len(parsed)}"
        proc.stdin.close()
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    parsed = [json.loads(line) for line in results]
    ids = sorted(r.get("id") for r in parsed)
    assert ids == list(range(10)), f"lost/duplicated: {ids}"
    assert not [r for r in parsed if "error" in r], "chaos lost requests"
    # identical prompts -> identical greedy tokens, whichever replica (and
    # whichever respawn generation) answered: token identity survived chaos
    by_prompt = {}
    for r in parsed:
        key = (r["id"] % 5, len(r["tokens"]))
        by_prompt.setdefault(key, set()).add(tuple(r["tokens"]))
    for key, variants in by_prompt.items():
        assert len(variants) == 1, f"prompt {key} answered divergently"
