# Copyright The HuggingFace Team. All rights reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.
"""Request-scoped distributed tracing: trace_id propagation across the
router → replica → engine hops, cross-process flow stitching in ``trace
merge``, tail-latency attribution (``trace tail``), OpenMetrics exemplars,
and the bounded completed-request ring."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from accelerate_tpu.diagnostics.reqtrace import (
    collect_request_flows,
    render_tail_report,
    request_timeline,
    tail_report,
)
from accelerate_tpu.diagnostics.tracing import (
    Tracer,
    ensure_trace_id,
    merge_traces,
    new_trace_id,
    set_active_tracer,
    valid_trace_id,
    validate_chrome_trace,
)

# ---------------------------------------------------------------------------
# trace-id contract
# ---------------------------------------------------------------------------


def test_trace_id_contract():
    tid = new_trace_id()
    assert valid_trace_id(tid) and len(tid) == 16
    assert ensure_trace_id("client-supplied_1.a:b") == "client-supplied_1.a:b"
    # malformed / unsafe ids are REPLACED, never rejected
    for bad in (None, 7, "", "a b", "x" * 65, 'quo"te', "new\nline"):
        out = ensure_trace_id(bad)
        assert out != bad and valid_trace_id(out)


# ---------------------------------------------------------------------------
# cross-process merge stitching (synthetic trace files)
# ---------------------------------------------------------------------------


def _write_trace(path, pid, wall_minus_mono_s, events, name=None):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rows = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": name or f"host_{pid}"}},
        {"name": "clock_sync", "ph": "M", "pid": pid, "tid": 0,
         "args": {"wall_minus_mono_s": wall_minus_mono_s}},
    ]
    with open(path, "w") as f:
        f.write("[\n")
        for row in rows + events:
            f.write(json.dumps(row) + ",\n")


def test_merge_stitches_flows_across_skewed_clocks(tmp_path):
    """Two processes with skewed clock_sync offsets share one trace_id:
    the merged flow events land in true wall-clock order and the stitch
    metadata counts the cross-process flow with zero orphans."""
    tid = "cafef00dcafef00d"
    # router's monotonic origin is 1000s "behind" the replica's, but its
    # wall offset compensates: submit/dispatch happen BEFORE arrive/finish
    router = [
        {"name": "req/submit", "cat": "request", "ph": "b", "id": tid,
         "ts": 1_000_000.0, "pid": 0, "tid": 1},
        {"name": "req/dispatch", "cat": "request", "ph": "n", "id": tid,
         "ts": 1_100_000.0, "pid": 0, "tid": 1, "args": {"replica": 0}},
        {"name": "req/hop", "cat": "request", "ph": "s", "id": tid,
         "ts": 1_100_001.0, "pid": 0, "tid": 1},
        {"name": "req/finish", "cat": "request", "ph": "e", "id": tid,
         "ts": 2_000_000.0, "pid": 0, "tid": 1, "args": {"ok": True}},
    ]
    replica = [
        {"name": "req/hop", "cat": "request", "ph": "f", "bp": "e", "id": tid,
         "ts": 5_200_000.0, "pid": 0, "tid": 9},
        {"name": "req/arrive", "cat": "request", "ph": "b", "id": tid,
         "ts": 5_200_002.0, "pid": 0, "tid": 9},
        {"name": "req/finish", "cat": "request", "ph": "e", "id": tid,
         "ts": 5_900_000.0, "pid": 0, "tid": 9,
         "args": {"finish_reason": "length"}},
    ]
    _write_trace(str(tmp_path / "traces" / "host_0.trace.json"), 0, 100.0,
                 router, name="router")
    # replica clock: wall = mono + 96.9 → its mono 5.2s sits at wall 102.1,
    # i.e. 1.0s after the router's dispatch at wall 101.1
    _write_trace(str(tmp_path / "replica_0" / "traces" / "host_0.trace.json"),
                 0, 96.9, replica, name="replica_0")

    from accelerate_tpu.diagnostics.tracing import discover_trace_files

    paths = discover_trace_files(str(tmp_path))
    assert len(paths) == 2
    merged = merge_traces(paths=paths, output_path=str(tmp_path / "m.json"))
    validate_chrome_trace(merged)

    flows = merged["metadata"]["request_flows"]
    assert flows == {"trace_ids": 1, "cross_process": 1, "orphan_flows": 0}
    # the two processes collided on pid 0 — the merge keeps them distinct
    req = [e for e in merged["traceEvents"] if e.get("id") == tid]
    assert len({e["pid"] for e in req}) == 2
    # wall-corrected order: submit → dispatch → s → f → arrive → finishes
    names = [e["name"] for e in sorted(req, key=lambda e: e["ts"])]
    assert names.index("req/dispatch") < names.index("req/arrive")
    assert names.index("req/hop", names.index("req/dispatch")) < names.index("req/arrive")


def test_request_timeline_from_stitched_flow(tmp_path):
    """The reqtrace reader reproduces phases from raw events: queued =
    arrive→admit, prefill the remainder, explicit swap_in seconds."""
    tid = "feedbeeffeedbeef"
    events = [
        {"name": "req/arrive", "cat": "request", "ph": "b", "id": tid,
         "ts": 1_000_000.0, "pid": 0, "tid": 1, "args": {"priority": "batch"}},
        {"name": "req/admit", "cat": "request", "ph": "n", "id": tid,
         "ts": 1_300_000.0, "pid": 0, "tid": 1, "args": {"slot": 0}},
        {"name": "req/swap_in", "cat": "request", "ph": "n", "id": tid,
         "ts": 1_310_000.0, "pid": 0, "tid": 1, "args": {"seconds": 0.05}},
        {"name": "req/first_token", "cat": "request", "ph": "n", "id": tid,
         "ts": 1_500_000.0, "pid": 0, "tid": 1},
        {"name": "req/finish", "cat": "request", "ph": "e", "id": tid,
         "ts": 1_900_000.0, "pid": 0, "tid": 1,
         "args": {"finish_reason": "eos", "new_tokens": 5, "tpot_s": 0.1}},
    ]
    _write_trace(str(tmp_path / "traces" / "host_0.trace.json"), 0, 0.0, events)
    flows = collect_request_flows(str(tmp_path))
    assert set(flows) == {tid}
    t = request_timeline(tid, flows[tid])
    assert t["complete"]
    assert t["ttft_s"] == pytest.approx(0.5)
    assert t["phases"]["queued"] == pytest.approx(0.3)
    assert t["phases"]["swap_in"] == pytest.approx(0.05)
    assert t["phases"]["prefill"] == pytest.approx(0.15)
    assert t["finish_reason"] == "eos" and t["tpot_s"] == pytest.approx(0.1)
    report = tail_report(str(tmp_path), k=5)
    assert report["k"] == 1 and report["attribution"]["queued"] == pytest.approx(60.0)
    assert "queued 60.0%" in render_tail_report(report)


def test_timeline_picks_first_finishing_engine_half_on_timeout_requeue(tmp_path):
    """A request_timeout requeue can run TWO full engine lifecycles under
    one trace_id (the slow-but-alive replica keeps going after the router
    re-dispatched). The router delivers the FIRST answer, so the timeline
    must come from the half that finished first — never a cross-replica
    splice of A's arrival with B's first token."""
    tid = "a0a0a0a0a0a0a0a0"

    def half(t0, ttft_us, dur_us):
        return [
            {"name": "req/arrive", "cat": "request", "ph": "b", "id": tid,
             "ts": t0, "pid": 0, "tid": 1},
            {"name": "req/admit", "cat": "request", "ph": "n", "id": tid,
             "ts": t0 + 1000.0, "pid": 0, "tid": 1, "args": {"slot": 0}},
            {"name": "req/first_token", "cat": "request", "ph": "n", "id": tid,
             "ts": t0 + ttft_us, "pid": 0, "tid": 1},
            {"name": "req/finish", "cat": "request", "ph": "e", "id": tid,
             "ts": t0 + dur_us, "pid": 0, "tid": 1,
             "args": {"finish_reason": "length", "new_tokens": 4}},
        ]

    # slow replica A: arrived first, finishes LAST; fast replica B's
    # answer is the one the router delivered
    _write_trace(str(tmp_path / "replica_0" / "traces" / "host_0.trace.json"),
                 0, 0.0, half(1_000_000.0, 900_000.0, 2_000_000.0),
                 name="replica_0")
    _write_trace(str(tmp_path / "replica_1" / "traces" / "host_1.trace.json"),
                 1, 0.0, half(1_400_000.0, 100_000.0, 400_000.0),
                 name="replica_1")
    flows = collect_request_flows(str(tmp_path))
    t = request_timeline(tid, flows[tid])
    assert t["engine_finish_events"] == 2  # both lifecycles are visible...
    assert t["ttft_s"] == pytest.approx(0.1)  # ...but the timeline is B's
    assert t["roles"] == ["replica_0", "replica_1"]


# ---------------------------------------------------------------------------
# engine: request events + completed ring (deadline-expiry path — finishes
# requests without ever compiling, so this stays in the fast lane)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


def test_completed_ring_caps_history_and_totals_keep_counting(tiny_model, tmp_path):
    from accelerate_tpu.serving import EngineConfig, InferenceEngine

    tracer = Tracer(logging_dir=str(tmp_path), host=0, process_name="serve")
    set_active_tracer(tracer)
    try:
        engine = InferenceEngine(
            tiny_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         completed_history=2),
        )
        for i in range(5):
            engine.add_request([1, 2, 3], max_new_tokens=4, deadline_ms=0.01,
                               trace_id=f"ring{i:012d}")
        time.sleep(0.05)
        finished = engine.step()  # all five expire in the queue — no compile
        assert len(finished) == 5
        stats = engine.stats()
        # the counter keeps counting past the cap; the window is the ring
        assert stats["completed"] == 5
        assert stats["completed_window"] == 2
        assert len(engine._completed) == 2
        assert stats["decode_compiles"] == 0 and stats["prefill_compiles"] == 0
    finally:
        tracer.close()
        set_active_tracer(None)
    # exactly one begin and one finish event per request, even on the
    # never-admitted deadline-expiry path
    flows = collect_request_flows(str(tmp_path))
    assert len(flows) == 5
    for tid, events in flows.items():
        t = request_timeline(tid, events)
        assert t["engine_finish_events"] == 1
        assert t["finish_reason"] == "deadline_exceeded"
        assert t["complete"]


@pytest.mark.slow
def test_engine_spans_reproduce_ttft_and_per_class_stats(tiny_model, tmp_path):
    """Acceptance: span-derived TTFT matches the engine-reported value to
    within 5ms, per-class percentiles appear, and tracing armed leaves the
    one-executable contract intact."""
    from accelerate_tpu.serving import EngineConfig, InferenceEngine

    tracer = Tracer(logging_dir=str(tmp_path), host=0, process_name="serve")
    set_active_tracer(tracer)
    try:
        engine = InferenceEngine(
            tiny_model,
            EngineConfig(num_slots=2, block_size=8, max_seq_len=64,
                         prefill_chunk=8, decode_burst=2),
        )
        done = []
        for i in range(4):
            engine.add_request(
                [1 + i, 2, 3, 4], max_new_tokens=5,
                priority="interactive" if i % 2 else "batch",
                trace_id=f"req{i:013d}",
            )
        done = engine.run_until_idle()
        stats = engine.stats()
        assert stats["decode_compiles"] == 1
    finally:
        tracer.close()
        set_active_tracer(None)

    assert {"interactive", "batch"} <= set(stats["ttft_s"]["by_class"])
    assert stats["ttft_s"]["by_class"]["interactive"]["p50"] > 0

    flows = collect_request_flows(str(tmp_path))
    assert len(flows) == 4
    by_id = {t["trace_id"]: t for t in (
        request_timeline(tid, evs) for tid, evs in flows.items()
    )}
    for req in done:
        t = by_id[req.trace_id]
        assert t["complete"] and t["engine_finish_events"] == 1
        assert abs(t["ttft_s"] - req.ttft_s) < 0.005, (t["ttft_s"], req.ttft_s)
        assert sum(t["phases"].values()) == pytest.approx(t["ttft_s"], abs=1e-6)


def test_requests_get_trace_ids_with_tracing_disabled(tiny_model):
    """No tracer: trace ids still exist (answer rows and exemplars key on
    them) and nothing else changes."""
    from accelerate_tpu.serving import EngineConfig, InferenceEngine

    engine = InferenceEngine(
        tiny_model, EngineConfig(num_slots=2, block_size=8, max_seq_len=64)
    )
    req = engine.add_request([1, 2, 3], max_new_tokens=2)
    assert valid_trace_id(req.trace_id)
    kept = engine.add_request([1, 2, 3], max_new_tokens=2, trace_id="keep-me-1")
    assert kept.trace_id == "keep-me-1"


# ---------------------------------------------------------------------------
# router: trace_id born at submit, stamped into the dispatched payload
# ---------------------------------------------------------------------------


def test_router_stamps_trace_id_into_dispatched_payload():
    from accelerate_tpu.serving.replica import ReplicaError, ReplicaHandle
    from accelerate_tpu.serving.router import Router

    class StubReplica(ReplicaHandle):
        def __init__(self, replica_id):
            super().__init__(replica_id, f"http://stub/{replica_id}")
            self.state = "ready"
            self.handled = []

        def check_health(self, timeout=2.0):
            self.last_heartbeat = time.time()
            return {"state": self.state}

        def generate(self, payload, timeout=None):
            self.handled.append(payload)
            return {"id": payload.get("id"), "tokens": [1],
                    "trace_id": payload.get("trace_id"),
                    "finish_reason": "length"}

    stub = StubReplica(0)
    router = Router([stub], health_interval=60.0)
    try:
        kept = router.submit({"id": 0, "prompt": [1] * 16,
                              "trace_id": "client-0001"})
        fresh = router.submit({"id": 1, "prompt": [2] * 16})
        malformed = router.submit({"id": 2, "prompt": [3] * 16,
                                   "trace_id": "spaced out"})
        for t in (kept, fresh, malformed):
            assert t.done.wait(timeout=10.0)
        assert kept.result["trace_id"] == "client-0001"
        assert valid_trace_id(fresh.result["trace_id"])
        assert valid_trace_id(malformed.result["trace_id"])
        assert malformed.result["trace_id"] != "spaced out"
        dispatched = {p["id"]: p for p in stub.handled}
        assert dispatched[0]["trace_id"] == "client-0001"
        assert all("trace_id" in p for p in stub.handled)
    finally:
        router.close()


def test_router_error_rows_carry_trace_id():
    from accelerate_tpu.serving.replica import ReplicaHandle
    from accelerate_tpu.serving.router import Router

    class DeadStub(ReplicaHandle):
        def __init__(self):
            super().__init__(0, "http://stub/0")
            self.state = "ready"

    router = Router([DeadStub()], health_interval=60.0)
    try:
        router.stop_admission()
        ticket = router.submit({"id": 9, "prompt": [1] * 16})
        assert ticket.done.wait(timeout=10.0)
        assert "error" in ticket.result
        assert valid_trace_id(ticket.result["trace_id"])
    finally:
        router.close()


# ---------------------------------------------------------------------------
# real-process acceptance: a client-supplied trace_id survives the router
# subprocess → replica row → trace file verbatim, and the merged fleet
# timeline stitches it with zero orphan flows
# ---------------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("ACCELERATE_TELEMETRY", None)
    return env


def test_route_cli_trace_id_survives_verbatim(tmp_path):
    logdir = tmp_path / "fleet"
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "route", "--replicas", "1", "--logging-dir", str(logdir),
         "--health-interval", "0.2",
         "--preset", "tiny", "--num-slots", "2", "--block-size", "8",
         "--max-seq-len", "64", "--prefill-chunk", "8", "--decode-burst", "2"],
        env=_cli_env(), stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    results = []

    def read():
        for line in proc.stdout:
            if line.strip():
                results.append(line.strip())

    threading.Thread(target=read, daemon=True).start()
    tid = "cafef00d-e2e-0001"
    try:
        proc.stdin.write(json.dumps(
            {"id": 0, "prompt": [1, 7, 3], "max_new_tokens": 4, "trace_id": tid}
        ) + "\n")
        proc.stdin.write(json.dumps(
            {"id": 1, "prompt": [2, 7, 3], "max_new_tokens": 4}
        ) + "\n")
        proc.stdin.flush()
        deadline = time.monotonic() + 240
        while len(results) < 2 and time.monotonic() < deadline and proc.poll() is None:
            time.sleep(0.1)
        proc.stdin.close()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert rc == 0
    rows = {r["id"]: r for r in map(json.loads, results)}
    # verbatim through submit → HTTP hop → engine → answer row
    assert rows[0]["trace_id"] == tid
    assert valid_trace_id(rows[1]["trace_id"])

    # ... and verbatim in BOTH processes' trace files
    router_flows = collect_request_flows(
        paths=[str(p) for p in (logdir / "traces").glob("host_*.trace.json")]
    )
    replica_flows = collect_request_flows(
        paths=[str(p) for p in logdir.glob("replica_*/traces/host_*.trace.json")]
    )
    assert tid in router_flows and tid in replica_flows

    # the stitched fleet timeline: cross-process flows, zero orphans,
    # exactly-once engine finish per request
    merged = merge_traces(
        paths=[str(p) for p in sorted(logdir.glob("**/host_*.trace.json"))],
        output_path=str(tmp_path / "merged.json"),
    )
    validate_chrome_trace(merged)
    flows = merged["metadata"]["request_flows"]
    assert flows["trace_ids"] == 2
    assert flows["cross_process"] == 2
    assert flows["orphan_flows"] == 0

    report = tail_report(str(logdir), k=5)
    assert report["measured_requests"] == 2 and report["incomplete"] == 0
    tail_by_id = {t["trace_id"]: t for t in report["tail"]}
    # trace tail reproduces the engine-reported TTFT within 5ms
    assert abs(tail_by_id[tid]["ttft_s"] - rows[0]["ttft_s"]) < 0.005
