"""Run every shipped example on the virtual CPU mesh (reference
``tests/test_examples.py`` runs its examples with mocked dataloaders; here
the vendored dataset makes them fully runnable) + quality bars (the
reference's ``external_deps/test_performance.py`` pins accuracy per
config).

Examples execute in-process (``runpy``) so they share the XLA compile
cache — the scripts use identical model/batch shapes, so the whole file
compiles once. The launcher boundary is still covered by one subprocess
test. The conftest fixture resets the state singletons between tests.
"""

import contextlib
import io
import os
import re
import runpy
import subprocess
import sys

import pytest

pytestmark = pytest.mark.examples  # end-to-end example runs: slowest lane (make test_all)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")
BY_FEATURE = os.path.join(EXAMPLES, "by_feature")


def _run(script, *args):
    """Execute an example in-process with argv patched; returns stdout."""
    path = script if os.path.isabs(script) else os.path.join(EXAMPLES, script)
    old_argv, old_cwd = sys.argv, os.getcwd()
    added = EXAMPLES not in sys.path
    if added:
        sys.path.insert(0, EXAMPLES)
    sys.argv = [path, *args]
    os.chdir(EXAMPLES)
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old_argv
        os.chdir(old_cwd)
        if added:
            sys.path.remove(EXAMPLES)
    return buf.getvalue()


def test_nlp_example_reaches_quality_bar():
    stdout = _run("nlp_example.py", "--num_epochs", "2")
    last = [l for l in stdout.splitlines() if l.startswith("epoch")][-1]
    acc = float(last.split("'accuracy': ")[1].split(",")[0].rstrip("}"))
    assert acc >= 0.85, f"accuracy bar missed: {last}"


def test_complete_nlp_example_checkpoints_and_tracks(tmp_path):
    stdout = _run(
        "complete_nlp_example.py", "--num_epochs", "1",
        "--checkpointing_steps", "epoch", "--with_tracking",
        "--output_dir", str(tmp_path),
    )
    assert "epoch 0" in stdout
    assert (tmp_path / "epoch_0").is_dir()
    assert any(p.name.startswith("complete_nlp") for p in tmp_path.iterdir())


def test_complete_nlp_example_resumes(tmp_path):
    _run(
        "complete_nlp_example.py", "--num_epochs", "1",
        "--checkpointing_steps", "epoch", "--output_dir", str(tmp_path),
    )
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    stdout = _run(
        "complete_nlp_example.py", "--num_epochs", "2",
        "--resume_from_checkpoint", str(tmp_path / "epoch_0"),
        "--output_dir", str(tmp_path),
    )
    assert "Resumed from checkpoint" in stdout
    assert "epoch 1" in stdout and "epoch 0:" not in stdout  # skipped epoch 0


def test_gradient_accumulation_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "gradient_accumulation.py"), "--num_epochs", "1"
    )
    assert "epoch 0" in stdout


def test_checkpointing_example(tmp_path):
    stdout = _run(
        os.path.join(BY_FEATURE, "checkpointing.py"), "--num_epochs", "1",
        "--output_dir", str(tmp_path),
    )
    assert "epoch 0" in stdout
    assert (tmp_path / "checkpoints" / "checkpoint_0").is_dir()


def test_memory_example():
    stdout = _run(os.path.join(BY_FEATURE, "memory.py"), "--num_epochs", "1")
    assert "ran with batch sizes: [16]" in stdout


def test_profiler_example(tmp_path):
    _run(
        os.path.join(BY_FEATURE, "profiler.py"), "--trace_dir", str(tmp_path),
        "--profile_steps", "2",
    )
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no trace files written"


def test_early_stopping_example():
    stdout = _run(os.path.join(BY_FEATURE, "early_stopping.py"), "--num_epochs", "4")
    assert "early stop at" in stdout


def test_local_sgd_example():
    stdout = _run(os.path.join(BY_FEATURE, "local_sgd.py"), "--num_epochs", "1")
    assert "final loss" in stdout


def test_ddp_comm_hook_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "ddp_comm_hook.py"), "--num_epochs", "2",
        "--comm_hook", "bf16",
    )
    assert "grad comm hook: bf16" in stdout  # active on the 8-device dp mesh
    last = [l for l in stdout.splitlines() if l.startswith("epoch")][-1]
    acc = float(last.split("'accuracy': ")[1].split(",")[0].rstrip("}"))
    # same bar as the canonical nlp example at 2 epochs: the compressed
    # reduction must not cost convergence
    assert acc >= 0.85, f"comm-hook training underperformed: {last}"


def test_context_parallel_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "context_parallel.py"),
        "--cp", "4", "--mode", "ring", "--seq", "128", "--steps", "24",
    )
    assert "'cp': 4" in stdout
    m = re.search(r"recall loss ([\d.]+) -> ([\d.]+)", stdout)
    assert m, stdout
    assert float(m.group(2)) < float(m.group(1))  # recall task is learnable


def test_megatron_lm_pretraining_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "megatron_lm_pretraining.py"),
        "--tp", "2", "--pp", "2", "--num_micro_batches", "4", "--num_epochs", "1",
    )
    assert "'pp': 2" in stdout and "'tp': 2" in stdout
    m = re.search(r"pretraining loss ([\d.]+) -> ([\d.]+)", stdout)
    assert m, stdout
    assert float(m.group(2)) < float(m.group(1))  # bigram structure is learnable


def test_tracking_example(tmp_path):
    stdout = _run(
        os.path.join(BY_FEATURE, "tracking.py"), "--num_epochs", "1",
        "--project_dir", str(tmp_path),
    )
    assert "epoch 0" in stdout
    assert any(tmp_path.iterdir()), "tracker wrote nothing"


def test_multi_process_metrics_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "multi_process_metrics.py"), "--num_epochs", "1"
    )
    assert "exact over 160 samples" in stdout


def test_fsdp_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "fsdp_with_peak_mem_tracking.py"),
        "--steps", "4", "--fsdp_degree", "2",
    )
    assert "loss" in stdout and "peak_mem" in stdout


@pytest.mark.slow
def test_nlp_example_under_launcher():
    """The example must also run through the product's own launcher
    (reference pattern: ``tests/test_examples.py`` + ``accelerate launch``)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [
            sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", "launch",
            "--num_cpu_devices", "8",
            os.path.join(EXAMPLES, "nlp_example.py"), "--num_epochs", "1",
        ],
        capture_output=True, text=True, cwd=EXAMPLES, timeout=420, env=env,
    )
    assert out.returncode == 0, f"launch failed:\n{out.stdout}\n{out.stderr}"
    assert "epoch 0" in out.stdout


def test_cv_example_reaches_quality_bar():
    stdout = _run("cv_example.py", "--num_epochs", "8")
    last = [l for l in stdout.splitlines() if l.startswith("epoch")][-1]
    acc = float(last.split("accuracy ")[1])
    assert acc >= 0.8, f"cv accuracy bar missed: {last}"


def test_deepspeed_config_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "deepspeed_with_config_support.py"), "--num_epochs", "1"
    )
    assert "resolved ds config" in stdout and '"auto"' not in stdout.split("resolved ds config:")[1].splitlines()[0]


def test_cross_validation_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "cross_validation.py"), "--num_folds", "2",
        "--num_epochs", "1",
    )
    assert "cross-validated accuracy" in stdout


def test_pippy_inference_examples():
    stdout = _run(
        os.path.join(EXAMPLES, "inference", "pippy", "llama.py"),
        "--layers", "4", "--hidden", "64", "--batch", "4", "--seq", "16",
    )
    assert "stages split at" in stdout and "logits" in stdout
    stdout = _run(
        os.path.join(EXAMPLES, "inference", "pippy", "gpt2.py"),
        "--layers", "4", "--batch", "4", "--seq", "16",
    )
    assert "stages split at" in stdout
    stdout = _run(
        os.path.join(EXAMPLES, "inference", "pippy", "t5.py"),
        "--layers", "2", "--batch", "4", "--seq", "16", "--dec_seq", "8",
    )
    assert "stages split at" in stdout
    stdout = _run(
        os.path.join(EXAMPLES, "inference", "pippy", "bert.py"),
        "--layers", "4", "--batch", "4", "--seq", "16",
    )
    assert "stages split at" in stdout


def test_split_inference_example():
    stdout = _run(
        os.path.join(EXAMPLES, "inference", "distributed", "split_inference.py"),
        "--num_prompts", "4",
    )
    assert "next-token predictions" in stdout


def test_distributed_inference_task_examples():
    """The task-shaped distributed-inference quartet (reference ships six
    Hub-checkpoint scripts; these run the same distribution patterns with
    synthetic weights)."""
    d = os.path.join(EXAMPLES, "inference", "distributed")
    assert "generated 4 images" in _run(
        os.path.join(d, "distributed_image_generation.py"), "--prompts", "4", "--steps", "4"
    )
    assert "synthesised" in _run(
        os.path.join(d, "distributed_speech_generation.py"),
        "--chunks", "3", "--codes_per_chunk", "4",
    )
    assert "answered" in _run(os.path.join(d, "florence2.py"), "--images", "2")
    assert "denoised" in _run(os.path.join(d, "stable_diffusion.py"), "--steps", "4")


def test_phi2_low_memory_example():
    stdout = _run(
        os.path.join(EXAMPLES, "inference", "distributed", "phi2.py"),
        "--prompts", "3", "--new_tokens", "4",
    )
    assert "generated 4 tokens for 3 prompts" in stdout


def test_config_yaml_templates_load():
    from accelerate_tpu.commands.config import ClusterConfig

    tpl_dir = os.path.join(EXAMPLES, "config_yaml_templates")
    for name in os.listdir(tpl_dir):
        cfg = ClusterConfig.load(os.path.join(tpl_dir, name))
        env = cfg.to_environment()
        assert "ACCELERATE_MIXED_PRECISION" in env, name


def test_deepspeed_templates_ingest():
    from accelerate_tpu import DeepSpeedPlugin

    tpl_dir = os.path.join(EXAMPLES, "deepspeed_config_templates")
    p2 = DeepSpeedPlugin(hf_ds_config=os.path.join(tpl_dir, "zero_stage2_config.json"))
    assert p2.zero_stage == 2
    p3 = DeepSpeedPlugin(hf_ds_config=os.path.join(tpl_dir, "zero_stage3_offload_config.json"))
    assert p3.zero_stage == 3 and p3.offload_param_device == "cpu"


def test_schedule_free_example():
    stdout = _run(os.path.join(BY_FEATURE, "schedule_free.py"), "--num_epochs", "1")
    assert "epoch 0" in stdout


def test_automatic_gradient_accumulation_example():
    stdout = _run(
        os.path.join(BY_FEATURE, "automatic_gradient_accumulation.py"), "--num_epochs", "1"
    )
    assert "ran with (batch_size, accumulation): [(16, 1)]" in stdout
