"""State singleton behaviour (reference analog: tests over state.py)."""

import jax
import numpy as np
import pytest

from accelerate_tpu import DistributedType, MeshPlugin
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


def test_partial_state_singleton():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.initialized
    assert a.num_processes == 1  # single host
    assert a.process_index == 0
    assert a.is_main_process and a.is_local_main_process and a.is_last_process


def test_mesh_built_over_8_cpu_devices():
    state = PartialState()
    assert state.num_devices == 8
    assert state.distributed_type == DistributedType.CPU_MESH
    assert dict(state.mesh.shape) == {"dp": 8, "pp": 1, "fsdp": 1, "ep": 1, "cp": 1, "tp": 1}
    assert state.data_parallel_size == 8


def test_mesh_plugin_shapes():
    state = PartialState(mesh_plugin=MeshPlugin(dp=-1, fsdp=2, tp=2))
    assert dict(state.mesh.shape) == {"dp": 2, "pp": 1, "fsdp": 2, "ep": 1, "cp": 1, "tp": 2}


def test_mesh_plugin_invalid_shape():
    with pytest.raises(ValueError):
        MeshPlugin(dp=3, tp=2).axis_sizes(8)
    with pytest.raises(ValueError):
        MeshPlugin(dp=-1, tp=-1).axis_sizes(8)


def test_split_between_processes_single():
    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as x:
        assert x == [1, 2, 3]


def test_on_main_process_decorator():
    state = PartialState()
    calls = []

    @state.on_main_process
    def fn(v):
        calls.append(v)
        return v

    fn(1)
    assert calls == [1]


def test_accelerator_state_precision_conflict():
    AcceleratorState(mixed_precision="bf16", _from_accelerator=True)
    with pytest.raises(ValueError):
        AcceleratorState(mixed_precision="fp16")


def test_accelerator_state_delegates_partial():
    s = AcceleratorState(mixed_precision="no", _from_accelerator=True)
    assert s.num_processes == 1
    assert s.mesh.size == 8
    assert s.mixed_precision == "no"


def test_gradient_state_defaults():
    gs = GradientState()
    assert gs.sync_gradients
    assert gs.num_steps == 1
    assert gs.remainder == -1
    assert not gs.end_of_dataloader


def test_wait_for_everyone_noop_single_host():
    PartialState().wait_for_everyone()  # must not raise
