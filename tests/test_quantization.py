"""Quantized loading (reference ``utils/bnb.py:44`` semantics;
``tests/test_quantization.py`` 966 LoC is the reference suite)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import load_and_quantize_model
from accelerate_tpu.big_modeling import cpu_offload, DispatchedModel
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.utils.modeling import flat_param_shapes, infer_auto_device_map
from accelerate_tpu.utils.quantization import (
    BnbQuantizationConfig,
    QTensor,
    dequantize_tree,
    quantize_array,
    quantize_model_params,
)


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 32)).astype(np.float32)
    qt = quantize_array(w)
    assert qt.q.dtype == np.int8
    assert qt.scale.shape == (1, 32)
    back = np.asarray(qt.q, np.float32) * qt.scale
    # absmax/127 per channel → max error is half a quantization step
    assert np.max(np.abs(back - w)) <= np.max(np.abs(w)) / 127 + 1e-6


def _tiny_llama():
    config = LlamaConfig.tiny(layers=2)
    model = LlamaForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    return config, model, ids


def test_quantized_model_forward_close_to_fp32():
    config, model, ids = _tiny_llama()
    ref = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])
    model = quantize_model_params(model, BnbQuantizationConfig())
    leaves = jax.tree.leaves(
        model.params, is_leaf=lambda l: isinstance(l, QTensor)
    )
    assert any(isinstance(l, QTensor) for l in leaves)
    out = np.asarray(jax.jit(model.apply_fn)(model.params, input_ids=ids)["logits"])
    # int8 per-channel error stays small relative to logit scale
    denom = max(np.abs(ref).max(), 1.0)
    assert np.max(np.abs(out - ref)) / denom < 0.05
    # ranking survives quantization for most positions
    agree = np.mean(ref.argmax(-1) == out.argmax(-1))
    assert agree > 0.9


def test_skip_modules_keep_fp32():
    config, model, _ = _tiny_llama()
    model = quantize_model_params(
        model, BnbQuantizationConfig(skip_modules=["embed_tokens", "lm_head"])
    )
    assert not isinstance(model.params["embed_tokens"], QTensor)
    assert not isinstance(model.params["lm_head"], QTensor)
    assert isinstance(model.params["layers"]["wq"], QTensor)


def test_device_map_sizing_halves_with_int8():
    config, model, _ = _tiny_llama()
    fp32_shapes = flat_param_shapes(model)
    fp32_bytes = sum(
        int(np.prod(s)) * 4 for s, _ in fp32_shapes.values()
    )
    model = quantize_model_params(
        model, BnbQuantizationConfig(quantize_embeddings=True)
    )
    q_shapes = flat_param_shapes(model)
    q_bytes = 0
    for shape, dtype in q_shapes.values():
        q_bytes += int(np.prod(shape) if shape else 1) * jnp.dtype(dtype).itemsize
    assert q_bytes < 0.3 * fp32_bytes  # int8 + small scales ≈ 25%

    # the quantized model fits a budget the fp32 one cannot
    budget = {0: int(q_bytes * 1.1), "cpu": 0, "disk": 0}
    dm = infer_auto_device_map(q_shapes, max_memory=budget)
    assert set(map(str, dm.values())) == {"0"}
    with pytest.raises(ValueError):
        infer_auto_device_map(fp32_shapes, max_memory=budget)


def test_quantized_streaming_offload_matches_resident():
    config, model, ids = _tiny_llama()
    model = quantize_model_params(model, BnbQuantizationConfig())
    ref = np.asarray(jax.jit(model.apply_fn)(model.params, input_ids=ids)["logits"])
    dispatched = cpu_offload(model)
    assert isinstance(dispatched, DispatchedModel)
    out = np.asarray(dispatched(input_ids=ids).logits)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_load_and_quantize_model_auto_map(tmp_path):
    config, model, ids = _tiny_llama()
    # save a checkpoint, reload+quantize+dispatch in one call
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(model.params)[0]:
        key = ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(leaf)
    np.savez(tmp_path / "model.npz", **flat)
    ref = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])

    fresh = LlamaForCausalLM.from_config(config, seed=0)  # different init
    quantized = load_and_quantize_model(
        fresh,
        BnbQuantizationConfig(),
        weights_location=str(tmp_path / "model.npz"),
        device_map={"": "cpu"},
    )
    out = np.asarray(quantized(input_ids=ids).logits)
    denom = max(np.abs(ref).max(), 1.0)
    assert np.max(np.abs(out - ref)) / denom < 0.05


def test_embeddings_skipped_by_default():
    from accelerate_tpu.utils.quantization import DEFAULT_SKIP_MODULES

    config, model, _ = _tiny_llama()
    model = quantize_model_params(model, BnbQuantizationConfig())
    assert not isinstance(model.params["embed_tokens"], QTensor)
    assert not isinstance(model.params["lm_head"], QTensor)
    assert isinstance(model.params["layers"]["wq"], QTensor)
    assert "wte" in DEFAULT_SKIP_MODULES  # gpt2 names covered too


def test_quantize_failure_leaves_model_intact():
    config, model, _ = _tiny_llama()
    orig_apply = model.apply_fn
    with pytest.raises(ValueError, match="eligible"):
        quantize_model_params(model, BnbQuantizationConfig(skip_modules=["layers"]))
    assert model.apply_fn is orig_apply
    assert not getattr(model, "is_quantized", False)
