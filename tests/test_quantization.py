"""Quantized loading (reference ``utils/bnb.py:44`` semantics;
``tests/test_quantization.py`` 966 LoC is the reference suite)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import load_and_quantize_model
from accelerate_tpu.big_modeling import cpu_offload, DispatchedModel
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.utils.modeling import flat_param_shapes, infer_auto_device_map
from accelerate_tpu.utils.quantization import (
    BnbQuantizationConfig,
    QTensor,
    dequantize_tree,
    quantize_array,
    quantize_model_params,
)

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 32)).astype(np.float32)
    qt = quantize_array(w)
    assert qt.q.dtype == np.int8
    assert qt.scale.shape == (1, 32)
    back = np.asarray(qt.q, np.float32) * qt.scale
    # absmax/127 per channel → max error is half a quantization step
    assert np.max(np.abs(back - w)) <= np.max(np.abs(w)) / 127 + 1e-6


def _tiny_llama():
    config = LlamaConfig.tiny(layers=2)
    model = LlamaForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    return config, model, ids


def test_quantized_model_forward_close_to_fp32():
    config, model, ids = _tiny_llama()
    ref = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])
    model = quantize_model_params(model, BnbQuantizationConfig())
    leaves = jax.tree.leaves(
        model.params, is_leaf=lambda l: isinstance(l, QTensor)
    )
    assert any(isinstance(l, QTensor) for l in leaves)
    out = np.asarray(jax.jit(model.apply_fn)(model.params, input_ids=ids)["logits"])
    # int8 per-channel error stays small relative to logit scale
    denom = max(np.abs(ref).max(), 1.0)
    assert np.max(np.abs(out - ref)) / denom < 0.05
    # ranking survives quantization for most positions
    agree = np.mean(ref.argmax(-1) == out.argmax(-1))
    assert agree > 0.9


def test_deep_stack_norms_stay_fp():
    """Regression: at >=16 stacked layers a [L, h] norm/vector leaf passed
    the shape[-2] >= 16 matmul-weight guard and was quantized with ONE
    scale shared across layers, breaking per-layer scan slicing (leading
    axes L vs 1). Stacked-prefix leaves must be rank >= 3 to quantize."""
    config = LlamaConfig.tiny(layers=16)
    model = LlamaForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    ref = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])
    model = quantize_model_params(model, BnbQuantizationConfig())
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        model.params, is_leaf=lambda l: isinstance(l, QTensor)
    )[0]:
        name = str(path[-1])
        if "norm" in name:
            assert not isinstance(leaf, QTensor), name
    out = np.asarray(jax.jit(model.apply_fn)(model.params, input_ids=ids)["logits"])
    assert np.max(np.abs(out - ref)) / max(np.abs(ref).max(), 1.0) < 0.05


def test_mixtral_declares_stacked_prefix():
    """Every layer-stacked zoo family must declare stacked_params_prefix —
    the quantization eligibility guard keys off it (review follow-up to
    test_deep_stack_norms_stay_fp: mixtral and vit scanned stacked layers
    without declaring)."""
    from accelerate_tpu.models import MODEL_ZOO

    for name in ("mixtral-8x7b", "vit-base-patch16-224"):
        import accelerate_tpu.big_modeling as bm

        cfg, factory = MODEL_ZOO[name]
        with bm.init_empty_weights():
            meta = factory(cfg)
        assert getattr(meta, "stacked_params_prefix", None) == "layers", name


def test_skip_modules_keep_fp32():
    config, model, _ = _tiny_llama()
    model = quantize_model_params(
        model, BnbQuantizationConfig(skip_modules=["embed_tokens", "lm_head"])
    )
    assert not isinstance(model.params["embed_tokens"], QTensor)
    assert not isinstance(model.params["lm_head"], QTensor)
    assert isinstance(model.params["layers"]["wq"], QTensor)


def test_device_map_sizing_halves_with_int8():
    config, model, _ = _tiny_llama()
    fp32_shapes = flat_param_shapes(model)
    fp32_bytes = sum(
        int(np.prod(s)) * 4 for s, _ in fp32_shapes.values()
    )
    model = quantize_model_params(
        model, BnbQuantizationConfig(quantize_embeddings=True)
    )
    q_shapes = flat_param_shapes(model)
    q_bytes = 0
    for shape, dtype in q_shapes.values():
        q_bytes += int(np.prod(shape) if shape else 1) * jnp.dtype(dtype).itemsize
    assert q_bytes < 0.3 * fp32_bytes  # int8 + small scales ≈ 25%

    # the quantized model fits a budget the fp32 one cannot
    budget = {0: int(q_bytes * 1.1), "cpu": 0, "disk": 0}
    dm = infer_auto_device_map(q_shapes, max_memory=budget)
    assert set(map(str, dm.values())) == {"0"}
    with pytest.raises(ValueError):
        infer_auto_device_map(fp32_shapes, max_memory=budget)


def test_quantized_streaming_offload_matches_resident():
    """The streamed path runs int8 GEMMs with row-quantized activations
    (bnb ``Linear8bitLt`` semantics — reference ``utils/bnb.py:221``), so
    it matches the resident exact-dequant path approximately: int8
    activation rounding is ~0.4% per matmul. The int8 bytes being both
    what crosses the offload tiers and what the GEMM reads is what makes
    quantized offload faster than fp32 (VERDICT r3 weak-3)."""
    config, model, ids = _tiny_llama()
    model = quantize_model_params(model, BnbQuantizationConfig())
    ref = np.asarray(jax.jit(model.apply_fn)(model.params, input_ids=ids)["logits"])
    dispatched = cpu_offload(model)
    assert isinstance(dispatched, DispatchedModel)
    out = np.asarray(dispatched(input_ids=ids).logits)
    rel = np.max(np.abs(out - ref)) / max(np.abs(ref).max(), 1e-6)
    assert rel < 0.03, f"streamed int8 GEMM drifted {rel:.4f} from exact dequant"
    # rankings survive: the argmax token agrees almost everywhere
    agree = np.mean(np.argmax(out, -1) == np.argmax(ref, -1))
    assert agree > 0.97, f"argmax agreement {agree:.3f}"


def test_load_and_quantize_model_auto_map(tmp_path):
    config, model, ids = _tiny_llama()
    # save a checkpoint, reload+quantize+dispatch in one call
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(model.params)[0]:
        key = ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(leaf)
    np.savez(tmp_path / "model.npz", **flat)
    ref = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])

    fresh = LlamaForCausalLM.from_config(config, seed=0)  # different init
    quantized = load_and_quantize_model(
        fresh,
        BnbQuantizationConfig(),
        weights_location=str(tmp_path / "model.npz"),
        device_map={"": "cpu"},
    )
    out = np.asarray(quantized(input_ids=ids).logits)
    denom = max(np.abs(ref).max(), 1.0)
    assert np.max(np.abs(out - ref)) / denom < 0.05


def test_embeddings_skipped_by_default():
    from accelerate_tpu.utils.quantization import DEFAULT_SKIP_MODULES

    config, model, _ = _tiny_llama()
    model = quantize_model_params(model, BnbQuantizationConfig())
    assert not isinstance(model.params["embed_tokens"], QTensor)
    assert not isinstance(model.params["lm_head"], QTensor)
    assert isinstance(model.params["layers"]["wq"], QTensor)
    assert "wte" in DEFAULT_SKIP_MODULES  # gpt2 names covered too


def test_quantize_failure_leaves_model_intact():
    config, model, _ = _tiny_llama()
    orig_apply = model.apply_fn
    with pytest.raises(ValueError, match="eligible"):
        quantize_model_params(model, BnbQuantizationConfig(skip_modules=["layers"]))
    assert model.apply_fn is orig_apply
    assert not getattr(model, "is_quantized", False)


# ---------------------------------------------------------------------------
# 4-bit (nf4 / int4) — reference utils/bnb.py:44 load_in_4bit path,
# config fields dataclasses.py:2365-2440
# ---------------------------------------------------------------------------


def test_4bit_roundtrip_error_bounded():
    from accelerate_tpu.utils.quantization import (
        dequantize_array_4bit,
        quantize_array_4bit,
    )

    rng = np.random.default_rng(0)
    w = rng.normal(size=(128, 64)).astype(np.float32)
    qt = quantize_array_4bit(w, block_size=64, quant_type="nf4")
    assert qt.packed.dtype == np.uint8
    assert qt.packed.shape == (128, 32)
    assert qt.shape == (128, 64)
    assert qt.block_size == 64
    back = np.asarray(dequantize_array_4bit(qt))
    assert back.shape == w.shape
    # nf4's worst-case step near ±1 is ~0.28 of absmax; double-quantized
    # scales add a small extra term — bound the error loosely but firmly
    err = np.abs(back - w)
    # blocks run along the contraction (first) dim: [nb=2, 64, 64]
    per_block_absmax = np.abs(w.reshape(2, 64, 64)).max(axis=1)  # [2, 64]
    assert np.max(err / np.repeat(per_block_absmax, 64, axis=0).reshape(w.shape)) < 0.2
    # 4-bit must be materially closer than sign-only, and strictly lossy
    assert 0 < np.mean(err) < 0.1 * np.abs(w).mean()


def test_4bit_storage_is_half_of_int8():
    from accelerate_tpu.utils.quantization import quantize_array, quantize_array_4bit

    w = np.random.default_rng(1).normal(size=(256, 256)).astype(np.float32)
    q8 = quantize_array(w)
    q4 = quantize_array_4bit(w)
    bytes8 = q8.q.nbytes + np.asarray(q8.scale).nbytes
    bytes4 = (
        q4.packed.nbytes + q4.scale_q.nbytes
        + np.asarray(q4.scale_offset).nbytes + np.asarray(q4.scale_scale).nbytes
        + np.asarray(q4.code).nbytes
    )
    assert bytes4 < 0.6 * bytes8  # ≈ 0.53 bytes/param vs 1.03


def test_4bit_model_forward_close_to_fp32():
    from accelerate_tpu.utils.quantization import Q4Tensor

    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=2, heads=4, seq=32)
    model = LlamaForCausalLM.from_config(cfg, seed=0)
    ids = np.random.default_rng(2).integers(0, 128, size=(2, 16)).astype(np.int32)
    ref = np.asarray(model.apply_fn(model.params, input_ids=ids)["logits"])

    q = quantize_model_params(
        LlamaForCausalLM.from_config(cfg, seed=0),
        BnbQuantizationConfig(load_in_4bit=True, bnb_4bit_quant_type="nf4"),
    )
    leaves = jax.tree.leaves(
        q.params, is_leaf=lambda l: isinstance(l, Q4Tensor)
    )
    assert any(isinstance(l, Q4Tensor) for l in leaves)
    out = np.asarray(q.apply_fn(q.params, input_ids=ids)["logits"])
    # a tiny random model has near-uniform logits, so argmax agreement is
    # noise; require the quantized logits to track the fp32 ones closely
    corr = np.corrcoef(out.ravel(), ref.ravel())[0, 1]
    assert corr > 0.9
    assert np.abs(out - ref).mean() < 0.5 * np.abs(ref).mean()


def test_4bit_generation_parity_within_tolerance():
    from accelerate_tpu.generation import generate

    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=2, heads=4, seq=64)
    model = LlamaForCausalLM.from_config(cfg, seed=0)

    def wrap(m):
        return lambda **kw: m.apply_fn(m.params, **kw)

    ids = np.random.default_rng(3).integers(0, 128, size=(2, 8)).astype(np.int32)
    ref = np.asarray(generate(wrap(model), ids, max_new_tokens=8))

    q = quantize_model_params(
        LlamaForCausalLM.from_config(cfg, seed=0),
        BnbQuantizationConfig(load_in_4bit=True),
    )
    out = np.asarray(generate(wrap(q), ids, max_new_tokens=8))
    # the prompt region is identical and a majority of greedy decode steps
    # survive quantization even on a noise-dominated tiny model
    assert out.shape == ref.shape
    assert (out[:, :8] == ref[:, :8]).all()
    assert (out == ref).mean() > 0.5


def test_4bit_streaming_offload_matches_resident(tmp_path):
    """The streamed path computes 4-bit matmuls as per-slab int8 GEMMs
    (``q4_matmul``: int8-rounded codebook + slab-quantized activations),
    so it matches the resident exact-dequant path approximately — both
    rounding terms are well inside nf4's own quantization error."""
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=2, heads=4, seq=32)
    q = quantize_model_params(
        LlamaForCausalLM.from_config(cfg, seed=0),
        BnbQuantizationConfig(load_in_4bit=True),
    )
    ids = np.random.default_rng(4).integers(0, 128, size=(2, 16)).astype(np.int32)
    resident = np.asarray(q.apply_fn(q.params, input_ids=ids)["logits"])

    offloaded = cpu_offload(q)
    out = np.asarray(offloaded(input_ids=ids)["logits"])
    # ~0.4% codebook rounding + ~0.4% activation rounding per matmul,
    # accumulated over 2 layers + head on a noise-dominated tiny model
    rel = np.max(np.abs(out - resident)) / max(np.abs(resident).max(), 1e-6)
    assert rel < 0.06, f"streamed q4 GEMM drifted {rel:.4f} from exact dequant"
    agree = np.mean(np.argmax(out, -1) == np.argmax(resident, -1))
    assert agree > 0.9, f"argmax agreement {agree:.3f}"


def test_4bit_streaming_without_native_decoder(monkeypatch):
    """Hosts where the native pshufb decoder cannot build (no compiler /
    non-x86 scalar build failure) must stream 4-bit models through the
    in-jit Q4Tensor path with the same results."""
    import accelerate_tpu.native as native

    monkeypatch.setattr(native, "q4_decode_codes", lambda *a, **k: None)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=2, heads=4, seq=32)
    q = quantize_model_params(
        LlamaForCausalLM.from_config(cfg, seed=0),
        BnbQuantizationConfig(load_in_4bit=True),
    )
    ids = np.random.default_rng(4).integers(0, 128, size=(2, 16)).astype(np.int32)
    resident = np.asarray(q.apply_fn(q.params, input_ids=ids)["logits"])
    out = np.asarray(cpu_offload(q)(input_ids=ids)["logits"])
    rel = np.max(np.abs(out - resident)) / max(np.abs(resident).max(), 1e-6)
    assert rel < 0.06, f"no-native streaming drifted {rel:.4f}"


def test_4bit_quarters_device_map_accounting():
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=2, heads=4, seq=32)
    fp32 = LlamaForCausalLM.from_config(cfg, seed=0)

    def total_bytes(m):
        from accelerate_tpu.utils.modeling import dtype_byte_size

        return sum(
            int(np.prod(shape)) * dtype_byte_size(dtype)
            for shape, dtype in flat_param_shapes(m).values()
        )

    base = total_bytes(fp32)
    q4 = quantize_model_params(
        LlamaForCausalLM.from_config(cfg, seed=0),
        BnbQuantizationConfig(load_in_4bit=True),
    )
    # embeddings/head stay fp32; the layer stack drops to ~1/8 of fp32
    assert total_bytes(q4) < 0.75 * base


def test_4bit_config_validation():
    with pytest.raises(ValueError, match="nf4"):
        BnbQuantizationConfig(load_in_4bit=True, bnb_4bit_quant_type="int3")
    c = BnbQuantizationConfig(load_in_4bit=True, bnb_4bit_compute_dtype="torch.bfloat16")
    assert not c.load_in_8bit
    assert c.compute_dtype == jnp.bfloat16
