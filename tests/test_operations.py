"""Collectives vs closed-form expectations (reference analog:
``test_utils/scripts/test_ops.py`` — gather/broadcast/pad/reduce checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu import operations as ops
from accelerate_tpu.mesh import data_sharding
from accelerate_tpu.state import PartialState


def _sharded_arange(state, n=16, width=2):
    x = jnp.arange(n * width, dtype=jnp.float32).reshape(n, width)
    return jax.device_put(x, data_sharding(state.mesh))


def test_gather_returns_global_view():
    state = PartialState()
    x = _sharded_arange(state)
    g = ops.gather(x)
    np.testing.assert_array_equal(np.asarray(g), np.arange(32, dtype=np.float32).reshape(16, 2))


def test_gather_pytree():
    state = PartialState()
    tree = {"a": _sharded_arange(state), "b": [jnp.ones((8,)), "keep"]}
    g = ops.gather(tree)
    assert g["b"][1] == "keep"
    assert np.asarray(g["a"]).shape == (16, 2)


def test_gather_object_single_process():
    assert ops.gather_object([1, "x"]) == [1, "x"]


def test_broadcast_identity_single_process():
    x = jnp.arange(4.0)
    np.testing.assert_array_equal(np.asarray(ops.broadcast(x)), np.arange(4.0))


def test_reduce_sum_over_shards():
    """A batch-sharded [16,2] over 8 dp shards reduces to [2,2]: the sum of
    the 8 per-shard tensors (the per-rank tensors of the torch contract)."""
    state = PartialState()
    x = _sharded_arange(state)  # [16, 2] split into 8 shards of [2, 2]
    out = ops.reduce(x, reduction="sum", scale=2.0)
    expected = np.asarray(x).reshape(8, 2, 2).sum(axis=0) * 2.0
    np.testing.assert_allclose(np.asarray(out), expected)
    mean_out = ops.reduce(x, reduction="mean")
    np.testing.assert_allclose(np.asarray(mean_out), expected / 16.0)


def test_reduce_replicated_identity():
    x = jnp.arange(6.0).reshape(3, 2)  # host value, single process
    out = ops.reduce(x, reduction="sum")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_pad_across_processes_noop_single():
    x = jnp.ones((3, 5))
    out = ops.pad_across_processes(x, dim=1)
    assert np.asarray(out).shape == (3, 5)


def test_pad_input_tensors():
    batch = {"x": np.arange(10).reshape(5, 2), "n": 5}
    out = ops.pad_input_tensors(batch, batch_size=5, num_processes=4, dim=0)
    assert out["x"].shape == (8, 2)
    np.testing.assert_array_equal(out["x"][5], out["x"][4])
    np.testing.assert_array_equal(out["x"][7], out["x"][4])


def test_concatenate_nested():
    a = {"t": jnp.ones((2, 3))}
    b = {"t": jnp.zeros((4, 3))}
    out = ops.concatenate([a, b])
    assert out["t"].shape == (6, 3)


def test_convert_to_fp32():
    tree = {"a": jnp.ones((2,), dtype=jnp.bfloat16), "b": jnp.ones((2,), dtype=jnp.int32)}
    out = ops.convert_to_fp32(tree)
    assert out["a"].dtype == jnp.float32
    assert out["b"].dtype == jnp.int32


def test_listify_and_structure():
    tree = {"a": jnp.arange(3)}
    assert ops.listify(tree) == {"a": [0, 1, 2]}
    s = ops.get_data_structure(tree)
    assert s["a"].shape == (3,)


def test_send_to_device_sharding():
    state = PartialState()
    sharding = data_sharding(state.mesh)
    x = np.ones((16, 4), dtype=np.float32)
    y = ops.send_to_device({"x": x}, sharding)["x"]
    assert isinstance(y, jax.Array)
    assert y.sharding == sharding


def test_jops_psum_inside_shard_map():
    state = PartialState()
    mesh = state.mesh
    from accelerate_tpu.utils.compat import shard_map

    x = jax.device_put(
        jnp.arange(8.0).reshape(8, 1), NamedSharding(mesh, P(("dp",), None))
    )

    def body(x):
        return ops.jops.psum(x, "dp")

    out = shard_map(
        body, mesh=mesh, in_specs=P(("dp",), None), out_specs=P(("dp",), None)
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))


def test_jops_ring_shift():
    state = PartialState()
    mesh = state.mesh
    from accelerate_tpu.utils.compat import shard_map

    x = jax.device_put(jnp.arange(8.0).reshape(8, 1), NamedSharding(mesh, P(("dp",), None)))

    def body(x):
        return ops.jops.ring_shift(x, "dp", shift=1)

    out = shard_map(body, mesh=mesh, in_specs=P(("dp",), None), out_specs=P(("dp",), None))(x)
    # shard i receives shard i-1's value: [7, 0, 1, ..., 6]
    np.testing.assert_allclose(np.asarray(out).ravel(), np.r_[7.0, np.arange(7.0)])


def test_copy_tensor_to_devices_replicates():
    state = PartialState()
    x = jnp.arange(4.0)
    y = ops.copy_tensor_to_devices(x)
    assert y.sharding.is_fully_replicated


def test_find_batch_size_and_device():
    x = jnp.ones((5, 2))
    assert ops.find_batch_size({"a": [x], "b": 3}) == 5
    assert ops.find_device({"a": x}) is not None
