"""Radix prefix-sharing cache + priority scheduler + swap preemption
(``accelerate_tpu/serving/radix.py`` and friends).

Host-side invariant tests (refcounts, trie matching/eviction, priority
admission, victim ordering, swap-pool accounting) run in the tier-1 lane —
pure Python, no compiles. Engine end-to-end proofs (prefix-hit logit
parity, swap round-trip parity, priority preemption, pool pressure
completing un-truncated) compile the tiny model and ride the slow lane
like the rest of the serving suite.
"""

import numpy as np
import pytest

from accelerate_tpu.serving import (
    BlockAllocator,
    EngineConfig,
    InferenceEngine,
    RadixCache,
    Request,
    RequestState,
    SlotScheduler,
    SwapPool,
    blocks_needed,
)

# ---------------------------------------------------------------------------
# refcounted allocator (tier-1)
# ---------------------------------------------------------------------------


def test_incref_decref_round_trip():
    alloc = BlockAllocator(num_blocks=5)
    blocks = alloc.allocate(2)
    alloc.incref(blocks)  # second holder
    assert all(alloc.refcount(b) == 2 for b in blocks)
    assert alloc.decref(blocks) == []  # still held
    assert alloc.free_count == 2
    assert alloc.decref(blocks) == blocks  # last holder -> freelist
    assert alloc.free_count == 4 and alloc.allocated_count == 0


def test_free_shared_block_raises():
    """Hard-freeing a block another holder still reads must raise — the
    CoW/sharing invariant the whole cache leans on."""
    alloc = BlockAllocator(num_blocks=5)
    blocks = alloc.allocate(1)
    alloc.incref(blocks)
    with pytest.raises(ValueError, match="shared"):
        alloc.free(blocks)
    alloc.decref(blocks)
    alloc.free(blocks)  # sole holder again: strict free works


def test_decref_double_release_raises():
    alloc = BlockAllocator(num_blocks=5)
    blocks = alloc.allocate(1)
    alloc.decref(blocks)
    with pytest.raises(ValueError, match="double free"):
        alloc.decref(blocks)
    with pytest.raises(ValueError, match="null block"):
        alloc.decref([0])


# ---------------------------------------------------------------------------
# radix trie (tier-1)
# ---------------------------------------------------------------------------


def _cache(num_blocks=17, block_size=4):
    alloc = BlockAllocator(num_blocks)
    return RadixCache(alloc, block_size), alloc


def _insert_prompt(cache, alloc, tokens):
    """Simulate a finished request: allocate its blocks, adopt the full
    ones into the trie, then drop the request's own references."""
    n = max(blocks_needed(len(tokens) + 1, cache.block_size), 1)
    blocks = alloc.allocate(n)
    cache.insert(tokens, blocks)
    alloc.decref(blocks)
    return blocks


def test_match_full_blocks_and_cap():
    cache, alloc = _cache()
    _insert_prompt(cache, alloc, list(range(12)))  # 3 full blocks cached
    # identical prompt: the cap leaves the final token to prefill — two
    # full blocks match outright and the third contributes 3 of its 4
    # tokens through the CoW path (11 of 12, never all 12)
    blocks, matched, cow = cache.match(list(range(12)))
    assert matched == 11 and len(blocks) == 2 and cow is not None
    # longer prompt with the same prefix: all 3 full blocks match
    blocks, matched, cow = cache.match(list(range(12)) + [99, 98])
    assert matched == 12 and len(blocks) == 3 and cow is None
    # divergent first block: no match
    assert cache.match([7, 1, 2, 3, 4])[1] == 0


def test_partial_block_match_returns_cow_source():
    cache, alloc = _cache()
    _insert_prompt(cache, alloc, list(range(8)))  # blocks (0-3), (4-7)
    # agree through token 5, diverge at 6: one full block + 2 partial
    prompt = [0, 1, 2, 3, 4, 5, 77, 78, 79]
    blocks, matched, cow = cache.match(prompt)
    assert len(blocks) == 1 and matched == 6
    assert cow is not None  # the (4,5,6,7) node's block, to be copied
    # acquire pins both the matched block and the CoW source
    shared, m, cow2 = cache.acquire(prompt)
    assert m == 6 and alloc.refcount(shared[0]) == 2 and alloc.refcount(cow2) == 2
    cache.release_acquired(shared, cow2)
    assert alloc.refcount(shared[0]) == 1 and alloc.refcount(cow2) == 1


def test_lru_eviction_leaves_first_and_skips_shared():
    cache, alloc = _cache(num_blocks=9, block_size=4)
    _insert_prompt(cache, alloc, list(range(8)))       # chain A: a0 -> a1
    _insert_prompt(cache, alloc, [50, 51, 52, 53])     # leaf B (younger)
    assert cache.cached_block_count == 3
    # touch chain A so B becomes the LRU leaf
    cache.release_acquired(*cache.acquire(list(range(8)) + [99])[::2])
    # a live request holds B's block: eviction must skip it
    b_node = cache.root.children[(50, 51, 52, 53)]
    alloc.incref([b_node.block])
    assert cache.evict(10) == 2  # a1 then a0 (leaf-first), B protected
    assert cache.cached_block_count == 1
    alloc.decref([b_node.block])
    assert cache.evict(1) == 1 and cache.cached_block_count == 0
    assert alloc.allocated_count == 0  # everything back on the freelist


def test_insert_keeps_existing_nodes():
    cache, alloc = _cache()
    first = _insert_prompt(cache, alloc, list(range(8)))
    # a second request with the same prompt prefilled its own duplicate
    # blocks: the cache keeps the original nodes, the duplicates stay out
    dup = alloc.allocate(2)
    assert cache.insert(list(range(8)), dup) == 0
    node = cache.root.children[(0, 1, 2, 3)]
    assert node.block == first[0]
    assert alloc.refcount(dup[0]) == 1  # not adopted
    alloc.free(dup)


# ---------------------------------------------------------------------------
# priority scheduler (tier-1)
# ---------------------------------------------------------------------------


def _sched(num_slots=2, num_blocks=9, block_size=8, max_seq=32, radix=False):
    alloc = BlockAllocator(num_blocks)
    cache = RadixCache(alloc, block_size) if radix else None
    return SlotScheduler(num_slots, alloc, block_size, max_seq, radix=cache)


def test_priority_admission_order():
    """Interactive requests admit before earlier-arrived batch ones; FCFS
    holds within a class."""
    sched = _sched(num_slots=3)
    b1 = sched.submit(Request(prompt=[1] * 4, max_new_tokens=4, priority="batch"))
    b2 = sched.submit(Request(prompt=[2] * 4, max_new_tokens=4, priority="batch"))
    i1 = sched.submit(Request(prompt=[3] * 4, max_new_tokens=4, priority="interactive"))
    admitted = sched.admit()
    assert [r.request_id for r in admitted] == [r.request_id for r in (i1, b1, b2)]


def test_submit_rejects_unknown_priority():
    sched = _sched()
    with pytest.raises(ValueError, match="priority"):
        sched.submit(Request(prompt=[1] * 4, max_new_tokens=4, priority="urgent"))


def test_pick_victim_lowest_class_latest_arrival():
    sched = _sched(num_slots=3, num_blocks=17)
    i1 = sched.submit(Request(prompt=[1] * 4, max_new_tokens=4, priority="interactive"))
    b1 = sched.submit(Request(prompt=[2] * 4, max_new_tokens=4, priority="batch"))
    b2 = sched.submit(Request(prompt=[3] * 4, max_new_tokens=4, priority="batch"))
    b1.arrival_time, b2.arrival_time = 1.0, 2.0
    sched.admit()
    assert sched.pick_victim() is b2  # batch before interactive, youngest first
    b2.state = RequestState.FINISHED
    sched.evict_finished()
    assert sched.pick_victim() is b1
    b1.state = RequestState.FINISHED
    sched.evict_finished()
    assert sched.pick_victim() is i1  # interactive only as a last resort


def test_requeue_preempted_goes_to_class_front():
    sched = _sched(num_slots=1)
    b1 = sched.submit(Request(prompt=[1] * 4, max_new_tokens=4, priority="batch"))
    sched.submit(Request(prompt=[2] * 4, max_new_tokens=4, priority="batch"))
    sched.admit()
    sched.requeue_preempted(b1)
    assert b1.preempted and b1.slot is None and sched.peek_head() is b1
    assert sched.waiting["batch"][0] is b1  # ahead of the never-run b2


def test_prefix_aware_admission_maps_shared_blocks():
    """Admission with a warm radix cache: the shared prefix arrives as
    refcount-2 blocks, prefill_pos skips the matched tokens, and only the
    tail is freshly allocated."""
    sched = _sched(num_slots=2, num_blocks=17, block_size=8, max_seq=64, radix=True)
    warm = sched.submit(Request(prompt=list(range(24)), max_new_tokens=4))
    (req,) = sched.admit()
    assert req is warm and req.matched_tokens == 0
    sched.radix.insert(req.prompt, req.blocks)
    req.state = RequestState.FINISHED
    sched.evict_finished()

    r2 = sched.submit(Request(prompt=list(range(24)) + [99] * 4, max_new_tokens=4))
    (admitted,) = sched.admit()
    assert admitted is r2
    assert r2.matched_tokens == 24 and r2.prefill_pos == 24
    total = max(blocks_needed(r2.prompt_len + 1, 8), 1)
    assert len(r2.blocks) == total
    assert all(sched.allocator.refcount(b) == 2 for b in r2.blocks[:3])
    assert sched.prefix_hit_tokens == 24
    assert sched.prompt_tokens_admitted == 24 + 28


def test_grow_for_decode_evicts_cached_blocks():
    """A dry freelist with evictable cached blocks is not exhaustion:
    growth LRU-evicts refcount-1 cache blocks before giving up."""
    sched = _sched(num_slots=1, num_blocks=5, block_size=8, max_seq=64, radix=True)
    warm = sched.submit(Request(prompt=list(range(16)), max_new_tokens=4))
    (req,) = sched.admit()  # 3 blocks (17 positions)
    sched.radix.insert(req.prompt, req.blocks)
    req.state = RequestState.FINISHED
    sched.evict_finished()
    assert sched.allocator.free_count == 2  # 2 of 4 held by the cache

    r2 = sched.submit(Request(prompt=[99] * 16, max_new_tokens=24))
    (r2a,) = sched.admit()  # cold: takes the 2 free + evicts 1 cached
    assert r2a is r2 and len(r2.blocks) == 3
    r2.prefill_pos = 16
    r2.output_tokens = [1] * 9  # context 24: next write needs block 4
    assert sched.grow_for_decode(r2, tokens_ahead=1)  # evicts the last cached
    assert len(r2.blocks) == 4
    assert sched.radix.cached_block_count == 0
    assert not sched.grow_for_decode(r2, tokens_ahead=99)  # now truly full
    assert warm.blocks == []  # eviction never resurrected the old request


# ---------------------------------------------------------------------------
# swap pool (tier-1)
# ---------------------------------------------------------------------------


def test_swap_pool_round_trip_and_capacity():
    pool = SwapPool(num_layers=2, block_size=4, num_kv_heads=2, head_dim=8,
                    dtype=np.float32, capacity_gb=3 * 2 * 4 * (2 * 4 * 2 * 8) / (1 << 30))
    assert pool.capacity_blocks == 3
    rng = np.random.default_rng(0)
    k = rng.standard_normal((2, 4, 2, 8)).astype(np.float32)
    v = rng.standard_normal((2, 4, 2, 8)).astype(np.float32)
    h = pool.store(k, v)
    assert pool.used_blocks == 1 and pool.can_hold(2) and not pool.can_hold(3)
    k2, v2, ks2, vs2 = pool.load(h)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)
    assert ks2 is None and vs2 is None  # non-quantized pool carries no scales
    pool.release(h)
    assert pool.used_blocks == 0
    with pytest.raises(ValueError, match="double release"):
        pool.release(h)
    for _ in range(3):
        pool.store(k, v)
    with pytest.raises(RuntimeError, match="swap pool exhausted"):
        pool.store(k, v)


# ---------------------------------------------------------------------------
# engine end-to-end (slow lane: compiles the tiny model)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=2, heads=4, seq=96)
    return LlamaForCausalLM.from_config(config, seed=0)


GEOM = dict(num_slots=3, block_size=8, max_seq_len=64, prefill_chunk=8, decode_burst=2)


def _drain(engine):
    return engine.run_until_idle(max_iterations=5000)


@pytest.mark.slow
def test_prefix_hit_token_parity(tiny_model):
    """A warm-cache admission (full-block hits) produces token-identical
    greedy output to the no-sharing engine — the acceptance bar for
    sharing never changing results."""
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 64, size=24).astype(np.int32)
    p1 = np.concatenate([shared, rng.integers(0, 64, size=4).astype(np.int32)])
    p2 = np.concatenate([shared, rng.integers(0, 64, size=5).astype(np.int32)])

    eng = InferenceEngine(tiny_model, EngineConfig(**GEOM))
    eng.add_request(p1, 6)
    _drain(eng)
    r2 = eng.add_request(p2, 6)
    _drain(eng)
    stats = eng.stats()
    assert stats["prefix_hit_tokens"] == 24  # 3 full blocks of the prefix
    assert stats["prefix_hit_ratio"] > 0
    assert stats["decode_compiles"] == 1 and stats["prefill_compiles"] == 1

    cold = InferenceEngine(tiny_model, EngineConfig(prefix_cache=False, **GEOM))
    rc = cold.add_request(p2, 6)
    _drain(cold)
    assert r2.output_tokens == rc.output_tokens
    # idle-engine invariant: every remaining allocation is cache-held
    assert stats["allocated_blocks"] == 0
    assert stats["cached_blocks"] > 0
    assert stats["free_blocks"] + stats["cached_blocks"] == eng.allocator.num_blocks - 1


@pytest.mark.slow
def test_cow_partial_block_parity(tiny_model):
    """A prompt diverging mid-block reuses the common rows via the CoW
    copy and still matches the cold engine token-for-token."""
    rng = np.random.default_rng(8)
    p1 = rng.integers(0, 64, size=32).astype(np.int32)
    p2 = p1.copy()
    p2[20] = (p2[20] + 1) % 64  # diverge inside block 2 (tokens 16-23)

    eng = InferenceEngine(tiny_model, EngineConfig(**GEOM))
    eng.add_request(p1, 4)
    _drain(eng)
    r2 = eng.add_request(p2, 6)
    _drain(eng)
    stats = eng.stats()
    assert stats["prefix_hit_tokens"] == 20  # 2 full blocks + 4 CoW tokens
    assert stats["decode_compiles"] == 1

    cold = InferenceEngine(tiny_model, EngineConfig(prefix_cache=False, **GEOM))
    rc = cold.add_request(p2, 6)
    _drain(cold)
    assert r2.output_tokens == rc.output_tokens
    # the pinned CoW source was released: nothing but the cache holds refs
    assert stats["allocated_blocks"] == 0


@pytest.mark.slow
def test_swap_round_trip_parity_and_untruncated(tiny_model):
    """THE acceptance scenario: a pool too small for both requests, where
    the PR 4 engine answered out_of_blocks, now completes BOTH requests
    fully via swap preemption — token-identical to a full-residency run —
    while the no-swap engine still truncates (regression reference)."""
    geom = dict(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                prefix_cache=False)
    prompts = [np.arange(8, dtype=np.int32), np.arange(8, dtype=np.int32) + 1]

    def run(num_blocks=None, swap_gb=0.0):
        eng = InferenceEngine(
            tiny_model, EngineConfig(num_blocks=num_blocks, swap_gb=swap_gb, **geom)
        )
        reqs = [eng.add_request(p, max_new_tokens=30) for p in prompts]
        _drain(eng)
        return eng.stats(), reqs

    # 5 usable blocks: each request needs 5 alone (38 positions), so they
    # cannot both be resident — preemption or truncation must pick
    no_swap_stats, no_swap = run(num_blocks=6)
    assert any(r.finish_reason == "out_of_blocks" for r in no_swap)
    assert no_swap_stats["out_of_blocks_total"] >= 1

    swap_stats, swapped = run(num_blocks=6, swap_gb=0.01)
    assert [r.finish_reason for r in swapped] == ["length", "length"]
    assert all(len(r.output_tokens) == 30 for r in swapped)
    assert swap_stats["preemptions"] >= 1
    assert swap_stats["swapped_out_blocks"] == swap_stats["swapped_in_blocks"] > 0
    assert swap_stats["out_of_blocks_total"] == 0
    assert swap_stats["decode_compiles"] == 1
    assert swap_stats["swap_used_blocks"] == 0  # every handle came home
    assert swap_stats["allocated_blocks"] == 0

    full_stats, full = run()
    for s, f in zip(swapped, full):
        assert s.output_tokens == f.output_tokens

    # same pressure with the prefix cache ON (the default): a victim's
    # cache-shared blocks are swapped as well — retaining them under the
    # victim's ref would pin capacity and force the truncation swap exists
    # to prevent (regression: the cache-only-shared pinning bug)
    geom["prefix_cache"] = True
    cache_stats, cached = run(num_blocks=6, swap_gb=0.01)
    assert [r.finish_reason for r in cached] == ["length", "length"]
    assert cache_stats["out_of_blocks_total"] == 0
    assert cache_stats["decode_compiles"] == 1
    for s, f in zip(cached, full):
        assert s.output_tokens == f.output_tokens


@pytest.mark.slow
def test_priority_preemption_ordering(tiny_model):
    """An interactive arrival under pool pressure swaps out the youngest
    BATCH request — never another interactive one, never itself."""
    geom = dict(num_slots=2, block_size=8, max_seq_len=64, prefill_chunk=8,
                prefix_cache=False, num_blocks=8, swap_gb=0.01)
    eng = InferenceEngine(tiny_model, EngineConfig(**geom))
    b1 = eng.add_request(np.arange(8, dtype=np.int32), 20, priority="batch")
    b2 = eng.add_request(np.arange(8, dtype=np.int32) + 2, 20, priority="batch")
    for _ in range(4):
        eng.step()
    i1 = eng.add_request(np.arange(8, dtype=np.int32) + 5, 8, priority="interactive")
    _drain(eng)
    stats = eng.stats()
    assert stats["preemptions"] >= 1
    assert i1.preemptions == 0
    assert b1.preemptions + b2.preemptions == stats["preemptions"]
    assert all(r.finish_reason == "length" for r in (b1, b2, i1))
    assert stats["out_of_blocks_total"] == 0
    assert stats["decode_compiles"] == 1


@pytest.mark.slow
def test_serving_stats_carry_sharing_fields(tiny_model, tmp_path):
    """The new counters ride the telemetry step rows and the monitor's
    serving panel."""
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status
    from accelerate_tpu.telemetry import TelemetryRecorder, set_active_recorder

    recorder = TelemetryRecorder(logging_dir=str(tmp_path))
    set_active_recorder(recorder)
    try:
        eng = InferenceEngine(
            tiny_model, EngineConfig(stats_interval=2, swap_gb=0.01, **GEOM)
        )
        rng = np.random.default_rng(4)
        shared = rng.integers(0, 64, size=16).astype(np.int32)
        for i in range(3):
            eng.add_request(
                np.concatenate([shared, rng.integers(0, 64, size=2 + i).astype(np.int32)]),
                4,
            )
            _drain(eng)
    finally:
        set_active_recorder(None)
        recorder.close()

    steps = [
        r for r in recorder.records
        if r.get("type") == "serving" and r.get("kind") == "step"
    ]
    assert steps
    assert steps[-1]["prefix_hit_tokens"] > 0
    assert 0 < steps[-1]["prefix_hit_ratio"] < 1
    for field in ("preemptions", "swapped_out_blocks", "swapped_in_blocks",
                  "out_of_blocks_total"):
        assert field in steps[-1]

    status = collect_status(str(tmp_path))
    assert status["serving"]["prefix_hit_ratio"] > 0
    assert "prefix cache:" in render_status(status)
