"""T5 encoder-decoder family: forward/loss semantics, relative-position
bias, sharded training, streaming offload, pipeline inference, HF name
conversion (reference exposure: transformers T5 in
``examples/inference/pippy/t5.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax

from accelerate_tpu import Accelerator, MeshPlugin, prepare_pippy
from accelerate_tpu.big_modeling import cpu_offload
from accelerate_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
    compute_position_bias,
    convert_hf_t5_state_dict,
    relative_position_bucket,
    shift_right,
)

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _tiny(layers=2, **kw):
    config = T5Config.tiny(layers=layers, **kw)
    model = T5ForConditionalGeneration.from_config(config, seed=1)
    rng = np.random.default_rng(0)
    enc_ids = rng.integers(0, 256, size=(2, 24)).astype(np.int32)
    dec_ids = rng.integers(0, 256, size=(2, 12)).astype(np.int32)
    return config, model, enc_ids, dec_ids


def test_forward_shapes_and_loss():
    config, model, enc_ids, dec_ids = _tiny()
    out = model.apply_fn(model.params, input_ids=enc_ids, labels=dec_ids)
    assert out["logits"].shape == (2, 12, 256)  # decoder length, not encoder
    assert out["encoder_last_hidden_state"].shape == (2, 24, 64)
    loss = float(out["loss"])
    assert np.isfinite(loss)
    # random model ≈ uniform over vocab
    assert abs(loss - np.log(256)) < 1.0


def test_shift_right_contract():
    labels = jnp.asarray([[5, 6, 7, -100]], jnp.int32)
    shifted = shift_right(labels, decoder_start_token_id=0)
    np.testing.assert_array_equal(np.asarray(shifted), [[0, 5, 6, 7]])


def test_relative_position_bucket_semantics():
    rel = jnp.asarray([[-3, 0, 3]], jnp.int32)
    bi = relative_position_bucket(rel, True, 32, 128)
    uni = relative_position_bucket(rel, False, 32, 128)
    # bidirectional separates past/future into disjoint bucket halves
    assert int(bi[0, 0]) != int(bi[0, 2])
    # causal mode collapses future keys (rel>0 → n=-rel<0 → bucket 0)
    assert int(uni[0, 2]) == 0 and int(uni[0, 0]) > 0
    bias = compute_position_bias(jnp.ones((32, 4)), 8, 8, True, 32, 128)
    assert bias.shape == (1, 4, 8, 8)


def test_decoder_is_causal():
    """Perturbing a later decoder token must not change earlier logits."""
    config, model, enc_ids, dec_ids = _tiny()
    out1 = model.apply_fn(model.params, input_ids=enc_ids, decoder_input_ids=dec_ids)
    dec2 = dec_ids.copy()
    dec2[:, -1] = (dec2[:, -1] + 1) % 256
    out2 = model.apply_fn(model.params, input_ids=enc_ids, decoder_input_ids=dec2)
    np.testing.assert_allclose(
        np.asarray(out1.logits[:, :-1]), np.asarray(out2.logits[:, :-1]),
        rtol=1e-5, atol=1e-5,
    )
    # ...while the encoder is bidirectional: perturbing ANY encoder token
    # changes all decoder logits
    enc2 = enc_ids.copy()
    enc2[:, 0] = (enc2[:, 0] + 1) % 256
    out3 = model.apply_fn(model.params, input_ids=enc2, decoder_input_ids=dec_ids)
    assert np.abs(np.asarray(out3.logits) - np.asarray(out1.logits)).max() > 1e-6


def test_encoder_mask_blocks_padding():
    config, model, enc_ids, dec_ids = _tiny()
    mask = np.ones_like(enc_ids)
    mask[:, -8:] = 0
    out_masked = model.apply_fn(
        model.params, input_ids=enc_ids, attention_mask=mask, decoder_input_ids=dec_ids
    )
    enc2 = enc_ids.copy()
    enc2[:, -8:] = 17  # garbage in the masked region must not matter
    out_masked2 = model.apply_fn(
        model.params, input_ids=enc2, attention_mask=mask, decoder_input_ids=dec_ids
    )
    np.testing.assert_allclose(
        np.asarray(out_masked.logits), np.asarray(out_masked2.logits),
        rtol=1e-5, atol=1e-5,
    )


def test_training_on_sharded_mesh():
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    config = T5Config.tiny(layers=2)
    model, opt = accelerator.prepare(
        T5ForConditionalGeneration.from_config(config, seed=0), optax.adamw(1e-2)
    )
    rng = np.random.default_rng(0)
    enc_ids = rng.integers(0, 256, size=(8, 16)).astype(np.int32)
    labels = rng.integers(0, 256, size=(8, 8)).astype(np.int32)
    losses = []
    for _ in range(5):
        out = model(input_ids=enc_ids, labels=labels)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]


def test_sharded_matches_replicated():
    config, model, enc_ids, dec_ids = _tiny()
    loss_plain = float(
        model.apply_fn(model.params, input_ids=enc_ids, labels=dec_ids)["loss"]
    )
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    prepared, _ = accelerator.prepare(model, optax.sgd(0.0))
    out = prepared(input_ids=enc_ids, labels=dec_ids)
    assert abs(float(out.loss) - loss_plain) < 1e-4


def test_streaming_offload_matches_resident():
    config, model, enc_ids, dec_ids = _tiny()
    ref = model.apply_fn(
        model.params, input_ids=enc_ids, decoder_input_ids=dec_ids
    )["logits"]
    out = cpu_offload(model)(input_ids=enc_ids, decoder_input_ids=dec_ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_inference_matches():
    config, model, enc_ids, dec_ids = _tiny(layers=2)
    ref = model.apply_fn(
        model.params, input_ids=enc_ids, decoder_input_ids=dec_ids
    )["logits"]
    pipelined = prepare_pippy(
        model,
        example_kwargs={"input_ids": enc_ids, "decoder_input_ids": dec_ids},
        devices=jax.devices()[:2],
    )
    out = pipelined(input_ids=enc_ids, decoder_input_ids=dec_ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_gated_gelu_variant_runs():
    config, model, enc_ids, dec_ids = _tiny(layers=1)
    c2 = T5Config.tiny(layers=1)
    c2.feed_forward_proj = "gated-gelu"
    c2.tie_word_embeddings = False
    m2 = T5ForConditionalGeneration.from_config(c2, seed=0)
    assert "lm_head" in m2.params
    assert "wi_0" in m2.params["encoder"]["layers"]
    out = m2.apply_fn(m2.params, input_ids=enc_ids, labels=dec_ids)
    assert np.isfinite(float(out["loss"]))


def test_hf_name_conversion_roundtrip():
    config, model, enc_ids, dec_ids = _tiny()
    p = jax.tree.map(np.asarray, model.params)
    hf = {"shared.weight": p["shared"]}
    for side in ("encoder", "decoder"):
        L = config.num_layers if side == "encoder" else config.num_decoder_layers
        lp = p[side]["layers"]
        hf[f"{side}.block.0.layer.0.SelfAttention.relative_attention_bias.weight"] = (
            p[side]["rel_bias"]
        )
        hf[f"{side}.final_layer_norm.weight"] = p[side]["final_norm"]
        ffn_idx = 1 if side == "encoder" else 2
        for i in range(L):
            hf[f"{side}.block.{i}.layer.0.layer_norm.weight"] = lp["attn_norm"][i]
            for n in "qkvo":
                hf[f"{side}.block.{i}.layer.0.SelfAttention.{n}.weight"] = lp[f"w{n}"][i].T
            if side == "decoder":
                hf[f"{side}.block.{i}.layer.1.layer_norm.weight"] = lp["cross_norm"][i]
                for n in "qkvo":
                    hf[f"{side}.block.{i}.layer.1.EncDecAttention.{n}.weight"] = (
                        lp[f"c{n}"][i].T
                    )
            hf[f"{side}.block.{i}.layer.{ffn_idx}.layer_norm.weight"] = lp["ffn_norm"][i]
            hf[f"{side}.block.{i}.layer.{ffn_idx}.DenseReluDense.wi.weight"] = lp["wi"][i].T
            hf[f"{side}.block.{i}.layer.{ffn_idx}.DenseReluDense.wo.weight"] = (
                lp["wo_ffn"][i].T
            )

    converted = convert_hf_t5_state_dict(hf, config)
    flat_a = jax.tree_util.tree_flatten_with_path(converted)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(p)[0]
    assert [k for k, _ in flat_a] == [k for k, _ in flat_b]
    for (ka, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(ka))


def test_zoo_and_factories_have_t5():
    from accelerate_tpu.models import MODEL_ZOO, model_factory_for_config

    assert "t5-small" in MODEL_ZOO and "t5-11b" in MODEL_ZOO
    assert model_factory_for_config(T5Config.tiny()) is not None


def test_seq2seq_generation_greedy_chain():
    """generate() routes encoder-decoder models through the seq2seq loop:
    tokens append to decoder_input_ids from decoder_start_token_id, and
    each greedy token is the argmax of the re-forwarded logits."""
    from accelerate_tpu.generation import generate

    config, model, enc_ids, _ = _tiny()
    assert model.is_encoder_decoder
    out = np.asarray(generate(model, enc_ids, max_new_tokens=5))
    assert out.shape == (2, 6)
    assert (out[:, 0] == config.decoder_start_token_id).all()
    logits = np.asarray(
        model.apply_fn(model.params, input_ids=enc_ids, decoder_input_ids=out).logits
    )
    for t in range(5):
        np.testing.assert_array_equal(logits[:, t, :].argmax(-1), out[:, t + 1])


def test_seq2seq_generation_respects_eos_and_sampling():
    from accelerate_tpu.generation import generate

    config, model, enc_ids, _ = _tiny()
    greedy = np.asarray(generate(model, enc_ids, max_new_tokens=4))
    eos = int(greedy[0, 1])  # first generated token → instant finish
    halted = np.asarray(generate(model, enc_ids, max_new_tokens=4, eos_token_id=eos))
    assert (halted[0, 1:] == eos).all()  # finished rows pad with eos
    sampled = np.asarray(
        generate(model, enc_ids, max_new_tokens=4, do_sample=True, temperature=5.0, seed=3)
    )
    assert sampled.shape == greedy.shape


def test_seq2seq_generation_on_prepared_and_dispatched_models():
    """The encoder-decoder flag lives on the raw Model; generation must
    still route wrapper models (prepared, cpu-offloaded) through the
    seq2seq loop instead of crashing in the decoder-only path."""
    from accelerate_tpu.generation import generate

    config, model, enc_ids, _ = _tiny()
    ref = np.asarray(generate(model, enc_ids, max_new_tokens=3))

    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=8))
    prepared, _ = accelerator.prepare(model, optax.sgd(0.0))
    out_p = np.asarray(generate(prepared, enc_ids, max_new_tokens=3))
    np.testing.assert_array_equal(out_p, ref)

    dispatched = cpu_offload(T5ForConditionalGeneration.from_config(config, seed=1))
    out_d = np.asarray(generate(dispatched, enc_ids, max_new_tokens=3))
    np.testing.assert_array_equal(out_d, ref)
