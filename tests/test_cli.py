"""CLI round-trips (reference ``tests/test_cli.py``): config save/load,
env report, launch of a real script on a virtual CPU mesh, estimate-memory,
merge-weights, tpu-config command construction."""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

from accelerate_tpu.commands.accelerate_cli import main as cli_main
from accelerate_tpu.commands.config import ClusterConfig, write_basic_config

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestConfig:
    def test_roundtrip_yaml(self, tmp_path):
        cfg = ClusterConfig(mesh_fsdp=4, mixed_precision="bf16", use_fsdp=True)
        path = cfg.save(str(tmp_path / "cfg.yaml"))
        loaded = ClusterConfig.load(path)
        assert loaded.mesh_fsdp == 4
        assert loaded.use_fsdp is True
        assert loaded.mixed_precision == "bf16"

    def test_roundtrip_json(self, tmp_path):
        cfg = ClusterConfig(mesh_tp=2, context_parallel_mode="ulysses")
        path = cfg.save(str(tmp_path / "cfg.json"))
        loaded = ClusterConfig.load(path)
        assert loaded.mesh_tp == 2
        assert loaded.context_parallel_mode == "ulysses"

    def test_write_basic_config(self, tmp_path):
        path = write_basic_config(save_location=str(tmp_path / "default.yaml"))
        assert os.path.exists(path)

    def test_to_environment_contract(self):
        cfg = ClusterConfig(
            mesh_fsdp=8, mixed_precision="bf16", gradient_accumulation_steps=4,
            use_fsdp=True, context_parallel_mode="ring", debug=True,
            num_machines=2, machine_rank=1, coordinator_address="10.0.0.1:8476",
        )
        env = cfg.to_environment()
        assert env["ACCELERATE_MESH_FSDP"] == "8"
        assert env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] == "4"
        assert env["ACCELERATE_USE_FSDP"] == "true"
        assert env["ACCELERATE_CP_MODE"] == "ring"
        assert env["ACCELERATE_DEBUG_MODE"] == "true"
        assert env["ACCELERATE_COORDINATOR_ADDR"] == "10.0.0.1:8476"
        assert env["ACCELERATE_PROCESS_ID"] == "1"


class TestEnvCommand:
    def test_env_runs(self, capsys):
        assert cli_main(["env"]) == 0
        out = capsys.readouterr().out
        assert "jax version" in out
        assert "Device count" in out


class TestEstimate:
    def test_zoo_model(self, capsys):
        assert cli_main(["estimate-memory", "tiny-llama"]) == 0
        out = capsys.readouterr().out
        assert "float32" in out and "int4" in out

    def test_llama7b_shapes_without_memory(self, capsys):
        # 7B params materialised would OOM the test runner; meta-shapes don't
        assert cli_main(["estimate-memory", "llama2-7b", "--dtypes", "bfloat16"]) == 0
        out = capsys.readouterr().out
        assert "6.7" in out or "6.6" in out  # ~6.7B params

    def test_hf_config_json(self, tmp_path, capsys):
        cfg = {
            "model_type": "llama", "vocab_size": 128, "hidden_size": 32,
            "intermediate_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 4,
        }
        p = tmp_path / "config.json"
        p.write_text(json.dumps(cfg))
        assert cli_main(["estimate-memory", str(p)]) == 0

    def test_hf_config_json_all_model_types(self, tmp_path, capsys):
        """Every zoo family reachable from an HF config.json by model_type
        (the reference's 'point estimate at any checkpoint' UX)."""
        cases = {
            "gpt2": {"n_embd": 32, "n_layer": 2, "n_head": 4, "vocab_size": 128},
            "bert": {"hidden_size": 32, "num_hidden_layers": 2,
                     "num_attention_heads": 4, "intermediate_size": 64,
                     "vocab_size": 128},
            "vit": {"hidden_size": 32, "num_hidden_layers": 2,
                    "num_attention_heads": 4, "intermediate_size": 64},
            "opt": {"hidden_size": 32, "ffn_dim": 64, "num_hidden_layers": 2,
                    "num_attention_heads": 4, "vocab_size": 128},
            "gpt_neox": {"hidden_size": 32, "intermediate_size": 64,
                         "num_hidden_layers": 2, "num_attention_heads": 4,
                         "vocab_size": 128},
            "gptj": {"n_embd": 32, "n_inner": 64, "n_layer": 2, "n_head": 4,
                     "rotary_dim": 4, "vocab_size": 128},
            "t5": {"d_model": 32, "d_kv": 8, "d_ff": 64, "num_layers": 2,
                   "num_heads": 4, "vocab_size": 128},
            "mixtral": {"hidden_size": 32, "intermediate_size": 64,
                        "num_hidden_layers": 2, "num_attention_heads": 4,
                        "num_key_value_heads": 4, "num_local_experts": 2,
                        "num_experts_per_tok": 1, "vocab_size": 128},
        }
        for mt, fields in cases.items():
            p = tmp_path / f"{mt}.json"
            p.write_text(json.dumps({"model_type": mt, **fields}))
            assert cli_main(["estimate-memory", str(p)]) == 0, mt
        capsys.readouterr()


class TestMerge:
    def test_merge_sharded(self, tmp_path, capsys):
        from accelerate_tpu.checkpointing import load_array_dict, save_array_dict

        src = tmp_path / "ckpt"
        src.mkdir()
        a = {"w1": np.ones((4, 4), np.float32)}
        b = {"w2": np.zeros((2, 2), np.float32)}
        f1 = save_array_dict(a, str(src / "model-00001-of-00002"))
        f2 = save_array_dict(b, str(src / "model-00002-of-00002"))
        index = {
            "weight_map": {"w1": os.path.basename(f1), "w2": os.path.basename(f2)}
        }
        (src / "model.safetensors.index.json").write_text(json.dumps(index))
        out = tmp_path / "merged"
        assert cli_main(["merge-weights", str(src), str(out)]) == 0
        merged = load_array_dict(str(out / "model.safetensors"))
        assert set(merged) == {"w1", "w2"}
        np.testing.assert_allclose(merged["w1"], a["w1"])


class TestTpuConfig:
    def test_debug_prints_gcloud(self, capsys):
        rc = cli_main([
            "tpu-config", "--debug", "--tpu_name", "pod1", "--tpu_zone",
            "us-central2-b", "--command", "echo hi",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "gcloud compute tpus tpu-vm ssh pod1" in out
        assert "--zone=us-central2-b" in out

    def test_pod_fanout_commands(self):
        from accelerate_tpu.commands.tpu import build_pod_commands

        cfg = ClusterConfig(num_machines=2, tpu_name="p", tpu_zone="z",
                            coordinator_address="10.0.0.1:8476")
        cmds = build_pod_commands(cfg, "train.py", ["--lr", "1"], {"ACCELERATE_MESH_DP": "-1"})
        assert len(cmds) == 2
        assert "--worker=0" in cmds[0] and "--worker=1" in cmds[1]
        assert "ACCELERATE_PROCESS_ID='1'" in cmds[1][-1]
        assert "ACCELERATE_COORDINATOR_ADDR='10.0.0.1:8476'" in cmds[0][-1]


@pytest.mark.slow
class TestLaunch:
    def test_launch_script_on_cpu_mesh(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(textwrap.dedent(
            """
            import os, jax
            assert jax.device_count() == 4, jax.device_count()
            from accelerate_tpu import Accelerator
            acc = Accelerator()
            assert os.environ["ACCELERATE_MIXED_PRECISION"] == "bf16"
            assert acc.mixed_precision == "bf16"
            assert dict(acc.mesh.shape)["fsdp"] == 2
            print("LAUNCH_OK")
            """
        ))
        proc = subprocess.run(
            [
                sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
                "launch", "--num_cpu_devices", "4", "--mesh_fsdp", "2",
                "--mixed_precision", "bf16", str(script),
            ],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "", "XLA_FLAGS": ""},
            timeout=240,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "LAUNCH_OK" in proc.stdout

    def test_launch_max_restarts_resumes_from_checkpoint(self, tmp_path):
        """Fault tolerance: the script crashes mid-training on its first
        run; ``--max_restarts`` re-execs it and ACCELERATE_AUTO_RESUME makes
        prepare() reload the latest checkpoint, so training finishes at the
        right step (TPU-native analog of torchrun elastic restarts,
        reference launchers.py:231-245; SURVEY §5)."""
        script = tmp_path / "train_crashy.py"
        script.write_text(textwrap.dedent(
            """
            import json, os

            import optax

            from accelerate_tpu import Accelerator
            from accelerate_tpu.utils.dataclasses import ProjectConfiguration
            from accelerate_tpu.test_utils import RegressionDataset, RegressionModel

            class Loader:
                def __init__(self, dataset, batch_size):
                    self.dataset = dataset
                    self.batch_size = batch_size
                    self.sampler = self.batch_sampler = self.collate_fn = None
                    self.drop_last = False

            class StepCounter:
                def __init__(self):
                    self.steps_done = 0
                def state_dict(self):
                    return {"steps_done": self.steps_done}
                def load_state_dict(self, sd):
                    self.steps_done = sd["steps_done"]

            train_dir = os.environ["TRAIN_DIR"]
            acc = Accelerator(project_config=ProjectConfiguration(
                project_dir=train_dir, automatic_checkpoint_naming=True))
            counter = StepCounter()
            acc.register_for_checkpointing(counter)
            model, opt, dl = acc.prepare(
                RegressionModel(a=0.0, b=0.0), optax.sgd(0.05),
                Loader(RegressionDataset(length=32), 8))

            restarted = "ACCELERATE_RESTART_COUNT" in os.environ
            start = counter.steps_done
            if restarted:
                assert start == 3, f"expected resume at step 3, got {start}"
            else:
                assert start == 0, start

            batches = iter([])
            while counter.steps_done < 6:
                try:
                    batch = next(batches)
                except StopIteration:
                    batches = iter(dl)
                    batch = next(batches)
                out = model(**batch)
                acc.backward(out.loss)
                opt.step()
                opt.zero_grad()
                counter.steps_done += 1
                acc.save_state()
                if counter.steps_done == 3 and not restarted:
                    os._exit(17)  # simulated mid-epoch crash, after a save

            with open(os.path.join(train_dir, "final.json"), "w") as f:
                json.dump({"steps_done": counter.steps_done,
                           "resumed_at": start, "restarted": restarted}, f)
            print("LAUNCH_FT_OK")
            """
        ))
        train_dir = tmp_path / "run"
        train_dir.mkdir()
        proc = subprocess.run(
            [
                sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
                "launch", "--num_cpu_devices", "2", "--max_restarts", "2",
                str(script),
            ],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "", "XLA_FLAGS": "",
                 "TRAIN_DIR": str(train_dir)},
            timeout=300,
        )
        assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-3000:])
        assert "LAUNCH_FT_OK" in proc.stdout
        assert "restart 1/2" in proc.stderr
        final = json.loads((train_dir / "final.json").read_text())
        assert final == {"steps_done": 6, "resumed_at": 3, "restarted": True}

    def test_bundled_test_script(self):
        proc = subprocess.run(
            [
                sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
                "test", "--num_cpu_devices", "4",
            ],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "", "XLA_FLAGS": ""},
            timeout=360,
        )
        assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-3000:])
        assert "Test is a success!" in proc.stdout


class TestDebugLauncher:
    def test_debug_launcher_runs_function(self):
        from accelerate_tpu.launchers import debug_launcher
        from accelerate_tpu.test_utils.scripts.test_script import main

        debug_launcher(main, num_processes=2)


@pytest.mark.slow
def test_two_real_processes_distributed():
    """VERDICT r5 Missing #3 closed: TWO real OS processes rendezvous via
    ``jax.distributed.initialize`` (CPU backend, TCP coordinator from the
    launcher's ``ACCELERATE_COORDINATOR_ADDR`` contract) and drive the
    eager multihost collectives + one ``prepare()``+train step. This is
    also the end-to-end fixture for the cross-host collective-digest diff:
    the sanitizer in each process writes its host's digest file, and the
    monitor-side diff must see two AGREEING hosts."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    workdir = tempfile.mkdtemp(prefix="multiproc_")
    procs = []
    for rank in range(2):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "ACCELERATE_COORDINATOR_ADDR": f"127.0.0.1:{port}",
            "ACCELERATE_NUM_PROCESSES": "2",
            "ACCELERATE_PROCESS_ID": str(rank),
            "MULTIPROC_DIR": workdir,
        }
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m",
                    "accelerate_tpu.test_utils.scripts.test_multiprocess",
                ],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outputs.append(out)
    finally:
        # a rank that dies pre-rendezvous wedges its peer in the gloo
        # coordinator forever — never leave orphans holding the port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rank, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {rank} failed:\n{out[-3000:]}"
        assert "ALL_MULTIPROC_OK" in out, f"process {rank}:\n{out[-2000:]}"

    # digest diff end to end: both hosts wrote, and they AGREE (same
    # program -> same collective sequence -> no divergence named)
    from accelerate_tpu.analysis.compiled import diff_host_digests, read_host_digests

    digests = read_host_digests(workdir)
    assert set(digests) == {0, 1}, sorted(digests)
    shared_labels = set(digests[0]) & set(digests[1])
    assert shared_labels, (digests[0].keys(), digests[1].keys())
    assert diff_host_digests(digests) == []


@pytest.mark.slow
def test_launched_ops_script():
    """The test_ops assertion script through the product launcher
    (reference ``tests/test_multigpu.py:48-53`` pattern)."""
    from accelerate_tpu.test_utils import DEFAULT_LAUNCH_COMMAND, execute_subprocess_async

    cmd = DEFAULT_LAUNCH_COMMAND + ["-m", "accelerate_tpu.test_utils.scripts.test_ops"]
    out = execute_subprocess_async(cmd)
    assert "ALL_OPS_OK" in out.stdout


@pytest.mark.slow
def test_launched_sync_script():
    from accelerate_tpu.test_utils import DEFAULT_LAUNCH_COMMAND, execute_subprocess_async

    cmd = DEFAULT_LAUNCH_COMMAND + ["-m", "accelerate_tpu.test_utils.scripts.test_sync"]
    out = execute_subprocess_async(cmd)
    assert "ALL_SYNC_OK" in out.stdout


@pytest.mark.slow
def test_launched_merge_weights_script():
    """Sharded save → merge-weights → reload proof rides OUR launcher at
    any device count (reference ``test_merge_weights.py:161``)."""
    from accelerate_tpu.test_utils import DEFAULT_LAUNCH_COMMAND, execute_subprocess_async

    cmd = DEFAULT_LAUNCH_COMMAND + ["-m", "accelerate_tpu.test_utils.scripts.test_merge_weights"]
    out = execute_subprocess_async(cmd)
    assert "ALL_MERGE_OK" in out.stdout


@pytest.mark.slow
def test_launched_performance_script():
    """Per-config quality bars (plain/fsdp/deepspeed/bf16) ride OUR
    launcher (reference ``external_deps/test_performance.py``)."""
    from accelerate_tpu.test_utils import DEFAULT_LAUNCH_COMMAND, execute_subprocess_async

    cmd = DEFAULT_LAUNCH_COMMAND + ["-m", "accelerate_tpu.test_utils.scripts.test_performance"]
    out = execute_subprocess_async(cmd)
    assert "ALL_PERFORMANCE_OK" in out.stdout


@pytest.mark.slow
def test_launched_notebook_script():
    """notebook_launcher's training + pre-initialized-canary proof rides
    OUR launcher (reference ``test_notebook.py:118``)."""
    from accelerate_tpu.test_utils import DEFAULT_LAUNCH_COMMAND, execute_subprocess_async

    cmd = DEFAULT_LAUNCH_COMMAND + ["-m", "accelerate_tpu.test_utils.scripts.test_notebook"]
    out = execute_subprocess_async(cmd)
    assert "ALL_NOTEBOOK_OK" in out.stdout


@pytest.mark.slow
def test_launched_data_loop_script():
    from accelerate_tpu.test_utils import DEFAULT_LAUNCH_COMMAND, execute_subprocess_async

    cmd = DEFAULT_LAUNCH_COMMAND + ["-m", "accelerate_tpu.test_utils.scripts.test_data_loop"]
    out = execute_subprocess_async(cmd)
    assert "ALL_DATA_LOOP_OK" in out.stdout
