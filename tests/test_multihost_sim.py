"""Multi-host code paths exercised without a cluster: the serialization /
padding / agreement logic of the eager collectives (reference runs these
under launched N-process tests, ``test_utils/scripts/test_ops.py``; here
``multihost_utils`` is faked so the branches run in one process) and the
pod-fanout command construction (VERDICT Weak-9)."""

import numpy as np
import pytest
from unittest import mock

# make jax.experimental.multihost_utils an existing attribute so
# mock.patch can swap it (it loads lazily otherwise)
from jax.experimental import multihost_utils as _real_multihost  # noqa: F401

from accelerate_tpu import operations as ops
from accelerate_tpu.state import PartialState


@pytest.fixture
def two_process_state():
    state = PartialState()
    saved = dict(num_processes=state.num_processes, process_index=state.process_index)
    state.num_processes = 2
    state.process_index = 0
    yield state
    state.num_processes = saved["num_processes"]
    state.process_index = saved["process_index"]


class _FakeMultihost:
    """Emulates a 2-process world: the 'other' process's contribution is
    primed per call."""

    def __init__(self, other_payloads):
        self.other = list(other_payloads)

    def process_allgather(self, x, tiled=False):
        other = self.other.pop(0)
        if tiled:
            return np.concatenate([np.asarray(x), np.asarray(other)])
        return np.stack([np.asarray(x), np.asarray(other)])

    def broadcast_one_to_all(self, x, is_source=True):
        if is_source:
            return np.asarray(x)
        return np.asarray(self.other.pop(0))


#: the int32-word wire format the object/byte broadcasts use — the tests
#: build expected wire payloads with the SAME helper the product uses so
#: the format stays single-source
_as_words = ops.pack_words


def test_gather_object_pads_and_unpacks_uneven_payloads(two_process_state):
    import pickle

    mine = ["short"]
    theirs = ["a much longer object from the other process", {"k": 1}]
    their_payload = np.frombuffer(pickle.dumps(theirs), dtype=np.uint8)
    my_payload = np.frombuffer(pickle.dumps(mine), dtype=np.uint8)
    max_size = max(their_payload.size, my_payload.size)
    their_padded = np.zeros(max_size, np.uint8)
    their_padded[: their_payload.size] = their_payload
    fake = _FakeMultihost(
        [np.array([their_payload.size], np.int64), their_padded]
    )
    with mock.patch("jax.experimental.multihost_utils", fake):
        out = ops.gather_object(mine)
    assert out == mine + theirs


def test_broadcast_object_list_receiver_side(two_process_state):
    import pickle

    two_process_state.process_index = 1  # not the source
    source_obj = [{"weights": [1, 2, 3]}, "tag"]
    payload = np.frombuffer(pickle.dumps(source_obj), dtype=np.uint8)
    fake = _FakeMultihost([np.array([payload.size], np.int64), _as_words(payload)])
    with mock.patch("jax.experimental.multihost_utils", fake):
        received = [None]
        ops.broadcast_object_list(received)
    assert received == source_obj


def test_broadcast_ships_non_4byte_dtypes_as_words(two_process_state):
    """Raw-tensor broadcast of int64/uint8 leaves rides the int32-word
    wire (gloo sub-4-byte corruption / x64 truncation — same fix as the
    dispatcher's _send_tensor); f32 leaves take the direct path."""
    two_process_state.process_index = 1  # receiver
    src_i64 = np.array([2**40 + 7, -3], np.int64)
    src_u8 = np.arange(5, dtype=np.uint8)
    src_f32 = np.array([1.5, -2.5], np.float32)
    fake = _FakeMultihost(
        [_as_words(src_i64.tobytes()), _as_words(src_u8.tobytes()), src_f32]
    )
    with mock.patch("jax.experimental.multihost_utils", fake):
        out_i64 = ops.broadcast(np.zeros(2, np.int64))
        out_u8 = ops.broadcast(np.zeros(5, np.uint8))
        out_f32 = ops.broadcast(np.zeros(2, np.float32))
    np.testing.assert_array_equal(out_i64, src_i64)
    assert out_i64.dtype == np.int64
    out_i64[0] = 1  # receivers get a WRITABLE copy, not a frombuffer view
    np.testing.assert_array_equal(out_u8, src_u8)
    np.testing.assert_array_equal(out_f32, src_f32)


def test_broadcast_source_side_word_wire_round_trips(two_process_state):
    src = np.array([[2**40, 1], [-1, 2**33]], np.int64)
    fake = _FakeMultihost([])  # source side never pops
    with mock.patch("jax.experimental.multihost_utils", fake):
        out = ops.broadcast(src)
    np.testing.assert_array_equal(out, src)
    assert out.dtype == np.int64 and out.shape == (2, 2)


def test_verify_operation_raises_on_shape_mismatch(two_process_state):
    import pickle

    two_process_state.debug = True
    # the other process reports a different shape for the same gather
    other_meta = [((4, 4), "float32")]
    their_payload = np.frombuffer(pickle.dumps([other_meta[0]]), dtype=np.uint8)

    # gather() first runs the debug meta agreement via gather_object
    my_meta = ((2, 2), "float32")
    my_payload = np.frombuffer(pickle.dumps([my_meta]), dtype=np.uint8)
    max_size = max(their_payload.size, my_payload.size)
    their_padded = np.zeros(max_size, np.uint8)
    their_padded[: their_payload.size] = their_payload
    fake = _FakeMultihost([np.array([their_payload.size], np.int64), their_padded])
    with mock.patch("jax.experimental.multihost_utils", fake):
        with pytest.raises(ops.DistributedOperationException, match="Mismatch"):
            ops.gather(np.zeros((2, 2), np.float32))


def test_verify_operation_passes_on_agreement(two_process_state):
    import pickle

    two_process_state.debug = True
    meta = ((2, 2), "float32")
    payload = np.frombuffer(pickle.dumps([meta]), dtype=np.uint8)
    # call 1+2: meta agreement gather_object; call 3: the actual allgather
    fake = _FakeMultihost([
        np.array([payload.size], np.int64), payload,
        np.ones((2, 2), np.float32),
    ])
    with mock.patch("jax.experimental.multihost_utils", fake):
        out = ops.gather(np.zeros((2, 2), np.float32))
    assert np.asarray(out).shape == (4, 2)  # tiled concat of 2 processes


# ---------------------------------------------------------------------------
# pod fanout (commands/tpu.py)
# ---------------------------------------------------------------------------


def _pod_cfg(**kw):
    from accelerate_tpu.commands.config import ClusterConfig

    defaults = dict(num_machines=2, tpu_name="my-pod", tpu_zone="us-central2-b")
    defaults.update(kw)
    return ClusterConfig(**defaults)


def test_build_pod_commands_explicit_coordinator():
    from accelerate_tpu.commands.tpu import build_pod_commands

    cfg = _pod_cfg(coordinator_address="10.0.0.2:8476")
    cmds = build_pod_commands(
        cfg, "train.py", ["--lr", "1e-3"], {"ACCELERATE_MIXED_PRECISION": "bf16"}
    )
    assert len(cmds) == 2
    for worker, cmd in enumerate(cmds):
        assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "my-pod"]
        assert f"--worker={worker}" in cmd
        inner = cmd[-1]
        assert f"ACCELERATE_PROCESS_ID='{worker}'" in inner
        assert "ACCELERATE_NUM_PROCESSES='2'" in inner
        assert "ACCELERATE_COORDINATOR_ADDR='10.0.0.2:8476'" in inner
        assert "ACCELERATE_MIXED_PRECISION='bf16'" in inner
        assert inner.endswith("python3 train.py --lr 1e-3")
        # the round-1 bug: a literal $(hostname -i) that never expands
        assert "hostname" not in inner


def test_resolve_coordinator_asks_gcloud_for_worker0():
    from accelerate_tpu.commands import tpu as tpu_mod

    cfg = _pod_cfg(coordinator_address=None)
    fake = mock.Mock(returncode=0, stdout="10.128.0.7\n")
    with mock.patch.object(tpu_mod.subprocess, "run", return_value=fake) as run:
        addr = tpu_mod.resolve_coordinator(cfg)
    assert addr == "10.128.0.7:8476"
    called = run.call_args[0][0]
    assert "describe" in called and "my-pod" in called


def test_resolve_coordinator_falls_back_to_autodetect():
    from accelerate_tpu.commands import tpu as tpu_mod

    cfg = _pod_cfg(coordinator_address=None)
    with mock.patch.object(tpu_mod.subprocess, "run", side_effect=OSError("no gcloud")):
        assert tpu_mod.resolve_coordinator(cfg) is None
    # None coordinator → workers use jax's TPU-pod metadata auto-detect;
    # the env must then omit the coordinator entirely
    with mock.patch.object(tpu_mod.subprocess, "run", side_effect=OSError("no gcloud")):
        cmds = tpu_mod.build_pod_commands(cfg, "t.py", [], {})
    assert "ACCELERATE_COORDINATOR_ADDR" not in cmds[0][-1]


def test_pod_fanout_dry_run_prints(capsys):
    from accelerate_tpu.commands.tpu import pod_fanout

    cfg = _pod_cfg(coordinator_address="10.0.0.2:8476")
    rc = pod_fanout(cfg, "train.py", [], {}, dry_run=True)
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("gcloud compute tpus tpu-vm ssh") == 2


# ---------------------------------------------------------------------------
# DataLoaderDispatcher tensor-path broadcast (data_loader.py:_raw_batches)
# ---------------------------------------------------------------------------


def _dispatcher(batches):
    """A DataLoaderDispatcher whose source yields ``batches`` verbatim, with
    no device placement so _raw_batches drives the broadcast protocol only."""
    from accelerate_tpu.data_loader import DataLoaderDispatcher

    return DataLoaderDispatcher(
        list(batches),
        batch_sampler=[[i] for i in range(len(batches))],
        collate_fn=lambda items: items[0],
        sharding=None,
    )


def test_dispatcher_broadcasts_tensors_not_pickles(two_process_state):
    """Main side: array leaves ride raw tensor broadcasts; the pickled
    descriptor goes out only when the structure CHANGES (first batch and the
    uneven tail), not per batch."""
    import pickle

    batches = [
        {"x": np.ones((4, 3), np.float32), "y": np.arange(4)},
        {"x": np.full((4, 3), 2.0, np.float32), "y": np.arange(4)},
        {"x": np.full((4, 3), 3.0, np.float32), "y": np.arange(4)},
        {"x": np.ones((2, 3), np.float32), "y": np.arange(2)},  # uneven tail
    ]
    dl = _dispatcher(batches)

    object_broadcasts = []
    orig = ops.broadcast_object_list

    def counting(object_list, from_process=0):
        object_broadcasts.append(pickle.dumps(list(object_list)))
        return orig(object_list, from_process)

    fake = _FakeMultihost([])  # source side never pops
    with mock.patch("jax.experimental.multihost_utils", fake), mock.patch.object(
        ops, "broadcast_object_list", counting
    ), mock.patch(
        "accelerate_tpu.data_loader.PartialState", lambda: two_process_state
    ):
        got = [b for b in dl._raw_batches()]

    assert len(got) == 4
    np.testing.assert_array_equal(got[1]["x"], batches[1]["x"])
    # exactly 2 structure broadcasts (initial + changed tail shape); the
    # steady-state batches moved with zero pickling
    assert len(object_broadcasts) == 2


def test_dispatcher_receiver_reconstructs_batches(two_process_state):
    """Receiver side: batches are rebuilt from the control stream +
    descriptor + raw tensor broadcasts."""
    import pickle

    two_process_state.process_index = 1  # not the source
    x0 = np.arange(12, dtype=np.float32).reshape(4, 3)
    x1 = np.arange(6, dtype=np.float32).reshape(2, 3)

    # build the descriptor exactly as the source would
    import jax as _jax

    leaves, treedef = _jax.tree.flatten({"x": x0})
    desc0 = (treedef, ((x0.shape, x0.dtype.str, False),))
    desc1 = (treedef, ((x1.shape, x1.dtype.str, False),))

    def obj_payload(obj):
        payload = np.frombuffer(pickle.dumps([obj]), dtype=np.uint8)
        return [np.array([payload.size], np.int64), _as_words(payload)]

    fake = _FakeMultihost(
        [np.array([2], np.int64), *obj_payload(desc0), x0]  # batch 0: new struct
        + [np.array([1], np.int64), x0 + 1.0]  # batch 1: same struct
        + [np.array([2], np.int64), *obj_payload(desc1), x1]  # tail: new struct
        + [np.array([0], np.int64)]  # end
    )
    dl = _dispatcher([])
    with mock.patch("jax.experimental.multihost_utils", fake), mock.patch(
        "accelerate_tpu.data_loader.PartialState", lambda: two_process_state
    ):
        got = [b for b in dl._raw_batches()]

    assert len(got) == 3
    np.testing.assert_array_equal(got[0]["x"], x0)
    np.testing.assert_array_equal(got[1]["x"], x0 + 1.0)
    np.testing.assert_array_equal(got[2]["x"], x1)


def test_dispatcher_wide_dtypes_survive_exactly(two_process_state):
    """int64 leaves (numpy/tokenizer default) must arrive dtype- and
    value-exact: the wire carries raw bytes for >4-byte dtypes, because
    broadcast_one_to_all's jax round-trip would truncate them to 32-bit
    under the default jax_enable_x64=False."""
    import pickle
    import jax as _jax

    two_process_state.process_index = 1  # receiver
    big = np.array([[2**40 + 7, -(2**35)], [1, 2]], np.int64)
    leaves, treedef = _jax.tree.flatten({"ids": big})
    desc = (treedef, ((big.shape, big.dtype.str, False),))

    payload = np.frombuffer(pickle.dumps([desc]), dtype=np.uint8)
    wire_words = _as_words(np.frombuffer(big.tobytes(), np.uint8))
    fake = _FakeMultihost(
        [np.array([2], np.int64), np.array([payload.size], np.int64),
         _as_words(payload), wire_words]
        + [np.array([0], np.int64)]
    )
    dl = _dispatcher([])
    with mock.patch("jax.experimental.multihost_utils", fake), mock.patch(
        "accelerate_tpu.data_loader.PartialState", lambda: two_process_state
    ):
        got = [b for b in dl._raw_batches()]
    assert got[0]["ids"].dtype == np.int64
    np.testing.assert_array_equal(got[0]["ids"], big)
