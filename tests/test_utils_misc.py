"""Tiny parity utils (reference utils/versions.py, rich.py, other.py, tqdm.py)."""

import pytest

from accelerate_tpu.utils import compare_versions, convert_bytes, is_jax_version


def test_compare_versions_operator_dispatch():
    assert compare_versions("jax", ">=", "0.4.0")
    assert not compare_versions("jax", "<", "0.4.0")
    assert is_jax_version(">=", "0.4.0")
    with pytest.raises(ValueError, match="operation"):
        compare_versions("jax", "~=", "1.0")


def test_convert_bytes_units():
    assert convert_bytes(512) == "512 bytes"
    assert convert_bytes(2048) == "2.0 KB"
    assert convert_bytes(3.2e9) == "2.98 GB"


def test_rich_module_contract():
    from accelerate_tpu.utils.imports import is_rich_available

    if is_rich_available():
        import accelerate_tpu.utils.rich  # noqa: F401 — installs the handler
    else:
        with pytest.raises(ModuleNotFoundError, match="rich"):
            import accelerate_tpu.utils.rich  # noqa: F401
