"""GPT-2 family: training on sharded meshes, streaming offload, pipeline
inference, HF name conversion (reference exposure: transformers GPT-2 in
``examples/inference/pippy/gpt2.py`` etc.)."""

import jax
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshPlugin, prepare_pippy
from accelerate_tpu.big_modeling import cpu_offload
from accelerate_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHeadModel,
    convert_hf_gpt2_state_dict,
)

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _tiny(layers=2):
    config = GPT2Config.tiny(layers=layers)
    model = GPT2LMHeadModel.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    return config, model, ids


def test_forward_shapes_and_loss():
    config, model, ids = _tiny()
    out = model.apply_fn(model.params, input_ids=ids, labels=ids)
    assert out["logits"].shape == (2, 16, 256)
    assert np.isfinite(float(out["loss"]))


def test_training_on_sharded_mesh():
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    config = GPT2Config.tiny(layers=2)
    model, opt = accelerator.prepare(
        GPT2LMHeadModel.from_config(config, seed=0), optax.adamw(1e-2)
    )
    ids = np.random.default_rng(0).integers(0, 256, size=(8, 16)).astype(np.int32)
    losses = []
    for _ in range(5):
        out = model(input_ids=ids, labels=ids)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]


def test_streaming_offload_matches_resident():
    config, model, ids = _tiny()
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    out = cpu_offload(model)(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_inference_matches():
    config, model, ids = _tiny(layers=4)
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids}, devices=jax.devices()[:2]
    )
    out = pipelined(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_hf_name_conversion_roundtrip():
    config, model, ids = _tiny()
    # build an HF-named flat dict from our params, convert back, compare
    hf = {}
    p = jax.tree.map(np.asarray, model.params)
    hf["transformer.wte.weight"] = p["wte"]
    hf["transformer.wpe.weight"] = p["wpe"]
    for i in range(config.num_hidden_layers):
        hf[f"transformer.h.{i}.ln_1.weight"] = p["layers"]["ln1_g"][i]
        hf[f"transformer.h.{i}.ln_1.bias"] = p["layers"]["ln1_b"][i]
        hf[f"transformer.h.{i}.attn.c_attn.weight"] = p["layers"]["w_qkv"][i]
        hf[f"transformer.h.{i}.attn.c_attn.bias"] = p["layers"]["b_qkv"][i]
        hf[f"transformer.h.{i}.attn.c_proj.weight"] = p["layers"]["w_proj"][i]
        hf[f"transformer.h.{i}.attn.c_proj.bias"] = p["layers"]["b_proj"][i]
        hf[f"transformer.h.{i}.ln_2.weight"] = p["layers"]["ln2_g"][i]
        hf[f"transformer.h.{i}.ln_2.bias"] = p["layers"]["ln2_b"][i]
        hf[f"transformer.h.{i}.mlp.c_fc.weight"] = p["layers"]["w_fc"][i]
        hf[f"transformer.h.{i}.mlp.c_fc.bias"] = p["layers"]["b_fc"][i]
        hf[f"transformer.h.{i}.mlp.c_proj.weight"] = p["layers"]["w_out"][i]
        hf[f"transformer.h.{i}.mlp.c_proj.bias"] = p["layers"]["b_out"][i]
    hf["transformer.ln_f.weight"] = p["ln_f_g"]
    hf["transformer.ln_f.bias"] = p["ln_f_b"]

    converted = convert_hf_gpt2_state_dict(hf, config)
    for leaf_a, leaf_b in zip(jax.tree.leaves(converted), jax.tree.leaves(p)):
        np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))


def test_zoo_has_gpt2():
    from accelerate_tpu.models import MODEL_ZOO

    assert "gpt2" in MODEL_ZOO and "gpt2-xl" in MODEL_ZOO
