"""Diagnostics subsystem tests: span tracing into Chrome trace files,
cross-host merge with clock-offset correction, the hang watchdog (stalled
step → HANG_REPORT with the stalled thread's stack + open span stack; a
healthy loop must NOT fire), the monitor status engine, the CLI surface,
and the PR's telemetry satellites (atexit/idempotent close, empty-ring
summary, unknown_skip counting, compile-record mono timestamps)."""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.diagnostics import (
    NULL_TRACER,
    Tracer,
    Watchdog,
    collect_status,
    get_tracer,
    merge_traces,
    render_status,
    set_active_tracer,
    trace_span,
    validate_chrome_trace,
)
from accelerate_tpu.diagnostics.watchdog import _set_active_watchdog, get_active_watchdog
from accelerate_tpu.telemetry import TelemetryRecorder, set_active_recorder
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, SimpleLoader

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_diagnostics_globals():
    """Tracing/watchdog/telemetry all register process-wide state; tests
    must not leak it into each other."""
    yield
    from accelerate_tpu import lazy

    wd = get_active_watchdog()
    if wd is not None:
        wd.stop()
    _set_active_watchdog(None)
    set_active_tracer(None)
    set_active_recorder(None)
    lazy.set_compile_callback(None)


def _toy(tmp_path, **kwargs):
    acc = Accelerator(project_dir=str(tmp_path), **kwargs)
    model, opt, dl = acc.prepare(
        RegressionModel(a=0.0, b=0.0),
        optax.sgd(0.1),
        SimpleLoader(RegressionDataset(length=64), batch_size=16),
    )
    return acc, model, opt, dl


def _train(acc, model, opt, dl, epochs=1):
    for _ in range(epochs):
        for batch in dl:
            out = model(**batch)
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


def test_toy_loop_writes_valid_trace_and_heartbeat(tmp_path):
    """Acceptance loop: 20 steps with telemetry+diagnostics produce a
    per-host trace file that merges into a schema-valid Chrome trace with
    the built-in spans, plus a heartbeat file with the step count."""
    acc, model, opt, dl = _toy(tmp_path, telemetry=True, diagnostics=True)
    _train(acc, model, opt, dl, epochs=5)  # 64/16 × 5 = 20 steps
    acc.end_training()

    trace_dir = tmp_path / "traces"
    assert (trace_dir / "host_0.trace.json").exists()
    merged = merge_traces(str(trace_dir), str(tmp_path / "merged.json"))
    validate_chrome_trace(merged)
    names = {e["name"] for e in merged["traceEvents"]}
    assert {"prepare", "backward/dispatch", "step/dispatch",
            "compile/trace_lower", "compile/compile", "dataloader/fetch"} <= names
    # merged output is well-formed standalone JSON, loadable by Perfetto
    reloaded = json.load(open(tmp_path / "merged.json"))
    validate_chrome_trace(reloaded)
    # spans carry sane timings: positive durations, rebased to t≥0
    complete = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert complete and all(e["dur"] >= 0 and e["ts"] >= 0 for e in complete)
    # 20 steps → 20 step/dispatch spans
    assert sum(1 for e in complete if e["name"] == "step/dispatch") == 20

    hb = json.load(open(tmp_path / "diagnostics" / "heartbeat_0.json"))
    assert hb["step"] == 20 and hb["ema_step_s"] > 0


def test_trace_survives_crash_without_close(tmp_path):
    """The append format must be parseable with no close() — the whole
    point is a SIGKILL'd run's trace still loads."""
    tracer = Tracer(logging_dir=str(tmp_path), host=0)
    with tracer.span("phase_a", step=1):
        pass
    tracer.flush()  # but never close()
    merged = merge_traces(str(tmp_path / "traces"))
    validate_chrome_trace(merged)
    assert any(e["name"] == "phase_a" for e in merged["traceEvents"])
    tracer.close()


def test_trace_merge_corrects_host_clock_offsets(tmp_path):
    """Two hosts whose monotonic clocks disagree wildly but whose wall
    clocks agree must land on ONE timeline: same-wall-time events align
    after the per-host wall-minus-mono correction."""
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    # host 0: mono origin 1000s, offset wall-mono = 500; event at wall 1503
    # host 1: mono origin 2000s, offset wall-mono = -500; event at wall 1503
    for host, (mono_ts, offset) in enumerate({0: (1003.0, 500.0), 1: (2003.0, -500.0)}.values()):
        lines = [
            "[\n",
            json.dumps({"name": "clock_sync", "ph": "M", "pid": host, "tid": 0,
                        "args": {"wall_minus_mono_s": offset}}) + ",\n",
            json.dumps({"name": "step", "ph": "X", "ts": mono_ts * 1e6,
                        "dur": 1000.0, "pid": host, "tid": 1}) + ",\n",
        ]
        (trace_dir / f"host_{host}.trace.json").write_text("".join(lines))
    merged = merge_traces(str(trace_dir))
    steps = [e for e in merged["traceEvents"] if e["name"] == "step"]
    assert len(steps) == 2
    # both events happened at the same wall instant → identical merged ts
    assert abs(steps[0]["ts"] - steps[1]["ts"]) < 1.0  # µs
    assert merged["metadata"]["merged_hosts"] == [0, 1]


def test_trace_merge_handles_restart_epochs_in_one_file(tmp_path):
    """Auto-resume appends a second monotonic epoch (fresh perf_counter
    origin + fresh clock_sync) to the SAME host file; each event must use
    the most recent clock_sync above it, so the resumed run's spans land
    at their true wall positions instead of the dead process's offset."""
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    lines = [
        "[\n",
        # first life: mono origin ~1000, wall = mono + 500 → event at wall 1501
        json.dumps({"name": "clock_sync", "ph": "M", "pid": 0, "tid": 0,
                    "args": {"wall_minus_mono_s": 500.0}}) + ",\n",
        json.dumps({"name": "step", "ph": "X", "ts": 1001.0 * 1e6,
                    "dur": 10.0, "pid": 0, "tid": 1}) + ",\n",
        # restart: mono origin resets to ~3, wall = mono + 1600 → wall 1603
        json.dumps({"name": "clock_sync", "ph": "M", "pid": 0, "tid": 0,
                    "args": {"wall_minus_mono_s": 1600.0}}) + ",\n",
        json.dumps({"name": "step", "ph": "X", "ts": 3.0 * 1e6,
                    "dur": 10.0, "pid": 0, "tid": 1}) + ",\n",
    ]
    (trace_dir / "host_0.trace.json").write_text("".join(lines))
    merged = merge_traces(str(trace_dir))
    steps = sorted(
        (e for e in merged["traceEvents"] if e["name"] == "step"),
        key=lambda e: e["ts"],
    )
    # wall gap is 1603 - 1501 = 102 s, regardless of the epoch reset
    assert steps[1]["ts"] - steps[0]["ts"] == pytest.approx(102.0 * 1e6)


def test_watchdog_only_mode_spans_defer_deadline_and_heartbeat(tmp_path):
    """tracing=False + watchdog=True: trace_span call sites still feed the
    watchdog progress (a long compile inside a span must not false-fire)
    and keep the heartbeat fresh for the monitor's staleness check."""
    set_active_tracer(None)
    wd = Watchdog(
        logging_dir=str(tmp_path),
        floor_seconds=0.4,
        check_interval_seconds=0.05,
        heartbeat_interval_seconds=0.0,  # unthrottled for the test
        host=0,
    ).start()
    try:
        hb_path = tmp_path / "diagnostics" / "heartbeat_0.json"
        t_end = time.time() + 1.0  # > floor: would fire without the touches
        while time.time() < t_end:
            with trace_span("compile/compile", label="fused_step"):
                time.sleep(0.05)  # "compiling" — progress only via the span
        assert not wd.fired
        assert not os.path.exists(wd.report_path)
        hb = json.load(open(hb_path))
        assert time.time() - hb["ts"] < 1.0  # refreshed by the touches
    finally:
        wd.stop()


def test_disabled_mode_is_strict_noop(tmp_path):
    """diagnostics off (the default): NULL tracer, no watchdog thread, no
    traces/ dir, and trace_span costs a shared no-op context manager."""
    acc, model, opt, dl = _toy(tmp_path)
    assert acc.tracer is NULL_TRACER and not acc.tracer
    assert acc.watchdog is None
    assert get_tracer() is NULL_TRACER
    assert get_active_watchdog() is None
    _train(acc, model, opt, dl)
    assert not (tmp_path / "traces").exists()
    assert not (tmp_path / "diagnostics").exists()
    span = trace_span("anything", k=1)
    assert span is trace_span("something_else")  # the shared singleton
    # the loop still trains
    assert float(np.asarray(model.params["a"])) != 0.0


def test_open_span_stack_tracks_nesting(tmp_path):
    tracer = Tracer(logging_dir=None, host=0)
    with tracer.span("outer"):
        with tracer.span("inner", step=3):
            spans = tracer.open_spans()
            (frames,) = spans.values()
            assert [f["name"] for f in frames] == ["outer", "inner"]
            assert frames[1]["attrs"] == {"step": 3}
    assert tracer.open_spans() == {}
    tracer.close()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_stalled_step_with_stack_and_spans(tmp_path):
    """A stalled step past the deadline must produce HANG_REPORT_<host>.json
    containing the stalled thread's Python stack and the open span stack,
    and name the innermost open span as the stalled phase."""
    tel = TelemetryRecorder(logging_dir=None, memory_interval=0)
    tel.record_event("marker", note="pre-hang")
    tracer = Tracer(logging_dir=str(tmp_path), host=0)
    set_active_tracer(tracer)
    wd = Watchdog(
        logging_dir=str(tmp_path),
        multiplier=3.0,
        floor_seconds=0.3,
        check_interval_seconds=0.05,
        telemetry=tel,
        host=0,
    ).start()
    try:
        for _ in range(3):
            time.sleep(0.02)
            wd.step_completed()
        with tracer.span("collective/wedged_allreduce", op="psum"):
            deadline = time.time() + 5.0
            while not os.path.exists(wd.report_path) and time.time() < deadline:
                time.sleep(0.05)  # the artificial wedge the watchdog sees
        assert os.path.exists(wd.report_path), "watchdog never fired"
        report = json.load(open(wd.report_path))
        assert report["stalled_phase"] == "collective/wedged_allreduce"
        frames = [f for frames in report["open_spans"].values() for f in frames]
        assert any(f["name"] == "collective/wedged_allreduce" for f in frames)
        # the stalled (main) thread's stack shows where it sits — this file
        stacks = "\n".join("\n".join(s) for s in report["threads"].values())
        assert "test_diagnostics" in stacks and "sleep" in stacks
        # the telemetry tail rode along
        assert any(r.get("kind") == "marker" for r in report["telemetry_tail"])
        assert report["elapsed_s"] > report["deadline_s"] >= 0.3
    finally:
        wd.stop()
        tracer.close()
        tel.close()


def test_watchdog_grace_phase_defers_deadline(tmp_path):
    """A stall inside a grace phase (compile/checkpoint/prepare — host-
    local, legitimately unbounded) must NOT fire the step deadline; the
    same stall inside a collective span must (see the stalled-step test)."""
    tracer = Tracer(logging_dir=None, host=0)
    set_active_tracer(tracer)
    wd = Watchdog(
        logging_dir=str(tmp_path),
        floor_seconds=0.2,
        check_interval_seconds=0.05,
        host=0,
    ).start()
    try:
        with tracer.span("compile/compile", label="fused_step"):
            time.sleep(0.8)  # ≫ floor, but grace_seconds (1800) governs
        assert not wd.fired
        assert not os.path.exists(wd.report_path)
    finally:
        wd.stop()
        tracer.close()


def test_watchdog_fire_publishes_fired_heartbeat(tmp_path):
    """_fire writes a heartbeat while fired is still True, so the monitor's
    wedged check sees the watchdog's own verdict, not just staleness."""
    wd = Watchdog(
        logging_dir=str(tmp_path),
        floor_seconds=0.2,
        check_interval_seconds=0.05,
        heartbeat_interval_seconds=3600.0,  # only forced writes land
        host=0,
    ).start()
    try:
        deadline = time.time() + 5.0
        while not os.path.exists(wd.report_path) and time.time() < deadline:
            time.sleep(0.05)
        hb = json.load(open(tmp_path / "diagnostics" / "heartbeat_0.json"))
        assert hb["fired"] is True
        status = collect_status(str(tmp_path))
        assert status["wedged"] == [0]
    finally:
        wd.stop()


def test_watchdog_does_not_fire_on_healthy_loop(tmp_path):
    wd = Watchdog(
        logging_dir=str(tmp_path),
        multiplier=5.0,
        floor_seconds=0.4,
        check_interval_seconds=0.05,
        host=0,
    ).start()
    try:
        t_end = time.time() + 1.2  # ≫ floor: plenty of chances to misfire
        while time.time() < t_end:
            time.sleep(0.02)
            wd.step_completed()
        assert not os.path.exists(wd.report_path)
        assert not wd.fired
    finally:
        wd.stop()


def test_watchdog_raises_preemption_flag_on_hang(tmp_path):
    """preempt_on_hang closes the loop with PR 2: a fired watchdog raises
    the active PreemptionHandler's flag so the consensus emergency-save
    path takes over at the next step boundary."""
    from accelerate_tpu.resilience.preemption import PreemptionHandler

    handler = PreemptionHandler(handle_signals=False)
    handler.install()
    wd = Watchdog(
        logging_dir=str(tmp_path),
        floor_seconds=0.2,
        check_interval_seconds=0.05,
        preempt_on_hang=True,
        host=0,
    ).start()
    try:
        deadline = time.time() + 5.0
        while not handler.preemption_requested and time.time() < deadline:
            time.sleep(0.05)
        assert handler.preemption_requested
        assert (handler.reason or "").startswith("watchdog-hang")
    finally:
        wd.stop()
        handler.uninstall()


_WEDGED_STEP_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    import numpy as np, optax
    from accelerate_tpu import Accelerator, DiagnosticsPlugin
    from accelerate_tpu.diagnostics import trace_span
    from accelerate_tpu.test_utils import RegressionModel

    project_dir = sys.argv[1]
    acc = Accelerator(
        project_dir=project_dir,
        telemetry=True,
        fault_tolerance=True,
        diagnostics=DiagnosticsPlugin(
            watchdog_floor_seconds=0.6,
            watchdog_check_seconds=0.05,
            watchdog_multiplier=3.0,
            preempt_on_hang=True,
        ),
    )
    model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.adam(0.05))
    x = np.arange(16, dtype=np.float32)
    for step in range(100):
        out = model(x=x, y=2 * x + 3)
        acc.backward(out.loss)   # checks the preemption flag at the boundary
        opt.step(); opt.zero_grad()
        if step == 2:
            print("WEDGING", flush=True)
            with trace_span("collective/wedged_allreduce"):
                time.sleep(2.5)  # >> deadline: the watchdog must fire here
    print("UNREACHABLE_COMPLETED", flush=True)
    """
)


def test_wedged_step_subprocess_exits_with_hang_report(tmp_path):
    """End-to-end acceptance: an artificially wedged step in a real loop →
    the watchdog writes HANG_REPORT naming the stalled phase AND raises the
    preemption flag, so the run emergency-saves and exits cleanly (143)
    instead of burning the slice."""
    script = tmp_path / "wedged.py"
    script.write_text(_WEDGED_STEP_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "proj")],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert "UNREACHABLE_COMPLETED" not in proc.stdout
    assert proc.returncode == 143, proc.stderr[-2000:]
    report_path = tmp_path / "proj" / "HANG_REPORT_0.json"
    assert report_path.exists(), proc.stderr[-2000:]
    report = json.load(open(report_path))
    assert report["stalled_phase"] == "collective/wedged_allreduce"
    assert report["threads"]  # all-thread stacks captured
    # PR 2's machinery finished the job: sentinel + emergency checkpoint
    sentinel = tmp_path / "proj" / "checkpoints" / "PREEMPTED.json"
    assert sentinel.exists()
    assert json.load(open(sentinel))["reason"].startswith("watchdog-hang")


# ---------------------------------------------------------------------------
# monitor
# ---------------------------------------------------------------------------


def _write_heartbeat(tmp_path, host, step, ts, ema=0.1, fired=False):
    hb_dir = tmp_path / "diagnostics"
    hb_dir.mkdir(exist_ok=True)
    (hb_dir / f"heartbeat_{host}.json").write_text(
        json.dumps(
            {"host": host, "pid": 1, "step": step, "ts": ts,
             "ema_step_s": ema, "last_step_s": ema, "fired": fired}
        )
    )


def test_monitor_collect_status_names_wedged_and_stragglers(tmp_path):
    now = 10_000.0
    _write_heartbeat(tmp_path, 0, step=100, ts=now - 1)          # healthy leader
    _write_heartbeat(tmp_path, 1, step=60, ts=now - 2)           # behind on steps
    _write_heartbeat(tmp_path, 2, step=100, ts=now - 500)        # heartbeat-silent
    status = collect_status(str(tmp_path), now=now)
    assert [h["host"] for h in status["hosts"]] == [0, 1, 2]
    assert status["wedged"] == [2]
    assert status["stragglers"] == [1]
    text = render_status(status)
    assert "WEDGED" in text and "STRAGGLER" in text


def test_monitor_reads_telemetry_tail_and_hang_reports(tmp_path):
    tel_dir = tmp_path / "telemetry"
    tel_dir.mkdir()
    now = time.time()
    with open(tel_dir / "telemetry.jsonl", "w") as f:
        for i in range(30):
            f.write(json.dumps({
                "type": "step", "step": i + 1, "optimizer_steps": i + 1,
                "step_time_s": 0.25, "recompiles": 2, "mfu": 0.41,
                "tokens_per_sec": 1000.0, "ts": now,
            }) + "\n")
    (tmp_path / "HANG_REPORT_3.json").write_text(
        json.dumps({"host": 3, "stalled_phase": "collective/gather",
                    "elapsed_s": 99.0, "ts": now})
    )
    status = collect_status(str(tmp_path), now=now)
    assert status["steps"] == 30
    assert status["step_rate"] == pytest.approx(4.0)
    assert status["mfu"] == pytest.approx(0.41)
    assert status["recompiles"] == 2
    assert status["hang_reports"][0]["stalled_phase"] == "collective/gather"
    assert "HANG" in render_status(status)


def test_monitor_cli_once_flags_unhealthy_run(tmp_path, capsys):
    from accelerate_tpu.commands.accelerate_cli import main

    assert main(["monitor", str(tmp_path), "--once"]) == 0
    (tmp_path / "HANG_REPORT_0.json").write_text(
        json.dumps({"host": 0, "stalled_phase": "x", "elapsed_s": 1.0})
    )
    assert main(["monitor", str(tmp_path), "--once"]) == 2
    assert "HANG" in capsys.readouterr().out


def test_trace_merge_cli(tmp_path):
    from accelerate_tpu.commands.accelerate_cli import main

    tracer = Tracer(logging_dir=str(tmp_path), host=0)
    with tracer.span("phase"):
        pass
    tracer.close()
    out = tmp_path / "merged.json"
    assert main(["trace", "merge", str(tmp_path), "-o", str(out)]) == 0
    validate_chrome_trace(json.load(open(out)))


# ---------------------------------------------------------------------------
# telemetry satellites
# ---------------------------------------------------------------------------


def test_summary_survives_empty_ring_buffer():
    rec = TelemetryRecorder(logging_dir=None, memory_interval=0)
    try:
        s = rec.summary()  # no records at all: must not warn or NaN
        assert s["steps"] == 0 and "step_time_s" not in s
        from accelerate_tpu.telemetry import _percentiles

        assert _percentiles([]) == {}
    finally:
        rec.close()


def test_unknown_skip_counted_separately():
    rec = TelemetryRecorder(logging_dir=None, memory_interval=0)
    try:
        rec.record_step(dispatch_s=0.01, skipped=False)
        rec.record_step(dispatch_s=0.01, skipped=None)   # fp16 flag on device
        rec.record_step(dispatch_s=0.01, skipped=None)
        rec.record_step(dispatch_s=0.01, skipped=True)
        s = rec.summary()
        assert s["unknown_skip"] == 2
        assert s["skipped_steps"] == 1
        # unknowns optimistically count toward optimizer_steps; true skips don't
        assert s["optimizer_steps"] == 3
        records = [r for r in rec.records if r["type"] == "step"]
        assert [r["skipped"] for r in records] == [False, None, None, True]
    finally:
        rec.close()


def test_close_is_idempotent_and_atexit_registered(tmp_path):
    import atexit

    rec = TelemetryRecorder(logging_dir=str(tmp_path), memory_interval=0)
    rec.record_event("x")
    rec.close()
    rec.close()  # second close must be a no-op, not an error
    assert rec.jsonl_path and os.path.exists(rec.jsonl_path)
    # after close, atexit must hold no reference (unregister happened);
    # registering/unregistering again proves the pair is balanced
    atexit.unregister(rec.close)  # no-op if already unregistered
    records = [json.loads(line) for line in open(rec.jsonl_path)]
    assert records[-1]["kind"] == "x"


def test_compile_records_carry_mono_timestamps(tmp_path):
    """Compile records keep wall-clock ``ts`` and add monotonic phase
    timestamps (the trace clock) — the contract trace export relies on."""
    acc, model, opt, dl = _toy(tmp_path, telemetry=True)
    _train(acc, model, opt, dl)
    compiles = [r for r in acc.telemetry.records if r["type"] == "compile"]
    assert compiles
    for r in compiles:
        assert r["ts"] > 1e9  # wall clock
        mono = r["mono"]
        assert mono["lower_start"] <= mono["compile_start"] <= mono["compile_end"]
    acc.telemetry.close()
