"""Fused paged attention + quantized KV storage (``ops/paged_attention.py``,
the ``ops/fp8.py`` KV quantize helpers, and the quantizing
``write_paged_kv``).

All ops-level and tier-1: tiny shapes, CPU-cheap. The parity contract is
layered — the fused lax walk must match the gather-then-dense reference to
f32 noise at float storage, and the quantized paths must match the f32
reference within the documented per-dtype tolerances (these same numbers
gate the engine-level matrix in ``tests/test_serving.py`` and are quoted in
``docs/source/usage_guides/serving.md``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from accelerate_tpu.ops.fp8 import (
    dequantize_kv,
    kv_qmax,
    kv_storage_dtype,
    quantize_kv_rows,
)
from accelerate_tpu.ops.layers import cached_attention, write_paged_kv
from accelerate_tpu.ops.paged_attention import (
    paged_attention,
    pallas_paged_attention_available,
)

#: ops-level |fused_quantized - f32_reference| ceilings on attention
#: outputs (unit-variance inputs). int8 carries ~0.4% relative error per
#: row (7-bit mantissa + rounding), fp8 e4m3 ~3% (3-bit mantissa).
KV_ATOL = {"int8": 0.05, "fp8": 0.12}


def _skip_without_fp8(name: str) -> None:
    """fp8 storage is a documented graceful-degradation path
    (kv_storage_dtype raises a guidance error where f8 casts don't
    lower) — its test legs must skip there, not fail."""
    if name == "fp8":
        from accelerate_tpu.utils.compat import has_fp8_storage

        if not has_fp8_storage():
            pytest.skip("float8_e4m3fn storage unsupported on this jax stack")


def _filled_pools(rng, *, b=3, n_kv=4, hd=16, bs=4, nb=12, mb=5, idx=(9, 6, 14),
                  dtype=None):
    """Pools written position-by-position through real block tables: the
    f32 pools are ground truth; quantized pools (dtype given) are written
    through the same scatter with scale arrays."""
    bt = np.zeros((b, mb), np.int32)
    used = iter(range(1, nb))
    for i, ix in enumerate(idx):
        for j in range((ix // bs) + 1):
            bt[i, j] = next(used)
    idx = np.asarray(idx, np.int32)
    kpf = jnp.zeros((nb, bs, n_kv, hd), jnp.float32)
    vpf = jnp.zeros_like(kpf)
    q_pools = None
    if dtype is not None:
        kp = jnp.zeros((nb, bs, n_kv, hd), dtype)
        vp = jnp.zeros_like(kp)
        ks = jnp.ones((nb, bs, n_kv), jnp.float32)
        vs = jnp.ones_like(ks)
        q_pools = (kp, vp, ks, vs)
    for p in range(int(idx.max()) + 1):
        k = jnp.asarray(rng.normal(size=(b, 1, n_kv, hd)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, 1, n_kv, hd)).astype(np.float32))
        mask = np.asarray([[p <= ix] for ix in idx])
        pos = np.full((b, 1), p, np.int32)
        kpf, vpf = write_paged_kv(kpf, vpf, k, v, bt, pos, write_mask=mask)
        if q_pools is not None:
            q_pools = write_paged_kv(
                *q_pools[:2], k, v, bt, pos, write_mask=mask,
                k_scale_l=q_pools[2], v_scale_l=q_pools[3],
            )
    return bt, idx, (kpf, vpf), q_pools


def test_fused_lax_matches_gather_reference():
    """The scan-over-blocks online softmax equals the PR 4
    gather-then-``cached_attention`` path to f32 noise — decode (s=1) and
    prefill-chunk (s>1) query shapes, GQA heads."""
    rng = np.random.default_rng(0)
    bt, idx, (kpf, vpf), _ = _filled_pools(rng)
    for s, offs in ((1, 0), (4, 3)):
        q = jnp.asarray(rng.normal(size=(3, s, 8, 16)).astype(np.float32))
        qi = np.maximum(idx - offs, 0)
        ref = paged_attention(q, kpf, vpf, bt, qi, impl="gather")
        fused = paged_attention(q, kpf, vpf, bt, qi, impl="lax")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_kernel_matches_gather_reference():
    """The Pallas block-table kernel (interpret mode off-TPU) computes the
    same attention as the gather reference."""
    if not pallas_paged_attention_available():
        pytest.skip("pallas paged-attention kernel unavailable on this stack")
    rng = np.random.default_rng(1)
    bt, idx, (kpf, vpf), _ = _filled_pools(rng)
    q = jnp.asarray(rng.normal(size=(3, 1, 8, 16)).astype(np.float32))
    ref = paged_attention(q, kpf, vpf, bt, idx, impl="gather")
    out = paged_attention(q, kpf, vpf, bt, idx, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("name", ["int8", "fp8"])
def test_quantized_pool_within_tolerance(name):
    """Quantize-on-scatter + in-register dequantize: every impl agrees
    with the f32 reference within the documented per-dtype ceiling, and
    the quantized impls agree with each other much tighter (same stored
    bytes, same math)."""
    _skip_without_fp8(name)
    dtype, quantized = kv_storage_dtype(name)
    assert quantized
    rng = np.random.default_rng(2)
    bt, idx, (kpf, vpf), (kp, vp, ks, vs) = _filled_pools(rng, dtype=dtype)
    q = jnp.asarray(rng.normal(size=(3, 1, 8, 16)).astype(np.float32))
    ref = np.asarray(paged_attention(q, kpf, vpf, bt, idx, impl="gather"))
    outs = {}
    impls = ["lax", "gather"]
    if pallas_paged_attention_available():
        impls.append("pallas")
    for impl in impls:
        out = np.asarray(paged_attention(
            q, kp, vp, bt, idx, k_scale_l=ks, v_scale_l=vs, impl=impl
        ))
        assert np.abs(out - ref).max() < KV_ATOL[name], (
            f"{name}/{impl} exceeded the documented tolerance"
        )
        outs[impl] = out
    np.testing.assert_allclose(outs["lax"], outs["gather"], rtol=1e-4, atol=1e-4)


def test_quantized_write_respects_mask_and_drop():
    """Masked lanes and out-of-range positions drop payload AND scale
    writes — the scale array can never disagree with the pool about which
    rows are real."""
    nb, bs, n_kv, hd = 4, 4, 2, 8
    kp = jnp.zeros((nb, bs, n_kv, hd), jnp.int8)
    vp = jnp.zeros_like(kp)
    ks = jnp.ones((nb, bs, n_kv), jnp.float32)
    vs = jnp.ones_like(ks)
    bt = np.asarray([[1, 2]], np.int32)
    k = jnp.full((1, 2, n_kv, hd), 5.0)
    v = jnp.full((1, 2, n_kv, hd), 5.0)
    # lane 0 real at position 1, lane 1 masked; then a position past the
    # table span (must drop, not clamp)
    kp, vp, ks, vs = write_paged_kv(
        kp, vp, k, v, bt, np.asarray([[1, 2]], np.int32),
        write_mask=np.asarray([[True, False]]), k_scale_l=ks, v_scale_l=vs,
    )
    kp, vp, ks, vs = write_paged_kv(
        kp, vp, k, v, bt, np.asarray([[98, 99]], np.int32),
        write_mask=np.asarray([[True, True]]), k_scale_l=ks, v_scale_l=vs,
    )
    kp_h, ks_h = np.asarray(kp), np.asarray(ks)
    assert kp_h[1, 1].any() and ks_h[1, 1, 0] != 1.0   # the real write landed
    assert not kp_h[1, 2].any() and ks_h[1, 2, 0] == 1.0  # masked lane dropped
    assert not kp_h[2].any() and (ks_h[2] == 1.0).all()   # past-span dropped
    assert not kp_h[0].any() and not kp_h[3].any()


def test_quantize_round_trip_and_zero_rows():
    from accelerate_tpu.utils.compat import has_fp8_storage

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(5, 7, 16)).astype(np.float32)) * 3.0
    for name in ("int8", "fp8") if has_fp8_storage() else ("int8",):
        dtype, _ = kv_storage_dtype(name)
        q, scale = quantize_kv_rows(x, dtype)
        back = np.asarray(dequantize_kv(q, scale))
        # per-row amax scaling: relative error bounded by the format's step
        rel = np.abs(back - np.asarray(x)).max() / np.abs(np.asarray(x)).max()
        assert rel < (0.005 if name == "int8" else 0.04)
    # all-zero rows keep scale 1 and dequantize to exactly 0
    z = jnp.zeros((2, 3, 8))
    q, scale = quantize_kv_rows(z, jnp.int8)
    assert (np.asarray(scale) == 1.0).all()
    assert not np.asarray(dequantize_kv(q, scale)).any()


def test_kv_storage_dtype_policy():
    assert kv_storage_dtype("bf16") == (jnp.bfloat16, False)
    assert kv_storage_dtype("f32") == (jnp.float32, False)
    assert kv_storage_dtype("int8") == (jnp.int8, True)
    assert kv_qmax(jnp.int8) == 127.0
    with pytest.raises(ValueError, match="unknown kv_dtype"):
        kv_storage_dtype("int4")
    with pytest.raises(ValueError, match="not a quantized"):
        kv_qmax(jnp.float32)


def test_cached_attention_gqa_grouped_einsum_matches_repeat():
    """The grouped-head einsum equals the materialised ``jnp.repeat``
    formulation to f32 noise (the satellite fix: repeated KV is never
    built). Reference computed inline with explicit repeat."""
    import jax

    rng = np.random.default_rng(4)
    b, s, nh, n_kv, hd, mc = 2, 3, 8, 2, 16, 24
    q = jnp.asarray(rng.normal(size=(b, s, nh, hd)).astype(np.float32))
    kc = jnp.asarray(rng.normal(size=(b, mc, n_kv, hd)).astype(np.float32))
    vc = jnp.asarray(rng.normal(size=(b, mc, n_kv, hd)).astype(np.float32))
    idx = np.asarray([7, 15], np.int32)

    got = cached_attention(q, kc, vc, idx)

    kr = jnp.repeat(kc, nh // n_kv, axis=2)
    vr = jnp.repeat(vc, nh // n_kv, axis=2)
    q_pos = idx[:, None] + np.arange(s)[None, :]
    valid = np.arange(mc)[None, None, :] <= q_pos[:, :, None]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr) / np.sqrt(float(hd))
    scores = jnp.where(valid[:, None, :, :], scores, jnp.finfo(jnp.float32).min)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1), vr)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_paged_attention_unknown_impl_raises():
    q = jnp.zeros((1, 1, 2, 4))
    kp = jnp.zeros((3, 2, 1, 4))
    with pytest.raises(ValueError, match="unknown paged attention impl"):
        paged_attention(q, kp, kp, np.zeros((1, 2), np.int32),
                        np.zeros((1,), np.int32), impl="cuda")
