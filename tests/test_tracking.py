"""Tracker zoo unit tests (reference ``tests/test_tracking.py``, 535 LoC —
the examples cover the end-to-end flow; these pin the module contracts:
the GeneralTracker ABC, filter_trackers resolution, availability gating,
and the Accelerator facade round-trip)."""

import json
import os

import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.tracking import (
    LOGGER_TYPE_TO_CLASS,
    GeneralTracker,
    filter_trackers,
)


class JSONTracker(GeneralTracker):
    """Custom tracker the reference docs model: log to a jsonl file."""

    name = "json_test"
    requires_logging_directory = False

    def __init__(self, path):
        super().__init__()
        self.path = path
        self.config = None

    @property
    def tracker(self):
        return self

    def store_init_configuration(self, values):
        self.config = dict(values)

    def log(self, values, step=None, **kwargs):
        with open(self.path, "a") as f:
            f.write(json.dumps({"step": step, **values}) + "\n")


def test_zoo_has_all_seven_reference_trackers():
    assert set(LOGGER_TYPE_TO_CLASS) == {
        "tensorboard", "wandb", "mlflow", "comet_ml", "aim", "clearml", "dvclive",
    }
    for cls in LOGGER_TYPE_TO_CLASS.values():
        assert issubclass(cls, GeneralTracker)
        assert isinstance(cls.requires_logging_directory, bool)


def test_filter_trackers_resolution_rules():
    assert filter_trackers(None) == []
    custom = JSONTracker("/dev/null")
    # instances pass through; unknown names raise; unavailable names skip
    assert filter_trackers(custom) == [custom]
    with pytest.raises(ValueError, match="unknown tracker"):
        filter_trackers("not_a_tracker")
    # "all" keeps instances and only-available built-ins
    resolved = filter_trackers(["all", custom], logging_dir="/tmp")
    assert custom in resolved


def test_logging_dir_requirement_enforced():
    from accelerate_tpu.tracking import _AVAILABILITY

    needs_dir = [
        name for name, cls in LOGGER_TYPE_TO_CLASS.items()
        if cls.requires_logging_directory and _AVAILABILITY[name]()
    ]
    for name in needs_dir:
        with pytest.raises(ValueError, match="logging_dir"):
            filter_trackers(name, logging_dir=None)


def test_accelerator_tracker_facade_roundtrip(tmp_path):
    path = tmp_path / "log.jsonl"
    tracker = JSONTracker(str(path))
    acc = Accelerator(log_with=tracker)
    acc.init_trackers("proj", config={"lr": 0.1})
    assert tracker.config == {"lr": 0.1}
    acc.log({"loss": 1.5}, step=0)
    acc.log({"loss": 0.5}, step=1)
    acc.end_training()

    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert rows == [{"step": 0, "loss": 1.5}, {"step": 1, "loss": 0.5}]
    # get_tracker by name; unwrap returns the underlying client
    got = acc.get_tracker("json_test")
    assert got is tracker or getattr(got, "tracker", None) is tracker


def test_tensorboard_tracker_writes_event_files(tmp_path):
    try:
        import torch.utils.tensorboard  # noqa: F401
    except ImportError:
        try:
            import tensorboardX  # noqa: F401
        except ImportError:
            pytest.skip("no SummaryWriter backend installed")
    acc = Accelerator(log_with="tensorboard", project_dir=str(tmp_path))
    acc.init_trackers("run1", config={"lr": 0.1})
    acc.log({"loss": 1.0}, step=0)
    acc.end_training()
    written = [
        os.path.join(root, f)
        for root, _, files in os.walk(tmp_path)
        for f in files
    ]
    assert any("events" in os.path.basename(f) for f in written), written
