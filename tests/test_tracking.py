"""Tracker zoo unit tests (reference ``tests/test_tracking.py``, 535 LoC —
the examples cover the end-to-end flow; these pin the module contracts:
the GeneralTracker ABC, filter_trackers resolution, availability gating,
and the Accelerator facade round-trip)."""

import json
import os

import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.tracking import (
    LOGGER_TYPE_TO_CLASS,
    GeneralTracker,
    filter_trackers,
)


class JSONTracker(GeneralTracker):
    """Custom tracker the reference docs model: log to a jsonl file."""

    name = "json_test"
    requires_logging_directory = False

    def __init__(self, path):
        super().__init__()
        self.path = path
        self.config = None

    @property
    def tracker(self):
        return self

    def store_init_configuration(self, values):
        self.config = dict(values)

    def log(self, values, step=None, **kwargs):
        with open(self.path, "a") as f:
            f.write(json.dumps({"step": step, **values}) + "\n")


def test_zoo_has_reference_trackers_plus_jsonl():
    assert set(LOGGER_TYPE_TO_CLASS) == {
        "tensorboard", "wandb", "mlflow", "comet_ml", "aim", "clearml", "dvclive",
        "jsonl",
    }
    for cls in LOGGER_TYPE_TO_CLASS.values():
        assert issubclass(cls, GeneralTracker)
        assert isinstance(cls.requires_logging_directory, bool)


def test_jsonl_tracker_by_name_roundtrip(tmp_path):
    """The built-in "jsonl" tracker resolves by string name and appends one
    parseable JSON object per log call."""
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("run1", config={"lr": 0.1})
    acc.log({"loss": 1.5, "nested": {"acc": 0.5}}, step=0)
    acc.log({"loss": 0.5}, step=1)
    acc.end_training()

    path = tmp_path / "run1" / "metrics.jsonl"
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert rows[0] == {"event": "init", "config": {"lr": 0.1}}
    assert rows[1]["step"] == 0 and rows[1]["loss"] == 1.5
    assert rows[1]["nested/acc"] == 0.5
    assert rows[2]["step"] == 1 and rows[2]["loss"] == 0.5


def test_filter_trackers_resolution_rules():
    assert filter_trackers(None) == []
    custom = JSONTracker("/dev/null")
    # instances pass through; unknown names raise; unavailable names skip
    assert filter_trackers(custom) == [custom]
    with pytest.raises(ValueError, match="unknown tracker"):
        filter_trackers("not_a_tracker")
    # "all" keeps instances and only-available built-ins
    resolved = filter_trackers(["all", custom], logging_dir="/tmp")
    assert custom in resolved


def test_logging_dir_requirement_enforced():
    from accelerate_tpu.tracking import _AVAILABILITY

    needs_dir = [
        name for name, cls in LOGGER_TYPE_TO_CLASS.items()
        if cls.requires_logging_directory and _AVAILABILITY[name]()
    ]
    for name in needs_dir:
        with pytest.raises(ValueError, match="logging_dir"):
            filter_trackers(name, logging_dir=None)


def test_accelerator_tracker_facade_roundtrip(tmp_path):
    path = tmp_path / "log.jsonl"
    tracker = JSONTracker(str(path))
    acc = Accelerator(log_with=tracker)
    acc.init_trackers("proj", config={"lr": 0.1})
    assert tracker.config == {"lr": 0.1}
    acc.log({"loss": 1.5}, step=0)
    acc.log({"loss": 0.5}, step=1)
    acc.end_training()

    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert rows == [{"step": 0, "loss": 1.5}, {"step": 1, "loss": 0.5}]
    # get_tracker by name; unwrap returns the underlying client
    got = acc.get_tracker("json_test")
    assert got is tracker or getattr(got, "tracker", None) is tracker


def _fake_wandb(calls):
    import types

    fake = types.ModuleType("wandb")

    class FakeTable:
        def __init__(self, columns=None, data=None, dataframe=None):
            self.columns, self.data, self.dataframe = columns, data, dataframe

    class FakeImage:
        def __init__(self, img):
            self.img = img

    class FakeRun:
        def log(self, values, step=None, **kw):
            calls.append(("log", values, step))

        def finish(self):
            calls.append(("finish",))

    fake.Table, fake.Image = FakeTable, FakeImage
    fake.init = lambda project=None, **kw: FakeRun()
    fake.config = types.SimpleNamespace(update=lambda *a, **k: None)
    return fake


def test_wandb_log_table_and_images(monkeypatch):
    """log_table wraps into a wandb.Table, log_images into wandb.Image
    (reference tracking.py:341,360)."""
    import sys

    import numpy as np

    calls = []
    monkeypatch.setitem(sys.modules, "wandb", _fake_wandb(calls))
    from accelerate_tpu.tracking import WandBTracker

    t = WandBTracker("proj")
    t.log_table("preds", columns=["x", "y"], data=[[1, 2]], step=4)
    t.log_images({"samples": [np.zeros((2, 2, 3))]}, step=5)

    (_, tbl_values, tbl_step), (_, img_values, img_step) = calls
    assert tbl_step == 4 and img_step == 5
    table = tbl_values["preds"]
    assert table.columns == ["x", "y"] and table.data == [[1, 2]]
    assert [type(i).__name__ for i in img_values["samples"]] == ["FakeImage"]


def test_clearml_log_table_and_images(monkeypatch):
    """log_table reports [columns]+rows (or a dataframe); log_images routes
    through report_image with title/series split (reference
    tracking.py:804,822)."""
    import sys
    import types

    import numpy as np

    reports = []

    class FakeLogger:
        def report_table(self, title, series, table_plot, iteration=None, **kw):
            reports.append(("table", title, series, table_plot, iteration))

        def report_image(self, title, series, iteration=None, image=None, **kw):
            reports.append(("image", title, series, image, iteration))

    class FakeTask:
        def get_logger(self):
            return FakeLogger()

        def close(self):
            pass

    fake = types.ModuleType("clearml")
    fake.Task = types.SimpleNamespace(init=lambda project_name=None, **kw: FakeTask())
    monkeypatch.setitem(sys.modules, "clearml", fake)
    from accelerate_tpu.tracking import ClearMLTracker

    t = ClearMLTracker("proj")
    t.log_table("eval/preds", columns=["a"], data=[[1], [2]], step=7)
    img = np.zeros((2, 2))
    t.log_images({"viz/recon": img}, step=8)
    with pytest.raises(ValueError, match="data"):
        t.log_table("bad")

    assert reports[0] == ("table", "eval", "preds", [["a"], [1], [2]], 7)
    kind, title, series, image, it = reports[1]
    assert (kind, title, series, it) == ("image", "viz", "recon", 8)
    assert image is img


def test_base_tracker_log_table_is_noop():
    t = JSONTracker("/dev/null")
    assert t.log_table("anything", data=[[1]]) is None


def test_tensorboard_log_images_jsonl_fallback(tmp_path, monkeypatch):
    """Without a SummaryWriter backend the images land as .npy files next
    to the scalar JSONL."""
    import numpy as np

    from accelerate_tpu.tracking import TensorBoardTracker

    t = TensorBoardTracker.__new__(TensorBoardTracker)
    GeneralTracker.__init__(t)
    t.writer = None
    t.logging_dir = str(tmp_path)
    t.log_images({"val/sample": np.zeros((2, 4, 4, 3))}, step=2)
    saved = os.listdir(tmp_path / "images")
    assert saved == ["val_sample_step2.npy"]


def test_tensorboard_tracker_writes_event_files(tmp_path):
    try:
        import torch.utils.tensorboard  # noqa: F401
    except ImportError:
        try:
            import tensorboardX  # noqa: F401
        except ImportError:
            pytest.skip("no SummaryWriter backend installed")
    acc = Accelerator(log_with="tensorboard", project_dir=str(tmp_path))
    acc.init_trackers("run1", config={"lr": 0.1})
    acc.log({"loss": 1.0}, step=0)
    acc.end_training()
    written = [
        os.path.join(root, f)
        for root, _, files in os.walk(tmp_path)
        for f in files
    ]
    assert any("events" in os.path.basename(f) for f in written), written
