"""Telemetry subsystem tests (no reference analog — the reference's
observability is host-side tracking only): recompile counting under forced
static-shape changes, JSONL schema round-trip, summary percentiles,
strict-no-op disabled mode, and tracker fan-out with main-process gating."""

import json
import os

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.telemetry import (
    NULL_TELEMETRY,
    TelemetryRecorder,
    get_active_recorder,
    set_active_recorder,
)
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, SimpleLoader


@pytest.fixture(autouse=True)
def _clear_telemetry_globals():
    """The recorder registers a process-wide compile callback + active
    recorder; tests must not leak them into each other."""
    yield
    from accelerate_tpu import lazy

    lazy.set_compile_callback(None)
    set_active_recorder(None)


def _train(acc, model, opt, dl, epochs=2):
    for epoch in range(epochs):
        for batch in dl:
            out = model(**batch)
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()


def _toy(tmp_path, telemetry=True, **kwargs):
    acc = Accelerator(project_dir=str(tmp_path), telemetry=telemetry, **kwargs)
    model, opt, dl = acc.prepare(
        RegressionModel(a=0.0, b=0.0),
        optax.sgd(0.1),
        SimpleLoader(RegressionDataset(length=64), batch_size=16),
    )
    return acc, model, opt, dl


def test_toy_loop_produces_jsonl_trail_and_summary(tmp_path):
    """The acceptance loop: step records + ≥1 compile event with FLOPs and
    collective-bytes fields; summary has percentiles and throughput."""
    acc, model, opt, dl = _toy(tmp_path)
    _train(acc, model, opt, dl)

    path = acc.telemetry.jsonl_path
    assert path and os.path.exists(path)
    records = [json.loads(line) for line in open(path)]
    compiles = [r for r in records if r["type"] == "compile"]
    steps = [r for r in records if r["type"] == "step"]
    assert len(compiles) >= 1
    assert "flops" in compiles[0] and "collective_bytes" in compiles[0]
    assert compiles[0]["lower_s"] >= 0 and compiles[0]["compile_s"] > 0
    assert len(steps) == 8
    for r in steps:
        assert r["step_time_s"] > 0 and r["dispatch_s"] > 0
        assert r["accum_phase"] == "sync" and r["sync_gradients"] is True
        assert r["examples"] == 16 and r["examples_per_sec"] > 0

    s = acc.telemetry.summary()
    assert s["steps"] == 8 and s["optimizer_steps"] == 8
    assert {"p50", "p95", "max"} <= set(s["step_time_s"])
    assert s["step_time_s"]["p50"] <= s["step_time_s"]["max"]
    assert s["examples_per_sec"] > 0
    assert s["recompiles"] >= 1


def test_recompile_count_tracks_distinct_static_shapes(tmp_path):
    """Feeding N distinct batch shapes through the same loop compiles N
    step programs — the recorder's recompile count must equal N."""
    acc, model, opt, _ = _toy(tmp_path)

    shapes = (16, 8, 4)
    for n in shapes:
        x = np.linspace(-1, 1, n).astype(np.float32)
        out = model(x=x, y=(2 * x + 3).astype(np.float32))
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()

    s = acc.telemetry.summary()
    assert s["recompiles"] == len(shapes)
    assert s["distinct_static_keys"] == len(shapes)
    # re-feeding an already-seen shape must NOT recompile
    x = np.linspace(-1, 1, 8).astype(np.float32)
    out = model(x=x, y=(2 * x + 3).astype(np.float32))
    acc.backward(out.loss)
    opt.step()
    opt.zero_grad()
    assert acc.telemetry.summary()["recompiles"] == len(shapes)


def test_summary_percentiles_from_synthetic_steps(tmp_path):
    rec = TelemetryRecorder(logging_dir=None, memory_interval=0)
    try:
        for ms in range(1, 101):  # 1..100 ms dispatch times
            rec._last_step_end = None  # isolate each step's own spans
            rec.record_step(dispatch_s=ms / 1000.0, device_s=0.0)
        s = rec.summary()
        assert s["steps"] == 100
        assert s["step_time_s"]["p50"] == pytest.approx(0.0505, rel=0.02)
        assert s["step_time_s"]["p95"] == pytest.approx(0.09505, rel=0.02)
        assert s["step_time_s"]["max"] == pytest.approx(0.1)
    finally:
        rec.close()


def test_disabled_mode_is_strict_noop(tmp_path):
    """telemetry=False: the accelerator holds the NULL singleton, no
    telemetry directory is created, no compile callback is registered."""
    from accelerate_tpu import lazy

    acc, model, opt, dl = _toy(tmp_path, telemetry=False)
    assert acc.telemetry is NULL_TELEMETRY
    assert not acc.telemetry
    assert lazy.get_compile_callback() is None
    _train(acc, model, opt, dl, epochs=1)
    assert acc.telemetry.summary() == {}
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry"))
    # the loop still trains
    assert float(np.asarray(model.params["a"])) != 0.0


def test_jsonl_schema_roundtrip(tmp_path):
    """Every record parses, carries type+ts, and the kinds the recorder
    claims to emit all appear for a loop that exercises them."""
    acc, model, opt, dl = _toy(tmp_path)
    acc.telemetry.memory_interval = 2  # force memory sampling in 8 steps
    _train(acc, model, opt, dl)
    acc.telemetry.record_event("custom", note="hello")
    records = [json.loads(line) for line in open(acc.telemetry.jsonl_path)]
    kinds = {r["type"] for r in records}
    assert {"step", "compile", "memory", "event"} <= kinds
    for r in records:
        assert "type" in r and "ts" in r
    mem = [r for r in records if r["type"] == "memory"][-1]
    assert "host_rss_bytes" in mem and "device_bytes_in_use" in mem


def test_tracker_fanout_with_main_process_gating(tmp_path, monkeypatch):
    """Telemetry metrics flow through Accelerator.log() into initialized
    trackers, prefixed telemetry/; a non-main process writes nothing —
    the same gate as tracking.on_main_process."""
    logged = []

    from accelerate_tpu.tracking import GeneralTracker

    class Capture(GeneralTracker):
        name = "capture"
        requires_logging_directory = False

        def __init__(self):
            super().__init__()

        def log(self, values, step=None, **kw):
            logged.append((values, step))

    tracker = Capture()
    acc, model, opt, dl = _toy(tmp_path, log_with=tracker)
    acc.init_trackers("proj")
    _train(acc, model, opt, dl, epochs=1)

    tel_logs = [v for v, _ in logged if any(k.startswith("telemetry/") for k in v)]
    assert tel_logs, "no telemetry records were fanned out to trackers"
    assert any("telemetry/step_time_s" in v for v in tel_logs)
    assert any("telemetry/flops" in v for v in tel_logs)  # compile events too

    # non-main process: the recorder's gate must suppress the fan-out
    from accelerate_tpu import telemetry as tel_mod

    monkeypatch.setattr(tel_mod, "_is_main_process", lambda: False)
    before = len(logged)
    acc.telemetry.record_step(dispatch_s=0.001, device_s=0.0)
    assert len(logged) == before


def test_generation_records_tokens_per_sec(tmp_path):
    """The decode loop reports through the process-wide active recorder."""
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    rec = TelemetryRecorder(logging_dir=str(tmp_path), memory_interval=0)
    set_active_recorder(rec)
    try:
        config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=1, heads=2, seq=32)
        model = LlamaForCausalLM.from_config(config, seed=0)
        ids = np.arange(8, dtype=np.int32)[None, :]
        out = generate(model, ids, max_new_tokens=4, use_cache=True)
        gen = [r for r in rec.records if r["type"] == "generate"]
        assert len(gen) == 1
        assert gen[0]["mode"] == "kv_cache"
        assert gen[0]["new_tokens"] == out.shape[1] - 8
        assert gen[0]["tokens_per_sec"] > 0
    finally:
        rec.close()
        assert get_active_recorder() is NULL_TELEMETRY


def test_speculative_decode_reports_accept_rate(tmp_path):
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    rec = TelemetryRecorder(logging_dir=None, memory_interval=0)
    set_active_recorder(rec)
    try:
        config = LlamaConfig.tiny(vocab_size=64, hidden_size=32, layers=1, heads=2, seq=64)
        model = LlamaForCausalLM.from_config(config, seed=0)
        draft = LlamaForCausalLM.from_config(config, seed=0)  # same model: all accepted
        ids = np.arange(8, dtype=np.int32)[None, :]
        generate(model, ids, max_new_tokens=8, draft_model=draft, num_draft_tokens=3)
        gen = [r for r in rec.records if r["type"] == "generate"]
        assert gen and gen[0]["mode"] == "speculative"
        assert gen[0]["verify_rounds"] >= 1
        assert 0.0 < gen[0]["accept_rate"] <= 1.0
    finally:
        rec.close()


def test_profile_session_emits_telemetry_record(tmp_path):
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    acc, model, opt, dl = _toy(tmp_path)
    handler = ProfileKwargs(wait=1, active=1, output_trace_dir=str(tmp_path / "trace"))
    with acc.profile(handler) as prof:
        _train(acc, model, opt, dl, epochs=1)
        for _ in range(3):
            prof.step()
    prof_records = [r for r in acc.telemetry.records if r["type"] == "profile"]
    assert len(prof_records) == 1
    assert prof_records[0]["steps"] == 3
    # wait/active cycle of 2: only the middle of the 3 steps was active
    assert prof_records[0]["active_steps"] == 1
    assert prof_records[0]["trace_dir"] == str(tmp_path / "trace")


def test_grad_accumulation_phase_recorded(tmp_path):
    from accelerate_tpu import GradientAccumulationPlugin

    acc = Accelerator(
        project_dir=str(tmp_path),
        telemetry=True,
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=2),
    )
    model, opt, dl = acc.prepare(
        RegressionModel(a=0.0, b=0.0),
        optax.sgd(0.1),
        SimpleLoader(RegressionDataset(length=64), batch_size=16),
    )
    for batch in dl:
        with acc.accumulate(model):
            out = model(**batch)
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()
    steps = [r for r in acc.telemetry.records if r["type"] == "step"]
    phases = [r["accum_phase"] for r in steps]
    assert "accumulate" in phases and "sync" in phases
    assert acc.telemetry.summary()["optimizer_steps"] == phases.count("sync")


def test_disabled_accelerator_silences_stale_recorder(tmp_path):
    """A new telemetry=False Accelerator must clear a prior instance's
    process-wide recorder + compile callback (Borg takeover), or 'disabled'
    keeps appending to the old run's trail."""
    from accelerate_tpu import lazy

    acc1, *_ = _toy(tmp_path / "run1", telemetry=True)
    assert get_active_recorder() is acc1.telemetry
    assert lazy.get_compile_callback() is not None

    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2 = Accelerator(telemetry=False)
    assert acc2.telemetry is NULL_TELEMETRY
    assert get_active_recorder() is NULL_TELEMETRY
    assert lazy.get_compile_callback() is None


def test_null_telemetry_survives_every_call():
    NULL_TELEMETRY.note_batch(1, 2)
    NULL_TELEMETRY.note_backward(0.1)
    NULL_TELEMETRY.record_step(dispatch_s=0.1)
    NULL_TELEMETRY.record_generation("full", 1, 0.1)
    NULL_TELEMETRY.record_profile("/tmp", 1)
    NULL_TELEMETRY.record_event("k")
    NULL_TELEMETRY.record_memory()
    NULL_TELEMETRY.close()
    assert NULL_TELEMETRY.summary() == {}
    assert not NULL_TELEMETRY
