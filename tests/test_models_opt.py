"""OPT family: training on sharded meshes, streaming offload, pipeline
inference, numerical parity against HF-transformers' torch OPT (reference
exposure: OPT-30B rows of ``benchmarks/big_model_inference/README.md:36-37``)."""

import jax
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshPlugin, prepare_pippy
from accelerate_tpu.big_modeling import cpu_offload
from accelerate_tpu.models.opt import (
    OPTConfig,
    OPTForCausalLM,
    convert_hf_opt_state_dict,
)

pytestmark = pytest.mark.slow  # compile-heavy: full-lane only (make test_all)


def _tiny(layers=2):
    config = OPTConfig.tiny(layers=layers)
    model = OPTForCausalLM.from_config(config, seed=1)
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    return config, model, ids


def test_forward_shapes_and_loss():
    config, model, ids = _tiny()
    out = model.apply_fn(model.params, input_ids=ids, labels=ids)
    assert out["logits"].shape == (2, 16, 256)
    assert np.isfinite(float(out["loss"]))


def test_training_on_sharded_mesh():
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    config = OPTConfig.tiny(layers=2)
    model, opt = accelerator.prepare(
        OPTForCausalLM.from_config(config, seed=0), optax.adamw(1e-2)
    )
    ids = np.random.default_rng(0).integers(0, 256, size=(8, 16)).astype(np.int32)
    losses = []
    for _ in range(5):
        out = model(input_ids=ids, labels=ids)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert losses[-1] < losses[0]


def test_streaming_offload_matches_resident():
    config, model, ids = _tiny()
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    out = cpu_offload(model)(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_inference_matches():
    config, model, ids = _tiny(layers=4)
    ref = model.apply_fn(model.params, input_ids=ids)["logits"]
    pipelined = prepare_pippy(
        model, example_kwargs={"input_ids": ids}, devices=jax.devices()[:2]
    )
    out = pipelined(input_ids=ids)
    np.testing.assert_allclose(np.asarray(out.logits), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_kv_cache_decode_matches_full_forward():
    config, model, ids = _tiny()
    full = model.apply_fn(model.params, input_ids=ids)["logits"]
    pre = model.apply_fn(
        model.params, input_ids=ids[:, :8], use_cache=True, max_cache_len=16
    )
    cache = pre["kv_cache"]
    logits = pre["logits"][:, -1:]
    outs = [logits]
    for t in range(8, 16):
        step = model.apply_fn(
            model.params,
            input_ids=ids[:, t : t + 1],
            kv_cache=cache,
            cache_index=np.full((2,), t, np.int32),
        )
        cache = step["kv_cache"]
        outs.append(step["logits"])
    decoded = np.concatenate([np.asarray(o) for o in outs], axis=1)
    np.testing.assert_allclose(
        decoded, np.asarray(full[:, 7:, :]), rtol=2e-4, atol=2e-4
    )


def test_parity_with_hf_transformers():
    """Logit-level parity against transformers' torch OPT built from the
    same (converted) weights: pins the HF ``[out, in]`` transpose and the
    legacy +2 position-embedding offset slicing. Run at ``highest`` matmul
    precision — XLA:CPU's default oneDNN fastmath matmul rounds at ~bf16."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    torch.manual_seed(0)
    hf_cfg = transformers.OPTConfig(
        vocab_size=256, hidden_size=64, ffn_dim=256, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=128,
        do_layer_norm_before=True, dropout=0.0, attention_dropout=0.0,
        activation_function="relu", word_embed_proj_dim=64,
    )
    hf = transformers.OPTForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    config = OPTConfig.tiny(layers=2)
    model = OPTForCausalLM.from_config(config)
    params = jax.tree.map(np.asarray, convert_hf_opt_state_dict(flat, config))
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    with jax.default_matmul_precision("highest"):
        ours = np.asarray(model.apply_fn(params, input_ids=ids)["logits"])
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(ids.astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_zoo_has_opt():
    from accelerate_tpu.models import MODEL_ZOO

    assert "opt-30b" in MODEL_ZOO and "opt-6.7b" in MODEL_ZOO
    # the benchmark-table flagship: ~30B params at the published shape
    import accelerate_tpu.big_modeling as bm

    cfg, factory = MODEL_ZOO["opt-30b"]
    with bm.init_empty_weights():
        meta = factory(cfg)
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(meta.params))
    assert 29e9 < n < 31e9
