"""Core-lane smoke slice of the compile-heavy subsystems (VERDICT r4 #9):
ONE cheapest config per path — model train step, GPipe schedule, flash
attention, generation, quantization — so a green default ``make test``
actually touches the compiled truth. The full per-subsystem matrices stay
in the slow lane (``make test_slow``); nothing here is marked slow."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshPlugin
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.state import AcceleratorState, GradientState


def _batch(b=4, s=16, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(b, s)).astype(np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


def _tiny_config(**kw):
    return LlamaConfig.tiny(vocab_size=128, hidden_size=32, layers=2, heads=2, seq=16, **kw)


def test_smoke_llama_train_step_reduces_loss():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator = Accelerator()
    model, opt = accelerator.prepare(
        LlamaForCausalLM.from_config(_tiny_config(), seed=0), optax.adamw(3e-3)
    )
    batch = _batch()
    losses = []
    for _ in range(3):
        out = model(**batch)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(float(np.asarray(out.loss.force())))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_smoke_pipeline_pp2_loss_matches_dense():
    from accelerate_tpu.mesh import build_mesh
    from accelerate_tpu.models.llama import init_llama_params, llama_apply
    from accelerate_tpu.ops.attention import attention_context

    c = _tiny_config()
    params = init_llama_params(jax.random.PRNGKey(0), c)
    batch = _batch()

    def loss_fn(p):
        return llama_apply(c, p, batch["input_ids"], labels=batch["labels"])["loss"]

    dense = float(loss_fn(params))
    mesh = build_mesh(MeshPlugin(pp=2))  # dp absorbs the remaining devices
    from accelerate_tpu.utils.compat import set_mesh

    with attention_context(mesh=mesh), set_mesh(mesh):
        piped = float(jax.jit(loss_fn)(params))
    assert piped == pytest.approx(dense, rel=1e-4)


def test_smoke_flash_attention_matches_blockwise():
    from accelerate_tpu.ops.flash_attention import blockwise_attention, flash_attention

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 32, 2, 16)), jnp.float32) for _ in range(3)
    )
    flash = flash_attention(q, k, v, causal=True, block_q=16, block_kv=16, interpret=True)
    block = blockwise_attention(q, k, v, causal=True, block_kv=16)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(block), rtol=2e-4, atol=2e-4)


def test_smoke_generation_greedy():
    from accelerate_tpu.generation import generate

    model = LlamaForCausalLM.from_config(_tiny_config(), seed=0)

    def fn(**kw):
        return model.apply_fn(model.params, **kw)

    ids = np.zeros((1, 4), np.int32)
    out = generate(fn, ids, max_new_tokens=3)
    assert out.shape == (1, 7)
    assert np.all(out[:, :4] == ids)


def test_smoke_quantization_roundtrip():
    from accelerate_tpu.utils.quantization import (
        dequantize_array,
        dequantize_array_4bit,
        quantize_array,
        quantize_array_4bit,
    )

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    int8_err = float(jnp.max(jnp.abs(dequantize_array(quantize_array(w)) - w)))
    assert int8_err < 0.05, int8_err
    nf4_err = float(jnp.max(jnp.abs(dequantize_array_4bit(quantize_array_4bit(w)) - w)))
    assert nf4_err < 0.5, nf4_err
