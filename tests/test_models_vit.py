"""ViT family (timm's ``vit_base_patch16_224`` — the standard CV
transformer the reference's users bring via timm)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models.vit import (
    ViTConfig,
    ViTForImageClassification,
    init_vit_params,
)
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.utils.dataclasses import MeshPlugin


def _batch(bsz=8, size=32, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "pixel_values": rng.standard_normal((bsz, size, size, 3)).astype(np.float32),
        "labels": rng.integers(0, classes, bsz).astype(np.int32),
    }


def test_vit_b16_param_count_matches_timm():
    cfg = ViTConfig.vit_b16(num_classes=1000)
    shapes = jax.eval_shape(lambda k: init_vit_params(k, cfg), jax.random.PRNGKey(0))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
    # timm vit_base_patch16_224: 86,567,656 params
    assert n == 86_567_656


def test_forward_shapes_and_nchw_acceptance():
    cfg = ViTConfig.tiny()
    model = ViTForImageClassification.from_config(cfg, seed=0)
    b = _batch()
    out = model.apply_fn(model.params, **b)
    assert out["logits"].shape == (8, 3)
    assert np.isfinite(float(out["loss"]))
    nchw = np.moveaxis(b["pixel_values"], -1, 1)
    out2 = model.apply_fn(model.params, pixel_values=nchw, labels=b["labels"])
    np.testing.assert_allclose(
        np.asarray(out2["logits"]), np.asarray(out["logits"]), rtol=1e-5, atol=1e-5
    )


def test_vit_trains_under_accelerator_mesh():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator = Accelerator(mesh_plugin=MeshPlugin(dp=2, fsdp=2, tp=2))
    model, opt = accelerator.prepare(
        ViTForImageClassification.from_config(ViTConfig.tiny(), seed=0),
        optax.adam(1e-3),
    )
    from accelerate_tpu.mesh import data_sharding

    sharding = data_sharding(accelerator.mesh)
    batch = {
        k: jax.device_put(jnp.asarray(v), sharding) for k, v in _batch().items()
    }
    losses = []
    for _ in range(5):
        out = model(**batch)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(float(np.asarray(out.loss.force())))
    assert losses[-1] < losses[0]
