"""Metrics subsystem tests: registry → OpenMetrics round-trip through the
strict parser (type lines, label escaping, histogram bucket monotonicity),
the goodput ledger's sum-to-wall invariant under synthetic span streams and
a real toy run, the sidecar exporter (incremental + rotation-proof
tailing), SLO alert rules with the monitor/exporter exit codes, and this
PR's satellites (telemetry JSONL rotation, schema versioning, trace merge
without clock_sync)."""

import json
import math
import os
import threading
import time
import urllib.request

import numpy as np
import optax
import pytest

from accelerate_tpu.metrics import (
    MetricsRegistry,
    NULL_REGISTRY,
    evaluate_alerts,
    get_active_registry,
    ledger_from_dir,
    ledger_from_events,
    parse_openmetrics,
    render_openmetrics,
    set_active_registry,
)
from accelerate_tpu.metrics.openmetrics import sample_value
from accelerate_tpu.telemetry import (
    SCHEMA_VERSION,
    TelemetryRecorder,
    schema_compatible,
    set_active_recorder,
    telemetry_segments,
)

E6 = 1e6  # trace timestamps are monotonic microseconds


@pytest.fixture(autouse=True)
def _clear_metrics_globals():
    """The registry/recorder/tracer are process-wide Borg state; tests must
    not leak them into each other."""
    yield
    from accelerate_tpu import lazy
    from accelerate_tpu.diagnostics import set_active_tracer

    set_active_registry(None)
    set_active_recorder(None)
    set_active_tracer(None)
    lazy.set_compile_callback(None)


# ---------------------------------------------------------------------------
# registry + OpenMetrics round-trip
# ---------------------------------------------------------------------------


def test_openmetrics_round_trip_counters_gauges_labels():
    reg = MetricsRegistry(gate_main_process=False)
    reg.counter("steps", "Training steps").inc()
    reg.counter("steps").inc(4)
    reg.gauge("mfu_ratio", "MFU").set(0.4175)
    # label escaping: backslash, quote, newline all survive the round trip
    nasty = 'quo"te\\back\nnewline'
    reg.counter("serving_requests", "done").inc(2, finish_reason=nasty)
    text = render_openmetrics(reg)
    families = parse_openmetrics(text)
    assert families["accelerate_steps"]["type"] == "counter"
    assert sample_value(families, "accelerate_steps") == 5
    assert sample_value(families, "accelerate_mfu_ratio") == pytest.approx(0.4175)
    assert sample_value(
        families, "accelerate_serving_requests", finish_reason=nasty
    ) == 2
    assert text.rstrip().endswith("# EOF")


def test_openmetrics_histogram_buckets_cumulative_and_inf():
    reg = MetricsRegistry(gate_main_process=False)
    h = reg.histogram("step_time_seconds", "per step", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.005, 0.05, 0.5, 99.0):  # last lands past every bound
        h.observe(v)
    families = parse_openmetrics(render_openmetrics(reg))
    fam = families["accelerate_step_time_seconds"]
    buckets = {
        labels["le"]: value
        for name, labels, value in fam["samples"]
        if name.endswith("_bucket")
    }
    assert buckets == {"0.01": 2, "0.1": 3, "1": 4, "+Inf": 5}
    assert sample_value(families, "accelerate_step_time_seconds",
                        "accelerate_step_time_seconds_count") == 5
    assert sample_value(families, "accelerate_step_time_seconds",
                        "accelerate_step_time_seconds_sum") == pytest.approx(99.56)


@pytest.mark.parametrize(
    "text",
    [
        "accelerate_x_total 1\n# EOF\n",  # sample without a declared family
        "# TYPE accelerate_x counter\naccelerate_x 1\n# EOF\n",  # counter w/o _total
        "# TYPE accelerate_x counter\naccelerate_x_total 1\n",  # missing # EOF
        '# TYPE a_h histogram\na_h_bucket{le="1"} 5\na_h_bucket{le="+Inf"} 3\n'
        "a_h_count 3\na_h_sum 1\n# EOF\n",  # non-monotonic buckets
        '# TYPE a_h histogram\na_h_bucket{le="1"} 2\na_h_count 2\na_h_sum 1\n'
        "# EOF\n",  # missing +Inf bucket
        '# TYPE a counter\na_total{l="bad\\q"} 1\n# EOF\n',  # bad escape
    ],
)
def test_strict_parser_rejects_malformed(text):
    with pytest.raises(ValueError):
        parse_openmetrics(text)


def test_counters_are_monotonic_and_kind_collisions_raise():
    reg = MetricsRegistry(gate_main_process=False)
    c = reg.counter("steps")
    with pytest.raises(ValueError):
        c.inc(-1)
    c.inc(10)
    c.set_total(5)  # ratchet: lower re-reads never move a counter back
    assert c.value() == 10
    c.set_total(15)
    assert c.value() == 15
    with pytest.raises(ValueError):
        reg.gauge("steps")  # already a counter


def test_null_registry_is_falsy_noop():
    assert not NULL_REGISTRY
    assert get_active_registry() is NULL_REGISTRY  # default state
    NULL_REGISTRY.counter("x").inc()
    NULL_REGISTRY.histogram("y").observe(1.0)
    assert NULL_REGISTRY.collect() == []
    assert parse_openmetrics(render_openmetrics(NULL_REGISTRY)) == {}


# ---------------------------------------------------------------------------
# goodput ledger
# ---------------------------------------------------------------------------


def _synthetic_events():
    """100s of wall: a 60s step span with a 20s compile INSIDE it, a 5s
    checkpoint, 3s of dataloader, a watchdog hang covering [90, 95], and a
    prepare span that must bill to idle."""
    return [
        {"ph": "X", "name": "step/dispatch", "ts": 0.0, "dur": 60 * E6},
        {"ph": "X", "name": "compile/compile", "ts": 10 * E6, "dur": 20 * E6},
        {"ph": "X", "name": "checkpoint/save", "ts": 70 * E6, "dur": 5 * E6},
        {"ph": "X", "name": "dataloader/fetch", "ts": 76 * E6, "dur": 3 * E6},
        {"ph": "i", "name": "watchdog/hang", "ts": 95 * E6, "args": {"elapsed_s": 5.0}},
        {"ph": "X", "name": "prepare", "ts": 99 * E6, "dur": 1 * E6},
    ]


def test_goodput_buckets_are_exclusive_and_sum_to_wall():
    ledger = ledger_from_events(_synthetic_events(), host=0)
    b = ledger["buckets_s"]
    assert ledger["elapsed_s"] == pytest.approx(100.0)
    # the compile overlap is billed to compile, NOT double-counted in step
    assert b["step"] == pytest.approx(40.0)
    assert b["compile"] == pytest.approx(20.0)
    assert b["checkpoint"] == pytest.approx(5.0)
    assert b["dataloader"] == pytest.approx(3.0)
    assert b["hang"] == pytest.approx(5.0)
    assert b["idle"] == pytest.approx(27.0)  # incl. the prepare second
    assert sum(b.values()) == pytest.approx(ledger["elapsed_s"], rel=1e-9)
    assert ledger["goodput_pct"] == pytest.approx(40.0)
    assert "step" not in ledger["lost_s_by_cause"]


def test_goodput_overlapping_same_bucket_spans_not_double_counted():
    events = [  # two step spans overlapping on [10, 20]: covered = 30s of 40
        {"ph": "X", "name": "step/dispatch", "ts": 0.0, "dur": 20 * E6},
        {"ph": "X", "name": "backward/dispatch", "ts": 10 * E6, "dur": 20 * E6},
        {"ph": "X", "name": "prepare", "ts": 30 * E6, "dur": 10 * E6},
    ]
    ledger = ledger_from_events(events)
    assert ledger["buckets_s"]["step"] == pytest.approx(30.0)
    assert ledger["buckets_s"]["idle"] == pytest.approx(10.0)
    assert sum(ledger["buckets_s"].values()) == pytest.approx(40.0, rel=1e-9)


def test_goodput_from_real_toy_run(tmp_path):
    """Acceptance bar: on a recorded trace fixture the buckets sum to the
    elapsed wall within 1%."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionModel

    acc = Accelerator(project_dir=str(tmp_path), telemetry=True, diagnostics=True)
    model, opt = acc.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
    x = np.linspace(-1, 1, 16).astype(np.float32)
    y = (2 * x + 3).astype(np.float32)
    for _ in range(20):
        out = model(x=x, y=y)
        acc.backward(out.loss)
        opt.step()
        opt.zero_grad()
    acc.end_training()

    ledger = ledger_from_dir(str(tmp_path))
    assert ledger is not None and ledger["hosts"] == 1
    total = sum(ledger["buckets_s"].values())
    assert total == pytest.approx(ledger["elapsed_s"], rel=0.01)
    assert ledger["buckets_s"]["step"] > 0  # the loop did productive work
    assert ledger["buckets_s"]["compile"] > 0  # and compiled at least once
    assert 0.0 < ledger["goodput_pct"] < 100.0


def test_goodput_none_without_traces(tmp_path):
    assert ledger_from_dir(str(tmp_path)) is None


def test_goodput_partitions_monotonic_epochs_at_clock_sync():
    """An auto-resumed run appends a SECOND monotonic epoch (fresh
    perf_counter origin + fresh clock_sync) to the same trail; raw
    timestamps across epochs are not comparable and must not be mixed into
    one giant elapsed window."""
    events = [
        {"ph": "M", "name": "clock_sync", "args": {"wall_minus_mono_s": 1.0}},
        # first life: mono 1000-2000s, 600s of step work
        {"ph": "X", "name": "step/dispatch", "ts": 1000 * E6, "dur": 600 * E6},
        {"ph": "X", "name": "prepare", "ts": 1600 * E6, "dur": 400 * E6},
        # restart: mono origin resets far BELOW the first epoch
        {"ph": "M", "name": "clock_sync", "args": {"wall_minus_mono_s": 2.0}},
        {"ph": "X", "name": "step/dispatch", "ts": 50 * E6, "dur": 100 * E6},
    ]
    ledger = ledger_from_events(events, host=0)
    assert ledger["epochs"] == 2
    # NOT max(ts)-min(ts) ≈ 1950s: each epoch attributed independently
    assert ledger["elapsed_s"] == pytest.approx(1000.0 + 100.0)
    assert ledger["buckets_s"]["step"] == pytest.approx(700.0)
    assert sum(ledger["buckets_s"].values()) == pytest.approx(
        ledger["elapsed_s"], rel=1e-9
    )
    assert ledger["goodput_pct"] == pytest.approx(700.0 / 1100.0 * 100.0)


def test_recompile_rate_needs_minimum_window(tmp_path):
    """One benign recompile in a seconds-wide trail must NOT extrapolate to
    an hours rate (MIN_RATE_WINDOW_S floor); a wide-enough trail computes
    the run-anchored rate from the cumulative field."""
    from accelerate_tpu.diagnostics.monitor import MIN_RATE_WINDOW_S, collect_status

    now = time.time()
    _write_fixture_rows(tmp_path, [
        {"type": "step", "step": 1, "optimizer_steps": 1, "step_time_s": 0.1,
         "recompiles": 0, "ts": now - 50, "schema": SCHEMA_VERSION},
        {"type": "compile", "total_s": 1.0, "ts": now, "schema": SCHEMA_VERSION},
        {"type": "step", "step": 2, "optimizer_steps": 2, "step_time_s": 0.1,
         "recompiles": 1, "ts": now, "schema": SCHEMA_VERSION},
    ])
    status = collect_status(str(tmp_path), now=now)
    assert status["recompiles_per_hour"] is None  # 50s window < floor

    (tmp_path / "telemetry" / "telemetry.jsonl").unlink()
    window = MIN_RATE_WINDOW_S * 2
    _write_fixture_rows(tmp_path, [
        {"type": "step", "step": 1, "optimizer_steps": 1, "step_time_s": 0.1,
         "recompiles": 0, "ts": now - window, "schema": SCHEMA_VERSION},
        {"type": "step", "step": 2, "optimizer_steps": 2, "step_time_s": 0.1,
         "recompiles": 2, "ts": now, "schema": SCHEMA_VERSION},
    ])
    status = collect_status(str(tmp_path), now=now)
    assert status["recompiles_per_hour"] == pytest.approx(2 / (window / 3600.0))


# ---------------------------------------------------------------------------
# in-process hooks (telemetry records + tracer spans → registry)
# ---------------------------------------------------------------------------


def test_telemetry_records_feed_active_registry():
    reg = MetricsRegistry(gate_main_process=False)
    set_active_registry(reg)
    rec = TelemetryRecorder(logging_dir=None, memory_interval=0)
    try:
        rec.record_step(dispatch_s=0.01)
        rec.record_step(dispatch_s=0.01, skipped=True)
        rec.record_checkpoint("save", seconds=1.5, bytes_written=1024)
        rec.record_serving(kind="request", ttft_s=0.2, new_tokens=8,
                           finish_reason="eos")
        rec.record_event("watchdog_hang", elapsed_s=9.0)
    finally:
        rec.close()
    assert reg.counter("steps").value() == 2
    assert reg.counter("skipped_steps").value() == 1
    assert reg.counter("checkpoints").value(kind="save") == 1
    assert reg.counter("checkpoint_bytes").value(kind="save") == 1024
    assert reg.counter("serving_requests").value(finish_reason="eos") == 1
    assert reg.counter("watchdog_hangs").value() == 1
    count, total = reg.histogram("step_time_seconds").value()
    assert count == 2 and total > 0
    # and the exposition of all of it round-trips strictly
    parse_openmetrics(render_openmetrics(reg))


def test_tracer_span_exits_feed_span_histogram(tmp_path):
    from accelerate_tpu.diagnostics import Tracer, set_active_tracer

    reg = MetricsRegistry(gate_main_process=False)
    set_active_registry(reg)
    tracer = Tracer(logging_dir=str(tmp_path), host=0)
    set_active_tracer(tracer)
    try:
        with tracer.span("collective/gather"):
            pass
        with tracer.span("collective/gather"):
            pass
    finally:
        tracer.close()
    count, _ = reg.histogram("span_seconds").value(name="collective/gather")
    assert count == 2


# ---------------------------------------------------------------------------
# satellite: telemetry JSONL rotation
# ---------------------------------------------------------------------------


def test_jsonl_rotation_caps_live_file_and_keeps_segments(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_TELEMETRY_MAX_BYTES", "600")
    monkeypatch.setenv("ACCELERATE_TELEMETRY_KEEP_SEGMENTS", "2")
    rec = TelemetryRecorder(logging_dir=str(tmp_path), memory_interval=0)
    try:
        for i in range(60):
            rec.record_event("filler", i=i, pad="x" * 40)
    finally:
        rec.close()
    jsonl = tmp_path / "telemetry" / "telemetry.jsonl"
    segments = telemetry_segments(str(jsonl))
    # keep=2 rotated segments + the live file, oldest first
    assert [os.path.basename(p) for p in segments] == [
        "telemetry.jsonl.2", "telemetry.jsonl.1", "telemetry.jsonl",
    ]
    assert not (tmp_path / "telemetry" / "telemetry.jsonl.3").exists()
    assert os.path.getsize(jsonl) <= 600 + 200  # one record of slack
    # every segment is intact JSONL and the newest record is in the live file
    rows = [json.loads(line) for p in segments for line in open(p)]
    assert rows[-1]["i"] == 59
    # the trail is contiguous from the newest surviving record backwards
    kept = [r["i"] for r in rows]
    assert kept == list(range(kept[0], 60))


def test_monitor_tail_reads_across_rotated_segments(tmp_path, monkeypatch):
    from accelerate_tpu.diagnostics.monitor import collect_status

    monkeypatch.setenv("ACCELERATE_TELEMETRY_MAX_BYTES", "2000")
    monkeypatch.setenv("ACCELERATE_TELEMETRY_KEEP_SEGMENTS", "3")
    rec = TelemetryRecorder(logging_dir=str(tmp_path), memory_interval=0)
    try:
        for _ in range(40):
            rec.record_step(dispatch_s=0.01)
    finally:
        rec.close()
    assert len(telemetry_segments(str(tmp_path / "telemetry" / "telemetry.jsonl"))) > 1
    status = collect_status(str(tmp_path))
    assert status["steps"] == 40  # the newest row, found despite rotation


# ---------------------------------------------------------------------------
# satellite: schema versioning
# ---------------------------------------------------------------------------


def test_schema_stamped_and_compat_logic():
    rec = TelemetryRecorder(logging_dir=None, memory_interval=0)
    try:
        rec.record_event("x")
        assert rec.records[-1]["schema"] == SCHEMA_VERSION
    finally:
        rec.close()
    assert schema_compatible({})  # legacy rows: accepted
    assert schema_compatible({"schema": SCHEMA_VERSION})
    assert not schema_compatible({"schema": SCHEMA_VERSION + 1})
    assert not schema_compatible({"schema": "garbage"})


def test_monitor_skips_unknown_schema_rows_without_keyerror(tmp_path):
    from accelerate_tpu.diagnostics.monitor import collect_status, render_status

    tel_dir = tmp_path / "telemetry"
    tel_dir.mkdir()
    now = time.time()
    with open(tel_dir / "telemetry.jsonl", "w") as f:
        f.write(json.dumps({"type": "step", "step": 7, "optimizer_steps": 7,
                            "step_time_s": 0.1, "recompiles": 1, "ts": now,
                            "schema": SCHEMA_VERSION}) + "\n")
        # a future writer reshaped the row: no step_time_s, new schema —
        # must be SKIPPED, not crash the reader
        f.write(json.dumps({"type": "step", "schema": SCHEMA_VERSION + 5,
                            "steps_v99": {"nested": True}, "ts": now}) + "\n")
    status = collect_status(str(tmp_path), now=now)
    assert status["steps"] == 7  # the compatible row still counts
    assert status["skipped_unknown_schema"] == 1
    assert "unknown schema" in render_status(status)


def test_trace_events_stamped_and_unknown_schema_skipped(tmp_path):
    from accelerate_tpu.diagnostics import Tracer
    from accelerate_tpu.diagnostics.tracing import (
        TRACE_SCHEMA_VERSION,
        parse_trace_file,
    )

    tracer = Tracer(logging_dir=str(tmp_path), host=0)
    with tracer.span("phase"):
        pass
    tracer.close()
    path = tmp_path / "traces" / "host_0.trace.json"
    events = parse_trace_file(str(path))
    assert events and all(e["schema"] == TRACE_SCHEMA_VERSION for e in events)
    with open(path, "a") as f:
        f.write(json.dumps({"name": "future", "ph": "X", "ts": 1, "dur": 1,
                            "schema": TRACE_SCHEMA_VERSION + 1}) + ",\n")
    names = {e["name"] for e in parse_trace_file(str(path))}
    assert "phase" in names and "future" not in names


# ---------------------------------------------------------------------------
# satellite: trace merge without clock_sync
# ---------------------------------------------------------------------------


def test_trace_merge_survives_missing_clock_sync(tmp_path):
    """A partial/killed host's file with no clock_sync metadata must merge
    with zero offset (warned, not crashed) and still be counted."""
    from accelerate_tpu.diagnostics import merge_traces, validate_chrome_trace

    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    (trace_dir / "host_0.trace.json").write_text(
        "[\n"
        + json.dumps({"name": "clock_sync", "ph": "M", "pid": 0, "tid": 0,
                      "args": {"wall_minus_mono_s": 100.0}}) + ",\n"
        + json.dumps({"name": "step", "ph": "X", "ts": 1.0 * E6, "dur": 10.0,
                      "pid": 0, "tid": 1}) + ",\n"
    )
    # host 1 was SIGKILLed before its clock_sync flushed
    (trace_dir / "host_1.trace.json").write_text(
        "[\n"
        + json.dumps({"name": "step", "ph": "X", "ts": 2.0 * E6, "dur": 10.0,
                      "pid": 1, "tid": 1}) + ",\n"
    )
    # and host 2's clock_sync line lost its args payload
    (trace_dir / "host_2.trace.json").write_text(
        "[\n"
        + json.dumps({"name": "clock_sync", "ph": "M", "pid": 2, "tid": 0}) + ",\n"
        + json.dumps({"name": "step", "ph": "X", "ts": 3.0 * E6, "dur": 10.0,
                      "pid": 2, "tid": 1}) + ",\n"
    )
    merged = merge_traces(str(trace_dir))
    validate_chrome_trace(merged)
    steps = [e for e in merged["traceEvents"] if e["name"] == "step"]
    assert {e["pid"] for e in steps} == {0, 1, 2}
    assert merged["metadata"]["merged_hosts"] == [0, 1, 2]
    assert merged["metadata"]["clock_offsets_s"]["1"] == 0.0  # assumed zero


# ---------------------------------------------------------------------------
# sidecar exporter
# ---------------------------------------------------------------------------


def _write_fixture_rows(tmp_path, rows):
    tel_dir = tmp_path / "telemetry"
    tel_dir.mkdir(exist_ok=True)
    with open(tel_dir / "telemetry.jsonl", "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_exporter_tails_incrementally_and_skips_unknown_schema(tmp_path):
    from accelerate_tpu.metrics.exporter import LoggingDirExporter

    now = time.time()
    _write_fixture_rows(tmp_path, [
        {"type": "step", "step": 1, "optimizer_steps": 1, "step_time_s": 0.1,
         "recompiles": 2, "ts": now, "schema": SCHEMA_VERSION},
        {"type": "compile", "total_s": 1.5, "ts": now, "schema": SCHEMA_VERSION},
        {"type": "step", "schema": SCHEMA_VERSION + 9, "ts": now},  # future row
    ])
    exporter = LoggingDirExporter(str(tmp_path))
    exporter.refresh(now=now)
    reg = exporter.registry
    assert reg.counter("steps").value() == 1
    assert reg.counter("recompiles").value() == 2  # ratcheted from the field
    assert reg.counter("compiles").value() == 1
    assert reg.counter("rows_skipped_unknown_schema").value() == 1
    # append two more rows: ONLY the delta is consumed on the next refresh
    _write_fixture_rows(tmp_path, [
        {"type": "step", "step": 2, "optimizer_steps": 2, "step_time_s": 0.1,
         "recompiles": 2, "ts": now + 1, "schema": SCHEMA_VERSION},
        {"type": "step", "step": 3, "optimizer_steps": 3, "step_time_s": 0.1,
         "recompiles": 2, "ts": now + 2, "schema": SCHEMA_VERSION},
    ])
    exporter.refresh(now=now + 2)
    assert reg.counter("steps").value() == 3
    parse_openmetrics(exporter.render())


def test_exporter_survives_rotation_without_recount(tmp_path, monkeypatch):
    """Segments are fingerprinted by content, not name: a rollover between
    refreshes must neither re-count nor drop rows."""
    from accelerate_tpu.metrics.exporter import LoggingDirExporter

    monkeypatch.setenv("ACCELERATE_TELEMETRY_MAX_BYTES", "1500")
    monkeypatch.setenv("ACCELERATE_TELEMETRY_KEEP_SEGMENTS", "4")
    rec = TelemetryRecorder(logging_dir=str(tmp_path), memory_interval=0)
    exporter = LoggingDirExporter(str(tmp_path))
    try:
        for _ in range(10):
            rec.record_step(dispatch_s=0.01)
        exporter.refresh()
        assert exporter.registry.counter("steps").value() == 10
        for _ in range(30):  # forces at least one rollover at 1500 bytes
            rec.record_step(dispatch_s=0.01)
    finally:
        rec.close()
    assert len(telemetry_segments(str(tmp_path / "telemetry" / "telemetry.jsonl"))) > 1
    exporter.refresh()
    assert exporter.registry.counter("steps").value() == 40


def test_exporter_reads_heartbeats_and_goodput(tmp_path):
    from accelerate_tpu.metrics.exporter import LoggingDirExporter

    hb_dir = tmp_path / "diagnostics"
    hb_dir.mkdir()
    now = time.time()
    (hb_dir / "heartbeat_0.json").write_text(
        json.dumps({"host": 0, "step": 12, "ts": now - 3, "fired": False})
    )
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    (trace_dir / "host_0.trace.json").write_text(
        "[\n"
        + json.dumps({"name": "step/dispatch", "ph": "X", "ts": 0.0,
                      "dur": 8 * E6, "pid": 0, "tid": 1}) + ",\n"
        + json.dumps({"name": "compile/compile", "ph": "X", "ts": 8 * E6,
                      "dur": 2 * E6, "pid": 0, "tid": 1}) + ",\n"
    )
    exporter = LoggingDirExporter(str(tmp_path))
    exporter.refresh(now=now)
    reg = exporter.registry
    assert reg.gauge("host_step").value(host="0") == 12
    assert reg.gauge("host_heartbeat_age_seconds").value(host="0") == pytest.approx(3, abs=1)
    assert reg.gauge("goodput_ratio").value() == pytest.approx(0.8)
    assert reg.gauge("goodput_bucket_seconds").value(bucket="compile") == pytest.approx(2.0)


def test_exporter_http_scrape(tmp_path):
    from accelerate_tpu.metrics.exporter import LoggingDirExporter, serve_exporter

    now = time.time()
    _write_fixture_rows(tmp_path, [
        {"type": "step", "step": 1, "optimizer_steps": 1, "step_time_s": 0.1,
         "recompiles": 0, "ts": now, "schema": SCHEMA_VERSION},
    ])
    exporter = LoggingDirExporter(str(tmp_path))
    server = serve_exporter(exporter, port=0)  # ephemeral port
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            assert "openmetrics-text" in resp.headers["Content-Type"]
            families = parse_openmetrics(resp.read().decode())
        assert sample_value(families, "accelerate_steps") == 1
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            health = json.load(resp)
        assert health["firing"] == []
    finally:
        server.shutdown()
        thread.join(timeout=10)


def test_metrics_export_cli_once(tmp_path, capsys, monkeypatch):
    from accelerate_tpu.commands.accelerate_cli import main

    now = time.time()
    _write_fixture_rows(tmp_path, [
        {"type": "step", "step": 5, "optimizer_steps": 5, "step_time_s": 0.1,
         "recompiles": 1, "ts": now, "schema": SCHEMA_VERSION},
    ])
    monkeypatch.delenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", raising=False)
    assert main(["metrics", "export", str(tmp_path), "--once"]) == 0
    families = parse_openmetrics(capsys.readouterr().out)
    assert sample_value(families, "accelerate_steps") == 1
    assert not (tmp_path / "ALERTS.json").exists()  # nothing armed, no file


# ---------------------------------------------------------------------------
# SLO alert rules
# ---------------------------------------------------------------------------


def test_evaluate_alerts_min_max_and_abstention(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", "90")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S", "0.5")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_RECOMPILES_PER_HOUR", "10")
    firing = evaluate_alerts(
        {"goodput_pct": 85.0, "ttft_p99_s": 0.4, "recompiles_per_hour": 50.0}
    )
    assert sorted(f["rule"] for f in firing) == [
        "max_recompiles_per_hour", "min_goodput_pct",
    ]
    # missing observations abstain — a dead exporter must not page
    assert evaluate_alerts({"goodput_pct": None}) == []
    # healthy values: quiet
    assert evaluate_alerts(
        {"goodput_pct": 95.0, "ttft_p99_s": 0.1, "recompiles_per_hour": 1.0}
    ) == []


def test_monitor_once_exit_codes_and_alerts_json(tmp_path, capsys, monkeypatch):
    """--once: 0 healthy, 3 on an SLO breach (ALERTS.json written), 2 when
    wedged/hung (precedence over the SLO code)."""
    from accelerate_tpu.commands.accelerate_cli import main

    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    (trace_dir / "host_0.trace.json").write_text(
        "[\n"
        + json.dumps({"name": "step/dispatch", "ph": "X", "ts": 0.0,
                      "dur": 5 * E6, "pid": 0, "tid": 1}) + ",\n"
        + json.dumps({"name": "prepare", "ph": "X", "ts": 5 * E6,
                      "dur": 5 * E6, "pid": 0, "tid": 1}) + ",\n"
    )  # goodput = 50%
    monkeypatch.delenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", raising=False)
    assert main(["monitor", str(tmp_path), "--once"]) == 0
    assert "goodput: 50.0%" in capsys.readouterr().out

    monkeypatch.setenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", "90")
    assert main(["monitor", str(tmp_path), "--once"]) == 3
    out = capsys.readouterr().out
    assert "SLO min_goodput_pct" in out
    alerts = json.load(open(tmp_path / "ALERTS.json"))
    assert alerts["firing"][0]["rule"] == "min_goodput_pct"
    assert alerts["firing"][0]["observed"] == pytest.approx(50.0)

    # resolved breach rewrites the file empty instead of leaving a stale page
    monkeypatch.setenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", "10")
    assert main(["monitor", str(tmp_path), "--once"]) == 0
    capsys.readouterr()
    assert json.load(open(tmp_path / "ALERTS.json"))["firing"] == []

    # wedged wins over SLO
    monkeypatch.setenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", "90")
    (tmp_path / "HANG_REPORT_0.json").write_text(
        json.dumps({"host": 0, "stalled_phase": "x", "elapsed_s": 1.0})
    )
    assert main(["monitor", str(tmp_path), "--once"]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# serve front end: GET /metrics
# ---------------------------------------------------------------------------


class _StubScheduler:
    queue_depth = 0

    def has_work(self):
        return False


class _StubEngine:
    """Just enough engine for the serve HTTP front end's read-only paths."""

    scheduler = _StubScheduler()

    def stats(self):
        return {
            "iterations": 4, "completed": 2, "queue_depth": 0,
            "tokens_emitted": 64, "decode_compiles": 1, "prefill_compiles": 1,
            "free_blocks": 7, "slot_occupancy_mean": 0.5, "tokens_per_sec": 123.0,
        }

    def step(self):
        return []


def test_serve_http_metrics_route(tmp_path):
    import queue as queue_mod

    from accelerate_tpu.commands.serve import _serve_http

    set_active_registry(MetricsRegistry(gate_main_process=False))
    engine = _StubEngine()
    inbox: queue_mod.Queue = queue_mod.Queue()
    stop = threading.Event()

    # find the bound port by racing the server up on an OS-assigned port is
    # not possible through _serve_http's signature; pick a free one first
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    thread = threading.Thread(
        target=_serve_http, args=(engine, inbox, stop, port), daemon=True
    )
    thread.start()
    try:
        body = None
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as resp:
                    assert "openmetrics-text" in resp.headers["Content-Type"]
                    body = resp.read().decode()
                break
            except OSError:
                time.sleep(0.1)
        assert body is not None, "serve HTTP front end never answered /metrics"
        families = parse_openmetrics(body)
        assert sample_value(families, "accelerate_serving_tokens") == 64
        assert sample_value(families, "accelerate_serving_free_blocks") == 7
        assert sample_value(families, "accelerate_serving_slot_occupancy") == 0.5
    finally:
        stop.set()
        thread.join(timeout=30)
        assert not thread.is_alive()
