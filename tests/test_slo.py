"""SLO closed-loop tests: the replayable workload suite (spec parsing,
byte-identical seeded schedules), the windowed burn-rate engine (multi-
window firing, abstention, the recompile-storm aging regression), the
file-fed evaluation path (router totals deltas, the fleet-wide expiry
counter, queued-phase fallback), the supervisor's SLO scaling policy
(scale up only for queued breaches, WRONG_REMEDY for device-bound tails,
scale down only with budget intact — every verdict an evidenced
``scale_decision`` row), schema-2 ``ALERTS.json``, and the ``slo report``
scorecard."""

import json
import os
import threading

import pytest

from accelerate_tpu.metrics.slo import (
    ALERTS_SCHEMA,
    LONG_WINDOW_FACTOR,
    NON_SCALABLE_PHASES,
    SloEngine,
    configured_objectives,
    evaluate_from_dir,
    write_slo_alerts,
)
from accelerate_tpu.serving.supervisor import ReplicaSupervisor, SupervisorConfig
from accelerate_tpu.serving.workload import (
    SCENARIOS,
    TraceSpecError,
    generate_schedule,
    parse_trace_spec,
    schedule_bytes,
    schedule_digest,
    write_workload_manifest,
)

NOW = 1_700_000_000.0  # fixed evaluation instant: no test reads the clock


@pytest.fixture(autouse=True)
def _no_ambient_slo(monkeypatch):
    """Objectives arm from ``ACCELERATE_SLO_*`` — strip any ambient config
    so each test arms exactly what it sets."""
    for key in list(os.environ):
        if key.startswith("ACCELERATE_SLO_"):
            monkeypatch.delenv(key)


# ---------------------------------------------------------------------------
# workload suite: spec parsing + seeded determinism
# ---------------------------------------------------------------------------


def test_parse_trace_spec_roundtrip():
    spec = parse_trace_spec("bursty-diurnal:7:30:4")
    assert (spec.name, spec.seed, spec.duration_s, spec.rps) == (
        "bursty-diurnal", 7, 30.0, 4.0,
    )
    assert parse_trace_spec(spec.as_text()) == spec
    replay = parse_trace_spec("replay:/tmp/some/schedule.jsonl")
    assert replay.name == "replay" and replay.path == "/tmp/some/schedule.jsonl"


@pytest.mark.parametrize(
    "bad",
    [
        "",
        "   ",
        "nope:1:2:3",              # unknown scenario
        "bursty-diurnal",          # missing fields
        "bursty-diurnal:1:2",      # wrong arity
        "bursty-diurnal:1:2:3:4",  # wrong arity
        "bursty-diurnal:-1:2:3",   # negative seed
        "bursty-diurnal:x:2:3",    # non-integer seed
        "bursty-diurnal:1:0:3",    # non-positive duration
        "bursty-diurnal:1:2:nan",  # NaN rps
        "replay",                  # replay without a path
    ],
)
def test_parse_trace_spec_rejects(bad):
    with pytest.raises(TraceSpecError):
        parse_trace_spec(bad)


def test_bursty_diurnal_7_schedule_is_byte_identical():
    """The acceptance determinism case: two independent parses of the same
    spec yield the same bytes (and therefore the same digest)."""
    a = generate_schedule(parse_trace_spec("bursty-diurnal:7:30:4"))
    b = generate_schedule(parse_trace_spec("bursty-diurnal:7:30:4"))
    assert schedule_bytes(a) == schedule_bytes(b)
    assert schedule_digest(a) == schedule_digest(b)
    assert a, "seeded schedule came out empty"


@pytest.mark.parametrize("name", SCENARIOS)
def test_every_scenario_is_deterministic_and_ordered(name):
    spec = parse_trace_spec(f"{name}:3:10:4")
    a, b = generate_schedule(spec), generate_schedule(spec)
    assert schedule_digest(a) == schedule_digest(b)
    arrivals = [r["t"] for r in a]
    assert arrivals == sorted(arrivals)
    for row in a:
        payload = row["payload"]
        assert isinstance(payload["id"], str) and payload["prompt"]
        assert payload["max_new_tokens"] > 0


def test_different_seed_different_schedule():
    a = generate_schedule(parse_trace_spec("agentic:1:10:4"))
    b = generate_schedule(parse_trace_spec("agentic:2:10:4"))
    assert schedule_digest(a) != schedule_digest(b)


# ---------------------------------------------------------------------------
# burn-rate engine
# ---------------------------------------------------------------------------


def test_disarmed_engine_is_inert():
    engine = SloEngine(objectives={})
    assert not engine.armed
    engine.observe_request(NOW, ttft_s=9.0, tpot_s=9.0, error=True)
    engine.observe_recompile(NOW, n=100)
    engine.observe_goodput(NOW, 0.0)
    assert engine.evaluate(NOW) == []
    assert engine.report(NOW) == {}
    assert not engine._outcomes and not engine._recompiles


def test_error_rate_fires_only_with_evidence_in_both_windows(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.01")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S", "60")
    engine = SloEngine()
    # violations only in the long window (older than 60 s): the short
    # window abstains, so the multi-window construction must NOT fire
    engine.observe_outcomes(NOW - 120, ok=10, errors=10)
    assert engine.evaluate(NOW) == []
    # fresh violations too → both windows burn > 1 → fires, worst evidence
    engine.observe_outcomes(NOW - 5, ok=10, errors=10)
    (breach,) = engine.evaluate(NOW)
    assert breach["rule"] == breach["objective"] == "max_error_rate"
    assert breach["env"] == "ACCELERATE_SLO_MAX_ERROR_RATE"
    assert breach["burn_rate"] > 1.0 and breach["burn_rate_long"] > 1.0
    assert breach["observed"] == pytest.approx(0.5)
    assert breach["budget_remaining"] == 0.0


def test_recompile_storm_ages_out_of_the_window(monkeypatch):
    """Regression for the lifetime-total bug: a recompile storm that ended
    more than two windows ago must not keep the alert firing forever."""
    monkeypatch.setenv("ACCELERATE_SLO_MAX_RECOMPILES_PER_HOUR", "10")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_RECOMPILES_PER_HOUR_WINDOW_S", "60")
    engine = SloEngine()
    engine.observe_recompile(NOW - 190, n=50)  # >2 windows old
    assert all(f["rule"] != "max_recompiles_per_hour" for f in engine.evaluate(NOW))
    fresh = SloEngine()
    fresh.observe_recompile(NOW - 5, n=50)
    (breach,) = fresh.evaluate(NOW)
    assert breach["rule"] == "max_recompiles_per_hour"
    assert breach["burn_rate"] > 1.0


def test_old_events_are_pruned_past_the_long_window(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.01")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S", "60")
    engine = SloEngine()
    engine.observe_outcomes(NOW - 60 * LONG_WINDOW_FACTOR - 10, ok=1, errors=1)
    engine.report(NOW)
    assert not engine._outcomes, "event survived past the long-window horizon"


def test_goodput_threshold_at_or_above_100_still_fires(monkeypatch):
    """The clamp: a target that leaves zero badness allowance (the smoke
    arms 101 to force a breach) must still produce a finite burn > 1."""
    monkeypatch.setenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", "101")
    engine = SloEngine()
    engine.observe_goodput(NOW - 1, 99.0)
    (breach,) = engine.evaluate(NOW)
    assert breach["rule"] == "min_goodput_pct"
    assert breach["observed"] == pytest.approx(99.0)
    assert breach["burn_rate"] > 1.0


def test_ttft_p99_burn_is_violating_fraction_over_budget(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S", "0.1")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S_WINDOW_S", "60")
    engine = SloEngine()
    for i in range(95):
        engine.observe_request(NOW - 10, ttft_s=0.01)
    for i in range(5):
        engine.observe_request(NOW - 10, ttft_s=0.5)
    report = engine.report(NOW)["max_ttft_p99_s"]
    # 5% of requests violate against a 1% budget → burn 5.0
    assert report["burn_rate"] == pytest.approx(5.0)
    assert report["firing"] is True
    assert report["observed"] == pytest.approx(0.5)  # the windowed p99


def test_abstention_on_no_evidence(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S", "0.1")
    engine = SloEngine()
    report = engine.report(NOW)["max_ttft_p99_s"]
    assert report["burn_rate"] is None and report["firing"] is False


def test_breach_carries_dominant_phase_and_sorts_worst_first(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.1")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S", "60")
    monkeypatch.setenv("ACCELERATE_SLO_MIN_GOODPUT_PCT", "99")
    engine = SloEngine()
    engine.observe_outcomes(NOW - 5, ok=0, errors=10)   # burn = 1/0.1 = 10
    engine.observe_goodput(NOW - 5, 97.0)               # burn = 3
    engine.observe_phases(NOW - 5, {"queued": 80.0, "device_wait": 20.0})
    firing = engine.evaluate(NOW)
    assert [f["rule"] for f in firing] == ["max_error_rate", "min_goodput_pct"]
    assert all(f["dominant_phase"] == "queued" for f in firing)


def test_window_and_budget_env_overrides(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S", "0.1")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S_WINDOW_S", "42")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_TTFT_P99_S_BUDGET", "0.05")
    obj = configured_objectives()["max_ttft_p99_s"]
    assert obj["window_s"] == 42.0 and obj["budget"] == 0.05


# ---------------------------------------------------------------------------
# file-fed evaluation: router totals deltas + the fleet-wide expiry counter
# ---------------------------------------------------------------------------


def _write_totals_rows(logging_dir, rows):
    os.makedirs(os.path.join(logging_dir, "router"), exist_ok=True)
    with open(os.path.join(logging_dir, "router", "replicas.jsonl"), "w") as f:
        for row in rows:
            f.write(json.dumps({"schema": 1, "kind": "router", **row}) + "\n")


def test_evaluate_from_dir_prefers_fleet_expiry_counter(tmp_path, monkeypatch):
    """Engine-side deadline evictions reach the totals row only via
    ``fleet_deadline_expired`` — the error-rate objective must count them
    even while the router-queue counter (``deadline_expired``) stays 0."""
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.01")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S", "60")
    logdir = str(tmp_path)
    _write_totals_rows(
        logdir,
        [
            {"ts": NOW - 20, "delivered": 0, "shed": 0,
             "deadline_expired": 0, "fleet_deadline_expired": 0,
             "queue_depth": 0, "replica_queue_depth": 0},
            {"ts": NOW - 5, "delivered": 15, "shed": 0,
             "deadline_expired": 0, "fleet_deadline_expired": 5,
             "queue_depth": 0, "replica_queue_depth": 3},
        ],
    )
    verdict = evaluate_from_dir(logdir, now=NOW)
    (breach,) = verdict["firing"]
    assert breach["rule"] == "max_error_rate"
    assert breach["observed"] == pytest.approx(5 / 20)
    # no traced tail exists, but the summed *replica* backlog is > 0 —
    # the fallback attributes the breach to queueing (the scalable phase)
    assert breach["dominant_phase"] == "queued"


def test_evaluate_from_dir_skips_counter_reset_seam(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.01")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S", "60")
    logdir = str(tmp_path)
    _write_totals_rows(
        logdir,
        [
            {"ts": NOW - 30, "delivered": 100, "shed": 0,
             "deadline_expired": 0, "fleet_deadline_expired": 40},
            # router restarted: counters reset — the negative delta is a
            # seam, not 40 fresh errors
            {"ts": NOW - 10, "delivered": 5, "shed": 0,
             "deadline_expired": 0, "fleet_deadline_expired": 0},
        ],
    )
    assert evaluate_from_dir(logdir, now=NOW)["firing"] == []


def test_write_slo_alerts_schema2_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.01")
    engine = SloEngine()
    engine.observe_outcomes(NOW - 5, ok=0, errors=10)
    objectives = engine.report(NOW)
    path = write_slo_alerts(str(tmp_path), engine.evaluate(NOW), objectives)
    with open(path) as f:
        payload = json.load(f)
    assert payload["schema"] == ALERTS_SCHEMA
    assert payload["rules"] == {"max_error_rate": 0.01}
    assert payload["firing"][0]["rule"] == "max_error_rate"
    assert payload["objectives"]["max_error_rate"]["firing"] is True
    # a resolved breach rewrites the file with an empty firing list
    # rather than leaving a stale page
    write_slo_alerts(str(tmp_path), [], objectives)
    with open(path) as f:
        assert json.load(f)["firing"] == []


# ---------------------------------------------------------------------------
# supervisor SLO policy — synthetic verdicts, no processes
# ---------------------------------------------------------------------------


class _FakeProcess:
    def poll(self):
        return None


class _FakeHandle:
    def __init__(self, replica_id, state="ready"):
        self.replica_id = replica_id
        self.state = state
        self.in_flight = 0
        self.process = _FakeProcess()
        self.drained = False

    def drain(self):
        self.drained = True


class _FakeRouter:
    def __init__(self, n_ready=2):
        self._lock = threading.Lock()
        self._queue = []
        self._outstanding = 0
        self.replicas = [_FakeHandle(i) for i in range(n_ready)]
        self.decision_rows = []

    def write_decision_row(self, fields):
        self.decision_rows.append(dict(fields))


def _breach(phase, objective="max_error_rate", burn=12.5):
    row = {
        "objective": objective,
        "rule": objective,
        "burn_rate": burn,
        "burn_rate_long": burn,
        "budget_remaining": 0.0,
        "dominant_phase": phase,
    }
    return {"firing": [row], "objectives": {objective: row}}


def _supervisor(router, slo, **cfg_kwargs):
    cfg = SupervisorConfig(
        min_replicas=1, max_replicas=3, scale_down_idle_ticks=1, **cfg_kwargs
    )
    spawned = []

    def spawn_fn(replica_id):
        handle = _FakeHandle(replica_id, state="starting")
        spawned.append(handle)
        return handle

    sup = ReplicaSupervisor(spawn_fn, cfg, slo_fn=lambda: slo)
    sup._router = router  # bind() would start the loop thread; drive by hand
    return sup, spawned


def test_queued_breach_scales_up_with_evidence():
    """The acceptance case: a queued-dominated breach ⇒ one spawn and a
    ``scale_decision`` row citing the objective, burn rate, and phase."""
    router = _FakeRouter()
    sup, spawned = _supervisor(router, _breach("queued"))
    sup._autoscale()
    assert len(spawned) == 1 and spawned[0].replica_id == 2
    assert spawned[0] in router.replicas
    (row,) = router.decision_rows
    assert row["action"] == "scale_up" and row["reason"] == "slo_breach"
    assert row["objective"] == "max_error_rate"
    assert row["burn_rate"] == pytest.approx(12.5)
    assert row["dominant_phase"] == "queued"


def test_queued_breach_at_max_replicas_holds():
    router = _FakeRouter(n_ready=3)
    sup, spawned = _supervisor(router, _breach("queued"))
    sup._autoscale()
    assert not spawned
    (row,) = router.decision_rows
    assert (row["action"], row["reason"]) == ("hold", "at_max_replicas")
    assert row["objective"] == "max_error_rate"


@pytest.mark.parametrize("phase", NON_SCALABLE_PHASES)
def test_device_bound_breach_holds_wrong_remedy(phase):
    router = _FakeRouter()
    sup, spawned = _supervisor(router, _breach(phase))
    sup._autoscale()
    assert not spawned, f"scaled up for a {phase}-bound breach"
    (row,) = router.decision_rows
    assert (row["action"], row["reason"]) == ("hold", "WRONG_REMEDY")
    assert row["dominant_phase"] == phase
    assert row["burn_rate"] == pytest.approx(12.5)


def test_unattributed_breach_holds_without_scaling():
    router = _FakeRouter()
    sup, spawned = _supervisor(router, _breach(None))
    sup._autoscale()
    assert not spawned
    (row,) = router.decision_rows
    assert (row["action"], row["reason"]) == ("hold", "phase_unattributed")


def test_holds_are_throttled_scale_ups_are_not():
    router = _FakeRouter()
    sup, _ = _supervisor(router, _breach("device_wait"))
    sup._autoscale()
    sup._autoscale()
    assert len(router.decision_rows) == 1, "steady-state hold logged twice"


def test_budget_intact_idle_scales_down():
    router = _FakeRouter(n_ready=2)
    intact = {"firing": [], "objectives": {
        "max_error_rate": {"budget_remaining": 0.8, "firing": False},
    }}
    sup, _ = _supervisor(router, intact)
    sup._autoscale()
    victim = router.replicas[1]  # highest replica_id above the floor
    assert victim.drained and victim.state == "draining"
    (row,) = router.decision_rows
    assert (row["action"], row["reason"]) == ("scale_down", "budget_intact_idle")


def test_spent_budget_blocks_scale_down():
    router = _FakeRouter(n_ready=2)
    spent = {"firing": [], "objectives": {
        "max_error_rate": {"budget_remaining": 0.0, "firing": False},
    }}
    sup, _ = _supervisor(router, spent)
    sup._autoscale()
    assert not any(r.drained for r in router.replicas)
    (row,) = router.decision_rows
    assert (row["action"], row["reason"]) == ("hold", "budget_spent")


# ---------------------------------------------------------------------------
# slo report scorecard
# ---------------------------------------------------------------------------


def _traced_run(tmp_path, monkeypatch, with_breach):
    import time

    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE", "0.01")
    monkeypatch.setenv("ACCELERATE_SLO_MAX_ERROR_RATE_WINDOW_S", "60")
    logdir = str(tmp_path)
    spec = parse_trace_spec("overbudget-storm:7:4:8")
    write_workload_manifest(logdir, spec, generate_schedule(spec))
    errors = 5 if with_breach else 0
    now = time.time()  # the report command evaluates at wall time
    _write_totals_rows(
        logdir,
        [
            {"ts": now - 20, "delivered": 0, "shed": 0, "deadline_expired": 0,
             "fleet_deadline_expired": 0},
            {"ts": now - 5, "delivered": 20, "shed": 0, "deadline_expired": 0,
             "fleet_deadline_expired": errors},
        ],
    )
    return logdir


def test_slo_report_fail_roundtrips_json(tmp_path, monkeypatch):
    from accelerate_tpu.commands.slo import build_report, render_report

    logdir = _traced_run(tmp_path, monkeypatch, with_breach=True)
    report = build_report(logdir)
    card = report["scenarios"][0]
    assert card["verdict"] == "fail" and report["pass"] is False
    assert card["spec"].startswith("overbudget-storm")
    roundtrip = json.loads(json.dumps(report, default=str))
    assert roundtrip["scenarios"][0]["verdict"] == "fail"
    text = render_report(report)
    assert "overbudget-storm" in text and "overall: FAIL" in text


def test_slo_report_pass_when_nothing_fires(tmp_path, monkeypatch):
    from accelerate_tpu.commands.slo import build_report, render_report

    logdir = _traced_run(tmp_path, monkeypatch, with_breach=False)
    report = build_report(logdir)
    assert report["scenarios"][0]["verdict"] == "pass"
    assert report["pass"] is True
    assert "overall: PASS" in render_report(report)
