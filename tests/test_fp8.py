"""fp8 matmul policy (reference backends: TransformerEngine
``utils/transformer_engine.py:26`` / MS-AMP ``accelerator.py:2034``;
coverage row §2.5 fp8 — previously silently bf16)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.ops.fp8 import (
    E4M3_MAX,
    FP8RecipeKwargs,
    dense,
    fp8_autocast,
    fp8_is_active,
    fp8_matmul,
)


def test_fp8_matmul_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    exact = x @ w
    out = fp8_matmul(x, w)
    # e4m3 carries ~3 mantissa bits (~6% per-element); cancellation makes
    # per-element relative error unbounded where the exact value ≈ 0, so
    # bound the global relative error and the typical element
    rel = np.abs(np.asarray(out - exact)) / (np.abs(np.asarray(exact)) + 1.0)
    assert np.median(rel) < 0.05
    norm_rel = np.linalg.norm(np.asarray(out - exact)) / np.linalg.norm(np.asarray(exact))
    assert norm_rel < 0.05, norm_rel


def test_dense_routes_by_context():
    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 2), jnp.float32)
    assert not fp8_is_active()
    exact = dense(x, w)
    np.testing.assert_array_equal(np.asarray(exact), np.asarray(x @ w))
    with fp8_autocast():
        assert fp8_is_active()
        out = dense(x, w)
    assert not fp8_is_active()
    np.testing.assert_allclose(np.asarray(out), np.asarray(exact), rtol=0.05)


def test_fp8_grads_flow_and_are_close():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)

    def loss_fp8(w):
        with fp8_autocast():
            return jnp.sum(dense(x, w) ** 2)

    def loss_exact(w):
        return jnp.sum((x @ w) ** 2)

    g8 = jax.grad(loss_fp8)(w)
    g = jax.grad(loss_exact)(w)
    cos = np.sum(np.asarray(g8) * np.asarray(g)) / (
        np.linalg.norm(g8) * np.linalg.norm(g)
    )
    assert cos > 0.99, f"gradient direction diverged: cos={cos}"


def test_fp8_training_decreases_loss():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    accelerator = Accelerator(
        mixed_precision="fp8", kwargs_handlers=[FP8RecipeKwargs(fp8_format="HYBRID")]
    )
    config = LlamaConfig.tiny(vocab_size=128, hidden_size=64, layers=2, heads=4, seq=32)
    model = LlamaForCausalLM.from_config(config, seed=0)
    model, opt = accelerator.prepare(model, optax.adamw(1e-2))
    assert model.fp8_recipe is not None
    ids = np.random.default_rng(0).integers(0, 128, size=(4, 32)).astype(np.int32)
    losses = []
    for _ in range(6):
        out = model(input_ids=ids, labels=ids)
        accelerator.backward(out.loss)
        opt.step()
        opt.zero_grad()
        losses.append(out.loss.item())
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0] * 0.7, losses


def test_fp8_quantization_is_actually_applied():
    """The fp8 path must change numerics vs plain bf16 — no silent
    fallthrough (the round-1 gap: fp8 mapped to bf16 with no policy)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 128)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(128, 8)), jnp.float32)
    exact = np.asarray(x @ w)
    with fp8_autocast():
        out = np.asarray(dense(x, w))
    assert not np.allclose(out, exact, rtol=1e-6), "fp8 path identical to fp32 — inactive"


def test_autocast_island_suspends_fp8():
    """AutocastKwargs(enabled=False) must suspend the fp8 recipe too —
    deferred calls inside the island run exact matmuls."""
    from accelerate_tpu.utils.dataclasses import AutocastKwargs
    from accelerate_tpu.test_utils import RegressionModel
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator = Accelerator(mixed_precision="fp8")
    model = accelerator.prepare_model(RegressionModel(a=1.0, b=0.0))
    assert model.fp8_recipe is not None
    x = np.asarray([1.0 / 3.0], np.float32)
    with accelerator.autocast(autocast_handler=AutocastKwargs(enabled=False)):
        island = model(x=x)
        assert model.fp8_recipe is None
    assert model.fp8_recipe is not None
    inside = float(np.asarray(island.prediction.force()))
    assert inside == np.float32(1.0 / 3.0)
