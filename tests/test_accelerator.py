"""End-to-end Accelerator slice (reference analogs: ``tests/test_accelerator.py``
and the launched ``test_utils/scripts/test_script.py`` training parity check
:449 — here the "multi-rank" side is the 8-device CPU mesh)."""

import numpy as np
import optax
import pytest

import accelerate_tpu
from accelerate_tpu import Accelerator, GradientAccumulationPlugin
from accelerate_tpu.lazy import Deferred
from accelerate_tpu.modules import Model, PreparedModel
from accelerate_tpu.optimizer import AcceleratedOptimizer
from accelerate_tpu.scheduler import AcceleratedScheduler
from accelerate_tpu.data_loader import DataLoaderShard
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


from accelerate_tpu.test_utils import SimpleLoader as _Loader  # noqa: E402


def _make(accelerator=None, lr=0.1, batch_size=16, length=64, accum=1):
    accelerator = accelerator or Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=accum)
    )
    model = RegressionModel(a=0.0, b=0.0)
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=lr)
    loader = _Loader(RegressionDataset(length=length), batch_size=batch_size)
    model, opt, dl = accelerator.prepare(model, tx, loader)
    return accelerator, model, opt, dl


def test_prepare_returns_wrappers():
    accelerator, model, opt, dl = _make()
    assert isinstance(model, PreparedModel)
    assert isinstance(opt, AcceleratedOptimizer)
    assert isinstance(dl, DataLoaderShard)
    assert opt.model is model
    assert opt.opt_state is not None


def test_model_call_is_deferred_and_forces():
    accelerator, model, opt, dl = _make()
    batch = next(iter(dl))
    out = model(**batch)
    assert isinstance(out, Deferred)
    loss = out.loss
    val = loss.item()
    assert np.isfinite(val) and val > 0


def test_training_loop_reduces_loss_and_learns():
    accelerator, model, opt, dl = _make(lr=0.2)
    losses = []
    for epoch in range(15):
        dl.set_epoch(epoch)
        for batch in dl:
            out = model(**batch)
            loss = out.loss
            accelerator.backward(loss)
            opt.step()
            opt.zero_grad()
            losses.append(loss.item())
    assert losses[-1] < losses[0] * 0.1
    a = float(np.asarray(model.params["a"]))
    b = float(np.asarray(model.params["b"]))
    assert abs(a - 2.0) < 0.3
    assert abs(b - 3.0) < 0.3


def test_gradient_accumulation_matches_full_batch():
    """Sum of grads over k microbatches (scaled 1/k) == grad of the same data
    as one batch — the semantics the reference pins in test_sync.py."""
    import jax
    import jax.numpy as jnp

    # full-batch reference
    acc1, model1, opt1, _ = _make(lr=0.1)
    x = np.linspace(-1, 1, 32).astype(np.float32)
    y = (2 * x + 3).astype(np.float32)
    shard = jax.sharding.NamedSharding(acc1.mesh, jax.sharding.PartitionSpec(("dp", "fsdp")))
    big = {"x": jax.device_put(jnp.asarray(x), shard), "y": jax.device_put(jnp.asarray(y), shard)}
    out = model1(**big)
    acc1.backward(out.loss)
    out.loss.item()  # forces the parked fused step down the split path
    g_full = jax.device_get(opt1._grads)

    # accumulated microbatches on a fresh accelerator
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc2, model2, opt2, _ = _make(accum=2, lr=0.1)
    for half in (slice(0, 16), slice(16, 32)):
        mb = {
            "x": jax.device_put(jnp.asarray(x[half]), jax.sharding.NamedSharding(acc2.mesh, jax.sharding.PartitionSpec(("dp", "fsdp")))),
            "y": jax.device_put(jnp.asarray(y[half]), jax.sharding.NamedSharding(acc2.mesh, jax.sharding.PartitionSpec(("dp", "fsdp")))),
        }
        out = model2(**mb)
        acc2.backward(out.loss)
    g_accum = jax.device_get(opt2._grads)
    # mean over 2 halves of mse == mse over full batch  ⇒  grads match
    for k in g_full:
        np.testing.assert_allclose(g_accum[k], g_full[k], rtol=1e-5)


def test_accumulate_context_controls_sync():
    accelerator, model, opt, dl = _make(accum=4, length=64, batch_size=8)
    sync_flags = []
    for batch in dl:
        with accelerator.accumulate(model):
            out = model(**batch)
            accelerator.backward(out.loss)
            sync_flags.append(accelerator.sync_gradients)
            opt.step()
            opt.zero_grad()
    # 8 batches, accum 4: sync on batches 4 and 8 (1-indexed)
    assert sync_flags == [False, False, False, True, False, False, False, True]


def test_clip_grad_norm():
    import jax.numpy as jnp

    accelerator, model, opt, dl = _make(lr=1000.0)
    batch = next(iter(dl))
    out = model(**batch)
    accelerator.backward(out.loss)
    norm = accelerator.clip_grad_norm_(model, max_norm=0.001)
    assert float(norm) > 0.001  # pre-clip norm returned
    clipped_norm = float(optax.global_norm(opt._grads))
    assert clipped_norm <= 0.0011


def test_scheduler_steps_with_optimizer():
    accelerator, model, opt, dl = _make(lr=0.1)
    schedule = optax.linear_schedule(init_value=0.1, end_value=0.0, transition_steps=100)
    sched = accelerator.prepare(schedule)
    assert isinstance(sched, AcceleratedScheduler)
    batch = next(iter(dl))
    out = model(**batch)
    accelerator.backward(out.loss)
    opt.step()
    sched.step()
    assert sched.get_last_lr()[0] < 0.1
    assert opt.learning_rate == pytest.approx(sched.get_last_lr()[0])


def test_gather_for_metrics_drops_duplicates():
    accelerator = Accelerator()
    model = accelerator.prepare(RegressionModel(a=1, b=0))
    # 30 samples, batch 8 → remainder 6 on last batch
    loader = _Loader(RegressionDataset(length=30), batch_size=8)
    dl = accelerator.prepare(loader)
    seen = []
    for batch in dl:
        out = model(x=batch["x"])
        pred = accelerator.gather_for_metrics(out.prediction)
        seen.append(np.asarray(pred))
    total = np.concatenate(seen)
    assert total.shape[0] == 30  # padding dropped, not 32


def test_mixed_precision_bf16_keeps_fp32_params():
    import jax.numpy as jnp

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator = Accelerator(mixed_precision="bf16")
    model, opt, dl = accelerator.prepare(
        RegressionModel(), optax.sgd(0.1), _Loader(RegressionDataset(), batch_size=16)
    )
    assert model.compute_dtype == jnp.bfloat16
    assert model.params["a"].dtype == jnp.float32
    batch = next(iter(dl))
    out = model(**batch)
    accelerator.backward(out.loss)
    out.loss.item()  # flush the fused fast path so grads are inspectable
    assert opt._grads["a"].dtype == jnp.float32
    opt.step()
    assert model.params["a"].dtype == jnp.float32


def test_backward_requires_deferred():
    accelerator, model, opt, dl = _make()
    with pytest.raises(TypeError):
        accelerator.backward(np.float32(1.0))


def test_trigger_api():
    accelerator = Accelerator()
    assert not accelerator.check_trigger()
    accelerator.set_trigger()
    assert accelerator.check_trigger()
    assert not accelerator.check_trigger()


def test_unwrap_model_roundtrip():
    accelerator, model, opt, dl = _make()
    raw = accelerator.unwrap_model(model)
    assert isinstance(raw, Model)
    sd = model.state_dict()
    assert set(sd) == {"a", "b"}


def test_free_memory_clears_registries():
    accelerator, model, opt, dl = _make()
    accelerator.free_memory()
    assert accelerator._models == []
    assert accelerator._optimizers == []


def test_fp16_clip_operates_on_unscaled_grads():
    """Regression: with fp16 loss scaling, clip_grad_norm_ must clip in true
    gradient units and return the true pre-clip norm."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.utils.dataclasses import GradScalerKwargs

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    # init_scale kept low enough that this model's first step is finite (the
    # 65536 default would overflow fp16 here and back off — tested elsewhere)
    accelerator = Accelerator(
        mixed_precision="fp16", kwargs_handlers=[GradScalerKwargs(init_scale=1024.0)]
    )
    model, opt, dl = accelerator.prepare(
        RegressionModel(), optax.sgd(0.1), _Loader(RegressionDataset(length=32), batch_size=32)
    )
    assert opt.scaler is not None and opt.scaler.get_scale() > 1
    batch = next(iter(dl))
    out = model(**batch)
    accelerator.backward(out.loss)
    # true grads: compute analytically from a fresh fp32 model
    x = np.asarray(batch["x"], dtype=np.float32)
    y = np.asarray(batch["y"], dtype=np.float32)
    true_ga = np.mean(2 * (0 * x + 0 - y) * x)
    true_gb = np.mean(2 * (0 * x + 0 - y))
    true_norm = np.sqrt(true_ga**2 + true_gb**2)
    norm = float(accelerator.clip_grad_norm_(model, max_norm=1e9))
    assert norm == pytest.approx(true_norm, rel=0.05)  # fp16 forward tolerance
    # and after a tight clip the post-step update is bounded by max_norm * lr
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    accelerator2 = Accelerator(
        mixed_precision="fp16", kwargs_handlers=[GradScalerKwargs(init_scale=1024.0)]
    )
    model2, opt2, dl2 = accelerator2.prepare(
        RegressionModel(), optax.sgd(1.0), _Loader(RegressionDataset(length=32), batch_size=32)
    )
    out2 = model2(**next(iter(dl2)))
    accelerator2.backward(out2.loss)
    accelerator2.clip_grad_norm_(model2, max_norm=0.5)
    opt2.step()
    delta = np.sqrt(
        float(model2.params["a"]) ** 2 + float(model2.params["b"]) ** 2
    )
    assert delta == pytest.approx(0.5, rel=0.05)


def _fp16_scaler_setup():
    from accelerate_tpu.utils.dataclasses import GradScalerKwargs

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    handler = GradScalerKwargs(
        init_scale=1024.0, growth_factor=2.0, backoff_factor=0.5, growth_interval=2
    )
    accelerator = Accelerator(mixed_precision="fp16", kwargs_handlers=[handler])
    model, opt, dl = accelerator.prepare(
        RegressionModel(a=0.0, b=0.0),
        optax.sgd(0.01),
        _Loader(RegressionDataset(length=32), batch_size=8),
    )
    good = next(iter(dl))
    # 2*(pred - y)*x with x = 6e4 overflows the fp16 max (65504) even before
    # the loss scale multiplies it: a deterministic non-finite gradient
    bad = {
        "x": np.full(np.shape(good["x"]), 6.0e4, dtype=np.float32),
        "y": np.ones(np.shape(good["y"]), dtype=np.float32),
    }
    return accelerator, model, opt, good, bad


def test_fp16_dynamic_scale_backoff_and_growth_fused():
    """Overflow → backoff → regrowth on the fused step path (the scaler
    state lives on device and updates inside the compiled step)."""
    accelerator, model, opt, good, bad = _fp16_scaler_setup()
    assert accelerator.scaler is opt.scaler
    assert accelerator.scaler.get_scale() == 1024.0

    out = model(**bad)
    accelerator.backward(out.loss)
    opt.step()
    assert opt.step_was_skipped
    assert float(np.asarray(model.params["a"])) == 0.0  # update suppressed
    assert accelerator.scaler.get_scale() == 512.0
    opt.zero_grad()

    for _ in range(2):  # growth_interval=2 finite steps → scale regrows
        out = model(**good)
        accelerator.backward(out.loss)
        opt.step()
        assert not opt.step_was_skipped
        opt.zero_grad()
    assert accelerator.scaler.get_scale() == 1024.0


def test_fp16_dynamic_scale_backoff_and_growth_split():
    """Same schedule on the split path (grads materialised before step —
    the scaler updates eagerly where the finite check already syncs)."""
    accelerator, model, opt, good, bad = _fp16_scaler_setup()

    out = model(**bad)
    accelerator.backward(out.loss)
    assert opt.grads is not None  # forces the pending loss → split path
    opt.step()
    assert opt.step_was_skipped
    assert accelerator.scaler.get_scale() == 512.0
    opt.zero_grad()

    for _ in range(2):
        out = model(**good)
        accelerator.backward(out.loss)
        assert opt.grads is not None
        opt.step()
        assert not opt.step_was_skipped
        opt.zero_grad()
    assert accelerator.scaler.get_scale() == 1024.0


def test_fp16_scaler_state_round_trips_through_checkpoint(tmp_path):
    accelerator, model, opt, good, bad = _fp16_scaler_setup()
    out = model(**bad)
    accelerator.backward(out.loss)
    opt.step()
    opt.zero_grad()
    assert accelerator.scaler.get_scale() == 512.0
    accelerator.save_state(str(tmp_path / "ckpt"))
    accelerator.scaler.load_state_dict({"scale": 64.0})
    accelerator.load_state(str(tmp_path / "ckpt"))
    assert accelerator.scaler.get_scale() == 512.0


def test_dynamo_backend_warns_once(caplog):
    import logging

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    Accelerator._dynamo_warned = False
    with caplog.at_level(logging.WARNING, logger="accelerate_tpu.accelerator"):
        # the reference's disabled spelling is uppercase "NO": no warning
        Accelerator(dynamo_backend="NO")
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        Accelerator(dynamo_backend="inductor")
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        Accelerator(dynamo_backend="inductor")
    hits = [r for r in caplog.records if "dynamo_backend" in r.getMessage()]
    assert len(hits) == 1


def test_auto_resume_covers_objects_prepared_in_later_calls(tmp_path, monkeypatch):
    """Regression: a restarted script that prepares its objects across
    SEVERAL prepare() calls must still have the last call's objects
    restored — auto-resume re-fires per prepare until training starts."""
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    def _project():
        return ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        )

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc1 = Accelerator(project_config=_project())
    model, opt, dl = acc1.prepare(
        RegressionModel(a=0.0, b=0.0), optax.sgd(0.1),
        _Loader(RegressionDataset(length=32), batch_size=8),
    )
    out = model(**next(iter(dl)))
    acc1.backward(out.loss)
    opt.step()
    opt.zero_grad()
    acc1.save_state()
    a_trained = float(np.asarray(model.params["a"]))
    assert a_trained != 0.0

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    monkeypatch.setenv("ACCELERATE_AUTO_RESUME", "true")
    acc2 = Accelerator(project_config=_project())
    dl2 = acc2.prepare(_Loader(RegressionDataset(length=32), batch_size=8))
    model2, opt2 = acc2.prepare(RegressionModel(a=0.0, b=0.0), optax.sgd(0.1))
    assert float(np.asarray(model2.params["a"])) == pytest.approx(a_trained)
    # training freezes further auto-resume: a third prepare() must not
    # clobber the live params with the checkpoint again
    out2 = model2(**next(iter(dl2)))
    acc2.backward(out2.loss)
    opt2.step()
    opt2.zero_grad()
    a_after_step = float(np.asarray(model2.params["a"]))
    extra_model = acc2.prepare(RegressionModel(a=0.0, b=0.0))
    assert float(np.asarray(model2.params["a"])) == pytest.approx(a_after_step)


def test_prepare_passes_through_unknown_callables():
    class FakeTokenizer:
        def __call__(self, text):
            return [1, 2, 3]

    accelerator, model, opt, dl = _make()
    tok = FakeTokenizer()
    out = accelerator.prepare(tok)
    assert out is tok
    assert out("hi") == [1, 2, 3]


def test_skip_first_batches_on_raw_loader():
    accelerator = Accelerator()
    raw = _Loader(RegressionDataset(length=32), batch_size=8)
    skipped = accelerator.skip_first_batches(raw, 2)
    assert len(list(skipped)) == 2


def test_fused_path_trains_and_matches_split():
    """Fused backward+step must produce the same params as the split path."""
    import jax

    acc1, m1, o1, d1 = _make(lr=0.1)
    batches = [b for b in d1]
    for b in batches[:2]:
        out = m1(**b)
        acc1.backward(out.loss)
        assert o1._pending_loss is not None  # fused path armed
        o1.step()
        o1.zero_grad()
    fused_params = {k: np.asarray(v) for k, v in m1.params.items()}

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2, m2, o2, d2 = _make(lr=0.1)
    for b in batches[:2]:
        out = m2(**b)
        acc2.backward(out.loss)
        out.loss.item()  # force split path
        o2.step()
        o2.zero_grad()
    split_params = {k: np.asarray(v) for k, v in m2.params.items()}
    for k in fused_params:
        np.testing.assert_allclose(fused_params[k], split_params[k], rtol=1e-6)


def test_fused_path_with_clip_matches_split():
    acc1, m1, o1, d1 = _make(lr=1.0)
    batch = next(iter(d1))
    out = m1(**batch)
    acc1.backward(out.loss)
    norm_pending = acc1.clip_grad_norm_(m1, max_norm=0.25)
    o1.step()
    fused_params = {k: float(np.asarray(v)) for k, v in m1.params.items()}
    fused_norm = float(norm_pending)

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2, m2, o2, d2 = _make(lr=1.0)
    batch2 = next(iter(d2))
    out2 = m2(**batch2)
    acc2.backward(out2.loss)
    out2.loss.item()  # split
    norm_split = float(acc2.clip_grad_norm_(m2, max_norm=0.25))
    o2.step()
    split_params = {k: float(np.asarray(v)) for k, v in m2.params.items()}
    assert fused_norm == pytest.approx(norm_split, rel=1e-5)
    for k in fused_params:
        assert fused_params[k] == pytest.approx(split_params[k], rel=1e-5)
