"""Shared helpers for the examples: offline tokenizer, dataset, metric.

The reference examples lean on transformers/datasets/evaluate from the Hub
(``/root/reference/examples/nlp_example.py:47-111``); this zero-egress build
vendors the equivalents — a whitespace word-piece vocabulary built from the
shipped CSVs, fixed-length padding (the reference pads to 128 on XLA for
static shapes, :81-84), and an accuracy+F1 metric matching
``evaluate.load("glue", "mrpc")``'s output keys.
"""

from __future__ import annotations

import csv
import os

import numpy as np

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
PAD, CLS, SEP, UNK = 0, 1, 2, 3
MAX_LENGTH = 48  # static shapes: always pad to full length on TPU


def read_split(name: str):
    rows = []
    with open(os.path.join(DATA_DIR, f"{name}.csv"), newline="") as f:
        for row in csv.DictReader(f):
            rows.append(
                (int(row["label"] == "equivalent"), row["sentence1"], row["sentence2"])
            )
    return rows


class WordTokenizer:
    """Deterministic whitespace vocabulary over the training split."""

    def __init__(self, rows):
        words = sorted({w for _, s1, s2 in rows for w in (s1 + " " + s2).split()})
        self.vocab = {w: i + 4 for i, w in enumerate(words)}  # 0..3 are specials

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + 4

    def encode_pair(self, s1: str, s2: str, max_length: int = MAX_LENGTH):
        """[CLS] s1 [SEP] s2 [SEP] with token-type ids, padded to max_length."""
        a = [self.vocab.get(w, UNK) for w in s1.split()]
        b = [self.vocab.get(w, UNK) for w in s2.split()]
        ids = [CLS] + a + [SEP] + b + [SEP]
        types = [0] * (len(a) + 2) + [1] * (len(b) + 1)
        ids, types = ids[:max_length], types[:max_length]
        mask = [1] * len(ids)
        pad = max_length - len(ids)
        return ids + [PAD] * pad, types + [0] * pad, mask + [0] * pad


class ParaphraseDataset:
    def __init__(self, rows, tokenizer: WordTokenizer, max_length: int = MAX_LENGTH):
        self.examples = []
        for label, s1, s2 in rows:
            ids, types, mask = tokenizer.encode_pair(s1, s2, max_length)
            self.examples.append(
                {
                    "input_ids": np.asarray(ids, np.int32),
                    "token_type_ids": np.asarray(types, np.int32),
                    "attention_mask": np.asarray(mask, np.int32),
                    "labels": np.int32(label),
                }
            )

    def __len__(self):
        return len(self.examples)

    def __getitem__(self, i):
        return self.examples[i]


class RandomSampler:
    """Marker sampler: its type name tells prepare_data_loader to shuffle
    (with the framework's seedable cross-process permutation)."""


class SimpleLoader:
    """Duck-typed loader for ``accelerator.prepare`` (dataset/batch_size/
    drop_last/sampler/batch_sampler/collate_fn is the accepted contract)."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = False, drop_last: bool = False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.sampler = RandomSampler() if shuffle else None
        self.batch_sampler = None
        self.collate_fn = None


def get_dataloaders(
    accelerator, batch_size: int = 16, eval_batch_size: int = 32,
    max_length: int = MAX_LENGTH,
):
    """Tokenize the vendored corpus and build train/eval loaders (reference
    ``get_dataloaders`` ``examples/nlp_example.py:47``). ``max_length=128``
    reproduces the reference's XLA pad-to-128 collate
    (``examples/nlp_example.py:81``)."""
    train_rows = read_split("train")
    with accelerator.main_process_first():
        tokenizer = WordTokenizer(train_rows)
        train = ParaphraseDataset(train_rows, tokenizer, max_length=max_length)
        dev = ParaphraseDataset(read_split("dev"), tokenizer, max_length=max_length)
    train_loader = SimpleLoader(train, batch_size, shuffle=True, drop_last=True)
    eval_loader = SimpleLoader(dev, eval_batch_size)
    return train_loader, eval_loader, tokenizer


def build_model(tokenizer, seed: int = 42, full_size: bool = False):
    """``full_size=True`` builds the BERT-base shape the reference trains
    (``bert-base-cased``: 12 layers, hidden 768, ~108M params —
    ``examples/nlp_example.py:91``); the embedding table is padded to the
    bert-base-cased vocab (28996) so the parameter count is honest even
    though the vendored word tokenizer uses fewer rows. The default tiny
    shape keeps example CI fast."""
    from accelerate_tpu.models.bert import BertConfig, BertForSequenceClassification

    if full_size:
        config = BertConfig(
            vocab_size=max(28996, tokenizer.vocab_size), num_labels=2
        )
    else:
        config = BertConfig.tiny(
            vocab_size=tokenizer.vocab_size, hidden_size=128, layers=2, heads=4,
            seq=MAX_LENGTH, num_labels=2,
        )
    return BertForSequenceClassification.from_config(config, seed=seed)


class PairMetric:
    """accuracy + F1, the keys ``evaluate.load("glue", "mrpc")`` reports."""

    def __init__(self):
        self.preds: list = []
        self.refs: list = []

    def add_batch(self, predictions, references):
        self.preds.extend(np.asarray(predictions).reshape(-1).tolist())
        self.refs.extend(np.asarray(references).reshape(-1).tolist())

    def compute(self) -> dict:
        p = np.asarray(self.preds)
        r = np.asarray(self.refs)
        self.preds, self.refs = [], []
        tp = int(np.sum((p == 1) & (r == 1)))
        fp = int(np.sum((p == 1) & (r == 0)))
        fn = int(np.sum((p == 0) & (r == 1)))
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
        return {"accuracy": float(np.mean(p == r)), "f1": f1}
