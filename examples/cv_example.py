"""Computer-vision example (reference ``examples/cv_example.py`` — resnet50
on an image-folder dataset; this zero-egress build generates a synthetic
shape-classification set and trains a small patch-embedding classifier).

Same 5-line accelerate contract as ``nlp_example.py``; demonstrates the
image pipeline: float image batches, per-channel normalisation, a custom
collate, and eval accuracy via ``gather_for_metrics``.
"""

import argparse

import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.modules import Model, ModelOutput
from accelerate_tpu.utils.random import set_seed

from example_utils import PairMetric, SimpleLoader

IMAGE_SIZE = 16
N_CLASSES = 3


def make_shape_dataset(n: int, seed: int):
    """n grayscale images of one of three shapes at random positions:
    filled square / hollow square / cross."""
    rng = np.random.default_rng(seed)
    images = np.zeros((n, IMAGE_SIZE, IMAGE_SIZE), np.float32)
    labels = rng.integers(0, N_CLASSES, size=(n,)).astype(np.int32)
    for i, label in enumerate(labels):
        cx, cy = rng.integers(4, IMAGE_SIZE - 4, size=2)
        r = int(rng.integers(2, 4))
        if label == 0:  # filled square
            images[i, cx - r : cx + r, cy - r : cy + r] = 1.0
        elif label == 1:  # hollow square
            images[i, cx - r : cx + r, cy - r : cy + r] = 1.0
            images[i, cx - r + 1 : cx + r - 1, cy - r + 1 : cy + r - 1] = 0.0
        else:  # cross
            images[i, cx - r : cx + r, cy] = 1.0
            images[i, cx, cy - r : cy + r] = 1.0
        images[i] += rng.normal(0, 0.05, size=(IMAGE_SIZE, IMAGE_SIZE))
    return images, labels


class ShapeDataset:
    def __init__(self, n: int, seed: int):
        self.images, self.labels = make_shape_dataset(n, seed)
        # per-dataset normalisation (the reference normalises with ImageNet
        # stats; here the stats come from the data)
        self.mean = self.images.mean()
        self.std = self.images.std() + 1e-6

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return {
            "pixel_values": (self.images[i] - self.mean) / self.std,
            "labels": self.labels[i],
        }


def make_model(seed: int, hidden: int = 64, patch: int = 4):
    """Patch-embedding MLP classifier: patchify → embed → mix → pool →
    head. Small, pure, and jit-friendly (static shapes)."""
    import jax
    import jax.numpy as jnp

    n_patches = (IMAGE_SIZE // patch) ** 2
    keys = jax.random.split(jax.random.key(seed), 3)
    params = {
        "embed": (jax.random.normal(keys[0], (patch * patch, hidden)) / patch).astype(jnp.float32),
        "mix": (jax.random.normal(keys[1], (hidden, hidden)) / np.sqrt(hidden)).astype(jnp.float32),
        "head": (jax.random.normal(keys[2], (hidden, N_CLASSES)) / np.sqrt(hidden)).astype(jnp.float32),
    }

    def apply_fn(p, pixel_values=None, labels=None, **kw):
        b = pixel_values.shape[0]
        x = pixel_values.reshape(
            b, IMAGE_SIZE // patch, patch, IMAGE_SIZE // patch, patch
        ).transpose(0, 1, 3, 2, 4).reshape(b, n_patches, patch * patch)
        x = jax.nn.gelu(x @ p["embed"])
        x = jax.nn.gelu(x @ p["mix"])
        pooled = x.mean(axis=1)
        logits = pooled @ p["head"]
        out = ModelOutput(logits=logits)
        if labels is not None:
            logp = jax.nn.log_softmax(logits, axis=-1)
            out["loss"] = -jnp.mean(
                jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)
            )
        return out

    return Model(apply_fn, params, name="ShapeClassifier")


def training_function(config, args):
    accelerator = Accelerator(cpu=args.cpu, mixed_precision=args.mixed_precision)
    lr, num_epochs = config["lr"], int(config["num_epochs"])
    seed, batch_size = int(config["seed"]), int(config["batch_size"])
    metric = PairMetric()

    set_seed(seed)
    train_loader = SimpleLoader(ShapeDataset(512, seed=0), batch_size, shuffle=True, drop_last=True)
    eval_loader = SimpleLoader(ShapeDataset(128, seed=1), 32)
    model = make_model(seed)
    optimizer = optax.inject_hyperparams(optax.adamw)(learning_rate=lr)
    model, optimizer, train_loader, eval_loader = accelerator.prepare(
        model, optimizer, train_loader, eval_loader
    )

    for epoch in range(num_epochs):
        model.train()
        train_loader.set_epoch(epoch)
        for step, batch in enumerate(train_loader):
            outputs = model(**batch)
            accelerator.backward(outputs.loss)
            optimizer.step()
            optimizer.zero_grad()

        model.eval()
        for step, batch in enumerate(eval_loader):
            outputs = model(**{k: v for k, v in batch.items() if k != "labels"})
            predictions = np.asarray(outputs.logits.force()).argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics(
                (predictions, batch["labels"])
            )
            metric.add_batch(predictions=predictions, references=references)
        eval_metric = metric.compute()
        accelerator.print(f"epoch {epoch}: accuracy {eval_metric['accuracy']:.4f}")
    accelerator.end_training()
    return eval_metric


def main():
    parser = argparse.ArgumentParser(description="CV example.")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16", "fp8"])
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_epochs", type=int, default=8)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 32}
    training_function(config, args)


if __name__ == "__main__":
    main()
