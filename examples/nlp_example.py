"""The canonical 5-line-change training loop (BASELINE config #1).

Mirrors the reference's ``examples/nlp_example.py:1-200`` — BERT-style
encoder on a paraphrase-pair task — with the TPU-native framework: the same
script runs unchanged on one chip, a v5e-8 data-parallel mesh, or a pod
(``accelerate-tpu launch examples/nlp_example.py``); the vendored dataset
replaces GLUE/MRPC (zero-egress environment, same schema).

The five accelerate lines are marked with  # [accelerate].
"""

import argparse

import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.random import set_seed

from example_utils import PairMetric, build_model, get_dataloaders

MAX_TPU_BATCH_SIZE = 16
EVAL_BATCH_SIZE = 32


def training_function(config, args):
    accelerator = Accelerator(  # [accelerate]
        cpu=args.cpu, mixed_precision=args.mixed_precision
    )
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    metric = PairMetric()

    gradient_accumulation_steps = 1
    if batch_size > MAX_TPU_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_TPU_BATCH_SIZE
        batch_size = MAX_TPU_BATCH_SIZE

    set_seed(seed)
    train_dataloader, eval_dataloader, tokenizer = get_dataloaders(
        accelerator, batch_size, EVAL_BATCH_SIZE
    )
    model = build_model(tokenizer, seed=seed)

    optimizer = optax.inject_hyperparams(optax.adamw)(learning_rate=lr)
    num_steps = (len(train_dataloader.dataset) // batch_size) * num_epochs
    lr_scheduler = optax.schedules.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps=20, decay_steps=max(num_steps, 21)
    )

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = (
        accelerator.prepare(  # [accelerate]
            model, optimizer, train_dataloader, eval_dataloader, lr_scheduler
        )
    )

    for epoch in range(num_epochs):
        model.train()
        train_dataloader.set_epoch(epoch)
        for step, batch in enumerate(train_dataloader):
            outputs = model(**batch)
            loss = outputs.loss
            loss = loss / gradient_accumulation_steps
            accelerator.backward(loss)  # [accelerate]
            if step % gradient_accumulation_steps == 0:
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        model.eval()
        for step, batch in enumerate(eval_dataloader):
            outputs = model(**{k: v for k, v in batch.items() if k != "labels"})
            predictions = np.asarray(outputs.logits.force()).argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics(  # [accelerate]
                (predictions, batch["labels"])
            )
            metric.add_batch(predictions=predictions, references=references)

        eval_metric = metric.compute()
        accelerator.print(f"epoch {epoch}:", eval_metric)  # [accelerate]
    accelerator.end_training()
    return eval_metric


def main():
    parser = argparse.ArgumentParser(description="Simple example of training script.")
    parser.add_argument(
        "--mixed_precision", type=str, default=None,
        choices=["no", "fp16", "bf16", "fp8"],
        help="Whether to use mixed precision (bf16 is the TPU-native default).",
    )
    parser.add_argument("--cpu", action="store_true", help="If passed, will train on the CPU.")
    parser.add_argument("--num_epochs", type=int, default=3)
    args = parser.parse_args()
    config = {"lr": 1e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
