"""Generate the vendored paraphrase-detection dataset (MRPC stand-in).

The reference's canonical example trains BERT on GLUE/MRPC downloaded from
the Hub (``/root/reference/examples/nlp_example.py:47-96``). This
environment has zero egress, so the examples ship a small synthetic
sentence-pair corpus with the same schema (``label,sentence1,sentence2``)
and the same task shape: decide whether two sentences are paraphrases.

Construction: sentences are drawn from a 200-word vocabulary with a simple
subject-verb-object grammar. A paraphrase keeps the content words and
re-orders/substitutes function words; a non-paraphrase swaps in different
content words. Learnable to >90% accuracy by a small encoder — enough to
give the examples a real quality bar (reference analog:
``test_performance.py`` accuracy thresholds).

Run: ``python make_paraphrase_data.py`` (writes train.csv / dev.csv here).
"""

import csv
import os

import numpy as np

SUBJECTS = [
    "the committee", "a spokesman", "the company", "the senator", "analysts",
    "the court", "researchers", "the bank", "officials", "the minister",
    "the board", "a witness", "the agency", "investors", "the union",
    "prosecutors", "the jury", "the mayor", "engineers", "the firm",
]
VERBS = [
    "announced", "rejected", "approved", "confirmed", "denied", "reported",
    "estimated", "acquired", "suspended", "criticised", "defended",
    "disclosed", "predicted", "reviewed", "settled", "postponed",
]
OBJECTS = [
    "the merger", "the proposal", "new tariffs", "the verdict", "its earnings",
    "the contract", "a major expansion", "the investigation", "the deal",
    "higher rates", "the policy", "the shutdown", "record profits",
    "the settlement", "new evidence", "the restructuring", "the takeover",
    "further cuts", "the partnership", "the upgrade",
]
TAILS = [
    "on monday", "last week", "after the meeting", "in a statement",
    "despite objections", "earlier this year", "without comment",
    "according to filings", "at the hearing", "before the deadline",
]
PARA_VERB = {  # near-synonym substitutions used in paraphrases
    "announced": "disclosed", "rejected": "dismissed", "approved": "endorsed",
    "confirmed": "verified", "denied": "disputed", "reported": "stated",
    "estimated": "projected", "acquired": "purchased", "suspended": "halted",
    "criticised": "attacked", "defended": "supported", "disclosed": "revealed",
    "predicted": "forecast", "reviewed": "examined", "settled": "resolved",
    "postponed": "delayed",
}


def make_pair(rng):
    s, v, o, t = (
        rng.choice(SUBJECTS), rng.choice(VERBS), rng.choice(OBJECTS), rng.choice(TAILS)
    )
    s1 = f"{s} {v} {o} {t}"
    if rng.random() < 0.5:
        # paraphrase: synonym verb, optionally drop/replace the tail
        t2 = t if rng.random() < 0.5 else rng.choice(TAILS)
        s2 = f"{s} {PARA_VERB[v]} {o} {t2}"
        return "equivalent", s1, s2
    # not a paraphrase: change the object (and often the verb)
    o2 = rng.choice([x for x in OBJECTS if x != o])
    v2 = rng.choice(VERBS) if rng.random() < 0.5 else v
    s2 = f"{s} {v2} {o2} {t}"
    return "not_equivalent", s1, s2


def write_split(path, n, seed):
    rng = np.random.default_rng(seed)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["label", "sentence1", "sentence2"])
        for _ in range(n):
            w.writerow(make_pair(rng))


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    write_split(os.path.join(here, "train.csv"), 600, seed=0)
    write_split(os.path.join(here, "dev.csv"), 160, seed=1)
    print("wrote train.csv (600) and dev.csv (160)")
