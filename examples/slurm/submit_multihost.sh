#!/bin/bash
# Multi-host TPU training under SLURM (reference analog:
# examples/slurm/submit_multinode.sh — torchrun rendezvous becomes
# jax.distributed coordinator discovery). One task per HOST: JAX drives
# all local chips from a single process.

#SBATCH --job-name=tpu-multihost
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=4                   # number of TPU hosts
#SBATCH --ntasks-per-node=1         # ONE process per host (JAX owns local chips)
#SBATCH --time=01:59:00

head_node_ip=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n 1)

export LAUNCHER="accelerate-tpu launch \
    --num_machines $SLURM_NNODES \
    --machine_rank \$SLURM_PROCID \
    --coordinator_address $head_node_ip:8476 \
    --mesh_fsdp 16 \
    "
export SCRIPT="examples/complete_nlp_example.py"
export SCRIPT_ARGS="--mixed_precision bf16"

srun bash -c "$LAUNCHER $SCRIPT $SCRIPT_ARGS"
