#!/bin/bash
# Single-host TPU training under SLURM (reference analog:
# examples/slurm/submit_multigpu.sh). No rendezvous needed — one process
# drives every chip attached to the host.

#SBATCH --job-name=tpu-singlehost
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=1
#SBATCH --ntasks-per-node=1
#SBATCH --time=01:59:00

accelerate-tpu launch \
    --mesh_fsdp 4 --mesh_tp 2 \
    examples/complete_nlp_example.py --mixed_precision bf16
