"""The full-featured CV training loop: checkpointing, resume, and tracking
on top of ``cv_example.py`` (reference
``/root/reference/examples/complete_cv_example.py`` — resnet50 with the
same flags; this zero-egress build reuses the synthetic shape-classifier).

Adds to ``cv_example.py``:
* ``--checkpointing_steps {N|epoch}`` — periodic ``accelerator.save_state``
* ``--resume_from_checkpoint DIR`` — ``load_state`` deep resume
* ``--with_tracking`` — tracker init/log/end (TensorBoard by default)
* ``--output_dir`` — checkpoint + tracker root
"""

import argparse
import os

import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.random import set_seed

from cv_example import ShapeDataset, make_model
from example_utils import PairMetric, SimpleLoader

EVAL_BATCH_SIZE = 32


def training_function(config, args):
    accelerator = Accelerator(
        cpu=args.cpu,
        mixed_precision=args.mixed_precision,
        log_with="tensorboard" if args.with_tracking else None,
        project_dir=args.output_dir,
    )
    if hasattr(args.checkpointing_steps, "isdigit"):
        if args.checkpointing_steps == "epoch":
            checkpointing_steps = args.checkpointing_steps
        elif args.checkpointing_steps.isdigit():
            checkpointing_steps = int(args.checkpointing_steps)
        else:
            raise ValueError(
                f"Argument `checkpointing_steps` must be either a number or `epoch`. "
                f"`{args.checkpointing_steps}` passed."
            )
    else:
        checkpointing_steps = None

    lr, num_epochs = config["lr"], int(config["num_epochs"])
    seed, batch_size = int(config["seed"]), int(config["batch_size"])

    if args.with_tracking:
        run = os.path.split(__file__)[-1].split(".")[0]
        accelerator.init_trackers(run, config)

    metric = PairMetric()
    set_seed(seed)
    train_loader = SimpleLoader(
        ShapeDataset(512, seed=0), batch_size, shuffle=True, drop_last=True
    )
    eval_loader = SimpleLoader(ShapeDataset(128, seed=1), EVAL_BATCH_SIZE)
    model = make_model(seed)

    optimizer = optax.inject_hyperparams(optax.adamw)(learning_rate=lr)
    steps_per_epoch = len(train_loader.dataset) // batch_size
    lr_scheduler = optax.schedules.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps=10, decay_steps=max(steps_per_epoch * num_epochs, 11)
    )

    model, optimizer, train_loader, eval_loader, lr_scheduler = accelerator.prepare(
        model, optimizer, train_loader, eval_loader, lr_scheduler
    )

    starting_epoch = 0
    overall_step = 0
    if args.resume_from_checkpoint:
        accelerator.print(f"Resumed from checkpoint: {args.resume_from_checkpoint}")
        accelerator.load_state(args.resume_from_checkpoint)
        overall_step = accelerator.step
        starting_epoch = overall_step // steps_per_epoch

    for epoch in range(starting_epoch, num_epochs):
        model.train()
        train_loader.set_epoch(epoch)
        total_loss = 0.0
        for step, batch in enumerate(train_loader):
            outputs = model(**batch)
            loss = outputs.loss
            accelerator.backward(loss)
            optimizer.step()
            lr_scheduler.step()
            optimizer.zero_grad()
            if args.with_tracking:
                total_loss += float(loss.item())
            overall_step += 1
            accelerator.step = overall_step

            if isinstance(checkpointing_steps, int) and overall_step % checkpointing_steps == 0:
                output_dir = os.path.join(args.output_dir or ".", f"step_{overall_step}")
                accelerator.save_state(output_dir)

        model.eval()
        for step, batch in enumerate(eval_loader):
            outputs = model(**{k: v for k, v in batch.items() if k != "labels"})
            predictions = np.asarray(outputs.logits.force()).argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics(
                (predictions, batch["labels"])
            )
            metric.add_batch(predictions=predictions, references=references)

        eval_metric = metric.compute()
        accelerator.print(f"epoch {epoch}: accuracy {eval_metric['accuracy']:.4f}")
        if args.with_tracking:
            accelerator.log(
                {
                    "accuracy": eval_metric["accuracy"],
                    "train_loss": total_loss / max(steps_per_epoch, 1),
                    "epoch": epoch,
                },
                step=overall_step,
            )

        if checkpointing_steps == "epoch":
            output_dir = os.path.join(args.output_dir or ".", f"epoch_{epoch}")
            accelerator.save_state(output_dir)

    accelerator.end_training()
    return eval_metric


def main():
    parser = argparse.ArgumentParser(description="Complete CV example.")
    parser.add_argument(
        "--mixed_precision", type=str, default=None,
        choices=["no", "fp16", "bf16", "fp8"],
        help="Whether to use mixed precision (bf16 is the TPU-native default).",
    )
    parser.add_argument("--cpu", action="store_true", help="If passed, will train on the CPU.")
    parser.add_argument(
        "--checkpointing_steps", type=str, default=None,
        help="Whether the various states should be saved at the end of every n steps, "
        "or 'epoch' for each epoch.",
    )
    parser.add_argument(
        "--resume_from_checkpoint", type=str, default=None,
        help="If the training should continue from a checkpoint folder.",
    )
    parser.add_argument(
        "--with_tracking", action="store_true",
        help="Whether to load in all available experiment trackers from the "
        "environment and use them for logging.",
    )
    parser.add_argument(
        "--output_dir", type=str, default=".",
        help="Optional save directory where all checkpoint folders will be stored.",
    )
    parser.add_argument("--num_epochs", type=int, default=8)
    args = parser.parse_args()
    config = {"lr": 2e-3, "num_epochs": args.num_epochs, "seed": 42, "batch_size": 32}
    training_function(config, args)


if __name__ == "__main__":
    main()
