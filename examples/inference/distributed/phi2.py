"""Memory-efficient distributed LLM inference (reference
``examples/inference/distributed/phi2.py`` — phi-2 loaded once with
``init_empty_weights`` + dispatched, prompts split across ranks).
Zero-egress analog: the llama slice is materialised shape-only, loaded
from a synthetic sharded checkpoint under a device map, and each process
generates for its prompt slice with the KV cache.

Run: accelerate-tpu launch --num_cpu_devices 8 examples/inference/distributed/phi2.py
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), *[".."] * 3))

from accelerate_tpu import Accelerator, init_empty_weights, load_checkpoint_and_dispatch
from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--prompts", type=int, default=6)
    parser.add_argument("--new_tokens", type=int, default=8)
    args = parser.parse_args()

    accelerator = Accelerator()
    config = LlamaConfig.tiny(vocab_size=256, hidden_size=64, layers=2, heads=4, seq=64)

    # write a synthetic checkpoint once (stands in for the downloaded repo)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        if accelerator.is_main_process:
            donor = LlamaForCausalLM.from_config(config, seed=0)
            accelerator.save_model(donor, ckpt_dir)
        accelerator.wait_for_everyone()

        # the reference's low-memory idiom: shapes first, weights streamed in
        with init_empty_weights():
            model = LlamaForCausalLM.from_config(config, seed=0)
        model = load_checkpoint_and_dispatch(model, ckpt_dir, device_map={"": 0})

        rng = np.random.default_rng(0)
        prompts = [
            rng.integers(0, 256, size=(8,)).astype(np.int32)
            for _ in range(args.prompts)
        ]
        with accelerator.split_between_processes(prompts, apply_padding=True) as shard:
            local = [
                np.asarray(
                    generate(model, p[None, :], max_new_tokens=args.new_tokens)
                )[0].tolist()
                for p in shard
            ]

        results = accelerator.gather_for_metrics(local, use_gather_object=True)
        if accelerator.is_main_process:
            results = results[: args.prompts]
            assert all(len(r) == 8 + args.new_tokens for r in results)
            print(
                f"generated {args.new_tokens} tokens for {len(results)} prompts "
                f"on {accelerator.num_processes} process(es)"
            )


if __name__ == "__main__":
    main()
