"""Distributed inference via ``split_between_processes`` (reference
``examples/inference/distributed/*``): each process takes its slice of the
prompt list, runs the model locally, and rank 0 gathers the results."""

import argparse
import sys, os

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from accelerate_tpu import Accelerator
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_prompts", type=int, default=10)
    args = parser.parse_args()

    accelerator = Accelerator()
    config = LlamaConfig.tiny(vocab_size=512, hidden_size=64, layers=2, heads=4, seq=32)
    model = accelerator.prepare_model(LlamaForCausalLM.from_config(config, seed=0))

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 512, size=(32,)).astype(np.int32) for _ in range(args.num_prompts)]

    # each process handles its contiguous slice (padded so every process
    # gets work; reference `split_between_processes(..., apply_padding=True)`)
    with accelerator.split_between_processes(prompts, apply_padding=True) as shard:
        local = []
        for prompt in shard:
            out = model(input_ids=prompt[None, :])
            local.append(int(np.asarray(out.logits.force())[0, -1].argmax()))

    results = accelerator.gather_for_metrics(local, use_gather_object=True)
    accelerator.print(f"next-token predictions for {args.num_prompts} prompts: "
                      f"{results[: args.num_prompts]}")


if __name__ == "__main__":
    main()
