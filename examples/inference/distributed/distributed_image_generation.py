"""Distributed image generation (reference
``examples/inference/distributed/distributed_image_generation.py`` — SD3
over prompt batches). Zero-egress analog: a tiny latent-denoising loop
(iterative refinement, the diffusion control flow) with synthetic weights;
the distribution pattern is identical — prompts are chunked with
``split_between_processes``, every process runs its slice, rank 0 gathers.

Run: accelerate-tpu launch --num_cpu_devices 8 examples/inference/distributed/distributed_image_generation.py
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), *[".."] * 3))

from accelerate_tpu import Accelerator

IMG = 16
LATENT = 8


def build_denoiser(seed: int):
    """A toy conditional denoiser: (latent, step_embedding, prompt_embedding)
    -> latent update. Stands in for the SD transformer; jit-friendly."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.key(seed))
    params = {
        "w_in": jax.random.normal(k1, (LATENT * LATENT + 2, 64)) * 0.1,
        "w_out": jax.random.normal(k2, (64, LATENT * LATENT)) * 0.1,
    }

    @jax.jit
    def denoise_step(p, latent, t, prompt_emb):
        b = latent.shape[0]
        feats = jnp.concatenate(
            [latent.reshape(b, -1), jnp.full((b, 1), t), prompt_emb[:, None]], axis=-1
        )
        update = jnp.tanh(feats @ p["w_in"]) @ p["w_out"]
        return latent - 0.1 * update.reshape(b, LATENT, LATENT)

    return params, denoise_step


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--prompts", type=int, default=8)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--save_dir", type=str, default=None)
    args = parser.parse_args()

    accelerator = Accelerator()
    params, denoise_step = build_denoiser(seed=0)

    # "prompts" are scalar embeddings here; real prompts would be encoded
    # by a text tower first — the distribution pattern is what matters
    rng = np.random.default_rng(0)
    prompts = [float(x) for x in rng.normal(size=args.prompts)]

    import jax.numpy as jnp

    with accelerator.split_between_processes(prompts, apply_padding=True) as shard:
        latents = jnp.asarray(
            rng.standard_normal((len(shard), LATENT, LATENT)), jnp.float32
        )
        emb = jnp.asarray(shard, jnp.float32)
        for t in range(args.steps, 0, -1):
            latents = denoise_step(params, latents, t / args.steps, emb)
        images = np.asarray(jnp.clip(latents, -1, 1))  # [n, 8, 8] "images"

    gathered = accelerator.gather_for_metrics(
        [img for img in images], use_gather_object=True
    )[: args.prompts]
    if accelerator.is_main_process:
        assert len(gathered) == args.prompts
        if args.save_dir:
            os.makedirs(args.save_dir, exist_ok=True)
            np.save(os.path.join(args.save_dir, "images.npy"), np.stack(gathered))
        print(
            f"generated {len(gathered)} images on {accelerator.num_processes} "
            f"process(es); mean |pixel| = {np.abs(np.stack(gathered)).mean():.4f}"
        )


if __name__ == "__main__":
    main()
